"""MinerNode — the event loop, job processors, and solver pipeline (L3').

Mirror of `miner/src/index.ts` restructured for in-process TPU inference:
chain events enqueue jobs in sqlite; `tick()` drains due jobs in the
reference's two-phase order (concurrent batch, then serial); the solve
path replaces the cog-HTTP hop with registry runners and — the TPU win —
groups compatible solve jobs into one dp-batched XLA dispatch.

Reference call-stack parity (SURVEY.md §3):
  boot self-test golden CID        index.ts:984-1001 → boot()
  event → task job                 index.ts:191-201  → _on_task_submitted
  processTask (filter+hydrate)     index.ts:506-564  → _process_task
  processSolve (cid→commit→reveal) index.ts:566-672  → _process_solve_batch
  contest-on-mismatch              index.ts:651-670  → same
  processClaim                     index.ts:728-750  → _process_claim
  stake auto-top-up                index.ts:397-472  → _process_validator_stake
  automine                         index.ts:474-503  → _process_automine
  vote-if-invalid                  index.ts:268-306  → _on_contestation

Time/blocks come from the chain facade — no wall clock — so tests drive
the node deterministically.
"""
from __future__ import annotations

import json
import logging
import threading
import time

from arbius_tpu.l0.commitment import taskid2seed
from arbius_tpu.node.chain_client import EngineError, LocalChain
from arbius_tpu.node.config import MiningConfig
from arbius_tpu.node.db import Job, NodeDB
from arbius_tpu.node.retry import RetriesExhausted, expretry
from arbius_tpu.node.solver import ModelRegistry, solve_cid, solve_cid_batch
from arbius_tpu.obs import Obs, span, use_obs
from arbius_tpu.templates.engine import (
    HydrationError,
    MiningFilter,
    check_model_filter,
    hydrate_input,
)

log = logging.getLogger("arbius.node")

MINER_VERSION = 0  # versionCheck: chain version must be <= ours

# lifecycle counters, exposed as arbius_<name>_total on GET /metrics and
# as attributes of the NodeMetrics back-compat view
_COUNTERS = {
    "solutions_submitted": "Solutions revealed on-chain",
    "solutions_claimed": "Solution rewards claimed",
    "contestations_submitted": "Contestations this node initiated",
    "votes_cast": "Contestation votes cast",
    "vote_finishes": "contestationVoteFinish calls that paid out",
    "tasks_seen": "TaskSubmitted events observed",
    "tasks_invalid": "Tasks marked invalid (bad version or input)",
}


class NodeMetrics:
    """Back-compat view over the obs registry (docs/observability.md).

    Pre-obs this was a dataclass of ints and rolling deques; the registry
    is now the single source of truth and this view derives the same
    attribute surface from it: counter attributes read the
    `arbius_*_total` counters, `solve_latency` / `stage_seconds` read the
    histograms' bounded recent-sample windows.
    """

    def __init__(self, obs: Obs):
        self._obs = obs

    def __getattr__(self, name: str):
        if name == "tasks_unprofitable":
            # per-model labeled since the costsched PR (a mispriced
            # family must be visible) — the back-compat attribute is
            # the sum over every model child
            c = self._obs.registry.counter(
                "arbius_tasks_unprofitable_total", labelnames=("model",))
            return int(sum(c.summary().values()))
        if name in _COUNTERS:
            return int(self._obs.registry.counter(
                f"arbius_{name}_total").value())
        raise AttributeError(name)

    @property
    def solve_latency(self) -> list:
        """Recent (taskid, chain-seconds) pairs, newest last."""
        return self._obs.registry.histogram(
            "arbius_solve_latency_chain_seconds").recent()

    @property
    def stage_seconds(self) -> dict:
        """Recent wall-clock seconds per solve stage: infer = model +
        encode + CID for a bucket dispatch; commit = chain txs for the
        bucket (SURVEY.md §5 tracing)."""
        h = self._obs.registry.histogram("arbius_stage_seconds",
                                         labelnames=("stage",))
        return {"infer": h.values(stage="infer"),
                "commit": h.values(stage="commit")}


class BootError(RuntimeError):
    pass


class MinerNode:
    def __init__(self, chain: LocalChain, config: MiningConfig,
                 registry: ModelRegistry, db: NodeDB | None = None,
                 store=None, pinner=None):
        self.chain = chain
        self.config = config
        self.registry = registry
        self.db = db or NodeDB(config.db_path)
        if store is None and config.store_dir:
            from arbius_tpu.node.store import ContentStore

            store = ContentStore(config.store_dir)
        self.store = store
        if pinner is None:
            from arbius_tpu.node.pinners import build_pinner

            pinner = build_pinner(config.ipfs, store)
        self.pinner = pinner
        self.obs = Obs(journal_capacity=config.obs_journal_capacity,
                       now_fn=lambda: self.chain.now,
                       enabled=config.obs_enabled)
        if config.perfscope.enabled:
            # perfscope card capture (docs/perfscope.md): installed on
            # the obs bundle — like the AOT cache — so every
            # jit_cache_get under this node's ambient obs records a
            # PerfCard at compile. Installed at construction, not boot:
            # the capture has no layout dependency and a non-booted
            # test node should meter exactly like a booted one.
            from arbius_tpu.obs.perfscope import PerfScope

            ps = config.perfscope
            self.obs.perfscope = PerfScope(
                self.obs, peak_flops=ps.peak_flops,
                peak_bytes_per_second=ps.peak_bytes_per_second,
                drift_min=ps.drift_min, drift_max=ps.drift_max)
        reg = self.obs.registry
        for name, help_text in _COUNTERS.items():
            reg.counter(f"arbius_{name}_total", help_text)
        self._c_unprofitable = reg.counter(
            "arbius_tasks_unprofitable_total",
            "Tasks skipped by the profitability gate, by model — a "
            "mispriced family shows up as its own series "
            "(docs/scheduler.md)", labelnames=("model",))
        self._h_stage = reg.histogram(
            "arbius_stage_seconds",
            "Wall-clock seconds per solve stage (infer=model+encode+CID "
            "per bucket dispatch, commit=chain txs per bucket)",
            labelnames=("stage",))
        self._h_latency = reg.histogram(
            "arbius_solve_latency_chain_seconds",
            "Chain-time seconds from solve dispatch to accepted solution")
        self._c_jobs_failed = reg.counter(
            "arbius_jobs_failed_total",
            "Jobs quarantined to failed_jobs, by method",
            labelnames=("method",))
        reg.gauge("arbius_queue_depth",
                  "Jobs currently in the queue (due or waiting)",
                  fn=self.db.job_count)
        self._c_idle = reg.counter(
            "arbius_chip_idle_seconds_total",
            "Seconds the solve path spent with nothing dispatched on the "
            "device (the host+network tail the pipeline exists to hide)")
        self.metrics = NodeMetrics(self.obs)
        self._retry_sleep = lambda s: None  # injectable; chain time is fake
        # fleet worker mode (docs/fleet.md), wired by LeaseFeed.attach:
        # `task_feed` replaces the TaskSubmitted subscription as the
        # task source (its pump() runs at the top of every tick — the
        # lease heartbeat woven into the tick), and `commit_guard` is
        # consulted before every signalCommitment so two fleet workers
        # never double-commit one (validator, taskid). Both None = the
        # bare single-node miner, bit-for-bit.
        self.task_feed = None
        self.commit_guard = None
        self.mesh = None          # built + validated at boot (cfg.mesh)
        # live alert engine (docs/healthwatch.md): installed at
        # construction — like perfscope — so the reference is
        # published before any RPC request thread can exist (the
        # /debug/alerts view reads it). Unclean-shutdown evidence is
        # read from the checkpoint HERE, before boot clears heartbeats
        # or any tick queues fresh work: a fresh db holds no jobs, a
        # checkpoint with in-flight work means the previous life died
        # mid-mine (the crash_recovered rule). None = no evaluation,
        # the pre-healthwatch node bit-for-bit.
        self.healthwatch = None
        if config.alerts.enabled:
            from arbius_tpu.obs.healthwatch import HealthWatch

            self.healthwatch = HealthWatch(
                self.obs, config.alerts, slo=config.slo,
                recovered=any(
                    j.method not in ("validatorStake", "automine")
                    for j in self.db.get_jobs(2**60, limit=50)))
        # AOT executable cache (docs/compile-cache.md), installed at
        # boot when cfg.aot_cache.enabled; the disk-warm tag set feeds
        # costsched's CROSS-LIFE warm boost (published under state_lock
        # — the /debug/costmodel request thread reads it)
        self.aot_cache = None
        self._disk_warm_tags: frozenset = frozenset()
        # mesh-layout tag of the solve programs (part of every cost-model
        # key: a tp2 bucket and a single-device bucket are different
        # programs with different chip-seconds); boot() refines it once
        # the mesh is up
        self.solve_layout = "single"
        # per-model precision modes (docs/quantization.md): fixed at
        # config load — part of every bucket key and cost tag, so an
        # int8 bucket never shares a dispatch, a cost row, or a warm
        # signal with its bf16 twin
        self.solve_modes = {m.id.lower(): config.precision.mode_for(m.template)
                            for m in config.models}
        # learned chip-seconds table (docs/scheduler.md): always
        # constructed — the gate consults it whenever rows have accrued,
        # and with an empty table every prediction is None, so the gate
        # is bit-for-bit the static path (test-pinned)
        from arbius_tpu.node.costmodel import CostModel

        # guards the scheduler-state surface shared with the ControlRPC
        # request threads (docs/concurrency.md): the learned cost table,
        # the packer's warm set + last pack order, and the boot-refined
        # solve_layout — everything GET /debug/costmodel snapshots while
        # the tick thread mutates it. Lock order is state_lock → db lock
        # (the tick's refit persists while holding it); nothing takes
        # them in reverse (conclint CONC402 audits the claim).
        self.state_lock = threading.Lock()
        self.costmodel = CostModel(min_samples=config.sched.min_samples)
        # no other thread exists yet, so this lock excludes nobody —
        # it is held so that EVERY call site of costmodel.load() holds
        # it, which is what proves (to conclint's interprocedural
        # held-set and to any future mid-life reload caller) that the
        # rows table is mutated only under the state lock
        with self.state_lock:
            self.costmodel.load(self.db)
        from arbius_tpu.node.sched import CostSched, FifoSched

        self._sched = CostSched(self, config.sched) \
            if config.sched.enabled else FifoSched()
        self._pipeline = None
        if config.pipeline.enabled:
            from arbius_tpu.node.pipeline import SolvePipeline

            self._pipeline = SolvePipeline(self, config.pipeline)

    def close(self) -> None:
        """Release owned resources: encode pool threads, then the sqlite
        handle. Safe to call more than once."""
        if self._pipeline is not None:
            self._pipeline.shutdown()
        self.db.close()

    # -- boot (start.ts:11-52 + index.ts:971-1020) -----------------------
    def boot(self, *, skip_self_test: bool = False) -> None:
        if self.config.compile_cache_dir:
            from arbius_tpu.utils import enable_compile_cache

            enable_compile_cache(self.config.compile_cache_dir)
        # solve mesh (docs/multichip.md): built and VALIDATED here — a
        # shape that doesn't fit jax.device_count() must die at boot
        # with one clear sentence, not as a deep XLA reshape failure
        # mid-mine. Also publishes arbius_mesh_devices and audits
        # canonical_batch divisibility against dp. (build_registry
        # builds its own mesh object for the runners; this one is the
        # node's validation + obs anchor — both come from the same
        # config, so they always agree.)
        from arbius_tpu.parallel import meshsolve

        self.mesh = meshsolve.boot_mesh(self.config.mesh,
                                        registry=self.obs.registry)
        # fleet-composition surface (docs/quantization.md): how many
        # enabled models this node serves at each precision mode — the
        # signal a mixed-precision fleet's pricing/packing reads
        modes_gauge = self.obs.registry.gauge(
            "arbius_precision_models",
            "Enabled models served at each precision mode (bf16 = the "
            "historic full-width programs; docs/quantization.md)",
            labelnames=("mode",))
        for mode in sorted({"bf16"} | set(self.solve_modes.values())):
            modes_gauge.set(float(sum(
                1 for m in self.config.models if m.enabled
                and self.solve_modes.get(m.id.lower()) == mode)),
                mode=mode)
        if self.mesh is not None:
            from arbius_tpu.parallel.mesh import mesh_tag

            # cost-model rows are keyed per layout: a relaid-out fleet
            # must not price its buckets from another layout's programs
            # (under the state lock: an early-started ControlRPC debug
            # view must never read the tag mid-publication)
            with self.state_lock:
                self.solve_layout = mesh_tag(self.mesh)
        from arbius_tpu.node.factory import mesh_contracts

        meshsolve.check_mesh_contract(self.mesh,
                                      mesh_contracts(self.config),
                                      self.config.canonical_batch)
        if self.config.aot_cache.enabled:
            # AOT executable cache (docs/compile-cache.md): installed
            # AFTER the mesh so the cache carries this node's solve
            # layout — published headers are stamped with it and the
            # warm scan filters on it, so differently-laid-out workers
            # sharing one directory never count each other's entries
            # as disk-warm. On the obs bundle so every jit_cache_get
            # under this node's ambient obs — including the boot
            # self-test below — gains the disk tier; the directory's
            # tags are scanned ONCE so disk-warm buckets count as warm
            # for the packer at boot (the cross-life half of
            # sched.warm_boost).
            from arbius_tpu.aotcache import AotCache

            self.aot_cache = AotCache(
                self.config.aot_cache.dir,
                max_bytes=self.config.aot_cache.max_bytes,
                layout=self.solve_layout)
            self.obs.aot_cache = self.aot_cache
            warm = self.aot_cache.tags()
            with self.state_lock:
                self._disk_warm_tags = warm
            if warm:
                self.obs.event("aot_cache_warm", tags=sorted(warm))
        self.db.clear_jobs_by_method("validatorStake")
        self.db.clear_jobs_by_method("automine")
        if self.chain.version() > MINER_VERSION:
            raise BootError(
                f"chain version {self.chain.version()} > miner {MINER_VERSION}"
                " — update the node (index.ts:960-969)")
        self._check_attention_impl(skip_self_test=skip_self_test)
        if not skip_self_test:
            self._boot_self_test()
        delegated = getattr(self.chain, "validator_address", self.chain.address)
        if delegated != self.chain.address:
            # the reference's seam exactly (blockchain.ts:44-67, disabled
            # there too): stake management redirects, but submitSolution
            # credits/validates msg.sender — so the SIGNER must hold its
            # own stake to mine until a delegation contract exists.
            # EngineV1.sol:398-404 gate.
            log.warning(
                "delegated_validator %s: stake reads/top-ups target the "
                "delegated address, but solutions are still submitted (and "
                "gated on-chain) as the node wallet %s — the wallet itself "
                "must hold validator stake to mine; delegated SOLVING needs "
                "the (unshipped) reference solver contract",
                delegated, self.chain.address)
        self.db.queue_job("validatorStake", {}, priority=100)
        if self.config.automine.enabled:
            self.db.queue_job("automine", {}, priority=10)
        self.chain.subscribe(self._on_event)
        log.info("node booted: %d models, address %s",
                 len(self.registry.ids()), self.chain.address)

    def _check_attention_impl(self, *, skip_self_test: bool) -> None:
        """A non-default attention impl is a different reduction order —
        a different determinism class — so it may only mine if the boot
        self-test proves it still reproduces the recorded goldens
        (ops/flash.py pins the impl once at import; runtime toggles are
        impossible by construction)."""
        from arbius_tpu.ops.flash import attention_impl

        impl = attention_impl()
        if impl == "auto":
            return
        has_golden = any(self.registry.get(mid).golden is not None
                         for mid in self.registry.ids())
        if not has_golden:
            log.warning(
                "ARBIUS_ATTN_IMPL=%s with no golden vectors registered — "
                "nothing proves this impl matches the fleet's determinism "
                "class; record goldens before mining for real", impl)
            return
        if skip_self_test:
            raise BootError(
                f"ARBIUS_ATTN_IMPL={impl}: a non-default attention impl "
                "must pass the boot self-test against the recorded goldens "
                "(its reduction order defines the determinism class) — do "
                "not skip the self-test, or unset the override")

    def _boot_self_test(self) -> None:
        """Golden-CID reproducibility check before mining anything
        (index.ts:984-1001): nondeterministic hardware must fail loudly
        at boot, not via slashing."""
        for mid in self.registry.ids():
            m = self.registry.get(mid)
            if m.golden is None:
                continue
            inp, seed, expected = m.golden
            hydrated = hydrate_input(dict(inp), m.template)
            got, _ = solve_cid(m, hydrated, seed)
            if got.lower() != expected.lower():
                raise BootError(
                    f"boot self-test failed for {mid}: got {got}, "
                    f"expected {expected} — nondeterministic build/hardware")

    def _inc(self, name: str, **labels) -> None:
        self.obs.registry.counter(f"arbius_{name}_total").inc(**labels)

    # -- event handlers ---------------------------------------------------
    def _on_event(self, ev) -> None:
        # events can arrive outside tick() (the local engine pushes
        # synchronously from any tx, including RPC-thread submits), so
        # the handler activates this node's obs itself
        with use_obs(self.obs):
            self._dispatch_event(ev)

    def _dispatch_event(self, ev) -> None:
        name = ev.name
        if name == "TaskSubmitted":
            self._on_task_submitted(ev.args)
        elif name == "SolutionSubmitted":
            self._on_solution_submitted(ev.args)
        elif name == "ContestationSubmitted":
            self._on_contestation(ev.args)
        elif name == "SolutionClaimed":
            # engine flips claimed before emitting, so the generic sync
            # stores claimed=True
            self._sync_solution("0x" + ev.args["task"].hex())
        elif name == "ContestationVote":
            self.db.store_vote("0x" + ev.args["task"].hex(),
                               ev.args["addr"], ev.args["yea"])
        elif name == "VersionChanged":
            if ev.args["version"] > MINER_VERSION:
                log.error("chain version now %d > miner %d — stop mining",
                          ev.args["version"], MINER_VERSION)

    def _on_task_submitted(self, args: dict) -> None:
        if self.task_feed is not None:
            # fleet worker mode: the coordinator owns the task stream —
            # work arrives only as leases (docs/fleet.md); the node
            # stays subscribed for solution/contestation vigilance
            return
        taskid = "0x" + args["id"].hex()
        model = "0x" + args["model"].hex()
        self._inc("tasks_seen")
        if self.registry.get(model) is None:
            return
        with span("task.event", taskid=taskid, model=model):
            self.db.store_task(taskid, model, args["fee"], args["sender"],
                               self.chain.now, 0, "")
            self.db.queue_job("task", {"taskid": taskid}, concurrent=True)

    def _sync_solution(self, taskid: str) -> None:
        sol = self.chain.get_solution(taskid)
        if sol is not None:
            self.db.store_solution(taskid, sol.validator, sol.blocktime,
                                   sol.claimed, "0x" + sol.cid.hex())

    def _on_solution_submitted(self, args: dict) -> None:
        taskid = "0x" + args["task"].hex()
        self._sync_solution(taskid)
        # solution for a task we proved invalid → contest (index.ts:236-266)
        if args["addr"] != self.chain.address and \
                self.db.is_invalid_task(taskid):
            self.db.queue_job("contest", {"taskid": taskid}, priority=50)

    def _on_contestation(self, args: dict) -> None:
        taskid = "0x" + args["task"].hex()
        self.db.store_contestation(taskid, args["addr"], self.chain.now)
        # if we are the accused solver the engine auto-nay-voted for us
        # (EngineV1.sol:922-934) — our escrow is locked until the vote
        # finishes, so schedule the finish ourselves
        sol = self.chain.get_solution(taskid)
        if sol is not None and sol.validator == self.chain.address:
            self._queue_vote_finish(taskid)
        if args["addr"] == self.chain.address:
            return
        if self.db.is_invalid_task(taskid):
            self.db.queue_job("vote", {"taskid": taskid, "yea": True},
                              priority=50)

    # -- job processing (two-phase, index.ts:879-958) ---------------------
    def run(self, *, stop: "callable | None" = None) -> None:
        """Production loop: poll the queue at poll_interval_ms
        (index.ts:1078-1101). `stop()` → True ends the loop (tests/SIGTERM
        handlers); chain time drives job due-ness, wall time drives cadence."""
        import time as _time

        while not (stop and stop()):
            self.tick()
            _time.sleep(self.config.poll_interval_ms / 1000.0)

    def tick(self) -> int:
        """One poll: run due concurrent jobs, then one serial pass.
        Returns number of jobs processed."""
        with use_obs(self.obs):
            return self._tick()

    def _tick(self) -> int:
        # one tick = one sqlite commit (docs/pipeline.md, db.batch()):
        # the window covers the event poll and the fleet lease pump
        # too, not just the job cycle — a poll delivering a burst of
        # events used to fsync per event-handler write (the 10k fleet
        # flood surfaced it). Losing the window to a crash is safe on
        # every path it now covers: a re-poll replays the event range
        # (RpcChain's cursor is in-memory; handlers dedupe via INSERT
        # OR IGNORE) and an expired lease whose local jobs vanished is
        # simply re-dealt (the lease table is the durable record).
        with self.db.batch():
            return self._tick_inner()

    def _tick_inner(self) -> int:
        # pull-based backends (RpcChain) deliver events here; the local
        # engine pushes synchronously and has no poll_events. A transport
        # blip must not kill the run() loop — the next tick re-polls the
        # same range (handlers dedupe replayed events).
        poll = getattr(self.chain, "poll_events", None)
        if poll is not None:
            try:
                poll()
            except Exception as e:  # noqa: BLE001 — endpoint flake
                # counted, not just logged: the healthwatch rpc_degraded
                # rule watches this — a flapping endpoint must be a
                # first-class signal, not log archaeology
                # (docs/healthwatch.md)
                self.obs.registry.counter(
                    "arbius_event_poll_failures_total",
                    "Event polls that failed (retried next tick) — a "
                    "flaky endpoint's first-class signal "
                    "(docs/healthwatch.md)").inc()
                log.warning("event poll failed (will retry): %r", e)
        if self.task_feed is not None:
            # fleet worker mode: settle/heartbeat/pull leases before the
            # queue drains, so freshly leased tasks run this very tick —
            # the same tick alignment the event path gives a bare node
            # (docs/fleet.md determinism argument). A lease-db hiccup
            # must not kill the run loop; the next tick re-pumps.
            try:
                self.task_feed.pump(self)
            except Exception as e:  # noqa: BLE001 — lease-db flake
                self.obs.registry.counter(
                    "arbius_lease_pump_failures_total",
                    "Lease pumps that failed (re-pumped next tick) — "
                    "the fleet worker's lease-plane health signal "
                    "(docs/healthwatch.md)").inc()
                log.warning("lease pump failed (will retry): %r", e)
        done = self._drain_jobs()
        if self.healthwatch is not None:
            # one evaluation per tick, AFTER the job cycle so this
            # tick's counters are judged exactly once; degrades to a
            # journaled skip internally — never why a tick fails
            self.healthwatch.evaluate(self, done)
        return done

    def _drain_jobs(self) -> int:
        jobs = self.db.get_jobs(self.chain.now)
        if not jobs:
            return 0
        done = 0
        concurrent = [j for j in jobs if j.concurrent]
        serial = [j for j in jobs if not j.concurrent]
        for job in concurrent:
            done += self._run_job(job)
        # dp batching: group due solve jobs into one XLA dispatch
        solves = [j for j in serial if j.method == "solve"]
        others = [j for j in serial if j.method != "solve"]
        if solves:
            done += self._process_solve_batch(solves)
        for job in others:
            done += self._run_job(job)
        return done

    def _run_job(self, job: Job) -> int:
        try:
            handler = {
                "task": self._process_task,
                "claim": self._process_claim,
                "contest": self._process_contest,
                "vote": self._process_vote,
                "validatorStake": self._process_validator_stake,
                "automine": self._process_automine,
                "pinTaskInput": self._process_pin_task_input,
                "voteFinish": self._process_vote_finish,
            }.get(job.method)
            if handler is None:
                log.error("unknown job method %s", job.method)
                self._fail_job(job, ValueError("unknown job method"))
                return 0
            with span("job." + job.method,
                      taskid=job.data.get("taskid"), job_id=job.id):
                handler(job.data)
            self.db.delete_job(job.id)
            return 1
        except Exception as e:  # noqa: BLE001 — failed_jobs quarantine
            log.warning("job %s failed: %r", job.method, e)
            self._fail_job(job, e)
            return 0

    def _fail_job(self, job: Job, e: Exception) -> None:
        """failed_jobs quarantine + the obs failure record (counter +
        journal) — retry/failure visibility the reference lacks."""
        self._c_jobs_failed.inc(method=job.method)
        self.obs.event("job_failed", method=job.method,
                       taskid=job.data.get("taskid"),
                       error=f"{type(e).__name__}: {e}")
        self.db.fail_job(job)

    # -- processors -------------------------------------------------------
    def _process_task(self, data: dict) -> None:
        """Validate + hydrate + queue solve (index.ts:506-564)."""
        taskid = data["taskid"]
        task = self.chain.get_task(taskid)
        if task is None:
            raise ValueError(f"task {taskid} not on chain")
        if task.version != 0:
            self.db.mark_invalid_task(taskid)
            self._inc("tasks_invalid")
            return
        model_id = "0x" + task.model.hex()
        m = self.registry.get(model_id)
        if m is None:
            return
        filters = [MiningFilter(minfee=m.min_fee, owner=o)
                   for o in m.allowed_owners] or \
                  [MiningFilter(minfee=m.min_fee)]
        result = check_model_filter(
            {model_id: (m.template, filters)}, model=model_id,
            now=self.chain.now, fee=task.fee, blocktime=task.blocktime,
            owner=task.owner)
        if not result.filter_passed:
            return
        # conservative pre-hydration floor — the gate's pre-costsched
        # placement: a task priced below EVERY cost the hydrated gate
        # could predict is rejected before its input is even fetched,
        # so a spam flood never costs chain RPCs or hydration
        if not self._fee_covers_cost(task.fee, model_id=model_id,
                                     taskid=taskid):
            self._c_unprofitable.inc(model=model_id)
            log.info("task %s fee %d below cost floor — skipping",
                     taskid, task.fee)
            return
        raw = self.chain.get_task_input_bytes(taskid)
        if raw is None:
            raise ValueError(f"no input bytes for {taskid}")
        try:
            with span("task.hydrate", taskid=taskid, model=model_id):
                obj = json.loads(raw.decode("utf-8"))
                hydrated = hydrate_input(obj, m.template)
        except (ValueError, HydrationError) as e:
            # invalid input: remember, so any solution gets contested
            log.info("task %s invalid input: %r", taskid, e)
            self.db.mark_invalid_task(taskid)
            self._inc("tasks_invalid")
            self.obs.event("task_invalid", taskid=taskid,
                           error=f"{type(e).__name__}: {e}")
            return
        hydrated["seed"] = taskid2seed(taskid)
        # runner intake hook: a family may stamp derived bucket fields
        # onto the hydrated input (textgen's _prompt_bucket/
        # _decode_bucket — docs/text-serving.md) so the precise gate,
        # store_task_input, and the solve-batch bucket_key all see one
        # consistent shape. Pure in (input, fleet config): every honest
        # node derives the same fields.
        prep = getattr(m.runner, "prepare_hydrated", None)
        if prep is not None:
            hydrated = prep(hydrated)
        # precise per-bucket gate, costsched only: the learned model
        # prices per bucket SHAPE, and the shape only exists once the
        # template's defaults are folded in — so this second pass can
        # only SHARPEN the pre-floor above, never relax it. Without
        # costsched the static pre-floor already decided, and a second
        # identical check would just double-journal.
        if self.config.sched.enabled and not self._fee_covers_cost(
                task.fee, model_id=model_id, taskid=taskid,
                hydrated=hydrated):
            self._c_unprofitable.inc(model=model_id)
            log.info("task %s fee %d below cost floor — skipping",
                     taskid, task.fee)
            return
        if self.mesh is not None:
            # mesh-shape intake gate (docs/multichip.md): a video task
            # whose num_frames does not divide sp cannot run on this
            # layout (the shard_map hard-partitions frames) — skip it
            # BEFORE queuing, instead of burning solve attempts on a
            # doomed compile. NOT marked invalid: the task is protocol-
            # valid and other layouts can mine it honestly.
            sp = self.mesh.shape.get("sp", 1)
            frames = hydrated.get("num_frames")
            if sp > 1 and frames is not None and int(frames) % sp:
                log.info("task %s num_frames=%s not divisible by mesh "
                         "sp=%d — not mineable under this layout, "
                         "skipping", taskid, frames, sp)
                self.obs.registry.counter(
                    "arbius_tasks_unmineable_total",
                    "Tasks skipped because their shape cannot run on "
                    "the configured mesh layout").inc()
                return
        self.db.store_task_input(taskid, "", hydrated)
        if self.store is not None or self.pinner is not None:
            # pin the raw input so contestation evidence stays
            # retrievable (index.ts:175-186 pinTaskInput)
            self.db.queue_job("pinTaskInput", {"taskid": taskid},
                              concurrent=True)
        self.db.queue_job("solve", {"taskid": taskid, "model": model_id},
                          concurrent=False)

    def _static_solve_seconds(self) -> float:
        """The pre-costsched cost estimate, unchanged: observed infer
        p50 across everything, or the configured prior before any
        samples. The gate AND the packer degrade to this exact number
        whenever the learned model has no row (docs/scheduler.md pins
        that an empty `cost_model` table reproduces it bit-for-bit)."""
        samples = self._h_stage.values(stage="infer")
        if samples:
            return sorted(samples)[len(samples) // 2]
        return self.config.assumed_solve_seconds

    def _fee_covers_cost(self, fee: int, *, model_id: str | None = None,
                         taskid: str | None = None,
                         hydrated: dict | None = None) -> bool:
        """Profitability gate (beyond the reference's static fee filter):
        predicted chip-seconds × operator rate must not exceed the fee.
        Disabled at rate 0. Learned pricing is opt-in via
        `sched.enabled` — disabled, the gate is the static path the node
        always had (estimate = infer p50, else the configured prior).

        Two placements share this method (docs/scheduler.md):

          * `hydrated=None` — the pre-hydration floor, at the gate's
            pre-costsched position: the estimate is the CHEAPEST cost
            any hydrated prediction could give (min of the static
            estimate and every predict-eligible learned row of this
            model+layout), so it rejects only tasks the precise gate
            would reject too — spam never costs an input fetch or a
            hydration. Source `"floor"` when a learned row set it.
          * `hydrated` given — the precise per-bucket gate (costsched
            only): the learned row for the task's exact (model, bucket,
            layout), else the static estimate.

        The FINAL decision is journaled (`gate_decision`: fee,
        predicted cost, provenance, verdict) exactly once per task —
        pre-floor accepts under costsched are re-decided (and then
        journaled) by the precise gate."""
        rate = self.config.min_fee_per_second
        if rate <= 0:
            return True
        from arbius_tpu.node.costmodel import bucket_str
        from arbius_tpu.node.solver import bucket_key

        sched_on = self.config.sched.enabled
        est = None
        source = "static"
        if sched_on and model_id is not None:
            mode = self.solve_mode(model_id)
            if hydrated is not None:
                key = bucket_key(model_id, hydrated, mode)
                est = self.costmodel.predict(model_id, bucket_str(key),
                                             self.solve_layout, mode)
                if est is not None:
                    source = "cost_model"
            else:
                learned = [
                    r.chip_seconds for r in self.costmodel.rows.values()
                    if r.model == model_id and r.layout == self.solve_layout
                    and r.mode == mode
                    and r.samples >= self.costmodel.min_samples]
                if learned:
                    static = self._static_solve_seconds()
                    est = min(min(learned), static)
                    if est < static:
                        source = "floor"
        if est is None:
            est = self._static_solve_seconds()
        floor = int(est * rate)
        ok = fee >= floor
        prefloor_accept = hydrated is None and sched_on and ok
        if not prefloor_accept:
            self.obs.event("gate_decision", taskid=taskid, model=model_id,
                           fee=str(fee), predicted_seconds=round(est, 6),
                           cost_floor=str(floor), source=source,
                           verdict="accept" if ok else "reject")
        return ok

    def solve_mode(self, model_id: str) -> str:
        """The precision mode this node serves a model at
        (docs/quantization.md) — bf16 for anything unconfigured."""
        return self.solve_modes.get(model_id.lower(), "bf16")

    def bucket_disk_warm(self, key: tuple, entries: list) -> bool:
        """Cross-life warm signal for the packer (docs/compile-cache.md):
        True when this bucket's executable is already serialized in the
        AOT cache — a boot-scanned tag-set lookup, no disk I/O per pack.
        The join key is the runner's `cache_tag` (which defers to the
        pipeline's one `bucket_tag` definition), so the scheduler's
        notion of "disk warm" can never drift from what a dispatch
        would actually load. Called under the state lock (the pack)."""
        tags = self._disk_warm_tags
        if not tags:
            return False
        tag = self._bucket_exec_tag(key, entries[0][1])
        return tag is not None and tag in tags

    def _bucket_exec_tag(self, key: tuple, hydrated: dict) -> str | None:
        """THE executable-cache tag a dispatch of this bucket would use
        — the one derivation `bucket_disk_warm` (scheduler disk-warm
        join) and `_observe_infer` (perf-card bind) both ride, so the
        two joins can never desynchronize. Defers to the runner's
        `cache_tag`, which defers to the pipeline's one `bucket_tag`
        definition (docs/compile-cache.md). None when the runner has no
        tag surface or derivation fails."""
        m = self.registry.get(key[0])
        cache_tag = getattr(m.runner, "cache_tag", None) \
            if m is not None else None
        if cache_tag is None:
            return None
        try:
            return cache_tag(hydrated, max(1, self.config.canonical_batch))
        except Exception:  # noqa: BLE001 — a tag is advisory metadata
            return None

    def _bucket_fees(self, entries: list) -> int:
        """Summed task fees of one bucket (the packer's reward side):
        from the task cache the event handler filled; a missing row
        prices as 0 — the packer only deprioritizes it."""
        total = 0
        for job, _ in entries:
            row = self.db.get_task(job.data["taskid"])
            if row is not None:
                total += int(row["fee"])
        return total

    def _ingest_costs(self) -> None:
        """Fold the tick's tagged stage=infer observations into the
        cost model, refit, and persist the fitted rows (inside the
        tick's batch window — no extra fsync). Holds the state lock:
        a /debug/costmodel snapshot mid-refit would iterate the rows
        dict while it grows."""
        with self.state_lock:
            if self.costmodel.ingest(self._h_stage):
                self.costmodel.refit(self.chain.now)
                self.costmodel.persist(self.db, self.chain.now)
        scope = self.obs.perfscope
        if scope is not None:
            # perfscope cards ride the same batch window as cost rows
            # (docs/perfscope.md): dirty cards persist once per tick,
            # no extra fsync
            rows = scope.dirty_rows(self.chain.now)
            if rows:
                self.db.upsert_perf_cards(rows)

    def _process_solve_batch(self, jobs: list[Job]) -> int:
        """Group solve jobs by shape bucket, pack the buckets (FIFO by
        default; predicted fee/chip-second under costsched —
        docs/scheduler.md), and run each bucket as ONE batched dispatch
        (solve_cid_batch → the runner's dp batch path). Commit/reveal
        stays per-task (chain semantics). Packing permutes whole
        buckets only; entries inside a bucket keep arrival order, so
        chunking — and therefore bytes — is packing-invariant."""
        from arbius_tpu.node.solver import bucket_key

        by_bucket: dict[tuple, list[tuple[Job, dict]]] = {}
        for job in jobs:
            hydrated = self.db.get_task_input(job.data["taskid"])
            if hydrated is None:
                self._fail_job(job, ValueError("no stored task input"))
                continue
            by_bucket.setdefault(
                bucket_key(job.data["model"], hydrated,
                           self.solve_mode(job.data["model"])), []).append(
                (job, hydrated))
        # fee SELECTs stay OUTSIDE the state lock (per-task sqlite I/O
        # must not stall the RPC debug views or the device stage's
        # mark_warm); only the pack itself reads/writes packer state
        scored = [(key, entries,
                   self._bucket_fees(entries) if self._sched.wants_fees
                   else 0)
                  for key, entries in by_bucket.items()]
        with self.state_lock:
            packed = self._sched.pack(scored)
        try:
            if self._pipeline is not None and not self.config.evilmode:
                # staged executor (docs/pipeline.md): same buckets, same
                # chunking, same bytes — a pipelined schedule in packed
                # order (the device stage feeds in pack order). evilmode
                # (a contestation drill that fabricates CIDs without
                # solving) stays on the reference-shaped path below.
                buckets = [(self.registry.get(b.key[0]), b.entries, b.key)
                           for b in packed]
                with span("solve.pipeline",
                          n=sum(len(e) for _, e, _ in buckets)):
                    return self._pipeline.run(buckets)
            done = 0
            for b in packed:
                m = self.registry.get(b.key[0])
                taskids = [job.data["taskid"] for job, _ in b.entries]
                with span("solve.batch", model=b.key[0], n=len(b.entries),
                          taskids=taskids):
                    done += self._solve_bucket(m, b.entries, b.key)
            return done
        finally:
            self._ingest_costs()

    def _cost_tag(self, key: tuple, n: int) -> str:
        from arbius_tpu.node.costmodel import bucket_str, make_cost_tag
        from arbius_tpu.node.solver import bucket_mode

        return make_cost_tag(key[0], bucket_str(key), self.solve_layout, n,
                             mode=bucket_mode(key))

    def _observe_infer(self, key: tuple, n: int, seconds: float,
                       hydrated: dict | None = None) -> None:
        """ONE bucket dispatch's infer observation, shared by both solve
        schedules: feeds the cost-tagged `arbius_stage_seconds{infer}`
        sample (the learned model's input) and, when perfscope is
        installed (docs/perfscope.md), binds the bucket's PerfCard to
        the same (model, bucket, layout, mode) cost key — with the
        padding waste `solver.chunk_items` would dispatch for `n` real
        tasks — and evaluates the drift band. `hydrated` is any one of
        the bucket's hydrated inputs (the runner's `cache_tag` join
        key, exactly as `bucket_disk_warm` uses it)."""
        self._h_stage.observe(seconds, stage="infer",
                              tag=self._cost_tag(key, n))
        scope = self.obs.perfscope
        if scope is None or hydrated is None:
            return
        exec_tag = self._bucket_exec_tag(key, hydrated)
        if exec_tag is None:
            return
        from arbius_tpu.node.costmodel import bucket_str
        from arbius_tpu.node.solver import bucket_mode

        m = self.registry.get(key[0])
        cb = max(1, self.config.canonical_batch)
        padded = 0
        if cb > 1 and getattr(m.runner, "run_batch", None) is not None:
            # chunk_items pads the last chunk to the canonical batch by
            # repeating its final real item — those slots burn chip
            # time without earning fees (the card's padding_waste)
            chunks = -(-n // cb)
            padded = chunks * cb - n
        else:
            # non-batching runner (or canonical_batch 1): each item is
            # its own executable dispatch, nothing padded
            chunks = n
        scope.observe_dispatch(
            exec_tag, model=key[0], bucket=bucket_str(key),
            layout=self.solve_layout, mode=bucket_mode(key),
            batch=cb, real=n, padded=padded, dispatches=chunks,
            seconds=seconds)

    def _solve_bucket(self, m, entries: list[tuple[Job, dict]],
                      key: tuple) -> int:
        t_start = self.chain.now
        # detlint: allow[DET101] obs stage timing; never reaches solve bytes
        w_start = time.perf_counter()
        try:
            with self._maybe_profile():
                results = solve_cid_batch(
                    m, [(h, h["seed"]) for _, h in entries],
                    evilmode=self.config.evilmode,
                    canonical_batch=self.config.canonical_batch)
        except Exception as e:  # noqa: BLE001 — whole bucket failed
            log.warning("bucket solve failed: %r", e)
            for job, _ in entries:
                self._fail_job(job, e)
            return 0
        # this bucket's executable is compiled now — the packer's
        # warm-preference signal (docs/scheduler.md)
        with self.state_lock:
            self._sched.mark_warm(key)
        # tagged with the cost key so the learned model can attribute
        # the bucket's wall seconds to (model, bucket, layout, n) —
        # and the perfscope card, when installed, binds on the same key
        # detlint: allow[DET101] obs stage timing; never reaches solve bytes
        self._observe_infer(key, len(entries),
                            time.perf_counter() - w_start,
                            hydrated=entries[0][1])
        done = 0
        # detlint: allow[DET101] obs stage timing; never reaches solve bytes
        w_commit = time.perf_counter()
        for (job, _), (cid, files) in zip(entries, results):
            try:
                with span("solve.task", taskid=job.data["taskid"], cid=cid):
                    # pin BEFORE revealing: a revealed CID whose bytes are
                    # nowhere fetchable is exactly what contestation
                    # slashes
                    self._store_solution(job.data["taskid"], cid, files)
                    self._commit_reveal(job.data["taskid"], cid, t_start)
                self.db.delete_job(job.id)
                done += 1
            except Exception as e:  # noqa: BLE001
                log.warning("solve commit failed: %r", e)
                self._fail_job(job, e)
        # detlint: allow[DET101] obs stage timing; never reaches solve bytes
        commit_seconds = time.perf_counter() - w_commit
        self._h_stage.observe(commit_seconds, stage="commit")
        # on the synchronous path the whole pin/commit tail runs with
        # nothing dispatched on the device — that window IS chip idle
        # (the pipeline's A/B comparison baseline, docs/pipeline.md)
        self._c_idle.inc(commit_seconds)
        return done

    def _store_solution(self, taskid: str, cid: str, files: dict) -> None:
        """Pin solution bytes under their CID (data availability: the
        committed CID must be fetchable — ipfs.ts:28-76 equivalent) via the
        configured strategy, with the reference's expretry envelope.

        Remote strategies additionally mirror into the local store (the
        node's own gateway keeps serving). If pinning exhausts its retries
        AND no local mirror holds the bytes, this RAISES — the caller must
        not reveal a CID nobody can fetch."""
        if not files:
            return
        from arbius_tpu.l0.cid import cid_hex
        from arbius_tpu.node.pinners import LocalPinner
        from arbius_tpu.node.retry import expretry

        with span("solve.pin", taskid=taskid, n=len(files)):
            mirrored = False
            if self.store is not None and \
                    not isinstance(self.pinner, LocalPinner):
                stored = cid_hex(self.store.put_files(files))
                if stored != cid:
                    # the mirror may end up the only copy (remote pin can
                    # fail below) — never let a silently-corrupt sole copy
                    # back a reveal
                    log.error("mirror/commit CID mismatch: %s != %s",
                              stored, cid)
                mirrored = stored == cid
            if self.pinner is None:
                return
            try:
                pinned = cid_hex(expretry(
                    lambda: self.pinner.pin_files(files, taskid=taskid),
                    max_delay=self.config.retry_max_delay,
                    sleep=self._retry_sleep, op="pin_files"))
            except Exception as e:  # noqa: BLE001 — availability decision
                if not mirrored:
                    raise  # no copy exists anywhere: block the reveal
                log.error("pinning %s failed (serving from local mirror): "
                          "%r", taskid, e)
                return
            if pinned != cid:
                # same pure function on the same bytes; a mismatch means
                # disk corruption or a codec bug — keep mining but say so
                # loudly
                log.error("pin/commit CID mismatch: %s != %s", pinned, cid)

    def _process_pin_task_input(self, data: dict) -> None:
        """Pin the raw task input through the configured strategy (the
        reference's pinTaskInput goes through the same pinFileToIPFS
        switch, index.ts:175-186) and mirror it into the local store."""
        raw = self.chain.get_task_input_bytes(data["taskid"])
        if raw is None:
            raise ValueError(f"no input bytes for {data['taskid']}")
        if self.store is not None:
            self.store.put_blob(raw)
        from arbius_tpu.node.pinners import LocalPinner
        from arbius_tpu.node.retry import expretry

        if self.pinner is not None and not isinstance(self.pinner, LocalPinner):
            # same expretry envelope the reference's pinTaskInput runs in
            # (index.ts:175-186) — one transient HTTP error must not
            # quarantine the job and lose contestation evidence
            expretry(lambda: self.pinner.pin_blob(raw,
                                                  filename=data["taskid"]),
                     max_delay=self.config.retry_max_delay,
                     sleep=self._retry_sleep, op="pin_blob")

    def _maybe_profile(self):
        """jax.profiler trace around every Nth solve dispatch when the
        operator sets profile_dir (SURVEY.md §5: the reference has no
        miner-side tracing at all)."""
        import contextlib

        cfg = self.config
        if not cfg.profile_dir or cfg.profile_every <= 0:
            return contextlib.nullcontext()
        self._profile_counter = getattr(self, "_profile_counter", 0) + 1
        if self._profile_counter % cfg.profile_every:
            return contextlib.nullcontext()
        import jax

        return jax.profiler.trace(cfg.profile_dir)

    def _commit_reveal(self, taskid: str, cid: str, t_start: int, *,
                       progress=None, skip_commit: bool = False) -> None:
        """index.ts:566-672: skip if solved (contest on CID mismatch —
        the reference merely bails, index.ts:568-579; contesting here is
        strictly more vigilant), else commit → reveal → queue claim.

        `progress(stage, resumed=...)` is the pipeline's checkpoint hook,
        called AFTER each chain write is known to have landed (commit,
        then reveal) — never before, so a recorded stage is always true.
        `skip_commit` resumes past a commitment the sqlite checkpoint
        proves landed in a previous life (same CID; re-signalling would
        only round-trip into the engine's already-signalled revert)."""
        if progress is None:
            progress = lambda stage, resumed=False: None  # noqa: E731
        existing = self.chain.get_solution(taskid)
        if existing is not None:
            if "0x" + existing.cid.hex() != cid:
                if existing.validator != self.chain.address:
                    self.db.mark_invalid_task(taskid)
                    self.db.queue_job("contest", {"taskid": taskid},
                                      priority=50)
                return
            if existing.validator == self.chain.address:
                # our own reveal from a previous life (crash after the
                # reveal landed but before the claim was scheduled) —
                # finish the bookkeeping instead of stranding the reward
                progress("reveal", resumed=True)
                if not existing.claimed and \
                        not self.db.has_job("claim", {"taskid": taskid}):
                    self.db.queue_job(
                        "claim", {"taskid": taskid},
                        waituntil=self.chain.now
                        + self.chain.min_claim_solution_time()
                        + self.config.claim_delay_buffer)
            return
        if skip_commit:
            progress("commit", resumed=True)
        else:
            if self.commit_guard is not None and \
                    not self.commit_guard(taskid, cid):
                # another fleet worker holds this task's commit rights
                # and its lease is live (docs/fleet.md cross-process
                # dedupe): signalling here would double-commit the
                # fleet's work — skip; the lease pump settles the lease
                # when their reveal lands
                self.obs.event("commit_deduped", taskid=taskid, cid=cid)
                return
            with span("solve.commit", taskid=taskid):
                commitment = self.chain.generate_commitment(taskid, cid)
                try:
                    self.chain.signal_commitment(commitment)
                except EngineError:
                    pass  # already signalled (e.g. replay); reveal decides
            progress("commit")
        try:
            with span("solve.reveal", taskid=taskid):
                expretry(lambda: self.chain.submit_solution(taskid, cid),
                         tries=3, max_delay=self.config.retry_max_delay,
                         sleep=self._retry_sleep, op="submit_solution")
        except RetriesExhausted:
            sol = self.chain.get_solution(taskid)
            if sol is None:
                # the reveal never landed at all — re-raise so the job
                # quarantines visibly instead of silently dropping the
                # task (simnet SIM101 task-conservation: every task must
                # reach an accounted terminal state)
                raise
            if "0x" + sol.cid.hex() != cid:
                # lost the race to a wrong answer → contest
                self.db.mark_invalid_task(taskid)
                self.db.queue_job("contest", {"taskid": taskid}, priority=50)
                return
            if sol.validator != self.chain.address:
                return  # honest race lost: same bytes, their reward
            # our reveal LANDED but the response was lost (the retries
            # saw "solution already submitted" for our own solution) —
            # fall through to the success bookkeeping, or the claim
            # would never be scheduled (found by simnet rpc-flap)
        progress("reveal")
        self._inc("solutions_submitted")
        self._h_latency.observe(self.chain.now - t_start, tag=taskid)
        self.db.queue_job(
            "claim", {"taskid": taskid},
            waituntil=self.chain.now
            + self.chain.min_claim_solution_time()
            + self.config.claim_delay_buffer)

    def _process_claim(self, data: dict) -> None:
        """index.ts:728-750."""
        taskid = data["taskid"]
        if self.chain.get_contestation(taskid) is not None:
            return  # resolved via contestationVoteFinish instead
        try:
            expretry(lambda: self.chain.claim_solution(taskid),
                     tries=3, max_delay=self.config.retry_max_delay,
                     sleep=self._retry_sleep, op="claim_solution")
        except RetriesExhausted:
            sol = self.chain.get_solution(taskid)
            if sol is None or not sol.claimed:
                raise  # genuinely unclaimed — quarantine visibly
            # the claim LANDED but the response was lost (the retries saw
            # "already claimed") — count it (found by simnet rpc-flap)
        self._inc("solutions_claimed")

    def _process_contest(self, data: dict) -> None:
        """index.ts:674-707: contest, or pile onto an existing one."""
        taskid = data["taskid"]
        try:
            self.chain.submit_contestation(taskid)
            self._inc("contestations_submitted")
            self._queue_vote_finish(taskid)
        except EngineError:
            if not self.chain.contestation_voted(taskid) and \
                    self.chain.validator_can_vote(taskid) == 0:
                self.chain.vote_on_contestation(taskid, True)
                self._inc("votes_cast")
                self._queue_vote_finish(taskid)

    def _process_vote(self, data: dict) -> None:
        """index.ts:709-726."""
        taskid = data["taskid"]
        if self.chain.contestation_voted(taskid):
            return
        if self.chain.validator_can_vote(taskid) != 0:
            return
        self.chain.vote_on_contestation(taskid, data["yea"])
        self._inc("votes_cast")
        self._queue_vote_finish(taskid)

    def _queue_vote_finish(self, taskid: str) -> None:
        """Schedule contestationVoteFinish after the vote window for a
        contestation we have a stake in. The reference leaves this as a
        stub (index.ts:392-395 'not implemented yet'), which strands every
        participant's escrowed slash until some human calls finish."""
        c = self.chain.get_contestation(taskid)
        if c is None:
            return
        data = {"taskid": taskid}
        if self.db.has_job("voteFinish", data):
            return
        due = c.blocktime + self.chain.min_contestation_vote_period() \
            + self.config.vote_finish_delay_buffer
        self.db.queue_job("voteFinish", data, waituntil=due)

    def _process_vote_finish(self, data: dict) -> None:
        """Finish the contestation vote (EngineV1.sol:1026-1106), paying
        out escrows pageful-by-pageful. Racing other finishers is fine —
        the pagination index advances on-chain."""
        taskid = data["taskid"]
        c = self.chain.get_contestation(taskid)
        if c is None:
            return
        period = self.chain.min_contestation_vote_period()
        if self.chain.now < c.blocktime + period:
            # clock skew between scheduling and chain time — push it back
            self.db.queue_job(
                "voteFinish", data,
                waituntil=c.blocktime + period
                + self.config.vote_finish_delay_buffer)
            return
        try:
            self.chain.contestation_vote_finish(taskid, 64)
            self._inc("vote_finishes")
        except EngineError as e:
            log.info("voteFinish %s: %r (already finished?)", taskid, e)

    def _process_validator_stake(self, data: dict) -> None:
        """Auto top-up (index.ts:397-472) with the 1%/20% buffers, then
        re-queue self at +interval — in a finally: a transient RPC fault
        must not kill the heartbeat forever (a quarantined stake job
        would never re-queue itself; found by simnet rpc-flap)."""
        try:
            minimum = self.chain.get_validator_minimum()
            staked = self.chain.validator_staked() - \
                self.chain.validator_withdraw_pending()
            floor = minimum + int(minimum * self.config.stake.buffer_min_percent)
            if staked < floor:
                target = minimum + int(minimum * self.config.stake.buffer_percent)
                need = target - staked
                if need > 0:
                    if self.chain.token_balance() < need:
                        log.error("stake top-up needs %d but balance is %d",
                                  need, self.chain.token_balance())
                    else:
                        self.chain.validator_deposit(need)
        finally:
            self.db.queue_job("validatorStake", {}, priority=100,
                              waituntil=self.chain.now
                              + self.config.stake.check_interval)

    def _process_automine(self, data: dict) -> None:
        """Self-submitted work (index.ts:474-503)."""
        a = self.config.automine
        try:
            self.chain.submit_task(
                a.version, self.chain.address, a.model, a.fee,
                json.dumps(a.input, sort_keys=True).encode())
        finally:
            self.db.queue_job("automine", {}, priority=10,
                              waituntil=self.chain.now + a.delay)
