"""Two-tier node configuration (SURVEY.md §5 config system).

Tier 1 (deployment constants): chain addresses and model ids — the
reference bakes these into `miner/src/config.json:1-24`.
Tier 2 (operator config): what the reference's `MiningConfig.json`
holds (`miner/src/types.ts:3-54`) — enabled models with filters,
stake buffers, automine, RPC port, db path. Parsed + schema-validated
up front (the reference only JSON-parses, start.ts:12-18; we reject
unknown keys and wrong types at boot instead of failing mid-mine).
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field


class ConfigError(ValueError):
    pass


@dataclass(frozen=True)
class ModelConfig:
    id: str                       # 0x model hash
    template: str                 # template name (e.g. "anythingv3")
    enabled: bool = True
    min_fee: int = 0              # wad; checkModelFilter mirror
    allowed_owners: tuple[str, ...] = ()
    checkpoint: str | None = None  # orbax param dir (None: random init)
    tiny: bool = False             # reduced topology (dev/CI hosts)
    # prompt tokenizer: "byte" (deterministic default) or "clip_bpe"
    # (vocab/merges files required — pairs with converted CLIP weights)
    tokenizer: str = "byte"
    vocab_path: str | None = None
    merges_path: str | None = None
    # weights dtype on-device: bfloat16 halves HBM weight traffic (the
    # reference's fp16-container trade); goldens are dtype-specific
    weights_dtype: str = "float32"
    # boot self-test golden vector: {"input": {...}, "seed": int,
    # "cid": "0x1220..."} — the TPU fleet's analogue of the reference's
    # pinned kandinsky CID (miner/src/index.ts:989-999)
    golden: dict | None = None
    # sequence-parallel comm strategy for video templates on an sp>1
    # mesh: "ring" (K/V rotation) or "ulysses" (all_to_all head
    # re-shard; needs heads % sp == 0). Ignored by image templates.
    sp_strategy: str = "ring"

    def __post_init__(self):
        if self.weights_dtype not in ("float32", "bfloat16"):
            raise ConfigError(f"model {self.id}: unknown weights_dtype "
                              f"{self.weights_dtype!r}")
        if self.sp_strategy not in ("ring", "ulysses"):
            raise ConfigError(f"model {self.id}: unknown sp_strategy "
                              f"{self.sp_strategy!r}")
        if self.tokenizer not in ("byte", "clip_bpe"):
            raise ConfigError(f"model {self.id}: unknown tokenizer "
                              f"{self.tokenizer!r}")
        if self.tokenizer == "clip_bpe" and not (
                self.vocab_path and self.merges_path):
            raise ConfigError(f"model {self.id}: clip_bpe tokenizer needs "
                              "vocab_path and merges_path")
        if self.golden is not None and not (
                isinstance(self.golden, dict)
                and {"input", "seed", "cid"} <= set(self.golden)):
            raise ConfigError(f"model {self.id}: golden needs "
                              "input/seed/cid keys")


@dataclass(frozen=True)
class AutomineConfig:
    enabled: bool = False
    version: int = 0
    model: str = ""
    fee: int = 0
    input: dict = field(default_factory=dict)
    delay: int = 60               # seconds between self-submitted tasks


@dataclass(frozen=True)
class StakeConfig:
    """Auto top-up thresholds (index.ts:411-472): keep staked above
    minimum*(1+buffer_min); when topping up, target minimum*(1+buffer)."""
    check_interval: int = 600
    buffer_min_percent: float = 0.01
    buffer_percent: float = 0.20


@dataclass(frozen=True)
class PipelineConfig:
    """Staged solve executor (docs/pipeline.md): decouples device
    compute, host encode+CID, and network pin/commit so the chip never
    waits for the host+network tail of the previous bucket.

    Disabled by default — `enabled: false` IS the reference-equivalent
    synchronous path (one bucket at a time, commit before the next
    dispatch). The knobs only change the *schedule*, never the bytes:
    solution CIDs are identical pipeline-on vs pipeline-off
    (tests/test_pipeline.py pins this per runner family)."""
    enabled: bool = False
    # how many canonical_batch chunks may be dispatched to the device
    # ahead of the encode stage (generalizes the old one-deep overlap)
    depth: int = 2
    # host worker threads for encode+CID; 0 = encode inline on the tick
    # thread (still pipelined against the chip via async dispatch)
    encode_workers: int = 0
    # backpressure bound on tasks queued for the network stage
    # (pin + commit/reveal) before the driver drains them
    max_inflight_pins: int = 4

    def __post_init__(self):
        if self.depth < 1:
            raise ConfigError("pipeline.depth must be >= 1")
        if self.encode_workers < 0:
            raise ConfigError("pipeline.encode_workers must be >= 0")
        if self.max_inflight_pins < 1:
            raise ConfigError("pipeline.max_inflight_pins must be >= 1")


@dataclass(frozen=True)
class SchedConfig:
    """Profit-aware continuous batching (docs/scheduler.md): pack the
    pending solve queue across families, bucket shapes, and warm
    executables by predicted fee/chip-second from the learned cost
    model (node/costmodel.py, sqlite `cost_model` table).

    Disabled by default — `enabled: false` IS the FIFO arrival-order
    path the node always had. The packer only permutes whole buckets,
    never the entries inside one, so bytes and CIDs are identical under
    either policy (tests/test_sched.py pins it)."""
    enabled: bool = False
    # per-(model, bucket, layout) samples the cost model must accrue
    # before its prediction replaces the static estimate (the gate and
    # the packer both degrade to the exact pre-costsched behavior
    # until then)
    min_samples: int = 8
    # packing-score multiplier for buckets whose executable is already
    # compiled this life (warm-executable preference; 1.0 disables)
    warm_boost: float = 1.5

    def __post_init__(self):
        if self.min_samples < 1:
            raise ConfigError("sched.min_samples must be >= 1")
        if self.warm_boost < 1.0:
            raise ConfigError("sched.warm_boost must be >= 1.0 "
                              "(1.0 disables the warm preference)")


@dataclass(frozen=True)
class PrecisionConfig:
    """Per-template precision modes (docs/quantization.md): `default`
    applies to every enabled template, `templates` overrides per
    template name. A mode is a DETERMINISM CLASS — `bf16` is the zoo's
    byte-identical historic program; `int8`/`fp8` quantize checkpoint
    weights at load (f32 dequant scales as explicit params) and run
    mode-specific XLA programs with their own graphlint goldens, AOT
    cache keys, and cost-model rows. A fleet mines ONE mode per
    template, exactly like one mesh layout and one canonical batch —
    miners advertise the mode, and the CID contract is per-mode, never
    silently mixed."""
    default: str = "bf16"
    templates: dict = field(default_factory=dict)

    def __post_init__(self):
        from arbius_tpu.quant.modes import validate_mode

        try:
            validate_mode(self.default, where="precision.default")
        except ValueError as e:
            raise ConfigError(str(e)) from None
        if not isinstance(self.templates, dict):
            raise ConfigError(
                "precision.templates must be a {template: mode} object "
                '(e.g. {"anythingv3": "int8"})')
        for tmpl, mode in self.templates.items():
            try:
                validate_mode(mode,
                              where=f"precision.templates[{tmpl!r}]")
            except ValueError as e:
                raise ConfigError(str(e)) from None

    def mode_for(self, template: str) -> str:
        """The precision mode a template serves at."""
        return self.templates.get(template, self.default)


@dataclass(frozen=True)
class AotCacheConfig:
    """Fleet-wide AOT executable cache (docs/compile-cache.md): persist
    compiled bucket executables on disk, keyed by the graphlint
    canonical program fingerprint + environment signature, so a warm
    boot deserializes instead of re-compiling (the cold-boot compile
    storm `arbius_compile_seconds` meters). The directory may be SHARED
    by every fleet worker on a host — writes are atomic tmp+rename.

    Disabled by default — `enabled: false` IS the memory-only
    executable caching the node always had, bit-for-bit. Enabling only
    changes WHERE an executable comes from, never its program: a
    drifted program hashes to a different key and misses to a fresh
    compile (tests/test_aotcache.py pins CID byte-equality on vs off)."""
    enabled: bool = False
    # shared cache directory (created on first write)
    dir: str = "aot-cache"
    # LRU size budget in bytes; 0 = unbounded. Enforced after each
    # write (oldest-mtime entries evicted first; the just-written entry
    # is always retained, so the budget is a soft ceiling of one entry)
    max_bytes: int = 0

    def __post_init__(self):
        if self.enabled and not self.dir:
            raise ConfigError("aot_cache.dir must be a directory path "
                              "when aot_cache.enabled is true")
        if self.dir == ":memory:":
            raise ConfigError("aot_cache.dir must be a directory path — "
                              "the cache is shared across lives (and "
                              "fleet workers)")
        if self.max_bytes < 0:
            raise ConfigError("aot_cache.max_bytes must be >= 0 "
                              "(0 = unbounded)")


@dataclass(frozen=True)
class PerfscopeConfig:
    """Per-bucket XLA cost/memory attribution + drift detection
    (docs/perfscope.md): capture a PerfCard (flops, bytes accessed, HBM
    sizes, padding waste, wire bytes, compile amortization) for every
    bucket executable at the compile seam, persist cards to the sqlite
    `perf_cards` table, and publish
    `arbius_perf_drift_ratio{model,bucket,layout,mode}` = observed
    infer p50 ÷ the card's static roofline estimate.

    Disabled by default — `enabled: false` IS the pre-perfscope node
    bit-for-bit (no capture, no eager compile at the lookup). Enabling
    never changes a program or its bytes: CIDs are pinned identical on
    vs off (tests/test_perfscope.py)."""
    enabled: bool = False
    # roofline peaks the static estimate divides by — set them to the
    # deployed accelerator (defaults are a v4-ish order of magnitude;
    # on CPU the ratio is only meaningful relative to itself)
    peak_flops: float = 1e12
    peak_bytes_per_second: float = 8e11
    # drift band: a ratio outside [drift_min, drift_max] journals a
    # `perf_drift` event (on the crossing) and is what PERF601 audits
    # offline. drift_max 0 disables live banding — the gauge and cards
    # still publish.
    drift_min: float = 0.0
    drift_max: float = 0.0

    def __post_init__(self):
        if self.peak_flops < 0 or self.peak_bytes_per_second < 0:
            raise ConfigError("perfscope peaks must be >= 0 "
                              "(0 disables that roofline term)")
        if self.drift_min < 0:
            raise ConfigError("perfscope.drift_min must be >= 0")
        if self.drift_max > 0 and self.drift_max < self.drift_min:
            raise ConfigError("perfscope.drift_max must be >= drift_min "
                              "(or 0 to disable live banding)")


@dataclass(frozen=True)
class AlertsConfig:
    """Live alert engine (docs/healthwatch.md): a catalog of named
    alert rules — each an ok → pending → firing → resolved state
    machine with hysteresis — evaluated once per node tick over the
    obs registry, the queue, and the `slo`/`perfscope` config.
    Chain/virtual time only, so the transition history is
    deterministic for a given tick history.

    Disabled by default — `enabled: false` IS the pre-healthwatch node
    bit-for-bit (no evaluation, no gauges). Enabling never perturbs a
    solve: the engine is bookkeeping-only and CIDs are pinned
    identical on vs off (tests/test_healthwatch.py)."""
    enabled: bool = False
    # consecutive active evaluations before a sustained-signal rule
    # fires (the pending window); instantaneous rules use 1
    for_ticks: int = 3
    # quiet evaluations a resolved alert holds before returning to ok
    resolve_ticks: int = 1
    # chain seconds of due-job starvation before stuck_tick activates
    stuck_after_seconds: int = 600
    # evaluations the crash_recovered condition holds after an
    # unclean-boot detection
    crash_hold_ticks: int = 3
    # consecutive gate-reject ticks before unprofitable_streak fires
    unprofitable_streak: int = 8
    # pipeline stage stalls per tick before pipeline_stall activates —
    # bounded-queue backpressure stalls a producer a few times per
    # tick by DESIGN (docs/pipeline.md); the alert is for a storm
    stall_burst: int = 8
    # per-rule for_ticks overrides, e.g. {"rpc_degraded": 5}
    per_rule: dict = field(default_factory=dict)

    def __post_init__(self):
        from arbius_tpu.obs.healthwatch import RULE_NAMES

        for name, bound in (("for_ticks", self.for_ticks),
                            ("resolve_ticks", self.resolve_ticks),
                            ("stuck_after_seconds",
                             self.stuck_after_seconds),
                            ("crash_hold_ticks", self.crash_hold_ticks),
                            ("stall_burst", self.stall_burst),
                            ("unprofitable_streak",
                             self.unprofitable_streak)):
            if not isinstance(bound, int) or bound < 1:
                raise ConfigError(f"alerts.{name} must be an integer "
                                  ">= 1")
        if not isinstance(self.per_rule, dict):
            raise ConfigError(
                'alerts.per_rule must be a {rule: for_ticks} object '
                '(e.g. {"rpc_degraded": 5})')
        for rule, ticks in self.per_rule.items():
            if rule not in RULE_NAMES:
                raise ConfigError(
                    f"alerts.per_rule names unknown rule {rule!r} — "
                    f"the catalog is: {', '.join(RULE_NAMES)}")
            if not isinstance(ticks, int) or ticks < 1:
                raise ConfigError(f"alerts.per_rule[{rule!r}] must be "
                                  "an integer >= 1")


@dataclass(frozen=True)
class TextgenConfig:
    """Sequence-bucket policy for the textgen family
    (docs/text-serving.md): a task's prompt pads to the smallest
    `prompt_buckets` edge that fits it and its requested budget rounds
    up to the smallest `decode_buckets` edge — each (prompt, decode,
    sampler, batch) combination is ONE jitted XLA program, so these
    edges bound the compile count AND define the family's determinism
    classes. Like canonical_batch and the mesh layout, bucket edges are
    fleet-wide per model class: the prompt edge changes the positions
    tokens sit at and therefore the output bytes."""
    prompt_buckets: tuple = (32, 64)
    decode_buckets: tuple = (16, 32)
    # hydration-level cap on a task's requested token budget; must be
    # servable by some decode bucket or the task could never solve
    max_new_tokens: int = 32
    # the k of seeded top-k sampling — part of the compiled program,
    # fleet-wide like the bucket edges
    top_k: int = 8

    def __post_init__(self):
        for name, edges in (("prompt_buckets", self.prompt_buckets),
                            ("decode_buckets", self.decode_buckets)):
            if not isinstance(edges, (tuple, list)) or not edges:
                raise ConfigError(f"textgen.{name} must be a non-empty "
                                  "ascending list of positive integers")
            prev = 0
            for e in edges:
                if not isinstance(e, int) or isinstance(e, bool) \
                        or e <= prev:
                    raise ConfigError(
                        f"textgen.{name} must be a non-empty ascending "
                        "list of positive integers")
                prev = e
        if self.prompt_buckets[0] < 3:
            raise ConfigError("textgen.prompt_buckets edges must be >= 3 "
                              "(bos + at least one byte + eos)")
        if not isinstance(self.max_new_tokens, int) \
                or isinstance(self.max_new_tokens, bool) \
                or self.max_new_tokens < 1:
            raise ConfigError("textgen.max_new_tokens must be an integer "
                              ">= 1")
        if self.max_new_tokens > max(self.decode_buckets):
            raise ConfigError("textgen.max_new_tokens must not exceed the "
                              "largest decode bucket edge — a budget no "
                              "bucket can serve would be unmineable")
        if not isinstance(self.top_k, int) or isinstance(self.top_k, bool) \
                or self.top_k < 1:
            raise ConfigError("textgen.top_k must be an integer >= 1")


@dataclass(frozen=True)
class SLOConfig:
    """First-class service-level objectives over the fleet's chain-time
    latency corpus (docs/fleetscope.md): each threshold declares an
    objective on a fixed-bucket percentile the SLO layer estimates
    (`obs.registry.estimate_percentile`); `null` declares none. The
    report always carries the percentiles — thresholds only decide
    whether a soak/scrape FAILS on them (`simsoak --flood` exits 1 on
    breach, SLO101)."""
    # chain-seconds from the coordinator's deal to the first worker
    # acquire, p95
    queue_wait_p95: float | None = None
    # chain-seconds from the task's entry into the fleet to its
    # accepted solution, p99. Anchor detail (docs/fleetscope.md): the
    # live histogram anchors on the coordinator's deal (the lease
    # row's intake time — coordinator poll lag is excluded); the
    # byte-deterministic flood report anchors on the exact on-chain
    # submission blocktime. On a healthy coordinator the two agree to
    # within one poll interval.
    time_to_commit_p99: float | None = None
    # chain-seconds an expired lease lingered past its heartbeat before
    # being stolen/reclaimed, p99
    steal_lag_p99: float | None = None
    # ceiling on chip-idle wall seconds / total solve-path wall seconds
    # (bench/live scrapes only — wall time never enters deterministic
    # flood reports)
    chip_idle_fraction: float | None = None

    def __post_init__(self):
        for name in ("queue_wait_p95", "time_to_commit_p99",
                     "steal_lag_p99"):
            v = getattr(self, name)
            if v is not None and v < 0:
                raise ConfigError(f"slo.{name} must be >= 0 seconds "
                                  "(or null for no objective)")
        f = self.chip_idle_fraction
        if f is not None and not 0.0 <= f <= 1.0:
            raise ConfigError("slo.chip_idle_fraction must be within "
                              "[0, 1] (or null for no objective)")


@dataclass(frozen=True)
class FleetConfig:
    """Multi-process fleet mining (docs/fleet.md): a coordinator owns
    the chain event stream and leases tasks across N worker processes
    through a shared sqlite lease table (WAL + busy_timeout); workers
    are full MinerNodes in worker mode (external task feed, lease
    heartbeat in the tick, cross-process commit dedupe).

    Disabled by default — `enabled: false` IS the single-node path.
    A fleet of one worker produces byte-identical CIDs to a bare
    MinerNode on the same event stream (tests/test_sim.py pins it)."""
    enabled: bool = False
    # worker processes the coordinator leases tasks across
    workers: int = 2
    # chain-time seconds a lease stays exclusive without a heartbeat;
    # a dead worker's tasks are stealable after this
    lease_ttl: int = 60
    # "per-worker": each worker signs with its own wallet (its own
    # validator stake). "shared": one wallet, tx signing serialized
    # through the lease db's wallet guard (nonce-safe, one validator)
    wallet_mode: str = "per-worker"
    # shared lease database path (every fleet process opens this file)
    lease_db: str = "fleet-leases.sqlite"
    # leases a worker may pull per tick, and the task/solve backlog
    # bound above which it stops pulling (the CONC302 story at fleet
    # scale: worker memory stays bounded, the lease table is the
    # durable overflow buffer)
    max_leases: int = 4
    backlog: int = 8
    # lease (re)deliveries before a task is marked failed fleet-wide
    # (a poison task must not ping-pong between workers forever)
    max_attempts: int = 4
    # sqlite busy_timeout for lease-db handles (milliseconds)
    busy_timeout_ms: int = 5000
    # fleetscope sidecar directory (docs/fleetscope.md): every fleet
    # member persists registry snapshots + journal segments to its own
    # `<member>.obs.sqlite` under this path, and the coordinator's
    # federated GET /metrics merges them. Empty = fleetscope sidecars
    # off (per-process obs only).
    sidecar_dir: str = ""
    # ticks between sidecar flushes (1 = every tick)
    sidecar_flush_every: int = 8

    def __post_init__(self):
        if self.sidecar_dir == ":memory:":
            raise ConfigError("fleet.sidecar_dir must be a directory "
                              "path — sidecars are merged across "
                              "processes (empty string disables)")
        if self.sidecar_flush_every < 1:
            raise ConfigError("fleet.sidecar_flush_every must be >= 1")
        if self.workers < 1:
            raise ConfigError("fleet.workers must be >= 1")
        if self.lease_ttl < 1:
            raise ConfigError("fleet.lease_ttl must be >= 1 second")
        if self.wallet_mode not in ("per-worker", "shared"):
            raise ConfigError(f"unknown fleet.wallet_mode "
                              f"{self.wallet_mode!r} (per-worker|shared)")
        if not self.lease_db or self.lease_db == ":memory:":
            raise ConfigError("fleet.lease_db must be a file path — the "
                              "lease table is shared across processes")
        if self.max_leases < 1:
            raise ConfigError("fleet.max_leases must be >= 1")
        if self.backlog < self.max_leases:
            raise ConfigError("fleet.backlog must be >= fleet.max_leases "
                              "(a pull may never overshoot the bound)")
        if self.max_attempts < 1:
            raise ConfigError("fleet.max_attempts must be >= 1")
        if self.busy_timeout_ms < 0:
            raise ConfigError("fleet.busy_timeout_ms must be >= 0")


@dataclass(frozen=True)
class IpfsConfig:
    """Pinning strategy selection (reference `types.ts:3-54` ipfs section):
    local = the node's own ContentStore + gateway (needs store_dir);
    http_daemon = kubo /api/v0/add; pinata = Pinata's pinning API."""
    strategy: str = "local"
    daemon_url: str = ""
    pinata_jwt: str = ""
    # per-pinner HTTP timeout in seconds — reaches every remote pin
    # request (build_pinner threads it through); 60 matches the old
    # hard-coded constant
    timeout: float = 60.0

    def __post_init__(self):
        if self.strategy not in ("local", "http_daemon", "pinata"):
            raise ConfigError(f"unknown ipfs strategy {self.strategy!r}")
        if self.timeout <= 0:
            raise ConfigError("ipfs.timeout must be positive seconds")
        if self.strategy == "http_daemon" and not self.daemon_url:
            raise ConfigError("ipfs strategy http_daemon needs daemon_url")
        if self.strategy == "pinata" and not self.pinata_jwt:
            raise ConfigError("ipfs strategy pinata needs pinata_jwt")


@dataclass(frozen=True)
class MiningConfig:
    db_path: str = ":memory:"
    # sqlite busy_timeout for the node db (milliseconds): ControlRPC
    # request threads and the tick thread contend on one file
    db_busy_timeout_ms: int = 5000
    log_path: str | None = None
    evilmode: bool = False        # fault injection: commit wrong CIDs
    models: tuple[ModelConfig, ...] = ()
    automine: AutomineConfig = AutomineConfig()
    stake: StakeConfig = StakeConfig()
    claim_delay_buffer: int = 120  # claim at solution+minClaimTime+this
    vote_finish_delay_buffer: int = 120  # finish at contest+votePeriod+this
    # profitability gate: skip tasks whose fee < estimated_solve_seconds *
    # this rate (wad/second). 0 disables (reference behavior: fee filters
    # only, no cost model)
    min_fee_per_second: int = 0
    assumed_solve_seconds: float = 10.0  # cost estimate before any samples
    poll_interval_ms: int = 100    # main-loop cadence (index.ts:1082-1096)
    # dp batch per solve dispatch; MUST be fleet-wide per model class
    # (batch size is part of the XLA program = the determinism class)
    canonical_batch: int = 1
    # device-mesh layout for the solve path (docs/multichip.md), e.g.
    # {"dp": 4, "tp": 2} or {"dp": 2, "sp": 2, "tp": 2}; null/absent =
    # the exact single-device path. dp shards the bucket batch
    # (bit-identical to mesh-off — test-pinned); tp/sp layouts are each
    # their OWN determinism class, pinned per (family, layout) by the
    # graphlint goldens, so a fleet mines one layout per model — the
    # same fleet-wide rule as canonical_batch. Axis names/values are
    # validated here; the device-count fit is checked at boot where jax
    # is up (parallel/meshsolve.boot_mesh).
    mesh: dict | None = None
    profile_dir: str | None = None   # jax.profiler trace output dir
    profile_every: int = 0           # trace every Nth solve dispatch
    # obs subsystem (docs/observability.md): span tracing + event journal.
    # obs_enabled=False stops span/journal recording (counters and the
    # /metrics registry stay live — the JSON metrics view depends on them);
    # obs_journal_capacity bounds the flight-recorder ring buffer.
    obs_enabled: bool = True
    obs_journal_capacity: int = 4096
    # bound on expretry's base**attempt backoff curve (seconds); None
    # preserves the reference's uncapped curve (utils.ts:21-39)
    retry_max_delay: float | None = 30.0
    compile_cache_dir: str | None = ".jax_cache"  # persistent XLA cache
    store_dir: str | None = None     # content store root (None: don't pin)
    rpc_port: int | None = None      # control RPC + explorer + /ipfs gateway
    ipfs: IpfsConfig = IpfsConfig()  # pinning strategy
    # staged solve executor (docs/pipeline.md); default OFF = the
    # synchronous reference-equivalent path behind a single switch
    pipeline: PipelineConfig = PipelineConfig()
    # profit-aware continuous batching (docs/scheduler.md); default OFF
    # = FIFO arrival-order bucket packing, static-cost gate only
    sched: SchedConfig = SchedConfig()
    # multi-process fleet mining (docs/fleet.md); default OFF = this
    # process is a bare single-node miner
    fleet: FleetConfig = FleetConfig()
    # service-level objectives over the chain-time latency corpus
    # (docs/fleetscope.md); all-null = report percentiles, fail nothing
    slo: SLOConfig = SLOConfig()
    # fleet-wide AOT executable cache (docs/compile-cache.md); default
    # OFF = memory-only bucket caching, compile on every boot
    aot_cache: AotCacheConfig = AotCacheConfig()
    # per-template precision modes (docs/quantization.md); the default
    # "bf16" everywhere IS the pre-quant node byte-for-byte — int8/fp8
    # are opt-in per-template determinism classes
    precision: PrecisionConfig = PrecisionConfig()
    # per-bucket cost/memory attribution + drift detection
    # (docs/perfscope.md); default OFF = no capture, the pre-perfscope
    # compile seam bit-for-bit
    perfscope: PerfscopeConfig = PerfscopeConfig()
    # live alert engine (docs/healthwatch.md); default OFF = no
    # evaluation, no alert gauges — the pre-healthwatch node
    alerts: AlertsConfig = AlertsConfig()
    # sequence-bucket policy for the textgen family
    # (docs/text-serving.md); fleet-wide determinism-class config like
    # canonical_batch — inert unless a textgen-template model is enabled
    textgen: TextgenConfig = TextgenConfig()
    # delegated-validator seam (blockchain.ts:44-67 keeps the same seam,
    # disabled): stake reads and deposits target this address instead of
    # the node's wallet — validatorDeposit(validator, amount) is already
    # anyone-may-top-up on-chain (EngineV1.sol:581-604). CAVEAT (boot
    # warns): submitSolution is still gated on msg.sender's OWN stake
    # (EngineV1.sol:398-404), so the signing wallet must also be staked
    # to mine; full delegated SOLVING needs the reference's never-shipped
    # solver contract. This field redirects stake management only,
    # exactly as the commented reference code does.
    delegated_validator: str | None = None

    def __post_init__(self):
        import re as _re

        if self.mesh is not None:
            from arbius_tpu.parallel.mesh import validate_axes

            if not isinstance(self.mesh, dict) or not self.mesh:
                raise ConfigError(
                    "mesh must be a non-empty {axis: size} object "
                    '(e.g. {"dp": 4, "tp": 2}) or null')
            try:
                validate_axes(dict(self.mesh), None, where="mesh config")
            except ValueError as e:
                raise ConfigError(str(e)) from None
        if self.delegated_validator is not None and not _re.fullmatch(
                r"0x[0-9a-fA-F]{40}", self.delegated_validator):
            raise ConfigError(
                f"delegated_validator {self.delegated_validator!r} is not "
                "a 0x address")
        if self.obs_journal_capacity < 1:
            raise ConfigError("obs_journal_capacity must be >= 1")
        if self.db_busy_timeout_ms < 0:
            raise ConfigError("db_busy_timeout_ms must be >= 0")
        if self.retry_max_delay is not None and self.retry_max_delay <= 0:
            raise ConfigError("retry_max_delay must be positive (or null "
                              "for the uncapped reference curve)")


@dataclass(frozen=True)
class DeploymentConfig:
    """Tier-1 deployment constants (the reference's `src/config.json:1-24`):
    where the chain lives and which contracts to talk to. Operator config
    (MiningConfig) says how to mine; this says where."""
    rpc_url: str
    engine_address: str
    token_address: str
    chain_id: int
    start_block: int = 0          # poll_events starts here
    governor_address: str = ""    # optional: governance verbs' target


def load_deployment(raw: str | dict) -> DeploymentConfig:
    obj = json.loads(raw) if isinstance(raw, str) else dict(raw)
    known = set(DeploymentConfig.__dataclass_fields__)
    unknown = set(obj) - known
    if unknown:
        raise ConfigError(f"unknown deployment keys: {sorted(unknown)}")
    missing = {"rpc_url", "engine_address", "token_address",
               "chain_id"} - set(obj)
    if missing:
        raise ConfigError(f"deployment config missing: {sorted(missing)}")
    return DeploymentConfig(**obj)


_KNOWN = {f for f in MiningConfig.__dataclass_fields__}


def load_config(raw: str | dict) -> MiningConfig:
    obj = json.loads(raw) if isinstance(raw, str) else dict(raw)
    unknown = set(obj) - _KNOWN
    if unknown:
        raise ConfigError(f"unknown config keys: {sorted(unknown)}")
    def build(cls, kwargs, where):
        try:
            return cls(**kwargs)
        except TypeError as e:
            raise ConfigError(f"{where}: {e}") from None

    models = []
    for m in obj.pop("models", []):
        m = dict(m)
        if "id" not in m or "template" not in m:
            raise ConfigError("model entry needs id and template")
        owners = tuple(a.lower() for a in m.pop("allowed_owners", []))
        models.append(build(ModelConfig,
                            dict(allowed_owners=owners, **m), "models"))
    automine = build(AutomineConfig, obj.pop("automine", {}), "automine")
    stake = build(StakeConfig, obj.pop("stake", {}), "stake")
    ipfs = build(IpfsConfig, obj.pop("ipfs", {}), "ipfs")
    pipeline = build(PipelineConfig, obj.pop("pipeline", {}), "pipeline")
    sched = build(SchedConfig, obj.pop("sched", {}), "sched")
    fleet = build(FleetConfig, obj.pop("fleet", {}), "fleet")
    slo = build(SLOConfig, obj.pop("slo", {}), "slo")
    aot_cache = build(AotCacheConfig, obj.pop("aot_cache", {}),
                      "aot_cache")
    precision = build(PrecisionConfig, obj.pop("precision", {}),
                      "precision")
    perfscope = build(PerfscopeConfig, obj.pop("perfscope", {}),
                      "perfscope")
    alerts = build(AlertsConfig, obj.pop("alerts", {}), "alerts")
    tg_raw = dict(obj.pop("textgen", {}))
    for k in ("prompt_buckets", "decode_buckets"):
        if isinstance(tg_raw.get(k), list):
            tg_raw[k] = tuple(tg_raw[k])
    textgen = build(TextgenConfig, tg_raw, "textgen")
    return build(MiningConfig,
                 dict(models=tuple(models), automine=automine, stake=stake,
                      ipfs=ipfs, pipeline=pipeline, sched=sched,
                      fleet=fleet, slo=slo, aot_cache=aot_cache,
                      precision=precision, perfscope=perfscope,
                      alerts=alerts, textgen=textgen, **obj),
                 "config")
