"""Deterministic PNG encoder.

The reference gets its output PNG bytes from inside the cog container
(`miner/src/index.ts:867-872` base64-decodes whatever the container wrote),
so the container's libpng version silently defines the determinism class.
Here the encoder IS part of the framework: RGB8, one IDAT, a fixed
per-row filter (Paeth, filter type 4 — good on natural images and fully
deterministic), and the spec-pinned DEFLATE from `deflate.py`. Every miner
running this code produces the same bytes, hence the same solution CID.

CRC32 and Adler32 are fully specified checksums (not compression), so the
stdlib implementations are safe to use.
"""
from __future__ import annotations

import struct
import zlib

import numpy as np

from arbius_tpu.codecs.deflate import compress, zlib_wrap

_SIG = b"\x89PNG\r\n\x1a\n"


def _chunk(tag: bytes, payload: bytes) -> bytes:
    return (struct.pack(">I", len(payload)) + tag + payload
            + struct.pack(">I", zlib.crc32(tag + payload)))


def _paeth_filter_rows(img: np.ndarray) -> bytes:
    """Filter type 4 (Paeth) applied to every row; returns the raw stream."""
    h, w, c = img.shape
    x = img.astype(np.int32)
    left = np.zeros_like(x)
    left[:, 1:] = x[:, :-1]
    up = np.zeros_like(x)
    up[1:] = x[:-1]
    upleft = np.zeros_like(x)
    upleft[1:, 1:] = x[:-1, :-1]
    p = left + up - upleft
    pa, pb, pc = np.abs(p - left), np.abs(p - up), np.abs(p - upleft)
    pred = np.where((pa <= pb) & (pa <= pc), left,
                    np.where(pb <= pc, up, upleft))
    filtered = ((x - pred) & 0xFF).astype(np.uint8)
    rows = np.concatenate(
        [np.full((h, 1), 4, np.uint8), filtered.reshape(h, w * c)], axis=1)
    return rows.tobytes()


def encode_png(image: np.ndarray) -> bytes:
    """uint8 [H, W, 3] RGB -> PNG bytes, deterministically."""
    if image.dtype != np.uint8 or image.ndim != 3 or image.shape[2] != 3:
        raise ValueError(f"expected uint8 [H,W,3] RGB, got "
                         f"{image.dtype} {image.shape}")
    h, w, _ = image.shape
    ihdr = struct.pack(">IIBBBBB", w, h, 8, 2, 0, 0, 0)  # 8-bit, color type 2
    raw = _paeth_filter_rows(np.ascontiguousarray(image))
    idat = zlib_wrap(compress(raw), raw)
    return (_SIG + _chunk(b"IHDR", ihdr) + _chunk(b"IDAT", idat)
            + _chunk(b"IEND", b""))
