"""MJPEG-MP4 demuxer — the input side of the video-matting path.

RVM's template input is a video *file* (`templates/robust_video_matting
.json`, type file); the node must turn those bytes into frames before
inference. This parses the ISO BMFF structure (stsz/stco sample tables)
and decodes the JPEG samples via PIL — handles the framework's own muxer
profile (codecs/mp4.py) and any MJPEG-in-MP4 file.

Note on determinism: input decoding sits UPSTREAM of inference, so the
decoder build is part of the solve's determinism class exactly like the
model weights are — the environment pins PIL. Output encoding (the bytes
that get CID'd) never goes through a third-party codec.
"""
from __future__ import annotations

import io
import struct

import numpy as np


def _boxes(data: bytes, start: int, end: int):
    off = start
    while off + 8 <= end:
        size = struct.unpack(">I", data[off:off + 4])[0]
        tag = data[off + 4:off + 8]
        if size == 1:  # 64-bit largesize
            size = struct.unpack(">Q", data[off + 8:off + 16])[0]
            yield tag, off + 16, off + size
        else:
            if size == 0:
                size = end - off
            yield tag, off + 8, off + size
        off += size


def _find(data: bytes, path: list[bytes], start=0, end=None):
    if end is None:
        end = len(data)
    if not path:
        return start, end
    for tag, s, e in _boxes(data, start, end):
        if tag == path[0]:
            return _find(data, path[1:], s, e)
    raise ValueError(f"box {path[0]!r} not found")


def _video_stbl(data: bytes):
    """(start, end) of the first VIDEO trak's stbl — external muxers
    often put an audio trak first, so trak selection must check the
    hdlr handler_type, not take the first trak."""
    moov = _find(data, [b"moov"])
    last_err = None
    for tag, s, e in _boxes(data, *moov):
        if tag != b"trak":
            continue
        try:
            mdia = _find(data, [b"mdia"], s, e)
            hs, _ = _find(data, [b"hdlr"], *mdia)
            if data[hs + 8:hs + 12] != b"vide":
                continue
            return _find(data, [b"minf", b"stbl"], *mdia)
        except ValueError as exc:
            last_err = exc
    raise ValueError(f"no video trak found ({last_err})")


def demux_samples(data: bytes) -> list[bytes]:
    """Walk the full sample tables (stsz/stco/co64/stsc incl. run
    expansion) of the first video track → per-sample bytes. Shared by the
    MJPEG and H.264 demux paths — an external muxer may pack many samples
    per chunk, which a naive zip(stco, stsz) silently truncates."""
    stbl = _video_stbl(data)
    sizes = chunk_offsets = stsc = None
    for tag, s, e in _boxes(data, *stbl):
        if tag == b"stsz":
            sample_size, count = struct.unpack(">II", data[s + 4:s + 12])
            if sample_size:
                sizes = [sample_size] * count
            else:
                sizes = list(struct.unpack(f">{count}I",
                                           data[s + 12:s + 12 + 4 * count]))
        elif tag == b"stco":
            count = struct.unpack(">I", data[s + 4:s + 8])[0]
            chunk_offsets = list(struct.unpack(
                f">{count}I", data[s + 8:s + 8 + 4 * count]))
        elif tag == b"co64":
            count = struct.unpack(">I", data[s + 4:s + 8])[0]
            chunk_offsets = list(struct.unpack(
                f">{count}Q", data[s + 8:s + 8 + 8 * count]))
        elif tag == b"stsc":
            count = struct.unpack(">I", data[s + 4:s + 8])[0]
            stsc = [struct.unpack(">III", data[s + 8 + 12 * i:
                                               s + 20 + 12 * i])
                    for i in range(count)]  # (first_chunk, per_chunk, desc)
    if sizes is None or chunk_offsets is None:
        raise ValueError("no sample tables (stsz/stco) found")

    # expand stsc runs into samples-per-chunk, then walk chunks laying
    # samples contiguously from each chunk offset
    n_chunks = len(chunk_offsets)
    per_chunk = [1] * n_chunks
    if stsc:
        for i, (first, count, _) in enumerate(stsc):
            last = stsc[i + 1][0] - 1 if i + 1 < len(stsc) else n_chunks
            for c in range(first - 1, last):
                per_chunk[c] = count
    offsets = []
    si = 0
    for ci, base in enumerate(chunk_offsets):
        off = base
        for _ in range(per_chunk[ci]):
            if si >= len(sizes):
                break
            offsets.append(off)
            off += sizes[si]
            si += 1
    if si != len(sizes):
        raise ValueError(
            f"sample tables inconsistent: stsc/stco cover {si} samples, "
            f"stsz declares {len(sizes)}")
    return [data[off:off + sz] for off, sz in zip(offsets, sizes)]


def demux_mjpeg_mp4(data: bytes) -> list[bytes]:
    """Extract per-sample JPEG bytes from an MJPEG MP4."""
    samples = demux_samples(data)
    for i, blob in enumerate(samples):
        if blob[:2] != b"\xff\xd8":
            raise ValueError(f"sample {i} is not a JPEG (MJPEG only)")
    return samples


def decode_mjpeg_mp4(data: bytes) -> np.ndarray:
    """MJPEG MP4 bytes → uint8 [T, H, W, 3] RGB frames."""
    from PIL import Image

    frames = [np.asarray(Image.open(io.BytesIO(s)).convert("RGB"))
              for s in demux_mjpeg_mp4(data)]
    if not frames:
        raise ValueError("no frames")
    return np.stack(frames)


def decode_video_mp4(data: bytes) -> np.ndarray:
    """MP4 bytes → uint8 [T, H, W, 3] RGB, dispatching on the sample
    entry: `avc1` (the framework's H.264 I_PCM class, codecs/h264.py)
    or MJPEG. The input side of the video-matting path."""
    try:
        stsd_s, stsd_e = _find(data, [b"stsd"], *_video_stbl(data))
    except ValueError:
        raise ValueError("not an ISO BMFF video file (no video stsd)")
    entry_tags = [tag for tag, _, _ in _boxes(data, stsd_s + 8, stsd_e)]
    if b"avc1" in entry_tags:
        from arbius_tpu.codecs.h264_decode import (
            decode_h264_mp4_yuv,
            yuv420_to_rgb,
        )

        frames = [yuv420_to_rgb(y, cb, cr)
                  for y, cb, cr in decode_h264_mp4_yuv(data)]
        if not frames:
            raise ValueError("no frames")
        return np.stack(frames)
    return decode_mjpeg_mp4(data)
