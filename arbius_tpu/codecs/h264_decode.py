"""H.264 I_PCM decoder — the round-trip oracle for codecs/h264.py.

A from-scratch parser for the exact stream class the encoder emits
(all-IDR, single slice, I_PCM macroblocks, CAVLC mode, 4:2:0): it walks
the avc1 MP4 sample tables, strips emulation prevention, parses SPS/PPS/
slice headers field-by-field (validating the pinned profile), and
reassembles the raw PCM planes. Because I_PCM is lossless, the decode
must recover the encoder's YCbCr samples BIT-EXACTLY — asserted by
tests/test_h264.py. The environment ships no third-party H.264 decoder,
so this is both the test oracle and the input-side capability for
H.264-class video files (the MJPEG analogue is mp4_demux.py).
"""
from __future__ import annotations

import re
import struct

import numpy as np

from arbius_tpu.codecs.mp4_demux import _boxes, _find

_UNESCAPE = re.compile(rb"\x00\x00\x03(?=[\x00-\x03])")


def unescape_rbsp(ebsp: bytes) -> bytes:
    return _UNESCAPE.sub(b"\x00\x00", ebsp)


class BitReader:
    def __init__(self, data: bytes):
        self._d = data
        self._pos = 0  # bit position

    def u(self, bits: int) -> int:
        v = 0
        for _ in range(bits):
            byte = self._d[self._pos >> 3]
            v = (v << 1) | ((byte >> (7 - (self._pos & 7))) & 1)
            self._pos += 1
        return v

    def ue(self) -> int:
        zeros = 0
        while self.u(1) == 0:
            zeros += 1
            if zeros > 32:
                raise ValueError("malformed exp-golomb code")
        return (1 << zeros) - 1 + (self.u(zeros) if zeros else 0)

    def se(self) -> int:
        code = self.ue()
        return (code + 1) // 2 if code % 2 else -(code // 2)

    def align(self) -> None:
        self._pos = (self._pos + 7) & ~7

    def raw(self, n: int) -> bytes:
        assert self._pos % 8 == 0
        start = self._pos >> 3
        self._pos += 8 * n
        return self._d[start:start + n]


def parse_sps(rbsp: bytes) -> dict:
    r = BitReader(rbsp)
    profile = r.u(8)
    r.u(8)  # constraint flags + reserved
    level = r.u(8)
    r.ue()  # sps id
    if profile in (100, 110, 122, 244, 44, 83, 86, 118, 128):
        raise ValueError("high-profile SPS not supported by this decoder")
    log2_max_frame_num = r.ue() + 4
    poc_type = r.ue()
    log2_max_poc_lsb = 0
    if poc_type == 0:
        log2_max_poc_lsb = r.ue() + 4
    elif poc_type == 1:
        raise ValueError("poc_type 1 not supported")
    r.ue()   # max_num_ref_frames
    r.u(1)   # gaps_in_frame_num_value_allowed_flag
    mbs_w = r.ue() + 1
    mbs_h = r.ue() + 1
    frame_mbs_only = r.u(1)
    if not frame_mbs_only:
        raise ValueError("interlaced streams not supported")
    r.u(1)   # direct_8x8_inference_flag
    crop = [0, 0, 0, 0]
    if r.u(1):
        crop = [r.ue(), r.ue(), r.ue(), r.ue()]  # l, r, t, b (chroma units)
    return {"profile": profile, "level": level,
            "log2_max_frame_num": log2_max_frame_num,
            "poc_type": poc_type, "log2_max_poc_lsb": log2_max_poc_lsb,
            "mbs_w": mbs_w, "mbs_h": mbs_h,
            "width": mbs_w * 16 - 2 * (crop[0] + crop[1]),
            "height": mbs_h * 16 - 2 * (crop[2] + crop[3])}


def parse_pps(rbsp: bytes) -> dict:
    r = BitReader(rbsp)
    r.ue()  # pps id
    r.ue()  # sps id
    cavlc = r.u(1) == 0
    if not cavlc:
        raise ValueError("CABAC streams not supported")
    r.u(1)
    if r.ue() != 0:
        raise ValueError("slice groups not supported")
    r.ue(); r.ue(); r.u(1); r.u(2)
    pic_init_qp = 26 + r.se()
    r.se(); r.se()
    deblock_control = r.u(1)
    return {"pic_init_qp": pic_init_qp, "deblock_control": deblock_control}


def decode_idr_ipcm(rbsp: bytes, sps: dict, pps: dict
                    ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """One IDR slice of I_PCM macroblocks → (Y, Cb, Cr) uint8 planes
    (uncropped)."""
    r = BitReader(rbsp)
    if r.ue() != 0:
        raise ValueError("multi-slice pictures not supported")
    slice_type = r.ue()
    if slice_type % 5 != 2:
        raise ValueError(f"not an I slice (slice_type {slice_type})")
    r.ue()                          # pps id
    r.u(sps["log2_max_frame_num"])  # frame_num
    r.ue()                          # idr_pic_id
    if sps.get("poc_type", 2) == 0:
        # poc_type-0 streams carry pic_order_cnt_lsb in EVERY slice
        # header (7.3.3) — skipping it misaligns the macroblock parse
        r.u(sps["log2_max_poc_lsb"])
    r.u(1); r.u(1)                  # dec_ref_pic_marking (IDR)
    r.se()                          # slice_qp_delta
    if pps["deblock_control"]:
        # alpha/beta offsets are present whenever idc != 1 (7.3.3) —
        # including idc == 0 (deblocking on; harmless for I_PCM samples,
        # which the filter bypasses)
        if r.ue() != 1:             # disable_deblocking_filter_idc
            r.se(); r.se()
    mbs_w, mbs_h = sps["mbs_w"], sps["mbs_h"]
    y = np.empty((mbs_h * 16, mbs_w * 16), np.uint8)
    cb = np.empty((mbs_h * 8, mbs_w * 8), np.uint8)
    cr = np.empty((mbs_h * 8, mbs_w * 8), np.uint8)
    for my in range(mbs_h):
        for mx in range(mbs_w):
            mb_type = r.ue()
            if mb_type != 25:
                raise ValueError(f"non-I_PCM mb_type {mb_type} "
                                 "not supported by this decoder")
            r.align()
            y[my * 16:(my + 1) * 16, mx * 16:(mx + 1) * 16] = \
                np.frombuffer(r.raw(256), np.uint8).reshape(16, 16)
            cb[my * 8:(my + 1) * 8, mx * 8:(mx + 1) * 8] = \
                np.frombuffer(r.raw(64), np.uint8).reshape(8, 8)
            cr[my * 8:(my + 1) * 8, mx * 8:(mx + 1) * 8] = \
                np.frombuffer(r.raw(64), np.uint8).reshape(8, 8)
    return y, cb, cr


def _avc_config(data: bytes) -> tuple[dict, dict]:
    """Parse avcC out of the avc1 sample entry → (sps, pps) dicts."""
    from arbius_tpu.codecs.mp4_demux import _video_stbl

    s, e = _find(data, [b"stsd"], *_video_stbl(data))
    payload = data[s:e]
    # stsd: version/flags + entry_count, then the avc1 entry
    entry_start = s + 8
    for tag, bs, be in _boxes(data, entry_start, e):
        if tag == b"avc1":
            # 78 bytes of VisualSampleEntry fields before child boxes
            for ctag, cs, ce in _boxes(data, bs + 78, be):
                if ctag == b"avcC":
                    cfg = data[cs:ce]
                    n_sps = cfg[5] & 0x1F
                    off = 6
                    sps_rbsp = None
                    for _ in range(n_sps):
                        ln = struct.unpack(">H", cfg[off:off + 2])[0]
                        sps_rbsp = unescape_rbsp(cfg[off + 3:off + 2 + ln])
                        off += 2 + ln
                    n_pps = cfg[off]
                    off += 1
                    pps_rbsp = None
                    for _ in range(n_pps):
                        ln = struct.unpack(">H", cfg[off:off + 2])[0]
                        pps_rbsp = unescape_rbsp(cfg[off + 3:off + 2 + ln])
                        off += 2 + ln
                    return parse_sps(sps_rbsp), parse_pps(pps_rbsp)
    raise ValueError("no avc1/avcC sample entry found")


def _samples(data: bytes) -> list[bytes]:
    # the full stsz/stco/co64/stsc walker (run expansion included) —
    # external muxers pack many samples per chunk, which a naive
    # zip(stco, stsz) silently truncates
    from arbius_tpu.codecs.mp4_demux import demux_samples

    return demux_samples(data)


def decode_h264_mp4_yuv(data: bytes
                        ) -> list[tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """avc1 MP4 → per-frame (Y, Cb, Cr) uint8 planes, cropped to the
    SPS-declared geometry.

    Supported input is the repo's own artifact class ONLY: all-IDR
    I_PCM streams (every frame a type-5 IDR slice, as codecs/h264.py
    emits). Inter-predicted input (VCL NAL types 1-4: non-IDR /
    partitioned slices, what a general encoder produces) is REJECTED
    rather than skipped — silently dropping those frames used to matte
    a truncated clip from an external avc1 file, which looks like a
    model bug instead of an input-format error."""
    sps, pps = _avc_config(data)
    out = []
    for sample in _samples(data):
        off = 0
        while off + 4 <= len(sample):
            ln = struct.unpack(">I", sample[off:off + 4])[0]
            nal = sample[off + 4:off + 4 + ln]
            off += 4 + ln
            nal_type = nal[0] & 0x1F
            if nal_type == 5:
                y, cb, cr = decode_idr_ipcm(unescape_rbsp(nal[1:]), sps, pps)
                h, wd = sps["height"], sps["width"]
                out.append((y[:h, :wd], cb[:h // 2, :wd // 2],
                            cr[:h // 2, :wd // 2]))
            elif nal_type in (1, 2, 3, 4):
                raise ValueError(
                    f"inter-predicted H.264 input (VCL NAL type {nal_type}"
                    f" at frame {len(out)}): only all-IDR I_PCM avc1 "
                    "streams are supported — re-encode the clip intra-only "
                    "(e.g. the codecs/h264.py encoder) before submitting")
    return out


def yuv420_to_rgb(y: np.ndarray, cb: np.ndarray, cr: np.ndarray
                  ) -> np.ndarray:
    """Inverse of h264.rgb_to_yuv420's color transform (pinned integer
    BT.601 limited-range), chroma upsampled by sample replication."""
    yf = (y.astype(np.int32) - 16) * 298
    cbu = np.repeat(np.repeat(cb.astype(np.int32) - 128, 2, 0), 2, 1)
    cru = np.repeat(np.repeat(cr.astype(np.int32) - 128, 2, 0), 2, 1)
    r = (yf + 409 * cru + 128) >> 8
    g = (yf - 100 * cbu - 208 * cru + 128) >> 8
    b = (yf + 516 * cbu + 128) >> 8
    return np.clip(np.stack([r, g, b], axis=-1), 0, 255).astype(np.uint8)
