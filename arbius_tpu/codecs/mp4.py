"""Deterministic MP4 (ISO BMFF) muxer — Motion-JPEG video track.

Video templates output `out-1.mp4` (`templates/zeroscopev2xl.json`,
`damo.json`, `robust_video_matting.json`); the reference takes whatever mp4
its cog container produced, so ffmpeg's encoder build defines its bytes.
Here the mp4 IS the framework's artifact, so every field that is normally
"now()" or encoder-version-dependent is pinned:

  - creation_time / modification_time = 0 in every box
  - Motion-JPEG samples ('jpeg' VisualSampleEntry — I-frame only, each
    sample an independent baseline JPEG from jpeg.py), so no inter-frame
    encoder state can introduce nondeterminism
  - fixed box order: ftyp, mdat, moov; fixed track/handler metadata

Layout is the classic single-track progressive file: stts (one run),
stsc (one run), stsz (per-sample sizes), stco (absolute offsets into mdat).
"""
from __future__ import annotations

import struct

import numpy as np

from arbius_tpu.codecs.jpeg import encode_jpeg


def _box(tag: bytes, payload: bytes) -> bytes:
    return struct.pack(">I", len(payload) + 8) + tag + payload


def _full(tag: bytes, version: int, flags: int, payload: bytes) -> bytes:
    return _box(tag, struct.pack(">B", version) + struct.pack(">I", flags)[1:]
                + payload)


_MATRIX = struct.pack(">9i", 0x10000, 0, 0, 0, 0x10000, 0, 0, 0, 0x40000000)


def _mvhd(timescale: int, duration: int) -> bytes:
    p = struct.pack(">IIII", 0, 0, timescale, duration)
    p += struct.pack(">iH", 0x10000, 0x100) + b"\x00" * 10  # rate, volume
    p += _MATRIX + b"\x00" * 24 + struct.pack(">I", 2)      # next track id
    return _full(b"mvhd", 0, 0, p)


def _tkhd(duration: int, width: int, height: int) -> bytes:
    p = struct.pack(">IIIII", 0, 0, 1, 0, duration)         # track id 1
    p += b"\x00" * 8 + struct.pack(">HHHH", 0, 0, 0, 0)
    p += _MATRIX
    p += struct.pack(">II", width << 16, height << 16)
    return _full(b"tkhd", 0, 3, p)                          # enabled|in-movie


def _mdhd(timescale: int, duration: int) -> bytes:
    p = struct.pack(">IIII", 0, 0, timescale, duration)
    p += struct.pack(">HH", 0x55C4, 0)                      # language 'und'
    return _full(b"mdhd", 0, 0, p)


def _hdlr() -> bytes:
    p = struct.pack(">I", 0) + b"vide" + b"\x00" * 12 + b"arbius video\x00"
    return _full(b"hdlr", 0, 0, p)


def _visual_entry(tag: bytes, width: int, height: int, name: bytes,
                  extra: bytes = b"") -> bytes:
    """VisualSampleEntry (78 fixed bytes) + child boxes (`extra`)."""
    entry = b"\x00" * 6 + struct.pack(">H", 1)              # reserved, dref 1
    entry += struct.pack(">HHIII", 0, 0, 0, 0, 0)           # pre-defined
    entry += struct.pack(">HH", width, height)
    entry += struct.pack(">II", 0x480000, 0x480000)         # 72 dpi
    entry += struct.pack(">IH", 0, 1)                       # frame count 1
    entry += bytes([len(name)]) + name + b"\x00" * (31 - len(name))
    entry += struct.pack(">Hh", 24, -1)                     # depth, color table
    return _box(tag, entry + extra)


def _stsd(sample_entry: bytes) -> bytes:
    return _full(b"stsd", 0, 0, struct.pack(">I", 1) + sample_entry)


def mux_mjpeg_mp4(jpeg_frames: list[bytes], fps: int,
                  width: int, height: int) -> bytes:
    return _mux_video(jpeg_frames, fps,
                      _visual_entry(b"jpeg", width, height, b"arbius mjpeg"),
                      width, height)


def mux_avc1_mp4(access_units: list[bytes], sps: bytes, pps: bytes,
                 fps: int, width: int, height: int) -> bytes:
    """H.264-in-MP4: each sample is one length-prefixed IDR NAL; SPS/PPS
    travel out-of-band in the avcC record (standard avc1 storage). Every
    sample is a sync sample (all-IDR), so no stss box is needed — its
    absence declares exactly that."""
    from arbius_tpu.codecs.h264 import avcc_box_payload

    samples = [struct.pack(">I", len(au)) + au for au in access_units]
    # avcC carries complete NAL units (header byte + escaped payload),
    # which is exactly what h264.sps_bytes/pps_bytes return
    avcc = _box(b"avcC", avcc_box_payload(sps, pps))
    entry = _visual_entry(b"avc1", width, height, b"arbius avc", avcc)
    return _mux_video(samples, fps, entry, width, height)


def _mux_video(samples: list[bytes], fps: int, sample_entry: bytes,
               width: int, height: int) -> bytes:
    n = len(samples)
    if n == 0:
        raise ValueError("need at least one frame")
    timescale = fps
    duration = n

    mdat_payload = b"".join(samples)
    ftyp = _box(b"ftyp", b"isom" + struct.pack(">I", 0x200) + b"isomiso2mp41")
    mdat = _box(b"mdat", mdat_payload)

    # sample offsets are absolute file offsets; mdat follows ftyp
    data_start = len(ftyp) + 8
    offsets = []
    off = data_start
    for f in samples:
        offsets.append(off)
        off += len(f)

    stts = _full(b"stts", 0, 0, struct.pack(">III", 1, n, 1))
    stsc = _full(b"stsc", 0, 0, struct.pack(">IIII", 1, 1, 1, 1))
    stsz = _full(b"stsz", 0, 0, struct.pack(">II", 0, n)
                 + b"".join(struct.pack(">I", len(f)) for f in samples))
    stco = _full(b"stco", 0, 0, struct.pack(">I", n)
                 + b"".join(struct.pack(">I", o) for o in offsets))
    stbl = _box(b"stbl", _stsd(sample_entry) + stts + stsc + stsz + stco)

    dref = _full(b"dref", 0, 0, struct.pack(">I", 1) + _full(b"url ", 0, 1, b""))
    dinf = _box(b"dinf", dref)
    vmhd = _full(b"vmhd", 0, 1, struct.pack(">HHHH", 0, 0, 0, 0))
    minf = _box(b"minf", vmhd + dinf + stbl)
    mdia = _box(b"mdia", _mdhd(timescale, duration) + _hdlr() + minf)
    trak = _box(b"trak", _tkhd(duration, width, height) + mdia)
    moov = _box(b"moov", _mvhd(timescale, duration) + trak)
    return ftyp + mdat + moov


def encode_mp4(frames: np.ndarray, fps: int = 8, quality: int = 90) -> bytes:
    """uint8 [T,H,W,3] RGB -> deterministic MJPEG-in-MP4 bytes."""
    if frames.dtype != np.uint8 or frames.ndim != 4 or frames.shape[3] != 3:
        raise ValueError(f"expected uint8 [T,H,W,3] RGB, got "
                         f"{frames.dtype} {frames.shape}")
    t, h, w, _ = frames.shape
    jpegs = [encode_jpeg(frames[i], quality=quality) for i in range(t)]
    return mux_mjpeg_mp4(jpegs, fps=fps, width=w, height=h)


def encode_mp4_h264(frames: np.ndarray, fps: int = 8) -> bytes:
    """uint8 [T,H,W,3] RGB -> deterministic H.264 (all-intra I_PCM,
    lossless-in-YCbCr) MP4 bytes — the browser-playable artifact class
    the reference's cog/ffmpeg outputs belong to (codecs/h264.py)."""
    from arbius_tpu.codecs.h264 import encode_h264

    t, h, w, _ = frames.shape
    sps, pps, aus = encode_h264(frames)
    return mux_avc1_mp4(aus, sps, pps, fps=fps, width=w, height=h)
