"""Deterministic media codecs — the artifact-byte layer of the framework.

The solution CID is computed over the *encoded* output files (SURVEY.md §7
hard part #2); the reference outsources encoding to its cog containers, we
own it. Everything here is pinned by specification (integer math, fixed
parameters, no library-version-dependent compressors) so a fleet of miners
produces identical bytes, hence identical CIDs.
"""
from arbius_tpu.codecs.deflate import compress as deflate_compress
from arbius_tpu.codecs.deflate import deflate_fixed, zlib_compress
from arbius_tpu.codecs.jpeg import encode_jpeg
from arbius_tpu.codecs.mp4 import (
    encode_mp4,
    encode_mp4_h264,
    mux_avc1_mp4,
    mux_mjpeg_mp4,
)
from arbius_tpu.codecs.png import encode_png

__all__ = [
    "deflate_compress", "deflate_fixed", "zlib_compress",
    "encode_jpeg", "encode_mp4", "encode_mp4_h264", "mux_avc1_mp4",
    "mux_mjpeg_mp4", "encode_png",
]
