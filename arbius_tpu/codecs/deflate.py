"""Deterministic DEFLATE (fixed-Huffman) — the compression core of PNG.

Why not stdlib zlib: `zlib.compress` output bytes depend on the zlib build
(version, vendor patches), and the solution CID is keccak-committed on-chain
(reference pins via its IPFS daemon, `miner/src/ipfs.ts:11-16`; the CID of
the PNG bytes IS the solution). A fleet of TPU miners must agree on every
byte, so the encoder is pinned by *specification*, not by library version:

  - one final block, BTYPE=01 (fixed Huffman codes, RFC 1951 §3.2.6)
  - greedy LZ77, window 32768, match length 3..258
  - hash over 3 bytes: h = (b0<<16 | b1<<8 | b2) * 2654435761 mod 2^32,
    top 15 bits; hash chains most-recent-first, walk capped at MAX_CHAIN
  - longest match wins; ties go to the nearest distance (first found)
  - every consumed byte position is inserted into the chain

Any implementation of this spec (the C++ one in native/codecs.cc and the
pure-Python one here) produces identical bytes for identical input. The
decompressed stream is standard DEFLATE — `zlib.decompress` verifies it.

`zlib_wrap` adds the RFC 1950 container (CMF/FLG 0x78 0x01 + adler32),
which is what PNG IDAT carries.
"""
from __future__ import annotations

import zlib

MIN_MATCH = 3
MAX_MATCH = 258
WINDOW = 32768
MAX_CHAIN = 32
HASH_BITS = 15

# RFC 1951 §3.2.5: length code, extra bits, base length for codes 257..285
_LENGTH_TABLE = []          # index: length-3 -> (code, extra_bits, extra_val)
_LEN_BASES = [
    (257, 0, 3), (258, 0, 4), (259, 0, 5), (260, 0, 6), (261, 0, 7),
    (262, 0, 8), (263, 0, 9), (264, 0, 10), (265, 1, 11), (266, 1, 13),
    (267, 1, 15), (268, 1, 17), (269, 2, 19), (270, 2, 23), (271, 2, 27),
    (272, 2, 31), (273, 3, 35), (274, 3, 43), (275, 3, 51), (276, 3, 59),
    (277, 4, 67), (278, 4, 83), (279, 4, 99), (280, 4, 115), (281, 5, 131),
    (282, 5, 163), (283, 5, 195), (284, 5, 227), (285, 0, 258),
]
_DIST_BASES = [
    (0, 0, 1), (1, 0, 2), (2, 0, 3), (3, 0, 4), (4, 1, 5), (5, 1, 7),
    (6, 2, 9), (7, 2, 13), (8, 3, 17), (9, 3, 25), (10, 4, 33), (11, 4, 49),
    (12, 5, 65), (13, 5, 97), (14, 6, 129), (15, 6, 193), (16, 7, 257),
    (17, 7, 385), (18, 8, 513), (19, 8, 769), (20, 9, 1025), (21, 9, 1537),
    (22, 10, 2049), (23, 10, 3073), (24, 11, 4097), (25, 11, 6145),
    (26, 12, 8193), (27, 12, 12289), (28, 13, 16385), (29, 13, 24577),
]


def _build_length_table():
    for length in range(MIN_MATCH, MAX_MATCH + 1):
        for i in range(len(_LEN_BASES) - 1, -1, -1):
            code, extra, base = _LEN_BASES[i]
            if length >= base:
                _LENGTH_TABLE.append((code, extra, length - base))
                break
    # code 285 (length 258) has 0 extra bits; the scan above handles it
    assert len(_LENGTH_TABLE) == MAX_MATCH - MIN_MATCH + 1


_build_length_table()

_DIST_TABLE = {}            # small distances precomputed; large ones computed


def _dist_code(dist: int):
    got = _DIST_TABLE.get(dist)
    if got is None:
        for i in range(len(_DIST_BASES) - 1, -1, -1):
            code, extra, base = _DIST_BASES[i]
            if dist >= base:
                got = (code, extra, dist - base)
                break
        if dist <= 4096:
            _DIST_TABLE[dist] = got
    return got


def _fixed_litlen_code(sym: int):
    """RFC 1951 §3.2.6 fixed literal/length code -> (codebits, nbits)."""
    if sym <= 143:
        return 0x30 + sym, 8
    if sym <= 255:
        return 0x190 + (sym - 144), 9
    if sym <= 279:
        return sym - 256, 7
    return 0xC0 + (sym - 280), 8


class _BitWriter:
    """LSB-first bit packing; Huffman codes are emitted bit-reversed."""

    def __init__(self):
        self.out = bytearray()
        self.acc = 0
        self.nbits = 0

    def bits(self, value: int, n: int):
        self.acc |= value << self.nbits
        self.nbits += n
        while self.nbits >= 8:
            self.out.append(self.acc & 0xFF)
            self.acc >>= 8
            self.nbits -= 8

    def huff(self, code: int, n: int):
        rev = 0
        for _ in range(n):
            rev = (rev << 1) | (code & 1)
            code >>= 1
        self.bits(rev, n)

    def finish(self) -> bytes:
        if self.nbits:
            self.out.append(self.acc & 0xFF)
            self.acc = 0
            self.nbits = 0
        return bytes(self.out)


def _hash3(data: bytes, i: int) -> int:
    word = (data[i] << 16) | (data[i + 1] << 8) | data[i + 2]
    return ((word * 2654435761) & 0xFFFFFFFF) >> (32 - HASH_BITS)


def deflate_fixed(data: bytes) -> bytes:
    """Compress per the module-docstring spec. Pure-Python reference path."""
    w = _BitWriter()
    w.bits(1, 1)        # BFINAL
    w.bits(1, 2)        # BTYPE=01 fixed Huffman
    n = len(data)
    head = [-1] * (1 << HASH_BITS)
    prev = [-1] * WINDOW
    i = 0
    while i < n:
        match_len = 0
        match_dist = 0
        if i + MIN_MATCH <= n:
            h = _hash3(data, i)
            cand = head[h]
            chain = 0
            limit = min(MAX_MATCH, n - i)
            while cand >= 0 and i - cand <= WINDOW and chain < MAX_CHAIN:
                # a candidate can only beat the current best if it also
                # matches at offset match_len — cheap pre-check, no effect
                # on which match is chosen
                if match_len == 0 or (match_len < limit and
                                      data[cand + match_len] == data[i + match_len]):
                    length = 0
                    while length < limit and data[cand + length] == data[i + length]:
                        length += 1
                    if length > match_len:
                        match_len = length
                        match_dist = i - cand
                        if length == limit:
                            break
                cand = prev[cand % WINDOW]
                chain += 1
        if match_len >= MIN_MATCH:
            code, extra, ev = _LENGTH_TABLE[match_len - MIN_MATCH]
            cb, cn = _fixed_litlen_code(code)
            w.huff(cb, cn)
            if extra:
                w.bits(ev, extra)
            dcode, dextra, dev = _dist_code(match_dist)
            w.huff(dcode, 5)
            if dextra:
                w.bits(dev, dextra)
            end = i + match_len
            while i < end:
                if i + MIN_MATCH <= n:
                    h = _hash3(data, i)
                    prev[i % WINDOW] = head[h]
                    head[h] = i
                i += 1
        else:
            cb, cn = _fixed_litlen_code(data[i])
            w.huff(cb, cn)
            if i + MIN_MATCH <= n:
                h = _hash3(data, i)
                prev[i % WINDOW] = head[h]
                head[h] = i
            i += 1
    cb, cn = _fixed_litlen_code(256)    # end of block
    w.huff(cb, cn)
    return w.finish()


def compress(data: bytes) -> bytes:
    """Spec-deflate via the native fast path when available, else Python."""
    from arbius_tpu.codecs import _native

    fn = _native.deflate_fixed()
    if fn is not None:
        return fn(data)
    return deflate_fixed(data)


def zlib_wrap(raw_deflate: bytes, data: bytes) -> bytes:
    """RFC 1950 container: 0x78 0x01 header + stream + adler32(data)."""
    return b"\x78\x01" + raw_deflate + zlib.adler32(data).to_bytes(4, "big")


def zlib_compress(data: bytes) -> bytes:
    return zlib_wrap(compress(data), data)
