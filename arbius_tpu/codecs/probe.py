"""Deterministic probe clip: the pinned input for file-input goldens.

`robust_video_matting`'s template input is a video FILE
(`templates/robust_video_matting.json: input_video`), so its boot
self-test golden must pin input bytes, not just a prompt. This clip is
generated with integer-only numpy — identical bytes on every platform
and numpy version — then MJPEG-MP4 encoded by the in-repo deterministic
codec, so (shape → clip bytes → CID) is reproducible anywhere and the
golden stays portable (`cli.py record-golden --probe-video TxHxW`).

Content: a quantized two-axis gradient background with a bright square
translating one step per frame — enough structure for the matting
network to produce non-trivial output on every frame.
"""
from __future__ import annotations

import numpy as np


def probe_clip(frames: int = 4, height: int = 64, width: int = 64) -> np.ndarray:
    """uint8 [T, H, W, 3] deterministic test pattern (integer ops only)."""
    y = np.arange(height, dtype=np.uint32)
    x = np.arange(width, dtype=np.uint32)
    base = np.zeros((height, width, 3), np.uint8)
    base[:, :, 0] = ((y[:, None] * 255) // max(height - 1, 1)).astype(np.uint8)
    base[:, :, 1] = ((x[None, :] * 255) // max(width - 1, 1)).astype(np.uint8)
    base[:, :, 2] = 32

    clip = np.empty((frames, height, width, 3), np.uint8)
    side = max(2, min(height, width) // 4)
    for t in range(frames):
        frame = base.copy()
        top = (t * max(1, height // max(frames, 1))) % max(height - side, 1)
        left = (t * max(1, width // max(frames, 1))) % max(width - side, 1)
        frame[top:top + side, left:left + side] = (255, 255, 224)
        clip[t] = frame
    return clip
