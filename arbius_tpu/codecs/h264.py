"""Deterministic H.264 (AVC) encoder — all-intra I_PCM, Constrained Baseline.

Why this exists: the reference's video models ship standard H.264 MP4s
produced by ffmpeg inside cog containers (templates/zeroscopev2xl.json
declares `out-1.mp4` type video; website/src/pages/task/[taskid].tsx
renders it in a <video> tag). The framework's round-4 artifact was
Motion-JPEG-in-MP4 — deterministic but not decodable by mainstream
browser <video> elements. This module closes the artifact-class gap while
keeping the determinism contract absolute:

  - Every coded field is a fixed function of the input pixels. There is
    no rate control, no lookahead, no encoder state across frames, no
    floating point — identical frames always produce identical bytes.
  - Every frame is an IDR picture made of I_PCM macroblocks: raw 8-bit
    YCbCr samples carried verbatim in the bitstream (spec 7.3.5 /
    8.3.5). I_PCM support is mandatory for every conformant decoder at
    every profile, and the mode is exactly LOSSLESS — the decoder
    reconstructs bit-identical samples, so the deblocking filter is the
    only possible mutation and the slice header turns it off
    (disable_deblocking_filter_idc=1).
  - The cost is size: PCM is uncompressed (1.5 bytes/pixel for 4:2:0),
    the honest trade for a byte-stable, universally decodable artifact.
    (A fixed-QP CAVLC transform path can layer under the same API later;
    it changes size, never the determinism story.)

Color: BT.601 limited-range RGB→YCbCr in pinned integer arithmetic
(8-bit coefficients, round-half-up, 2x2 chroma average with fixed
rounding) — the same class of pinned math as codecs/jpeg.py.

Geometry: dimensions must be even (4:2:0 chroma siting); non-multiples
of 16 are edge-replicated up to whole macroblocks and declared via SPS
frame cropping, so decoders output exactly HxW.

Self-validation: codecs/h264_decode.py is a from-scratch I_PCM decoder;
tests/test_h264.py round-trips encoder→decoder and asserts LOSSLESS
sample recovery (the environment has no third-party H.264 decoder, and
output bytes must never depend on one anyway).
"""
from __future__ import annotations

import re
import struct

import numpy as np

PROFILE_IDC = 66          # Baseline
CONSTRAINT_FLAGS = 0xC0   # constraint_set0+1: Constrained Baseline
LEVEL_IDC = 51            # 5.1 — PCM bitrates exceed low-level caps

_EP_PATTERN = re.compile(rb"\x00\x00(?=[\x00-\x03])")


class BitWriter:
    """MSB-first RBSP bit writer."""

    def __init__(self):
        self._bytes = bytearray()
        self._acc = 0
        self._n = 0

    def u(self, value: int, bits: int) -> None:
        for i in range(bits - 1, -1, -1):
            self._acc = (self._acc << 1) | ((value >> i) & 1)
            self._n += 1
            if self._n == 8:
                self._bytes.append(self._acc)
                self._acc = 0
                self._n = 0

    def ue(self, value: int) -> None:
        code = value + 1
        nbits = code.bit_length()
        self.u(0, nbits - 1)
        self.u(code, nbits)

    def se(self, value: int) -> None:
        self.ue(2 * value - 1 if value > 0 else -2 * value)

    def align_zero(self) -> None:
        if self._n:
            self.u(0, 8 - self._n)

    def raw(self, data: bytes) -> None:
        assert self._n == 0, "raw() requires byte alignment"
        self._bytes += data

    def trailing(self) -> None:
        """rbsp_stop_one_bit + alignment zeros."""
        self.u(1, 1)
        self.align_zero()

    def bytes(self) -> bytes:
        assert self._n == 0, "unterminated bitstream"
        return bytes(self._bytes)


def escape_rbsp(rbsp: bytes) -> bytes:
    """Emulation prevention: 00 00 0x -> 00 00 03 0x (spec 7.4.1.1)."""
    return _EP_PATTERN.sub(b"\x00\x00\x03", rbsp)


def _nal(ref_idc: int, nal_type: int, rbsp: bytes) -> bytes:
    return bytes([(ref_idc << 5) | nal_type]) + escape_rbsp(rbsp)


def sps_bytes(width: int, height: int) -> bytes:
    """Sequence parameter set for WxH all-IDR 4:2:0 video (NAL included)."""
    mbs_w = (width + 15) // 16
    mbs_h = (height + 15) // 16
    w = BitWriter()
    w.u(PROFILE_IDC, 8)
    w.u(CONSTRAINT_FLAGS, 8)
    w.u(LEVEL_IDC, 8)
    w.ue(0)            # seq_parameter_set_id
    w.ue(0)            # log2_max_frame_num_minus4 (frame_num is 0: all IDR)
    w.ue(2)            # pic_order_cnt_type 2: POC = output order, no syntax
    w.ue(1)            # max_num_ref_frames (unused by all-IDR, legal floor)
    w.u(0, 1)          # gaps_in_frame_num_value_allowed_flag
    w.ue(mbs_w - 1)    # pic_width_in_mbs_minus1
    w.ue(mbs_h - 1)    # pic_height_in_map_units_minus1
    w.u(1, 1)          # frame_mbs_only_flag
    w.u(1, 1)          # direct_8x8_inference_flag
    crop_r = mbs_w * 16 - width
    crop_b = mbs_h * 16 - height
    if crop_r or crop_b:
        if crop_r % 2 or crop_b % 2:
            raise ValueError("width/height must be even (4:2:0 crop units)")
        w.u(1, 1)
        w.ue(0)                 # left
        w.ue(crop_r // 2)       # right, in 2-sample crop units
        w.ue(0)                 # top
        w.ue(crop_b // 2)       # bottom
    else:
        w.u(0, 1)
    w.u(0, 1)          # vui_parameters_present_flag
    w.trailing()
    return _nal(3, 7, w.bytes())


def pps_bytes() -> bytes:
    """Picture parameter set (NAL included): CAVLC, deblock control on."""
    w = BitWriter()
    w.ue(0)            # pic_parameter_set_id
    w.ue(0)            # seq_parameter_set_id
    w.u(0, 1)          # entropy_coding_mode_flag: CAVLC
    w.u(0, 1)          # bottom_field_pic_order_in_frame_present_flag
    w.ue(0)            # num_slice_groups_minus1
    w.ue(0)            # num_ref_idx_l0_default_active_minus1
    w.ue(0)            # num_ref_idx_l1_default_active_minus1
    w.u(0, 1)          # weighted_pred_flag
    w.u(0, 2)          # weighted_bipred_idc
    w.se(0)            # pic_init_qp_minus26
    w.se(0)            # pic_init_qs_minus26
    w.se(0)            # chroma_qp_index_offset
    w.u(1, 1)          # deblocking_filter_control_present_flag
    w.u(0, 1)          # constrained_intra_pred_flag
    w.u(0, 1)          # redundant_pic_cnt_present_flag
    w.trailing()
    return _nal(3, 8, w.bytes())


def rgb_to_yuv420(frame: np.ndarray) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """uint8 [H,W,3] RGB → (Y [H,W], Cb [H/2,W/2], Cr) — BT.601 limited
    range, pinned integer math (JFIF-class determinism; see jpeg.py)."""
    if frame.dtype != np.uint8 or frame.ndim != 3 or frame.shape[2] != 3:
        raise ValueError(f"expected uint8 [H,W,3], got {frame.dtype} "
                         f"{frame.shape}")
    h, wd = frame.shape[:2]
    if h % 2 or wd % 2:
        raise ValueError("height/width must be even for 4:2:0")
    r = frame[:, :, 0].astype(np.int32)
    g = frame[:, :, 1].astype(np.int32)
    b = frame[:, :, 2].astype(np.int32)
    y = 16 + ((66 * r + 129 * g + 25 * b + 128) >> 8)
    cb = 128 + ((-38 * r - 74 * g + 112 * b + 128) >> 8)
    cr = 128 + ((112 * r - 94 * g - 18 * b + 128) >> 8)
    # 2x2 chroma average with fixed round-half-up
    def sub(c):
        return (c[0::2, 0::2] + c[0::2, 1::2] + c[1::2, 0::2]
                + c[1::2, 1::2] + 2) >> 2
    return (np.clip(y, 0, 255).astype(np.uint8),
            np.clip(sub(cb), 0, 255).astype(np.uint8),
            np.clip(sub(cr), 0, 255).astype(np.uint8))


def _pad_to_mbs(plane: np.ndarray, mb: int) -> np.ndarray:
    """Edge-replicate a plane up to whole macroblock multiples (the
    decoder crops these samples away; replication keeps them pinned)."""
    h, wd = plane.shape
    ph = (-h) % mb
    pw = (-wd) % mb
    if ph == 0 and pw == 0:
        return plane
    return np.pad(plane, ((0, ph), (0, pw)), mode="edge")


def _mb_blocks(plane: np.ndarray, mb: int) -> np.ndarray:
    """[H, W] plane → [n_mbs, mb*mb] raster-ordered macroblock payloads."""
    h, wd = plane.shape
    return (plane.reshape(h // mb, mb, wd // mb, mb)
            .transpose(0, 2, 1, 3).reshape(-1, mb * mb))


def idr_slice_ipcm(y: np.ndarray, cb: np.ndarray, cr: np.ndarray,
                   idr_pic_id: int) -> bytes:
    """One IDR picture (single slice, all I_PCM macroblocks) as a NAL.

    y: uint8 [H,W] (H,W multiples of 16); cb/cr: uint8 [H/2,W/2].

    Vectorized: after the slice header's first macroblock, every MB
    starts byte-aligned, so its syntax is a CONSTANT 2-byte prefix
    (ue(25) = '000011010' → 0x0D, then the 9th bit + 7 pcm-alignment
    zeros → 0x00) followed by 384 raw sample bytes — the whole slice
    body is one numpy concatenation instead of a 2304-iteration Python
    loop per 1024×576 frame (~20× faster, byte-identical; equality vs
    the scalar BitWriter construction is asserted in tests/test_h264.py).
    """
    mbs_h, mbs_w = y.shape[0] // 16, y.shape[1] // 16
    n = mbs_h * mbs_w
    w = BitWriter()
    w.ue(0)            # first_mb_in_slice
    w.ue(7)            # slice_type: I (all slices in picture are I)
    w.ue(0)            # pic_parameter_set_id
    w.u(0, 4)          # frame_num (log2_max_frame_num = 4; IDR ⇒ 0)
    w.ue(idr_pic_id & 1)  # idr_pic_id (consecutive IDRs must differ)
    w.u(0, 1)          # no_output_of_prior_pics_flag
    w.u(0, 1)          # long_term_reference_flag
    w.se(0)            # slice_qp_delta
    w.ue(1)            # disable_deblocking_filter_idc: OFF (losslessness)
    # first MB via the bit writer (the header leaves an arbitrary bit
    # position; ue(25) + pcm alignment re-aligns)
    w.u(25 + 1, 9)     # ue(25): 4 zeros + '11010'
    w.align_zero()
    mb = np.concatenate([_mb_blocks(y, 16), _mb_blocks(cb, 8),
                         _mb_blocks(cr, 8)], axis=1)   # [n, 384]
    w.raw(mb[0].tobytes())
    if n > 1:
        body = np.concatenate(
            [np.tile(np.array([[0x0D, 0x00]], np.uint8), (n - 1, 1)),
             mb[1:]], axis=1)
        w.raw(body.tobytes())
    w.trailing()
    return _nal(3, 5, w.bytes())


def encode_h264(frames: np.ndarray) -> tuple[bytes, bytes, list[bytes]]:
    """uint8 [T,H,W,3] RGB → (sps_nal, pps_nal, [access_unit_nal, ...])."""
    if frames.dtype != np.uint8 or frames.ndim != 4 or frames.shape[3] != 3:
        raise ValueError(f"expected uint8 [T,H,W,3] RGB, got "
                         f"{frames.dtype} {frames.shape}")
    t, h, wd, _ = frames.shape
    sps = sps_bytes(wd, h)
    pps = pps_bytes()
    aus = []
    for i in range(t):
        y, cb, cr = rgb_to_yuv420(frames[i])
        aus.append(idr_slice_ipcm(_pad_to_mbs(y, 16), _pad_to_mbs(cb, 8),
                                  _pad_to_mbs(cr, 8), idr_pic_id=i))
    return sps, pps, aus


def avcc_box_payload(sps: bytes, pps: bytes) -> bytes:
    """AVCDecoderConfigurationRecord (the avcC box payload)."""
    return (bytes([1, PROFILE_IDC, CONSTRAINT_FLAGS, LEVEL_IDC,
                   0xFF,            # reserved | lengthSizeMinusOne=3
                   0xE1])           # reserved | numOfSPS=1
            + struct.pack(">H", len(sps)) + sps
            + bytes([1])            # numOfPPS
            + struct.pack(">H", len(pps)) + pps)
