"""Deterministic baseline JPEG encoder (integer-exact).

Video solutions need a container the dapp can render (`out-1.mp4`,
`templates/zeroscopev2xl.json` / `damo.json`); we mux Motion-JPEG samples
into MP4 (see mp4.py), so the JPEG bytes must be deterministic across every
miner host. All arithmetic here is integer fixed-point with explicitly
defined rounding — no libm, no floats at encode time — so the output is
pinned by this file, not by a library version:

  - RGB->YCbCr: 16-bit fixed-point constants, add-half then >>16
  - 8x8 FDCT: two 1D passes with a hardcoded 13-bit fixed-point
    cosine matrix, (acc + 4096) >> 13 after each pass
  - quantization: Annex K tables scaled by the libjpeg quality formula,
    coefficient rounding sign * ((|v| + q//2) // q)
  - entropy: standard Annex K Huffman tables, 4:4:4 sampling

Quality defaults to 90 — MJPEG frames are an intermediate the template's
output.type=video consumer decodes, not a fidelity benchmark.
"""
from __future__ import annotations

import struct

import numpy as np

# round(alpha(u)/2 * cos((2x+1)u*pi/16) * 8192); alpha(0)=1/sqrt(2), else 1.
# Hardcoded so no libm call can perturb the table across platforms.
_DCT_M = np.array([
    [2896,  2896,  2896,  2896,  2896,  2896,  2896,  2896],
    [4017,  3406,  2276,   799,  -799, -2276, -3406, -4017],
    [3784,  1567, -1567, -3784, -3784, -1567,  1567,  3784],
    [3406,  -799, -4017, -2276,  2276,  4017,   799, -3406],
    [2896, -2896, -2896,  2896,  2896, -2896, -2896,  2896],
    [2276, -4017,   799,  3406, -3406,  -799,  4017, -2276],
    [1567, -3784,  3784, -1567, -1567,  3784, -3784,  1567],
    [ 799, -2276,  3406, -4017,  4017, -3406,  2276,  -799],
], dtype=np.int64)

_Q_LUMA = np.array([
    16, 11, 10, 16, 24, 40, 51, 61, 12, 12, 14, 19, 26, 58, 60, 55,
    14, 13, 16, 24, 40, 57, 69, 56, 14, 17, 22, 29, 51, 87, 80, 62,
    18, 22, 37, 56, 68, 109, 103, 77, 24, 35, 55, 64, 81, 104, 113, 92,
    49, 64, 78, 87, 103, 121, 120, 101, 72, 92, 95, 98, 112, 100, 103, 99,
], dtype=np.int64)
_Q_CHROMA = np.array([
    17, 18, 24, 47, 99, 99, 99, 99, 18, 21, 26, 66, 99, 99, 99, 99,
    24, 26, 56, 99, 99, 99, 99, 99, 47, 66, 99, 99, 99, 99, 99, 99,
    99, 99, 99, 99, 99, 99, 99, 99, 99, 99, 99, 99, 99, 99, 99, 99,
    99, 99, 99, 99, 99, 99, 99, 99, 99, 99, 99, 99, 99, 99, 99, 99,
], dtype=np.int64)

_ZIGZAG = np.array([
    0, 1, 8, 16, 9, 2, 3, 10, 17, 24, 32, 25, 18, 11, 4, 5,
    12, 19, 26, 33, 40, 48, 41, 34, 27, 20, 13, 6, 7, 14, 21, 28,
    35, 42, 49, 56, 57, 50, 43, 36, 29, 22, 15, 23, 30, 37, 44, 51,
    58, 59, 52, 45, 38, 31, 39, 46, 53, 60, 61, 54, 47, 55, 62, 63,
], dtype=np.int64)

# Annex K Huffman table specs: (bits[1..16], huffval[])
_DC_LUMA = ([0, 1, 5, 1, 1, 1, 1, 1, 1, 0, 0, 0, 0, 0, 0, 0],
            list(range(12)))
_DC_CHROMA = ([0, 3, 1, 1, 1, 1, 1, 1, 1, 1, 1, 0, 0, 0, 0, 0],
              list(range(12)))
_AC_LUMA = ([0, 2, 1, 3, 3, 2, 4, 3, 5, 5, 4, 4, 0, 0, 1, 0x7D], [
    0x01, 0x02, 0x03, 0x00, 0x04, 0x11, 0x05, 0x12, 0x21, 0x31, 0x41, 0x06,
    0x13, 0x51, 0x61, 0x07, 0x22, 0x71, 0x14, 0x32, 0x81, 0x91, 0xA1, 0x08,
    0x23, 0x42, 0xB1, 0xC1, 0x15, 0x52, 0xD1, 0xF0, 0x24, 0x33, 0x62, 0x72,
    0x82, 0x09, 0x0A, 0x16, 0x17, 0x18, 0x19, 0x1A, 0x25, 0x26, 0x27, 0x28,
    0x29, 0x2A, 0x34, 0x35, 0x36, 0x37, 0x38, 0x39, 0x3A, 0x43, 0x44, 0x45,
    0x46, 0x47, 0x48, 0x49, 0x4A, 0x53, 0x54, 0x55, 0x56, 0x57, 0x58, 0x59,
    0x5A, 0x63, 0x64, 0x65, 0x66, 0x67, 0x68, 0x69, 0x6A, 0x73, 0x74, 0x75,
    0x76, 0x77, 0x78, 0x79, 0x7A, 0x83, 0x84, 0x85, 0x86, 0x87, 0x88, 0x89,
    0x8A, 0x92, 0x93, 0x94, 0x95, 0x96, 0x97, 0x98, 0x99, 0x9A, 0xA2, 0xA3,
    0xA4, 0xA5, 0xA6, 0xA7, 0xA8, 0xA9, 0xAA, 0xB2, 0xB3, 0xB4, 0xB5, 0xB6,
    0xB7, 0xB8, 0xB9, 0xBA, 0xC2, 0xC3, 0xC4, 0xC5, 0xC6, 0xC7, 0xC8, 0xC9,
    0xCA, 0xD2, 0xD3, 0xD4, 0xD5, 0xD6, 0xD7, 0xD8, 0xD9, 0xDA, 0xE1, 0xE2,
    0xE3, 0xE4, 0xE5, 0xE6, 0xE7, 0xE8, 0xE9, 0xEA, 0xF1, 0xF2, 0xF3, 0xF4,
    0xF5, 0xF6, 0xF7, 0xF8, 0xF9, 0xFA])
_AC_CHROMA = ([0, 2, 1, 2, 4, 4, 3, 4, 7, 5, 4, 4, 0, 1, 2, 0x77], [
    0x00, 0x01, 0x02, 0x03, 0x11, 0x04, 0x05, 0x21, 0x31, 0x06, 0x12, 0x41,
    0x51, 0x07, 0x61, 0x71, 0x13, 0x22, 0x32, 0x81, 0x08, 0x14, 0x42, 0x91,
    0xA1, 0xB1, 0xC1, 0x09, 0x23, 0x33, 0x52, 0xF0, 0x15, 0x62, 0x72, 0xD1,
    0x0A, 0x16, 0x24, 0x34, 0xE1, 0x25, 0xF1, 0x17, 0x18, 0x19, 0x1A, 0x26,
    0x27, 0x28, 0x29, 0x2A, 0x35, 0x36, 0x37, 0x38, 0x39, 0x3A, 0x43, 0x44,
    0x45, 0x46, 0x47, 0x48, 0x49, 0x4A, 0x53, 0x54, 0x55, 0x56, 0x57, 0x58,
    0x59, 0x5A, 0x63, 0x64, 0x65, 0x66, 0x67, 0x68, 0x69, 0x6A, 0x73, 0x74,
    0x75, 0x76, 0x77, 0x78, 0x79, 0x7A, 0x82, 0x83, 0x84, 0x85, 0x86, 0x87,
    0x88, 0x89, 0x8A, 0x92, 0x93, 0x94, 0x95, 0x96, 0x97, 0x98, 0x99, 0x9A,
    0xA2, 0xA3, 0xA4, 0xA5, 0xA6, 0xA7, 0xA8, 0xA9, 0xAA, 0xB2, 0xB3, 0xB4,
    0xB5, 0xB6, 0xB7, 0xB8, 0xB9, 0xBA, 0xC2, 0xC3, 0xC4, 0xC5, 0xC6, 0xC7,
    0xC8, 0xC9, 0xCA, 0xD2, 0xD3, 0xD4, 0xD5, 0xD6, 0xD7, 0xD8, 0xD9, 0xDA,
    0xE2, 0xE3, 0xE4, 0xE5, 0xE6, 0xE7, 0xE8, 0xE9, 0xEA, 0xF2, 0xF3, 0xF4,
    0xF5, 0xF6, 0xF7, 0xF8, 0xF9, 0xFA])


def _build_huff(bits, huffval):
    """Canonical JPEG Huffman: symbol -> (code, size)."""
    table = {}
    code = 0
    k = 0
    for size in range(1, 17):
        for _ in range(bits[size - 1]):
            table[huffval[k]] = (code, size)
            code += 1
            k += 1
        code <<= 1
    return table

_HUFF_DC = (_build_huff(*_DC_LUMA), _build_huff(*_DC_CHROMA))
_HUFF_AC = (_build_huff(*_AC_LUMA), _build_huff(*_AC_CHROMA))


def _quality_tables(quality: int):
    quality = max(1, min(100, quality))
    scale = 5000 // quality if quality < 50 else 200 - 2 * quality
    out = []
    for base in (_Q_LUMA, _Q_CHROMA):
        q = (base * scale + 50) // 100
        out.append(np.clip(q, 1, 255).astype(np.int64))
    return out


def _rgb_to_ycbcr(img: np.ndarray):
    r = img[..., 0].astype(np.int64)
    g = img[..., 1].astype(np.int64)
    b = img[..., 2].astype(np.int64)
    y = (19595 * r + 38470 * g + 7471 * b + 32768) >> 16
    cb = ((-11056 * r - 21712 * g + 32768 * b + 32768) >> 16) + 128
    cr = ((32768 * r - 27440 * g - 5328 * b + 32768) >> 16) + 128
    return (np.clip(y, 0, 255), np.clip(cb, 0, 255), np.clip(cr, 0, 255))


def _fdct_blocks(blocks: np.ndarray) -> np.ndarray:
    """[N,8,8] level-shifted samples -> [N,8,8] DCT coefficients."""
    t = (np.einsum("ux,nxy->nuy", _DCT_M, blocks) + 4096) >> 13
    return (np.einsum("vy,nuy->nuv", _DCT_M, t) + 4096) >> 13


def _to_blocks(plane: np.ndarray) -> np.ndarray:
    """[H,W] (multiples of 8) -> [N,8,8] in raster block order."""
    h, w = plane.shape
    return (plane.reshape(h // 8, 8, w // 8, 8)
            .transpose(0, 2, 1, 3).reshape(-1, 8, 8))


class _BitWriter:
    """MSB-first JPEG entropy bits with 0xFF byte stuffing."""

    def __init__(self):
        self.out = bytearray()
        self.acc = 0
        self.nbits = 0

    def write(self, code: int, size: int):
        self.acc = (self.acc << size) | (code & ((1 << size) - 1))
        self.nbits += size
        while self.nbits >= 8:
            byte = (self.acc >> (self.nbits - 8)) & 0xFF
            self.out.append(byte)
            if byte == 0xFF:
                self.out.append(0x00)
            self.nbits -= 8
        self.acc &= (1 << self.nbits) - 1

    def finish(self) -> bytes:
        if self.nbits:
            pad = 8 - self.nbits
            self.write((1 << pad) - 1, pad)  # pad with 1-bits per spec
        return bytes(self.out)


def _magnitude(v: int):
    """JPEG magnitude category + value bits (one's-complement negatives)."""
    if v == 0:
        return 0, 0
    a = v if v > 0 else -v
    size = a.bit_length()
    bits = v if v > 0 else v + (1 << size) - 1
    return size, bits


def _dqt(tables) -> bytes:
    payload = b""
    for tid, q in enumerate(tables):
        payload += bytes([tid]) + bytes(int(q[z]) for z in _ZIGZAG)
    return b"\xff\xdb" + struct.pack(">H", len(payload) + 2) + payload


def _dht() -> bytes:
    payload = b""
    for tc, specs in ((0, (_DC_LUMA, _DC_CHROMA)), (1, (_AC_LUMA, _AC_CHROMA))):
        for th, (bits, huffval) in enumerate(specs):
            payload += bytes([(tc << 4) | th]) + bytes(bits) + bytes(huffval)
    return b"\xff\xc4" + struct.pack(">H", len(payload) + 2) + payload


def encode_jpeg(image: np.ndarray, quality: int = 90) -> bytes:
    """uint8 [H,W,3] RGB (H,W multiples of 8) -> baseline JPEG, 4:4:4."""
    if image.dtype != np.uint8 or image.ndim != 3 or image.shape[2] != 3:
        raise ValueError(f"expected uint8 [H,W,3] RGB, got "
                         f"{image.dtype} {image.shape}")
    h, w = image.shape[:2]
    if h % 8 or w % 8:
        raise ValueError("JPEG encoder requires H, W multiples of 8")
    qt = _quality_tables(quality)
    planes = _rgb_to_ycbcr(image)

    coeffs = []
    for ci, plane in enumerate(planes):
        blocks = _to_blocks(plane) - 128
        dct = _fdct_blocks(blocks)
        # DQT stores tables zigzagged; quantization applies in natural order
        qnat = qt[0 if ci == 0 else 1].reshape(8, 8)
        a = np.abs(dct)
        quant = np.sign(dct) * ((a + qnat // 2) // qnat)
        coeffs.append(quant.astype(np.int64))

    bw = _BitWriter()
    dc = [0, 0, 0]
    # interleaved MCU scan, 4:4:4: one block per component per MCU
    zzs = [c.reshape(-1, 64)[:, _ZIGZAG] for c in coeffs]
    n_mcu = zzs[0].shape[0]
    for m in range(n_mcu):
        for ci in range(3):
            chroma = ci > 0
            dc_tab = _HUFF_DC[1 if chroma else 0]
            ac_tab = _HUFF_AC[1 if chroma else 0]
            block = zzs[ci][m]
            diff = int(block[0]) - dc[ci]
            dc[ci] = int(block[0])
            size, bits = _magnitude(diff)
            code, n = dc_tab[size]
            bw.write(code, n)
            if size:
                bw.write(bits, size)
            nz = np.nonzero(block[1:])[0]
            prev = 0
            for idx in nz:
                run = int(idx) - prev
                prev = int(idx) + 1
                while run > 15:
                    code, n = ac_tab[0xF0]
                    bw.write(code, n)
                    run -= 16
                size, bits = _magnitude(int(block[1 + idx]))
                code, n = ac_tab[(run << 4) | size]
                bw.write(code, n)
                bw.write(bits, size)
            if prev < 63:
                code, n = ac_tab[0x00]
                bw.write(code, n)
    scan = bw.finish()

    out = bytearray(b"\xff\xd8")                       # SOI
    out += (b"\xff\xe0" + struct.pack(">H", 16) + b"JFIF\x00"
            + bytes([1, 1, 0]) + struct.pack(">HH", 1, 1) + bytes([0, 0]))
    out += _dqt(qt)
    sof = struct.pack(">BHHB", 8, h, w, 3)
    for cid in range(3):
        sof += bytes([cid + 1, 0x11, 0 if cid == 0 else 1])
    out += b"\xff\xc0" + struct.pack(">H", len(sof) + 2) + sof
    out += _dht()
    sos = bytes([3])
    for cid in range(3):
        th = 0 if cid == 0 else 1
        sos += bytes([cid + 1, (th << 4) | th])
    sos += bytes([0, 63, 0])
    out += b"\xff\xda" + struct.pack(">H", len(sos) + 2) + sos
    out += scan
    out += b"\xff\xd9"                                 # EOI
    return bytes(out)
