"""Loader for the native codec core (native/codecs.cc).

Tries, in order: a prebuilt `native/build/libarbius_codecs.so`, building one
with g++ on first use (cached on disk), else returns None so callers fall
back to the pure-Python reference implementation. Both paths implement the
same byte-exact spec, so the fallback changes speed, never output.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "codecs.cc")
_BUILD_DIR = os.path.join(_REPO_ROOT, "native", "build")
_SO = os.path.join(_BUILD_DIR, "libarbius_codecs.so")

_lock = threading.Lock()
_lib = None
_tried = False


def _load() -> ctypes.CDLL | None:
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SO) and os.path.exists(_SRC):
            try:
                os.makedirs(_BUILD_DIR, exist_ok=True)
                tmp = _SO + f".tmp{os.getpid()}"
                # detlint: allow[CONC403] the lock EXISTS to serialize
                # this one-time native build — concurrent callers must
                # block until the .so is compiled, and the 120 s timeout
                # bounds the stall
                subprocess.run(
                    ["g++", "-O2", "-shared", "-fPIC", "-o", tmp, _SRC],
                    check=True, capture_output=True, timeout=120)
                os.replace(tmp, _SO)
            except Exception:
                return None
        if not os.path.exists(_SO):
            return None
        try:
            lib = ctypes.CDLL(_SO)
            lib.arbius_deflate_fixed.restype = ctypes.c_size_t
            lib.arbius_deflate_fixed.argtypes = [
                ctypes.c_char_p, ctypes.c_size_t,
                ctypes.POINTER(ctypes.c_uint8), ctypes.c_size_t]
            _lib = lib
        except Exception:
            _lib = None
        return _lib


def deflate_fixed():
    """Return a bytes->bytes compressor backed by the .so, or None."""
    lib = _load()
    if lib is None:
        return None

    def fn(data: bytes) -> bytes:
        # worst case fixed-Huffman: 9 bits/literal + 3-bit header + EOB
        cap = len(data) + len(data) // 4 + 64
        out = (ctypes.c_uint8 * cap)()
        written = lib.arbius_deflate_fixed(data, len(data), out, cap)
        if written == 0 and data:
            raise RuntimeError("native deflate overflow (bug: cap too small)")
        return bytes(out[:written])

    return fn
