"""Deterministic diffusion samplers (template enum `scheduler`)."""
from arbius_tpu.schedulers.diffusion import (
    NUM_TRAIN_TIMESTEPS,
    alphas_cumprod,
)
from arbius_tpu.schedulers.samplers import (
    SAMPLER_NAMES,
    Sampler,
    get_sampler,
    sampler_tag,
)

__all__ = [
    "NUM_TRAIN_TIMESTEPS",
    "SAMPLER_NAMES",
    "Sampler",
    "alphas_cumprod",
    "get_sampler",
    "sampler_tag",
]
