"""Shared diffusion-process tables.

The reference's image models are Stable-Diffusion-family latent diffusion
(templates/anythingv3.json declares the six scheduler choices; the cog
containers run diffusers samplers on top of the SD-1.5 noise schedule).
All schedule math is done host-side in float64 numpy — tables are static
per (scheduler, num_steps) so jit caching is clean — and cast to float32
for the device.
"""
from __future__ import annotations

import numpy as np

NUM_TRAIN_TIMESTEPS = 1000
BETA_START = 0.00085
BETA_END = 0.012


def alphas_cumprod(
    num_train_timesteps: int = NUM_TRAIN_TIMESTEPS,
    beta_start: float = BETA_START,
    beta_end: float = BETA_END,
) -> np.ndarray:
    """SD "scaled_linear" schedule: betas linear in sqrt-space."""
    betas = np.linspace(beta_start ** 0.5, beta_end ** 0.5,
                        num_train_timesteps, dtype=np.float64) ** 2
    return np.cumprod(1.0 - betas)


def leading_timesteps(num_steps: int, num_train: int = NUM_TRAIN_TIMESTEPS,
                      steps_offset: int = 1) -> np.ndarray:
    """'leading' spacing with offset, descending (DDIM / PNDM family)."""
    ratio = num_train // num_steps
    ts = (np.arange(num_steps) * ratio).round()[::-1].astype(np.int64)
    return ts + steps_offset


def linspace_timesteps(num_steps: int, num_train: int = NUM_TRAIN_TIMESTEPS) -> np.ndarray:
    """'linspace' spacing, descending, float (Euler / LMS family)."""
    return np.linspace(0, num_train - 1, num_steps, dtype=np.float64)[::-1].copy()


def karras_style_sigmas(timesteps: np.ndarray,
                        acp: np.ndarray) -> np.ndarray:
    """sigma(t) = sqrt((1-acp)/acp) interpolated at (possibly fractional) t."""
    full_sigmas = np.sqrt((1.0 - acp) / acp)
    return np.interp(timesteps, np.arange(len(acp)), full_sigmas)
