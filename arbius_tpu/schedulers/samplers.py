"""The six deterministic samplers the templates declare.

`templates/anythingv3.json` enumerates: DDIM, K_EULER, DPMSolverMultistep,
K_EULER_ANCESTRAL, PNDM, KLMS. The reference runs these inside its cog
container (diffusers semantics on the SD-1.5 schedule); here each is
implemented from the published sampler math, TPU-first:

  - every per-step quantity is precomputed host-side in float64 into fixed
    tables (static per (sampler, num_steps) -> stable jit cache keys);
  - the device-side `step` is a pure function of (i, x, eps, carry, noise)
    made of table lookups and fused elementwise ops -> scan-friendly, no
    data-dependent control flow;
  - ancestral noise is supplied BY THE CALLER (derived from the task seed
    via fold_in) so sampling stays bit-reproducible.

All samplers are linear in (x, eps) with per-step scalar coefficients; the
history-based ones (PNDM, KLMS, DPM++) carry small ring buffers through the
scan.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from arbius_tpu.schedulers.diffusion import (
    NUM_TRAIN_TIMESTEPS,
    alphas_cumprod,
    karras_style_sigmas,
    leading_timesteps,
    linspace_timesteps,
)

__all__ = ["get_sampler", "SAMPLER_NAMES", "Sampler", "sampler_tag"]


class Sampler:
    """Uniform sampler interface consumed by pipelines.

    Attributes:
      num_model_calls: static number of model evaluations.
      timesteps: f32[num_model_calls] conditioning value per call.
      input_scale: f32[num_model_calls] multiplier applied to x before the
        model (sigma-space samplers divide by sqrt(sigma^2+1)).
      init_noise_sigma: float; initial latent noise multiplier.
      needs_noise: whether `step` consumes fresh noise (ancestral only).
    """

    name: str = ""
    needs_noise: bool = False
    init_noise_sigma: float = 1.0

    def init_carry(self, x: jax.Array):
        return ()

    def step(self, i, x, eps, carry, noise):
        raise NotImplementedError


def _f32(a) -> jax.Array:
    return jnp.asarray(np.asarray(a, dtype=np.float32))


class DDIMSampler(Sampler):
    """DDIM, eta=0: x' = c_x[i]*x + c_e[i]*eps (pure deterministic ODE step).

    Leading timestep spacing with offset 1; final step targets
    alphas_cumprod[0] (set_alpha_to_one=False convention for SD).
    """

    name = "DDIM"

    def __init__(self, num_steps: int):
        acp = alphas_cumprod()
        ts = leading_timesteps(num_steps)
        ratio = NUM_TRAIN_TIMESTEPS // num_steps
        acp_t = acp[ts]
        prev = ts - ratio
        acp_p = np.where(prev >= 0, acp[np.clip(prev, 0, None)], acp[0])
        a_t, s_t = np.sqrt(acp_t), np.sqrt(1 - acp_t)
        a_p, s_p = np.sqrt(acp_p), np.sqrt(1 - acp_p)
        self.num_model_calls = num_steps
        self.timesteps = _f32(ts)
        self.input_scale = _f32(np.ones(num_steps))
        self._c_x = _f32(a_p / a_t)
        self._c_e = _f32(s_p - a_p * s_t / a_t)

    def step(self, i, x, eps, carry, noise):
        return self._c_x[i] * x + self._c_e[i] * eps, carry


class EulerSampler(Sampler):
    """K_EULER — Euler method on the sigma-space probability-flow ODE."""

    name = "K_EULER"

    def __init__(self, num_steps: int):
        acp = alphas_cumprod()
        ts = linspace_timesteps(num_steps)
        sig = np.concatenate([karras_style_sigmas(ts, acp), [0.0]])
        self.num_model_calls = num_steps
        self.timesteps = _f32(ts)
        self.input_scale = _f32(1.0 / np.sqrt(sig[:-1] ** 2 + 1))
        self._dsigma = _f32(sig[1:] - sig[:-1])
        self.init_noise_sigma = float(sig[0])

    def step(self, i, x, eps, carry, noise):
        # d = (x - (x - sigma*eps)) / sigma = eps
        return x + self._dsigma[i] * eps, carry


class EulerAncestralSampler(Sampler):
    """K_EULER_ANCESTRAL — Euler step to sigma_down plus fresh noise*sigma_up.

    Noise comes from the caller (seeded per task+step), keeping the sampler
    bit-deterministic for a given task id.
    """

    name = "K_EULER_ANCESTRAL"
    needs_noise = True

    def __init__(self, num_steps: int):
        acp = alphas_cumprod()
        ts = linspace_timesteps(num_steps)
        sig = np.concatenate([karras_style_sigmas(ts, acp), [0.0]])
        s, sn = sig[:-1], sig[1:]
        with np.errstate(divide="ignore", invalid="ignore"):
            sig_up = np.sqrt(np.maximum(sn**2 * (s**2 - sn**2) / s**2, 0.0))
        sig_down = np.sqrt(np.maximum(sn**2 - sig_up**2, 0.0))
        self.num_model_calls = num_steps
        self.timesteps = _f32(ts)
        self.input_scale = _f32(1.0 / np.sqrt(s**2 + 1))
        self._dsigma = _f32(sig_down - s)
        self._sig_up = _f32(sig_up)
        self.init_noise_sigma = float(sig[0])

    def step(self, i, x, eps, carry, noise):
        return x + self._dsigma[i] * eps + self._sig_up[i] * noise, carry


class LMSSampler(Sampler):
    """KLMS — 4th-order linear multistep over the sigma-space ODE.

    Adams-Bashforth-style coefficients: integrals of the Lagrange basis over
    each [sigma_i, sigma_{i+1}] interval, computed host-side on a fixed
    Simpson grid (deterministic, no adaptive quadrature).
    """

    name = "KLMS"
    ORDER = 4

    def __init__(self, num_steps: int):
        acp = alphas_cumprod()
        ts = linspace_timesteps(num_steps)
        sig = np.concatenate([karras_style_sigmas(ts, acp), [0.0]])
        coeffs = np.zeros((num_steps, self.ORDER), dtype=np.float64)
        for i in range(num_steps):
            order = min(i + 1, self.ORDER)
            for j in range(order):
                coeffs[i, j] = self._lms_coeff(sig, i, j, order)
        self.num_model_calls = num_steps
        self.timesteps = _f32(ts)
        self.input_scale = _f32(1.0 / np.sqrt(sig[:-1] ** 2 + 1))
        self._coeffs = _f32(coeffs)
        self.init_noise_sigma = float(sig[0])

    @staticmethod
    def _lms_coeff(sig: np.ndarray, i: int, j: int, order: int) -> float:
        # integral over [sig[i], sig[i+1]] of prod_{k!=j} (s - sig[i-k]) /
        # (sig[i-j] - sig[i-k]); fixed 4096-interval Simpson rule.
        n = 4096
        s = np.linspace(sig[i], sig[i + 1], n + 1)
        prod = np.ones_like(s)
        for k in range(order):
            if k == j:
                continue
            prod *= (s - sig[i - k]) / (sig[i - j] - sig[i - k])
        w = np.ones(n + 1)
        w[1:-1:2], w[2:-1:2] = 4.0, 2.0
        h = (sig[i + 1] - sig[i]) / n
        return float(h / 3.0 * np.sum(w * prod))

    def init_carry(self, x):
        return (jnp.zeros((self.ORDER,) + x.shape, x.dtype),)

    def step(self, i, x, eps, carry, noise):
        (hist,) = carry
        # recent-first derivative history; d = eps in sigma space
        hist = jnp.concatenate([eps[None], hist[:-1]], axis=0)
        w = self._coeffs[i]  # [ORDER]
        upd = jnp.tensordot(w, hist, axes=1)
        return x + upd, (hist,)


class DPMSolverMultistepSampler(Sampler):
    """DPMSolverMultistep — DPM-Solver++(2M), epsilon-pred, midpoint rule.

    Second-order multistep in lambda = log(alpha/sigma) space; first-order
    (=DDIM-like) on the first call and, matching common practice, on the
    final call when num_steps < 15.
    """

    name = "DPMSolverMultistep"

    def __init__(self, num_steps: int):
        acp = alphas_cumprod()
        ts = np.linspace(0, NUM_TRAIN_TIMESTEPS - 1,
                         num_steps + 1).round()[::-1][:-1].astype(np.int64)
        # boundary target after the last call: t=0
        t_all = np.concatenate([ts, [0]])
        acp_all = acp[t_all]
        alpha = np.sqrt(acp_all)
        sigma = np.sqrt(1 - acp_all)
        lam = np.log(alpha / sigma)
        h = lam[1:] - lam[:-1]                       # [S] per-call step in lambda
        self.num_model_calls = num_steps
        self.timesteps = _f32(ts)
        self.input_scale = _f32(np.ones(num_steps))
        # x0 prediction: x0 = inv_alpha[i]*x - sig_ratio[i]*eps
        self._inv_alpha = _f32(1.0 / alpha[:-1])
        self._sig_over_alpha = _f32(sigma[:-1] / alpha[:-1])
        self._xcoef = _f32(sigma[1:] / sigma[:-1])   # (sigma_t / sigma_s0)
        self._d0coef = _f32(-alpha[1:] * (np.exp(-h) - 1.0))
        with np.errstate(divide="ignore", invalid="ignore"):
            r0 = np.concatenate([[1.0], (lam[1:-1] - lam[:-2]) / h[1:]])
        self._inv_2r0 = _f32(np.where(np.isfinite(r0), 0.5 / r0, 0.0))
        second = np.ones(num_steps, dtype=bool)
        second[0] = False
        if num_steps < 15:
            second[-1] = False
        self._second = jnp.asarray(second)

    def init_carry(self, x):
        return (jnp.zeros_like(x),)

    def step(self, i, x, eps, carry, noise):
        (m_prev,) = carry
        m0 = self._inv_alpha[i] * x - self._sig_over_alpha[i] * eps
        d1 = (m0 - m_prev) * self._inv_2r0[i]
        d = jnp.where(self._second[i], m0 + d1, m0)
        x_next = self._xcoef[i] * x + self._d0coef[i] * d
        return x_next, (m0,)


class PNDMSampler(Sampler):
    """PNDM (PLMS path, skip_prk_steps) — pseudo linear multistep.

    Call sequence duplicates the second timestep (S+1 model calls for S
    steps): call 1 refines call 0's step via a trapezoid correction applied
    from the SAVED pre-step sample. History weights and the transfer
    coefficients of the underlying DDIM-like update are all precomputed.
    """

    name = "PNDM"
    ORDER = 3  # history slots used in addition to the current eps

    def __init__(self, num_steps: int):
        acp = alphas_cumprod()
        ratio = NUM_TRAIN_TIMESTEPS // num_steps
        ts = leading_timesteps(num_steps)  # descending [T0..T_{S-1}]
        # model-call timesteps: [T0, T1, T1, T2, ..., T_{S-1}]
        call_ts = np.concatenate([ts[:1], ts[1:2], ts[1:]])
        # per-call (from, to) pairs
        pair_from = np.concatenate([ts[:1], ts[:1], ts[1:]])
        pair_to = pair_from - ratio
        acp_t = acp[pair_from]
        acp_p = np.where(pair_to >= 0, acp[np.clip(pair_to, 0, None)], acp[0])
        self._sc = _f32(np.sqrt(acp_p / acp_t))
        denom = acp_t * np.sqrt(1 - acp_p) + np.sqrt(acp_t * (1 - acp_t) * acp_p)
        self._dc = _f32(-(acp_p - acp_t) / denom)
        calls = num_steps + 1
        w_cur = np.zeros(calls)
        w_hist = np.zeros((calls, self.ORDER))
        for i in range(calls):
            if i == 0:
                w_cur[i] = 1.0
            elif i == 1:
                w_cur[i], w_hist[i, 0] = 0.5, 0.5
            elif i == 2:
                w_cur[i], w_hist[i, 0] = 1.5, -0.5
            elif i == 3:
                w_cur[i], w_hist[i, :2] = 23 / 12, (-16 / 12, 5 / 12)
            else:
                w_cur[i], w_hist[i, :3] = 55 / 24, (-59 / 24, 37 / 24, -9 / 24)
        self.num_model_calls = calls
        self.timesteps = _f32(call_ts)
        self.input_scale = _f32(np.ones(calls))
        self._w_cur = _f32(w_cur)
        self._w_hist = _f32(w_hist)

    def init_carry(self, x):
        return (jnp.zeros((self.ORDER,) + x.shape, x.dtype), jnp.zeros_like(x))

    def step(self, i, x, eps, carry, noise):
        hist, cur_sample = carry
        e_prime = self._w_cur[i] * eps + jnp.tensordot(self._w_hist[i], hist, axes=1)
        x_from = jnp.where(i == 1, cur_sample, x)
        x_next = self._sc[i] * x_from + self._dc[i] * e_prime
        # append eps to history except on the trapezoid-refinement call
        appended = jnp.concatenate([eps[None], hist[:-1]], axis=0)
        hist = jnp.where(i == 1, hist, appended)
        cur_sample = jnp.where(i == 0, x, cur_sample)
        return x_next, (hist, cur_sample)


_REGISTRY = {
    "DDIM": DDIMSampler,
    "K_EULER": EulerSampler,
    "K_EULER_ANCESTRAL": EulerAncestralSampler,
    "DPMSolverMultistep": DPMSolverMultistepSampler,
    "PNDM": PNDMSampler,
    "KLMS": LMSSampler,
}

SAMPLER_NAMES = tuple(_REGISTRY)


@functools.lru_cache(maxsize=64)
def get_sampler(name: str, num_steps: int) -> Sampler:
    """Sampler instance cache — static tables are reused across tasks."""
    try:
        cls = _REGISTRY[name]
    except KeyError:
        raise ValueError(f"unknown scheduler {name!r}; expected one of {SAMPLER_NAMES}")
    if num_steps < 1 or num_steps > NUM_TRAIN_TIMESTEPS:
        raise ValueError(f"num_steps must be in [1, {NUM_TRAIN_TIMESTEPS}]")
    return cls(num_steps)


def sampler_tag(name: str, num_steps: int) -> str:
    """Filename-safe tag identifying one (sampler, num_steps) program
    slice — e.g. ``ddim.s2``. The sampler's static tables are baked into
    the traced graph as constants, so (name, num_steps) is part of XLA
    program identity; graphlint trace specs (models/trace_specs.py) use
    this tag inside their shape-bucket keys and golden filenames."""
    if name not in _REGISTRY:
        raise ValueError(
            f"unknown scheduler {name!r}; expected one of {SAMPLER_NAMES}")
    return f"{name.lower().replace('_', '-')}.s{int(num_steps)}"
