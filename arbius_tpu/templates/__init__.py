"""Template/schema engine (L0b) — model templates drive everything.

A template declares a model's input schema, output files, and metadata
(documented in the reference at `docs/src/pages/register-model.mdx:63-120`).
The five reference templates ship as data files under ``data/``.
"""
from arbius_tpu.templates.engine import (
    FilterResult,
    HydrationError,
    InputField,
    MiningFilter,
    OutputField,
    Template,
    check_model_filter,
    hydrate_input,
    load_template,
    template_names,
)

__all__ = [
    "FilterResult",
    "HydrationError",
    "InputField",
    "MiningFilter",
    "OutputField",
    "Template",
    "check_model_filter",
    "hydrate_input",
    "load_template",
    "template_names",
]
