"""Template parsing, input hydration, and mining filters.

Behavioral parity with the reference miner's `models.ts`:
  - hydrate_input       ≡ hydrateInput   (`miner/src/models.ts:145-220`)
  - check_model_filter  ≡ checkModelFilter (`miner/src/models.ts:100-143`)

Two deliberate divergences from reference bugs, both documented here:
  1. `models.ts:194` writes ``row > col.max`` (comparing the schema row
     object against an undefined property), so the reference never enforces
     the declared max. We enforce both bounds.
  2. `models.ts:185-188` type-checks ``decimal`` with the same int cast as
     ``int`` (``col !== (col|0)``), so fractional decimals like
     guidance_scale 17.5 are rejected by the reference validator even though
     templates declare decimal ranges. We accept finite int/float.
"""
from __future__ import annotations

import json
import math
from dataclasses import dataclass, field
from importlib import resources
from typing import Any

VALID_TYPES = ("string", "int", "decimal", "string_enum", "int_enum", "file")
VALID_OUTPUT_TYPES = ("image", "video", "text", "audio")


class HydrationError(ValueError):
    """Input does not satisfy the template schema."""


@dataclass(frozen=True)
class InputField:
    variable: str
    type: str
    required: bool = False
    default: Any = None
    min: float | None = None
    max: float | None = None
    choices: tuple = ()
    description: str = ""


@dataclass(frozen=True)
class OutputField:
    filename: str
    type: str


@dataclass(frozen=True)
class Template:
    """Parsed model template (schema in `docs/src/pages/register-model.mdx`)."""
    title: str
    description: str
    version: int
    git: str = ""
    docker: str = ""
    inputs: tuple[InputField, ...] = ()
    outputs: tuple[OutputField, ...] = ()

    @classmethod
    def from_dict(cls, raw: dict) -> "Template":
        meta = raw.get("meta", {})
        inputs = []
        for row in raw.get("input", []):
            typ = row["type"]
            if typ not in VALID_TYPES:
                raise ValueError(f"unknown input type {typ!r} for {row.get('variable')}")
            inputs.append(InputField(
                variable=row["variable"],
                type=typ,
                required=bool(row.get("required", False)),
                default=row.get("default"),
                min=row.get("min"),
                max=row.get("max"),
                choices=tuple(row.get("choices", ())),
                description=row.get("description", ""),
            ))
        outputs = []
        for row in raw.get("output", []):
            if row["type"] not in VALID_OUTPUT_TYPES:
                raise ValueError(f"unknown output type {row['type']!r}")
            outputs.append(OutputField(filename=row["filename"], type=row["type"]))
        return cls(
            title=meta.get("title", ""),
            description=meta.get("description", ""),
            version=int(meta.get("version", 0)),
            git=meta.get("git", ""),
            docker=meta.get("docker", ""),
            inputs=tuple(inputs),
            outputs=tuple(outputs),
        )

    def to_json_bytes(self) -> bytes:
        """Canonical bytes for CID/registration purposes — not reconstructed,
        use the original file via load_template_bytes for registration."""
        raise NotImplementedError("register with the original template bytes")


def _data_root():
    return resources.files("arbius_tpu.templates") / "data"


def template_names() -> list[str]:
    return sorted(p.name[:-5] for p in _data_root().iterdir() if p.name.endswith(".json"))


def load_template_bytes(name: str) -> bytes:
    return (_data_root() / f"{name}.json").read_bytes()


def load_template(name: str) -> Template:
    return Template.from_dict(json.loads(load_template_bytes(name)))


def _is_int(value: Any) -> bool:
    return isinstance(value, int) and not isinstance(value, bool)


def _is_number(value: Any) -> bool:
    return (_is_int(value) or isinstance(value, float)) and math.isfinite(value)


def hydrate_input(preprocessed: dict, template: Template) -> dict:
    """Validate raw task input against the template; fill defaults.

    Mirrors `miner/src/models.ts:145-220`: required-field check, type check,
    range check for numerics, enum membership, defaults for absent optionals.
    Raises HydrationError with a message in the reference's format.
    """
    out: dict[str, Any] = {}
    for row in template.inputs:
        col = preprocessed.get(row.variable)
        present = row.variable in preprocessed

        if row.required and not present:
            raise HydrationError(f"input missing required field ({row.variable})")

        if present:
            if row.type in ("string", "string_enum", "file"):
                if not isinstance(col, str):
                    raise HydrationError(f"input wrong type ({row.variable})")
            elif row.type in ("int", "int_enum"):
                if not _is_int(col):
                    raise HydrationError(f"input wrong type ({row.variable})")
            elif row.type == "decimal":
                if not _is_number(col):
                    raise HydrationError(f"input wrong type ({row.variable})")

            if row.type in ("int", "decimal"):
                if row.min is not None and col < row.min:
                    raise HydrationError(f"input out of bounds ({row.variable})")
                if row.max is not None and col > row.max:
                    raise HydrationError(f"input out of bounds ({row.variable})")

            if row.type in ("string_enum", "int_enum"):
                if col not in row.choices:
                    raise HydrationError(f"input not in enum ({row.variable})")

            out[row.variable] = col
        else:
            out[row.variable] = row.default

    return out


@dataclass(frozen=True)
class MiningFilter:
    """Operator-side task acceptance rule (`miner/src/types.ts` MiningFilter)."""
    minfee: int = 0          # wei; task fee must be >= this
    mintime: int = 0         # seconds the task must have aged, 0 = no wait
    owner: str | None = None  # restrict to a task owner address


@dataclass(frozen=True)
class FilterResult:
    model_enabled: bool
    filter_passed: bool
    template: Template | None


def check_model_filter(
    models: dict[str, tuple[Template, list[MiningFilter]]],
    *,
    model: str,
    now: float,
    fee: int,
    blocktime: float,
    owner: str,
) -> FilterResult:
    """≡ checkModelFilter (`miner/src/models.ts:100-143`).

    Note the reference semantics, preserved here: a model with an EMPTY
    filter list never passes — operators must configure at least one filter
    (MiningFilter() accepts everything).
    """
    entry = models.get(model)
    if entry is None:
        return FilterResult(False, False, None)
    template, filters = entry
    for f in filters:
        if f.owner and owner != f.owner:
            continue
        if not fee >= f.minfee:
            continue
        age = now - blocktime
        if f.mintime > 0 and age < f.mintime:
            continue
        return FilterResult(True, True, template)
    return FilterResult(True, False, template)
