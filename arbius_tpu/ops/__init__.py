"""TPU op layer: ring + Ulysses sequence parallelism and pallas kernels.

Custom compute that XLA's default lowering doesn't give us: exact
sequence-parallel attention over a mesh axis, and (ops.flash) a pallas
flash-attention kernel for long single-device sequences.
"""
from arbius_tpu.ops.ring import ring_attention, sp_attention_reference
from arbius_tpu.ops.ulysses import ulysses_attention

__all__ = ["ring_attention", "sp_attention_reference", "ulysses_attention"]
