"""Ring attention over a named mesh axis — sequence/context parallelism.

The reference has no long-context machinery at all (SURVEY.md §2.6: max
"sequence" is 96 video frames inside one GPU container). Here long
sequences are first-class: shard the sequence axis over the mesh ('sp'),
keep Q local, and rotate K/V shards around the ring with `ppermute` while
accumulating attention in the numerically safe online-softmax form
(flash-attention accumulation: running max m, normalizer l, weighted sum
acc — all float32).

ICI mapping: each step overlaps one K/V shard's worth of compute with one
neighbor hop; after sp steps every query has attended to the full
sequence without any all-gather materializing it. This is the substrate
for UNet3D temporal attention (frame axis) and any future long-context
model.

Use inside shard_map with the sequence axis sharded over `axis_name`:
    out = ring_attention(q, k, v, axis_name="sp")
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def _attn_block(q, k, v, scale):
    """Scores for one (local Q, one K/V shard) block; f32 softmax stats.

    q: [B, H, Sq, D], k/v: [B, H, Sk, D] → (scores_max [B,H,Sq],
    exp-weighted sum [B,H,Sq,D], normalizer [B,H,Sq])."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    m = s.max(axis=-1)
    p = jnp.exp(s - m[..., None])
    l = p.sum(axis=-1)
    acc = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v).astype(jnp.float32)
    return m, acc, l


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                   axis_name: str) -> jax.Array:
    """Exact attention over a sequence sharded on `axis_name`.

    Shapes per shard: q/k/v [B, H, S_local, D]. Returns [B, H, S_local, D]
    in q.dtype. Must run inside shard_map with `axis_name` in the mesh.
    """
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    scale = 1.0 / np.sqrt(q.shape[-1])
    perm = [(i, (i + 1) % n) for i in range(n)]  # pass K/V to the next rank

    m0, acc0, l0 = _attn_block(q, k, v, scale)

    def body(carry, _):
        m, acc, l, k, v = carry
        k = jax.lax.ppermute(k, axis_name, perm)
        v = jax.lax.ppermute(v, axis_name, perm)
        mb, accb, lb = _attn_block(q, k, v, scale)
        m_new = jnp.maximum(m, mb)
        a1 = jnp.exp(m - m_new)
        a2 = jnp.exp(mb - m_new)
        acc = acc * a1[..., None] + accb * a2[..., None]
        l = l * a1 + lb * a2
        return (m_new, acc, l, k, v), None

    if n > 1:
        (m, acc, l, _, _), _ = jax.lax.scan(
            body, (m0, acc0, l0, k, v), None, length=n - 1)
    else:
        m, acc, l = m0, acc0, l0
    _ = idx  # rank only matters for causal variants; full attention here
    return (acc / l[..., None]).astype(q.dtype)


def sp_attention_reference(q, k, v):
    """Single-device exact attention with the same f32 softmax policy —
    the correctness oracle for ring_attention tests."""
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v).astype(q.dtype)
