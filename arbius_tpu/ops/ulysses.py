"""Ulysses-style all-to-all sequence parallelism over a named mesh axis.

The second of the two first-class long-context strategies (alongside
`ops/ring.py`): instead of rotating K/V shards around a ring, ONE
`all_to_all` re-shards the layout from sequence-sharded to head-sharded,
every head group then attends over the FULL sequence locally, and a
second `all_to_all` restores sequence sharding (the DeepSpeed-Ulysses
communication pattern).

Trade-off vs ring: two all-to-alls of activation size total (cheap,
latency-bound) versus (sp−1) K/V hops (bandwidth overlapped with
compute); Ulysses needs heads % sp == 0 and holds the FULL sequence's
K/V per head group (activation memory is identical — S·H/sp ≡ S/sp·H —
the asymmetry is score/working-set shape: a flash attend over full-S
blocks here vs ring's (S/sp)-sized blocks, and ring never materializes
full-S K/V on a chip). Rule of thumb on TPU: Ulysses when heads are
plentiful and full-S K/V fits per chip (video frame axes, ≤~10^4
tokens); ring when the sequence axis is the thing that doesn't fit.
Both are exact — same math, same bytes.

Use inside shard_map with the sequence axis sharded over `axis_name`:
    out = ulysses_attention(q, k, v, axis_name="sp")
"""
from __future__ import annotations

import jax

from arbius_tpu.ops.flash import attention as _attend


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                      axis_name: str) -> jax.Array:
    """Exact attention over a sequence sharded on `axis_name`.

    Shapes per shard: q/k/v [B, H, S_local, D] with H % sp == 0.
    Returns [B, H, S_local, D] in q.dtype. Must run inside shard_map
    with `axis_name` in the mesh.
    """
    sp = jax.lax.psum(1, axis_name)
    h = q.shape[1]
    if h % sp:
        raise ValueError(f"ulysses needs heads ({h}) divisible by the "
                         f"sp axis size ({sp})")

    def seq_to_heads(t):
        # [B, H, S/sp, D] → [B, H/sp, S, D]: hand each rank a head group
        # carrying the full sequence
        return jax.lax.all_to_all(t, axis_name, split_axis=1,
                                  concat_axis=2, tiled=True)

    def heads_to_seq(t):
        return jax.lax.all_to_all(t, axis_name, split_axis=2,
                                  concat_axis=1, tiled=True)

    q, k, v = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    # backend-dispatching attention (ops/flash.py): pallas flash kernel on
    # TPU for long sequences — memory stays linear in S, which is the
    # whole point at this strategy's operating range — XLA einsum
    # otherwise; identical bytes either way, already q.dtype
    out = _attend(q, k, v)
    return heads_to_seq(out)
