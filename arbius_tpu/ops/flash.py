"""Pallas flash attention — blockwise softmax attention in VMEM.

Why: the video UNet's spatial attention at zeroscope shape (1024×576 →
latent 128×72 = 9216 tokens) materializes a 9216² f32 score matrix per
head through the XLA einsum path (~340 MB/head-batch) — HBM-bound. The
flash form never materializes scores: K/V stream through VMEM in blocks
while running max/normalizer/accumulator stats (the same online-softmax
math as ops/ring.py, one level down the memory hierarchy).

Kernel layout (pallas_guide.md patterns):
  grid = (batch*heads, Sq/BLOCK_Q); each program owns one Q block in
  VMEM, loops over K/V blocks with fori_loop, f32 accumulators, MXU
  matmuls via jnp.dot(preferred_element_type=f32). Shapes are padded to
  the (8, 128) f32 tile grid; padded K positions are masked with -inf
  before the softmax stats, so padding never changes the math.

`flash_attention` is a drop-in for `sp_attention_reference` ([B, H, S, D]
→ [B, H, S, D]); `interpret=True` runs it on CPU for tests.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

BLOCK_Q = 128
BLOCK_K = 128
NEG_INF = -1e30


def _kernel(q_ref, k_ref, v_ref, o_ref, *, kv_len: int, scale: float):
    q = q_ref[0].astype(jnp.float32)                  # [BLOCK_Q, D]
    n_kv = k_ref.shape[1] // BLOCK_K

    m0 = jnp.full((BLOCK_Q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((BLOCK_Q,), jnp.float32)
    acc0 = jnp.zeros((BLOCK_Q, q.shape[-1]), jnp.float32)

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(j * BLOCK_K, BLOCK_K), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * BLOCK_K, BLOCK_K), :].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        # mask K padding (positions >= kv_len)
        kpos = j * BLOCK_K + jax.lax.broadcasted_iota(jnp.int32,
                                                      (1, BLOCK_K), 1)
        s = jnp.where(kpos < kv_len, s, NEG_INF)
        mb = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - mb[:, None])
        alpha = jnp.exp(m - mb)
        l = l * alpha + p.sum(axis=-1)
        acc = acc * alpha[:, None] + jnp.dot(
            p, v, preferred_element_type=jnp.float32)
        return mb, l, acc

    m, l, acc = jax.lax.fori_loop(0, n_kv, body, (m0, l0, acc0))
    o_ref[0] = (acc / l[:, None]).astype(o_ref.dtype)


def _pad_to(x, axis, mult):
    size = x.shape[axis]
    pad = (-size) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


@functools.partial(jax.jit, static_argnames=("interpret", "pad_d"))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    interpret: bool = False, pad_d: bool = True) -> jax.Array:
    """Exact attention, flash-style. q/k/v: [B, H, S, D] → [B, H, Sq, D].

    `pad_d=False` skips the explicit head-dim pad to 128 lanes and hands
    the native D (40/80/160 at SD-1.5 levels) straight to the kernel —
    Mosaic lane-pads blocks in VMEM internally, so the math is identical,
    but the jnp.pad round-trips through HBM (a 3.2× inflation of Q/K/V
    traffic at D=40) disappear. MXU pass count is the same either way
    (contraction/lane dims ≤128 occupy one pass regardless), so this
    targets HBM bandwidth, not FLOPs — measured per-impl by
    tools/tpu_profile.py before it becomes the default."""
    b, h, sq, d = q.shape
    kv_len = k.shape[2]
    scale = 1.0 / np.sqrt(d)

    d_mult = 128 if pad_d else 1
    qf = _pad_to(_pad_to(q.reshape(b * h, sq, d), 1, BLOCK_Q), 2, d_mult)
    kf = _pad_to(_pad_to(k.reshape(b * h, kv_len, d), 1, BLOCK_K), 2, d_mult)
    vf = _pad_to(_pad_to(v.reshape(b * h, kv_len, d), 1, BLOCK_K), 2, d_mult)
    bh, sq_p, d_p = qf.shape
    kv_p = kf.shape[1]

    out = pl.pallas_call(
        functools.partial(_kernel, kv_len=kv_len, scale=scale),
        grid=(bh, sq_p // BLOCK_Q),
        in_specs=[
            pl.BlockSpec((1, BLOCK_Q, d_p), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, kv_p, d_p), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, kv_p, d_p), lambda i, j: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, BLOCK_Q, d_p), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq_p, d_p), q.dtype),
        interpret=interpret,
    )(qf, kf, vf)
    return out[:, :sq, :d].reshape(b, h, sq, d)


VALID_ATTN_IMPLS = ("auto", "flash", "flash_nopad", "einsum")


def _read_attn_impl() -> str:
    import os

    impl = os.environ.get("ARBIUS_ATTN_IMPL", "auto")
    if impl not in VALID_ATTN_IMPLS:
        # a typo must not silently measure/run a different impl than the
        # label claims — the A/B exists to decide the production dispatch
        raise ValueError(f"ARBIUS_ATTN_IMPL={impl!r} not in "
                         + "|".join(VALID_ATTN_IMPLS))
    return impl


# Pinned ONCE at import. Reading the env var at trace time looked like a
# runtime toggle but wasn't one: jitted callers only re-read it on a
# retrace, so flipping it after a shape bucket compiled silently kept
# the old impl — and a flip that DID land would change reduction order,
# i.e. the golden CIDs' determinism class. The node boots against this
# pinned value (MinerNode._check_attention_impl) and the profiler
# threads its A/B through set_attention_impl(), re-jitting per impl.
_ATTN_IMPL = _read_attn_impl()


def attention_impl() -> str:
    """The attention dispatch pinned for this process."""
    return _ATTN_IMPL


def set_attention_impl(impl: str | None) -> str:
    """Explicitly re-pin the dispatch (A/B measurement only — callers
    own the retrace; tools/tpu_profile.py builds a fresh jit per impl).
    `None` restores the env-pinned import-time value. Returns the
    previous value so callers can restore it."""
    global _ATTN_IMPL

    if impl is None:
        impl = _read_attn_impl()
    if impl not in VALID_ATTN_IMPLS:
        raise ValueError(f"attention impl {impl!r} not in "
                         + "|".join(VALID_ATTN_IMPLS))
    prior, _ATTN_IMPL = _ATTN_IMPL, impl
    return prior


def attention(q: jax.Array, k: jax.Array, v: jax.Array) -> jax.Array:
    """Backend-dispatching exact attention for [B, H, S, D].

    TPU + long sequences → the pallas flash kernel; otherwise the XLA
    einsum path (which XLA already fuses well at short S, and which is
    the only compiled option off-TPU).

    The module-level pinned impl (ARBIUS_ATTN_IMPL at import, or an
    explicit set_attention_impl) overrides the dispatch for on-chip A/B
    measurement (tools/tpu_profile.py drives the FULL UNet step under
    each value): "flash" | "flash_nopad" | "einsum" | "auto" (default).
    All three are exact attention; they differ in reduction order
    (ULP-class output drift), so a fleet pins ONE impl per determinism
    class — changing the production dispatch re-records the platform
    goldens, and a node booting with a non-default impl must prove its
    goldens still hold (node.py boot check).
    """
    from arbius_tpu.ops.ring import sp_attention_reference

    impl = _ATTN_IMPL
    if impl == "einsum":
        return sp_attention_reference(q, k, v)
    on_tpu = jax.default_backend() == "tpu"
    if impl == "flash" and on_tpu:
        return flash_attention(q, k, v)
    if impl == "flash_nopad" and on_tpu:
        return flash_attention(q, k, v, pad_d=False)
    # flash impls requested off-TPU fall through here: einsum is the only
    # compiled option off-TPU, so a fleet pinning "flash" still boots on
    # CPU dev hosts (the profiler only labels non-auto impls on TPU)
    if on_tpu and q.shape[2] >= 1024:
        return flash_attention(q, k, v)
    return sp_attention_reference(q, k, v)
