"""arbius-tpu CLI — ops tooling (L4').

Parity targets from the reference's hardhat task suite
(`contract/tasks/index.ts:12-465`) reinterpreted for the in-process stack:

  wallet-gen        gen-wallet: new private key + address
  templates         list bundled model templates
  template <name>   inspect a template's schema
  validate-config   parse + schema-check a MiningConfig.json
  cid <file>        L0 CID of a file's bytes (generateIPFSCID parity)
  commitment        generateCommitment(address, taskid, cid)
  emission          targetTs/diffMul/reward table for a time/supply
  demo-mine         end-to-end local mine: fake chain + tiny SD-1.5,
                    task → solve → commit → reveal → claim (the §3.2
                    money path, observable in one command)

Run: python -m arbius_tpu.cli <command> [...args]
"""
from __future__ import annotations

import argparse
import json
import sys


def cmd_wallet_gen(args) -> int:
    from arbius_tpu.chain.wallet import Wallet

    w = Wallet.generate()
    print(json.dumps({"address": w.address,
                      "privateKey": "0x" + w.private_key.hex()}))
    return 0


def cmd_templates(args) -> int:
    from arbius_tpu.templates.engine import load_template, template_names

    for name in template_names():
        t = load_template(name)
        print(f"{name}: {t.title} -> "
              f"{', '.join(o.filename for o in t.outputs)}")
    return 0


def cmd_template(args) -> int:
    from arbius_tpu.templates.engine import load_template

    t = load_template(args.name)
    print(json.dumps({
        "title": t.title,
        "inputs": [{"variable": f.variable, "type": f.type,
                    "required": f.required, "default": f.default}
                   for f in t.inputs],
        "outputs": [{"filename": o.filename, "type": o.type}
                    for o in t.outputs],
    }, indent=2))
    return 0


def cmd_validate_config(args) -> int:
    from arbius_tpu.node.config import ConfigError, load_config

    try:
        cfg = load_config(open(args.path).read())
    except (OSError, json.JSONDecodeError, ConfigError) as e:
        print(f"INVALID: {e}", file=sys.stderr)
        return 1
    print(f"OK: {len(cfg.models)} model(s), automine="
          f"{cfg.automine.enabled}, db={cfg.db_path}")
    return 0


def cmd_cid(args) -> int:
    from arbius_tpu.l0.cid import cid_base58, cid_hex, dag_of_file

    data = open(args.path, "rb").read()
    node = dag_of_file(data)
    print(json.dumps({"cid": cid_base58(node.cid),
                      "hex": cid_hex(node.cid), "size": len(data)}))
    return 0


def cmd_commitment(args) -> int:
    from arbius_tpu.l0.commitment import generate_commitment_hex

    print(generate_commitment_hex(args.address, args.taskid, args.cid))
    return 0


def cmd_emission(args) -> int:
    from arbius_tpu.chain.fixedpoint import WAD, diff_mul, reward, target_ts

    t = args.t
    ts = int(args.supply * WAD)
    out = {"t": t, "targetTs": target_ts(t) / WAD}
    if ts > 0 and t > 0:
        out["diffMul"] = diff_mul(t, ts) / WAD
        out["reward"] = reward(t, ts) / WAD
    print(json.dumps(out))
    return 0


def cmd_demo_mine(args) -> int:
    import os

    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        # honor a deliberate CPU run: the deployment's axon plugin
        # monkeypatches backend lookup and would dial the remote-TPU
        # tunnel regardless of the env var (hanging when it's unhealthy)
        from arbius_tpu.utils import force_cpu_devices

        force_cpu_devices(1, strict=False)
    from arbius_tpu.chain import Engine, TokenLedger, WAD
    from arbius_tpu.models.sd15 import ByteTokenizer, SD15Config, SD15Pipeline
    from arbius_tpu.node import (
        LocalChain,
        MinerNode,
        MiningConfig,
        ModelConfig,
        ModelRegistry,
        RegisteredModel,
        SD15Runner,
    )
    from arbius_tpu.templates.engine import load_template

    miner, user = "0x" + "aa" * 20, "0x" + "01" * 20
    tok = TokenLedger()
    eng = Engine(tok, start_time=0)
    tok.mint(Engine.ADDRESS, 600_000 * WAD)
    for a in (miner, user):
        tok.mint(a, 1000 * WAD)
        tok.approve(a, Engine.ADDRESS, 10**30)
    mid_b = eng.register_model(user, user, 0, b'{"meta":{"title":"demo"}}')
    print(f"model registered: 0x{mid_b.hex()}")

    pipe = SD15Pipeline(SD15Config.tiny(),
                        tokenizer=ByteTokenizer(max_length=16, bos_id=257,
                                                eos_id=258))
    params = pipe.init_params(seed=0)
    reg = ModelRegistry()
    reg.register(RegisteredModel(id="0x" + mid_b.hex(),
                                 template=load_template("anythingv3"),
                                 runner=SD15Runner(pipe, params)))
    chain = LocalChain(eng, miner)
    chain.validator_deposit(100 * WAD)
    node = MinerNode(chain, MiningConfig(
        models=(ModelConfig(id="0x" + mid_b.hex(),
                            template="anythingv3"),)), reg)
    node.boot()

    tid = eng.submit_task(user, 0, user, mid_b, 0, json.dumps({
        "prompt": args.prompt, "negative_prompt": "", "width": 128,
        "height": 128, "num_inference_steps": 2,
        "scheduler": "DDIM"}).encode())
    print(f"task submitted: 0x{tid.hex()}")
    while node.tick():
        pass
    sol = eng.solutions[tid]
    print(f"solution by {sol.validator}: cid 0x{sol.cid.hex()}")
    eng.advance_time(2200)
    while node.tick():
        pass
    print(f"claimed: {node.metrics.solutions_claimed == 1}")
    return 0


def cmd_devnet(args) -> int:
    """Local chain world (setup_local.sh parity): funded devnet over HTTP
    with a registered model, ready for `node-run` against it."""
    from arbius_tpu.chain import Engine, TokenLedger, WAD
    from arbius_tpu.chain.devnet import DevnetNode

    tok = TokenLedger()
    eng = Engine(tok, start_time=args.start_time)
    tok.mint(Engine.ADDRESS, 600_000 * WAD)
    node = DevnetNode(eng, chain_id=args.chain_id)
    for addr in args.fund or []:
        tok.mint(addr.lower(), 1000 * WAD)
        print(f"funded {addr} with 1000 AIUS")
    mid = eng.register_model("0x" + "01" * 20, "0x" + "01" * 20, 0,
                             b'{"meta":{"title":"devnet"}}')
    print(json.dumps({
        "rpc_url": f"http://{args.host}:{args.port}",
        "engine_address": node.engine_address,
        "token_address": node.token_address,
        "chain_id": args.chain_id,
        "model_id": "0x" + mid.hex(),
    }, indent=2))
    server = node.serve(args.host, args.port)
    print(f"devnet listening on {args.host}:{args.port} (ctrl-c to stop)",
          file=sys.stderr)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        server.shutdown()
    return 0


def cmd_node_run(args) -> int:
    """Run the miner against a real JSON-RPC endpoint (start.ts parity)."""
    from arbius_tpu.chain.rpc_client import EngineRpcClient, JsonRpcTransport
    from arbius_tpu.chain.wallet import Wallet
    from arbius_tpu.node import MinerNode, load_config
    from arbius_tpu.node.config import load_deployment
    from arbius_tpu.node.factory import build_registry
    from arbius_tpu.node.rpc_chain import RpcChain

    cfg = load_config(open(args.config).read())
    dep = load_deployment(open(args.deployment).read())
    key = args.key or open(args.key_file).read().strip()
    wallet = Wallet.from_hex(key)
    client = EngineRpcClient(JsonRpcTransport(dep.rpc_url),
                             dep.engine_address, wallet,
                             chain_id=dep.chain_id)
    chain = RpcChain(client, dep.token_address, start_block=dep.start_block)
    store = None
    if cfg.store_dir:
        from arbius_tpu.node.store import ContentStore

        store = ContentStore(cfg.store_dir)
    registry = build_registry(
        cfg, resolve_file=store.get_file if store else None)
    node = MinerNode(chain, cfg, registry, store=store)
    node.boot(skip_self_test=args.skip_self_test)
    rpc = None
    if cfg.rpc_port is not None:
        from arbius_tpu.node.rpc import ControlRPC

        rpc = ControlRPC(node, port=cfg.rpc_port)
        rpc.start()
        print(f"control RPC + explorer on 127.0.0.1:{rpc.port}",
              file=sys.stderr)
    print(f"mining as {wallet.address} against {dep.rpc_url}",
          file=sys.stderr)
    if args.ticks > 0:
        for _ in range(args.ticks):
            node.tick()
        return 0
    node.run()
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="arbius-tpu", description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)
    sub.add_parser("wallet-gen").set_defaults(fn=cmd_wallet_gen)
    sub.add_parser("templates").set_defaults(fn=cmd_templates)
    sp = sub.add_parser("template")
    sp.add_argument("name")
    sp.set_defaults(fn=cmd_template)
    sp = sub.add_parser("validate-config")
    sp.add_argument("path")
    sp.set_defaults(fn=cmd_validate_config)
    sp = sub.add_parser("cid")
    sp.add_argument("path")
    sp.set_defaults(fn=cmd_cid)
    sp = sub.add_parser("commitment")
    sp.add_argument("address")
    sp.add_argument("taskid")
    sp.add_argument("cid")
    sp.set_defaults(fn=cmd_commitment)
    sp = sub.add_parser("emission")
    sp.add_argument("--t", type=int, default=31536000)
    sp.add_argument("--supply", type=float, default=100000.0)
    sp.set_defaults(fn=cmd_emission)
    sp = sub.add_parser("demo-mine")
    sp.add_argument("--prompt", default="arbius test cat")
    sp.set_defaults(fn=cmd_demo_mine)
    sp = sub.add_parser("devnet")
    sp.add_argument("--host", default="127.0.0.1")
    sp.add_argument("--port", type=int, default=8545)
    sp.add_argument("--chain-id", type=int, default=31337)
    sp.add_argument("--start-time", type=int, default=1000)
    sp.add_argument("--fund", action="append",
                    help="address to mint 1000 AIUS to (repeatable)")
    sp.set_defaults(fn=cmd_devnet)
    sp = sub.add_parser("node-run")
    sp.add_argument("config", help="MiningConfig.json path")
    sp.add_argument("--deployment", required=True,
                    help="deployment constants json")
    keyg = sp.add_mutually_exclusive_group(required=True)
    keyg.add_argument("--key", help="0x private key")
    keyg.add_argument("--key-file", help="file holding the private key")
    sp.add_argument("--skip-self-test", action="store_true")
    sp.add_argument("--ticks", type=int, default=0,
                    help="run N ticks then exit (0 = run forever)")
    sp.set_defaults(fn=cmd_node_run)
    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
