"""arbius-tpu CLI — ops tooling (L4').

Parity targets from the reference's hardhat task suite
(`contract/tasks/index.ts:12-465`) reinterpreted for the in-process stack:

  wallet-gen        gen-wallet: new private key + address
  templates         list bundled model templates
  template <name>   inspect a template's schema
  validate-config   parse + schema-check a MiningConfig.json
  cid <file>        L0 CID of a file's bytes (generateIPFSCID parity)
  commitment        generateCommitment(address, taskid, cid)
  emission          targetTs/diffMul/reward table for a time/supply
  demo-mine         end-to-end local mine: fake chain + tiny SD-1.5,
                    task → solve → commit → reveal → claim (the §3.2
                    money path, observable in one command)
  devnet            serve a funded in-process chain over JSON-RPC
  node-run          mine against a JSON-RPC endpoint (start.ts parity)

Ops verbs against an endpoint (--deployment + --key, signed txs):
  model-register    model:register — template → on-chain model id
  validator-stake   validator:stake — approve + deposit to minimum
  task-submit       submitTask w/ hydrate validation + fee approval
  task-status       task/solution view (task/[taskid] page data)
  claim             mining:claimSolution
  balance           mining:balance
  transfer          mining:transfer — signed ERC20 transfer
  task-retract      retractTask — owner reclaims unsolved task fee
  signal-support    mining:signalSupport — validator model signal
  decode-tx         decode a raw signed EIP-1559 transaction (offline)
  treasury-withdraw treasury:withdrawAccruedFees — sweep protocol fees
  timetravel        mine/timetravel — devnet blocks/seconds
  governance …      delegate/propose/vote/queue/execute/cancel/proposal
  convert-checkpoint published weights → factory orbax tree
  record-golden     boot self-test golden CID on this platform

Run: python -m arbius_tpu.cli <command> [...args]
"""
from __future__ import annotations

import argparse
import json
import re
import sys


def _wad(amount: str) -> int:
    """Exact decimal AIUS string → wei wad (parseEther semantics). Float
    would drift off-by-wei for most decimal inputs — e.g. int(1.1*10**18)
    is not 11*10**17 — and a drifted fee reverts submitTask or skews the
    registered model id."""
    from decimal import Decimal, InvalidOperation

    try:
        wad = Decimal(amount) * 10**18
    except InvalidOperation:
        raise SystemExit(f"bad AIUS amount {amount!r}")
    if not wad.is_finite() or wad < 0:
        raise SystemExit(f"AIUS amount must be finite and >= 0, "
                         f"got {amount!r}")
    if wad != int(wad):
        raise SystemExit(f"{amount!r} has more than 18 decimal places")
    return int(wad)


def _abi_cli_value(typ: str, arg: str):
    """CLI string literal → abi_encode-ready value for one static type."""
    if typ.startswith(("uint", "int")):
        try:
            return int(arg, 0)
        except ValueError:
            raise SystemExit(f"bad integer literal {arg!r}")
    if typ == "bool":
        low = arg.lower()
        if low in ("true", "1"):
            return 1
        if low in ("false", "0"):
            return 0
        raise SystemExit(f"bad bool literal {arg!r}")
    return arg


def cmd_wallet_gen(args) -> int:
    from arbius_tpu.chain.wallet import Wallet

    w = Wallet.generate()
    print(json.dumps({"address": w.address,
                      "privateKey": "0x" + w.private_key.hex()}))
    return 0


def cmd_templates(args) -> int:
    from arbius_tpu.templates.engine import load_template, template_names

    for name in template_names():
        t = load_template(name)
        print(f"{name}: {t.title} -> "
              f"{', '.join(o.filename for o in t.outputs)}")
    return 0


def cmd_template(args) -> int:
    from arbius_tpu.templates.engine import load_template

    t = load_template(args.name)
    print(json.dumps({
        "title": t.title,
        "inputs": [{"variable": f.variable, "type": f.type,
                    "required": f.required, "default": f.default}
                   for f in t.inputs],
        "outputs": [{"filename": o.filename, "type": o.type}
                    for o in t.outputs],
    }, indent=2))
    return 0


def cmd_validate_config(args) -> int:
    from arbius_tpu.node.config import ConfigError, load_config

    try:
        cfg = load_config(open(args.path).read())
    except (OSError, json.JSONDecodeError, ConfigError) as e:
        print(f"INVALID: {e}", file=sys.stderr)
        return 1
    print(f"OK: {len(cfg.models)} model(s), automine="
          f"{cfg.automine.enabled}, db={cfg.db_path}")
    return 0


def cmd_cid(args) -> int:
    from arbius_tpu.l0.cid import cid_base58, cid_hex, dag_of_file

    data = open(args.path, "rb").read()
    node = dag_of_file(data)
    print(json.dumps({"cid": cid_base58(node.cid),
                      "hex": cid_hex(node.cid), "size": len(data)}))
    return 0


def cmd_commitment(args) -> int:
    from arbius_tpu.l0.commitment import generate_commitment_hex

    print(generate_commitment_hex(args.address, args.taskid, args.cid))
    return 0


def cmd_emission(args) -> int:
    from arbius_tpu.chain.fixedpoint import WAD, diff_mul, reward, target_ts

    t = args.t
    ts = _wad(args.supply)
    out = {"t": t, "targetTs": target_ts(t) / WAD}
    if ts > 0 and t > 0:
        out["diffMul"] = diff_mul(t, ts) / WAD
        out["reward"] = reward(t, ts) / WAD
    print(json.dumps(out, sort_keys=True))
    return 0


def _maybe_force_cpu() -> None:
    """Honor a deliberate JAX_PLATFORMS=cpu run: the deployment's axon
    plugin monkeypatches backend lookup and would dial the remote-TPU
    tunnel regardless of the env var (hanging when it's unhealthy)."""
    import os

    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        from arbius_tpu.utils import force_cpu_devices

        force_cpu_devices(1, strict=False)


def cmd_demo_mine(args) -> int:
    _maybe_force_cpu()
    from arbius_tpu.chain import Engine, TokenLedger, WAD
    from arbius_tpu.models.sd15 import ByteTokenizer, SD15Config, SD15Pipeline
    from arbius_tpu.node import (
        LocalChain,
        MinerNode,
        MiningConfig,
        ModelConfig,
        ModelRegistry,
        RegisteredModel,
        SD15Runner,
    )
    from arbius_tpu.templates.engine import load_template

    miner, user = "0x" + "aa" * 20, "0x" + "01" * 20
    tok = TokenLedger()
    eng = Engine(tok, start_time=0)
    tok.mint(Engine.ADDRESS, 600_000 * WAD)
    for a in (miner, user):
        tok.mint(a, 1000 * WAD)
        tok.approve(a, Engine.ADDRESS, 10**30)
    mid_b = eng.register_model(user, user, 0, b'{"meta":{"title":"demo"}}')
    print(f"model registered: 0x{mid_b.hex()}")

    pipe = SD15Pipeline(SD15Config.tiny(),
                        tokenizer=ByteTokenizer(max_length=16, bos_id=257,
                                                eos_id=258))
    params = pipe.init_params(seed=0)
    reg = ModelRegistry()
    reg.register(RegisteredModel(id="0x" + mid_b.hex(),
                                 template=load_template("anythingv3"),
                                 runner=SD15Runner(pipe, params)))
    chain = LocalChain(eng, miner)
    chain.validator_deposit(100 * WAD)
    node = MinerNode(chain, MiningConfig(
        models=(ModelConfig(id="0x" + mid_b.hex(),
                            template="anythingv3"),)), reg)
    node.boot()

    tid = eng.submit_task(user, 0, user, mid_b, 0, json.dumps({
        "prompt": args.prompt, "negative_prompt": "", "width": 128,
        "height": 128, "num_inference_steps": 2,
        "scheduler": "DDIM"}).encode())
    print(f"task submitted: 0x{tid.hex()}")
    while node.tick():
        pass
    sol = eng.solutions[tid]
    print(f"solution by {sol.validator}: cid 0x{sol.cid.hex()}")
    eng.advance_time(2200)
    while node.tick():
        pass
    print(f"claimed: {node.metrics.solutions_claimed == 1}")
    return 0


def _load_torch_state_dict(path: str) -> dict:
    """Published checkpoint file → flat {key: numpy} dict.

    Accepts .safetensors or torch pickle (.bin/.pt/.pth, weights_only);
    unwraps torch-hub style {'state_dict': ...} envelopes; bf16/fp16
    tensors are upcast to f32 on BOTH paths (numpy has no bf16, and the
    two distribution formats of the same weights must convert to the
    same artifact)."""
    import torch

    if path.endswith(".safetensors"):
        # torch-side loader: safetensors.numpy cannot represent bf16
        from safetensors.torch import load_file

        obj = load_file(path)
    else:
        obj = torch.load(path, map_location="cpu", weights_only=True)
        if isinstance(obj, dict) and "state_dict" in obj \
                and isinstance(obj["state_dict"], dict):
            obj = obj["state_dict"]
    out = {}
    for k, v in obj.items():
        if isinstance(v, torch.Tensor):
            v = v.detach()
            if v.is_floating_point():
                v = v.to(torch.float32)
            out[k] = v.numpy()
        else:
            out[k] = v
    return out


def cmd_convert_checkpoint(args) -> int:
    """Offline converter: published torch/safetensors checkpoints → the
    orbax tree the node factory loads (`ModelConfig.checkpoint`). The
    template tree comes from jax.eval_shape, so no params are ever
    materialized — conversion is pure host-side numpy."""
    import jax

    from arbius_tpu.utils import force_cpu_devices, save_params

    # host-side tool; never dial the TPU tunnel
    force_cpu_devices(1, strict=False)
    fam = args.family

    def need(flag: str) -> dict:
        v = getattr(args, flag)
        if not v:
            raise SystemExit(f"--{flag} is required for family {fam}")
        return _load_torch_state_dict(v)

    if fam == "anythingv3":
        from arbius_tpu.models.sd15 import ByteTokenizer, SD15Config, SD15Pipeline
        from arbius_tpu.models.sd15.convert import (
            convert_sd15_text,
            convert_sd15_unet,
            convert_sd15_vae,
        )

        cfg = SD15Config()
        pipe = SD15Pipeline(cfg, tokenizer=ByteTokenizer())
        tmpl = jax.eval_shape(lambda: pipe.init_params(seed=0))
        params = {
            "unet": convert_sd15_unet(need("unet"), tmpl["unet"]),
            "vae": convert_sd15_vae(need("vae"), tmpl["vae"]),
            "text": convert_sd15_text(need("text"), tmpl["text"],
                                      cfg.text.heads,
                                      cfg.text.width // cfg.text.heads),
        }
    elif fam in ("zeroscopev2xl", "damo"):
        from arbius_tpu.models.sd15 import ByteTokenizer
        from arbius_tpu.models.video import (
            Text2VideoConfig,
            Text2VideoPipeline,
            convert_unet3d,
        )
        from arbius_tpu.models.video.convert import (
            convert_video_text,
            convert_video_vae,
        )

        cfg = Text2VideoConfig()
        pipe = Text2VideoPipeline(cfg, tokenizer=ByteTokenizer())
        tmpl = jax.eval_shape(lambda: pipe.init_params(seed=0))
        params = {
            "unet": convert_unet3d(need("unet"), tmpl["unet"]),
            "vae": convert_video_vae(need("vae"), tmpl["vae"]),
            "text": convert_video_text(need("text"), tmpl["text"],
                                       cfg.text.heads,
                                       cfg.text.width // cfg.text.heads),
        }
    elif fam == "kandinsky2":
        from arbius_tpu.models.kandinsky2 import (
            Kandinsky2Config,
            Kandinsky2Pipeline,
            convert_kandinsky2_decoder,
            convert_kandinsky2_movq,
            convert_kandinsky2_prior,
            convert_kandinsky2_text_projection,
        )
        from arbius_tpu.models.sd15 import ByteTokenizer
        from arbius_tpu.models.sd15.convert import convert_sd15_text

        cfg = Kandinsky2Config()
        pipe = Kandinsky2Pipeline(cfg, tokenizer=ByteTokenizer())
        tmpl = jax.eval_shape(lambda: pipe.init_params(seed=0))
        prior_tree, stats = convert_kandinsky2_prior(need("prior"),
                                                     tmpl["prior"])
        if tuple(stats.shape) != tuple(tmpl["prior_stats"].shape):
            raise SystemExit(
                f"prior clip stats shape {tuple(stats.shape)} != configured "
                f"{tuple(tmpl['prior_stats'].shape)} — wrong prior variant")
        text_sd = need("text")
        params = {
            "prior": prior_tree,
            "prior_stats": stats,
            "decoder": convert_kandinsky2_decoder(need("decoder"),
                                                  tmpl["decoder"]),
            "movq": convert_kandinsky2_movq(need("movq"), tmpl["movq"]),
            "text": convert_sd15_text(text_sd, tmpl["text"],
                                      cfg.text.heads,
                                      cfg.text.width // cfg.text.heads),
            "text_proj": convert_kandinsky2_text_projection(
                text_sd, tmpl["text_proj"]),
        }
    elif fam == "robust_video_matting":
        from arbius_tpu.models.rvm import RVMPipeline, RVMPipelineConfig, convert_rvm

        pipe = RVMPipeline(RVMPipelineConfig())
        tmpl = jax.eval_shape(lambda: pipe.init_params(seed=0))
        params = convert_rvm(need("weights"), tmpl)
    else:
        raise SystemExit(f"unknown family {fam!r}")

    save_params(args.out, params)
    n = sum(x.size for x in jax.tree_util.tree_leaves(params))
    print(json.dumps({"family": fam, "out": args.out,
                      "param_count": int(n)}))
    return 0


def cmd_record_golden(args) -> int:
    """Compute a model's golden CID — the boot self-test vector
    (`MinerNode.boot`) that pins the fleet's deterministic build, the TPU
    analogue of the reference's hard-coded kandinsky CID
    (miner/src/index.ts:984-1001, input {prompt:"arbius test cat",
    seed:1337}). Run on the SAME platform the fleet mines on (the TPU
    chip); the printed snippet drops into ModelConfig.golden."""
    import time

    _maybe_force_cpu()
    import jax

    from arbius_tpu.node.config import MiningConfig, ModelConfig
    from arbius_tpu.node.factory import build_registry
    from arbius_tpu.node.solver import solve_cid
    from arbius_tpu.templates.engine import hydrate_input

    raw = (json.loads(args.input) if args.input
           else {"prompt": "arbius test cat", "negative_prompt": ""})
    resolve_file = None
    if args.template == "robust_video_matting" and not args.probe_video:
        raise SystemExit(
            "robust_video_matting's input is a video FILE: pass "
            "--probe-video TxHxW to pin the deterministic in-repo probe "
            "clip as input_video (codecs/probe.py)")
    if args.probe_video:
        # file-input templates: pin the deterministic in-repo probe clip
        # by CID and resolve it in-memory — the recorded golden's
        # input_video reproduces bit-identically on any platform
        from arbius_tpu.node.factory import probe_golden_input

        resolve_file, probe_raw = probe_golden_input(args.probe_video)
        raw.pop("prompt", None)
        raw.pop("negative_prompt", None)
        raw.update(probe_raw)
    mid = args.model_id or "0x" + "00" * 32
    mc = ModelConfig(
        id=mid, template=args.template, tiny=args.tiny,
        checkpoint=args.checkpoint,
        weights_dtype=args.weights_dtype,
        tokenizer="clip_bpe" if args.vocab else "byte",
        vocab_path=args.vocab, merges_path=args.merges)
    m = build_registry(MiningConfig(models=(mc,)),
                       resolve_file=resolve_file).get(mid)
    hydrated = hydrate_input(dict(raw), m.template)
    platform = jax.devices()[0].platform
    # detlint: allow[DET101] operator-facing elapsed_s; never hashed
    t0 = time.perf_counter()
    cid, _files = solve_cid(m, hydrated, args.seed)
    golden = {"input": raw, "seed": args.seed, "cid": cid}
    if args.probe_video:
        # regeneration recipe IN the vector: a node whose golden carries
        # probe_video synthesizes the clip at boot (factory.probe_resolver)
        # — the artifact is reproducible without any pre-pinned store
        golden["probe_video"] = args.probe_video
    print(json.dumps({
        "template": args.template, "platform": platform,
        "tiny": args.tiny, "weights_dtype": args.weights_dtype,
        # detlint: allow[DET101] operator-facing elapsed_s; never hashed
        "elapsed_s": round(time.perf_counter() - t0, 1),
        "golden": golden,
    }))
    return 0


def cmd_devnet(args) -> int:
    """Local chain world (setup_local.sh parity): funded devnet over HTTP
    with a registered model, ready for `node-run` against it."""
    from arbius_tpu.chain import Engine, TokenLedger, WAD
    from arbius_tpu.chain.devnet import DevnetNode

    tok = TokenLedger()
    owner = args.owner
    if owner and not re.fullmatch(r"0x[0-9a-fA-F]{40}", owner):
        raise SystemExit(f"bad owner address {owner!r}")
    eng = Engine(tok, start_time=args.start_time, owner=owner)
    tok.mint(Engine.ADDRESS, 600_000 * WAD)
    node = DevnetNode(eng, chain_id=args.chain_id)
    for addr in args.fund or []:
        tok.mint(addr.lower(), 1000 * WAD)
        print(f"funded {addr} with 1000 AIUS")
    if owner:
        print(f"engine owner/pauser: {owner}")
    mid = eng.register_model("0x" + "01" * 20, "0x" + "01" * 20, 0,
                             b'{"meta":{"title":"devnet"}}')
    print(json.dumps({
        "rpc_url": f"http://{args.host}:{args.port}",
        "engine_address": node.engine_address,
        "token_address": node.token_address,
        "governor_address": node.governor_address,
        "chain_id": args.chain_id,
        "model_id": "0x" + mid.hex(),
    }, indent=2))
    server = node.serve(args.host, args.port)
    print(f"devnet listening on {args.host}:{args.port} (ctrl-c to stop)",
          file=sys.stderr)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        server.shutdown()
    return 0


def _rpc_client(args):
    """Build the signed-tx client every ops verb composes
    (contract/tasks/index.ts boilerplate: provider + wallet + contracts)."""
    from arbius_tpu.chain.rpc_client import EngineRpcClient, JsonRpcTransport
    from arbius_tpu.chain.wallet import Wallet
    from arbius_tpu.node.config import load_deployment

    dep = load_deployment(open(args.deployment).read())
    key = args.key or (open(args.key_file).read().strip()
                       if args.key_file else None)
    # read-only verbs may omit the key; views don't sign
    wallet = Wallet.from_hex(key) if key else Wallet.generate()
    client = EngineRpcClient(JsonRpcTransport(dep.rpc_url),
                             dep.engine_address, wallet,
                             chain_id=dep.chain_id)
    return client, dep


def _governor_address(dep) -> str:
    if dep.governor_address:
        return dep.governor_address
    from arbius_tpu.chain.devnet import GOVERNOR_ADDRESS

    return GOVERNOR_ADDRESS


def cmd_model_register(args) -> int:
    """model:register parity (contract/tasks/index.ts:106-143): register a
    template as an on-chain model and print the derived model id."""
    from arbius_tpu.l0.abi import abi_encode
    from arbius_tpu.l0.cid import cid_onchain
    from arbius_tpu.l0.keccak import keccak256
    from arbius_tpu.templates.engine import load_template, load_template_bytes

    client, dep = _rpc_client(args)
    if args.template_file:
        template_bytes = open(args.template_file, "rb").read()
    else:
        load_template(args.template)  # validate it parses
        template_bytes = load_template_bytes(args.template)
    fee = _wad(args.fee)
    addr = args.addr or client.wallet.address
    txhash = client.send("registerModel", [addr, fee, template_bytes])
    # id = keccak(abi.encode(sender, addr, fee, cid)) — EngineV1.sol:421-426
    cid = cid_onchain(template_bytes)
    mid = keccak256(abi_encode(["address", "address", "uint256", "bytes"],
                               [client.wallet.address, addr, fee, cid]))
    print(json.dumps({"txhash": txhash, "model_id": "0x" + mid.hex(),
                      "template_cid": "0x" + cid.hex()}))
    return 0


def cmd_validator_stake(args) -> int:
    """validator:stake parity (contract/tasks/index.ts:145-157):
    approve-then-deposit up to the validator minimum (with headroom)."""
    from arbius_tpu.node.rpc_chain import RpcChain

    client, dep = _rpc_client(args)
    chain = RpcChain(client, dep.token_address)
    if args.amount is not None:
        amount = _wad(args.amount)
    else:
        # reference default: minimum * 1.1 headroom against emission drift
        amount = chain.get_validator_minimum() * 11 // 10
    chain.validator_deposit(amount)
    staked = chain.validator_staked()
    print(json.dumps({"staked_wad": str(staked),
                      "staked": staked / 10**18}))
    return 0


def cmd_task_submit(args) -> int:
    """submitTask from the command line (the dapp's generate page /
    Example/SubmitTask.sol path): hydrate input against the template,
    submit, and print the taskid recovered from the TaskSubmitted log."""
    from arbius_tpu.templates.engine import hydrate_input, load_template

    client, dep = _rpc_client(args)
    raw = json.loads(args.input) if args.input else {}
    if args.template:
        hydrate_input(dict(raw), load_template(args.template))  # validate
    fee = _wad(args.fee)
    if fee:
        # self-heal the fee allowance like the dapp's approve-then-submit
        from arbius_tpu.node.rpc_chain import RpcChain

        RpcChain(client, dep.token_address).ensure_fee_allowance(fee)
    # canonical form (sorted keys, tight separators) — the same bytes the
    # node's POST /api/task path would submit for this input
    input_bytes = json.dumps(raw, separators=(",", ":"),
                             sort_keys=True).encode()
    if args.sign_only:
        # user-wallet dapp path (generate.tsx wagmi parity): sign here,
        # let the node forward the bytes via POST /api/tx/raw. Nonce/gas
        # are read from the endpoint; nothing is sent. (A nonzero --fee
        # already sent its approve above — allowance is a separate tx.)
        raw = client.sign_engine_call("submitTask", [
            args.version, client.wallet.address, args.model, fee,
            input_bytes])
        print(json.dumps({"raw": "0x" + raw.hex(),
                          "from": client.wallet.address}))
        return 0
    from_block = client.block_number()
    txhash = client.send("submitTask", [
        args.version, client.wallet.address, args.model, fee, input_bytes])
    # the id is assigned on-chain (hash chains prevhash) — recover it from
    # our TaskSubmitted log, like the dapp does from the receipt
    taskid = None
    me = client.wallet.address.lower()
    for lg in client.get_logs("TaskSubmitted", from_block,
                              client.block_number()):
        sender = "0x" + lg["topics"][3][-40:]
        if sender.lower() == me:
            taskid = lg["topics"][1]
    print(json.dumps({"txhash": txhash, "taskid": taskid}))
    return 0


def cmd_task_status(args) -> int:
    """Task / solution view (task/[taskid] page data), through the same
    RpcChain decode the node mines with (incl. its missing-key sentinels)."""
    from arbius_tpu.node.rpc_chain import RpcChain

    client, dep = _rpc_client(args)
    chain = RpcChain(client, dep.token_address)
    task = chain.get_task(args.taskid)
    if task is None:
        print(json.dumps({"taskid": args.taskid, "error": "task not found"}))
        return 1
    sol = chain.get_solution(args.taskid)
    out = {
        "taskid": args.taskid,
        "model": "0x" + task.model.hex(), "fee": str(task.fee),
        "owner": task.owner, "blocktime": task.blocktime,
        "version": task.version, "input_cid": "0x" + task.cid.hex(),
        "solution": None,
    }
    if sol is not None:
        out["solution"] = {"validator": sol.validator,
                           "blocktime": sol.blocktime,
                           "claimed": sol.claimed,
                           "cid": "0x" + sol.cid.hex()}
    print(json.dumps(out, indent=2, sort_keys=True))
    return 0


def cmd_claim(args) -> int:
    """mining:claimSolution parity (contract/tasks/index.ts:87-94)."""
    client, _ = _rpc_client(args)
    txhash = client.send("claimSolution", [args.taskid])
    print(json.dumps({"txhash": txhash}))
    return 0


def cmd_balance(args) -> int:
    """mining:balance parity (contract/tasks/index.ts:67-74)."""
    client, dep = _rpc_client(args)
    from arbius_tpu.l0.abi import abi_decode

    addr = args.address or client.wallet.address
    bal = abi_decode(["uint256"], client.eth_call_to(
        dep.token_address, "balanceOf(address)", ["address"], [addr]))[0]
    print(json.dumps({"address": addr, "balance_wad": str(bal),
                      "balance": bal / 10**18}))
    return 0


def cmd_transfer(args) -> int:
    """mining:transfer parity (contract/tasks/index.ts:76-87): signed
    ERC20 transfer to an address."""
    client, dep = _rpc_client(args)
    amount = _wad(args.amount)
    txhash = client.send_to(dep.token_address, "transfer(address,uint256)",
                            ["address", "uint256"], [args.to, amount])
    print(json.dumps({"txhash": txhash, "to": args.to,
                      "amount_wad": str(amount)}))
    return 0


def cmd_decode_tx(args) -> int:
    """decode-tx parity (contract/tasks/index.ts:24-34): parse a raw
    signed EIP-1559 transaction and recover its sender."""
    from arbius_tpu.chain.rlp import decode_signed_eip1559

    raw = bytes.fromhex(args.raw.removeprefix("0x"))
    d = decode_signed_eip1559(raw)
    data = d.tx.data or b""
    print(json.dumps({
        "from": d.sender, "to": d.tx.to, "nonce": d.tx.nonce,
        "chain_id": d.tx.chain_id, "value": str(d.tx.value),
        "gas_limit": d.tx.gas_limit,
        "max_fee_per_gas": str(d.tx.max_fee_per_gas),
        "selector": "0x" + data[:4].hex() if len(data) >= 4 else None,
        "data": "0x" + data.hex(),
        "tx_hash": "0x" + d.tx_hash.hex(),
    }))
    return 0


def cmd_treasury_withdraw(args) -> int:
    """treasury:withdrawAccruedFees parity (contract/tasks/index.ts) —
    sweep accrued protocol fees to the treasury address."""
    from arbius_tpu.l0.abi import abi_decode

    client, dep = _rpc_client(args)
    # report the accrued amount OBSERVED BEFORE the send: the tx may
    # still be pending on a real endpoint (no receipt wait here), so a
    # post-send read would race the sweep and other accruals
    accrued = abi_decode(["uint256"], client.eth_call("accruedFees()",
                                                      [], []))[0]
    txhash = client.send("withdrawAccruedFees", [])
    print(json.dumps({"txhash": txhash,
                      "accrued_wad_before": str(accrued)}))
    return 0


def cmd_engine_admin(args) -> int:
    """engine:pause / admin:setVersion parity — owner/pauser-gated direct
    admin calls (EngineV1.sol:266-306; governance reaches the same
    surface via the timelock)."""
    client, dep = _rpc_client(args)
    if args.admin_verb == "pause":
        paused = bool(_abi_cli_value("bool", args.value))
        txhash = client.send_to(dep.engine_address, "setPaused(bool)",
                                ["bool"], [int(paused)])
        print(json.dumps({"txhash": txhash, "paused": paused}))
    elif args.admin_verb == "set-version":
        version = _abi_cli_value("uint256", args.value)
        txhash = client.send_to(dep.engine_address, "setVersion(uint256)",
                                ["uint256"], [version])
        print(json.dumps({"txhash": txhash, "version": version}))
    elif args.admin_verb == "transfer-pauser":
        if not re.fullmatch(r"0x[0-9a-fA-F]{40}", args.value):
            raise SystemExit(f"bad address {args.value!r}")
        txhash = client.send_to(dep.engine_address,
                                "transferPauser(address)", ["address"],
                                [args.value])
        print(json.dumps({"txhash": txhash, "pauser": args.value}))
    else:  # transfer-ownership
        if not re.fullmatch(r"0x[0-9a-fA-F]{40}", args.value):
            raise SystemExit(f"bad address {args.value!r}")
        txhash = client.send_to(dep.engine_address,
                                "transferOwnership(address)", ["address"],
                                [args.value])
        print(json.dumps({"txhash": txhash, "owner": args.value}))
    return 0


def cmd_task_retract(args) -> int:
    """retractTask: the task owner reclaims the fee (minus retraction
    cut) after the wait period, while unsolved (EngineV1.sol:718-736)."""
    client, dep = _rpc_client(args)
    txhash = client.send("retractTask", [args.taskid])
    print(json.dumps({"txhash": txhash, "taskid": args.taskid}))
    return 0


def cmd_signal_support(args) -> int:
    """mining:signalSupport parity (contract/tasks/index.ts:96-103):
    validator-gated, event-only model-support signal for indexers."""
    client, dep = _rpc_client(args)
    support = bool(_abi_cli_value("bool", args.support))
    txhash = client.send("signalSupport", [args.model, int(support)])
    print(json.dumps({"txhash": txhash, "model": args.model,
                      "support": support}))
    return 0


def cmd_timetravel(args) -> int:
    """timetravel/mine parity (contract/tasks/index.ts:36-47) against a
    devnet endpoint: advance chain seconds and/or mine blocks."""
    from arbius_tpu.chain.rpc_client import JsonRpcTransport
    from arbius_tpu.node.config import load_deployment

    dep = load_deployment(open(args.deployment).read())
    t = JsonRpcTransport(dep.rpc_url)
    if args.seconds:
        t.request("evm_increaseTime", [args.seconds])
    if args.blocks:
        t.request("hardhat_mine", [hex(args.blocks)])
    block = int(t.request("eth_blockNumber", []), 16)
    print(json.dumps({"block": block}))
    return 0


def cmd_governance(args) -> int:
    """governance:{delegate,propose,vote,queue,execute,proposal} parity
    (contract/tasks/index.ts:234-380) against the devnet governor."""
    from arbius_tpu.l0.abi import abi_decode
    from arbius_tpu.chain.rpc_client import call_data

    client, dep = _rpc_client(args)
    gov = _governor_address(dep)
    verb = args.gov_verb
    if verb == "delegate":
        to = args.to or client.wallet.address
        txhash = client.send_to(dep.token_address, "delegate(address)",
                                ["address"], [to])
        print(json.dumps({"txhash": txhash, "delegatee": to}))
        return 0
    if verb == "propose":
        # arg types come from the --fn signature itself (the selector is
        # derived from the same string, so they can never disagree)
        m = re.fullmatch(r"[A-Za-z_]\w*\(([^()]*)\)", args.gov_fn)
        if m is None:
            raise SystemExit(f"bad function signature {args.gov_fn!r}")
        types = [t for t in m.group(1).split(",") if t]
        given = args.args or []
        if len(given) != len(types):
            raise SystemExit(f"{args.gov_fn} takes {len(types)} arg(s), "
                             f"got {len(given)}")
        values = [_abi_cli_value(t, a) for t, a in zip(types, given)]
        calldata = call_data(args.gov_fn, types, values)
        target = args.target or client.engine_address
        from_block = client.block_number()
        txhash = client.send_to(
            gov, "propose(address,uint256,bytes,string)",
            ["address", "uint256", "bytes", "string"],
            [target, 0, calldata, args.description])
        # recover the id from our ProposalCreated log rather than
        # re-deriving Governor._proposal_id client-side (same pattern as
        # task-submit: the chain is the source of truth for assigned ids)
        pid = None
        me = client.wallet.address.lower()
        for lg in client.get_logs("ProposalCreated", from_block,
                                  client.block_number()):
            if ("0x" + lg["topics"][2][-40:]).lower() == me:
                pid = lg["topics"][1]
        print(json.dumps({"txhash": txhash, "proposal_id": pid}))
        return 0
    if verb == "vote":
        txhash = client.send_to(gov, "castVote(bytes32,uint8)",
                                ["bytes32", "uint8"],
                                [args.pid, args.support])
        print(json.dumps({"txhash": txhash}))
        return 0
    if verb in ("queue", "execute", "cancel"):
        txhash = client.send_to(gov, f"{verb}(bytes32)", ["bytes32"],
                                [args.pid])
        print(json.dumps({"txhash": txhash}))
        return 0
    if verb == "proposal":
        state = abi_decode(["uint8"], client.eth_call_to(
            gov, "state(bytes32)", ["bytes32"], [args.pid]))[0]
        against, for_, abstain = abi_decode(
            ["uint256", "uint256", "uint256"],
            client.eth_call_to(gov, "proposalVotes(bytes32)", ["bytes32"],
                               [args.pid]))
        from arbius_tpu.chain.governance import ProposalState

        print(json.dumps({
            "proposal_id": args.pid,
            "state": ProposalState(state).name,
            "votes": {"against": str(against), "for": str(for_),
                      "abstain": str(abstain)}}))
        return 0
    raise SystemExit(f"unknown governance verb {verb}")


def cmd_node_run(args) -> int:
    """Run the miner against a real JSON-RPC endpoint (start.ts parity)."""
    _maybe_force_cpu()
    from arbius_tpu.chain.rpc_client import EngineRpcClient, JsonRpcTransport
    from arbius_tpu.chain.wallet import Wallet
    from arbius_tpu.node import MinerNode, load_config
    from arbius_tpu.node.config import load_deployment
    from arbius_tpu.node.factory import build_registry
    from arbius_tpu.node.rpc_chain import RpcChain

    cfg = load_config(open(args.config).read())
    dep = load_deployment(open(args.deployment).read())
    key = args.key or open(args.key_file).read().strip()
    wallet = Wallet.from_hex(key)
    client = EngineRpcClient(JsonRpcTransport(dep.rpc_url),
                             dep.engine_address, wallet,
                             chain_id=dep.chain_id)
    chain = RpcChain(client, dep.token_address, start_block=dep.start_block,
                     validator_address=cfg.delegated_validator)
    store = None
    if cfg.store_dir:
        from arbius_tpu.node.store import ContentStore

        store = ContentStore(cfg.store_dir)
    registry = build_registry(
        cfg, resolve_file=store.get_file if store else None)
    node = MinerNode(chain, cfg, registry, store=store)
    node.boot(skip_self_test=args.skip_self_test)
    rpc = None
    if cfg.rpc_port is not None:
        from arbius_tpu.node.rpc import ControlRPC

        rpc = ControlRPC(node, port=cfg.rpc_port)
        rpc.start()
        print(f"control RPC + explorer on 127.0.0.1:{rpc.port}",
              file=sys.stderr)
    print(f"mining as {wallet.address} against {dep.rpc_url}",
          file=sys.stderr)
    if args.ticks > 0:
        for _ in range(args.ticks):
            node.tick()
        return 0
    node.run()
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="arbius-tpu", description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)
    sub.add_parser("wallet-gen").set_defaults(fn=cmd_wallet_gen)
    sub.add_parser("templates").set_defaults(fn=cmd_templates)
    sp = sub.add_parser("template")
    sp.add_argument("name")
    sp.set_defaults(fn=cmd_template)
    sp = sub.add_parser("validate-config")
    sp.add_argument("path")
    sp.set_defaults(fn=cmd_validate_config)
    sp = sub.add_parser("cid")
    sp.add_argument("path")
    sp.set_defaults(fn=cmd_cid)
    sp = sub.add_parser("commitment")
    sp.add_argument("address")
    sp.add_argument("taskid")
    sp.add_argument("cid")
    sp.set_defaults(fn=cmd_commitment)
    sp = sub.add_parser("emission")
    sp.add_argument("--t", type=int, default=31536000)
    sp.add_argument("--supply", default="100000")
    sp.set_defaults(fn=cmd_emission)
    sp = sub.add_parser("demo-mine")
    sp.add_argument("--prompt", default="arbius test cat")
    sp.set_defaults(fn=cmd_demo_mine)
    sp = sub.add_parser(
        "convert-checkpoint",
        help="published torch/safetensors weights -> factory orbax tree")
    sp.add_argument("--family", required=True,
                    choices=["anythingv3", "kandinsky2", "zeroscopev2xl",
                             "damo", "robust_video_matting"])
    sp.add_argument("--out", required=True, help="orbax output directory")
    for comp in ("unet", "vae", "text", "prior", "decoder", "movq",
                 "weights"):
        sp.add_argument(f"--{comp}", help=f"{comp} checkpoint file")
    sp.set_defaults(fn=cmd_convert_checkpoint)

    sp = sub.add_parser(
        "record-golden",
        help="compute a model's boot self-test golden CID on this platform")
    sp.add_argument("--template", required=True,
                    choices=["anythingv3", "kandinsky2", "zeroscopev2xl",
                             "damo", "robust_video_matting"])
    sp.add_argument("--input", help='hydratable input JSON (default: '
                                    '{"prompt": "arbius test cat", ...})')
    sp.add_argument("--probe-video", metavar="TxHxW",
                    help="file-input templates (robust_video_matting): "
                         "generate the deterministic in-repo probe clip at "
                         "this shape, pin it by CID, and use it as "
                         "input_video — any platform reproduces the same "
                         "input bytes, so the golden stays portable")
    sp.add_argument("--seed", type=int, default=1337)  # index.ts:988
    sp.add_argument("--tiny", action="store_true")
    sp.add_argument("--checkpoint", help="orbax params (default: random init)")
    sp.add_argument("--weights-dtype", dest="weights_dtype",
                    default="float32", choices=["float32", "bfloat16"],
                    help="goldens are dtype-specific: record with the "
                         "fleet's production weights dtype")
    sp.add_argument("--model-id", dest="model_id")
    sp.add_argument("--vocab", help="CLIP BPE vocab.json (selects clip_bpe)")
    sp.add_argument("--merges", help="CLIP BPE merges.txt")
    sp.set_defaults(fn=cmd_record_golden)

    sp = sub.add_parser("devnet")
    sp.add_argument("--host", default="127.0.0.1")
    sp.add_argument("--port", type=int, default=8545)
    sp.add_argument("--chain-id", type=int, default=31337)
    sp.add_argument("--start-time", type=int, default=1000)
    sp.add_argument("--fund", action="append",
                    help="address to mint 1000 AIUS to (repeatable)")
    sp.add_argument("--owner", help="engine owner/pauser address; unset "
                                    "leaves roles unconfigured (direct "
                                    "admin calls denied, governance path "
                                    "unrestricted)")
    sp.set_defaults(fn=cmd_devnet)
    def add_rpc_args(sp, *, key_required=True):
        sp.add_argument("--deployment", required=True,
                        help="deployment constants json")
        keyg = sp.add_mutually_exclusive_group(required=key_required)
        keyg.add_argument("--key", help="0x private key")
        keyg.add_argument("--key-file", help="file holding the private key")

    sp = sub.add_parser("model-register",
                        help="register a template as an on-chain model")
    add_rpc_args(sp)
    tgroup = sp.add_mutually_exclusive_group(required=True)
    tgroup.add_argument("--template", help="bundled template name")
    tgroup.add_argument("--template-file", help="path to a template json")
    sp.add_argument("--fee", default="0", help="model fee (AIUS)")
    sp.add_argument("--addr", help="model payee address (default: wallet)")
    sp.set_defaults(fn=cmd_model_register)

    sp = sub.add_parser("validator-stake",
                        help="approve + deposit validator stake")
    add_rpc_args(sp)
    sp.add_argument("--amount",
                    help="AIUS to deposit (default: minimum * 1.1)")
    sp.set_defaults(fn=cmd_validator_stake)

    sp = sub.add_parser("task-submit", help="submit a task on-chain")
    add_rpc_args(sp)
    sp.add_argument("--model", required=True, help="0x model id")
    sp.add_argument("--input", help="input json object")
    sp.add_argument("--template", help="validate input against template")
    sp.add_argument("--fee", default="0")
    sp.add_argument("--version", type=int, default=0)
    sp.add_argument("--sign-only", action="store_true",
                    help="print the signed raw tx instead of sending it "
                         "(paste into the dapp's raw-tx form / POST "
                         "/api/tx/raw — the user-wallet path)")
    sp.set_defaults(fn=cmd_task_submit)

    sp = sub.add_parser("task-status", help="task/solution view")
    add_rpc_args(sp, key_required=False)
    sp.add_argument("taskid")
    sp.set_defaults(fn=cmd_task_status)

    sp = sub.add_parser("claim", help="claim a solved task's fee+reward")
    add_rpc_args(sp)
    sp.add_argument("taskid")
    sp.set_defaults(fn=cmd_claim)

    sp = sub.add_parser("balance", help="token balance lookup")
    add_rpc_args(sp, key_required=False)
    sp.add_argument("--address", help="default: wallet address")
    sp.set_defaults(fn=cmd_balance)

    sp = sub.add_parser("transfer", help="signed ERC20 transfer")
    add_rpc_args(sp)
    sp.add_argument("--to", required=True)
    sp.add_argument("--amount", required=True, help="AIUS decimal amount")
    sp.set_defaults(fn=cmd_transfer)

    sp = sub.add_parser("decode-tx",
                        help="decode a raw signed EIP-1559 transaction")
    sp.add_argument("raw", help="0x-prefixed raw tx hex")
    sp.set_defaults(fn=cmd_decode_tx)

    sp = sub.add_parser("treasury-withdraw",
                        help="sweep accrued protocol fees to the treasury")
    add_rpc_args(sp)
    sp.set_defaults(fn=cmd_treasury_withdraw)

    sp = sub.add_parser("task-retract",
                        help="owner reclaims an unsolved task's fee")
    add_rpc_args(sp)
    sp.add_argument("taskid", help="0x task id")
    sp.set_defaults(fn=cmd_task_retract)

    sp = sub.add_parser("signal-support",
                        help="validator signals support for a model")
    add_rpc_args(sp)
    sp.add_argument("--model", required=True)
    sp.add_argument("--support", default="true")
    sp.set_defaults(fn=cmd_signal_support)

    sp = sub.add_parser("engine-admin",
                        help="owner/pauser-gated engine admin calls")
    sp.add_argument("admin_verb", choices=["pause", "set-version",
                                           "transfer-pauser",
                                           "transfer-ownership"])
    sp.add_argument("value", help="bool / version / address")
    add_rpc_args(sp)
    sp.set_defaults(fn=cmd_engine_admin)

    sp = sub.add_parser("timetravel",
                        help="advance devnet time and/or mine blocks")
    sp.add_argument("--deployment", required=True)
    sp.add_argument("--seconds", type=int, default=0)
    sp.add_argument("--blocks", type=int, default=0)
    sp.set_defaults(fn=cmd_timetravel)

    sp = sub.add_parser("governance", help="DAO verbs against the governor")
    gsub = sp.add_subparsers(dest="gov_verb", required=True)
    gp = gsub.add_parser("delegate")
    add_rpc_args(gp)
    gp.add_argument("--to", help="delegatee (default: self)")
    gp = gsub.add_parser("propose")
    add_rpc_args(gp)
    gp.add_argument("--target", help="call target (default: engine)")
    gp.add_argument("--fn", dest="gov_fn", required=True,
                    help='e.g. "setSolutionMineableRate(bytes32,uint256)"')
    gp.add_argument("--args", nargs="*", help="call arguments")
    gp.add_argument("--description", required=True)
    for v in ("vote", "queue", "execute", "cancel", "proposal"):
        gp = gsub.add_parser(v)
        add_rpc_args(gp, key_required=(v != "proposal"))
        gp.add_argument("--pid", required=True, help="0x proposal id")
        if v == "vote":
            gp.add_argument("--support", type=int, default=1,
                            help="0=against 1=for 2=abstain")
    sp.set_defaults(fn=cmd_governance)

    sp = sub.add_parser("node-run")
    sp.add_argument("config", help="MiningConfig.json path")
    sp.add_argument("--deployment", required=True,
                    help="deployment constants json")
    keyg = sp.add_mutually_exclusive_group(required=True)
    keyg.add_argument("--key", help="0x private key")
    keyg.add_argument("--key-file", help="file holding the private key")
    sp.add_argument("--skip-self-test", action="store_true")
    sp.add_argument("--ticks", type=int, default=0,
                    help="run N ticks then exit (0 = run forever)")
    sp.set_defaults(fn=cmd_node_run)
    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
