"""Precision-mode registry — the names, nothing else.

Kept jax-free so config validation (`node/config.py`) and CLI tooling
can name-check a mode without importing the accelerator stack; the
actual quantization math lives in `quant/core.py`.

A precision mode is a DETERMINISM CLASS, exactly like a mesh layout or
a canonical batch size (docs/quantization.md): `bf16` is the zoo's
shipped bf16-compute/f32-stats program, byte-for-byte; `int8`/`fp8`
quantize the checkpoint weights (per-output-channel symmetric, f32
dequant scales carried as explicit params) and dequantize inside the
jitted bucket program, so each mode is its OWN pinned XLA program —
its own graphlint golden, its own AOT cache key, its own cost-model
rows. A fleet mines one mode per template; modes are never mixed
inside one program.
"""
from __future__ import annotations

# mode → wire/storage width in bytes for a quantized tensor element.
# bf16 maps to None: "no quantization — the leaf's own dtype" (the
# pre-quant path, byte-identical).
PRECISION_MODES: dict[str, int | None] = {"bf16": None, "int8": 1,
                                          "fp8": 1}

DEFAULT_MODE = "bf16"

# symmetric quantization bounds: int8 uses the symmetric [-127, 127]
# grid (never -128 — the symmetric grid keeps 0 exact and negation
# lossless); fp8 e4m3 saturates at +-448
INT8_BOUND = 127.0
FP8_BOUND = 448.0


def validate_mode(mode, where: str = "precision") -> str:
    """Name-check a precision mode with a one-sentence boot-quality
    error (the mesh/slo/aot_cache ConfigError style)."""
    if mode not in PRECISION_MODES:
        known = "|".join(sorted(PRECISION_MODES))
        raise ValueError(
            f"{where}: unknown precision mode {mode!r} — each mode is a "
            f"pinned determinism class, and only {known} ship goldens "
            "(docs/quantization.md)")
    return mode


def wire_width(mode: str) -> int | None:
    """Bytes per element a quantized tensor of this mode occupies on
    the wire (and in HBM); None = the leaf's own dtype width (bf16 —
    no quantization)."""
    return PRECISION_MODES[validate_mode(mode)]


def mode_tag(mode: str) -> str:
    """The suffix a non-default mode contributes to executable-cache
    tags and golden keys; empty for bf16 so every pre-quant tag — and
    therefore every existing golden, AOT entry, and warm-set join —
    stays byte-identical."""
    validate_mode(mode)
    return "" if mode == DEFAULT_MODE else f".{mode}"
