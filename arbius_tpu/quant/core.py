"""quantserve core — weight quantization + dequant under the determinism gate.

The zoo runs bf16-compute/f32-stats everywhere (models/common.py); this
module adds the int8/fp8 execution modes the ROADMAP's quantized-serving
item calls for. The scheme is symmetric per-output-channel weight
quantization (the last axis of every kernel is the output-feature axis
throughout the zoo — flax Dense/Conv convention):

    scale  = absmax(w, all axes but -1) / bound        (float32)
    int8   q = clip(round(w / scale), -127, 127)       (int8 storage)
    fp8    q = (w / scale) -> float8_e4m3fn            (fp8 storage)
    dequant  = q -> float32 * scale                    (inside the jit)

Quantization happens ONCE at checkpoint-load (node/factory.py); the
runner then holds the quantized tree — int8/fp8 kernels plus explicit
f32 scales — and every bucket program begins by dequantizing it, so HBM
weight residency and any cross-chip weight collective move 1-byte
elements while the compute path stays the bf16/f32 program the family
always ran. Dequant ALWAYS passes through float32 (never int8→bf16
directly) and scales are always float32 — GRAPH407 audits exactly this
contract in every traced program.

Determinism: `quantize_tree` is a pure jittable function of the weight
tree, so a checkpoint quantizes to the same bits on every host, and the
dequantizing bucket program is one fixed XLA program per (family,
bucket, layout, mode) — its own graphlint golden, its own AOT cache
key. A mode is never a runtime branch inside a program.
"""
# detlint: enforce[DET101,DET102,DET103,DET104,DET105]
from __future__ import annotations

from arbius_tpu.quant.modes import (
    DEFAULT_MODE,
    FP8_BOUND,
    INT8_BOUND,
    PRECISION_MODES,
    mode_tag,
    validate_mode,
    wire_width,
)

# guard against all-zero kernels: a zero absmax would divide out to
# NaN scales; the floor keeps the scale finite and the dequant exact 0
_SCALE_FLOOR = 1e-12

# the sentinel keys a quantized leaf carries; dict leaves of exactly
# this shape are what `dequantize_tree` unpacks (pytree-stable: dict
# keys flatten sorted, so "qs" then "qv")
QUANT_KEYS = frozenset({"qs", "qv"})


def storage_dtype(mode: str):
    """The on-device array dtype quantized tensors of `mode` use."""
    import jax.numpy as jnp

    validate_mode(mode)
    if mode == "int8":
        return jnp.int8
    if mode == "fp8":
        return jnp.float8_e4m3fn
    return None


def is_quantized_leaf(x) -> bool:
    """True for the {"qs": scale, "qv": values} dict a quantized leaf
    becomes (the `is_leaf` predicate tree walks use)."""
    return isinstance(x, dict) and set(x) == set(QUANT_KEYS)


def _eligible(leaf) -> bool:
    """Which leaves quantize: floating kernels/embeddings (ndim >= 2).
    Biases, norm scales, and every other 0/1-D leaf stay full-width —
    they are a rounding error of the byte budget and the f32-statistics
    convention (models/common.py) wants them exact."""
    import jax.numpy as jnp

    dtype = getattr(leaf, "dtype", None)
    return (dtype is not None and jnp.issubdtype(dtype, jnp.inexact)
            and getattr(leaf, "ndim", 0) >= 2)


def quantize_leaf(w, mode: str) -> dict:
    """One kernel → {"qs": f32 per-out-channel scale, "qv": quantized
    values}. Pure and jittable; f32 math throughout."""
    import jax.numpy as jnp

    validate_mode(mode)
    w32 = w.astype(jnp.float32)
    axes = tuple(range(w32.ndim - 1))
    bound = INT8_BOUND if mode == "int8" else FP8_BOUND
    absmax = jnp.max(jnp.abs(w32), axis=axes)
    scale = (jnp.maximum(absmax, _SCALE_FLOOR) / bound).astype(jnp.float32)
    scaled = w32 / scale
    if mode == "int8":
        q = jnp.clip(jnp.round(scaled), -INT8_BOUND, INT8_BOUND) \
            .astype(jnp.int8)
    else:
        q = scaled.astype(jnp.float8_e4m3fn)
    return {"qs": scale, "qv": q}


def dequantize_leaf(leaf):
    """{"qs", "qv"} → float32 kernel: the quantized values convert to
    float32 FIRST, then multiply by the f32 scale (the GRAPH407
    contract — never int8/fp8 → bf16 directly). Full-width leaves pass
    through untouched."""
    import jax.numpy as jnp

    if not is_quantized_leaf(leaf):
        return leaf
    return leaf["qv"].astype(jnp.float32) * leaf["qs"]


def quantize_tree(params, mode: str):
    """Quantize every eligible leaf of a param tree; `bf16` returns the
    tree UNTOUCHED (the pre-quant path, byte-identical). Pure and
    jittable — factory wraps it in one jitted program at boot so the
    full-width tree is freed leaf-by-leaf as it quantizes."""
    import jax

    validate_mode(mode)
    if mode == DEFAULT_MODE:
        return params
    return jax.tree_util.tree_map(
        lambda w: quantize_leaf(w, mode) if _eligible(w) else w, params)


def dequantize_tree(params):
    """Inverse of `quantize_tree` up to quantization error: rebuilds a
    float tree with quantized kernels dequantized to f32 (flax modules
    cast to their compute dtype at use, exactly as with f32 checkpoint
    params). The no-op on an unquantized tree, so bucket programs can
    call it unconditionally."""
    import jax

    return jax.tree_util.tree_map(dequantize_leaf, params,
                                  is_leaf=is_quantized_leaf)


def quantize_params(params, mode: str):
    """Boot-time entry point (node/factory.py): ONE jitted program
    quantizing the loaded checkpoint tree on-device — eager per-leaf
    quantizes would dispatch hundreds of ops one-by-one over a
    remote-TPU transport (the boot-cast rationale). No donation: an
    int8 output can never alias its f32 source, and XLA frees each
    full-width leaf when its last read (the absmax/divide) retires."""
    import jax

    validate_mode(mode)
    if mode == DEFAULT_MODE:
        return params
    return jax.jit(lambda p: quantize_tree(p, mode))(params)


def abstract_quantized(shapes, mode: str):
    """The quantized tree's abstract (ShapeDtypeStruct) form for a given
    full-width abstract tree — what trace specs feed `jax.make_jaxpr`
    so quantized-mode goldens trace without allocating weights."""
    import jax

    return jax.eval_shape(lambda p: quantize_tree(p, mode), shapes)


def quantized_dot(qx, qw, sx, sw, mode: str = "int8"):
    """Fully-quantized matmul for activation-quantized paths: int8
    operands accumulate in int32 (`preferred_element_type`), fp8
    operands in f32, and the result dequantizes by the f32 product of
    both scales — the accumulation-dtype contract GRAPH407 pins.

    The weight-only serving path dequantizes before the matmul instead
    (the checkpoint programs above); this primitive is the building
    block for activation quantization — the quantized collective's
    wire math (parallel/collectives.py) and the GRAPH407 fixtures use
    it, and a future W8A8 bucket program would too."""
    import jax.numpy as jnp
    from jax import lax

    validate_mode(mode)
    if mode == DEFAULT_MODE:
        raise ValueError("quantized_dot needs a quantized mode "
                         "(int8|fp8) — bf16 is the unquantized path")
    acc = jnp.int32 if mode == "int8" else jnp.float32
    out = lax.dot_general(qx, qw, (((qx.ndim - 1,), (0,)), ((), ())),
                          preferred_element_type=acc)
    return out.astype(jnp.float32) * (sx[..., None] * sw)
