"""arbius_tpu.quant — int8/fp8 execution modes under the determinism gate.

Weight quantization at checkpoint-load, f32 dequant scales as explicit
params, per-mode program identities (docs/quantization.md). The mode
registry (`modes`) is jax-free for config/CLI use; the math (`core`)
imports jax lazily.
"""
from arbius_tpu.quant.modes import (
    DEFAULT_MODE,
    FP8_BOUND,
    INT8_BOUND,
    PRECISION_MODES,
    mode_tag,
    validate_mode,
    wire_width,
)
from arbius_tpu.quant.core import (
    QUANT_KEYS,
    abstract_quantized,
    dequantize_leaf,
    dequantize_tree,
    is_quantized_leaf,
    quantize_leaf,
    quantize_params,
    quantize_tree,
    quantized_dot,
    storage_dtype,
)

__all__ = [
    "DEFAULT_MODE", "FP8_BOUND", "INT8_BOUND", "PRECISION_MODES",
    "QUANT_KEYS", "abstract_quantized", "dequantize_leaf",
    "dequantize_tree", "is_quantized_leaf", "mode_tag", "quantize_leaf",
    "quantize_params", "quantize_tree", "quantized_dot", "storage_dtype",
    "validate_mode", "wire_width",
]
