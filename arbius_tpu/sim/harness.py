"""SimHarness — one scenario run: world, workload, crash-restart, drain.

The system under test is the REAL production stack end to end: a
`MinerNode` whose chain facade is `RpcChain` over signed EIP-1559
transactions into the in-process `DevnetNode`, with the fault plane's
`FaultTransport` as the only wire between them. The workload submitter
(user wallet) rides a clean transport — the user is not under test —
while an adversarial validator and a juror act directly on the engine
(their behavior is scripted, not simulated).

Run shape:

  setup   genesis mint/approve/stake, emit 100k wad from the engine so
          the validator-minimum and slashing thresholds actually bite,
          register the model, boot the node (plane disarmed — a dead
          endpoint at boot is a boot failure, not a scenario)
  rounds  one task submitted per round until the workload is exhausted
          (some flagged invalid-input or front-run by the adversary,
          per seeded draws), node.tick(), juror votes on open
          contestations, stakes sampled, virtual clock advanced
  drain   keep ticking; when nothing is due, jump the clock to the
          earliest pending job (claim windows, vote-finish windows);
          quiescent when only heartbeat jobs and no in-flight fault-
          plane events remain
  crash   a `SimCrash` out of tick() tears the node down (db connection
          closed, obs journal snapshotted) and a fresh node boots from
          the same sqlite file — re-polling the chain from block 0 and
          recovering its queue from the checkpoint

The result bundle (`SimResult`) is everything the invariant checkers
audit; `run_scenario()` is the one-call front door the CLI and tests
share.
"""
# detlint: enforce[DET101,DET102,DET103,DET105]
from __future__ import annotations

from dataclasses import dataclass, field

from arbius_tpu.chain.devnet import DevnetError, DevnetNode
from arbius_tpu.chain.engine import Engine
from arbius_tpu.chain.fixedpoint import WAD
from arbius_tpu.chain.rpc_client import EngineRpcClient, RpcError
from arbius_tpu.chain.token import TokenLedger
from arbius_tpu.chain.wallet import Wallet
from arbius_tpu.node import (
    LocalChain,
    MinerNode,
    MiningConfig,
    ModelConfig,
    ModelRegistry,
    NodeDB,
    RegisteredModel,
)
from arbius_tpu.node.config import (
    AlertsConfig,
    PerfscopeConfig,
    PipelineConfig,
    PrecisionConfig,
    SchedConfig,
)
from arbius_tpu.node.solver import EVIL_CID
from arbius_tpu.obs import use_obs
from arbius_tpu.sim.clock import VirtualClock
from arbius_tpu.sim.faults import (
    AuditedRpcChain,
    FaultPlane,
    FaultTransport,
    FaultyRunner,
    FaultyTextRunner,
    SimCrash,
    SimPinner,
)
from arbius_tpu.sim.scenario import Scenario
from arbius_tpu.templates.engine import load_template

CHAIN_ID = 31337
KEY_MINER = "0x" + "a1" * 32
KEY_USER = "0x" + "b2" * 32
EVIL = "0x" + "ee" * 20
JUROR = "0x" + "dc" * 20
START_TIME = 100_000
EMITTED_WAD = 100_000        # pseudo-supply so minimum/slash are nonzero
_HEARTBEATS = ("automine", "validatorStake")


class _CleanTransport:
    """Faultless DevnetNode transport for actors not under test."""

    def __init__(self, dev: DevnetNode):
        self.dev = dev

    def request(self, method: str, params: list):
        try:
            return self.dev.request(method, params)
        except DevnetError as e:
            raise RpcError(str(e)) from None


@dataclass
class TaskFlags:
    index: int
    invalid: bool = False
    evil: bool = False


@dataclass
class SimResult:
    """Everything a checker can audit, plus the run's summary numbers."""
    scenario: Scenario
    seed: int
    plane: FaultPlane
    engine: Engine
    db: NodeDB
    tasks: dict[str, TaskFlags] = field(default_factory=dict)
    journal_events: list[dict] = field(default_factory=list)
    min_stake_seen: int = 0
    quiescent: bool = True
    rounds: int = 0
    restarts: int = 0
    retry_max_delay: float = 30.0
    miner_address: str = ""
    # the matrix runs the staged solve executor (docs/pipeline.md);
    # SIM109 audits its journaled stage order only when it actually ran
    pipeline_enabled: bool = False
    # conclint runtime-witness record (docs/concurrency.md): observed
    # lock-order graph + watched-attr writes; None when the run was not
    # instrumented — SIM110 audits it only when present
    witness_report: dict | None = None
    # fleet runs (sim/fleet.py, docs/fleet.md): worker validator
    # addresses in worker-index order, every worker's NodeDB (task
    # conservation must see ALL local verdicts), and the lease table's
    # terminal rows + transition history — SIM111 audits these; empty
    # on single-node runs
    fleet_workers: list = field(default_factory=list)
    worker_dbs: list = field(default_factory=list)
    lease_rows: list = field(default_factory=list)
    lease_history: list = field(default_factory=list)
    lease_counts: dict = field(default_factory=dict)
    commit_rows: list = field(default_factory=list)
    # fleetscope sidecar directory (docs/fleetscope.md): one
    # `<member>.obs.sqlite` per fleet member, flushed at drain —
    # federation tests read these; empty on single-node runs
    sidecar_dir: str = ""
    # events evicted from any fleet worker's journal ring: when > 0,
    # SIM112 cannot assert adoption COMPLETENESS (a missing lease_hop
    # may simply have fallen off the ring) and downgrades to its
    # structural checks
    journal_dropped: int = 0
    # healthwatch alert engine (docs/healthwatch.md) ran on every node
    # this result audits — SIM113's fault→alert coverage invariant
    # applies only when True (the engine defaults off, like perfscope)
    healthwatch_enabled: bool = False

    def repro(self) -> str:
        return (f"python -m arbius_tpu.sim --scenario "
                f"{self.scenario.name} --seed {self.seed} "
                f"--tasks {self.scenario.tasks}")


class SimHarness:
    def __init__(self, scenario: Scenario, seed: int,
                 db_path: str = ":memory:",
                 node_cls: type[MinerNode] = MinerNode,
                 pipeline: bool = True,
                 mesh: dict | None = None,
                 witness: bool = False,
                 precision: str = "bf16",
                 perfscope: bool = False,
                 healthwatch: bool = False):
        if scenario.faults.crash_after_commit is not None \
                and db_path == ":memory:":
            # a restart from :memory: builds an EMPTY NodeDB — the run
            # would "test" recovery from a checkpoint that never existed
            # and report violations whose repro line (which always uses a
            # real file) passes
            raise ValueError(
                f"scenario {scenario.name!r} crash-restarts the node: "
                "pass a real sqlite db_path so the reboot actually "
                "recovers from the checkpoint")
        self.scenario = scenario
        self.seed = seed
        self.db_path = db_path
        self.node_cls = node_cls
        self.pipeline = pipeline
        # perfscope card capture (docs/perfscope.md): metering-only —
        # cards must not perturb CIDs, so every scenario must hold its
        # invariants (and its bytes) perfscope-on (test-pinned)
        self.perfscope = perfscope
        # healthwatch alert engine (docs/healthwatch.md): bookkeeping-
        # only — CIDs must match a healthwatch-off run byte for byte,
        # and SIM113 audits the fault→alert coverage of every run that
        # enables it (the matrix fixture does)
        self.healthwatch = healthwatch
        # conclint runtime witness (docs/concurrency.md): instrumented
        # lock wrappers + watched-attr sampling on every node this
        # harness spawns. Bookkeeping-only — CIDs must stay
        # byte-identical to a witness-off run (test-pinned).
        self.witness = None
        if witness:
            from arbius_tpu.analysis.conc.witness import ConcWitness

            self.witness = ConcWitness()
            self.witness.register_root("tick")
        # mesh scenarios (docs/multichip.md): a `mesh` config swaps the
        # hash-fake FaultyRunner for meshsolve's ShardedImageProbe — a
        # REAL jitted GSPMD program over the forced 8-way CPU devices,
        # fault-gated per dispatch exactly where FaultyRunner gates. The
        # probe's bytes are layout-invariant by construction, so a run
        # at mesh={"dp":2} must produce the same CIDs as mesh=None
        # (tests/test_meshsolve.py pins it); SIM101-109 audit unchanged.
        # mesh={} means "probe runner, no mesh" — the equality baseline.
        self.mesh_cfg = mesh
        self.mesh = None
        if mesh is not None and mesh:
            from arbius_tpu.parallel import meshsolve

            self.mesh = meshsolve.boot_mesh(dict(mesh))
        # precision mode (docs/quantization.md): a non-bf16 mode needs
        # the probe runner (the hash-fake FaultyRunner has no XLA
        # program to quantize), quantizes the probe weights, and rides
        # every bucket key / cost tag through the node — SIM101-112
        # must hold at int8 exactly as at bf16
        from arbius_tpu.quant import validate_mode

        self.precision = validate_mode(precision)
        if self.precision != "bf16" and mesh is None:
            raise ValueError(
                f"precision {precision!r} needs the probe runner — pass "
                "mesh={} (probe, no mesh) or a real mesh config")

        self.token = TokenLedger()
        self.engine = Engine(self.token, start_time=START_TIME)
        self.token.mint(Engine.ADDRESS, 600_000 * WAD)
        self.dev = DevnetNode(self.engine, chain_id=CHAIN_ID)
        self.clock = VirtualClock(self.engine)

        self.miner_wallet = Wallet.from_hex(KEY_MINER)
        self.user_wallet = Wallet.from_hex(KEY_USER)
        self.plane = FaultPlane(scenario, seed, self.clock, self.engine,
                                self.miner_wallet.address)
        self._rng_work = self.plane._rng_rpc.stream("workload")

        # genesis: emitted supply + funded actors + adversary/juror stakes
        self.token.transfer(Engine.ADDRESS, "0x" + "99" * 20,
                            EMITTED_WAD * WAD)
        for addr in (self.miner_wallet.address, self.user_wallet.address,
                     EVIL, JUROR):
            self.token.mint(addr, 1_000 * WAD)
            self.token.approve(addr.lower(), Engine.ADDRESS, 10**30)
        self.evil_chain = LocalChain(self.engine, EVIL)
        self.juror_chain = LocalChain(self.engine, JUROR)
        self.evil_chain.validator_deposit(200 * WAD)
        self.juror_chain.validator_deposit(200 * WAD)
        # pre-stake the miner well above the minimum: per-contest slash
        # escrows subtract from usable stake mid-run, and a node wedged
        # below the minimum between stake-heartbeat runs would turn every
        # scenario into a stake test
        self.engine.validator_deposit(self.miner_wallet.address,
                                      self.miner_wallet.address, 400 * WAD)
        # age the stakes past the anti-vote-buying gate (EngineV1.sol:976)
        self.engine.advance_time(
            self.engine.max_contestation_validator_stake_since + 100,
            blocks=0)

        mid_b = self.engine.register_model(
            self.user_wallet.address, self.user_wallet.address, 0,
            b'{"meta":{"title":"simnet"}}')
        self.model_id = "0x" + mid_b.hex()
        # mixed-family scenarios (sched-flood, docs/scheduler.md):
        # additional registered models share the template but form their
        # own buckets, so the packer has real cross-family choices
        self.model_ids = [self.model_id]
        for f in range(1, scenario.families):
            mb = self.engine.register_model(
                self.user_wallet.address, self.user_wallet.address, 0,
                f'{{"meta":{{"title":"simnet-f{f}"}}}}'.encode())
            self.model_ids.append("0x" + mb.hex())
        self.user_client = EngineRpcClient(
            _CleanTransport(self.dev), self.dev.engine_address,
            self.user_wallet, chain_id=CHAIN_ID)

        self._submitted_ids: list[str] = []
        self.engine.subscribe(self._record_task_event)

        self.result = SimResult(scenario=scenario, seed=seed,
                                plane=self.plane, engine=self.engine,
                                db=None, miner_address=self.miner_wallet
                                .address.lower())
        self.node: MinerNode | None = None
        self._spawn_node()

    # -- world ------------------------------------------------------------
    def _record_task_event(self, ev) -> None:
        if ev.name == "TaskSubmitted":
            self._submitted_ids.append("0x" + ev.args["id"].hex())

    def _spawn_node(self) -> None:
        transport = FaultTransport(self.dev, self.plane)
        client = EngineRpcClient(transport, self.dev.engine_address,
                                 self.miner_wallet, chain_id=CHAIN_ID)
        chain = AuditedRpcChain(client, self.dev.token_address, self.plane)
        cfg = MiningConfig(
            db_path=":memory:",  # unused: db object injected below
            models=tuple(ModelConfig(id=mid,
                                     template=self.scenario.template)
                         for mid in self.model_ids),
            # costsched packer (docs/scheduler.md) when the scenario
            # says so: bucket order becomes the scheduler's choice and
            # every SIM1xx invariant must hold regardless
            sched=SchedConfig(enabled=True) if self.scenario.sched
            else SchedConfig(),
            compile_cache_dir=None,
            obs_journal_capacity=16384,
            retry_max_delay=self.result.retry_max_delay,
            # the staged executor runs under EVERY scenario's fault mix
            # by default (docs/pipeline.md): real encode worker threads,
            # a 2-deep device window, a bounded network backlog —
            # SIM101-108 must hold unchanged and SIM109 audits the stage
            # order. pipeline=False drives the shipped synchronous
            # default through the same fault plane (tests/test_sim.py
            # runs both so neither schedule's path rots uncovered).
            pipeline=PipelineConfig(enabled=True, depth=2,
                                    encode_workers=2, max_inflight_pins=2)
            if self.pipeline else PipelineConfig(),
            # canonical_batch 2 so a dp2 mesh actually shards the
            # dispatch (batch 1 degrades to replicated — still correct,
            # but then the scenario would not exercise the dp path);
            # the mesh-off probe baseline runs the same batch so the
            # chunking is identical and only the layout differs
            mesh=dict(self.mesh_cfg) if self.mesh_cfg else None,
            canonical_batch=2 if self.mesh_cfg is not None else 1,
            precision=PrecisionConfig(default=self.precision),
            perfscope=PerfscopeConfig(enabled=True)
            if self.perfscope else PerfscopeConfig(),
            alerts=AlertsConfig(enabled=True)
            if self.healthwatch else AlertsConfig())
        self.result.pipeline_enabled = self.pipeline
        self.result.healthwatch_enabled = self.healthwatch
        if self.mesh_cfg is not None:
            from arbius_tpu.parallel.meshsolve import ShardedImageProbe

            runner = ShardedImageProbe(mesh=self.mesh,
                                       gate=self.plane.runner_gate,
                                       mode=self.precision)
        elif self.scenario.template == "textgen":
            # text-family scenarios (docs/text-serving.md): the
            # token-progress hash-fake with the decode-stall edge
            runner = FaultyTextRunner(self.plane)
        else:
            runner = FaultyRunner(self.plane)
        registry = ModelRegistry()
        for mid in self.model_ids:
            registry.register(RegisteredModel(
                id=mid, template=load_template(self.scenario.template),
                runner=runner))
        db = NodeDB(self.db_path)
        node = self.node_cls(chain, cfg, registry, db=db, store=None,
                             pinner=SimPinner(self.plane))
        node._retry_sleep = self.clock.sleep
        if self.witness is not None:
            from arbius_tpu.analysis.conc.witness import instrument_node

            # before boot/tick: no thread can be inside a wrapped lock
            # during the swap (the encode pool is parked on its queue)
            instrument_node(node, self.witness)
        node.boot(skip_self_test=True)
        self.node = node
        self.result.db = db

    def _restart_node(self) -> None:
        """Crash recovery: snapshot the dead node's flight recorder,
        close its db handle, boot a replacement from the same sqlite
        checkpoint (fresh RpcChain — it re-polls from block 0 and the
        db's INSERT OR IGNORE absorbs the replayed history)."""
        self.result.journal_events.extend(self.node.obs.journal.events())
        self.result.journal_dropped += self.node.obs.journal.dropped
        self.result.restarts += 1
        self.node.close()   # encode pool + sqlite handle
        armed = self.plane.armed
        self.plane.armed = False     # boot is not under fault injection
        try:
            self._spawn_node()
        finally:
            self.plane.armed = armed

    # -- workload ----------------------------------------------------------
    def _task_input(self, i: int, invalid: bool) -> bytes:
        import json

        if invalid:
            # undecodable JSON: hydration must fail and the node must
            # remember the task as invalid (contestation evidence)
            return b'{"prompt": broken'
        if self.scenario.template == "textgen":
            # text workload (docs/text-serving.md): mixed decode
            # budgets land in different decode buckets, alternating
            # samplers split the greedy/top_k determinism classes
            obj = {"prompt": f"simnet text {i} {self._rng_work.u64():x}",
                   "max_new_tokens": (8, 16, 24)[i % 3],
                   "sampler": "top_k" if i % 2 else "greedy"}
            return json.dumps(obj, sort_keys=True).encode()
        obj = {"prompt": f"simnet task {i} {self._rng_work.u64():x}",
               "negative_prompt": ""}
        if i % self.scenario.families:
            # the mixed-family flood also mixes SHAPES, so the packer
            # reorders across genuinely different buckets (width is part
            # of the bucket key; the template enum admits 256)
            obj["width"] = 256
            obj["height"] = 256
        return json.dumps(obj, sort_keys=True).encode()

    def _submit_task(self, i: int) -> None:
        invalid = self._rng_work.chance(self.scenario.invalid_rate)
        evil = (not invalid) and self._rng_work.chance(self.scenario.evil_rate)
        family = i % self.scenario.families
        # fees differ per family so costsched's fee/chip-second ranking
        # has a real gradient to act on
        fee = self.scenario.fee_wad * WAD * (1 + family)
        self.user_client.send("submitTask", [
            0, self.user_wallet.address, self.model_ids[family], fee,
            self._task_input(i, invalid)])
        tid = self._submitted_ids[-1]
        self.result.tasks[tid] = TaskFlags(index=i, invalid=invalid,
                                           evil=evil)
        if evil:
            # adversary front-runs with a deliberately wrong CID before
            # the node can even see the task (commit tx mines a block, so
            # the reveal is immediately valid)
            c = self.evil_chain.generate_commitment(tid, EVIL_CID)
            self.evil_chain.signal_commitment(c)
            self.evil_chain.submit_solution(tid, EVIL_CID)

    def _juror_pass(self) -> None:
        """Scripted third validator: votes yea on every open contestation
        (the node's yea + juror's yea out-vote the accused's auto-nay, so
        a contested wrong answer actually loses)."""
        for tid, flags in self.result.tasks.items():
            if not flags.evil:
                continue
            tb = bytes.fromhex(tid[2:])
            if tb not in self.engine.contestations:
                continue
            if self.juror_chain.contestation_voted(tid):
                continue
            if self.juror_chain.validator_can_vote(tid) != 0:
                continue
            self.juror_chain.vote_on_contestation(tid, True)

    # -- driving -----------------------------------------------------------
    def _tick(self) -> int:
        try:
            return self.node.tick()
        except SimCrash:
            self._restart_node()
            return 0

    def _sample_stakes(self) -> None:
        for v in self.engine.validators.values():
            if v.staked < self.result.min_stake_seen:
                self.result.min_stake_seen = v.staked

    def _pending_jobs(self) -> list:
        jobs = self.node.db.get_jobs(2**60, limit=1000)
        return [j for j in jobs if j.method not in _HEARTBEATS]

    def run(self) -> SimResult:
        try:
            return self._run()
        finally:
            # even when a scenario bug/interrupt escapes mid-run: the
            # class-level __setattr__ watch hook must come off (a stale
            # hook would double-count the next witness's records) and
            # whatever was observed rides the result for post-mortems
            if self.witness is not None:
                self.result.witness_report = self.witness.report()
                self.witness.unwatch_all()

    def _run(self) -> SimResult:
        scenario, result = self.scenario, self.result
        with use_obs(self.node.obs):
            self._tick()             # settle the boot-queued stake job
        self.plane.armed = True
        submitted = 0
        rounds = 0
        while rounds < scenario.max_rounds:
            rounds += 1
            # a restart swaps self.node — re-enter the obs context each
            # round so sim counters land in the live node's registry
            with use_obs(self.node.obs):
                # flood scenarios submit bursts so the queue actually
                # holds multiple buckets when the packer runs
                for _ in range(max(1, scenario.burst)):
                    if submitted >= scenario.tasks:
                        break
                    self._submit_task(submitted)
                    submitted += 1
                self._tick()
                self._juror_pass()
                self._sample_stakes()
                if submitted >= scenario.tasks:
                    pending = self._pending_jobs()
                    if not pending and self.plane.pending_events() == 0:
                        break
                    if pending:
                        due = [j for j in pending
                               if j.waituntil <= self.clock.now]
                        if not due:
                            # nothing actionable now: jump to the next
                            # deadline (claim / vote-finish windows)
                            nxt = min(j.waituntil for j in pending)
                            if nxt > self.clock.now:
                                self.clock.advance(nxt - self.clock.now)
                self.clock.advance(scenario.tick_seconds)
                # a real chain produces blocks whether or not we
                # transact; an empty block per round keeps the poll
                # range moving so delayed/replayed logs actually flush
                # (poll_events short-circuits when latest < next_block)
                self.engine.mine_block()
        else:
            result.quiescent = False
        result.rounds = rounds
        result.journal_events.extend(self.node.obs.journal.events())
        result.journal_dropped += self.node.obs.journal.dropped
        if self.node._pipeline is not None:
            # stop the encode pool; the db handle stays open — the
            # invariant checkers still audit it through the result
            self.node._pipeline.shutdown()
        self.plane.armed = False
        return result


def run_scenario(scenario: Scenario, seed: int, *,
                 db_path: str = ":memory:",
                 node_cls: type[MinerNode] = MinerNode,
                 pipeline: bool = True,
                 mesh: dict | None = None,
                 witness: bool = False,
                 precision: str = "bf16",
                 perfscope: bool = False,
                 healthwatch: bool = False) -> SimResult:
    """Build a world, drive the scenario to quiescence, return the
    auditable result. `node_cls` lets regression tests inject a
    deliberately buggy node (tests/test_sim.py double-commit);
    `pipeline=False` runs the shipped synchronous solve path instead of
    the staged executor. `mesh` (e.g. ``{"dp": 2}``) runs the solves as
    real sharded XLA programs on the virtual device mesh via the
    meshsolve image probe; ``{}`` selects the probe with no mesh (the
    CID-equality baseline for a meshed run). `witness=True` instruments
    the node with the conclint runtime witness and attaches its report
    to the result for SIM110 (docs/concurrency.md). `precision` runs
    the solves at a quantized mode through the probe runner
    (docs/quantization.md) — every SIM invariant must hold unchanged.
    `perfscope=True` installs the perf-card capture (docs/perfscope.md);
    cards are metering only, so CIDs must match a perfscope-off run
    byte for byte (test-pinned). `healthwatch=True` enables the live
    alert engine (docs/healthwatch.md) on every node the harness
    spawns — SIM113 then audits the fault→alert coverage (every
    injected fault class raised its mapped alert, clean runs raised
    none) and CIDs stay byte-identical on vs off."""
    return SimHarness(scenario, seed, db_path=db_path,
                      node_cls=node_cls, pipeline=pipeline,
                      mesh=mesh, witness=witness,
                      precision=precision, perfscope=perfscope,
                      healthwatch=healthwatch).run()
