"""The fault plane: seeded adversarial wrappers around the node's I/O.

Three edges, one shared `FaultPlane` (rng streams + virtual clock +
audit trace):

  `FaultTransport`   wraps `DevnetNode.request` — the node's ENTIRE
                     chain surface (views, event polling, signed txs)
                     crosses this one choke point, so transport errors,
                     lost tx responses, injected latency, delayed/
                     replayed logs, shallow log-replay reorgs, and the
                     crash trigger all live here. Every *landed* write
                     is RLP/ABI-decoded into the audit trace the
                     invariant checkers consume.
  `SimPinner`        a pinning "service" that fails, stalls, or answers
                     a mismatched root CID (raising `PinMismatchError`
                     exactly as the remote pinners do).
  `FaultyRunner`     a deterministic solve function (bytes are a pure
                     hash of input+seed — fault draws NEVER touch
                     output bytes, only timing/failure) that can run
                     slow or crash mid-batch.

`SimCrash` derives from BaseException on purpose: the node's job loop
quarantines `Exception`s, and a simulated `kill -9` must tear through
those handlers exactly as a real process death would — the harness
catches it at the tick boundary and reboots the node from its sqlite
checkpoint.

Reorg model: the engine state machine never forks; a "reorg" here is
what a log subscriber observes during a shallow one — recent logs
re-served, out of order, past the consumer's high-water mark. The
node's INSERT OR IGNORE event handling must absorb it.
"""
# detlint: enforce[DET101,DET102,DET103,DET105]
from __future__ import annotations

from dataclasses import dataclass, field

from arbius_tpu.chain.devnet import EVENT_TOPIC0, DevnetError
from arbius_tpu.chain.rlp import decode_signed_eip1559
from arbius_tpu.chain.rpc_client import ENGINE_FNS, RpcError, selector
from arbius_tpu.l0.abi import abi_decode
from arbius_tpu.node.pinners import PinMismatchError
from arbius_tpu.node.rpc_chain import RpcChain
from arbius_tpu.obs import current_obs

_TASK_SUBMITTED_TOPIC = "0x" + EVENT_TOPIC0["TaskSubmitted"].hex()

# selector -> (method name, arg types) for every write the audit decodes
_WRITE_ABI = {selector(sig): (name, types)
              for name, (sig, types) in ENGINE_FNS.items()}
_WRITE_ABI[selector("approve(address,uint256)")] = (
    "approve", ["address", "uint256"])


class SimCrash(BaseException):
    """Simulated process death (kill -9). BaseException so the node's
    quarantine handlers cannot swallow it — only the harness catches."""


class SimPinError(RuntimeError):
    """Transient pinning-service failure (the 5xx class)."""


class SimRunnerError(RuntimeError):
    """Runner died mid-batch (the OOM/preemption class)."""


@dataclass
class AuditRecord:
    """One landed (or rejected) chain write, as decoded from the raw tx."""
    seq: int
    block: int          # block the tx lands in (pre-automine number)
    now: int            # chain time at apply
    method: str
    sender: str
    values: list
    ok: bool
    error: str = ""


@dataclass
class PendingLog:
    release_poll: int
    log: dict = field(default_factory=dict)


class FaultPlane:
    """Shared state of one scenario run: rng streams, clock, fault
    counters, the audit trace, and the commitment registry."""

    def __init__(self, scenario, seed: int, clock, engine,
                 miner_address: str):
        from arbius_tpu.sim.rng import SimRng

        self.scenario = scenario
        self.spec = scenario.faults
        self.seed = seed
        self.clock = clock
        self.engine = engine
        self.miner_address = miner_address.lower()
        root = SimRng(seed)
        self._rng_rpc = root.stream("rpc")
        self._rng_events = root.stream("events")
        self._rng_pin = root.stream("pin")
        self._rng_runner = root.stream("runner")
        self._rng_decode = root.stream("decode")
        self.armed = False           # faults suppressed until the harness arms
        self.fault_counts: dict[str, int] = {}
        self.audit: list[AuditRecord] = []
        self.commitments: dict[bytes, tuple[str, str, str]] = {}
        self.delivered_taskids: set[str] = set()
        self.crash_seqs: list[int] = []
        self._commits_landed = 0
        self._crash_pending = False
        self.poll_index = 0
        self._delayed: list[PendingLog] = []
        self._replay_next: list[dict] = []

    # -- bookkeeping ------------------------------------------------------
    def count(self, kind: str) -> None:
        self.fault_counts[kind] = self.fault_counts.get(kind, 0) + 1
        obs = current_obs()
        if obs is not None:
            obs.registry.counter(
                "arbius_sim_faults_total",
                "Faults injected by the simnet fault plane, by kind",
                labelnames=("kind",)).inc(kind=kind)

    def register_commitment(self, commitment: bytes, sender: str,
                            taskid: str, cid: str) -> None:
        """Plaintext (validator, taskid, cid) behind a commitment hash —
        recorded at generate time, where the args are still visible."""
        self.commitments[commitment] = (sender.lower(), taskid, cid)

    def record(self, method: str, sender: str, values: list, *, ok: bool,
               error: str = "") -> AuditRecord:
        rec = AuditRecord(seq=len(self.audit),
                          block=self.engine.block_number,
                          now=self.engine.now, method=method,
                          sender=sender, values=values, ok=ok, error=error)
        self.audit.append(rec)
        return rec

    def pending_events(self) -> int:
        return len(self._delayed) + len(self._replay_next)

    # -- crash trigger ----------------------------------------------------
    def _note_landed(self, method: str, sender: str) -> bool:
        """Count the miner's landed commits; True = die now (once)."""
        if (self.spec.crash_after_commit is None
                or method != "signalCommitment"
                or sender != self.miner_address):
            return False
        self._commits_landed += 1
        if (not self._crash_pending
                and self._commits_landed == self.spec.crash_after_commit):
            self._crash_pending = True
            return True
        return False

    def crash_now(self) -> SimCrash:
        self.count("crash")
        self.crash_seqs.append(len(self.audit))
        obs = current_obs()
        if obs is not None:
            obs.event("sim_crash", commits_landed=self._commits_landed)
        return SimCrash(
            f"sim: node killed after commit #{self._commits_landed} landed")

    # -- edge gates (called by the wrappers) ------------------------------
    def rpc_gate(self, method: str) -> None:
        """Latency + 5xx for read-side RPC (views, polls)."""
        if not self.armed:
            return
        if self.spec.latency_max > 0:
            lat = self._rng_rpc.randint(0, self.spec.latency_max)
            if lat:
                # counted like every injected fault: timing-only faults
                # still mark a run as fault-laden, which is what lets
                # SIM113 hold "clean scenarios raise no alerts" while
                # allowing latency-driven pipeline stalls to alert
                # (docs/healthwatch.md coverage map)
                self.count("latency")
                self.clock.advance(lat)
        if method == "eth_getLogs":
            if self._rng_rpc.chance(self.spec.poll_error_rate):
                self.count("poll_error")
                raise RpcError("sim: eth_getLogs 503")
        elif method == "eth_call":
            if self._rng_rpc.chance(self.spec.view_error_rate):
                self.count("view_error")
                raise RpcError("sim: eth_call 503")

    def pin_gate(self) -> None:
        if not self.armed:
            return
        if self.spec.pin_stall_seconds > 0:
            stall = self._rng_pin.randint(0, self.spec.pin_stall_seconds)
            if stall:
                self.count("pin_stall")
                self.clock.advance(stall)
        if self._rng_pin.chance(self.spec.pin_fail_rate):
            self.count("pin_fail")
            raise SimPinError("sim: pinning service 502")
        if self._rng_pin.chance(self.spec.pin_mismatch_rate):
            self.count("pin_mismatch")
            raise PinMismatchError(
                "sim: service answered a different root CID")

    def runner_gate(self) -> None:
        if not self.armed:
            return
        if self.spec.runner_slow_seconds > 0:
            slow = self._rng_runner.randint(0, self.spec.runner_slow_seconds)
            if slow:
                self.count("runner_slow")   # timing-only, see rpc_gate
                self.clock.advance(slow)
        if self._rng_runner.chance(self.spec.runner_crash_rate):
            self.count("runner_crash")
            raise SimRunnerError("sim: runner crashed mid-batch")

    def decode_gate(self) -> None:
        """Text-family decode stall (docs/text-serving.md): the solve
        "decoded zero output bytes" — surfaced through the SAME
        production counter the real TextGenRunner.finalize bumps, so
        the healthwatch decode_stall rule sees sim and production
        stalls identically. Observation-only: output bytes are NEVER
        touched (the sim's determinism anchor holds)."""
        if not self.armed:
            return
        if self._rng_decode.chance(self.spec.decode_stall_rate):
            from arbius_tpu.node.solver import count_decode_stall

            self.count("decode_stall")
            count_decode_stall()


class FaultTransport:
    """JsonRpcTransport-compatible wrapper over an in-process DevnetNode
    with the fault plane's chain-RPC edge applied. This is the ONLY path
    between the node under test and the chain."""

    def __init__(self, dev, plane: FaultPlane):
        self.dev = dev
        self.plane = plane

    def request(self, method: str, params: list):
        self.plane.rpc_gate(method)
        if method == "eth_sendRawTransaction":
            return self._send_raw(params)
        if method == "eth_getLogs":
            return self._get_logs(params)
        try:
            return self.dev.request(method, params)
        except DevnetError as e:
            raise RpcError(str(e)) from None

    # -- writes -----------------------------------------------------------
    def _decode_write(self, raw_hex: str) -> tuple[str, str, list]:
        dec = decode_signed_eip1559(bytes.fromhex(raw_hex[2:]))
        sel = dec.tx.data[:4]
        name, types = _WRITE_ABI.get(sel, (sel.hex(), None))
        values = abi_decode(types, dec.tx.data[4:]) if types else []
        return name, dec.sender.lower(), values

    def _send_raw(self, params: list):
        plane = self.plane
        method, sender, values = self._decode_write(params[0])
        if plane.armed and plane._rng_rpc.chance(plane.spec.tx_error_rate):
            plane.count("tx_error")
            plane.record(method, sender, values, ok=False,
                         error="sim: dropped before send")
            raise RpcError(f"sim: {method} tx dropped before send")
        try:
            result = self.dev.request("eth_sendRawTransaction", params)
        except DevnetError as e:
            plane.record(method, sender, values, ok=False, error=str(e))
            raise RpcError(str(e)) from None
        plane.record(method, sender, values, ok=True)
        if plane._note_landed(method, sender):
            raise plane.crash_now()
        if plane.armed and plane._rng_rpc.chance(
                plane.spec.tx_lost_response_rate):
            plane.count("tx_lost_response")
            raise RpcError(f"sim: {method} landed but the response was lost")
        return result

    # -- event plane ------------------------------------------------------
    def _note_delivered(self, logs: list[dict]) -> None:
        for lg in logs:
            if lg.get("topics") and lg["topics"][0] == _TASK_SUBMITTED_TOPIC:
                self.plane.delivered_taskids.add(lg["topics"][1])

    def _get_logs(self, params: list):
        plane = self.plane
        try:
            logs = self.dev.request("eth_getLogs", params)
        except DevnetError as e:  # pragma: no cover — devnet never 5xxs
            raise RpcError(str(e)) from None
        plane.poll_index += 1
        out: list[dict] = []
        # release previously-delayed logs first (they are the oldest)
        still: list[PendingLog] = []
        for p in plane._delayed:
            if p.release_poll <= plane.poll_index:
                out.append(p.log)
            else:
                still.append(p)
        plane._delayed = still
        for lg in logs:
            if plane.armed and plane._rng_events.chance(
                    plane.spec.event_delay_rate):
                plane.count("event_delay")
                plane._delayed.append(PendingLog(
                    plane.poll_index + plane._rng_events.randint(1, 3), lg))
                continue
            out.append(lg)
            if plane.armed and plane._rng_events.chance(
                    plane.spec.event_replay_rate):
                plane.count("event_replay")
                plane._replay_next.append(lg)
        if plane._replay_next:
            # duplicates marked last poll ride along with this one
            out.extend(plane._replay_next)
            plane._replay_next = []
        if (plane.armed and plane.spec.reorg_every > 0
                and plane.poll_index % plane.spec.reorg_every == 0):
            cutoff = max(0, self.dev.engine.block_number
                         - plane.spec.reorg_depth)
            replayed = [lg for lg in self.dev.logs
                        if int(lg["blockNumber"], 16) >= cutoff]
            if replayed:
                plane.count("reorg")
                out.extend(replayed)
        self._note_delivered(out)
        return out


class AuditedRpcChain(RpcChain):
    """RpcChain that reports commitment plaintexts to the fault plane —
    the piece that lets the checkers resolve on-chain commitment hashes
    back to (validator, taskid, cid) without inverting keccak."""

    def __init__(self, client, token_address: str, plane: FaultPlane,
                 **kwargs):
        super().__init__(client, token_address, **kwargs)
        self._plane = plane

    def generate_commitment(self, taskid: str, cid: str) -> bytes:
        c = super().generate_commitment(taskid, cid)
        self._plane.register_commitment(c, self.address, taskid, cid)
        return c


class SimPinner:
    """Pinner-protocol "remote service" under fault-plane control: the
    root CID is computed locally (the real remote pinners verify against
    exactly this), and the plane decides whether the service call fails,
    stalls, or answers a mismatched root."""

    def __init__(self, plane: FaultPlane):
        self.plane = plane
        self.pinned: dict[str, int] = {}    # cid hex -> times pinned

    def pin_files(self, files: dict[str, bytes], taskid: str = "") -> bytes:
        from arbius_tpu.l0.cid import cid_of_solution_files

        self.plane.pin_gate()
        root = cid_of_solution_files(files)
        key = "0x" + root.hex()
        self.pinned[key] = self.pinned.get(key, 0) + 1
        return root

    def pin_blob(self, content: bytes, filename: str = "input") -> bytes:
        from arbius_tpu.l0.cid import dag_of_file

        self.plane.pin_gate()
        cid = dag_of_file(content).cid
        key = "0x" + cid.hex()
        self.pinned[key] = self.pinned.get(key, 0) + 1
        return cid


class FaultyRunner:
    """Deterministic solve function with timing/crash faults. Output
    bytes are a pure hash of (hydrated-minus-seed, seed) — a fault can
    delay or kill a solve but can NEVER change the bytes, so the CID a
    task commits to is identical across retries, crashes, and seeds of
    the fault schedule (the sim's determinism anchor)."""

    def __init__(self, plane: FaultPlane, out_name: str = "out-1.png"):
        self.plane = plane
        self.out_name = out_name

    def __call__(self, hydrated: dict, seed: int) -> dict:
        import hashlib
        import json

        self.plane.runner_gate()
        canon = json.dumps(
            {k: v for k, v in hydrated.items() if k != "seed"},
            sort_keys=True).encode()
        blob = hashlib.sha256(canon + seed.to_bytes(8, "big")).digest()
        return {self.out_name: b"\x89PNG" + blob}


class FaultyTextRunner(FaultyRunner):
    """Text-family hash-fake (docs/text-serving.md): output bytes are a
    pure hash stream of (hydrated-minus-seed, seed) truncated to the
    task's decode budget — so solve cost and output size track
    `max_new_tokens` the way a real decode loop's do, while staying
    jax-free. Mirrors the production TextGenRunner's intake hook
    (`prepare_hydrated` stamps the sequence buckets) so costsched packs
    real 9-tuple sequence buckets in simnet. Decode-stall faults are
    counted and surfaced through the production stall counter but NEVER
    touch the bytes (the sim's determinism anchor)."""

    # the production defaults (node/config.py TextgenConfig) — simnet
    # buckets must look like a shipped node's
    PROMPT_EDGES = (32, 64)
    DECODE_EDGES = (16, 32)

    def __init__(self, plane: FaultPlane, out_name: str = "out-1.txt"):
        super().__init__(plane, out_name)

    def prepare_hydrated(self, hydrated: dict) -> dict:
        h = dict(hydrated)
        need = len(str(h.get("prompt", "")).encode("utf-8")) + 2
        h["_prompt_bucket"] = next(
            (e for e in self.PROMPT_EDGES if e >= need),
            self.PROMPT_EDGES[-1])
        budget = int(h.get("max_new_tokens") or 16)
        h["_decode_bucket"] = next(
            (e for e in self.DECODE_EDGES if e >= max(1, budget)),
            self.DECODE_EDGES[-1])
        return h

    def __call__(self, hydrated: dict, seed: int) -> dict:
        import hashlib
        import json

        self.plane.decode_gate()
        self.plane.runner_gate()
        canon = json.dumps(
            {k: v for k, v in hydrated.items() if k != "seed"},
            sort_keys=True).encode()
        budget = int(hydrated.get("max_new_tokens") or 16)
        stream = b""
        counter = 0
        while len(stream) < budget:
            stream += hashlib.sha256(
                canon + seed.to_bytes(8, "big")
                + counter.to_bytes(4, "big")).digest()
            counter += 1
        return {self.out_name: stream[:budget]}
