"""Seeded, stream-split PRNG for the simulation harness.

Counter-mode SHA-256: every draw is `sha256(prefix || counter)` — pure,
platform-independent, and free of the host RNG the determinism rules ban
(`random`, `os.urandom` are DET102 findings; this module is the one
sanctioned randomness source in the sim). Streams are derived by name
(`rng.stream("pin")`), so adding draws to one fault site never shifts
the sequence another site sees — the FoundationDB trick that keeps a
seed reproducing the same schedule across harness refactors.
"""
# detlint: enforce[DET101,DET102,DET103,DET105]
from __future__ import annotations

import hashlib


class SimRng:
    """Deterministic stream of draws from (seed, stream-name)."""

    def __init__(self, seed: int, stream: str = "root"):
        self.seed = int(seed)
        self.name = stream
        self._prefix = hashlib.sha256(
            f"simnet/{self.seed}/{stream}".encode()).digest()
        self._n = 0

    def stream(self, name: str) -> "SimRng":
        """Derive an independent named sub-stream (same seed)."""
        return SimRng(self.seed, f"{self.name}/{name}")

    def u64(self) -> int:
        digest = hashlib.sha256(
            self._prefix + self._n.to_bytes(8, "big")).digest()
        self._n += 1
        return int.from_bytes(digest[:8], "big")

    def uniform(self) -> float:
        """Uniform float in [0, 1)."""
        return self.u64() / 2**64

    def chance(self, p: float) -> bool:
        """True with probability `p` (p <= 0 never draws: a zero-rate
        fault consumes no counter, so disabling one fault can't shift
        another's schedule)."""
        if p <= 0.0:
            return False
        return self.uniform() < p

    def randint(self, lo: int, hi: int) -> int:
        """Uniform int in [lo, hi] inclusive."""
        if hi < lo:
            raise ValueError(f"randint: empty range [{lo}, {hi}]")
        return lo + self.u64() % (hi - lo + 1)

    def choice(self, seq):
        if not seq:
            raise ValueError("choice: empty sequence")
        return seq[self.u64() % len(seq)]
