"""Virtual clock — the sim's single time authority.

All time in a scenario is the engine's chain time: the node already
reads `chain.now` for job due-ness, and its retry sleeps are injectable
(`MinerNode._retry_sleep`), so pointing both at this clock removes the
wall clock entirely. Injected RPC latency, pinner stalls, slow solves,
and expretry backoff all `advance()` the same engine — a scenario's
entire timeline is a pure function of the seed.

`sleep()` (the `expretry` hook) records each requested delay so tests
can assert the exact backoff curve a retry envelope injected
(tests/test_sim_retry.py — the reference's `base**attempt` sequence and
the `max_delay` cap).
"""
# detlint: enforce[DET101,DET102,DET103,DET105]
from __future__ import annotations


class VirtualClock:
    def __init__(self, engine):
        self.engine = engine
        self.slept = 0.0          # total seconds requested via sleep()
        self.advanced = 0         # total whole seconds applied to engine
        self.sleeps: list[float] = []   # each sleep() request, in order

    @property
    def now(self) -> int:
        return self.engine.now

    def advance(self, seconds: float) -> int:
        """Advance chain time by ceil(seconds) without mining a block
        (blocks advance via txs — devnet automine). Returns the applied
        whole-second amount."""
        whole = int(seconds)
        if whole < seconds:
            whole += 1
        if whole > 0:
            self.engine.advance_time(whole, blocks=0)
            self.advanced += whole
        return whole

    def sleep(self, seconds: float) -> None:
        """Drop-in for `time.sleep` in retry envelopes: records the
        request and advances chain time instead of blocking."""
        self.sleeps.append(round(float(seconds), 6))
        self.slept += seconds
        self.advance(seconds)
