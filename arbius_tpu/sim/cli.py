"""simnet command line — `python -m arbius_tpu.sim` / tools/simsoak.py.

Same contract as detlint/graphlint (arbius_tpu.analysis.cli defines it
once): exit 0 = every scenario run passed every invariant checker,
1 = findings, 2 = usage error. Any failing run prints the exact
`--scenario`/`--seed` pair that reproduces it byte-identically.

    python -m arbius_tpu.sim                         # clean, seed 0
    python -m arbius_tpu.sim --scenario rpc-flap --seed 7
    python -m arbius_tpu.sim --scenario all --seeds 3 --json
    python -m arbius_tpu.sim --scenario fleet-race   # 2-miner fleet
    python -m arbius_tpu.sim --flood 10000           # 10k fleet soak
    python -m arbius_tpu.sim --flood 10000 --slo time_to_commit_p99=300
    python -m arbius_tpu.sim --list                  # scenario catalog
    python -m arbius_tpu.sim --inject-bug double-commit   # must exit 1
"""
# detlint: enforce[DET101,DET102,DET103,DET105]
from __future__ import annotations

import argparse
import json
import sys

from arbius_tpu.analysis.cli import EXIT_CLEAN, EXIT_FINDINGS, EXIT_USAGE


def build_arg_parser(p: argparse.ArgumentParser | None = None
                     ) -> argparse.ArgumentParser:
    if p is None:
        p = argparse.ArgumentParser(
            prog="simsoak", description=__doc__,
            formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--scenario", default="clean",
                   help="scenario name, 'all' for the full catalog, or "
                        "'tier1' for the acceptance matrix (default: clean)")
    p.add_argument("--seed", type=int, default=0,
                   help="base scenario seed (default: 0)")
    p.add_argument("--seeds", type=int, default=1,
                   help="soak mode: run seeds seed..seed+N-1 per scenario "
                        "(default: 1)")
    p.add_argument("--tasks", type=int, default=None,
                   help="override the scenario's task count")
    p.add_argument("--json", action="store_true",
                   help="machine-readable report (stable key order; "
                        "byte-identical for identical scenario+seed)")
    p.add_argument("--list", action="store_true",
                   help="list the scenario catalog and exit")
    p.add_argument("--inject-bug", default=None,
                   help="run with a deliberately broken node (checker "
                        "regression); known: double-commit, "
                        "racy-counter, double-lease, span-gap")
    p.add_argument("--flood", type=int, default=None, metavar="N",
                   help="fleet flood soak (docs/fleet.md): push N task "
                        "lifecycles through a fleet over the in-process "
                        "engine and audit bounded worker backlogs, "
                        "lease settlement, commit dedupe, and the "
                        "byte-deterministic SLO percentile report "
                        "(docs/fleetscope.md) (e.g. --flood 10000)")
    p.add_argument("--workers", type=int, default=4,
                   help="fleet size for --flood (default: 4)")
    p.add_argument("--slo", default=None, metavar="K=V[,K=V...]",
                   help="SLO thresholds for --flood (chain seconds; "
                        "docs/fleetscope.md): queue_wait_p95, "
                        "time_to_commit_p99, steal_lag_p99 — a "
                        "breached objective fails the run (SLO101), "
                        "e.g. --slo time_to_commit_p99=120")
    p.add_argument("--healthwatch", action="store_true",
                   help="run the live alert engine on every node "
                        "(docs/healthwatch.md): SIM113 audits the "
                        "fault→alert coverage — every injected fault "
                        "class must raise its mapped alert, clean "
                        "runs must raise none; implied by "
                        "--inject-bug silent-fault")
    p.add_argument("--witness", action="store_true",
                   help="instrument the node with the conclint runtime "
                        "witness (docs/concurrency.md): SIM110 audits "
                        "the observed lock-order graph and watched-attr "
                        "writes; implied by --inject-bug racy-counter")
    p.add_argument("--witness-out", default=None,
                   help="write the merged witness report (all runs) as "
                        "JSON — feed it to `conclint --witness-report` "
                        "to confirm/downgrade static CONC401 findings; "
                        "implies --witness")
    p.add_argument("--workdir", default=None,
                   help="directory for node sqlite checkpoints (default: "
                        "a temporary directory; crash-restart scenarios "
                        "need durable files either way)")
    return p


def _resolve_scenarios(name: str):
    from arbius_tpu.sim.scenario import (
        FLEET_TIER1,
        SCENARIOS,
        TIER1_MATRIX,
        get_scenario,
    )

    if name == "all":
        return [SCENARIOS[k] for k in sorted(SCENARIOS)]
    if name == "tier1":
        return [SCENARIOS[k] for k in TIER1_MATRIX + FLEET_TIER1]
    return [get_scenario(name)]


def collect(ns: argparse.Namespace):
    """Run the requested (scenario × seed) grid; findings are the
    invariant violations across every run. Returns (exit_code, findings)
    with lint_main's short-circuit convention; run summaries ride on
    `ns` for render()."""
    import os
    import tempfile

    from arbius_tpu.node import MinerNode
    from arbius_tpu.sim.bugs import INJECTABLE_BUGS
    from arbius_tpu.sim.harness import run_scenario
    from arbius_tpu.sim.invariants import check_all, summarize
    from arbius_tpu.sim.scenario import SCENARIOS

    ns._runs = []
    ns._flood = None
    if ns.list:
        for name in sorted(SCENARIOS):
            s = SCENARIOS[name]
            print(f"{name:15s} tasks={s.tasks:<3d} {s.description}")
        return EXIT_CLEAN, []
    if ns.slo is not None and ns.flood is None:
        # fail-closed: silently ignoring a declared objective is the
        # exact bug the SLO layer exists to prevent
        print("simsoak: --slo only applies to --flood (scenario runs "
              "are audited by the SIM1xx invariants)", file=sys.stderr)
        return EXIT_USAGE, []
    node_cls = MinerNode
    if ns.inject_bug is not None:
        node_cls = INJECTABLE_BUGS.get(ns.inject_bug)
        if node_cls is None:
            print(f"simsoak: unknown --inject-bug {ns.inject_bug!r} "
                  f"(known: {', '.join(sorted(INJECTABLE_BUGS))})",
                  file=sys.stderr)
            return EXIT_USAGE, []
    if ns.flood is not None:
        if ns.flood < 1 or ns.workers < 1:
            print("simsoak: --flood and --workers must be >= 1",
                  file=sys.stderr)
            return EXIT_USAGE, []
        from arbius_tpu.node.config import ConfigError, SLOConfig
        from arbius_tpu.sim.fleet import FleetFloodHarness, flood_findings

        slo = SLOConfig()
        if ns.slo is not None:
            # only the chain-time objectives the deterministic flood
            # report measures — accepting e.g. chip_idle_fraction here
            # would "validate" an objective the run can never evaluate
            flood_keys = ("queue_wait_p95", "time_to_commit_p99",
                          "steal_lag_p99")
            try:
                kwargs = {}
                for part in ns.slo.split(","):
                    key, _, value = part.partition("=")
                    key = key.strip()
                    if key not in flood_keys:
                        raise ValueError(
                            f"{key!r} is not a --flood objective "
                            f"(known: {', '.join(flood_keys)})")
                    kwargs[key] = float(value)
                slo = SLOConfig(**kwargs)
            except (TypeError, ValueError, ConfigError) as e:
                print(f"simsoak: bad --slo {ns.slo!r}: {e}",
                      file=sys.stderr)
                return EXIT_USAGE, []
        with tempfile.TemporaryDirectory(prefix="simflood-") as tmp:
            harness = FleetFloodHarness(ns.flood, ns.workers,
                                        ns.workdir or tmp, seed=ns.seed,
                                        slo=slo)
            try:
                ns._flood = harness.run()
            finally:
                harness.close()
        return None, flood_findings(ns._flood)
    try:
        scenarios = _resolve_scenarios(ns.scenario)
    except KeyError as e:
        print(f"simsoak: {e.args[0]}", file=sys.stderr)
        return EXIT_USAGE, []
    if ns.seeds < 1:
        print("simsoak: --seeds must be >= 1", file=sys.stderr)
        return EXIT_USAGE, []
    from arbius_tpu.sim.bugs import FAULT_BUGS, FLEET_BUGS

    if ns.inject_bug in FLEET_BUGS and not any(
            s.fleet is not None for s in scenarios):
        # a fleet-only bug demonstrates nothing outside a fleet
        from arbius_tpu.sim.scenario import get_scenario

        scenarios = [get_scenario("fleet-race")]
    if ns.inject_bug in FAULT_BUGS:
        # a monitoring blackout demonstrates nothing unless faults are
        # actually being injected for healthwatch to miss
        from arbius_tpu.sim.scenario import FaultSpec, get_scenario

        if all(s.faults == FaultSpec() for s in scenarios):
            scenarios = [get_scenario("rpc-flap")]
    # silent-fault exists to be caught by SIM113 — running it without
    # the alert engine would test nothing (the racy-counter pattern)
    healthwatch = ns.healthwatch or ns.inject_bug in FAULT_BUGS

    findings = []
    # racy-counter exists to be caught by the witness's SIM110 —
    # running it uninstrumented would test nothing
    witness = ns.witness or ns.witness_out is not None \
        or ns.inject_bug == "racy-counter"
    reports = []
    with tempfile.TemporaryDirectory(prefix="simnet-") as tmp:
        workdir = ns.workdir or tmp
        for scenario in scenarios:
            scenario = scenario.with_tasks(ns.tasks)
            for seed in range(ns.seed, ns.seed + ns.seeds):
                if scenario.fleet is not None:
                    from arbius_tpu.sim.fleet import run_fleet_scenario

                    fleet_dir = os.path.join(
                        workdir, f"{scenario.name}-{seed}")
                    os.makedirs(fleet_dir, exist_ok=True)
                    result = run_fleet_scenario(scenario, seed,
                                                workdir=fleet_dir,
                                                node_cls=node_cls,
                                                healthwatch=healthwatch)
                else:
                    db_path = os.path.join(
                        workdir, f"{scenario.name}-{seed}.sqlite")
                    result = run_scenario(scenario, seed,
                                          db_path=db_path,
                                          node_cls=node_cls,
                                          witness=witness,
                                          healthwatch=healthwatch)
                if result.witness_report is not None:
                    reports.append(result.witness_report)
                run_findings = check_all(result)
                findings.extend(run_findings)
                summary = summarize(result)
                summary["findings"] = len(run_findings)
                ns._runs.append(summary)
                if run_findings:
                    print(f"simsoak: {len(run_findings)} invariant "
                          f"violation(s) — reproduce with: {result.repro()}",
                          file=sys.stderr)
    if ns.witness_out is not None:
        from arbius_tpu.analysis.conc.witness import merge_reports

        with open(ns.witness_out, "w", encoding="utf-8",
                  newline="\n") as fh:
            json.dump(merge_reports(reports), fh, indent=2,
                      sort_keys=True)
            fh.write("\n")
        print(f"simsoak: witness report written to {ns.witness_out}",
              file=sys.stderr)
    return None, findings


def render(ns: argparse.Namespace, findings, out) -> None:
    runs = getattr(ns, "_runs", [])
    flood = getattr(ns, "_flood", None)
    if ns.json:
        doc = {"version": 1,
               "findings": [f.to_json() for f in findings],
               "runs": runs}
        if flood is not None:
            doc["flood"] = flood
        out.write(json.dumps(doc, indent=2, sort_keys=True) + "\n")
        return
    if flood is not None:
        depths = " ".join(f"{w}={d}" for w, d
                          in sorted(flood["max_backlog"].items()))
        out.write(
            f"flood           tasks={flood['tasks']:<6d} "
            f"workers={flood['workers']} rounds={flood['rounds']:<5d} "
            f"claimed={flood['claimed']:<6d} "
            f"dedup={flood['commit_dedup']}\n"
            f"  worker backlog bound {flood['backlog_bound']}, "
            f"max depths [{depths}], peak pending leases "
            f"{flood['max_pending_leases']}\n"
            f"  sqlite commits per worker "
            f"{dict(sorted(flood['db_commits'].items()))} "
            f"(one fsync per tick, not per job)\n")
        slo = flood.get("slo")
        if slo is not None:
            def _pcts(block):
                return " ".join(
                    f"{p}={block.get(p)}" for p in ("p50", "p95", "p99"))
            out.write(
                f"  slo {'OK' if slo.get('ok') else 'BREACHED'}: "
                f"queue-wait [{_pcts(slo['queue_wait_seconds'])}] "
                f"time-to-commit "
                f"[{_pcts(slo['time_to_commit_seconds'])}] "
                f"steal-lag [{_pcts(slo['steal_lag_seconds'])}] "
                "(chain seconds, fixed-bucket estimate — "
                "docs/fleetscope.md)\n")
    for r in runs:
        terminal = " ".join(f"{k}={v}" for k, v in r["terminal"].items())
        faults = sum(r["faults_injected"].values())
        out.write(
            f"{r['scenario']:15s} seed={r['seed']:<4d} "
            f"tasks={r['tasks']:<3d} rounds={r['rounds']:<4d} "
            f"faults={faults:<4d} restarts={r['restarts']} "
            f"[{terminal}]\n")
    for f in findings:
        out.write(f.text() + "\n")
    if findings:
        out.write(f"simsoak: {len(findings)} invariant violation(s)\n")


def run(ns: argparse.Namespace, out=None) -> int:
    out = out or sys.stdout
    rc, findings = collect(ns)
    if rc is not None:
        return rc
    render(ns, findings, out)
    return EXIT_FINDINGS if findings else EXIT_CLEAN


def main(argv: list[str] | None = None) -> int:
    from arbius_tpu.analysis.cli import cli_entry

    return cli_entry(build_arg_parser, collect, render, argv)


if __name__ == "__main__":
    sys.exit(main())
