"""simnet — deterministic fault-injection simulation of the miner lifecycle.

FoundationDB-style deterministic simulation testing for the node: a real
`MinerNode` mines over the full signed-tx JSON-RPC stack (wallet →
EIP-1559 RLP → `DevnetNode` → EngineV1 state machine) while a **fault
plane** wraps its three I/O edges —

  chain RPC   injected latency, transport timeouts/5xx, lost-response
              txs, delayed/replayed/reorged event logs
  pinners     failures, stalls, CID-mismatch responses
  runners     slow solves, crashes mid-batch

— plus whole-process crash-restarts (the node is torn down mid-flight
and rebooted from its sqlite checkpoint, `node/db.py`). Everything is
derived from one scenario seed through a counter-mode SHA-256 PRNG and
a virtual clock over the engine's chain time: no wall clock, no host
RNG, no filesystem-order dependence — the whole subsystem carries
`detlint: enforce` and a failing run is reproduced byte-identically by
its `--seed`/`--scenario` pair.

After a scenario drains to quiescence, **invariant checkers** (SIM1xx,
`sim/invariants.py`) audit the recorded tx trace, the obs journal, and
the devnet's terminal state: task conservation, commit-strictly-before-
reveal, no duplicate commitment per (validator, taskid), stake never
negative, expretry-bounded retries, CID stability across crash-restart,
and token conservation.

Front doors: `python -m arbius_tpu.sim` and `tools/simsoak.py` (both on
the detlint/graphlint 0/1/2 exit contract via `tools/_common.py`
`lint_main`). Docs: docs/fault-injection.md.
"""
# detlint: enforce[DET101,DET102,DET103,DET105]
from arbius_tpu.sim.clock import VirtualClock
from arbius_tpu.sim.faults import FaultPlane, SimCrash
from arbius_tpu.sim.harness import SimHarness, run_scenario
from arbius_tpu.sim.invariants import SimFinding, check_all
from arbius_tpu.sim.rng import SimRng
from arbius_tpu.sim.scenario import SCENARIOS, FaultSpec, Scenario

__all__ = [
    "SCENARIOS", "FaultPlane", "FaultSpec", "Scenario", "SimCrash",
    "SimFinding", "SimHarness", "SimRng", "VirtualClock", "check_all",
    "run_scenario",
]
