"""Declarative scenarios: seed + fault schedule + workload spec.

A `Scenario` is everything a run needs besides its seed: how many task
lifecycles to drive, which fraction are adversarial (front-run with a
wrong CID → contestation path) or malformed (hydration failure →
invalid path), and the `FaultSpec` rates the fault plane draws against.
All rates are *per-opportunity* probabilities evaluated on named rng
streams, so two scenarios with one differing rate share every other
decision at the same seed.

The named catalog (`SCENARIOS`) is the tier-1 matrix: `clean` must end
with every delivered task claimed (strict mode); the fault mixes must
end with every task in exactly one accounted terminal state and every
SIM1xx invariant intact. Reproduce any run byte-identically with
`python -m arbius_tpu.sim --scenario <name> --seed <n>`.
"""
# detlint: enforce[DET101,DET102,DET103,DET105]
from __future__ import annotations

from dataclasses import asdict, dataclass, field, replace


@dataclass(frozen=True)
class FaultSpec:
    """Per-opportunity fault rates for the three I/O edges + crash."""

    # -- chain RPC edge (FaultTransport) --------------------------------
    tx_error_rate: float = 0.0        # sendRawTransaction fails BEFORE landing
    tx_lost_response_rate: float = 0.0  # tx lands, response is dropped
    view_error_rate: float = 0.0      # eth_call answers 5xx
    poll_error_rate: float = 0.0      # eth_getLogs answers 5xx
    latency_max: int = 0              # virtual seconds injected per RPC, 0..max
    event_delay_rate: float = 0.0     # log held back 1-3 polls (reorders)
    event_replay_rate: float = 0.0    # log delivered again next poll
    reorg_every: int = 0              # every N polls, redeliver recent blocks
    reorg_depth: int = 4              # how many trailing blocks a reorg replays
    # -- pinner edge (SimPinner) ----------------------------------------
    pin_fail_rate: float = 0.0        # pin request 5xx
    pin_stall_seconds: int = 0        # virtual stall per pin attempt, 0..max
    pin_mismatch_rate: float = 0.0    # service answers a different root CID
    # -- runner edge (FaultyRunner) -------------------------------------
    runner_slow_seconds: int = 0      # virtual seconds per solve, 0..max
    runner_crash_rate: float = 0.0    # runner raises mid-batch
    # -- decode edge (FaultyTextRunner, docs/text-serving.md) -----------
    decode_stall_rate: float = 0.0    # text solve decodes zero bytes
    # -- process crash ---------------------------------------------------
    crash_after_commit: int | None = None  # kill node after Nth commit lands


@dataclass(frozen=True)
class FleetSpec:
    """Fleet topology + failure schedule for multi-node scenarios
    (docs/fleet.md): N workers race one coordinator-owned event stream
    through the shared lease table; pause windows model partitions
    (the member can reach neither the chain nor the lease db for those
    rounds), and the coordinator crash-restart proves lease recovery."""

    workers: int = 2
    lease_ttl: int = 30            # chain-seconds before a lease is stealable
    wallet_mode: str = "per-worker"
    max_leases: int = 2            # pulls per worker per tick
    backlog: int = 4               # worker task/solve backlog bound
    max_attempts: int = 4          # lease deliveries before failed
    # (worker_index, from_round, to_round): that worker skips its ticks
    # in [from, to) — its leases expire and MUST be stolen
    pause_worker: tuple = ()
    # (from_round, to_round): the coordinator skips its ticks — intake
    # stalls but leased work keeps mining
    pause_coordinator: tuple = ()
    # round at which the coordinator is killed and rebuilt from the
    # on-disk lease table + a from-genesis event re-poll
    crash_coordinator_round: int | None = None


@dataclass(frozen=True)
class Scenario:
    name: str
    description: str = ""
    tasks: int = 8                 # lifecycles to drive
    fee_wad: int = 1               # task fee in wad (fees exercise splits)
    evil_rate: float = 0.0         # fraction front-run with a wrong CID
    invalid_rate: float = 0.0      # fraction submitted with broken input
    strict: bool = False           # every normal task MUST end claimed
    tick_seconds: int = 5          # virtual seconds between rounds
    max_rounds: int = 600          # liveness bound (SIM108 if exceeded)
    burst: int = 1                 # tasks submitted per round (flood > 1)
    families: int = 1              # registered model families to mix
    template: str = "anythingv3"   # task template the workload speaks
    sched: bool = False            # costsched packer on (docs/scheduler.md)
    fleet: FleetSpec | None = None  # multi-node fleet run (docs/fleet.md)
    faults: FaultSpec = field(default_factory=FaultSpec)

    def to_json(self) -> dict:
        return asdict(self)

    def with_tasks(self, tasks: int | None) -> "Scenario":
        return self if tasks is None else replace(self, tasks=tasks)


SCENARIOS: dict[str, Scenario] = {s.name: s for s in (
    Scenario(
        name="clean",
        description="no faults; strict: every delivered task must be "
                    "solved, revealed, and claimed",
        strict=True),
    Scenario(
        name="rpc-flap",
        description="flaky endpoint: transport errors, lost tx "
                    "responses, 5xx views/polls, injected latency",
        faults=FaultSpec(tx_error_rate=0.12, tx_lost_response_rate=0.10,
                         view_error_rate=0.03, poll_error_rate=0.15,
                         latency_max=7)),
    Scenario(
        name="pin-fail",
        description="pinning service misbehaves: 5xx, stalls, CID "
                    "mismatches; slow solves ride along",
        faults=FaultSpec(pin_fail_rate=0.30, pin_stall_seconds=5,
                         pin_mismatch_rate=0.15, runner_slow_seconds=3)),
    Scenario(
        name="reorg",
        description="event plane chaos: delayed + replayed logs and "
                    "shallow log-replay reorgs every few polls",
        faults=FaultSpec(event_delay_rate=0.25, event_replay_rate=0.20,
                         reorg_every=3, reorg_depth=4)),
    Scenario(
        name="crash-restart",
        description="node process killed right after its 2nd commit "
                    "lands; rebooted from the sqlite checkpoint and must "
                    "reveal the SAME CID (SIM106)",
        tasks=6, strict=True,
        faults=FaultSpec(crash_after_commit=2)),
    Scenario(
        name="contested",
        description="an adversary front-runs half the tasks with a "
                    "wrong CID; the node must contest, vote, and finish "
                    "every dispute",
        tasks=6, evil_rate=0.5, strict=True),
    Scenario(
        name="sched-flood",
        description="mixed-family task flood under the costsched packer: "
                    "two model families, bursts of 4, varied shapes and "
                    "fees — the scheduler reorders buckets freely and "
                    "every SIM1xx invariant (incl. per-task CID "
                    "stability) must hold regardless",
        tasks=16, burst=4, families=2, sched=True, strict=True,
        faults=FaultSpec(latency_max=3, runner_slow_seconds=2)),
    Scenario(
        name="text-stream",
        description="text-generation flood (docs/text-serving.md): "
                    "token-progress solve times under the fault plane, "
                    "mixed decode budgets and samplers, costsched "
                    "packing sequence buckets — decode stalls must "
                    "surface through healthwatch (SIM113) and every "
                    "SIM1xx invariant must hold",
        tasks=12, burst=3, strict=True, sched=True, template="textgen",
        faults=FaultSpec(decode_stall_rate=0.35, runner_slow_seconds=2,
                         latency_max=3)),
    Scenario(
        name="fleet-race",
        description="two miners race one coordinator-owned event "
                    "stream through the shared lease table (bursts of "
                    "4, so both actually pull work): every task "
                    "claimed exactly once fleet-wide, no cross-worker "
                    "double-commit (SIM111)",
        tasks=8, burst=4, strict=True, fleet=FleetSpec(workers=2)),
    Scenario(
        name="fleet-partition",
        description="worker 1 AND the coordinator partitioned mid-run: "
                    "worker 1's leases expire and worker 0 steals them "
                    "directly (no coordinator sweep available), task "
                    "intake stalls and then catches up — no task lost "
                    "either way",
        tasks=12, burst=4, strict=True,
        fleet=FleetSpec(workers=2, lease_ttl=20,
                        pause_worker=(1, 3, 9),
                        pause_coordinator=(4, 10))),
    Scenario(
        name="fleet-coord-crash",
        description="the coordinator is killed mid-run and rebuilt "
                    "from the on-disk lease table + a from-genesis "
                    "event re-poll: every in-flight lease recovered, "
                    "every task still claimed",
        tasks=8, burst=3, strict=True,
        fleet=FleetSpec(workers=2, crash_coordinator_round=4)),
    Scenario(
        name="chaos",
        description="everything at once, at moderated rates — the soak "
                    "mix for tools/simsoak.py",
        tasks=10, evil_rate=0.2, invalid_rate=0.2,
        faults=FaultSpec(tx_error_rate=0.08, tx_lost_response_rate=0.05,
                         poll_error_rate=0.10, latency_max=5,
                         event_delay_rate=0.15, event_replay_rate=0.10,
                         reorg_every=5, reorg_depth=3,
                         pin_fail_rate=0.15, pin_stall_seconds=3,
                         pin_mismatch_rate=0.05, runner_slow_seconds=3,
                         runner_crash_rate=0.08)),
)}

# the acceptance matrix every PR must keep green (tests/test_sim.py) —
# the FULL catalog since the staged solve executor landed: chaos (every
# fault at once) is exactly the mix that would expose a pipeline
# ordering bug, so it gates tier-1 too
TIER1_MATRIX = ("clean", "rpc-flap", "pin-fail", "reorg",
                "crash-restart", "contested", "chaos")

# the fleet half of the matrix (docs/fleet.md): multi-node scenarios
# driven by sim/fleet.py's harness and audited by SIM111 on top of the
# applicable SIM1xx set; `--scenario tier1` runs both halves
FLEET_TIER1 = ("fleet-race", "fleet-partition", "fleet-coord-crash")


def get_scenario(name: str) -> Scenario:
    try:
        return SCENARIOS[name]
    except KeyError:
        raise KeyError(
            f"unknown scenario {name!r} — known: "
            f"{', '.join(sorted(SCENARIOS))}") from None
