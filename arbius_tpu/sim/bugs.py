"""Deliberately broken nodes — regression ammunition for the checkers.

A checker that has never caught anything is a checker you can't trust.
Each class here injects one protocol violation into an otherwise-real
`MinerNode`; the tier-1 regression (tests/test_sim.py) and the CLI's
`--inject-bug` flag run a scenario with the buggy node and require the
matching SIM1xx finding to fire with a readable diff. These nodes must
NEVER be reachable from production wiring — only the sim harness's
`node_cls` seam constructs them.
"""
# detlint: enforce[DET101,DET102,DET103,DET105]
from __future__ import annotations

import threading

from arbius_tpu.chain.devnet import DevnetError
from arbius_tpu.node import MinerNode
from arbius_tpu.node.chain_client import EngineError


class DoubleCommitMinerNode(MinerNode):
    """Signals a SECOND commitment — for a corrupted CID — next to every
    real one: the double-commit a slashing-grade bug would produce.
    The chain happily accepts both (they are different hashes), so only
    the SIM103 checker can see the violation."""

    @staticmethod
    def _corrupt(cid: str) -> str:
        flipped = format(int(cid[-1], 16) ^ 0x1, "x")
        return cid[:-1] + flipped

    def _commit_reveal(self, taskid: str, cid: str, t_start: int,
                       **kwargs) -> None:
        if self.chain.get_solution(taskid) is None:
            wrong = self._corrupt(cid)
            second = self.chain.generate_commitment(taskid, wrong)
            try:
                self.chain.signal_commitment(second)
            except (EngineError, DevnetError):  # pragma: no cover
                pass
        super()._commit_reveal(taskid, cid, t_start, **kwargs)


class RacyCounterMinerNode(MinerNode):
    """Bumps an UNLOCKED counter from the tick thread and from its own
    spawned daemon — one injected bug, two gates that must both fail
    closed (docs/concurrency.md): conclint's static CONC401 (the
    regression test strips the waivers below and requires the finding),
    and SIM110 at runtime (the witness watches `racy_counter` via
    WITNESS_WATCH_ATTRS and must record lock-free writes from two
    roots). The counter feeds nothing — CIDs stay byte-identical."""

    WITNESS_WATCH_ATTRS = ("racy_counter",)

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.racy_counter = 0
        self._racy_stop = threading.Event()
        self._racy_thread = threading.Thread(
            target=self._racy_run, daemon=True, name="racy-counter")
        self._racy_thread.start()

    def _racy_run(self) -> None:
        while not self._racy_stop.wait(0.0005):
            # detlint: allow[CONC301,CONC401] deliberate injected race —
            # regression ammunition; tests strip this waiver and require
            # the static finding, and the simnet witness must see it
            self.racy_counter += 1

    def tick(self) -> int:
        # detlint: allow[CONC301,CONC401] deliberate injected race (the
        # other side — see _racy_run above)
        self.racy_counter += 1
        return super().tick()

    def close(self) -> None:
        self._racy_stop.set()
        self._racy_thread.join(timeout=2.0)
        super().close()


class DoubleLeaseWorkerNode(MinerNode):
    """A fleet worker that violates the lease plane's exclusivity: each
    tick it scans the shared commit-rights table and signals its OWN
    commitment for every task another worker already committed — acting
    as if it held the lease itself (the double-lease a broken lease
    claim would produce). The chain accepts the commitments (different
    validators hash differently), so only SIM111's cross-worker dedupe
    audit can see the violation; it must fail closed. Only meaningful
    under a fleet scenario (the CLI forces one)."""

    def tick(self) -> int:
        feed = getattr(self, "task_feed", None)
        if feed is not None:
            seen = getattr(self, "_double_leased", None)
            if seen is None:
                seen = self._double_leased = set()
            for row in feed.leases.commit_rows():
                tid = row["taskid"]
                if row["worker"] == feed.worker_id or tid in seen:
                    continue
                seen.add(tid)
                try:
                    second = self.chain.generate_commitment(
                        tid, row["cid"])
                    self.chain.signal_commitment(second)
                except (EngineError, DevnetError):  # pragma: no cover
                    pass
        return super().tick()


class SpanGapWorkerNode(MinerNode):
    """A fleet worker whose obs drops the `lease_hop` adoption events —
    the worker-side half of the cross-process trace chain
    (docs/fleetscope.md). Work still flows: leases are acquired, tasks
    solve, CIDs land byte-identically, SIM101-111 all hold — but every
    acquire/steal hop the shared lease table granted this worker is now
    missing its journal adoption, so the task's span chain has a gap
    only SIM112's trace-completeness audit can see. It must fail
    closed, and fail ALONE."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        real_event = self.obs.event

        def dropping(kind: str, **fields) -> None:
            if kind == "lease_hop":
                return  # the injected trace gap
            real_event(kind, **fields)

        self.obs.event = dropping


class SilentFaultMinerNode(MinerNode):
    """A miner whose health monitoring went dark: the healthwatch
    engine still evaluates (gauges keep moving), but every
    `alert_transition` journal event is swallowed — the flight
    recorder shows a node that never raised an alert while the fault
    plane was actively injecting failures. Work still flows, retries
    still journal, CIDs land byte-identically, SIM101-112 all hold —
    the fault is SILENT, which is exactly the condition SIM113's
    coverage invariant exists to catch: a fault class that raised no
    mapped alert must fail the run, and fail it ALONE. (The CLI forces
    a fault-injecting scenario + healthwatch, sim/cli.py.)"""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        real_event = self.obs.event

        def muting(kind: str, **fields) -> None:
            if kind == "alert_transition":
                return  # the injected monitoring blackout
            real_event(kind, **fields)

        self.obs.event = muting


INJECTABLE_BUGS = {
    "double-commit": DoubleCommitMinerNode,
    "racy-counter": RacyCounterMinerNode,
    "double-lease": DoubleLeaseWorkerNode,
    "span-gap": SpanGapWorkerNode,
    "silent-fault": SilentFaultMinerNode,
}

# bugs that only make sense inside a fleet (the CLI swaps the scenario
# to a fleet one when needed)
FLEET_BUGS = ("double-lease", "span-gap")

# bugs that only demonstrate anything under an actively fault-injecting
# scenario with the healthwatch engine on (the CLI swaps a fault-free
# scenario for rpc-flap and implies --healthwatch)
FAULT_BUGS = ("silent-fault",)
