"""Fleet simnet — multi-node scenarios and the task-flood soak.

Two harnesses share this module:

`FleetSimHarness` extends the single-node `SimHarness` world with a
real fleet over the signed-tx stack: a coordinator (RpcChain polling
through the fault plane) feeding the shared lease table, and N worker
`MinerNode`s — each with its own wallet, its own sqlite checkpoint,
and its own `FaultTransport` into the one devnet — racing the same
event stream. Scenario `FleetSpec`s add the fleet failure modes:
worker partitions (a paused worker's leases expire and MUST be stolen
within the TTL), coordinator partitions (intake stalls, mining
continues), and a coordinator crash-restart that rebuilds from the
on-disk lease table plus a from-genesis event re-poll. SIM111 audits
the fleet invariants on top of the applicable SIM1xx set.

`FleetFloodHarness` is the load half (`tools/simsoak.py --flood N`):
10k+ tasks through a fleet over the in-process engine facade
(`LocalChain` — no signing, the protocol-fidelity-under-faults job
belongs to the signed-stack scenarios above). It exists to prove the
operational bounds at load: worker task/solve backlogs never exceed
their configured bound (the CONC302 story at fleet scale — the lease
table, not worker memory, absorbs the flood), every lease settles,
commit dedupe holds, and NodeDB's one-fsync-per-tick batching keeps
the sqlite commit count sub-linear in tasks. Reports are
byte-identical per (tasks, workers, seed).
"""
# detlint: enforce[DET101,DET102,DET103,DET105]
from __future__ import annotations

import os

from arbius_tpu.chain.rpc_client import EngineRpcClient
from arbius_tpu.chain.wallet import Wallet
from arbius_tpu.fleet import (
    FleetCoordinator,
    LeaseFeed,
    LeaseTable,
    make_worker_id,
)
from arbius_tpu.node import (
    MinerNode,
    MiningConfig,
    ModelConfig,
    ModelRegistry,
    NodeDB,
    RegisteredModel,
)
from arbius_tpu.node.config import FleetConfig, PipelineConfig, SLOConfig
from arbius_tpu.node.rpc_chain import RpcChain
from arbius_tpu.obs import use_obs
from arbius_tpu.obs.fleetscope import (
    ObsSidecar,
    evaluate_slo,
    latency_summary,
    sidecar_path,
)
from arbius_tpu.sim.faults import (
    AuditedRpcChain,
    FaultTransport,
    FaultyRunner,
    SimPinner,
)
from arbius_tpu.sim.harness import (
    CHAIN_ID,
    KEY_MINER,
    _HEARTBEATS,
    SimHarness,
    SimResult,
)
from arbius_tpu.node.db import Job
from arbius_tpu.sim.scenario import Scenario
from arbius_tpu.templates.engine import load_template

# coordinator wallet: polls logs, never transacts — needs no funding
KEY_COORD = "0x" + "c0" * 32


def worker_key(index: int) -> str:
    """Worker 0 IS the base harness miner (KEY_MINER), so the plane's
    crash trigger and the single-node checkers keep their anchor;
    workers 1.. vary the last byte."""
    if index == 0:
        return KEY_MINER
    return "0x" + "a1" * 31 + f"{0xb0 + index:02x}"


def _in_window(r: int, window: tuple) -> bool:
    return bool(window) and window[0] <= r < window[1]


class FleetSimHarness(SimHarness):
    """SimHarness world + a fleet instead of one node. The scenario
    MUST carry a FleetSpec. Workers run with the staged pipeline OFF
    (the fleet layer is schedule-transparent; pipeline×fault coverage
    is the single-node matrix's job).

    `aot_dir` (docs/compile-cache.md) swaps the hash-fake FaultyRunner
    for meshsolve's image probe — a REAL jitted XLA program, gated by
    the fault plane exactly like the fake — and points every worker's
    `aot_cache` config at that ONE shared directory: the first worker
    to dispatch a bucket compiles and publishes it, the rest
    deserialize, and SIM101-112 must hold over the whole run with zero
    `aot_cache_reject` events in a clean scenario
    (tests/test_aotcache.py pins it)."""

    def __init__(self, scenario: Scenario, seed: int, workdir: str,
                 node_cls: type[MinerNode] = MinerNode,
                 aot_dir: str | None = None,
                 healthwatch: bool = False):
        if scenario.fleet is None:
            raise ValueError(f"scenario {scenario.name!r} has no fleet "
                             "spec — use SimHarness")
        self.workdir = workdir
        self.aot_dir = aot_dir
        self.workers: list[MinerNode] = []
        self.feeds: list[LeaseFeed] = []
        self.sidecars: list[ObsSidecar] = []
        self.leases: LeaseTable | None = None
        self.coordinator: FleetCoordinator | None = None
        self._ticks = 0
        super().__init__(scenario, seed,
                         db_path=os.path.join(workdir, "worker-0.sqlite"),
                         node_cls=node_cls, pipeline=False,
                         witness=False, healthwatch=healthwatch)

    # -- fleet construction ----------------------------------------------
    def _spawn_node(self) -> None:
        """Called once from the base __init__: build the lease plane,
        the coordinator, and every worker. (The base _restart_node path
        is unused — fleet failure modes are pause windows and the
        coordinator crash, driven from _tick.)"""
        spec = self.scenario.fleet
        self.fleet_cfg = FleetConfig(
            enabled=True, workers=spec.workers,
            lease_ttl=spec.lease_ttl, wallet_mode=spec.wallet_mode,
            lease_db=os.path.join(self.workdir, "leases.sqlite"),
            max_leases=spec.max_leases, backlog=spec.backlog,
            max_attempts=spec.max_attempts)
        self.leases = LeaseTable(self.fleet_cfg.lease_db,
                                 self.fleet_cfg.busy_timeout_ms)
        self.coord_wallet = Wallet.from_hex(KEY_COORD)
        self.coordinator = self._build_coordinator()
        from arbius_tpu.chain.fixedpoint import WAD

        for i in range(spec.workers):
            wallet = Wallet.from_hex(worker_key(i))
            if i > 0:
                # extra workers join genesis: funded and staked exactly
                # like the base miner (worker 0 rides the base genesis)
                self.token.mint(wallet.address, 1_000 * WAD)
                self.token.approve(wallet.address.lower(),
                                   self.engine.ADDRESS, 10**30)
                self.engine.validator_deposit(wallet.address,
                                              wallet.address, 400 * WAD)
            self.workers.append(self._build_worker(i, wallet))
        self.node = self.workers[0]
        self.result.db = self.node.db
        self.result.fleet_workers = [w.chain.address
                                     for w in self.workers]

    def _build_coordinator(self) -> FleetCoordinator:
        transport = FaultTransport(self.dev, self.plane)
        client = EngineRpcClient(transport, self.dev.engine_address,
                                 self.coord_wallet, chain_id=CHAIN_ID)
        chain = RpcChain(client, self.dev.token_address)
        coord = FleetCoordinator(chain, self.leases, self.model_ids,
                                 self.fleet_cfg)
        # a restarted coordinator is a NEW obs stream (its journal seqs
        # restart at 1), so each incarnation gets its own sidecar member
        # name — federation sees the restart honestly instead of
        # colliding seqs in one file (docs/fleetscope.md)
        member = "coordinator" if self.result.restarts == 0 \
            else f"coordinator-r{self.result.restarts}"
        coord.sidecar = ObsSidecar(sidecar_path(self.workdir, member),
                                   member, coord.obs)
        self.sidecars.append(coord.sidecar)
        return coord

    def _build_worker(self, index: int, wallet: Wallet) -> MinerNode:
        transport = FaultTransport(self.dev, self.plane)
        tx_guard = None
        if self.fleet_cfg.wallet_mode == "shared":
            wid = make_worker_id(index)
            tx_guard = lambda: self.leases.wallet_guard(  # noqa: E731
                wallet.address, wid)
        client = EngineRpcClient(transport, self.dev.engine_address,
                                 wallet, chain_id=CHAIN_ID,
                                 tx_guard=tx_guard)
        chain = AuditedRpcChain(client, self.dev.token_address,
                                self.plane)
        from arbius_tpu.node.config import AlertsConfig, AotCacheConfig

        cfg = MiningConfig(
            db_path=":memory:",  # unused: db object injected below
            models=tuple(ModelConfig(id=mid, template="anythingv3")
                         for mid in self.model_ids),
            compile_cache_dir=None,
            obs_journal_capacity=16384,
            retry_max_delay=self.result.retry_max_delay,
            pipeline=PipelineConfig(),
            aot_cache=AotCacheConfig(enabled=True, dir=self.aot_dir)
            if self.aot_dir else AotCacheConfig(),
            # per-member healthwatch (docs/healthwatch.md): every
            # worker runs its own alert engine; its state gauges ride
            # the sidecar export, so federate() merges fleet health
            alerts=AlertsConfig(enabled=True)
            if self.healthwatch else AlertsConfig(),
            canonical_batch=1)
        if self.aot_dir:
            # real XLA through the shared executable cache: the probe's
            # bytes are pure in (input, seed), so every SIM1xx check
            # audits unchanged whether a worker compiled or deserialized
            from arbius_tpu.parallel.meshsolve import ShardedImageProbe

            runner = ShardedImageProbe(gate=self.plane.runner_gate)
        else:
            runner = FaultyRunner(self.plane)
        registry = ModelRegistry()
        for mid in self.model_ids:
            registry.register(RegisteredModel(
                id=mid, template=load_template("anythingv3"),
                runner=runner))
        db = NodeDB(os.path.join(self.workdir,
                                 f"worker-{index}.sqlite"))
        node = self.node_cls(chain, cfg, registry, db=db, store=None,
                             pinner=SimPinner(self.plane))
        node._retry_sleep = self.clock.sleep
        wid = make_worker_id(index)
        feed = LeaseFeed(self.leases, wid, self.fleet_cfg).attach(node)
        sidecar = ObsSidecar(sidecar_path(self.workdir, wid), wid,
                             node.obs)
        feed.attach_sidecar(sidecar, every=4)
        self.feeds.append(feed)
        self.sidecars.append(sidecar)
        node.boot(skip_self_test=True)
        return node

    def _crash_coordinator(self) -> None:
        """Kill + replace the coordinator: the replacement opens the
        same on-disk lease table and re-polls events from genesis (the
        db's INSERT OR IGNORE absorbs the replay) — nothing but the
        poll cursor is lost, which is the lease-recovery claim."""
        self.plane.count("coordinator_crash")
        self.result.restarts += 1
        self.coordinator = self._build_coordinator()

    # -- driving -----------------------------------------------------------
    def _tick(self) -> int:
        spec = self.scenario.fleet
        self._ticks += 1
        r = self._ticks
        if spec.crash_coordinator_round is not None \
                and r == spec.crash_coordinator_round:
            self._crash_coordinator()
        if not _in_window(r, spec.pause_coordinator):
            self.coordinator.tick()
        done = 0
        for i, worker in enumerate(self.workers):
            if spec.pause_worker and spec.pause_worker[0] == i \
                    and _in_window(r, spec.pause_worker[1:]):
                continue
            done += worker.tick()
        return done

    def _pending_jobs(self) -> list:
        jobs = []
        for worker in self.workers:
            jobs.extend(j for j in worker.db.get_jobs(2**60, limit=1000)
                        if j.method not in _HEARTBEATS)
        counts = self.leases.counts()
        if counts.get("pending", 0) + counts.get("leased", 0) > 0:
            # unsettled leases are pending fleet work even when no
            # worker has pulled them yet — keep the drain loop alive
            # (due now: the next tick's pumps can act immediately)
            jobs.append(Job(id=-1, priority=0, waituntil=self.clock.now,
                            concurrent=False, method="fleet-lease",
                            data={}))
        return jobs

    def run(self) -> SimResult:
        result = super().run()
        for worker in self.workers[1:]:
            result.journal_events.extend(worker.obs.journal.events())
        result.journal_dropped = sum(w.obs.journal.dropped
                                     for w in self.workers)
        result.worker_dbs = [w.db for w in self.workers]
        result.lease_rows = [dict(r) for r in self.leases.rows()]
        result.lease_history = list(self.leases.history)
        result.lease_counts = self.leases.counts()
        result.commit_rows = [dict(r) for r in self.leases.commit_rows()]
        # final fleetscope flush: every member's last journal segment
        # lands before federation reads the sidecars (the files stay on
        # disk for post-mortems — result.sidecar_dir points at them)
        now = self.clock.now
        for feed in self.feeds:
            feed.flush_sidecar(now)
        if self.coordinator is not None and \
                self.coordinator.sidecar is not None:
            self.coordinator.sidecar.flush(now)
        for sidecar in self.sidecars:
            sidecar.close()
        result.sidecar_dir = self.workdir
        return result


def run_fleet_scenario(scenario: Scenario, seed: int, *, workdir: str,
                       node_cls: type[MinerNode] = MinerNode,
                       aot_dir: str | None = None,
                       healthwatch: bool = False) -> SimResult:
    """One-call front door for fleet scenarios (the fleet analogue of
    harness.run_scenario); `node_cls` injects buggy WORKERS
    (sim/bugs.py double-lease), `aot_dir` shares one AOT executable
    cache across every worker (docs/compile-cache.md), `healthwatch`
    runs the per-member alert engine and puts the run under SIM113's
    fault→alert coverage audit (docs/healthwatch.md)."""
    return FleetSimHarness(scenario, seed, workdir,
                           node_cls=node_cls, aot_dir=aot_dir,
                           healthwatch=healthwatch).run()


# ---------------------------------------------------------------------------
# the flood soak
# ---------------------------------------------------------------------------

class _FloodRunner:
    """FaultyRunner's pure-hash solve without the fault plane: flood
    bytes must be deterministic and instant."""

    def __call__(self, hydrated: dict, seed: int) -> dict:
        import hashlib
        import json

        canon = json.dumps(
            {k: v for k, v in hydrated.items() if k != "seed"},
            sort_keys=True).encode()
        blob = hashlib.sha256(canon + seed.to_bytes(8, "big")).digest()
        return {"out-1.png": b"\x89PNG" + blob}


class FleetFloodHarness:
    """`tasks` lifecycles through a `workers`-node fleet over the
    in-process engine. See the module docstring for what this proves
    (bounds at load) and what it deliberately skips (signing)."""

    def __init__(self, tasks: int, workers: int, workdir: str, *,
                 seed: int = 0, burst: int = 200, backlog: int = 64,
                 max_leases: int = 32, canonical_batch: int = 4,
                 slo: SLOConfig | None = None):
        import json

        from arbius_tpu.chain import Engine
        from arbius_tpu.chain.fixedpoint import WAD
        from arbius_tpu.chain.token import TokenLedger
        from arbius_tpu.node import LocalChain

        self.tasks = tasks
        self.n_workers = workers
        self.seed = seed
        self.burst = burst
        self.slo = slo if slo is not None else SLOConfig()
        self.workdir = workdir
        self._json = json
        self.token = TokenLedger()
        self.engine = Engine(self.token, start_time=100_000)
        self.token.mint(Engine.ADDRESS, 600_000 * WAD)
        self.user = "0x" + "b2" * 20
        addrs = ["0x" + "a1" * 19 + f"{0xa0 + i:02x}"
                 for i in range(workers)]
        for a in [self.user] + addrs:
            self.token.mint(a, 1_000_000 * WAD)
            self.token.approve(a, Engine.ADDRESS, 10**40)
        self.token.transfer(Engine.ADDRESS, "0x" + "99" * 20,
                            100_000 * WAD)
        for a in addrs:
            self.engine.validator_deposit(a, a, 400 * WAD)
        mid_b = self.engine.register_model(
            self.user, self.user, 0, b'{"meta":{"title":"flood"}}')
        self.model_id = "0x" + mid_b.hex()
        self.fleet_cfg = FleetConfig(
            enabled=True, workers=workers, lease_ttl=600,
            lease_db=os.path.join(workdir, "flood-leases.sqlite"),
            max_leases=max_leases, backlog=backlog,
            max_attempts=4)
        self.leases = LeaseTable(self.fleet_cfg.lease_db,
                                 self.fleet_cfg.busy_timeout_ms)
        self.coordinator = FleetCoordinator(
            LocalChain(self.engine, "0x" + "c0" * 20), self.leases,
            [self.model_id], self.fleet_cfg)
        runner = _FloodRunner()
        self.workers: list[MinerNode] = []
        self._feeds: list[LeaseFeed] = []
        for i, a in enumerate(addrs):
            registry = ModelRegistry()
            registry.register(RegisteredModel(
                id=self.model_id, template=load_template("anythingv3"),
                runner=runner))
            cfg = MiningConfig(
                models=(ModelConfig(id=self.model_id,
                                    template="anythingv3"),),
                compile_cache_dir=None,
                canonical_batch=canonical_batch)
            node = MinerNode(
                LocalChain(self.engine, a), cfg, registry,
                db=NodeDB(os.path.join(workdir, f"flood-{i}.sqlite")),
                store=None, pinner=None)
            wid = make_worker_id(i)
            feed = LeaseFeed(self.leases, wid, self.fleet_cfg
                             ).attach(node)
            # flood sidecars flush ONLY at close (flood wall time is a
            # pinned tier-1 budget — the final segment is all the bench
            # flood stage needs to federate)
            self._feeds.append(feed.attach_sidecar(
                ObsSidecar(sidecar_path(workdir, wid), wid, node.obs),
                every=10**9))
            node.boot(skip_self_test=True)
            self.workers.append(node)
        self.user_chain = LocalChain(self.engine, self.user)

    def _submit(self, i: int) -> None:
        from arbius_tpu.chain.fixedpoint import WAD

        self.user_chain.submit_task(
            0, self.user, self.model_id, 1 * WAD,
            self._json.dumps({"prompt": f"flood {self.seed} {i}",
                              "negative_prompt": ""},
                             sort_keys=True).encode())

    def run(self) -> dict:
        """Drive to quiescence; returns the deterministic report."""
        backlog_methods = ("task", "solve", "pinTaskInput")
        max_backlog = [0] * self.n_workers
        max_pending = 0
        submitted = 0
        rounds = 0
        max_rounds = self.tasks // max(1, self.burst) \
            + self.tasks // 50 + 400
        from contextlib import ExitStack, contextmanager

        @contextmanager
        def _batched(w):
            # the window's exit-commit must run under the worker's own
            # obs so arbius_db_commits_total attributes per worker
            with use_obs(w.obs):
                with w.db.batch():
                    yield

        while rounds < max_rounds:
            rounds += 1
            # a round-wide batch window on EVERY worker db: in-process
            # LocalChain pushes hit other workers' dbs synchronously
            # (an artifact of the whole fleet sharing one process —
            # a real fleet worker only receives events via its own
            # poll, inside its own tick's window), so without this the
            # flood measures a fsync schedule no production fleet has
            with ExitStack() as stack:
                for w in self.workers:
                    stack.enter_context(_batched(w))
                while submitted < self.tasks \
                        and submitted < rounds * self.burst:
                    self._submit(submitted)
                    submitted += 1
                self.coordinator.tick()
                open_jobs = []
                for i, w in enumerate(self.workers):
                    with use_obs(w.obs):
                        w.tick()
                    depth = w.db.count_jobs(backlog_methods)
                    if depth > max_backlog[i]:
                        max_backlog[i] = depth
                    open_jobs.extend(
                        j for j in w.db.get_jobs(2**60, limit=100000)
                        if j.method not in _HEARTBEATS)
            counts = self.leases.counts()
            pending = counts.get("pending", 0)
            if pending > max_pending:
                max_pending = pending
            open_leases = pending + counts.get("leased", 0)
            if submitted >= self.tasks and not open_jobs \
                    and open_leases == 0:
                break
            if submitted >= self.tasks and open_jobs:
                due = [j for j in open_jobs
                       if j.waituntil <= self.engine.now]
                if not due and open_leases == 0:
                    nxt = min(j.waituntil for j in open_jobs)
                    if nxt > self.engine.now:
                        self.engine.advance_time(nxt - self.engine.now,
                                                 blocks=0)
            self.engine.advance_time(5, blocks=0)
            self.engine.mine_block()
        claimed = sum(1 for s in self.engine.solutions.values()
                      if s.claimed)
        per_worker: dict[str, int] = {}
        for s in self.engine.solutions.values():
            per_worker[s.validator] = per_worker.get(s.validator, 0) + 1
        db_commits = {
            make_worker_id(i): int(w.obs.registry.counter(
                "arbius_db_commits_total").value())
            for i, w in enumerate(self.workers)}
        dedup = sum(1 for h in self.leases.history
                    if h[0] == "commit_dedup")
        return {
            "tasks": self.tasks,
            "workers": self.n_workers,
            "seed": self.seed,
            "rounds": rounds,
            "claimed": claimed,
            "per_worker_solutions": dict(sorted(per_worker.items())),
            "backlog_bound": self.fleet_cfg.backlog,
            "max_backlog": {make_worker_id(i): d
                            for i, d in enumerate(max_backlog)},
            "max_pending_leases": max_pending,
            "lease_counts": dict(sorted(self.leases.counts().items())),
            "commit_dedup": dedup,
            "db_commits": db_commits,
            "slo": self._slo_report(),
        }

    def _slo_report(self) -> dict:
        """Byte-deterministic SLO block (docs/fleetscope.md): every
        latency is CHAIN time — queue wait from the lease table's trace
        hops (deal → first acquire), time-to-commit from the engine's
        exact task/solution blocktimes, steal lag from the hop chain's
        recorded lags — estimated through the centralized fixed-bucket
        edges (p50/p95/p99). Wall-clock quantities (chip-idle fraction)
        are deliberately excluded here: they belong to bench/live
        scrapes, never to a byte-identical report."""
        import json as _json

        queue_waits: list[int] = []
        steal_lags: list[int] = []
        for row in self.leases.rows():
            hops = _json.loads(row["hops"] or "[]")
            for h in hops:
                if h.get("op") in ("acquire", "steal"):
                    queue_waits.append(int(h["now"])
                                       - int(row["blocktime"]))
                    break
            steal_lags.extend(int(h["lag"]) for h in hops
                              if "lag" in h)
        commits = [int(s.blocktime - self.engine.tasks[t].blocktime)
                   for t, s in self.engine.solutions.items()
                   if t in self.engine.tasks]
        report = {
            "queue_wait_seconds": latency_summary(sorted(queue_waits)),
            "time_to_commit_seconds": latency_summary(sorted(commits)),
            "steal_lag_seconds": latency_summary(sorted(steal_lags)),
            "thresholds": {
                "queue_wait_p95": self.slo.queue_wait_p95,
                "time_to_commit_p99": self.slo.time_to_commit_p99,
                "steal_lag_p99": self.slo.steal_lag_p99,
            },
        }
        report["breaches"] = evaluate_slo(self.slo, report)
        report["ok"] = not report["breaches"]
        return report

    def close(self) -> None:
        now = self.engine.now
        for feed in self._feeds:
            feed.flush_sidecar(now)
            if feed._sidecar is not None:
                feed._sidecar.close()
        for w in self.workers:
            w.close()
        self.leases.close()


def flood_findings(report: dict):
    """Audit a flood report: the bounds the soak exists to prove.
    Returns SimFindings (rule SIM111) so the CLI's exit contract and
    rendering are the scenario machinery's."""
    from arbius_tpu.sim.invariants import SimFinding

    out = []

    def find(msg):
        out.append(SimFinding(rule="SIM111", message=msg,
                              scenario="flood", seed=report["seed"]))

    if report["claimed"] != report["tasks"]:
        find(f"flood lost tasks: {report['claimed']}/{report['tasks']} "
             "claimed")
    bound = report["backlog_bound"]
    for wid, depth in sorted(report["max_backlog"].items()):
        if depth > bound:
            find(f"worker {wid} task/solve backlog hit {depth} > "
                 f"configured bound {bound} — the lease pull gate "
                 "failed to exert backpressure (CONC302 at load)")
    for state, n in sorted(report["lease_counts"].items()):
        if state not in ("done", "invalid", "failed"):
            find(f"{n} lease(s) stuck non-terminal in state {state!r} "
                 "after drain")
    # the SLO layer (docs/fleetscope.md): a declared objective that the
    # measured chain-time percentiles breach fails the soak — SLO101,
    # the acceptance gate the million-task nightly will stand on
    for breach in (report.get("slo") or {}).get("breaches", ()):
        out.append(SimFinding(rule="SLO101", message=breach,
                              scenario="flood", seed=report["seed"]))
    return out
