# detlint: enforce[DET101,DET102,DET103,DET105]
import sys

from arbius_tpu.sim.cli import main

sys.exit(main())
