"""SIM1xx invariant checkers — the audit that makes a scenario a test.

Every checker consumes the `SimResult` bundle (decoded tx audit trace,
commitment plaintext registry, obs journal, node db, engine terminal
state) and returns findings. A clean run returns none; any finding is a
protocol-invariant violation and the run's `--seed`/`--scenario` pair
reproduces it byte-identically.

  SIM101  task conservation    every delivered task ends in exactly one
                               accounted terminal state (claimed /
                               contested-resolved / invalid /
                               quarantined); strict scenarios narrow the
                               allowed set per task class
  SIM102  commit before reveal every revealed solution's commitment
                               landed in a strictly earlier block
  SIM103  no duplicate commit  one (validator, taskid) never signals
                               commitments for two different CIDs
  SIM104  stake never negative no validator stake ever sampled below 0
  SIM105  retries bounded      every journaled retry obeys expretry's
                               tries bound and exact capped backoff curve
  SIM106  CID crash-stability  a commitment signalled before a crash is
                               revealed with the SAME CID after reboot
  SIM107  token conservation   ledger sums to total supply; the engine
                               stays solvent for stakes+escrow+fees
  SIM108  liveness             the scenario drained inside its round
                               bound
  SIM109  stage monotonicity   per task, the staged solve executor's
                               journaled pipeline_stage ranks never
                               regress inside one node life (solve →
                               encode → pin → commit → reveal); a crash
                               boundary may reset them (the reboot
                               re-executes from the checkpoint), and a
                               pipeline-enabled run that solved tasks
                               but journaled NO stage events is itself
                               a finding (the executor went unexercised)
  SIM110  witness discipline   when the conclint runtime witness
                               instrumented the run (docs/concurrency.md)
                               its observed lock-order graph holds no
                               cycle, and no watched (CONC401-flagged)
                               attribute was written lock-free from two
                               concurrently-live thread roots — the
                               injected-race regression in sim/bugs.py
                               must trip exactly this
  SIM111  fleet discipline     fleet runs only (docs/fleet.md): the
                               per-validator generalization of
                               SIM102/103 over every worker, no task
                               committed by two fleet workers (the
                               cross-process commit dedupe), every
                               lease terminal at quiescence, expired
                               leases stolen/reclaimed within the TTL,
                               and no reveal without granted commit
                               rights — sim/bugs.py's double-lease
                               node must trip exactly this
  SIM113  fault→alert coverage healthwatch runs only
                               (docs/healthwatch.md): the live alert
                               engine's journaled `alert_transition`
                               record must COVER the run's faults —
                               every injected fault class raised its
                               mapped alert (a fault the monitoring
                               never surfaced is a silent fault), AND
                               every raised alert is explained by some
                               injected fault or node-visible evidence
                               (a clean run raises none; fail closed
                               in BOTH directions) — sim/bugs.py's
                               silent-fault node (drops the alert
                               journal) must trip exactly this
  SIM112  trace completeness   fleet runs only (docs/fleetscope.md):
                               every task's cross-process span chain is
                               gap-free and hop-consistent — the lease
                               table's hop indices are contiguous and
                               start at the coordinator's deal, every
                               worker-journaled `lease_hop` adoption
                               matches a hop the table actually
                               granted, every acquire/steal hop WAS
                               adopted in that worker's journal, and no
                               fleet reveal happened without a hop —
                               sim/bugs.py's span-gap worker (drops the
                               adoption events) must trip exactly this

The checkers are deliberately redundant with the engine's own reverts
(defense in depth): their job is to catch a *node* that violates the
protocol in ways the chain happens to accept — the injected
double-commit regression in tests/test_sim.py proves SIM103 does.
"""
# detlint: enforce[DET101,DET102,DET103,DET105]
from __future__ import annotations

from dataclasses import dataclass

from arbius_tpu.l0.commitment import generate_commitment
from arbius_tpu.node.retry import BASE as RETRY_BASE


@dataclass
class SimFinding:
    """One invariant violation. Shaped for the shared lint plumbing:
    `.rule` feeds the stderr triage table, `.text()` the report lines,
    `.to_json()` the stable JSON document (analysis.cli.render_json)."""
    rule: str
    message: str
    taskid: str | None = None
    scenario: str = ""
    seed: int = 0

    def text(self) -> str:
        where = f" task={self.taskid}" if self.taskid else ""
        return (f"{self.rule} [scenario={self.scenario} seed={self.seed}"
                f"{where}] {self.message}")

    def to_json(self) -> dict:
        return {"rule": self.rule, "message": self.message,
                "taskid": self.taskid, "scenario": self.scenario,
                "seed": self.seed}


def _node_dbs(result) -> list:
    """Every node-local database a verdict can live in: one for a
    single-node run, one per worker for a fleet run (a task proven
    invalid or quarantined on worker 2 is accounted, docs/fleet.md)."""
    dbs = list(getattr(result, "worker_dbs", ()) or ())
    return dbs if dbs else [result.db]


def _failed_methods_by_task(result) -> dict[str, list[str]]:
    out: dict[str, list[str]] = {}
    for db in _node_dbs(result):
        for method, data in db.failed_jobs():
            tid = data.get("taskid")
            if tid:
                out.setdefault(tid, []).append(method)
    return out


def classify_tasks(result) -> dict[str, str]:
    """One terminal label per submitted task (precedence order: dispute
    outcome > chain solution state > node-local verdicts)."""
    labels: dict[str, str] = {}
    failed = _failed_methods_by_task(result)
    dbs = _node_dbs(result)
    for tid in result.tasks:
        tb = bytes.fromhex(tid[2:])
        sol = result.engine.solutions.get(tb)
        con = result.engine.contestations.get(tb)
        if tid not in result.plane.delivered_taskids:
            labels[tid] = "undelivered"
            continue
        if con is not None:
            if con.finish_start_index > 0:
                labels[tid] = "contested_resolved"
            elif "voteFinish" in failed.get(tid, ()):
                labels[tid] = "quarantined"
            else:
                labels[tid] = "contested_unresolved"
        elif sol is not None:
            if sol.claimed:
                labels[tid] = "claimed"
            elif failed.get(tid):
                labels[tid] = "quarantined"
            else:
                labels[tid] = "unclaimed"
        elif any(db.is_invalid_task(tid) for db in dbs):
            labels[tid] = "invalid"
        elif failed.get(tid):
            labels[tid] = "quarantined"
        else:
            labels[tid] = "lost"
    return labels


# terminal states that account for a task (anything else is a leak)
_ALWAYS_BAD = ("contested_unresolved", "unclaimed", "lost", "undelivered")


def _allowed_labels(flags, strict: bool) -> tuple[str, ...]:
    if flags.invalid:
        return ("invalid",) if strict else ("invalid", "quarantined")
    if flags.evil:
        return ("contested_resolved",) if strict else (
            "contested_resolved", "quarantined")
    return ("claimed",) if strict else (
        "claimed", "quarantined", "contested_resolved")


def check_task_conservation(result, find) -> None:
    labels = classify_tasks(result)
    for tid, flags in result.tasks.items():
        label = labels[tid]
        allowed = _allowed_labels(flags, result.scenario.strict)
        if label in _ALWAYS_BAD or label not in allowed:
            tb = bytes.fromhex(tid[2:])
            sol = result.engine.solutions.get(tb)
            con = result.engine.contestations.get(tb)
            detail = (f"solution="
                      f"{('cid 0x' + sol.cid.hex() + ' by ' + sol.validator + (' claimed' if sol.claimed else ' UNCLAIMED')) if sol else 'none'}"
                      f", contestation="
                      f"{('finish_start_index ' + str(con.finish_start_index)) if con else 'none'}")
            find("SIM101", tid,
                 f"task leaked: terminal state {label!r} not in allowed "
                 f"{list(allowed)} (class: "
                 f"{'invalid-input' if flags.invalid else 'front-run' if flags.evil else 'normal'}"
                 f"; {detail})")


def _sender_writes(result, method: str, sender: str):
    return [r for r in result.plane.audit
            if r.ok and r.method == method and r.sender == sender]


def _miner_writes(result, method: str):
    return _sender_writes(result, method, result.miner_address)


def _check_commit_before_reveal_for(result, find, sender: str,
                                    rule: str = "SIM102") -> None:
    commits = {r.values[0]: r
               for r in _sender_writes(result, "signalCommitment",
                                       sender)}
    for rev in _sender_writes(result, "submitSolution", sender):
        taskid, cid = rev.values
        tid = "0x" + taskid.hex()
        expected = generate_commitment(sender, taskid, cid)
        commit = commits.get(expected)
        if commit is None:
            find(rule, tid,
                 f"solution 0x{cid.hex()} revealed at block {rev.block} "
                 f"by {sender} with NO matching signalCommitment in the "
                 "audit trace")
        elif commit.block >= rev.block:
            find(rule, tid,
                 f"commit landed at block {commit.block} but the reveal "
                 f"landed at block {rev.block} — commit must be strictly "
                 "earlier")


def check_commit_before_reveal(result, find) -> None:
    _check_commit_before_reveal_for(result, find, result.miner_address)


def _check_no_duplicate_commitment_for(result, find, sender: str,
                                       rule: str = "SIM103") -> None:
    landed_blocks = {r.values[0]: r.block
                     for r in _sender_writes(result, "signalCommitment",
                                             sender)}
    per_task: dict[tuple[str, str], dict[str, int]] = {}
    for chash, (csender, tid, cid) in result.plane.commitments.items():
        if chash not in landed_blocks or csender != sender:
            continue
        per_task.setdefault((csender, tid), {})[cid] = landed_blocks[chash]
    for (csender, tid), cids in per_task.items():
        if len(cids) > 1:
            listing = ", ".join(f"{cid} @ block {blk}"
                                for cid, blk in sorted(cids.items()))
            find(rule, tid,
                 f"validator {csender} signalled {len(cids)} different "
                 f"commitments for one task — a double-commit: {listing}")


def check_no_duplicate_commitment(result, find) -> None:
    _check_no_duplicate_commitment_for(result, find,
                                       result.miner_address)


def check_stake_never_negative(result, find) -> None:
    if result.min_stake_seen < 0:
        find("SIM104", None,
             f"validator stake sampled below zero mid-run: "
             f"{result.min_stake_seen}")
    for addr, v in result.engine.validators.items():
        if v.staked < 0:
            find("SIM104", None,
                 f"terminal stake negative for {addr}: {v.staked}")


def check_retries_bounded(result, find) -> None:
    cap = result.retry_max_delay
    for ev in result.journal_events:
        if ev.get("kind") != "retry":
            continue
        attempt, tries = ev.get("attempt", 0), ev.get("tries", 0)
        if attempt > tries:
            find("SIM105", ev.get("taskid"),
                 f"retry op={ev.get('op')} attempt {attempt} exceeds its "
                 f"tries bound {tries}")
            continue
        expected = 0.0 if attempt >= tries else round(
            min(RETRY_BASE ** (attempt - 1), cap), 6)
        got = ev.get("delay", 0.0)
        if got != expected:
            find("SIM105", ev.get("taskid"),
                 f"retry op={ev.get('op')} attempt {attempt}/{tries} slept "
                 f"{got}s, expretry policy says {expected}s "
                 f"(base {RETRY_BASE}, max_delay {cap})")


def check_cid_stability(result, find) -> None:
    """Crash-restart determinism: a commitment that landed before a
    crash binds the CID the rebooted node must reveal."""
    if result.scenario.faults.crash_after_commit is None:
        return
    if not result.plane.crash_seqs:
        find("SIM106", None,
             "scenario configured crash_after_commit="
             f"{result.scenario.faults.crash_after_commit} but the node "
             "never crashed — the schedule degenerated")
        return
    crash_seq = result.plane.crash_seqs[0]
    pre_commits = {r.values[0] for r in result.plane.audit[:crash_seq]
                   if r.ok and r.method == "signalCommitment"
                   and r.sender == result.miner_address}
    committed_cid = {}   # tid -> cid committed before the crash
    for chash in pre_commits:
        reg = result.plane.commitments.get(chash)
        if reg is not None:
            committed_cid[reg[1]] = reg[2]
    crossed = 0
    for rev in result.plane.audit[crash_seq:]:
        if not (rev.ok and rev.method == "submitSolution"
                and rev.sender == result.miner_address):
            continue
        tid = "0x" + rev.values[0].hex()
        if tid not in committed_cid:
            continue
        crossed += 1
        revealed = "0x" + rev.values[1].hex()
        if revealed != committed_cid[tid]:
            find("SIM106", tid,
                 f"pre-crash commitment bound CID {committed_cid[tid]} "
                 f"but the rebooted node revealed {revealed} — the "
                 "sqlite checkpoint did not reproduce the solve")
    if crossed == 0:
        find("SIM106", None,
             "node crashed but no pre-crash commitment was revealed "
             "after the restart — the recovery path went unexercised")


def check_token_conservation(result, find) -> None:
    tok = result.engine.token
    total = sum(tok.balances.values())
    if total != tok.total_supply:
        find("SIM107", None,
             f"ledger out of balance: Σbalances {total} != total supply "
             f"{tok.total_supply}")
    eng = result.engine
    obligations = (eng.accrued_fees
                   + sum(v.staked for v in eng.validators.values())
                   + sum(eng.withdraw_pending.values()))
    held = tok.balance_of(eng.ADDRESS)
    if held < obligations:
        find("SIM107", None,
             f"engine insolvent: holds {held} but owes {obligations} "
             "(accrued fees + stakes + pending withdraws)")


def check_liveness(result, find) -> None:
    if not result.quiescent:
        find("SIM108", None,
             f"scenario did not drain within {result.scenario.max_rounds} "
             f"rounds ({len(result.plane.audit)} writes audited, "
             f"{result.plane.pending_events()} events still in flight)")


def check_stage_order(result, find) -> None:
    """SIM109: the staged executor's per-task lifecycle must advance
    monotonically through solve → encode → pin → commit → reveal inside
    one node life. A `sim_crash` journal event marks a reboot — the
    recovered node legitimately re-executes earlier stages, so the
    per-task high-water marks reset there."""
    if not getattr(result, "pipeline_enabled", False):
        return
    from arbius_tpu.node.pipeline import STAGE_RANK

    # keyed per (task, solve-job attempt): replayed chain events
    # legitimately queue duplicate solve jobs for an already-solved
    # task, and each attempt re-walks the stages from the top — within
    # one attempt the ranks must never regress
    last: dict[tuple, tuple[int, str]] = {}
    saw_any = False
    for ev in result.journal_events:
        kind = ev.get("kind")
        if kind == "sim_crash":
            last.clear()
            continue
        if kind != "pipeline_stage":
            continue
        saw_any = True
        tid, stage = ev.get("taskid"), ev.get("stage")
        rank = STAGE_RANK.get(stage)
        if rank is None:
            find("SIM109", tid,
                 f"unknown pipeline stage {stage!r} in the journal")
            continue
        key = (tid, ev.get("jobid"))
        prev = last.get(key)
        if prev is not None and rank < prev[0]:
            find("SIM109", tid,
                 f"stage order regressed within solve attempt "
                 f"{ev.get('jobid')}: {stage!r} (rank {rank}) journaled "
                 f"after {prev[1]!r} (rank {prev[0]}) with no crash "
                 "boundary between them")
            continue
        last[key] = (rank, stage)
    if not saw_any and any(
            r.ok and r.method == "signalCommitment"
            and r.sender == result.miner_address
            for r in result.plane.audit):
        find("SIM109", None,
             "pipeline enabled and the node committed solutions, but the "
             "journal holds no pipeline_stage events — the staged "
             "executor went unexercised")


def check_witness(result, find) -> None:
    """SIM110: audit the conclint runtime-witness record (present only
    on instrumented runs — harness `witness=True`)."""
    report = getattr(result, "witness_report", None)
    if report is None:
        return
    from arbius_tpu.analysis.conc.witness import (
        contested_attrs,
        order_cycle,
    )

    cycle = order_cycle(report)
    if cycle is not None:
        find("SIM110", None,
             "runtime lock-order cycle observed: "
             + " → ".join(cycle)
             + " — two threads interleaving these acquisitions deadlock")
    for (cls, attr), entry in sorted(contested_attrs(report).items()):
        if len(entry["roots"]) >= 2 and entry["lock_free_roots"]:
            find("SIM110", None,
                 f"watched attribute `{cls}.{attr}` written with NO "
                 f"witnessed lock from root(s) "
                 f"{sorted(entry['lock_free_roots'])} while root(s) "
                 f"{sorted(entry['roots'])} were writing it — the "
                 "CONC401 race is live at runtime, not just static")


def check_fleet(result, find) -> None:
    """SIM111 (fleet runs only, docs/fleet.md): the per-validator
    generalization of the single-node invariants plus the lease-plane
    contract.

      (a) SIM102/SIM103 per worker: every fleet validator's reveals
          have a strictly-earlier matching commit, and no validator
          double-commits one task;
      (b) cross-process commit dedupe: no task is committed by two
          DIFFERENT fleet workers — the wasted-work race the lease
          table's claim_commit exists to prevent (the shipped
          scenarios never cross a reclaim-after-commit boundary, so
          one committer per task is exact there; sim/bugs.py's
          double-lease node must trip this);
      (c) every lease terminal after drain (a pending/leased row at
          quiescence is a lost or stuck task);
      (d) expired leases reclaimed/stolen within the TTL: the steal/
          reclaim lag recorded in the lease history never exceeds
          max(lease_ttl, 2 × tick_seconds) — a dead worker's tasks
          become someone else's work, promptly;
      (e) commit-rights rows match what actually landed on chain: the
          registered CID of each fleet reveal equals the rights-holder
          row's CID (the dedupe table cannot drift from the chain)."""
    workers = getattr(result, "fleet_workers", ())
    if not workers:
        return
    for addr in workers:
        _check_commit_before_reveal_for(result, find, addr,
                                        rule="SIM111")
        _check_no_duplicate_commitment_for(result, find, addr,
                                           rule="SIM111")
    committers: dict[str, set] = {}
    for addr in workers:
        for r in _sender_writes(result, "signalCommitment", addr):
            reg = result.plane.commitments.get(r.values[0])
            if reg is not None:
                committers.setdefault(reg[1], set()).add(addr)
    for tid, who in sorted(committers.items()):
        if len(who) > 1:
            find("SIM111", tid,
                 f"{len(who)} fleet workers {sorted(who)} each "
                 "signalled a commitment for one task — the "
                 "cross-process commit dedupe failed (double-lease)")
    for row in getattr(result, "lease_rows", ()):
        if row["state"] not in ("done", "invalid", "failed"):
            find("SIM111", row["taskid"],
                 f"lease stuck non-terminal after drain: state "
                 f"{row['state']!r} held by {row['worker']!r} "
                 f"(attempts {row['attempts']}, steals {row['steals']})")
    spec = result.scenario.fleet
    if spec is not None:
        lag_bound = max(spec.lease_ttl, 2 * result.scenario.tick_seconds)
        for op, tid, worker, now, extra in getattr(
                result, "lease_history", ()):
            if op in ("steal", "reclaim") and \
                    extra.get("lag", 0) > lag_bound:
                find("SIM111", tid,
                     f"expired lease lingered {extra['lag']}s past its "
                     f"heartbeat before {op} (bound {lag_bound}s) — "
                     "reclaim is not keeping up with the TTL")
    worker_of_addr = {addr: f"worker-{i}"
                      for i, addr in enumerate(workers)}
    claims = {}
    for op, tid, worker, now, extra in getattr(result,
                                               "lease_history", ()):
        if op == "commit_claim":
            claims.setdefault(tid, []).append(worker)
    rights = {row["taskid"]: row
              for row in getattr(result, "commit_rows", ())}
    for addr in workers:
        for r in _sender_writes(result, "submitSolution", addr):
            tid = "0x" + r.values[0].hex()
            cid = "0x" + r.values[1].hex()
            holders = claims.get(tid, [])
            if holders and worker_of_addr[addr] not in holders:
                find("SIM111", tid,
                     f"{worker_of_addr[addr]} ({addr}) revealed a "
                     "solution without ever being granted the task's "
                     f"commit rights (granted to {sorted(set(holders))})"
                     " — the commit guard was bypassed")
            row = rights.get(tid)
            if row is not None and row["cid"] != cid:
                find("SIM111", tid,
                     f"commit-rights table records CID {row['cid']} "
                     f"(holder {row['worker']}) but {addr} revealed "
                     f"{cid} on chain — the dedupe table drifted from "
                     "the chain")


_HOP_OPS = ("deal", "acquire", "steal", "reclaim")


def check_trace_chain(result, find) -> None:
    """SIM112 (fleet runs only, docs/fleetscope.md): cross-process
    trace completeness. The lease table's `hops` column is the shared
    truth of every task's deal/acquire/steal/reclaim chain; each
    worker's `lease_hop` journal events are its local adoption record.
    A settled task is traceable iff (a) the chain parses, is
    index-contiguous, and starts at the coordinator's deal, (b) every
    journaled adoption matches a hop the table granted to that worker,
    (c) every granted acquire/steal hop was adopted in that worker's
    journal (the gap the span-gap bug injects), and (d) no fleet
    reveal landed without the revealer holding a hop."""
    import json as _json

    workers = getattr(result, "fleet_workers", ())
    if not workers:
        return
    hops_by_task: dict[str, list[dict]] = {}
    for row in getattr(result, "lease_rows", ()):
        tid = row["taskid"]
        try:
            hops = _json.loads(row.get("hops") or "[]")
        except ValueError:
            find("SIM112", tid, "lease hop chain is not valid JSON: "
                 f"{row.get('hops')!r}")
            continue
        hops_by_task[tid] = hops
        if [h.get("hop") for h in hops] != list(range(len(hops))):
            find("SIM112", tid,
                 "hop chain has gaps or reordered indices: "
                 + str([h.get("hop") for h in hops]))
        if not hops or hops[0].get("op") != "deal":
            find("SIM112", tid,
                 "hop chain does not start at the coordinator's deal: "
                 f"{hops[:1]}")
        for h in hops:
            if h.get("op") not in _HOP_OPS:
                find("SIM112", tid,
                     f"unknown hop op {h.get('op')!r} at index "
                     f"{h.get('hop')}")
    adopted: dict[tuple, list[str]] = {}
    for ev in result.journal_events:
        if ev.get("kind") != "lease_hop":
            continue
        adopted.setdefault((ev.get("taskid"), ev.get("hop")),
                           []).append(ev.get("worker"))
    for (tid, hop), who in sorted(adopted.items()):
        hops = hops_by_task.get(tid)
        h = hops[hop] if hops is not None and isinstance(hop, int) \
            and 0 <= hop < len(hops) else None
        if h is None:
            find("SIM112", tid,
                 f"worker(s) {sorted(who)} journaled adoption of hop "
                 f"{hop} the lease table never granted")
            continue
        for w in who:
            if h.get("op") not in ("acquire", "steal") \
                    or h.get("worker") != w:
                find("SIM112", tid,
                     f"hop {hop} adopted by {w} but the lease table "
                     f"records op={h.get('op')!r} "
                     f"worker={h.get('worker')!r} — the chain is "
                     "hop-inconsistent across processes")
    if getattr(result, "journal_dropped", 0) == 0:
        # adoption COMPLETENESS is only decidable when no worker's
        # journal ring evicted events — a missing lease_hop behind a
        # nonzero dropped count may simply have fallen off the ring,
        # and a false "gap" here would poison the one checker whose
        # contract is that span-gap fails it ALONE
        for tid, hops in sorted(hops_by_task.items()):
            for h in hops:
                if h.get("op") in ("acquire", "steal") and \
                        h.get("worker") not in adopted.get(
                            (tid, h.get("hop")), []):
                    find("SIM112", tid,
                         f"span chain gap: hop {h.get('hop')} "
                         f"({h.get('op')} by {h.get('worker')}) was "
                         "never adopted in that worker's journal — "
                         "the cross-process trace is broken")
    held = {tid: {h.get("worker") for h in hops
                  if h.get("op") in ("acquire", "steal")}
            for tid, hops in hops_by_task.items()}
    worker_of_addr = {addr: f"worker-{i}"
                      for i, addr in enumerate(workers)}
    for addr in workers:
        for r in _sender_writes(result, "submitSolution", addr):
            tid = "0x" + r.values[0].hex()
            if worker_of_addr[addr] not in held.get(tid, ()):
                find("SIM112", tid,
                     f"{worker_of_addr[addr]} ({addr}) revealed a "
                     "solution without ever holding a hop in the "
                     "task's trace chain")


# -- SIM113: fault→alert coverage (docs/healthwatch.md) ---------------------
#
# The coverage map: which healthwatch alert class each injected fault
# kind must raise. A fault kind maps to a TUPLE of acceptable alerts —
# the invariant is "at least one of the class was raised" (reaching
# pending counts: the class left ok, which is what an operator's pager
# keys on). Timing-only faults (latency, runner_slow, pin_stall) have
# no required alert: they are observable only as latency, and mapping
# them would make the invariant lie. docs/healthwatch.md renders this
# table; keep the two in sync.
FAULT_ALERTS: dict[str, tuple[str, ...]] = {
    "tx_error": ("rpc_degraded", "job_quarantine"),
    "tx_lost_response": ("rpc_degraded", "job_quarantine"),
    "view_error": ("rpc_degraded", "job_quarantine"),
    "poll_error": ("rpc_degraded",),
    "pin_fail": ("pin_degraded", "job_quarantine"),
    "pin_mismatch": ("pin_degraded", "job_quarantine"),
    "runner_crash": ("job_quarantine",),
    "event_delay": ("chain_replay",),
    "event_replay": ("chain_replay",),
    "reorg": ("chain_replay",),
    # a view error can raise out of an event SUBSCRIBER mid-dispatch,
    # making the node re-poll (and honestly re-observe) the range —
    # so chain faults may legitimately surface as observed replays
    "crash": ("crash_recovered",),
    # a simulated zero-byte decode bumps the SAME production counter
    # the real TextGenRunner.finalize does (docs/text-serving.md), so
    # the decode_stall rule must see it
    "decode_stall": ("decode_stall",),
    # latency / runner_slow / pin_stall / coordinator_crash: timing or
    # out-of-scope — no required alert (documented, not forgotten)
}


def _raised_alerts(result) -> set[str]:
    return {ev.get("alert") for ev in result.journal_events
            if ev.get("kind") == "alert_transition"}


def check_alert_coverage(result, find) -> None:
    """SIM113 (healthwatch-enabled runs only): the journaled
    alert_transition record covers the run's faults in BOTH directions.

      (a) required: every injected fault kind with a row in
          FAULT_ALERTS saw at least one alert of its class raised
          (leave ok at least once) somewhere in the run — across
          crash-restarts and every fleet worker (journals are
          unioned). Downgraded when any journal ring evicted events
          (the SIM112 honesty bound: a missing transition behind a
          nonzero dropped count may simply have fallen off the ring).
          Evidence-derived requirements ride along: a task the node
          drove to contested_resolved must have raised `contention`, a
          task marked invalid must have raised `invalid_inputs`, and a
          fleet run with lease steals must have raised `steal_surge`.
      (b) allowed: every raised alert is explained by an injected
          fault or by node-visible evidence — a clean run raises
          NOTHING, so a trigger-happy rule (or a stale coverage map)
          fails closed instead of normalizing alert noise."""
    if not getattr(result, "healthwatch_enabled", False):
        return
    raised = _raised_alerts(result)
    labels = classify_tasks(result)
    faults = result.plane.fault_counts
    # STEALS only — a coordinator RECLAIM's lag is observed under the
    # coordinator's obs, and the coordinator runs no healthwatch, so
    # no engine can ever raise steal_surge for it (a reclaimed lease
    # reaches workers as an ordinary re-deal); requiring it would fail
    # healthy reclaim-heavy runs
    steals = any(h[0] == "steal"
                 for h in getattr(result, "lease_history", ()))

    # (a) required coverage
    if getattr(result, "journal_dropped", 0) == 0:
        required: dict[str, tuple[str, ...]] = {}
        for kind, n in sorted(faults.items()):
            if n > 0 and kind in FAULT_ALERTS:
                required[f"fault {kind!r} (injected {n}x)"] = \
                    FAULT_ALERTS[kind]
        if any(lbl == "contested_resolved" for lbl in labels.values()):
            required["a contestation this node drove to resolution"] = \
                ("contention",)
        if any(lbl == "invalid" for lbl in labels.values()):
            required["a task marked invalid"] = ("invalid_inputs",)
        if steals:
            required["lease steals in the fleet history"] = \
                ("steal_surge",)
        for what, alerts in required.items():
            if not (set(alerts) & raised):
                find("SIM113", None,
                     f"{what} raised NO alert of its mapped class "
                     f"{list(alerts)} — the fault was silent: live "
                     "monitoring never surfaced what the fault plane "
                     "injected (docs/healthwatch.md coverage map)")

    # (b) no unexplained alerts
    allowed: set[str] = set()
    for kind, n in faults.items():
        if n > 0:
            allowed.update(FAULT_ALERTS.get(kind, ()))
    if faults.get("view_error", 0) > 0:
        # a view error raising out of an event subscriber makes the
        # node re-poll the range — an honestly OBSERVED replay
        allowed.add("chain_replay")
    if sum(faults.values()) > 0:
        # any fault — including the timing-only kinds — may back up
        # the staged executor; a stall alert under faults is signal,
        # under a clean run it is noise
        allowed.add("pipeline_stall")
    if any(f.evil for f in result.tasks.values()) \
            or result.engine.contestations:
        allowed.add("contention")
    if any(lbl == "invalid" for lbl in labels.values()):
        allowed.add("invalid_inputs")
    if any(db.failed_jobs() for db in _node_dbs(result)):
        allowed.add("job_quarantine")
    if result.restarts > 0:
        allowed.add("crash_recovered")
    if steals:
        allowed.add("steal_surge")
    for ev in result.journal_events:
        if ev.get("kind") in ("retry", "retry_exhausted"):
            allowed.add("pin_degraded" if str(ev.get("op", ""))
                        .startswith("pin_") else "rpc_degraded")
    for alert in sorted(raised - allowed):
        find("SIM113", None,
             f"alert {alert!r} was raised with no injected fault or "
             "node-visible evidence mapping to it — either the rule is "
             "trigger-happy or the FAULT_ALERTS coverage map is stale "
             "(docs/healthwatch.md)")


CHECKERS = (
    check_task_conservation,
    check_commit_before_reveal,
    check_no_duplicate_commitment,
    check_stake_never_negative,
    check_retries_bounded,
    check_cid_stability,
    check_token_conservation,
    check_liveness,
    check_stage_order,
    check_witness,
    check_fleet,
    check_trace_chain,
    check_alert_coverage,
)


def check_all(result) -> list[SimFinding]:
    findings: list[SimFinding] = []
    for checker in CHECKERS:
        def find(rule: str, taskid: str | None, message: str) -> None:
            findings.append(SimFinding(
                rule=rule, message=message, taskid=taskid,
                scenario=result.scenario.name, seed=result.seed))
        checker(result, find)
    return findings


def summarize(result) -> dict:
    """Deterministic per-run summary for reports (no wall-clock, no
    object addresses — byte-identical for identical (scenario, seed))."""
    labels = classify_tasks(result)
    terminal: dict[str, int] = {}
    for label in labels.values():
        terminal[label] = terminal.get(label, 0) + 1
    doc = {
        "scenario": result.scenario.name,
        "seed": result.seed,
        "tasks": len(result.tasks),
        "terminal": dict(sorted(terminal.items())),
        "per_task": {tid: {"index": f.index, "invalid": f.invalid,
                           "evil": f.evil, "state": labels[tid]}
                     for tid, f in sorted(result.tasks.items())},
        "faults_injected": dict(sorted(result.plane.fault_counts.items())),
        "writes_audited": len(result.plane.audit),
        "restarts": result.restarts,
        "rounds": result.rounds,
        "virtual_seconds": result.engine.now
        - result.engine.start_block_time,
        "quiescent": result.quiescent,
    }
    if getattr(result, "fleet_workers", ()):
        # fleet runs only — single-node summaries stay byte-identical
        # to their pre-fleet shape (test-pinned)
        per_worker: dict[str, int] = {}
        for s in result.engine.solutions.values():
            if s.validator in result.fleet_workers:
                per_worker[s.validator] = per_worker.get(
                    s.validator, 0) + 1
        doc["fleet"] = {
            "workers": len(result.fleet_workers),
            "per_worker_solutions": dict(sorted(per_worker.items())),
            "lease_counts": dict(sorted(result.lease_counts.items())),
            "steals": sum(1 for h in result.lease_history
                          if h[0] == "steal"),
            "reclaims": sum(1 for h in result.lease_history
                            if h[0] == "reclaim"),
            "commit_dedups": sum(1 for h in result.lease_history
                                 if h[0] == "commit_dedup"),
        }
    return doc
