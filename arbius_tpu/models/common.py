"""Shared neural building blocks for the diffusion model zoo.

TPU-first design notes:
  - NHWC activation layout throughout (XLA's native conv layout on TPU —
    keeps the MXU fed without transposes).
  - bfloat16 compute / float32 params by default: matmuls and convs hit the
    MXU in bf16; GroupNorm/softmax statistics are computed in float32 for
    numerical stability and cross-run determinism.
  - No data-dependent Python control flow — everything jit/scan friendly.

Architecture parity targets (what the reference's model class requires, per
SURVEY.md §2.3): SD-1.5-family UNet2D + VAE + CLIP text encoder
(templates/anythingv3.json), Kandinsky prior+decoder, UNet3D video models,
RVM ConvGRU. The blocks here are the common substrate.
"""
from __future__ import annotations

import math

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


def sinusoidal_embedding(t: jax.Array, dim: int, max_period: float = 10000.0,
                         flip: bool = True) -> jax.Array:
    """Transformer-style timestep embedding; [B] -> [B, dim] float32."""
    half = dim // 2
    freqs = jnp.exp(-np.log(max_period) * jnp.arange(half, dtype=jnp.float32) / half)
    args = t.astype(jnp.float32)[:, None] * freqs[None, :]
    sin, cos = jnp.sin(args), jnp.cos(args)
    emb = jnp.concatenate([cos, sin] if flip else [sin, cos], axis=-1)
    if dim % 2:
        emb = jnp.pad(emb, ((0, 0), (0, 1)))
    return emb


class GroupNorm32(nn.Module):
    """GroupNorm computed in float32 regardless of activation dtype."""
    num_groups: int = 32
    epsilon: float = 1e-5

    @nn.compact
    def __call__(self, x):
        orig = x.dtype
        groups = math.gcd(x.shape[-1], self.num_groups)
        x = nn.GroupNorm(num_groups=groups, epsilon=self.epsilon,
                         dtype=jnp.float32, param_dtype=jnp.float32)(
            x.astype(jnp.float32))
        return x.astype(orig)


class TimestepEmbedding(nn.Module):
    """MLP lift of the sinusoidal embedding: dim -> 4*dim typically."""
    out_dim: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, emb):
        emb = nn.Dense(self.out_dim, dtype=self.dtype)(emb.astype(self.dtype))
        emb = nn.silu(emb)
        return nn.Dense(self.out_dim, dtype=self.dtype)(emb)


class ResnetBlock(nn.Module):
    """GN-SiLU-conv ×2 with timestep conditioning and learned skip.

    `scale_shift=True` switches the timestep injection to the FiLM-style
    scale/shift form some published UNets use (time_emb_proj predicts
    [scale, shift] pairs applied after the second GroupNorm) — parameter
    shapes differ (2× projection width), so the flag is part of the
    checkpoint topology, not a numerics toggle.

    `resample` ("none"|"down"|"up") folds the unCLIP-family resnet-based
    down/upsampling into the block (parameter-free 2× average-pool /
    nearest-upsample applied to BOTH branches between the first norm and
    conv) — the published "ResnetDownsample/Upsample" and "Simple" block
    samplers are resnets of exactly this shape.
    """
    out_channels: int
    dtype: jnp.dtype = jnp.bfloat16
    scale_shift: bool = False
    resample: str = "none"
    # published norm eps differs per family: diffusers UNets use 1e-5,
    # AutoencoderKL/VQ VAEs use 1e-6 — part of checkpoint fidelity
    norm_eps: float = 1e-5

    def _resample(self, x):
        if self.resample == "down":
            return nn.avg_pool(x, (2, 2), strides=(2, 2))
        if self.resample == "up":
            b, h, w, c = x.shape
            return jax.image.resize(x, (b, h * 2, w * 2, c), method="nearest")
        return x

    @nn.compact
    def __call__(self, x, temb=None):
        h = GroupNorm32(epsilon=self.norm_eps)(x)
        h = nn.silu(h)
        if self.resample != "none":
            h = self._resample(h)
            x = self._resample(x)
        h = nn.Conv(self.out_channels, (3, 3), padding=1, dtype=self.dtype)(h)
        t = None
        if temb is not None:
            width = self.out_channels * (2 if self.scale_shift else 1)
            t = nn.Dense(width, dtype=self.dtype)(nn.silu(temb))
            if not self.scale_shift:
                h = h + t[:, None, None, :]
        h = GroupNorm32(epsilon=self.norm_eps)(h)
        if t is not None and self.scale_shift:
            scale, shift = jnp.split(t[:, None, None, :], 2, axis=-1)
            h = h * (1 + scale) + shift
        h = nn.silu(h)
        h = nn.Conv(self.out_channels, (3, 3), padding=1, dtype=self.dtype)(h)
        if x.shape[-1] != self.out_channels:
            x = nn.Conv(self.out_channels, (1, 1), dtype=self.dtype,
                        name="skip_proj")(x)
        return x + h


class Attention(nn.Module):
    """Multi-head attention; self- or cross- depending on `context`.

    Softmax in float32. Uses jnp.einsum so XLA fuses QK^T/softmax/V on the
    MXU; a pallas flash kernel can swap in behind the same interface for
    long sequences (see arbius_tpu/ops).
    """
    num_heads: int
    head_dim: int
    dtype: jnp.dtype = jnp.bfloat16
    qkv_bias: bool = False  # SD UNet attention: no bias; VAE attention: bias

    @nn.compact
    def __call__(self, x, context=None, mask=None):
        ctx = x if context is None else context
        inner = self.num_heads * self.head_dim
        q = nn.Dense(inner, use_bias=self.qkv_bias, dtype=self.dtype,
                     name="to_q")(x)
        k = nn.Dense(inner, use_bias=self.qkv_bias, dtype=self.dtype,
                     name="to_k")(ctx)
        v = nn.Dense(inner, use_bias=self.qkv_bias, dtype=self.dtype,
                     name="to_v")(ctx)

        def split(t):  # [B, S, inner] -> [B, H, S, D]
            b, s, _ = t.shape
            return t.reshape(b, s, self.num_heads, self.head_dim).transpose(0, 2, 1, 3)

        q, k, v = split(q), split(k), split(v)
        if mask is None:
            # dispatches to the pallas flash kernel on TPU for long S
            # (ops/flash.py), XLA einsum otherwise — same math either way
            from arbius_tpu.ops.flash import attention as fused_attention

            out = fused_attention(q, k, v)
        else:
            scale = 1.0 / np.sqrt(self.head_dim)
            logits = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
            logits = logits + mask
            probs = jax.nn.softmax(logits, axis=-1).astype(self.dtype)
            out = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
        b, h, s, d = out.shape
        out = out.transpose(0, 2, 1, 3).reshape(b, s, h * d)
        return nn.Dense(inner, dtype=self.dtype, name="to_out")(out)


class GEGLU(nn.Module):
    dim_out: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        # value/gate as two named projections (not one fused kernel) so a
        # tensor-parallel P(None, 'tp') sharding keeps each half's columns
        # local to a chip — a fused kernel's midpoint split would straddle
        # the tp shards and force a reshard before the elementwise gate.
        h = nn.Dense(self.dim_out, dtype=self.dtype, name="ff_val")(x)
        gate = nn.Dense(self.dim_out, dtype=self.dtype, name="ff_gate")(x)
        # diffusers GEGLU gates with torch F.gelu's EXACT erf form;
        # jax.nn.gelu defaults to the tanh approximation
        return h * nn.gelu(gate, approximate=False)


class TransformerBlock(nn.Module):
    """LN->self-attn, LN->cross-attn, LN->GEGLU-FF, all residual."""
    num_heads: int
    head_dim: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, context=None):
        x = x + Attention(self.num_heads, self.head_dim, self.dtype, name="attn1")(
            nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32)(x).astype(self.dtype))
        x = x + Attention(self.num_heads, self.head_dim, self.dtype, name="attn2")(
            nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32)(x).astype(self.dtype), context=context)
        h = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32)(x).astype(self.dtype)
        h = GEGLU(x.shape[-1] * 4, self.dtype, name="ff")(h)
        h = nn.Dense(x.shape[-1], dtype=self.dtype, name="ff_out")(h)
        return x + h


class SpatialTransformer(nn.Module):
    """Transformer over flattened H*W tokens with 1x1 in/out projections."""
    num_heads: int
    head_dim: int
    depth: int = 1
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, context=None):
        b, h, w, c = x.shape
        residual = x
        # diffusers Transformer2DModel pins its pre-proj_in GroupNorm to
        # eps=1e-6 (unlike the 1e-5 resnet norms) — checkpoint fidelity
        x = GroupNorm32(epsilon=1e-6)(x)
        x = nn.Conv(c, (1, 1), dtype=self.dtype, name="proj_in")(x)
        x = x.reshape(b, h * w, c)
        for i in range(self.depth):
            x = TransformerBlock(self.num_heads, self.head_dim, self.dtype,
                                 name=f"block_{i}")(x, context)
        x = x.reshape(b, h, w, c)
        x = nn.Conv(c, (1, 1), dtype=self.dtype, name="proj_out")(x)
        return x + residual


class Downsample(nn.Module):
    channels: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        return nn.Conv(self.channels, (3, 3), strides=(2, 2), padding=1,
                       dtype=self.dtype)(x)


class Upsample(nn.Module):
    channels: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        b, h, w, c = x.shape
        x = jax.image.resize(x, (b, h * 2, w * 2, c), method="nearest")
        return nn.Conv(self.channels, (3, 3), padding=1, dtype=self.dtype)(x)
