"""JAX/Flax model zoo — one family per reference template class.

Each pipeline module also exports `trace_specs()` — its jittable entry
points as abstract, CPU-traceable `TraceSpec`s; `all_trace_specs()`
aggregates the registry for graphlint (`arbius_tpu/analysis/graph`),
which fingerprints every spec's XLA program against `goldens/graph/`.
"""
from arbius_tpu.models.trace_specs import (
    TraceSpec,
    all_trace_specs,
    validate_specs,
)

__all__ = ["TraceSpec", "all_trace_specs", "validate_specs"]
