"""JAX/Flax model zoo — one family per reference template class."""
