"""Robust Video Matting family: the published RVM recurrent matting
network (`templates/robust_video_matting.json` model class)."""
from arbius_tpu.models.rvm.convert import convert_rvm, rvm_key_for
from arbius_tpu.models.rvm.model import (
    MOBILENETV3_LARGE_ROWS,
    ConvGRU,
    MattingStep,
    RVMConfig,
)
from arbius_tpu.models.rvm.pipeline import (
    OUTPUT_TYPES,
    RVMPipeline,
    RVMPipelineConfig,
)

__all__ = ["ConvGRU", "MOBILENETV3_LARGE_ROWS", "MattingStep",
           "OUTPUT_TYPES", "RVMConfig", "RVMPipeline", "RVMPipelineConfig",
           "convert_rvm", "rvm_key_for"]
