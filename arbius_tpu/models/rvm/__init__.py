"""Robust Video Matting family: recurrent ConvGRU matting
(`templates/robust_video_matting.json` model class)."""
from arbius_tpu.models.rvm.model import ConvGRUCell, RVMConfig, RVMStep
from arbius_tpu.models.rvm.pipeline import (
    OUTPUT_TYPES,
    RVMPipeline,
    RVMPipelineConfig,
)

__all__ = ["ConvGRUCell", "OUTPUT_TYPES", "RVMConfig", "RVMPipeline",
           "RVMPipelineConfig", "RVMStep"]
