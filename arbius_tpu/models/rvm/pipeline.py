"""RVM matting pipeline: streamed video → matted video.

One jitted program scans all frames with the four ConvGRU states as carry
(`lax.scan` — the TPU form of the published model's frame-streaming
inference loop). The published auto-downsample rule is applied statically
per bucket: working resolution = min(512/max(H,W), 1) of the source
(snapped to the encoder granule), with the DeepGuidedFilter refiner
recovering full resolution — the same downsample-then-refine path the
reference's cog container runs on large frames.

Output composition follows the template's output_type enum
(`templates/robust_video_matting.json`):

  green-screen    — foreground over solid green
  alpha-mask      — alpha as grayscale video
  foreground-mask — hard foreground matte (alpha > 0.5) as black/white

Deterministic: no sampling anywhere; bytes depend only on (model build,
input video, output_type). The seed is accepted for interface parity and
unused — matching the reference where RVM output is seed-independent.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from arbius_tpu.models.rvm.model import MattingStep, RVMConfig

OUTPUT_TYPES = ("green-screen", "alpha-mask", "foreground-mask")


@dataclass(frozen=True)
class RVMPipelineConfig:
    model: RVMConfig = RVMConfig()
    # published inference.py auto_downsample_ratio: min(512 / max(h, w), 1)
    auto_downsample_px: int = 512

    @classmethod
    def tiny(cls) -> "RVMPipelineConfig":
        return cls(model=RVMConfig.tiny())


class RVMPipeline:
    GRANULE = 16  # encoder pyramid depth ⇒ H, W must divide by this

    def __init__(self, config: RVMPipelineConfig | None = None):
        self.config = config or RVMPipelineConfig()
        self.step = MattingStep(self.config.model)
        self._buckets: dict[tuple, object] = {}

    def base_hw(self, height: int, width: int) -> tuple[int, int] | None:
        """Static working resolution per the published auto rule; None =
        run direct (no refiner). Snapped to GRANULE so every pyramid level
        has even dims (the published crop semantics then cost nothing)."""
        ratio = min(self.config.auto_downsample_px / max(height, width), 1.0)
        if ratio >= 1.0:
            return None
        g = self.GRANULE
        snap = lambda v: max(g, int(round(v * ratio / g)) * g)  # noqa: E731
        return snap(height), snap(width)

    def init_params(self, seed: int = 0, height: int = 64,
                    width: int = 64, dtype=None) -> dict:
        """One jitted init program; `dtype` folds the weights cast in
        (see SD15Pipeline.init_params for the HBM-peak rationale)."""
        # init through the downsample+refine path so the refiner's
        # published weights are materialized in the tree; base snapped to
        # the granule like base_hw does
        g = self.GRANULE
        base = (max(g, height // 2 // g * g), max(g, width // 2 // g * g))

        def _init(key):
            frame = jnp.zeros((1, height, width, 3))
            rec = self.step.init_rec(1, *base)
            return self.step.init(key, frame, rec, base)["params"]

        from arbius_tpu.utils import with_cast

        return jax.jit(with_cast(_init, dtype))(jax.random.PRNGKey(seed))

    def compiled_bucket(self, frames: int, height: int, width: int):
        key = (frames, height, width)
        cached = self._buckets.get(key)
        if cached is not None:
            return cached
        base = self.base_hw(height, width)

        def run(params, video):  # video: f32 [T, H, W, 3] in [0, 1]
            rec = self.step.init_rec(1, *(base or (height, width)))

            def body(rec, frame):
                fgr, pha, rec = self.step.apply(
                    {"params": params}, frame[None], rec, base)
                return rec, (pha[0], fgr[0])

            _, (alphas, fgrs) = jax.lax.scan(body, rec, video)
            return alphas, fgrs

        fn = jax.jit(run)
        self._buckets[key] = fn
        return fn

    def matte(self, params: dict, video: np.ndarray, *,
              output_type: str = "green-screen") -> np.ndarray:
        """uint8 [T,H,W,3] video → uint8 [T,H,W,3] matted video."""
        if output_type not in OUTPUT_TYPES:
            raise ValueError(f"output_type must be one of {OUTPUT_TYPES}")
        if video.dtype != np.uint8 or video.ndim != 4 or video.shape[3] != 3:
            raise ValueError(f"expected uint8 [T,H,W,3], got "
                             f"{video.dtype} {video.shape}")
        t, h, w, _ = video.shape
        if h % self.GRANULE or w % self.GRANULE:
            raise ValueError(f"H, W must be multiples of {self.GRANULE}")
        fn = self.compiled_bucket(t, h, w)
        alphas, fgrs = fn(params, jnp.asarray(video, jnp.float32) / 255.0)
        alphas = np.asarray(alphas, np.float32)
        fgrs = np.asarray(fgrs, np.float32)
        if output_type == "alpha-mask":
            out = np.repeat(alphas, 3, axis=-1)
        elif output_type == "foreground-mask":
            out = np.repeat((alphas > 0.5).astype(np.float32), 3, axis=-1)
        else:  # green-screen composite
            green = np.zeros_like(fgrs)
            green[..., 1] = 1.0
            out = fgrs * alphas + green * (1.0 - alphas)
        return np.clip(np.rint(out * 255.0), 0, 255).astype(np.uint8)


def trace_specs():
    """graphlint trace spec (models/trace_specs.py): the frame-scan
    matting program (ConvGRU carry over T frames) at tiny topology —
    the only pipeline with no sampler/PRNG in its graph at all."""
    from arbius_tpu.models.trace_specs import TraceSpec

    def build():
        p = RVMPipeline(RVMPipelineConfig.tiny())
        shapes = jax.eval_shape(
            lambda: p.init_params(height=64, width=64))
        args = (shapes,
                jax.ShapeDtypeStruct((2, 64, 64, 3), jnp.float32))
        return p.compiled_bucket(2, 64, 64), args

    return [TraceSpec(model="robust_video_matting", entry="matte",
                      bucket="t2.64x64", mesh="single", dtype="bfloat16",
                      build=build)]
