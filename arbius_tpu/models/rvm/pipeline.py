"""RVM matting pipeline: streamed video → matted video.

One jitted program scans all frames with the ConvGRU states as carry
(`lax.scan` — the TPU form of the reference's frame-streaming container).
Output composition follows the template's output_type enum
(`templates/robust_video_matting.json`):

  green-screen    — foreground over solid green
  alpha-mask      — alpha as grayscale video
  foreground-mask — hard foreground matte (alpha > 0.5) as black/white

Deterministic: no sampling anywhere; bytes depend only on (model build,
input video, output_type). The seed is accepted for interface parity and
unused — matching the reference where RVM output is seed-independent.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from arbius_tpu.models.rvm.model import RVMConfig, RVMStep

OUTPUT_TYPES = ("green-screen", "alpha-mask", "foreground-mask")


@dataclass(frozen=True)
class RVMPipelineConfig:
    model: RVMConfig = RVMConfig()

    @classmethod
    def tiny(cls) -> "RVMPipelineConfig":
        return cls(model=RVMConfig.tiny())


class RVMPipeline:
    GRANULE = 16  # encoder pyramid depth ⇒ H, W must divide by this

    def __init__(self, config: RVMPipelineConfig | None = None):
        self.config = config or RVMPipelineConfig()
        self.step = RVMStep(self.config.model)
        self._buckets: dict[tuple, object] = {}

    def init_params(self, seed: int = 0, height: int = 64,
                    width: int = 64) -> dict:
        frame = jnp.zeros((1, height, width, 3))
        states = self.step.init_states(1, height, width)
        return self.step.init(jax.random.PRNGKey(seed), frame,
                              states)["params"]

    def compiled_bucket(self, frames: int, height: int, width: int):
        key = (frames, height, width)
        cached = self._buckets.get(key)
        if cached is not None:
            return cached

        def run(params, video):  # video: f32 [T, H, W, 3] in [0, 1]
            states = self.step.init_states(1, height, width)

            def body(states, frame):
                alpha, fgr, states = self.step.apply(
                    {"params": params}, frame[None], states)
                return states, (alpha[0], fgr[0])

            _, (alphas, fgrs) = jax.lax.scan(body, states, video)
            return alphas, fgrs

        fn = jax.jit(run)
        self._buckets[key] = fn
        return fn

    def matte(self, params: dict, video: np.ndarray, *,
              output_type: str = "green-screen") -> np.ndarray:
        """uint8 [T,H,W,3] video → uint8 [T,H,W,3] matted video."""
        if output_type not in OUTPUT_TYPES:
            raise ValueError(f"output_type must be one of {OUTPUT_TYPES}")
        if video.dtype != np.uint8 or video.ndim != 4 or video.shape[3] != 3:
            raise ValueError(f"expected uint8 [T,H,W,3], got "
                             f"{video.dtype} {video.shape}")
        t, h, w, _ = video.shape
        if h % self.GRANULE or w % self.GRANULE:
            raise ValueError(f"H, W must be multiples of {self.GRANULE}")
        fn = self.compiled_bucket(t, h, w)
        alphas, fgrs = fn(params, jnp.asarray(video, jnp.float32) / 255.0)
        alphas = np.asarray(alphas, np.float32)
        fgrs = np.asarray(fgrs, np.float32)
        if output_type == "alpha-mask":
            out = np.repeat(alphas, 3, axis=-1)
        elif output_type == "foreground-mask":
            out = np.repeat((alphas > 0.5).astype(np.float32), 3, axis=-1)
        else:  # green-screen composite
            green = np.zeros_like(fgrs)
            green[..., 1] = 1.0
            out = fgrs * alphas + green * (1.0 - alphas)
        return np.clip(np.rint(out * 255.0), 0, 255).astype(np.uint8)
