"""Checkpoint conversion: published RVM state dict → param tree.

The reference mines robust_video_matting through a cog container wrapping
the published `rvm_mobilenetv3` checkpoint
(`templates/robust_video_matting.json` pins
github.com/PeterL1n/RobustVideoMatting). This module maps that checkpoint's
torch key space — torchvision MobileNetV3-Large `backbone.features.*`,
`aspp.*`, the recurrent `decoder.decode{4..0}.*`, `project_mat`/
`project_seg`, and the `refiner.*` deep-guided-filter head — onto
`models/rvm/model.py`'s flax tree, 1:1.

Same contract as sd15/convert.py (the family template): input is a flat
`{key: numpy array}` dict; completeness is enforced (every target leaf must
be produced; shape mismatches fail loudly; `num_batches_tracked` entries
are naturally ignored — conversion pulls, it doesn't push). Bijectivity
(ours → published naming → ours) is tested in tests/test_rvm_convert.py.
Numeric validation against the live published network needs real weights
and is a deployment-time step — the boot self-test's golden CID is the
final arbiter either way.
"""
from __future__ import annotations

import re

import numpy as np

from arbius_tpu.models.rvm.model import RVMConfig
from arbius_tpu.models.sd15.convert import (
    ConversionError,
    _conv,
    _convert_tree,
    _ident,
)

__all__ = ["convert_rvm", "rvm_key_for", "export_tree"]

# BNInf leaf ↔ torch BatchNorm2d state-dict entry
_BN = {"scale": "weight", "bias": "bias", "mean": "running_mean",
       "var": "running_var"}


def _block_layer_indices(row: tuple) -> dict[str, int]:
    """torch `block.{j}` index per stage, from the row alone — torchvision
    appends expand only when expanded≠in and SE only when use_se."""
    in_ch, _k, exp, _out, use_se, _act, _s, _d = row
    idx = {}
    j = 0
    if exp != in_ch:
        idx["expand"] = j
        j += 1
    idx["depthwise"] = j
    j += 1
    if use_se:
        idx["se"] = j
        j += 1
    idx["project"] = j
    return idx


def _cna(prefix: str, rest: str):
    """Conv2dNormActivation: `.0` conv(no bias), `.1` BN."""
    if rest == "conv/kernel":
        return f"{prefix}.0.weight", _conv
    m = re.match(r"bn/(scale|bias|mean|var)$", rest)
    if m:
        return f"{prefix}.1.{_BN[m.group(1)]}", _ident
    raise ConversionError(f"unmapped ConvBNAct leaf {rest!r} under {prefix}")


def _gru(prefix: str, rest: str):
    """ConvGRU: ih/hh are Sequential(Conv2d, activation) → `.0`."""
    m = re.match(r"(ih|hh)/(kernel|bias)$", rest)
    if m:
        leaf = "weight" if m.group(2) == "kernel" else "bias"
        tf = _conv if m.group(2) == "kernel" else _ident
        return f"{prefix}.{m.group(1)}.0.{leaf}", tf
    raise ConversionError(f"unmapped ConvGRU leaf {rest!r} under {prefix}")


def rvm_key_for(path: str, config: RVMConfig = RVMConfig()):
    """our param path → (published torch key, leaf transform)."""
    part, _, rest = path.partition("/")

    if part == "backbone":
        sub, _, rest = rest.partition("/")
        if sub == "stem":
            return _cna("backbone.features.0", rest)
        if sub == "lastconv":
            n = len(config.ir_rows) + 1
            return _cna(f"backbone.features.{n}", rest)
        m = re.match(r"block_(\d+)$", sub)
        if m:
            fi = int(m.group(1))
            row = config.ir_rows[fi - 1]
            idx = _block_layer_indices(row)
            stage, _, leaf = rest.partition("/")
            if stage == "se":
                mm = re.match(r"(fc1|fc2)/(kernel|bias)$", leaf)
                if mm:
                    tname = "weight" if mm.group(2) == "kernel" else "bias"
                    tf = _conv if mm.group(2) == "kernel" else _ident
                    return (f"backbone.features.{fi}.block.{idx['se']}."
                            f"{mm.group(1)}.{tname}"), tf
            elif stage in idx:
                return _cna(f"backbone.features.{fi}.block.{idx[stage]}",
                            leaf)

    elif part == "aspp":
        if rest == "aspp1_conv/kernel":
            return "aspp.aspp1.0.weight", _conv
        m = re.match(r"aspp1_bn/(scale|bias|mean|var)$", rest)
        if m:
            return f"aspp.aspp1.1.{_BN[m.group(1)]}", _ident
        if rest == "aspp2_conv/kernel":
            return "aspp.aspp2.1.weight", _conv

    elif part == "decoder":
        stage, _, rest = rest.partition("/")
        if stage == "decode4":
            if rest.startswith("gru/"):
                return _gru("decoder.decode4.gru", rest[4:])
        elif stage in ("decode3", "decode2", "decode1"):
            if rest == "conv/kernel":
                return f"decoder.{stage}.conv.0.weight", _conv
            m = re.match(r"bn/(scale|bias|mean|var)$", rest)
            if m:
                return f"decoder.{stage}.conv.1.{_BN[m.group(1)]}", _ident
            if rest.startswith("gru/"):
                return _gru(f"decoder.{stage}.gru", rest[4:])
        elif stage == "decode0":
            # Sequential(conv,BN,ReLU,conv,BN,ReLU) → 0,1,3,4
            if rest == "conv_a/kernel":
                return "decoder.decode0.conv.0.weight", _conv
            if rest == "conv_b/kernel":
                return "decoder.decode0.conv.3.weight", _conv
            m = re.match(r"bn_([ab])/(scale|bias|mean|var)$", rest)
            if m:
                j = 1 if m.group(1) == "a" else 4
                return f"decoder.decode0.conv.{j}.{_BN[m.group(2)]}", _ident

    elif part in ("project_mat", "project_seg"):
        if rest == "conv/kernel":
            return f"{part}.conv.weight", _conv
        if rest == "conv/bias":
            return f"{part}.conv.bias", _ident

    elif part == "refiner":
        if rest == "box_filter/kernel":
            return "refiner.box_filter.weight", _conv
        # Sequential(conv,BN,ReLU,conv,BN,ReLU,conv) → 0,1,3,4,6
        if rest == "conv_a/kernel":
            return "refiner.conv.0.weight", _conv
        if rest == "conv_b/kernel":
            return "refiner.conv.3.weight", _conv
        if rest == "conv_c/kernel":
            return "refiner.conv.6.weight", _conv
        if rest == "conv_c/bias":
            return "refiner.conv.6.bias", _ident
        m = re.match(r"bn_([ab])/(scale|bias|mean|var)$", rest)
        if m:
            j = 1 if m.group(1) == "a" else 4
            return f"refiner.conv.{j}.{_BN[m.group(2)]}", _ident

    raise ConversionError(f"unmapped rvm param path {path!r}")


def convert_rvm(state_dict: dict, template_params: dict,
                config: RVMConfig = RVMConfig()) -> dict:
    """Published MattingNetwork state dict → MattingStep param tree."""
    return _convert_tree(template_params, state_dict,
                         lambda p: rvm_key_for(p, config))


def export_tree(params: dict, config: RVMConfig = RVMConfig()) -> dict:
    """ours → published naming, inverting the leaf transforms (test
    round-trip + fixture fabrication)."""
    import jax

    out: dict[str, np.ndarray] = {}

    def visit(path, leaf):
        p = "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                     for k in path)
        key, tf = rvm_key_for(p, config)
        w = np.asarray(leaf)
        out[key] = np.transpose(w, (3, 2, 0, 1)) if tf is _conv else w

    jax.tree_util.tree_map_with_path(visit, params)
    return out
