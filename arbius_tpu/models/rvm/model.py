"""Robust Video Matting — recurrent ConvGRU matting network.

Capability target: `templates/robust_video_matting.json` (SURVEY.md §2.3):
video file in, matted video out (output_type ∈ green-screen | alpha-mask |
foreground-mask). RVM's defining property is *recurrence*: per-scale
ConvGRU states carry temporal context frame to frame, so the model streams
— which on TPU means `lax.scan` over the frame axis with the GRU states as
carry (no frame-axis SP here by design; the reference model is inherently
sequential over frames, SURVEY.md §5 long-context notes).

Topology (faithful to the RVM design, sized for the template's task):
strided-conv encoder pyramid (1/2..1/16) → bottleneck → decoder that
upsamples with skip connections and a ConvGRU at each scale → output head
producing alpha [0,1] + foreground residual.
"""
from __future__ import annotations

from dataclasses import dataclass

import flax.linen as nn
import jax
import jax.numpy as jnp

from arbius_tpu.models.common import GroupNorm32


@dataclass(frozen=True)
class RVMConfig:
    enc_channels: tuple[int, ...] = (16, 32, 64, 128)   # scales 1/2..1/16
    dec_channels: tuple[int, ...] = (80, 40, 32, 16)    # coarse→fine
    dtype: str = "bfloat16"

    @property
    def jdtype(self):
        return jnp.dtype(self.dtype)

    @classmethod
    def tiny(cls) -> "RVMConfig":
        return cls(enc_channels=(4, 8, 8, 8), dec_channels=(8, 8, 4, 4))


class ConvGRUCell(nn.Module):
    """Convolutional GRU over NHWC feature maps (the RVM recurrent unit)."""
    channels: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, h, x):
        hx = jnp.concatenate([h.astype(self.dtype), x.astype(self.dtype)],
                             axis=-1)
        zr = nn.Conv(2 * self.channels, (3, 3), padding=1, dtype=self.dtype,
                     name="zr")(hx)
        z, r = jnp.split(nn.sigmoid(zr.astype(jnp.float32)), 2, axis=-1)
        cand = nn.Conv(self.channels, (3, 3), padding=1, dtype=self.dtype,
                       name="cand")(
            jnp.concatenate([(r * h.astype(jnp.float32)).astype(self.dtype),
                             x.astype(self.dtype)], axis=-1))
        cand = jnp.tanh(cand.astype(jnp.float32))
        return (1 - z) * h.astype(jnp.float32) + z * cand


class EncoderBlock(nn.Module):
    channels: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(self.channels, (3, 3), strides=(2, 2), padding=1,
                    dtype=self.dtype)(x)
        x = GroupNorm32()(x)
        x = nn.silu(x)
        x = nn.Conv(self.channels, (3, 3), padding=1, dtype=self.dtype)(x)
        x = GroupNorm32()(x)
        return nn.silu(x)


class RVMStep(nn.Module):
    """One frame through encoder+recurrent decoder.

    __call__(frame[B,H,W,3], states) -> (alpha[B,H,W,1], fgr[B,H,W,3],
    new_states); `states` is a tuple of per-scale GRU hidden maps.
    """
    config: RVMConfig

    @nn.compact
    def __call__(self, frame, states):
        cfg = self.config
        dt = cfg.jdtype
        x = frame.astype(dt)
        feats = []
        h = x
        for i, ch in enumerate(cfg.enc_channels):
            h = EncoderBlock(ch, dt, name=f"enc_{i}")(h)
            feats.append(h)

        new_states = []
        d = feats[-1]
        for i, ch in enumerate(cfg.dec_channels):
            scale_idx = len(cfg.enc_channels) - 1 - i
            d = nn.Conv(ch, (3, 3), padding=1, dtype=dt,
                        name=f"dec_conv_{i}")(d)
            d = nn.silu(GroupNorm32(name=f"dec_norm_{i}")(d))
            s = ConvGRUCell(ch, dt, name=f"gru_{i}")(states[i], d)
            new_states.append(s)
            d = s.astype(dt)
            if scale_idx > 0:
                b, hh, ww, c = d.shape
                d = jax.image.resize(d, (b, hh * 2, ww * 2, c),
                                     method="nearest")
                skip = feats[scale_idx - 1]
                d = jnp.concatenate([d, skip], axis=-1)
        # final upsample to input resolution (encoder starts at 1/2)
        b, hh, ww, c = d.shape
        d = jax.image.resize(d, (b, hh * 2, ww * 2, c), method="nearest")
        d = jnp.concatenate([d, x], axis=-1)
        d = nn.Conv(cfg.dec_channels[-1], (3, 3), padding=1, dtype=dt,
                    name="out_conv")(d)
        d = nn.silu(GroupNorm32(name="out_norm")(d))
        out = nn.Conv(4, (3, 3), padding=1, dtype=jnp.float32,
                      name="head")(d.astype(jnp.float32))
        alpha = nn.sigmoid(out[..., :1])
        fgr = jnp.clip(frame.astype(jnp.float32) + out[..., 1:], 0.0, 1.0)
        return alpha, fgr, tuple(new_states)

    def init_states(self, batch: int, height: int, width: int):
        """Zero GRU states for a (batch, H, W) stream."""
        cfg = self.config
        states = []
        for i, ch in enumerate(cfg.dec_channels):
            scale = 2 ** (len(cfg.enc_channels) - i)
            states.append(jnp.zeros((batch, height // scale, width // scale,
                                     ch), jnp.float32))
        return tuple(states)
