"""Robust Video Matting — the published RVM network, TPU-native.

Capability target: `templates/robust_video_matting.json`, which pins
github.com/PeterL1n/RobustVideoMatting (the `rvm_mobilenetv3` variant the
reference's cog container serves). This module implements that published
topology 1:1 so the published checkpoint converts onto this param tree
(`models/rvm/convert.py`):

  backbone     MobileNetV3-Large encoder (torchvision layout: stem conv,
               15 inverted-residual blocks, final 1×1 conv), last stage
               dilated so f4 sits at 1/16 — taps f1@1/2(16ch),
               f2@1/4(24ch), f3@1/8(40ch), f4@1/16(960ch)
  aspp         LR-ASPP head: 1×1+BN+ReLU gated by a global-pool sigmoid
               branch → 128ch
  decoder      recurrent decoder: BottleneckBlock(ConvGRU over half the
               channels) at 1/16, three UpsamplingBlocks (bilinear ×2 +
               skip + downsampled-src concat + ConvGRU on half channels),
               OutputBlock at full res
  project_mat  1×1 conv → [fgr residual(3) | pha(1)]
  project_seg  1×1 conv → segmentation logits (checkpoint completeness)
  refiner      DeepGuidedFilter head used when inference runs the
               downsample-then-refine path (the published auto
               downsample_ratio = min(512/max(H,W), 1))

RVM's defining property is *recurrence*: the four ConvGRU states carry
temporal context frame to frame, so the model streams — on TPU that is
`lax.scan` over the frame axis with the GRU states as carry (no frame-axis
SP by design; the model is inherently sequential over frames, SURVEY.md §5).

BatchNorm runs in inference form (`BNInf`): the published running stats are
parameters, normalization is a fused scale/shift — the TPU-correct shape
for a frozen-stats conv net (no batch-stat reductions in the scan body).
Conv compute is bfloat16; norms, gates and the matting head are float32.
"""
from __future__ import annotations

from dataclasses import dataclass

import flax.linen as nn
import jax
import jax.numpy as jnp

# torchvision mobilenet_v3_large inverted-residual plan, dilated last stage —
# exactly the row list RVM's MobileNetV3LargeEncoder builds:
# (in_ch, kernel, expanded_ch, out_ch, use_se, activation, stride, dilation)
MOBILENETV3_LARGE_ROWS: tuple[tuple, ...] = (
    (16, 3, 16, 16, False, "relu", 1, 1),
    (16, 3, 64, 24, False, "relu", 2, 1),
    (24, 3, 72, 24, False, "relu", 1, 1),
    (24, 5, 72, 40, True, "relu", 2, 1),
    (40, 5, 120, 40, True, "relu", 1, 1),
    (40, 5, 120, 40, True, "relu", 1, 1),
    (40, 3, 240, 80, False, "hardswish", 2, 1),
    (80, 3, 200, 80, False, "hardswish", 1, 1),
    (80, 3, 184, 80, False, "hardswish", 1, 1),
    (80, 3, 184, 80, False, "hardswish", 1, 1),
    (80, 3, 480, 112, True, "hardswish", 1, 1),
    (112, 3, 672, 112, True, "hardswish", 1, 1),
    (112, 5, 672, 160, True, "hardswish", 2, 2),
    (160, 5, 960, 160, True, "hardswish", 1, 2),
    (160, 5, 960, 160, True, "hardswish", 1, 2),
)

# ImageNet normalization the published backbone was trained with.
_IMAGENET_MEAN = (0.485, 0.456, 0.406)
_IMAGENET_STD = (0.229, 0.224, 0.225)


def _make_divisible(v: float, divisor: int = 8) -> int:
    """torchvision's channel-rounding rule (SE squeeze widths)."""
    new_v = max(divisor, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


@dataclass(frozen=True)
class RVMConfig:
    """Published rvm_mobilenetv3 by default; tiny() shrinks every stage but
    keeps the exact module structure so the converter's key schema is
    identical."""
    ir_rows: tuple[tuple, ...] = MOBILENETV3_LARGE_ROWS
    stem_ch: int = 16
    last_ch: int = 960           # final 1×1 conv of the backbone
    taps: tuple[int, int, int] = (1, 3, 6)  # feature idx for f1, f2, f3
    aspp_ch: int = 128           # LR-ASPP out = bottleneck channels
    dec_ch: tuple[int, int, int] = (80, 40, 32)  # UpsamplingBlock outs
    out_ch: int = 16             # OutputBlock hidden = refiner hid channels
    dtype: str = "bfloat16"

    @property
    def jdtype(self):
        return jnp.dtype(self.dtype)

    @classmethod
    def tiny(cls) -> "RVMConfig":
        return cls(
            ir_rows=(
                (8, 3, 8, 8, False, "relu", 1, 1),
                (8, 3, 16, 12, False, "relu", 2, 1),
                (12, 5, 36, 12, True, "relu", 2, 1),
                (12, 3, 24, 16, False, "hardswish", 2, 1),
            ),
            stem_ch=8, last_ch=24, taps=(1, 2, 3),
            aspp_ch=16, dec_ch=(16, 8, 8), out_ch=8)


class BNInf(nn.Module):
    """Inference-form BatchNorm2d: the published running stats are params.

    Torch key mapping: scale↔weight, bias↔bias, mean↔running_mean,
    var↔running_var (`num_batches_tracked` has no analogue). eps matches
    the source module (1e-3 for torchvision MobileNetV3 BNs, 1e-5 for
    RVM's own decoder/aspp/refiner BNs)."""
    channels: int
    eps: float = 1e-5

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, (self.channels,),
                           jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (self.channels,),
                          jnp.float32)
        mean = self.param("mean", nn.initializers.zeros, (self.channels,),
                          jnp.float32)
        var = self.param("var", nn.initializers.ones, (self.channels,),
                         jnp.float32)
        orig = x.dtype
        x = x.astype(jnp.float32)
        x = (x - mean) * (scale * jax.lax.rsqrt(var + self.eps)) + bias
        return x.astype(orig)


def _act(name: str | None, x):
    if name is None:
        return x
    if name == "relu":
        return nn.relu(x)
    if name == "hardswish":
        # computed in f32: hard_swish has a subtraction of near-equal terms
        return jax.nn.hard_swish(x.astype(jnp.float32)).astype(x.dtype)
    raise ValueError(f"unknown activation {name!r}")


class ConvBNAct(nn.Module):
    """torchvision Conv2dNormActivation: conv(bias=False) + BN + act."""
    channels: int
    kernel: int = 3
    stride: int = 1
    dilation: int = 1
    groups: int = 1
    activation: str | None = "relu"
    bn_eps: float = 1e-3
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        pad = (self.kernel - 1) // 2 * self.dilation
        x = nn.Conv(self.channels, (self.kernel, self.kernel),
                    strides=(self.stride, self.stride), padding=pad,
                    kernel_dilation=(self.dilation, self.dilation),
                    feature_group_count=self.groups, use_bias=False,
                    dtype=self.dtype, name="conv")(x)
        x = BNInf(self.channels, eps=self.bn_eps, name="bn")(x)
        return _act(self.activation, x)


class SqueezeExcite(nn.Module):
    """torchvision SqueezeExcitation: pool → fc1 → ReLU → fc2 → hardsigmoid."""
    channels: int
    squeeze: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        s = jnp.mean(x.astype(jnp.float32), axis=(1, 2), keepdims=True)
        s = nn.Conv(self.squeeze, (1, 1), dtype=jnp.float32, name="fc1")(s)
        s = nn.relu(s)
        s = nn.Conv(self.channels, (1, 1), dtype=jnp.float32, name="fc2")(s)
        return (x.astype(jnp.float32) * jax.nn.hard_sigmoid(s)).astype(x.dtype)


class InvertedResidual(nn.Module):
    """One MobileNetV3 block; submodule presence mirrors torchvision, so
    torch `block.{j}` indices are recoverable from the row alone."""
    row: tuple  # (in, kernel, exp, out, se, act, stride, dilation)
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        in_ch, kernel, exp, out, use_se, act, stride, dilation = self.row
        # torchvision: dilation forces effective stride 1 (shape preserved)
        eff_stride = 1 if dilation > 1 else stride
        h = x
        if exp != in_ch:
            h = ConvBNAct(exp, 1, activation=act, dtype=self.dtype,
                          name="expand")(h)
        h = ConvBNAct(exp, kernel, stride=eff_stride, dilation=dilation,
                      groups=exp, activation=act, dtype=self.dtype,
                      name="depthwise")(h)
        if use_se:
            h = SqueezeExcite(exp, _make_divisible(exp // 4),
                              dtype=self.dtype, name="se")(h)
        h = ConvBNAct(out, 1, activation=None, dtype=self.dtype,
                      name="project")(h)
        if stride == 1 and in_ch == out:
            h = h + x
        return h


class MobileNetV3Encoder(nn.Module):
    """RVM's MobileNetV3LargeEncoder: normalize, stem, IR blocks, last 1×1;
    returns the four pyramid taps (f1..f3 per config, f4 after last conv)."""
    config: RVMConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        dt = cfg.jdtype
        x = (x.astype(jnp.float32) - jnp.asarray(_IMAGENET_MEAN)) \
            / jnp.asarray(_IMAGENET_STD)
        x = ConvBNAct(cfg.stem_ch, 3, stride=2, activation="hardswish",
                      dtype=dt, name="stem")(x.astype(dt))
        feats = {}
        for i, row in enumerate(cfg.ir_rows):
            x = InvertedResidual(row, dtype=dt, name=f"block_{i + 1}")(x)
            feats[i + 1] = x
        x = ConvBNAct(cfg.last_ch, 1, activation="hardswish", dtype=dt,
                      name="lastconv")(x)
        t1, t2, t3 = cfg.taps
        return feats[t1], feats[t2], feats[t3], x


class LRASPP(nn.Module):
    """RVM's LR-ASPP: 1×1+BN+ReLU gated by global-pool → 1×1 → sigmoid."""
    channels: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        a = nn.Conv(self.channels, (1, 1), use_bias=False, dtype=self.dtype,
                    name="aspp1_conv")(x)
        a = nn.relu(BNInf(self.channels, name="aspp1_bn")(a))
        g = jnp.mean(x.astype(jnp.float32), axis=(1, 2), keepdims=True)
        g = nn.Conv(self.channels, (1, 1), use_bias=False, dtype=jnp.float32,
                    name="aspp2_conv")(g)
        return (a.astype(jnp.float32) * nn.sigmoid(g)).astype(a.dtype)


class ConvGRU(nn.Module):
    """RVM's ConvGRU: ih conv → sigmoid → (r,z); hh conv over [x, r·h] →
    tanh candidate; h' = (1−z)·h + z·c. Gates in float32 (state is the
    temporal memory; bf16 accumulation drifts over long streams)."""
    channels: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, h):
        xh = jnp.concatenate([x.astype(self.dtype), h.astype(self.dtype)],
                             axis=-1)
        rz = nn.Conv(2 * self.channels, (3, 3), padding=1, dtype=self.dtype,
                     name="ih")(xh)
        r, z = jnp.split(nn.sigmoid(rz.astype(jnp.float32)), 2, axis=-1)
        c = nn.Conv(self.channels, (3, 3), padding=1, dtype=self.dtype,
                    name="hh")(
            jnp.concatenate([x.astype(self.dtype),
                             (r * h.astype(jnp.float32)).astype(self.dtype)],
                            axis=-1))
        c = jnp.tanh(c.astype(jnp.float32))
        return (1.0 - z) * h.astype(jnp.float32) + z * c


class BottleneckBlock(nn.Module):
    """decode4: ConvGRU over the second half of the channels only."""
    channels: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, r):
        a, b = jnp.split(x, 2, axis=-1)
        b = ConvGRU(self.channels // 2, dtype=self.dtype, name="gru")(b, r)
        return jnp.concatenate([a, b.astype(x.dtype)], axis=-1), b


class UpsamplingBlock(nn.Module):
    """decode3/2/1: bilinear ×2, concat [x | skip | downsampled src],
    conv+BN+ReLU, ConvGRU over the second half of the channels."""
    channels: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, f, s, r):
        b_, h, w, c = x.shape
        x = jax.image.resize(x.astype(jnp.float32), (b_, 2 * h, 2 * w, c),
                             method="bilinear").astype(self.dtype)
        x = x[:, :s.shape[1], :s.shape[2]]  # crop to skip (odd sizes)
        x = jnp.concatenate([x, f.astype(self.dtype), s.astype(self.dtype)],
                            axis=-1)
        x = nn.Conv(self.channels, (3, 3), padding=1, use_bias=False,
                    dtype=self.dtype, name="conv")(x)
        x = nn.relu(BNInf(self.channels, name="bn")(x))
        a, b = jnp.split(x, 2, axis=-1)
        b = ConvGRU(self.channels // 2, dtype=self.dtype, name="gru")(b, r)
        return jnp.concatenate([a, b.astype(x.dtype)], axis=-1), b


class OutputBlock(nn.Module):
    """decode0: bilinear ×2 to src res, concat src, two conv+BN+ReLU."""
    channels: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, s):
        b_, h, w, c = x.shape
        x = jax.image.resize(x.astype(jnp.float32), (b_, 2 * h, 2 * w, c),
                             method="bilinear").astype(self.dtype)
        x = x[:, :s.shape[1], :s.shape[2]]
        x = jnp.concatenate([x, s.astype(self.dtype)], axis=-1)
        x = nn.Conv(self.channels, (3, 3), padding=1, use_bias=False,
                    dtype=self.dtype, name="conv_a")(x)
        x = nn.relu(BNInf(self.channels, name="bn_a")(x))
        x = nn.Conv(self.channels, (3, 3), padding=1, use_bias=False,
                    dtype=self.dtype, name="conv_b")(x)
        return nn.relu(BNInf(self.channels, name="bn_b")(x))


def _avgpool2(x):
    """AvgPool2d(2,2) — pipeline guarantees even dims at every level."""
    b, h, w, c = x.shape
    return jnp.mean(x.reshape(b, h // 2, 2, w // 2, 2, c), axis=(2, 4))


class RecurrentDecoder(nn.Module):
    """RVM's RecurrentDecoder: src pyramid by avg-pool, four recurrent
    stages coarse→fine; returns (hid at src res, new states r1..r4)."""
    config: RVMConfig

    @nn.compact
    def __call__(self, s0, f1, f2, f3, f4, rec):
        cfg = self.config
        dt = cfg.jdtype
        r1, r2, r3, r4 = rec
        s0 = s0.astype(jnp.float32)
        s1 = _avgpool2(s0)
        s2 = _avgpool2(s1)
        s3 = _avgpool2(s2)
        x4, r4 = BottleneckBlock(cfg.aspp_ch, dt, name="decode4")(f4, r4)
        x3, r3 = UpsamplingBlock(cfg.dec_ch[0], dt, name="decode3")(
            x4, f3, s3, r3)
        x2, r2 = UpsamplingBlock(cfg.dec_ch[1], dt, name="decode2")(
            x3, f2, s2, r2)
        x1, r1 = UpsamplingBlock(cfg.dec_ch[2], dt, name="decode1")(
            x2, f1, s1, r1)
        x0 = OutputBlock(cfg.out_ch, dt, name="decode0")(x1, s0)
        return x0, (r1, r2, r3, r4)


class Projection(nn.Module):
    """1×1 conv head (project_mat / project_seg)."""
    channels: int

    @nn.compact
    def __call__(self, x):
        return nn.Conv(self.channels, (1, 1), dtype=jnp.float32,
                       name="conv")(x.astype(jnp.float32))


class DeepGuidedFilterRefiner(nn.Module):
    """RVM's deep guided filter: box-filter statistics of the base
    (downsampled) prediction against the base source, a learned 1×1 head
    producing the affine A, bilinear-upsampled A·x+b on the fine source."""
    hid_channels: int = 16

    @nn.compact
    def __call__(self, fine_src, base_src, base_fgr, base_pha, base_hid):
        f32 = jnp.float32
        fine_x = jnp.concatenate(
            [fine_src, jnp.mean(fine_src, axis=-1, keepdims=True)],
            axis=-1).astype(f32)
        base_x = jnp.concatenate(
            [base_src, jnp.mean(base_src, axis=-1, keepdims=True)],
            axis=-1).astype(f32)
        base_y = jnp.concatenate([base_fgr, base_pha], axis=-1).astype(f32)

        box = nn.Conv(4, (3, 3), padding=1, feature_group_count=4,
                      use_bias=False, dtype=f32, name="box_filter")
        mean_x = box(base_x)
        mean_y = box(base_y)
        cov_xy = box(base_x * base_y) - mean_x * mean_y
        var_x = box(base_x * base_x) - mean_x * mean_x

        h = jnp.concatenate([cov_xy, var_x, base_hid.astype(f32)], axis=-1)
        h = nn.Conv(self.hid_channels, (1, 1), use_bias=False, dtype=f32,
                    name="conv_a")(h)
        h = nn.relu(BNInf(self.hid_channels, name="bn_a")(h))
        h = nn.Conv(self.hid_channels, (1, 1), use_bias=False, dtype=f32,
                    name="conv_b")(h)
        h = nn.relu(BNInf(self.hid_channels, name="bn_b")(h))
        A = nn.Conv(4, (1, 1), dtype=f32, name="conv_c")(h)
        b = mean_y - A * mean_x

        bb, hh, ww, _ = fine_src.shape
        A = jax.image.resize(A, (bb, hh, ww, 4), method="bilinear")
        b = jax.image.resize(b, (bb, hh, ww, 4), method="bilinear")
        out = A * fine_x + b
        return out[..., :3], out[..., 3:]


class MattingStep(nn.Module):
    """One frame through the full MattingNetwork.

    __call__(src[B,H,W,3] in [0,1], rec, base_hw) →
    (fgr[B,H,W,3], pha[B,H,W,1], new_rec). `base_hw` is the static
    downsampled working size; None runs the direct full-res path (no
    refiner), matching the published downsample_ratio semantics. The
    segmentation head is computed (and discarded by XLA when unused) so
    its published weights live in the param tree."""
    config: RVMConfig

    @nn.compact
    def __call__(self, src, rec, base_hw: tuple[int, int] | None = None):
        cfg = self.config
        if base_hw is not None:
            b, _, _, c = src.shape
            src_sm = jax.image.resize(
                src.astype(jnp.float32), (b, base_hw[0], base_hw[1], c),
                method="bilinear")
        else:
            src_sm = src
        f1, f2, f3, f4 = MobileNetV3Encoder(cfg, name="backbone")(src_sm)
        f4 = LRASPP(cfg.aspp_ch, cfg.jdtype, name="aspp")(f4)
        hid, new_rec = RecurrentDecoder(cfg, name="decoder")(
            src_sm, f1, f2, f3, f4, rec)
        out = Projection(4, name="project_mat")(hid)
        _seg = Projection(1, name="project_seg")(hid)  # checkpoint parity
        fgr_res, pha = out[..., :3], out[..., 3:]
        if base_hw is not None:
            fgr_res, pha = DeepGuidedFilterRefiner(
                cfg.out_ch, name="refiner")(src, src_sm, fgr_res, pha, hid)
        fgr = jnp.clip(fgr_res + src.astype(jnp.float32), 0.0, 1.0)
        pha = jnp.clip(pha, 0.0, 1.0)
        return fgr, pha, new_rec

    def init_rec(self, batch: int, height: int, width: int):
        """Zero GRU states for a working (base) resolution of H×W.
        Scales: r1@1/2, r2@1/4, r3@1/8, r4@1/16; channels are half of
        each stage's output (the GRU runs on the split half)."""
        cfg = self.config
        chans = (cfg.dec_ch[2] // 2, cfg.dec_ch[1] // 2, cfg.dec_ch[0] // 2,
                 cfg.aspp_ch // 2)
        return tuple(
            jnp.zeros((batch, height >> s, width >> s, c), jnp.float32)
            for s, c in zip((1, 2, 3, 4), chans))
