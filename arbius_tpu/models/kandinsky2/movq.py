"""MOVQ decoder — Kandinsky-2's latent→pixel stage.

Capability target: the MOVQ/VQ decoder of the kandinsky2 template
(`templates/kandinsky2.json` model class, SURVEY.md §2.3). MOVQ is a
VQGAN-style decoder whose distinguishing feature is *spatially modulated*
group norm: normalization parameters are conv-predicted from the quantized
latent, re-injecting spatial detail at every scale.

Topology mirrors the published diffusers-format VQModel decoder
(norm_type="spatial") so converted weights drive it 1:1
(kandinsky2/convert.py): post_quant conv → conv_in → mid
(res, spatially-normed attention, res) → up tower with
`layers_per_block + 1` resnets per level (the published VQ decoder's
count) → spatial norm_out → conv_out. The Kandinsky latent path decodes
CONTINUOUS latents (the published pipeline's force_not_quantize), so no
codebook lookup exists here.

TPU notes: NHWC convs in bf16, norms in f32 (same policy as models/common);
attention at the lowest resolution only, so the op mix is conv-dominated —
pure MXU work with no dynamic shapes.
"""
from __future__ import annotations

from dataclasses import dataclass

import flax.linen as nn
import jax
import jax.numpy as jnp

from arbius_tpu.models.common import Attention, GroupNorm32, Upsample


@dataclass(frozen=True)
class MOVQConfig:
    latent_channels: int = 4
    block_channels: tuple[int, ...] = (128, 256, 256, 512)  # low→high res order
    layers_per_block: int = 2     # published decoder runs this + 1 resnets
    dtype: str = "bfloat16"

    @property
    def jdtype(self):
        return jnp.dtype(self.dtype)

    @classmethod
    def tiny(cls) -> "MOVQConfig":
        return cls(block_channels=(8, 8, 8, 8), layers_per_block=1)


class SpatialNorm(nn.Module):
    """GroupNorm whose scale/shift are conv-predicted from the latent."""
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, h, z):
        b, hh, ww, c = h.shape
        z_up = jax.image.resize(z, (b, hh, ww, z.shape[-1]), method="nearest")
        normed = GroupNorm32(epsilon=1e-6, name="norm")(h)
        scale = nn.Conv(c, (1, 1), dtype=self.dtype, name="conv_y")(z_up)
        shift = nn.Conv(c, (1, 1), dtype=self.dtype, name="conv_b")(z_up)
        return normed * scale.astype(normed.dtype) + shift.astype(normed.dtype)


class MOVQResBlock(nn.Module):
    out_channels: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, z):
        h = SpatialNorm(self.dtype, name="norm1")(x, z)
        h = nn.silu(h)
        h = nn.Conv(self.out_channels, (3, 3), padding=1, dtype=self.dtype)(h)
        h = SpatialNorm(self.dtype, name="norm2")(h, z)
        h = nn.silu(h)
        h = nn.Conv(self.out_channels, (3, 3), padding=1, dtype=self.dtype)(h)
        if x.shape[-1] != self.out_channels:
            x = nn.Conv(self.out_channels, (1, 1), dtype=self.dtype,
                        name="skip")(x)
        return x + h


class MOVQDecoder(nn.Module):
    """__call__(z[B,h,w,4]) -> pixels[B,8h,8w,3] in [-1, 1]."""
    config: MOVQConfig

    @nn.compact
    def __call__(self, z):
        cfg = self.config
        dt = cfg.jdtype
        z = z.astype(dt)
        # spatial norms condition on the RAW latent; the post-quant conv
        # feeds only the conv tower (published decode(quant) semantics)
        zin = nn.Conv(cfg.latent_channels, (1, 1), dtype=dt,
                      name="post_quant")(z)
        chans = cfg.block_channels
        h = nn.Conv(chans[-1], (3, 3), padding=1, dtype=dt, name="conv_in")(zin)

        # mid: res + attention + res at the lowest resolution
        h = MOVQResBlock(chans[-1], dt, name="mid_res_0")(h, z)
        b, hh, ww, c = h.shape
        attn_in = SpatialNorm(dt, name="mid_attn_norm")(h, z).reshape(b, hh * ww, c)
        h = h + Attention(num_heads=1, head_dim=c, dtype=dt, qkv_bias=True,
                          name="mid_attn")(attn_in).reshape(b, hh, ww, c)
        h = MOVQResBlock(chans[-1], dt, name="mid_res_1")(h, z)

        # upsampling tower: 3 doublings (×8 total like the VAE factor);
        # layers_per_block + 1 resnets per level, the published count
        for level in reversed(range(len(chans))):
            for j in range(cfg.layers_per_block + 1):
                h = MOVQResBlock(chans[level], dt,
                                 name=f"up_{level}_res_{j}")(h, z)
            if level > 0:
                h = Upsample(chans[level], dt, name=f"up_{level}_us")(h)

        h = SpatialNorm(dt, name="norm_out")(h, z)
        h = nn.silu(h)
        return nn.Conv(3, (3, 3), padding=1, dtype=jnp.float32,
                       name="conv_out")(h.astype(jnp.float32))
