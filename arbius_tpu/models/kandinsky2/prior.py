"""Kandinsky-2 diffusion prior: text embedding → CLIP-image embedding.

Capability target: the prior stage of the kandinsky2 template — the
reference's only enabled + boot-self-test model (`templates/
kandinsky2.json`, `miner/src/index.ts:844-877`). Kandinsky generates in
two diffusion stages; the first denoises a single CLIP-image-embedding
VECTOR conditioned on the text encoding.

The computation graph mirrors the published diffusers `PriorTransformer`
(the format the kandinsky-community checkpoints ship in) so converted
weights drive this module 1:1 (see kandinsky2/convert.py):

  token sequence = [ projected text states (77),
                     projected pooled text embed (1),
                     time embedding (1),
                     projected noisy image embed (1),
                     learned prd query token (1) ]  + positional embedding
  → pre-LN transformer blocks (biased attention, plain-GELU FF)
  → final LayerNorm → clip-embedding readout at the prd position.

The prior operates in a NORMALIZED clip space: checkpoints carry
clip_mean/clip_std vectors and the sampled embedding is de-normalized on
the way out (`x * clip_std + clip_mean`).

TPU-first shape: everything is a [B, S, D] matmul — ideal MXU work; no
pixel tensors exist at this stage. Sampling is an x0-prediction DDIM loop
under `lax.scan` (deterministic, eta=0); the published UnCLIP ancestral
scheduler is replaced by this deterministic rule — weights are compatible,
the protocol requires determinism, and the sampler is not part of the
checkpoint.
"""
from __future__ import annotations

from dataclasses import dataclass

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from arbius_tpu.models.common import Attention, sinusoidal_embedding

NEG_INF = -1e9


@dataclass(frozen=True)
class PriorConfig:
    clip_dim: int = 1280          # image-embedding dimensionality (2.2: bigG)
    width: int = 2048             # heads * head_dim
    layers: int = 20
    heads: int = 32
    text_len: int = 77
    dtype: str = "bfloat16"

    @property
    def jdtype(self):
        return jnp.dtype(self.dtype)

    @classmethod
    def tiny(cls) -> "PriorConfig":
        return cls(clip_dim=16, width=32, layers=2, heads=2, text_len=8)


class PriorBlock(nn.Module):
    """Pre-LN self-attention (biased projections) + plain-GELU MLP.

    Matches the published prior's block (diffusers BasicTransformerBlock
    with attention_bias=True, activation_fn="gelu", self-attention only).
    """
    heads: int
    head_dim: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, mask=None):
        h = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32, name="norm1")(x).astype(self.dtype)
        x = x + Attention(self.heads, self.head_dim, self.dtype,
                          qkv_bias=True, name="attn1")(h, mask=mask)
        h = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32, name="norm3")(x).astype(self.dtype)
        h = nn.Dense(x.shape[-1] * 4, dtype=self.dtype, name="ff_in")(h)
        h = nn.gelu(h, approximate=False)  # diffusers 'gelu' = exact erf
        h = nn.Dense(x.shape[-1], dtype=self.dtype, name="ff_out")(h)
        return x + h


class PriorTransformer(nn.Module):
    """Predicts the clean (normalized-space) image embedding.

    __call__(noisy_embed[B,D], t[B], text_tokens[B,L,C], text_pooled[B,C],
             text_mask[B,L] or None) -> x0 prediction [B, D].
    """
    config: PriorConfig

    @nn.compact
    def __call__(self, noisy_embed, t, text_tokens, text_pooled, text_mask=None):
        cfg = self.config
        dt = cfg.jdtype
        B = noisy_embed.shape[0]
        W = cfg.width

        # time embedding: sinusoidal -> 2-layer MLP (published naming:
        # time_proj + time_embedding.linear_1/linear_2; flip=True matches
        # the published flip_sin_to_cos=True [cos, sin] layout)
        temb = sinusoidal_embedding(t, W)
        temb = nn.Dense(W, dtype=dt, name="time_linear_1")(temb.astype(dt))
        temb = nn.Dense(W, dtype=dt, name="time_linear_2")(nn.silu(temb))

        seq = jnp.concatenate([
            nn.Dense(W, dtype=dt, name="text_proj")(text_tokens.astype(dt)),
            nn.Dense(W, dtype=dt, name="pooled_proj")(
                text_pooled.astype(dt))[:, None],
            temb[:, None],
            nn.Dense(W, dtype=dt, name="embed_proj")(
                noisy_embed.astype(dt))[:, None],
            jnp.broadcast_to(
                self.param("prd_embed", nn.initializers.normal(0.02),
                           (1, 1, W)).astype(dt), (B, 1, W)),
        ], axis=1)
        pos = self.param("pos_embed", nn.initializers.normal(0.02),
                         (1, cfg.text_len + 4, W))
        seq = seq + pos.astype(dt)

        mask = None
        if text_mask is not None:
            # padding positions attend nowhere useful; additive key mask
            # over [text (L), pooled, time, embed, prd] — the 4 appended
            # slots are always valid.
            full = jnp.concatenate(
                [text_mask.astype(jnp.float32),
                 jnp.ones((B, 4), jnp.float32)], axis=1)
            mask = (1.0 - full)[:, None, None, :] * NEG_INF  # [B,1,1,S]

        for i in range(cfg.layers):
            seq = PriorBlock(cfg.heads, W // cfg.heads, dt,
                             name=f"block_{i}")(seq, mask=mask)
        out = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32, name="norm_out")(
            seq[:, -1].astype(jnp.float32))
        return nn.Dense(cfg.clip_dim, dtype=jnp.float32, name="out_proj")(out)


def prior_stats_init(rng, shape):
    """clip_mean starts at 0, clip_std at 1 (random init stand-in); a real
    checkpoint overwrites both (convert_kandinsky2_prior)."""
    del rng
    return jnp.concatenate([jnp.zeros((1,) + shape[1:]),
                            jnp.ones((1,) + shape[1:])], axis=0)


def prior_sample(model: PriorTransformer, params, text_tokens, text_pooled,
                 keys, guidance, *, steps: int = 25, text_mask=None,
                 clip_stats=None) -> jax.Array:
    """Deterministic DDIM (eta=0) x0-prediction sampling of the embedding.

    cosine alpha-bar schedule; CFG mixes conditional/unconditional x0
    predictions (text context zeroed for the unconditional branch).
    `clip_stats` is a [2, D] array (mean row 0, std row 1); when given,
    the sampled normalized-space embedding is de-normalized on return —
    matching the published pipeline's post_process_latents.
    """
    B, D = text_pooled.shape[0], model.config.clip_dim
    ts = np.linspace(999, 0, steps, dtype=np.float64)
    abar = np.cos((ts / 1000 + 0.008) / 1.008 * np.pi / 2) ** 2
    abar = jnp.asarray(abar, jnp.float32)
    t_cond = jnp.asarray(ts, jnp.float32)

    x = jax.vmap(lambda k: jax.random.normal(
        jax.random.fold_in(k, 0x9A10), (D,), jnp.float32))(keys)
    g = guidance.astype(jnp.float32)[:, None]
    # CFG as one doubled batch (uncond first), like the decoder loop
    tok2 = jnp.concatenate([jnp.zeros_like(text_tokens), text_tokens], axis=0)
    pool2 = jnp.concatenate([jnp.zeros_like(text_pooled), text_pooled], axis=0)
    mask2 = None
    if text_mask is not None:
        # the unconditional branch sees an all-valid (zero-content) context
        mask2 = jnp.concatenate(
            [jnp.ones_like(text_mask), text_mask], axis=0)

    def body(x, i):
        t = jnp.full((2 * B,), t_cond[i])
        x0_both = model.apply({"params": params},
                              jnp.concatenate([x, x], axis=0), t, tok2, pool2,
                              mask2)
        x0_u, x0_c = jnp.split(x0_both, 2, axis=0)
        x0 = x0_u + g * (x0_c - x0_u)
        a_t = abar[i]
        a_prev = jnp.where(i + 1 < steps, abar[jnp.minimum(i + 1, steps - 1)],
                           jnp.float32(1.0))
        eps = (x - jnp.sqrt(a_t) * x0) / jnp.sqrt(1.0 - a_t)
        x_next = jnp.sqrt(a_prev) * x0 + jnp.sqrt(1.0 - a_prev) * eps
        return x_next, None

    x, _ = jax.lax.scan(body, x, jnp.arange(steps))
    if clip_stats is not None:
        x = x * clip_stats[1][None, :] + clip_stats[0][None, :]
    return x
