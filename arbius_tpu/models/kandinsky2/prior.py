"""Kandinsky-2 diffusion prior: text embedding → CLIP-image embedding.

Capability target: the prior stage of the kandinsky2 template — the
reference's only enabled + boot-self-test model (`templates/
kandinsky2.json`, `miner/src/index.ts:844-877`). Kandinsky generates in
two diffusion stages; the first denoises a single CLIP-image-embedding
VECTOR conditioned on the text encoding.

TPU-first shape: the token sequence [text tokens, pooled text, time
embedding, current noisy image-embed, learned query] runs through a
causal-free transformer; sampling is an x0-prediction DDIM loop under
`lax.scan` (the prior predicts the clean embedding directly, not epsilon
— standard for CLIP-space priors). Everything is a [B, S, D] matmul —
ideal MXU work; no pixel tensors exist at this stage.
"""
from __future__ import annotations

from dataclasses import dataclass

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from arbius_tpu.models.common import TransformerBlock, sinusoidal_embedding


@dataclass(frozen=True)
class PriorConfig:
    clip_dim: int = 768           # image-embedding dimensionality
    width: int = 2048
    layers: int = 10
    heads: int = 32
    text_len: int = 77
    dtype: str = "bfloat16"

    @property
    def jdtype(self):
        return jnp.dtype(self.dtype)

    @classmethod
    def tiny(cls) -> "PriorConfig":
        return cls(clip_dim=16, width=32, layers=2, heads=2, text_len=8)


class PriorTransformer(nn.Module):
    """Predicts the clean image embedding from the noisy one + text."""
    config: PriorConfig

    @nn.compact
    def __call__(self, noisy_embed, t, text_tokens, text_pooled):
        cfg = self.config
        dt = cfg.jdtype
        B = noisy_embed.shape[0]

        temb = sinusoidal_embedding(t, cfg.width)
        proj = lambda name: nn.Dense(cfg.width, dtype=dt, name=name)
        seq = jnp.concatenate([
            proj("text_proj")(text_tokens.astype(dt)),          # [B, L, W]
            proj("pooled_proj")(text_pooled.astype(dt))[:, None],
            temb.astype(dt)[:, None],
            proj("embed_proj")(noisy_embed.astype(dt))[:, None],
            jnp.broadcast_to(
                self.param("query", nn.initializers.normal(0.02),
                           (1, 1, cfg.width)).astype(dt), (B, 1, cfg.width)),
        ], axis=1)
        pos = self.param("pos_embed", nn.initializers.normal(0.02),
                         (1, cfg.text_len + 4, cfg.width))
        seq = seq + pos.astype(dt)
        for i in range(cfg.layers):
            seq = TransformerBlock(cfg.heads, cfg.width // cfg.heads, dt,
                                   name=f"block_{i}")(seq)
        out = nn.LayerNorm(dtype=jnp.float32)(seq[:, -1].astype(jnp.float32))
        return nn.Dense(cfg.clip_dim, dtype=jnp.float32, name="out_proj")(out)


def prior_sample(model: PriorTransformer, params, text_tokens, text_pooled,
                 keys, guidance, *, steps: int = 25) -> jax.Array:
    """Deterministic DDIM (eta=0) x0-prediction sampling of the embedding.

    cosine alpha-bar schedule; CFG mixes conditional/unconditional x0
    predictions (text context zeroed for the unconditional branch).
    """
    B, D = text_pooled.shape[0], model.config.clip_dim
    ts = np.linspace(999, 0, steps, dtype=np.float64)
    abar = np.cos((ts / 1000 + 0.008) / 1.008 * np.pi / 2) ** 2
    abar = jnp.asarray(abar, jnp.float32)
    t_cond = jnp.asarray(ts, jnp.float32)

    x = jax.vmap(lambda k: jax.random.normal(
        jax.random.fold_in(k, 0x9A10), (D,), jnp.float32))(keys)
    g = guidance.astype(jnp.float32)[:, None]
    # CFG as one doubled batch (uncond first), like the decoder loop
    tok2 = jnp.concatenate([jnp.zeros_like(text_tokens), text_tokens], axis=0)
    pool2 = jnp.concatenate([jnp.zeros_like(text_pooled), text_pooled], axis=0)

    def body(x, i):
        t = jnp.full((2 * B,), t_cond[i])
        x0_both = model.apply({"params": params},
                              jnp.concatenate([x, x], axis=0), t, tok2, pool2)
        x0_u, x0_c = jnp.split(x0_both, 2, axis=0)
        x0 = x0_u + g * (x0_c - x0_u)
        a_t = abar[i]
        a_prev = jnp.where(i + 1 < steps, abar[jnp.minimum(i + 1, steps - 1)],
                           jnp.float32(1.0))
        eps = (x - jnp.sqrt(a_t) * x0) / jnp.sqrt(1.0 - a_t)
        x_next = jnp.sqrt(a_prev) * x0 + jnp.sqrt(1.0 - a_prev) * eps
        return x_next, None

    x, _ = jax.lax.scan(body, x, jnp.arange(steps))
    return x
