"""Kandinsky-2 decoder UNet: CLIP-image-embedding-conditioned denoiser.

Second diffusion stage of the kandinsky2 template: where SD-1.5
cross-attends over 77 text tokens, Kandinsky's decoder conditions on the
single CLIP image embedding the prior produced — projected both into a
short context token sequence (cross-attention) and into the timestep
embedding (additive). Reuses the shared UNet2DCondition topology; only
the conditioning head differs, so the TPU execution profile (bucketed
static shapes, bf16 MXU convs/attention) is identical to SD-1.5's.
"""
from __future__ import annotations

from dataclasses import dataclass

import flax.linen as nn
import jax.numpy as jnp

from arbius_tpu.models.sd15.unet import UNet2DCondition, UNetConfig


@dataclass(frozen=True)
class DecoderConfig:
    unet: UNetConfig = UNetConfig(block_channels=(384, 768, 1152, 1536),
                                  num_heads=12, context_dim=768)
    clip_dim: int = 768
    context_tokens: int = 10      # image embed → this many pseudo-tokens

    @classmethod
    def tiny(cls) -> "DecoderConfig":
        return cls(unet=UNetConfig.tiny(), clip_dim=16, context_tokens=2)


class DecoderUNet(nn.Module):
    """__call__(latents[B,h,w,4], t[B], image_embed[B,clip_dim]) -> eps."""
    config: DecoderConfig

    @nn.compact
    def __call__(self, x, t, image_embed):
        cfg = self.config
        dt = cfg.unet.jdtype
        emb = image_embed.astype(dt)
        ctx = nn.Dense(cfg.context_tokens * cfg.unet.context_dim, dtype=dt,
                       name="embed_to_context")(emb)
        ctx = ctx.reshape(emb.shape[0], cfg.context_tokens,
                          cfg.unet.context_dim)
        ctx = nn.LayerNorm(dtype=jnp.float32, name="context_norm")(
            ctx.astype(jnp.float32)).astype(dt)
        return UNet2DCondition(cfg.unet, name="unet")(x, t, ctx)
