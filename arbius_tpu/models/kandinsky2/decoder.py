"""Kandinsky-2 decoder UNet: CLIP-image-embedding-conditioned denoiser.

Second diffusion stage of the kandinsky2 template: where SD-1.5
cross-attends over 77 text tokens, Kandinsky's decoder conditions on the
single CLIP image embedding the prior produced — projected BOTH into a
short context token sequence (the published ImageProjection head: linear
→ reshape to tokens → LayerNorm) AND into the timestep embedding (the
published add_embedding MLP).

The UNet interior follows the published unCLIP-family decoder (diffusers
`UNet2DConditionModel` with ResnetDownsample/SimpleCrossAttn blocks), NOT
SD's transformer blocks:

  - attention is single-layer ADDED-KV attention: queries from spatial
    tokens, keys/values from [projected context ‖ spatial tokens]
    (`add_k_proj`/`add_v_proj`), group-normed input, biased projections —
    no proj_in/proj_out, no GEGLU feed-forward;
  - attention sits at every level EXCEPT the highest resolution
    (attention_levels (False, True, True, True));
  - down/upsampling is resnet-based (a resnet whose both branches 2×
    average-pool / nearest-upsample), not a strided conv;
  - resnet time conditioning is scale/shift (FiLM), head size is a fixed
    64 (head count grows with width), and the output carries 2× channels
    (epsilon + learned variance; samplers here consume the epsilon half).

TPU execution profile: bucketed static shapes, bf16 MXU convs/attention,
one jitted program per shape bucket — identical discipline to SD-1.5.
Conversion source: the diffusers-format kandinsky decoder checkpoint —
see kandinsky2/convert.py (`kandinsky_unet_key_for`).
"""
from __future__ import annotations

from dataclasses import dataclass

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from arbius_tpu.models.common import (
    GroupNorm32,
    ResnetBlock,
    TimestepEmbedding,
    sinusoidal_embedding,
)
from arbius_tpu.models.sd15.unet import UNetConfig


@dataclass(frozen=True)
class DecoderConfig:
    unet: UNetConfig = UNetConfig(block_channels=(384, 768, 1152, 1536),
                                  layers_per_block=3,
                                  attention_levels=(False, True, True, True),
                                  out_channels=8, head_dim=64,
                                  context_dim=768, time_scale_shift=True)
    clip_dim: int = 1280
    context_tokens: int = 10      # image embed → this many pseudo-tokens

    @classmethod
    def tiny(cls) -> "DecoderConfig":
        import dataclasses

        unet = dataclasses.replace(
            UNetConfig.tiny(), attention_levels=(False, True, True, True),
            time_scale_shift=True)
        return cls(unet=unet, clip_dim=16, context_tokens=2)


class AttnAddedKV(nn.Module):
    """unCLIP-family attention: group-normed spatial queries over
    [context ‖ spatial] keys/values, all projections biased, residual
    inside. Softmax in float32 (determinism + stability policy)."""
    num_heads: int
    head_dim: int
    context_dim: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, context):
        b, hh, ww, c = x.shape
        inner = self.num_heads * self.head_dim
        residual = x
        hs = GroupNorm32(name="group_norm")(x).reshape(b, hh * ww, c)
        hs = hs.astype(self.dtype)
        ctx = context.astype(self.dtype)
        q = nn.Dense(inner, dtype=self.dtype, name="to_q")(hs)
        k = nn.Dense(inner, dtype=self.dtype, name="to_k")(hs)
        v = nn.Dense(inner, dtype=self.dtype, name="to_v")(hs)
        ek = nn.Dense(inner, dtype=self.dtype, name="add_k_proj")(ctx)
        ev = nn.Dense(inner, dtype=self.dtype, name="add_v_proj")(ctx)
        # context tokens lead the key/value sequence (published order)
        k = jnp.concatenate([ek, k], axis=1)
        v = jnp.concatenate([ev, v], axis=1)

        def split(t):
            return t.reshape(t.shape[0], t.shape[1], self.num_heads,
                             self.head_dim).transpose(0, 2, 1, 3)

        q, k, v = split(q), split(k), split(v)
        scale = 1.0 / np.sqrt(self.head_dim)
        logits = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
        probs = jax.nn.softmax(logits, axis=-1).astype(self.dtype)
        out = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
        out = out.transpose(0, 2, 1, 3).reshape(b, hh * ww, inner)
        out = nn.Dense(c, dtype=self.dtype, name="to_out")(out)
        return residual + out.reshape(b, hh, ww, c)


class KandinskyUNet(nn.Module):
    """__call__(latents[B,h,w,4], t[B], context[B,S,D], extra_temb[B,4ch0])
    -> eps[+variance]. Published unCLIP-style topology (module docstring)."""
    config: UNetConfig

    @nn.compact
    def __call__(self, x, t, context, extra_temb=None):
        cfg = self.config
        dt = cfg.jdtype
        x = x.astype(dt)
        context = context.astype(dt)
        ss = cfg.time_scale_shift

        temb = sinusoidal_embedding(t, cfg.block_channels[0])
        temb = TimestepEmbedding(cfg.block_channels[0] * 4, dt)(temb)
        if extra_temb is not None:
            temb = temb + extra_temb.astype(temb.dtype)

        h = nn.Conv(cfg.block_channels[0], (3, 3), padding=1, dtype=dt,
                    name="conv_in")(x)
        skips = [h]

        # encoder
        for level, ch in enumerate(cfg.block_channels):
            for j in range(cfg.layers_per_block):
                h = ResnetBlock(ch, dt, ss,
                                name=f"down_{level}_res_{j}")(h, temb)
                if cfg.attention_levels[level]:
                    heads, hd = cfg.heads_for(ch)
                    h = AttnAddedKV(heads, hd, cfg.context_dim, dt,
                                    name=f"down_{level}_attn_{j}")(h, context)
                skips.append(h)
            if level < len(cfg.block_channels) - 1:
                h = ResnetBlock(ch, dt, ss, resample="down",
                                name=f"down_{level}_ds")(h, temb)
                skips.append(h)

        # mid
        mid_ch = cfg.block_channels[-1]
        h = ResnetBlock(mid_ch, dt, ss, name="mid_res_0")(h, temb)
        mheads, mhd = cfg.heads_for(mid_ch)
        h = AttnAddedKV(mheads, mhd, cfg.context_dim, dt,
                        name="mid_attn")(h, context)
        h = ResnetBlock(mid_ch, dt, ss, name="mid_res_1")(h, temb)

        # decoder
        for level in reversed(range(len(cfg.block_channels))):
            ch = cfg.block_channels[level]
            for j in range(cfg.layers_per_block + 1):
                h = jnp.concatenate([h, skips.pop()], axis=-1)
                h = ResnetBlock(ch, dt, ss,
                                name=f"up_{level}_res_{j}")(h, temb)
                if cfg.attention_levels[level]:
                    heads, hd = cfg.heads_for(ch)
                    h = AttnAddedKV(heads, hd, cfg.context_dim, dt,
                                    name=f"up_{level}_attn_{j}")(h, context)
            if level > 0:
                h = ResnetBlock(ch, dt, ss, resample="up",
                                name=f"up_{level}_us")(h, temb)

        h = GroupNorm32(name="norm_out")(h)
        h = nn.silu(h)
        return nn.Conv(cfg.out_channels, (3, 3), padding=1,
                       dtype=jnp.float32, name="conv_out")(h.astype(jnp.float32))


class DecoderUNet(nn.Module):
    """__call__(latents[B,h,w,4], t[B], image_embed[B,clip_dim]) -> eps[+var]."""
    config: DecoderConfig

    @nn.compact
    def __call__(self, x, t, image_embed):
        cfg = self.config
        dt = cfg.unet.jdtype
        emb = image_embed.astype(dt)
        # cross-attention context (published ImageProjection)
        ctx = nn.Dense(cfg.context_tokens * cfg.unet.context_dim, dtype=dt,
                       name="embed_to_context")(emb)
        ctx = ctx.reshape(emb.shape[0], cfg.context_tokens,
                          cfg.unet.context_dim)
        ctx = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32, name="context_norm")(
            ctx.astype(jnp.float32)).astype(dt)
        # additive timestep-embedding branch (published add_embedding)
        tdim = cfg.unet.block_channels[0] * 4
        add = nn.Dense(tdim, dtype=dt, name="add_linear_1")(emb)
        add = nn.Dense(tdim, dtype=dt, name="add_linear_2")(nn.silu(add))
        return KandinskyUNet(cfg.unet, name="unet")(x, t, ctx,
                                                    extra_temb=add)
