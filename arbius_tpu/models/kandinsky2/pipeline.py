"""Kandinsky-2 txt2img pipeline: text → prior → decoder → MOVQ, in-process.

The reference's flagship mining path (kandinsky2 is its only enabled model
AND the boot self-test, `miner/src/index.ts:844-877`, :984-1001) as one
jitted XLA program per shape bucket. Same determinism contract as SD-1.5:
the per-task seed keys every stochastic draw via fold_in, buckets run at a
canonical batch, so output bytes depend only on (model build, input, seed).

Template parity (`templates/kandinsky2.json`): prompt, negative_prompt
(unused by the prior's CFG-zero branch but accepted), w/h ∈ {768, 1024},
num_inference_steps, guidance_scale, seed; output out-1.png.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from arbius_tpu.models.kandinsky2.decoder import DecoderConfig, DecoderUNet
from arbius_tpu.models.kandinsky2.movq import MOVQConfig, MOVQDecoder
from arbius_tpu.models.kandinsky2.prior import (
    PriorConfig,
    PriorTransformer,
    prior_sample,
)
from arbius_tpu.models.sd15.text_encoder import TextEncoder, TextEncoderConfig
from arbius_tpu.models.sd15.tokenizer import ByteTokenizer
from arbius_tpu.models.sd15.vae import decode_to_images
from arbius_tpu.schedulers import get_sampler


@dataclass(frozen=True)
class Kandinsky2Config:
    prior: PriorConfig = PriorConfig()
    decoder: DecoderConfig = DecoderConfig()
    movq: MOVQConfig = MOVQConfig()
    text: TextEncoderConfig = TextEncoderConfig()
    prior_steps: int = 25

    @classmethod
    def tiny(cls) -> "Kandinsky2Config":
        return cls(prior=PriorConfig.tiny(), decoder=DecoderConfig.tiny(),
                   movq=MOVQConfig.tiny(), text=TextEncoderConfig.tiny(),
                   prior_steps=2)


class Kandinsky2Pipeline:
    """Stateless module bundle + jitted per-bucket executables."""

    MOVQ_FACTOR = 8

    def __init__(self, config: Kandinsky2Config | None = None, tokenizer=None,
                 mesh=None):
        self.config = config or Kandinsky2Config()
        self.mesh = mesh
        if self.config.text.width != self.config.prior.clip_dim:
            raise ValueError(
                f"text width ({self.config.text.width}) must equal prior "
                f"clip_dim ({self.config.prior.clip_dim}) — the prior "
                "consumes raw text-encoder states")
        if self.config.text.max_length < self.config.prior.text_len:
            raise ValueError(
                f"text max_length ({self.config.text.max_length}) must be "
                f">= prior text_len ({self.config.prior.text_len})")
        self.tokenizer = tokenizer or ByteTokenizer(
            max_length=self.config.text.max_length)
        self.text_encoder = TextEncoder(self.config.text)
        self.prior = PriorTransformer(self.config.prior)
        self.decoder = DecoderUNet(self.config.decoder)
        self.movq = MOVQDecoder(self.config.movq)
        self._buckets: dict[tuple, object] = {}

    # -- params ----------------------------------------------------------
    def init_params(self, seed: int = 0, height: int = 64, width: int = 64) -> dict:
        k1, k2, k3, k4 = jax.random.split(jax.random.PRNGKey(seed), 4)
        cfg = self.config
        lh, lw = height // self.MOVQ_FACTOR, width // self.MOVQ_FACTOR
        ids = jnp.zeros((1, cfg.text.max_length), jnp.int32)
        tok = jnp.zeros((1, cfg.prior.text_len, cfg.prior.clip_dim))
        pooled = jnp.zeros((1, cfg.prior.clip_dim))
        embed = jnp.zeros((1, cfg.prior.clip_dim))
        lat = jnp.zeros((1, lh, lw, cfg.decoder.unet.in_channels))
        return {
            "text": self.text_encoder.init(k1, ids)["params"],
            "prior": self.prior.init(k2, embed, jnp.zeros((1,)), tok,
                                     pooled)["params"],
            "decoder": self.decoder.init(k3, lat, jnp.zeros((1,)),
                                         embed)["params"],
            "movq": self.movq.init(k4, lat)["params"],
        }

    def place_params(self, params: dict, tp_rules=None) -> dict:
        if self.mesh is None:
            return params
        from arbius_tpu.parallel import DEFAULT_TP_RULES, shard_params

        return shard_params(params, self.mesh,
                            tp_rules if tp_rules is not None else DEFAULT_TP_RULES)

    def _place_batch(self, *arrays):
        if self.mesh is None:
            return arrays
        from arbius_tpu.parallel import batch_sharding

        return tuple(jax.device_put(a, batch_sharding(self.mesh, a.ndim))
                     for a in arrays)

    # -- compiled bucket -------------------------------------------------
    def compiled_bucket(self, batch: int, height: int, width: int,
                        steps: int, scheduler: str):
        key = (batch, height, width, steps, scheduler)
        cached = self._buckets.get(key)
        if cached is not None:
            return cached
        cfg = self.config
        sampler = get_sampler(scheduler, steps)
        lh, lw = height // self.MOVQ_FACTOR, width // self.MOVQ_FACTOR
        lat_shape = (batch, lh, lw, cfg.decoder.unet.in_channels)
        text_len = cfg.prior.text_len

        def run(params, ids, guidance, seeds_lo, seeds_hi):
            states = self.text_encoder.apply({"params": params["text"]}, ids)
            # prior consumes a fixed text_len window + pooled (last token)
            tok = states[:, :text_len]
            pooled = states[:, -1]
            keys = jax.vmap(
                lambda lo, hi: jax.random.fold_in(jax.random.PRNGKey(lo), hi)
            )(seeds_lo, seeds_hi)
            g = guidance.astype(jnp.float32)

            embed = prior_sample(self.prior, params["prior"], tok, pooled,
                                 keys, g, steps=cfg.prior_steps)

            x = jax.vmap(lambda k: jax.random.normal(
                k, lat_shape[1:], jnp.float32))(keys)
            x = x * sampler.init_noise_sigma
            zero_embed = jnp.zeros_like(embed)
            g4 = g[:, None, None, None]

            def body(carry, i):
                x, state = carry
                xin = jnp.concatenate([x, x], axis=0) * sampler.input_scale[i]
                t = jnp.full((2 * batch,), sampler.timesteps[i])
                emb2 = jnp.concatenate([zero_embed, embed], axis=0)
                eps = self.decoder.apply({"params": params["decoder"]},
                                         xin, t, emb2)
                eps_u, eps_c = jnp.split(eps.astype(jnp.float32), 2, axis=0)
                eps = eps_u + g4 * (eps_c - eps_u)
                noise = jax.vmap(lambda k: jax.random.normal(
                    jax.random.fold_in(k, i), lat_shape[1:], jnp.float32))(keys)
                x, state = sampler.step(i, x, eps, state, noise)
                return (x, state), None

            (x, _), _ = jax.lax.scan(body, (x, sampler.init_carry(x)),
                                     jnp.arange(sampler.num_model_calls))
            pixels = self.movq.apply({"params": params["movq"]}, x)
            return decode_to_images(pixels)

        fn = jax.jit(run)
        self._buckets[key] = fn
        return fn

    # -- public API ------------------------------------------------------
    def generate(self, params: dict, prompts: list[str],
                 negative_prompts: list[str] | None, seeds: list[int], *,
                 width: int = 768, height: int = 768,
                 num_inference_steps: int = 50,
                 guidance_scale: float | list[float] = 4.0,
                 scheduler: str = "DDIM") -> np.ndarray:
        batch = len(prompts)
        if len(seeds) != batch:
            raise ValueError("prompts/seeds must align")
        levels = len(self.config.decoder.unet.block_channels)
        granule = self.MOVQ_FACTOR * (2 ** (levels - 1))
        if height % granule or width % granule:
            raise ValueError(f"height/width must be multiples of {granule}")
        g = list(guidance_scale) if isinstance(guidance_scale, (list, tuple)) \
            else [guidance_scale] * batch
        if self.mesh is not None and batch % self.mesh.shape["dp"]:
            raise ValueError(
                f"batch {batch} not divisible by dp={self.mesh.shape['dp']}")
        fn = self.compiled_bucket(batch, height, width, num_inference_steps,
                                  scheduler)
        ids = self.tokenizer.encode_batch(prompts)
        seeds_arr = np.asarray(seeds, dtype=np.uint64)
        args = self._place_batch(
            jnp.asarray(ids),
            jnp.asarray(g, jnp.float32),
            jnp.asarray(seeds_arr & 0xFFFFFFFF, jnp.uint32),
            jnp.asarray(seeds_arr >> np.uint64(32), jnp.uint32),
        )
        return np.asarray(fn(params, *args))
