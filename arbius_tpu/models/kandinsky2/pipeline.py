"""Kandinsky-2 txt2img pipeline: text → prior → decoder → MOVQ, in-process.

The reference's flagship mining path (kandinsky2 is its only enabled model
AND the boot self-test, `miner/src/index.ts:844-877`, :984-1001) as one
jitted XLA program per shape bucket. Same determinism contract as SD-1.5:
the per-task seed keys every stochastic draw via fold_in, buckets run at a
canonical batch, so output bytes depend only on (model build, input, seed).

Stage wiring follows the published two-pipeline graph so converted
checkpoints drive it 1:1 (kandinsky2/convert.py):

  text tower (+ projection)  → hidden states, EOT-pooled projected embed
  prior                      → CLIP-image embedding (normalized space;
                               de-normalized via the checkpoint's
                               clip_mean/clip_std stats)
  decoder UNet               → epsilon (the learned-variance half of the
                               8-channel output is discarded — samplers
                               here are deterministic)
  MOVQ                       → pixels

Template parity (`templates/kandinsky2.json`): prompt, negative_prompt
(unused by the prior's CFG-zero branch but accepted), w/h ∈ {768, 1024},
num_inference_steps, guidance_scale, seed; output out-1.png.
"""
from __future__ import annotations

import dataclasses
from dataclasses import dataclass

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from arbius_tpu.models.kandinsky2.decoder import DecoderConfig, DecoderUNet
from arbius_tpu.models.kandinsky2.movq import MOVQConfig, MOVQDecoder
from arbius_tpu.models.kandinsky2.prior import (
    PriorConfig,
    PriorTransformer,
    prior_sample,
    prior_stats_init,
)
from arbius_tpu.models.sd15.text_encoder import TextEncoder, TextEncoderConfig
from arbius_tpu.models.sd15.tokenizer import ByteTokenizer
from arbius_tpu.models.sd15.vae import decode_to_images
from arbius_tpu.schedulers import get_sampler


@dataclass(frozen=True)
class Kandinsky2Config:
    # defaults are the published checkpoint shapes: open_clip bigG text
    # tower (1280-wide, plain gelu) + 1280-dim image embedding space
    prior: PriorConfig = PriorConfig()
    decoder: DecoderConfig = DecoderConfig()
    movq: MOVQConfig = MOVQConfig()
    text: TextEncoderConfig = TextEncoderConfig(width=1280, layers=32,
                                                heads=20, act="gelu")
    prior_steps: int = 25

    @classmethod
    def tiny(cls) -> "Kandinsky2Config":
        dec = DecoderConfig.tiny()
        # exercise the learned-variance slice even at toy size
        dec = dataclasses.replace(
            dec, unet=dataclasses.replace(dec.unet, out_channels=8))
        return cls(prior=PriorConfig.tiny(), decoder=dec,
                   movq=MOVQConfig.tiny(), text=TextEncoderConfig.tiny(),
                   prior_steps=2)


class TextProjection(nn.Module):
    """CLIP text_projection: EOT-pooled hidden state → embedding space."""
    dim: int

    @nn.compact
    def __call__(self, x):
        return nn.Dense(self.dim, use_bias=False, dtype=jnp.float32,
                        name="proj")(x)


class Kandinsky2Pipeline:
    """Stateless module bundle + jitted per-bucket executables."""

    MOVQ_FACTOR = 8

    def __init__(self, config: Kandinsky2Config | None = None, tokenizer=None,
                 mesh=None, precision: str = "bf16"):
        from arbius_tpu.quant import validate_mode

        self.config = config or Kandinsky2Config()
        self.mesh = mesh
        # precision mode (docs/quantization.md): "bf16" is the historic
        # program byte-for-byte; int8/fp8 take the factory-quantized
        # weight tree and dequantize in-program — own golden per mode
        self.precision = validate_mode(precision)
        if self.config.text.max_length < self.config.prior.text_len:
            raise ValueError(
                f"text max_length ({self.config.text.max_length}) must be "
                f">= prior text_len ({self.config.prior.text_len})")
        self.tokenizer = tokenizer or ByteTokenizer(
            max_length=self.config.text.max_length)
        self.text_encoder = TextEncoder(self.config.text)
        self.text_projection = TextProjection(self.config.prior.clip_dim)
        self.prior = PriorTransformer(self.config.prior)
        self.decoder = DecoderUNet(self.config.decoder)
        self.movq = MOVQDecoder(self.config.movq)
        self._buckets: dict[tuple, object] = {}
        self._coll_est: dict[tuple, dict] = {}  # per-bucket traffic estimate

    # -- params ----------------------------------------------------------
    def init_params(self, seed: int = 0, height: int = 64, width: int = 64,
                    dtype=None) -> dict:
        """One jitted init program; `dtype` folds the weights cast in so
        the full f32 tree is never resident (the ~3B tree is 12 GB f32 —
        a separate cast program OOMs a 16 GB chip; fused, XLA frees each
        f32 leaf at its convert)."""
        cfg = self.config
        lh, lw = height // self.MOVQ_FACTOR, width // self.MOVQ_FACTOR

        def _init(key):
            k1, k2, k3, k4, k5 = jax.random.split(key, 5)
            ids = jnp.zeros((1, cfg.text.max_length), jnp.int32)
            tok = jnp.zeros((1, cfg.prior.text_len, cfg.text.width))
            pooled = jnp.zeros((1, cfg.prior.clip_dim))
            embed = jnp.zeros((1, cfg.prior.clip_dim))
            lat = jnp.zeros((1, lh, lw, cfg.decoder.unet.in_channels))
            return {
                "text": self.text_encoder.init(k1, ids)["params"],
                "text_proj": self.text_projection.init(
                    k5, jnp.zeros((1, cfg.text.width)))["params"],
                "prior": self.prior.init(k2, embed, jnp.zeros((1,)), tok,
                                         pooled)["params"],
                "prior_stats": prior_stats_init(None, (2, cfg.prior.clip_dim)),
                "decoder": self.decoder.init(k3, lat, jnp.zeros((1,)),
                                             embed)["params"],
                "movq": self.movq.init(k4, lat)["params"],
            }

        from arbius_tpu.utils import with_cast

        return jax.jit(with_cast(_init, dtype))(jax.random.PRNGKey(seed))

    def place_params(self, params: dict, tp_rules=None) -> dict:
        if self.mesh is None:
            return params
        from arbius_tpu.parallel import DEFAULT_TP_RULES, shard_params

        return shard_params(params, self.mesh,
                            tp_rules if tp_rules is not None else DEFAULT_TP_RULES)

    def _place_batch(self, *arrays):
        # meshsolve.shard_batch: dp when the batch divides, else
        # replicated (under-filled buckets idle dp lanes, never error)
        if self.mesh is None:
            return arrays
        from arbius_tpu.parallel import meshsolve

        return meshsolve.shard_batch(self.mesh, *arrays)

    # -- compiled bucket -------------------------------------------------
    def compiled_bucket(self, batch: int, height: int, width: int,
                        steps: int, scheduler: str):
        return self._get_bucket(batch, height, width, steps, scheduler)[0]

    def bucket_tag(self, batch: int, height: int, width: int, steps: int,
                   scheduler: str) -> str:
        """One definition of this family's executable-cache tag — the
        warm sets and the AOT disk-warm scan join on it
        (docs/compile-cache.md). Non-default precision modes suffix it
        (".int8"/".fp8") so a quantized bucket never shares a warm
        signal with its bf16 twin; bf16 tags stay byte-identical."""
        from arbius_tpu.quant import mode_tag

        return "kandinsky2." + ".".join(
            str(k) for k in (batch, height, width, steps, scheduler)) \
            + mode_tag(self.precision)

    def _get_bucket(self, batch: int, height: int, width: int,
                    steps: int, scheduler: str, aot_args=None):
        """(fn, warm, tag) — cache lookup reported through the
        jit-cache metrics (docs/observability.md); `aot_args` opts into
        the AOT disk tier (docs/compile-cache.md)."""
        from arbius_tpu.obs import jit_cache_get

        key = (batch, height, width, steps, scheduler)
        return jit_cache_get(
            self._buckets, key,
            lambda: self._build_bucket(batch, height, width, steps,
                                       scheduler),
            tag=self.bucket_tag(*key), aot_args=aot_args)

    def _build_bucket(self, batch: int, height: int, width: int,
                      steps: int, scheduler: str):
        cfg = self.config
        sampler = get_sampler(scheduler, steps)
        lh, lw = height // self.MOVQ_FACTOR, width // self.MOVQ_FACTOR
        in_ch = cfg.decoder.unet.in_channels
        lat_shape = (batch, lh, lw, in_ch)
        text_len = cfg.prior.text_len
        eos_id = self.tokenizer.eos_id
        precision = self.precision

        def run(params, ids, guidance, seeds_lo, seeds_hi):
            if precision != "bf16":
                from arbius_tpu.quant import dequantize_tree

                # int8/fp8 kernels → f32 via their f32 scales (GRAPH407
                # contract); guarded so bf16 stays byte-identical
                params = dequantize_tree(params)
            states = self.text_encoder.apply({"params": params["text"]}, ids)
            # EOT pooling: hidden state at the first EOS position, then the
            # projection into embedding space (CLIP *WithProjection heads)
            first_eos = jnp.argmax((ids == eos_id).astype(jnp.int32), axis=1)
            pooled_pre = states[jnp.arange(states.shape[0]), first_eos]
            pooled = self.text_projection.apply(
                {"params": params["text_proj"]}, pooled_pre)
            # attention mask: real tokens up to and including the EOT
            positions = jnp.arange(ids.shape[1])[None, :]
            mask = (positions <= first_eos[:, None]).astype(jnp.float32)

            tok = states[:, :text_len]
            keys = jax.vmap(
                lambda lo, hi: jax.random.fold_in(jax.random.PRNGKey(lo), hi)
            )(seeds_lo, seeds_hi)
            g = guidance.astype(jnp.float32)

            embed = prior_sample(self.prior, params["prior"], tok, pooled,
                                 keys, g, steps=cfg.prior_steps,
                                 text_mask=mask[:, :text_len],
                                 clip_stats=params["prior_stats"])

            x = jax.vmap(lambda k: jax.random.normal(
                k, lat_shape[1:], jnp.float32))(keys)
            x = x * sampler.init_noise_sigma
            zero_embed = jnp.zeros_like(embed)
            g4 = g[:, None, None, None]

            def body(carry, i):
                x, state = carry
                xin = jnp.concatenate([x, x], axis=0) * sampler.input_scale[i]
                t = jnp.full((2 * batch,), sampler.timesteps[i])
                emb2 = jnp.concatenate([zero_embed, embed], axis=0)
                out = self.decoder.apply({"params": params["decoder"]},
                                         xin, t, emb2)
                # learned-variance half (if present) is dropped: the
                # deterministic samplers never consume it
                eps = out.astype(jnp.float32)[..., :in_ch]
                eps_u, eps_c = jnp.split(eps, 2, axis=0)
                eps = eps_u + g4 * (eps_c - eps_u)
                noise = jax.vmap(lambda k: jax.random.normal(
                    jax.random.fold_in(k, i), lat_shape[1:], jnp.float32))(keys)
                x, state = sampler.step(i, x, eps, state, noise)
                return (x, state), None

            (x, _), _ = jax.lax.scan(body, (x, sampler.init_carry(x)),
                                     jnp.arange(sampler.num_model_calls))
            pixels = self.movq.apply({"params": params["movq"]}, x)
            return decode_to_images(pixels)

        if self.mesh is None:
            # the exact pre-mesh program: goldens pin this byte-for-byte
            fn = jax.jit(run)
        else:
            # GSPMD batch/output specs; params inherit their boot-time
            # rule-table placement (docs/multichip.md)
            from arbius_tpu.parallel import meshsolve

            spec, _ = meshsolve.batch_specs(self.mesh, batch)
            fn = jax.jit(run,
                         in_shardings=(None, spec(2), spec(1), spec(1),
                                       spec(1)),
                         out_shardings=spec(4))
        return fn

    # -- public API ------------------------------------------------------
    def generate(self, params: dict, prompts: list[str],
                 negative_prompts: list[str] | None, seeds: list[int], *,
                 width: int = 768, height: int = 768,
                 num_inference_steps: int = 50,
                 guidance_scale: float | list[float] = 4.0,
                 scheduler: str = "DDIM",
                 as_device: bool = False) -> np.ndarray:
        batch = len(prompts)
        if len(seeds) != batch:
            raise ValueError("prompts/seeds must align")
        levels = len(self.config.decoder.unet.block_channels)
        granule = self.MOVQ_FACTOR * (2 ** (levels - 1))
        if height % granule or width % granule:
            raise ValueError(f"height/width must be multiples of {granule}")
        g = list(guidance_scale) if isinstance(guidance_scale, (list, tuple)) \
            else [guidance_scale] * batch
        ids = self.tokenizer.encode_batch(prompts)
        vocab = self.config.text.vocab_size
        if int(ids.max()) >= vocab:
            raise ValueError(
                f"tokenizer produced id >= vocab_size ({vocab}); "
                "tokenizer and text-encoder config are mismatched")
        seeds_arr = np.asarray(seeds, dtype=np.uint64)
        args = self._place_batch(
            jnp.asarray(ids),
            jnp.asarray(g, jnp.float32),
            jnp.asarray(seeds_arr & 0xFFFFFFFF, jnp.uint32),
            jnp.asarray(seeds_arr >> np.uint64(32), jnp.uint32),
        )
        # args before the lookup: the AOT tier keys against the exact
        # dispatch operands (docs/compile-cache.md)
        fn, warm, tag = self._get_bucket(
            batch, height, width, num_inference_steps, scheduler,
            aot_args=lambda: (params, *args))
        from arbius_tpu.obs import timed_dispatch

        with timed_dispatch(warm, tag):
            images = fn(params, *args)
        if self.mesh is not None:
            from arbius_tpu.parallel import meshsolve
            from arbius_tpu.quant import storage_dtype

            meshsolve.record_bucket_estimate(
                self._coll_est,
                (batch, height, width, num_inference_steps, scheduler),
                self.mesh, images, batch, params=params,
                wire_dtype=storage_dtype(self.precision)
                if self.precision != "bf16" else None, tag=tag)
        if as_device:
            # async-dispatch handle: the solver's chunk pipeline encodes
            # the previous chunk while the chip crunches this one
            return images
        return np.asarray(images)


# mesh layouts this family ships (docs/multichip.md): same table as
# SD-1.5 — dp-only is bit-identical to mesh-off, dp×tp (DEFAULT_TP_RULES
# over the decoder/prior attention + FF kernels) is its own determinism
# class. One graphlint golden per layout below.
MESH_LAYOUTS: tuple[tuple[str, ...], ...] = (("dp",), ("dp", "tp"))


def trace_specs():
    """graphlint trace specs (models/trace_specs.py): the whole
    text→prior→decoder→MOVQ bucket program — one jitted graph, so one
    fingerprint covers both published sub-pipelines — single-device and
    under each shipped mesh layout (MESH_LAYOUTS, traced over
    `parallel.abstract_mesh` so no devices are involved)."""
    from arbius_tpu.models.trace_specs import TraceSpec
    from arbius_tpu.parallel import meshsolve
    from arbius_tpu.schedulers import sampler_tag

    def build_bucket(axes=(), precision="bf16"):
        def build():
            from arbius_tpu.quant import abstract_quantized

            p = Kandinsky2Pipeline(Kandinsky2Config.tiny(),
                                   mesh=meshsolve.golden_mesh(axes),
                                   precision=precision)
            batch = 2 if axes else 1
            shapes = jax.eval_shape(
                lambda: p.init_params(height=64, width=64))
            if precision != "bf16":
                shapes = abstract_quantized(shapes, precision)
            sds = jax.ShapeDtypeStruct
            length = p.config.text.max_length
            args = (shapes, sds((batch, length), jnp.int32),
                    sds((batch,), jnp.float32),
                    sds((batch,), jnp.uint32), sds((batch,), jnp.uint32))
            return p.compiled_bucket(batch, 64, 64, 2, "DDIM"), args

        return build

    return [
        TraceSpec(model="kandinsky2", entry="txt2img",
                  bucket=f"b1.64x64.{sampler_tag('DDIM', 2)}",
                  mesh="single", dtype="bfloat16", build=build_bucket()),
        # quantized mode (docs/quantization.md): its own pinned class
        TraceSpec(model="kandinsky2", entry="txt2img",
                  bucket=f"b1.64x64.{sampler_tag('DDIM', 2)}",
                  mesh="single", dtype="int8",
                  build=build_bucket(precision="int8")),
    ] + [
        TraceSpec(model="kandinsky2", entry="txt2img",
                  bucket=f"b2.64x64.{sampler_tag('DDIM', 2)}",
                  mesh=meshsolve.golden_layout_tag(axes), dtype="bfloat16",
                  build=build_bucket(axes))
        for axes in MESH_LAYOUTS
    ]
