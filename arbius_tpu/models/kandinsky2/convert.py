"""Checkpoint conversion: published Kandinsky-2 state dicts → param trees.

The reference mines kandinsky2 through a cog container wrapping the
published Kandinsky-2 weights (`templates/kandinsky2.json` pins the repo;
`miner/src/index.ts:844-877` is the invocation). This module maps the
diffusers-format distribution of those weights — prior `PriorTransformer`,
decoder `UNet2DConditionModel` (image-conditioned), MOVQ `VQModel`
(norm_type="spatial"), and the CLIP text tower `*WithProjection` — onto
this framework's flax trees, so the same weights drive the TPU path.

Same contract as sd15/convert.py (the family template): input is a flat
`{key: numpy array}` dict; completeness is enforced (every target leaf
must be produced; shape mismatches fail loudly); bijectivity
(ours → published naming → ours) is tested in
tests/test_kandinsky_convert.py. Numeric validation against a live
reference pipeline needs real weights and is a deployment-time step —
the boot self-test's golden CID is the final arbiter either way.
"""
from __future__ import annotations

import re

import numpy as np

from arbius_tpu.models.sd15.convert import (
    _RESNET_LEAVES,
    ConversionError,
    _conv,
    _convert_tree,
    _ident,
    _linear,
)

__all__ = [
    "convert_kandinsky2_prior",
    "convert_kandinsky2_decoder",
    "convert_kandinsky2_movq",
    "convert_kandinsky2_text_projection",
    "prior_key_for",
    "decoder_key_for",
    "movq_key_for",
]


# -- prior -----------------------------------------------------------------

_PRIOR_SIMPLE = {
    "time_linear_1/kernel": ("time_embedding.linear_1.weight", _linear),
    "time_linear_1/bias": ("time_embedding.linear_1.bias", _ident),
    "time_linear_2/kernel": ("time_embedding.linear_2.weight", _linear),
    "time_linear_2/bias": ("time_embedding.linear_2.bias", _ident),
    "embed_proj/kernel": ("proj_in.weight", _linear),
    "embed_proj/bias": ("proj_in.bias", _ident),
    "pooled_proj/kernel": ("embedding_proj.weight", _linear),
    "pooled_proj/bias": ("embedding_proj.bias", _ident),
    "text_proj/kernel": ("encoder_hidden_states_proj.weight", _linear),
    "text_proj/bias": ("encoder_hidden_states_proj.bias", _ident),
    "pos_embed": ("positional_embedding", _ident),
    "prd_embed": ("prd_embedding", _ident),
    "norm_out/scale": ("norm_out.weight", _ident),
    "norm_out/bias": ("norm_out.bias", _ident),
    "out_proj/kernel": ("proj_to_clip_embeddings.weight", _linear),
    "out_proj/bias": ("proj_to_clip_embeddings.bias", _ident),
}

_PRIOR_BLOCK = {
    "norm1/scale": ("norm1.weight", _ident),
    "norm1/bias": ("norm1.bias", _ident),
    "norm3/scale": ("norm3.weight", _ident),
    "norm3/bias": ("norm3.bias", _ident),
    "attn1/to_q/kernel": ("attn1.to_q.weight", _linear),
    "attn1/to_q/bias": ("attn1.to_q.bias", _ident),
    "attn1/to_k/kernel": ("attn1.to_k.weight", _linear),
    "attn1/to_k/bias": ("attn1.to_k.bias", _ident),
    "attn1/to_v/kernel": ("attn1.to_v.weight", _linear),
    "attn1/to_v/bias": ("attn1.to_v.bias", _ident),
    "attn1/to_out/kernel": ("attn1.to_out.0.weight", _linear),
    "attn1/to_out/bias": ("attn1.to_out.0.bias", _ident),
    "ff_in/kernel": ("ff.net.0.proj.weight", _linear),
    "ff_in/bias": ("ff.net.0.proj.bias", _ident),
    "ff_out/kernel": ("ff.net.2.weight", _linear),
    "ff_out/bias": ("ff.net.2.bias", _ident),
}


def prior_key_for(path: str):
    """our PriorTransformer path -> (published PriorTransformer key, tf)."""
    leaf = _PRIOR_SIMPLE.get(path)
    if leaf:
        return leaf
    m = re.match(r"block_(\d+)/(.+)$", path)
    if m:
        leaf = _PRIOR_BLOCK.get(m.group(2))
        if leaf:
            return f"transformer_blocks.{m.group(1)}.{leaf[0]}", leaf[1]
    raise ConversionError(f"unmapped prior path {path!r}")


def convert_kandinsky2_prior(state_dict: dict, template_params: dict
                             ) -> tuple[dict, np.ndarray]:
    """published prior state dict → (our prior tree, clip stats [2, D]).

    The stats row order is (clip_mean, clip_std) — the layout
    `prior_stats_init` establishes and `prior_sample` de-normalizes with.
    """
    tree = _convert_tree(template_params, state_dict, prior_key_for)
    for k in ("clip_mean", "clip_std"):
        if k not in state_dict:
            raise ConversionError(f"prior state dict missing {k!r}")
    stats = np.stack([np.asarray(state_dict["clip_mean"]).reshape(-1),
                      np.asarray(state_dict["clip_std"]).reshape(-1)])
    return tree, stats


# -- decoder ---------------------------------------------------------------

_ADDED_KV_ATTN = {
    "group_norm/GroupNorm_0/scale": ("group_norm.weight", _ident),
    "group_norm/GroupNorm_0/bias": ("group_norm.bias", _ident),
    "to_q/kernel": ("to_q.weight", _linear),
    "to_q/bias": ("to_q.bias", _ident),
    "to_k/kernel": ("to_k.weight", _linear),
    "to_k/bias": ("to_k.bias", _ident),
    "to_v/kernel": ("to_v.weight", _linear),
    "to_v/bias": ("to_v.bias", _ident),
    "add_k_proj/kernel": ("add_k_proj.weight", _linear),
    "add_k_proj/bias": ("add_k_proj.bias", _ident),
    "add_v_proj/kernel": ("add_v_proj.weight", _linear),
    "add_v_proj/bias": ("add_v_proj.bias", _ident),
    "to_out/kernel": ("to_out.0.weight", _linear),
    "to_out/bias": ("to_out.0.bias", _ident),
}


def kandinsky_unet_key_for(path: str, n_levels: int = 4):
    """our KandinskyUNet path -> (published unCLIP-style UNet key, tf).

    Resnets (including the resnet-based down/upsamplers) reuse the shared
    resnet leaf table; attention is the added-KV single-layer form."""
    simple = {
        "conv_in/kernel": ("conv_in.weight", _conv),
        "conv_in/bias": ("conv_in.bias", _ident),
        "conv_out/kernel": ("conv_out.weight", _conv),
        "conv_out/bias": ("conv_out.bias", _ident),
        "norm_out/GroupNorm_0/scale": ("conv_norm_out.weight", _ident),
        "norm_out/GroupNorm_0/bias": ("conv_norm_out.bias", _ident),
    }
    if path in simple:
        return simple[path]
    m = re.match(r"TimestepEmbedding_0/Dense_(\d)/(kernel|bias)$", path)
    if m:
        which = "linear_1" if m.group(1) == "0" else "linear_2"
        tf = _linear if m.group(2) == "kernel" else _ident
        return f"time_embedding.{which}.{'weight' if m.group(2) == 'kernel' else 'bias'}", tf
    part, _, rest = path.partition("/")

    def res(prefix):
        leaf = _RESNET_LEAVES.get(rest)
        if leaf is None:
            raise ConversionError(f"unmapped kandinsky unet path {path!r}")
        return f"{prefix}.{leaf[0]}", leaf[1]

    def attn(prefix):
        leaf = _ADDED_KV_ATTN.get(rest)
        if leaf is None:
            raise ConversionError(f"unmapped kandinsky unet path {path!r}")
        return f"{prefix}.{leaf[0]}", leaf[1]

    m = re.match(r"down_(\d+)_res_(\d+)$", part)
    if m:
        return res(f"down_blocks.{m.group(1)}.resnets.{m.group(2)}")
    m = re.match(r"down_(\d+)_attn_(\d+)$", part)
    if m:
        return attn(f"down_blocks.{m.group(1)}.attentions.{m.group(2)}")
    m = re.match(r"down_(\d+)_ds$", part)
    if m:
        return res(f"down_blocks.{m.group(1)}.downsamplers.0")
    m = re.match(r"up_(\d+)_res_(\d+)$", part)
    if m:
        return res(f"up_blocks.{n_levels - 1 - int(m.group(1))}"
                   f".resnets.{m.group(2)}")
    m = re.match(r"up_(\d+)_attn_(\d+)$", part)
    if m:
        return attn(f"up_blocks.{n_levels - 1 - int(m.group(1))}"
                    f".attentions.{m.group(2)}")
    m = re.match(r"up_(\d+)_us$", part)
    if m:
        return res(f"up_blocks.{n_levels - 1 - int(m.group(1))}"
                   ".upsamplers.0")
    if part == "mid_res_0":
        return res("mid_block.resnets.0")
    if part == "mid_res_1":
        return res("mid_block.resnets.1")
    if part == "mid_attn":
        return attn("mid_block.attentions.0")
    raise ConversionError(f"unmapped kandinsky unet path {path!r}")


_DECODER_HEAD = {
    "embed_to_context/kernel": ("encoder_hid_proj.image_embeds.weight", _linear),
    "embed_to_context/bias": ("encoder_hid_proj.image_embeds.bias", _ident),
    "context_norm/scale": ("encoder_hid_proj.norm.weight", _ident),
    "context_norm/bias": ("encoder_hid_proj.norm.bias", _ident),
    "add_linear_1/kernel": ("add_embedding.linear_1.weight", _linear),
    "add_linear_1/bias": ("add_embedding.linear_1.bias", _ident),
    "add_linear_2/kernel": ("add_embedding.linear_2.weight", _linear),
    "add_linear_2/bias": ("add_embedding.linear_2.bias", _ident),
}


def decoder_key_for(path: str, n_levels: int = 4):
    """our DecoderUNet path -> (published image-conditioned UNet key, tf).

    The conditioning head maps to ImageProjection/add_embedding; the inner
    `unet/` scope is the unCLIP-style UNet (`kandinsky_unet_key_for`)."""
    leaf = _DECODER_HEAD.get(path)
    if leaf:
        return leaf
    if path.startswith("unet/"):
        return kandinsky_unet_key_for(path[len("unet/"):], n_levels)
    raise ConversionError(f"unmapped decoder path {path!r}")


def convert_kandinsky2_decoder(state_dict: dict, template_params: dict,
                               n_levels: int = 4) -> dict:
    return _convert_tree(template_params, state_dict,
                         lambda p: decoder_key_for(p, n_levels))


# -- movq ------------------------------------------------------------------

def _spatial_norm_leaves(rest: str):
    """leaves under one of our SpatialNorm scopes -> published suffix."""
    table = {
        "norm/GroupNorm_0/scale": ("norm_layer.weight", _ident),
        "norm/GroupNorm_0/bias": ("norm_layer.bias", _ident),
        "conv_y/kernel": ("conv_y.weight", _conv),
        "conv_y/bias": ("conv_y.bias", _ident),
        "conv_b/kernel": ("conv_b.weight", _conv),
        "conv_b/bias": ("conv_b.bias", _ident),
    }
    return table.get(rest)


def _movq_res_leaves(rest: str):
    for norm in ("norm1", "norm2"):
        if rest.startswith(norm + "/"):
            leaf = _spatial_norm_leaves(rest[len(norm) + 1:])
            if leaf:
                return f"{norm}.{leaf[0]}", leaf[1]
    table = {
        "Conv_0/kernel": ("conv1.weight", _conv),
        "Conv_0/bias": ("conv1.bias", _ident),
        "Conv_1/kernel": ("conv2.weight", _conv),
        "Conv_1/bias": ("conv2.bias", _ident),
        "skip/kernel": ("conv_shortcut.weight", _conv),
        "skip/bias": ("conv_shortcut.bias", _ident),
    }
    return table.get(rest)


_MOVQ_ATTN = {
    "to_q/kernel": ("to_q.weight", _linear),
    "to_q/bias": ("to_q.bias", _ident),
    "to_k/kernel": ("to_k.weight", _linear),
    "to_k/bias": ("to_k.bias", _ident),
    "to_v/kernel": ("to_v.weight", _linear),
    "to_v/bias": ("to_v.bias", _ident),
    "to_out/kernel": ("to_out.0.weight", _linear),
    "to_out/bias": ("to_out.0.bias", _ident),
}


def movq_key_for(path: str, n_levels: int = 4):
    """our MOVQDecoder path -> (published VQModel key, transform)."""
    simple = {
        "post_quant/kernel": ("post_quant_conv.weight", _conv),
        "post_quant/bias": ("post_quant_conv.bias", _ident),
        "conv_in/kernel": ("decoder.conv_in.weight", _conv),
        "conv_in/bias": ("decoder.conv_in.bias", _ident),
        "conv_out/kernel": ("decoder.conv_out.weight", _conv),
        "conv_out/bias": ("decoder.conv_out.bias", _ident),
    }
    if path in simple:
        return simple[path]
    part, _, rest = path.partition("/")
    if part == "norm_out":
        leaf = _spatial_norm_leaves(rest)
        if leaf:
            return f"decoder.conv_norm_out.{leaf[0]}", leaf[1]
    m = re.match(r"mid_res_(\d)$", part)
    if m:
        leaf = _movq_res_leaves(rest)
        if leaf:
            return (f"decoder.mid_block.resnets.{m.group(1)}.{leaf[0]}",
                    leaf[1])
    if part == "mid_attn_norm":
        leaf = _spatial_norm_leaves(rest)
        if leaf:
            return (f"decoder.mid_block.attentions.0.spatial_norm.{leaf[0]}",
                    leaf[1])
    if part == "mid_attn":
        leaf = _MOVQ_ATTN.get(rest)
        if leaf:
            return f"decoder.mid_block.attentions.0.{leaf[0]}", leaf[1]
    m = re.match(r"up_(\d+)_res_(\d+)$", part)
    if m:
        leaf = _movq_res_leaves(rest)
        if leaf:
            return (f"decoder.up_blocks.{n_levels - 1 - int(m.group(1))}"
                    f".resnets.{m.group(2)}.{leaf[0]}", leaf[1])
    m = re.match(r"up_(\d+)_us$", part)
    if m:
        if rest == "Conv_0/kernel":
            return (f"decoder.up_blocks.{n_levels - 1 - int(m.group(1))}"
                    ".upsamplers.0.conv.weight", _conv)
        if rest == "Conv_0/bias":
            return (f"decoder.up_blocks.{n_levels - 1 - int(m.group(1))}"
                    ".upsamplers.0.conv.bias", _ident)
    raise ConversionError(f"unmapped movq path {path!r}")


def convert_kandinsky2_movq(state_dict: dict, template_params: dict,
                            n_levels: int = 4) -> dict:
    return _convert_tree(template_params, state_dict,
                         lambda p: movq_key_for(p, n_levels))


# -- text projection -------------------------------------------------------

def convert_kandinsky2_text_projection(state_dict: dict,
                                       template_params: dict) -> dict:
    """`text_projection.weight` → our TextProjection tree."""
    return _convert_tree(template_params, state_dict,
                         lambda p: ("text_projection.weight", _linear)
                         if p == "proj/kernel"
                         else (_ for _ in ()).throw(
                             ConversionError(f"unmapped text-proj path {p!r}")))


# -- inverse direction (interop tests) -------------------------------------

def export_tree(params: dict, key_for) -> dict:
    """ours → published naming, inverting the leaf transforms. GEGLU halves
    (decoder unet ff) are re-fused like export_sd15_unet."""
    import jax

    from arbius_tpu.models.sd15.convert import (
        _geglu_gate,
        _geglu_gate_b,
        _geglu_val,
        _geglu_val_b,
    )

    out: dict[str, np.ndarray] = {}
    fuse: dict[str, dict[str, np.ndarray]] = {}

    def visit(path, leaf):
        p = "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                     for k in path)
        key, tf = key_for(p)
        w = np.asarray(leaf)
        if tf is _conv:
            out[key] = np.transpose(w, (3, 2, 0, 1))
        elif tf is _linear:
            out[key] = np.transpose(w)
        elif tf in (_geglu_val, _geglu_gate, _geglu_val_b, _geglu_gate_b):
            half = "val" if tf in (_geglu_val, _geglu_val_b) else "gate"
            w2 = np.transpose(w) if tf in (_geglu_val, _geglu_gate) else w
            fuse.setdefault(key, {})[half] = w2
        else:
            out[key] = w

    jax.tree_util.tree_map_with_path(visit, params)
    for key, halves in fuse.items():
        out[key] = np.concatenate([halves["val"], halves["gate"]], axis=0)
    return out
