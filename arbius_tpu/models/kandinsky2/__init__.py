"""Kandinsky-2 model family: diffusion prior + decoder UNet + MOVQ.

The reference's flagship/boot-self-test model class
(`templates/kandinsky2.json`, `miner/src/index.ts:844-877`).
"""
from arbius_tpu.models.kandinsky2.convert import (
    convert_kandinsky2_decoder,
    convert_kandinsky2_movq,
    convert_kandinsky2_prior,
    convert_kandinsky2_text_projection,
)
from arbius_tpu.models.kandinsky2.decoder import DecoderConfig, DecoderUNet
from arbius_tpu.models.kandinsky2.movq import MOVQConfig, MOVQDecoder
from arbius_tpu.models.kandinsky2.pipeline import (
    Kandinsky2Config,
    Kandinsky2Pipeline,
)
from arbius_tpu.models.kandinsky2.prior import (
    PriorConfig,
    PriorTransformer,
    prior_sample,
)

__all__ = [
    "DecoderConfig", "DecoderUNet", "Kandinsky2Config", "Kandinsky2Pipeline",
    "MOVQConfig", "MOVQDecoder", "PriorConfig", "PriorTransformer",
    "convert_kandinsky2_decoder", "convert_kandinsky2_movq",
    "convert_kandinsky2_prior", "convert_kandinsky2_text_projection",
    "prior_sample",
]
