"""SD-1.5 model family (anythingv3 template class)."""
from arbius_tpu.models.sd15.pipeline import SD15Config, SD15Pipeline
from arbius_tpu.models.sd15.text_encoder import TextEncoder, TextEncoderConfig
from arbius_tpu.models.sd15.tokenizer import ByteTokenizer, CLIPBPETokenizer
from arbius_tpu.models.sd15.unet import UNet2DCondition, UNetConfig
from arbius_tpu.models.sd15.vae import VAEConfig, VAEDecoder, VAEEncoder

__all__ = [
    "ByteTokenizer",
    "CLIPBPETokenizer",
    "SD15Config",
    "SD15Pipeline",
    "TextEncoder",
    "TextEncoderConfig",
    "UNet2DCondition",
    "UNetConfig",
    "VAEConfig",
    "VAEDecoder",
    "VAEEncoder",
]
