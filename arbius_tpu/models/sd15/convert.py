"""Checkpoint conversion: diffusers/CLIP state dicts → arbius param trees.

A user of the reference mines with published SD-1.5-family weights
(anythingv3's cog container wraps a diffusers checkpoint). This module
maps those state dicts onto this framework's flax trees so the same
weights drive the TPU path:

  - torch Linear [out, in]      → flax kernel [in, out] (transpose)
  - torch Conv2d [O, I, kH, kW] → flax kernel [kH, kW, I, O]
  - diffusers fused GEGLU ff.net.0.proj → split into ff_val/ff_gate
    (value half first, matching diffusers' .chunk(2) order)
  - CLIP attention q/k/v/out [E, E] → flax attention heads
    [E, H, D] / [H, D, E]

Input is a flat `{key: numpy array}` dict (load a .safetensors /
torch .bin with your loader of choice and pass `{k: v.numpy()}`).
Completeness is enforced: every leaf of the target tree must be produced,
and shape mismatches fail loudly with both shapes in the message.
Bijectivity (ours → diffusers naming → ours is the identity) is tested in
tests/test_convert.py; numeric validation against a live diffusers
pipeline needs real weights and is a deployment-time step (the boot
self-test's golden CID is the final arbiter either way).
"""
from __future__ import annotations

import re

import numpy as np


class ConversionError(ValueError):
    pass


def _linear(w):  # torch [out, in] -> flax [in, out]
    return np.ascontiguousarray(np.transpose(w))


def _conv(w):    # torch [O, I, kH, kW] -> flax [kH, kW, I, O]
    return np.ascontiguousarray(np.transpose(w, (2, 3, 1, 0)))


def _ident(w):
    return np.asarray(w)


# -- UNet key translation --------------------------------------------------

def _unet_block_prefix(part: str, n_levels: int) -> str | None:
    """our 'down_2_res_1' style prefix -> diffusers block prefix."""
    m = re.match(r"down_(\d+)_res_(\d+)$", part)
    if m:
        return f"down_blocks.{m.group(1)}.resnets.{m.group(2)}"
    m = re.match(r"down_(\d+)_attn_(\d+)$", part)
    if m:
        return f"down_blocks.{m.group(1)}.attentions.{m.group(2)}"
    m = re.match(r"down_(\d+)_ds$", part)
    if m:
        return f"down_blocks.{m.group(1)}.downsamplers.0"
    m = re.match(r"up_(\d+)_res_(\d+)$", part)
    if m:
        return (f"up_blocks.{n_levels - 1 - int(m.group(1))}"
                f".resnets.{m.group(2)}")
    m = re.match(r"up_(\d+)_attn_(\d+)$", part)
    if m:
        return (f"up_blocks.{n_levels - 1 - int(m.group(1))}"
                f".attentions.{m.group(2)}")
    m = re.match(r"up_(\d+)_us$", part)
    if m:
        return f"up_blocks.{n_levels - 1 - int(m.group(1))}.upsamplers.0"
    if part == "mid_res_0":
        return "mid_block.resnets.0"
    if part == "mid_res_1":
        return "mid_block.resnets.1"
    if part == "mid_attn":
        return "mid_block.attentions.0"
    return None


_RESNET_LEAVES = {
    "GroupNorm32_0/GroupNorm_0/scale": ("norm1.weight", _ident),
    "GroupNorm32_0/GroupNorm_0/bias": ("norm1.bias", _ident),
    "Conv_0/kernel": ("conv1.weight", _conv),
    "Conv_0/bias": ("conv1.bias", _ident),
    "Dense_0/kernel": ("time_emb_proj.weight", _linear),
    "Dense_0/bias": ("time_emb_proj.bias", _ident),
    "GroupNorm32_1/GroupNorm_0/scale": ("norm2.weight", _ident),
    "GroupNorm32_1/GroupNorm_0/bias": ("norm2.bias", _ident),
    "Conv_1/kernel": ("conv2.weight", _conv),
    "Conv_1/bias": ("conv2.bias", _ident),
    "skip_proj/kernel": ("conv_shortcut.weight", _conv),
    "skip_proj/bias": ("conv_shortcut.bias", _ident),
}

_TXBLOCK_LEAVES = {
    "LayerNorm_0/scale": ("norm1.weight", _ident),
    "LayerNorm_0/bias": ("norm1.bias", _ident),
    "LayerNorm_1/scale": ("norm2.weight", _ident),
    "LayerNorm_1/bias": ("norm2.bias", _ident),
    "LayerNorm_2/scale": ("norm3.weight", _ident),
    "LayerNorm_2/bias": ("norm3.bias", _ident),
    "attn1/to_q/kernel": ("attn1.to_q.weight", _linear),
    "attn1/to_k/kernel": ("attn1.to_k.weight", _linear),
    "attn1/to_v/kernel": ("attn1.to_v.weight", _linear),
    "attn1/to_out/kernel": ("attn1.to_out.0.weight", _linear),
    "attn1/to_out/bias": ("attn1.to_out.0.bias", _ident),
    "attn2/to_q/kernel": ("attn2.to_q.weight", _linear),
    "attn2/to_k/kernel": ("attn2.to_k.weight", _linear),
    "attn2/to_v/kernel": ("attn2.to_v.weight", _linear),
    "attn2/to_out/kernel": ("attn2.to_out.0.weight", _linear),
    "attn2/to_out/bias": ("attn2.to_out.0.bias", _ident),
    "ff_out/kernel": ("ff.net.2.weight", _linear),
    "ff_out/bias": ("ff.net.2.bias", _ident),
}


def _geglu_val(w):
    return _linear(np.split(np.asarray(w), 2, axis=0)[0])


def _geglu_gate(w):
    return _linear(np.split(np.asarray(w), 2, axis=0)[1])


def _geglu_val_b(b):
    return np.split(np.asarray(b), 2, axis=0)[0]


def _geglu_gate_b(b):
    return np.split(np.asarray(b), 2, axis=0)[1]


_GEGLU_LEAVES = {
    "ff/ff_val/kernel": ("ff.net.0.proj.weight", _geglu_val),
    "ff/ff_val/bias": ("ff.net.0.proj.bias", _geglu_val_b),
    "ff/ff_gate/kernel": ("ff.net.0.proj.weight", _geglu_gate),
    "ff/ff_gate/bias": ("ff.net.0.proj.bias", _geglu_gate_b),
}

_SPATIAL_LEAVES = {
    "GroupNorm32_0/GroupNorm_0/scale": ("norm.weight", _ident),
    "GroupNorm32_0/GroupNorm_0/bias": ("norm.bias", _ident),
    "proj_in/kernel": ("proj_in.weight", _conv),
    "proj_in/bias": ("proj_in.bias", _ident),
    "proj_out/kernel": ("proj_out.weight", _conv),
    "proj_out/bias": ("proj_out.bias", _ident),
}


def unet_key_for(path: str, n_levels: int):
    """our flax path (joined with /) -> (diffusers key, transform)."""
    if path == "conv_in/kernel":
        return "conv_in.weight", _conv
    if path == "conv_in/bias":
        return "conv_in.bias", _ident
    if path == "conv_out/kernel":
        return "conv_out.weight", _conv
    if path == "conv_out/bias":
        return "conv_out.bias", _ident
    if path == "norm_out/GroupNorm_0/scale":
        return "conv_norm_out.weight", _ident
    if path == "norm_out/GroupNorm_0/bias":
        return "conv_norm_out.bias", _ident
    m = re.match(r"TimestepEmbedding_0/Dense_(\d)/(kernel|bias)$", path)
    if m:
        which = "linear_1" if m.group(1) == "0" else "linear_2"
        if m.group(2) == "kernel":
            return f"time_embedding.{which}.weight", _linear
        return f"time_embedding.{which}.bias", _ident
    part, _, rest = path.partition("/")
    prefix = _unet_block_prefix(part, n_levels)
    if prefix is None:
        raise ConversionError(f"unmapped unet path {path!r}")
    if "_res_" in part or part.startswith("mid_res"):
        leaf = _RESNET_LEAVES.get(rest)
        if leaf:
            return f"{prefix}.{leaf[0]}", leaf[1]
    if part.endswith("_ds") or part.endswith("_us"):
        if rest == "Conv_0/kernel":
            return f"{prefix}.conv.weight", _conv
        if rest == "Conv_0/bias":
            return f"{prefix}.conv.bias", _ident
    if "_attn_" in part or part == "mid_attn":
        leaf = _SPATIAL_LEAVES.get(rest)
        if leaf:
            return f"{prefix}.{leaf[0]}", leaf[1]
        m = re.match(r"block_(\d+)/(.+)$", rest)
        if m:
            tb = f"{prefix}.transformer_blocks.{m.group(1)}"
            inner = m.group(2)
            leaf = _TXBLOCK_LEAVES.get(inner)
            if leaf:
                return f"{tb}.{leaf[0]}", leaf[1]
            leaf = _GEGLU_LEAVES.get(inner)
            if leaf:
                return f"{tb}.{leaf[0]}", leaf[1]
    raise ConversionError(f"unmapped unet path {path!r}")


# -- tree walk -------------------------------------------------------------

def _convert_tree(template: dict, state_dict: dict, key_for) -> dict:
    import jax

    flat = {}
    def record(path, leaf):
        parts = tuple(str(getattr(k, "key", getattr(k, "idx", k)))
                      for k in path)
        flat[parts] = leaf
    jax.tree_util.tree_map_with_path(record, template)

    out = {}
    missing = []
    for parts, leaf in flat.items():
        path = "/".join(parts)
        key, tf = key_for(path)
        if key not in state_dict:
            missing.append(key)
            continue
        w = tf(state_dict[key])
        if tuple(w.shape) != tuple(leaf.shape):
            raise ConversionError(
                f"{path}: converted shape {tuple(w.shape)} != expected "
                f"{tuple(leaf.shape)} (from {key})")
        node = out
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = w
    if missing:
        raise ConversionError(
            f"{len(missing)} keys missing from state dict, e.g. "
            f"{sorted(missing)[:5]}")
    return out


# -- VAE decoder key translation -------------------------------------------

_VAE_RESNET = {
    "GroupNorm32_0/GroupNorm_0/scale": ("norm1.weight", _ident),
    "GroupNorm32_0/GroupNorm_0/bias": ("norm1.bias", _ident),
    "Conv_0/kernel": ("conv1.weight", _conv),
    "Conv_0/bias": ("conv1.bias", _ident),
    "GroupNorm32_1/GroupNorm_0/scale": ("norm2.weight", _ident),
    "GroupNorm32_1/GroupNorm_0/bias": ("norm2.bias", _ident),
    "Conv_1/kernel": ("conv2.weight", _conv),
    "Conv_1/bias": ("conv2.bias", _ident),
    "skip_proj/kernel": ("conv_shortcut.weight", _conv),
    "skip_proj/bias": ("conv_shortcut.bias", _ident),
}

_VAE_ATTN = {
    "GroupNorm32_0/GroupNorm_0/scale": ("group_norm.weight", _ident),
    "GroupNorm32_0/GroupNorm_0/bias": ("group_norm.bias", _ident),
    "Attention_0/to_q/kernel": ("to_q.weight", _linear),
    "Attention_0/to_q/bias": ("to_q.bias", _ident),
    "Attention_0/to_k/kernel": ("to_k.weight", _linear),
    "Attention_0/to_k/bias": ("to_k.bias", _ident),
    "Attention_0/to_v/kernel": ("to_v.weight", _linear),
    "Attention_0/to_v/bias": ("to_v.bias", _ident),
    "Attention_0/to_out/kernel": ("to_out.0.weight", _linear),
    "Attention_0/to_out/bias": ("to_out.0.bias", _ident),
}


def vae_key_for(path: str, n_levels: int = 4):
    """our VAEDecoder path -> (diffusers AutoencoderKL key, transform)."""
    simple = {
        "post_quant/kernel": ("post_quant_conv.weight", _conv),
        "post_quant/bias": ("post_quant_conv.bias", _ident),
        "conv_in/kernel": ("decoder.conv_in.weight", _conv),
        "conv_in/bias": ("decoder.conv_in.bias", _ident),
        "conv_out/kernel": ("decoder.conv_out.weight", _conv),
        "conv_out/bias": ("decoder.conv_out.bias", _ident),
        "norm_out/GroupNorm_0/scale": ("decoder.conv_norm_out.weight", _ident),
        "norm_out/GroupNorm_0/bias": ("decoder.conv_norm_out.bias", _ident),
    }
    if path in simple:
        return simple[path]
    part, _, rest = path.partition("/")
    m = re.match(r"mid_res_(\d)$", part)
    if m:
        leaf = _VAE_RESNET.get(rest)
        if leaf:
            return (f"decoder.mid_block.resnets.{m.group(1)}.{leaf[0]}",
                    leaf[1])
    if part == "mid_attn":
        leaf = _VAE_ATTN.get(rest)
        if leaf:
            return f"decoder.mid_block.attentions.0.{leaf[0]}", leaf[1]
    m = re.match(r"up_(\d+)_res_(\d+)$", part)
    if m:
        leaf = _VAE_RESNET.get(rest)
        if leaf:
            return (f"decoder.up_blocks.{n_levels - 1 - int(m.group(1))}"
                    f".resnets.{m.group(2)}.{leaf[0]}", leaf[1])
    m = re.match(r"up_(\d+)_us$", part)
    if m:
        if rest == "Conv_0/kernel":
            return (f"decoder.up_blocks.{n_levels - 1 - int(m.group(1))}"
                    ".upsamplers.0.conv.weight", _conv)
        if rest == "Conv_0/bias":
            return (f"decoder.up_blocks.{n_levels - 1 - int(m.group(1))}"
                    ".upsamplers.0.conv.bias", _ident)
    raise ConversionError(f"unmapped vae path {path!r}")


# -- CLIP text encoder key translation -------------------------------------

def _make_attn_head_tf(heads: int, head_dim: int, kind: str):
    """CLIP [E, E]/[E] projections -> flax SelfAttention head layout."""
    if kind == "qkv_kernel":
        return lambda w: _linear(w).reshape(-1, heads, head_dim)
    if kind == "qkv_bias":
        return lambda b: np.asarray(b).reshape(heads, head_dim)
    if kind == "out_kernel":
        return lambda w: _linear(w).reshape(heads, head_dim, -1)
    return _ident  # out bias


def text_key_for(path: str, heads: int, head_dim: int):
    """our TextEncoder path -> (transformers CLIPTextModel key, transform).

    Production note: real CLIP checkpoints pair with the CLIP BPE
    tokenizer; the TextEncoder consumes any id stream, so swap the
    ByteTokenizer for a BPE tokenizer when loading converted weights.
    """
    simple = {
        "token_embed/embedding":
            ("text_model.embeddings.token_embedding.weight", _ident),
        "pos_embed":
            ("text_model.embeddings.position_embedding.weight", _ident),
        "final_norm/scale": ("text_model.final_layer_norm.weight", _ident),
        "final_norm/bias": ("text_model.final_layer_norm.bias", _ident),
    }
    if path in simple:
        return simple[path]
    m = re.match(r"layer_(\d+)/(.+)$", path)
    if not m:
        raise ConversionError(f"unmapped text path {path!r}")
    base = f"text_model.encoder.layers.{m.group(1)}"
    rest = m.group(2)
    attn_names = {"query": "q_proj", "key": "k_proj", "value": "v_proj"}
    for ours, theirs in attn_names.items():
        if rest == f"attn/{ours}/kernel":
            return (f"{base}.self_attn.{theirs}.weight",
                    _make_attn_head_tf(heads, head_dim, "qkv_kernel"))
        if rest == f"attn/{ours}/bias":
            return (f"{base}.self_attn.{theirs}.bias",
                    _make_attn_head_tf(heads, head_dim, "qkv_bias"))
    if rest == "attn/out/kernel":
        return (f"{base}.self_attn.out_proj.weight",
                _make_attn_head_tf(heads, head_dim, "out_kernel"))
    if rest == "attn/out/bias":
        return f"{base}.self_attn.out_proj.bias", _ident
    mlp = {
        "Dense_0/kernel": ("mlp.fc1.weight", _linear),
        "Dense_0/bias": ("mlp.fc1.bias", _ident),
        "Dense_1/kernel": ("mlp.fc2.weight", _linear),
        "Dense_1/bias": ("mlp.fc2.bias", _ident),
        "LayerNorm_0/scale": ("layer_norm1.weight", _ident),
        "LayerNorm_0/bias": ("layer_norm1.bias", _ident),
        "LayerNorm_1/scale": ("layer_norm2.weight", _ident),
        "LayerNorm_1/bias": ("layer_norm2.bias", _ident),
    }
    if rest in mlp:
        key, tf = mlp[rest]
        return f"{base}.{key}", tf
    raise ConversionError(f"unmapped text path {path!r}")


def convert_sd15_vae(state_dict: dict, template_params: dict,
                     n_levels: int = 4) -> dict:
    """diffusers AutoencoderKL state dict → our VAEDecoder param tree."""
    return _convert_tree(template_params, state_dict,
                         lambda p: vae_key_for(p, n_levels))


def convert_sd15_text(state_dict: dict, template_params: dict,
                      heads: int, head_dim: int) -> dict:
    """transformers CLIPTextModel state dict → our TextEncoder tree."""
    return _convert_tree(template_params, state_dict,
                         lambda p: text_key_for(p, heads, head_dim))


def convert_sd15_unet(state_dict: dict, template_params: dict,
                      n_levels: int = 4) -> dict:
    """diffusers UNet2DConditionModel state dict → our unet param tree.

    `template_params` is an init_params()['unet'] tree providing the
    target structure and shapes.
    """
    return _convert_tree(template_params, state_dict,
                         lambda p: unet_key_for(p, n_levels))


def export_sd15_unet(params: dict, n_levels: int = 4) -> dict:
    """Inverse direction (ours → diffusers naming), for interop tests.

    GEGLU halves are re-fused; conv/linear transforms are inverted.
    """
    import jax

    out: dict[str, np.ndarray] = {}
    fuse: dict[str, dict[str, np.ndarray]] = {}

    def visit(path, leaf):
        p = "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                     for k in path)
        key, tf = unet_key_for(p, n_levels)
        w = np.asarray(leaf)
        if tf is _conv:
            out[key] = np.transpose(w, (3, 2, 0, 1))
        elif tf is _linear:
            out[key] = np.transpose(w)
        elif tf in (_geglu_val, _geglu_gate, _geglu_val_b, _geglu_gate_b):
            half = "val" if tf in (_geglu_val, _geglu_val_b) else "gate"
            w2 = np.transpose(w) if tf in (_geglu_val, _geglu_gate) else w
            fuse.setdefault(key, {})[half] = w2
        else:
            out[key] = w

    jax.tree_util.tree_map_with_path(visit, params)
    for key, halves in fuse.items():
        out[key] = np.concatenate([halves["val"], halves["gate"]], axis=0)
    return out
