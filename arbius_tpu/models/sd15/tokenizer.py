"""Deterministic prompt tokenizer.

The reference's tokenization happens inside its cog container (CLIP BPE).
This environment has zero egress, so the real BPE vocab/merges can't be
fetched; the framework therefore ships a fully deterministic byte-level
tokenizer as the default, and can load a standard CLIP BPE vocab from local
files when an operator provides one (`CLIPBPETokenizer.from_files`).

Determinism is the property the protocol needs — the tokenizer is part of
the model's identity (a template pins a specific model build), and any
fixed mapping works as long as every miner runs the same one.
"""
from __future__ import annotations

import json
import re

import numpy as np

# CLIP's pre-tokenization pattern (contractions, letter runs, single digits,
# punctuation runs) expressed with stdlib re: [^\W\d_]+ matches unicode
# letter runs, \d single digits, [^\s\w]+ punctuation/symbol runs.
_CLIP_SPLIT = re.compile(
    r"'s|'t|'re|'ve|'m|'ll|'d|[^\W\d_]+|\d|[^\s\w]+|_+", re.IGNORECASE)

BOS_ID = 49406
EOS_ID = 49407
MAX_LENGTH = 77


class ByteTokenizer:
    """UTF-8 byte-level tokenizer into the CLIP id space.

    ids 0..255 are raw bytes; BOS/EOS/pad use the CLIP special ids so the
    embedding table shape matches the standard text tower.
    """

    def __init__(self, max_length: int = MAX_LENGTH,
                 bos_id: int = BOS_ID, eos_id: int = EOS_ID):
        self.max_length = max_length
        self.bos_id = bos_id
        self.eos_id = eos_id

    def encode(self, text: str) -> np.ndarray:
        raw = list(text.encode("utf-8"))[: self.max_length - 2]
        ids = [self.bos_id] + raw + [self.eos_id]
        ids += [self.eos_id] * (self.max_length - len(ids))  # CLIP pads with EOS
        return np.asarray(ids, dtype=np.int32)

    def encode_batch(self, texts: list[str]) -> np.ndarray:
        return np.stack([self.encode(t) for t in texts])


class CLIPBPETokenizer:
    """Standard CLIP byte-pair tokenizer, loaded from local vocab files.

    Implements lowercasing, whitespace-split + punctuation regex-free word
    splitting, and greedy merge ranking over `merges.txt`, producing ids
    compatible with pretrained CLIP text towers.
    """

    def __init__(self, encoder: dict[str, int], merges: list[tuple[str, str]],
                 max_length: int = MAX_LENGTH):
        self.encoder = encoder
        self.ranks = {m: i for i, m in enumerate(merges)}
        self.max_length = max_length
        self.bos_id = encoder.get("<|startoftext|>", BOS_ID)
        self.eos_id = encoder.get("<|endoftext|>", EOS_ID)
        self._byte_encoder = _bytes_to_unicode()

    @classmethod
    def from_files(cls, vocab_path: str, merges_path: str) -> "CLIPBPETokenizer":
        with open(vocab_path) as f:
            encoder = json.load(f)
        with open(merges_path) as f:
            lines = f.read().splitlines()
        merges = [tuple(l.split()) for l in lines
                  if l and not l.startswith("#") and len(l.split()) == 2]
        return cls(encoder, merges)

    def _bpe(self, token: str) -> list[str]:
        # CLIP attaches </w> to the LAST CHARACTER, not as its own symbol
        if token.endswith("</w>") and len(token) > 4:
            base = token[:-4]
            word = list(base[:-1]) + [base[-1] + "</w>"]
        else:
            word = list(token)
        while len(word) > 1:
            pairs = {(word[i], word[i + 1]) for i in range(len(word) - 1)}
            best = min(pairs, key=lambda p: self.ranks.get(p, float("inf")))
            if best not in self.ranks:
                break
            merged = []
            i = 0
            while i < len(word):
                if i < len(word) - 1 and (word[i], word[i + 1]) == best:
                    merged.append(word[i] + word[i + 1])
                    i += 2
                else:
                    merged.append(word[i])
                    i += 1
            word = merged
        return word

    def encode(self, text: str) -> np.ndarray:
        text = re.sub(r"\s+", " ", text.lower().strip())
        words = _CLIP_SPLIT.findall(text)
        ids = [self.bos_id]
        for w in words:
            mapped = "".join(self._byte_encoder[b] for b in w.encode("utf-8"))
            for piece in self._bpe(mapped + "</w>"):
                ids.append(self.encoder.get(piece, self.eos_id))
        ids = ids[: self.max_length - 1] + [self.eos_id]
        ids += [self.eos_id] * (self.max_length - len(ids))
        return np.asarray(ids, dtype=np.int32)

    def encode_batch(self, texts: list[str]) -> np.ndarray:
        return np.stack([self.encode(t) for t in texts])


def _bytes_to_unicode() -> dict[int, str]:
    """GPT-2/CLIP reversible byte->unicode mapping."""
    bs = (list(range(ord("!"), ord("~") + 1))
          + list(range(ord("¡"), ord("¬") + 1))
          + list(range(ord("®"), ord("ÿ") + 1)))
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return dict(zip(bs, (chr(c) for c in cs)))
