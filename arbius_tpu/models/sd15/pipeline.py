"""SD-1.5 txt2img pipeline — the anythingv3 execution path, in-process.

Replaces the reference's HTTP hop to a cog container
(`miner/src/index.ts:852-876`) with a jit-compiled XLA program per shape
bucket. Determinism root: the per-task seed (taskid2seed) feeds a JAX PRNG
key; init latents and every ancestral noise draw derive from it via fold_in,
so a task id always produces the same bytes on the same model build.

Batching: `generate` takes a batch of tasks sharing one shape bucket
(width, height, steps, scheduler are the bucket key; the template enums make
this a small finite set). Per-sample guidance scales and seeds vary freely
within a batch. The runtime layer (arbius_tpu/runtime) groups queued tasks
into buckets and shards the batch axis over the device mesh.

Determinism vs batching: a task's output bytes must not depend on which
other tasks happened to share its batch. XLA guarantees identical bits for
identical compiled programs, but batch size is part of the program — so the
runtime always pads a bucket to its CANONICAL batch size (dp_size × the
bucket's per-chip batch) with dummy samples rather than compiling per
occupancy. One program per bucket ⇒ one determinism class per bucket.
"""
from __future__ import annotations


from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from arbius_tpu.models.sd15.text_encoder import TextEncoder, TextEncoderConfig
from arbius_tpu.models.sd15.tokenizer import ByteTokenizer
from arbius_tpu.models.sd15.unet import UNet2DCondition, UNetConfig
from arbius_tpu.models.sd15.vae import (
    SD_LATENT_SCALE,
    VAEConfig,
    VAEDecoder,
    decode_to_images,
)
from arbius_tpu.schedulers import get_sampler


@dataclass(frozen=True)
class SD15Config:
    unet: UNetConfig = UNetConfig()
    vae: VAEConfig = VAEConfig()
    text: TextEncoderConfig = TextEncoderConfig()

    @classmethod
    def tiny(cls) -> "SD15Config":
        return cls(UNetConfig.tiny(), VAEConfig.tiny(), TextEncoderConfig.tiny())


class SD15Pipeline:
    """Stateless module bundle + jitted per-bucket executables."""

    VAE_FACTOR = 8

    def __init__(self, config: SD15Config | None = None, tokenizer=None,
                 mesh=None, precision: str = "bf16"):
        from arbius_tpu.quant import validate_mode

        self.config = config or SD15Config()
        self.mesh = mesh  # jax.sharding.Mesh with a 'dp' axis, or None
        # precision mode (docs/quantization.md): "bf16" is THIS
        # pipeline's historic program byte-for-byte; int8/fp8 expect
        # checkpoint weights quantized at load (factory) and dequantize
        # them inside the bucket program — each mode its own golden
        self.precision = validate_mode(precision)
        if self.config.text.width != self.config.unet.context_dim:
            raise ValueError(
                f"text encoder width ({self.config.text.width}) must equal "
                f"unet context_dim ({self.config.unet.context_dim})")
        self.tokenizer = tokenizer or ByteTokenizer(
            max_length=self.config.text.max_length)
        self.unet = UNet2DCondition(self.config.unet)
        self.vae = VAEDecoder(self.config.vae)
        self.text_encoder = TextEncoder(self.config.text)
        # per-instance executable cache: dies with the pipeline (an lru_cache
        # on the method would pin self in a class-global cache)
        self._buckets: dict[tuple, object] = {}
        self._coll_est: dict[tuple, dict] = {}  # per-bucket traffic estimate

    # -- params ----------------------------------------------------------
    def init_params(self, seed: int = 0, height: int = 64, width: int = 64,
                    dtype=None) -> dict:
        """Deterministic parameter init (stands in for converted weights).

        The whole init is one jitted XLA program so parameters materialize
        directly on the accelerator: eager flax `.init` dispatches hundreds
        of small ops one-by-one, which is pathological over a remote-TPU
        tunnel (each dispatch is a round-trip), and host-side init would
        need a multi-GB host→HBM transfer afterwards. Same bits either way
        (JAX PRNG is algorithmically deterministic under jit).

        `dtype` folds the weights cast into the SAME program via
        utils.with_cast (HBM-peak rationale in its docstring)."""
        from arbius_tpu.utils import with_cast

        lh, lw = height // self.VAE_FACTOR, width // self.VAE_FACTOR

        return jax.jit(with_cast(self._init_fn(lh, lw), dtype))(
            jax.random.PRNGKey(seed))

    def _init_fn(self, lh: int, lw: int):
        def _init(key):
            k1, k2, k3 = jax.random.split(key, 3)
            latents = jnp.zeros((1, lh, lw, self.config.unet.in_channels))
            ids = jnp.zeros((1, self.config.text.max_length), jnp.int32)
            ctx = jnp.zeros(
                (1, self.config.text.max_length, self.config.unet.context_dim))
            return {
                "unet": self.unet.init(k1, latents, jnp.zeros((1,)), ctx)["params"],
                "vae": self.vae.init(k2, latents)["params"],
                "text": self.text_encoder.init(k3, ids)["params"],
            }

        return _init

    def init_params_placed(self, seed: int = 0, height: int = 64,
                           width: int = 64, tp_rules=None) -> dict:
        """Fused init + mesh placement: ONE jitted program whose
        out_shardings are the rule table's shardings, so parameters
        materialize directly in their sharded layout. The per-leaf
        device_put path (init then shard_params) dispatched ~700 host
        transfers and took minutes for the 860M tree on a 1-core host;
        this is one XLA program. Same bits as init_params (JAX PRNG is
        deterministic under jit regardless of sharding)."""
        if self.mesh is None:
            return self.init_params(seed=seed, height=height, width=width)
        from arbius_tpu.parallel import DEFAULT_TP_RULES, sharding_tree

        if tp_rules is None:
            tp_rules = DEFAULT_TP_RULES
        lh, lw = height // self.VAE_FACTOR, width // self.VAE_FACTOR
        init = self._init_fn(lh, lw)
        key = jax.random.PRNGKey(seed)
        shapes = jax.eval_shape(init, key)
        out = sharding_tree(shapes, self.mesh, tp_rules)
        return jax.jit(init, out_shardings=out)(key)

    def place_params(self, params: dict, tp_rules=None) -> dict:
        """Shard params onto self.mesh: TP kernels by rule (the family's
        DEFAULT_TP_RULES unless overridden), everything else replicated.
        On a tp=1 mesh the rules degrade to replication, so the default
        is always safe — and on tp>1 it is required (replicating every
        param would waste the tp axis entirely)."""
        if self.mesh is None:
            return params
        from arbius_tpu.parallel import DEFAULT_TP_RULES, shard_params

        if tp_rules is None:
            tp_rules = DEFAULT_TP_RULES
        return shard_params(params, self.mesh, tp_rules)

    def _place_batch(self, *arrays):
        """Shard batch-leading arrays over the dp axis of the mesh
        (meshsolve.shard_batch: replicates instead when the batch does
        not divide dp, so an under-filled bucket runs with idle dp lanes
        rather than erroring)."""
        if self.mesh is None:
            return arrays
        from arbius_tpu.parallel import meshsolve

        return meshsolve.shard_batch(self.mesh, *arrays)

    # -- compiled bucket -------------------------------------------------
    def _bucket_fn(self, batch: int, height: int, width: int,
                   steps: int, scheduler: str):
        return self._get_bucket(batch, height, width, steps, scheduler)[0]

    def bucket_tag(self, batch: int, height: int, width: int, steps: int,
                   scheduler: str) -> str:
        """The ONE definition of this family's executable-cache tag —
        the jit-cache warm set, the AOT cache's disk-warm scan, and the
        scheduler's cross-life warm boost all join on this string
        (docs/compile-cache.md), so it may never be rebuilt ad hoc.
        Non-default precision modes suffix the tag (".int8"/".fp8") —
        a quantized bucket and its bf16 twin are different programs and
        must never share a warm signal; bf16 tags are byte-identical to
        the pre-quant node."""
        from arbius_tpu.quant import mode_tag

        return "sd15." + ".".join(
            str(k) for k in (batch, height, width, steps, scheduler)) \
            + mode_tag(self.precision)

    def _get_bucket(self, batch: int, height: int, width: int,
                    steps: int, scheduler: str, aot_args=None):
        """(fn, warm, tag) — the cached bucket executable, whether it
        was already built, and its cache tag; the lookup reports
        through the jit-cache metrics (docs/observability.md) so
        warm-executable reuse is fleet-visible. `aot_args` (the exact
        dispatch arguments, as a thunk) opts the lookup into the AOT
        disk tier when one is installed (docs/compile-cache.md)."""
        from arbius_tpu.obs import jit_cache_get

        key = (batch, height, width, steps, scheduler)
        return jit_cache_get(
            self._buckets, key,
            lambda: self._build_bucket(batch, height, width, steps,
                                       scheduler),
            tag=self.bucket_tag(*key), aot_args=aot_args)

    def _build_bucket(self, batch: int, height: int, width: int,
                      steps: int, scheduler: str):
        sampler = get_sampler(scheduler, steps)
        lh, lw = height // self.VAE_FACTOR, width // self.VAE_FACTOR
        lat_shape = (batch, lh, lw, self.config.unet.in_channels)
        precision = self.precision

        def run(params, ids_cond, ids_uncond, guidance, seeds_lo, seeds_hi):
            if precision != "bf16":
                from arbius_tpu.quant import dequantize_tree

                # int8/fp8 kernels → f32 via their explicit f32 scales
                # (GRAPH407 contract); the modules then cast to their
                # bf16 compute dtype exactly as with f32 checkpoints.
                # Guarded so the bf16 program stays byte-identical.
                params = dequantize_tree(params)
            ctx_c = self.text_encoder.apply({"params": params["text"]}, ids_cond)
            ctx_u = self.text_encoder.apply({"params": params["text"]}, ids_uncond)
            context = jnp.concatenate([ctx_u, ctx_c], axis=0)  # [2B, L, D]

            # full 53-bit taskid2seed space: low word keys, high word folded in
            keys = jax.vmap(
                lambda lo, hi: jax.random.fold_in(jax.random.PRNGKey(lo), hi)
            )(seeds_lo, seeds_hi)
            x = jax.vmap(
                lambda k: jax.random.normal(k, lat_shape[1:], jnp.float32))(keys)
            x = x * sampler.init_noise_sigma
            g = guidance.astype(jnp.float32)[:, None, None, None]

            def body(carry, i):
                x, state = carry
                xin = jnp.concatenate([x, x], axis=0) * sampler.input_scale[i]
                t = jnp.full((2 * batch,), sampler.timesteps[i])
                eps = self.unet.apply({"params": params["unet"]}, xin, t, context)
                eps_u, eps_c = jnp.split(eps.astype(jnp.float32), 2, axis=0)
                eps = eps_u + g * (eps_c - eps_u)
                noise = jax.vmap(lambda k: jax.random.normal(
                    jax.random.fold_in(k, i), lat_shape[1:], jnp.float32))(keys)
                x, state = sampler.step(i, x, eps, state, noise)
                return (x, state), None

            (x, _), _ = jax.lax.scan(
                body, (x, sampler.init_carry(x)),
                jnp.arange(sampler.num_model_calls))
            pixels = self.vae.apply({"params": params["vae"]}, x / SD_LATENT_SCALE)
            return decode_to_images(pixels)

        if self.mesh is None:
            # the exact pre-mesh program: goldens pin this byte-for-byte
            fn = jax.jit(run)
        else:
            # GSPMD (docs/multichip.md): batch args dp-sharded, params
            # inherit their boot-time rule-table placement (None =
            # unspecified), output left dp-sharded — the gather happens
            # host-side in canonical order. XLA inserts the tp
            # collectives from the param shardings.
            from arbius_tpu.parallel import meshsolve

            spec, _ = meshsolve.batch_specs(self.mesh, batch)
            fn = jax.jit(
                run,
                in_shardings=(None, spec(2), spec(2), spec(1), spec(1),
                              spec(1)),
                out_shardings=spec(4))
        return fn

    # -- public API ------------------------------------------------------
    def compiled_bucket(self, batch: int, height: int, width: int,
                        steps: int, scheduler: str):
        """Public handle on a bucket executable: the jittable solve-step fn
        with signature (params, ids_cond, ids_uncond, guidance, seeds_lo,
        seeds_hi) -> uint8 images. Contract for external drivers."""
        return self._bucket_fn(batch, height, width, steps, scheduler)

    def generate(
        self,
        params: dict,
        prompts: list[str],
        negative_prompts: list[str],
        seeds: list[int],
        *,
        width: int = 512,
        height: int = 512,
        num_inference_steps: int = 20,
        guidance_scale: float | list[float] = 7.5,
        scheduler: str = "DDIM",
        as_device: bool = False,
    ) -> np.ndarray:
        """Run a shape bucket; returns uint8 images [B, H, W, 3].

        `as_device=True` returns the jax.Array WITHOUT forcing the
        device→host transfer: JAX dispatch is asynchronous, so the caller
        can queue the next bucket's dispatch and convert this result
        while the chip crunches it (the solver's codec/CID overlap —
        node/solver.py). Same bits either way."""
        batch = len(prompts)
        if len(negative_prompts) != batch or len(seeds) != batch:
            raise ValueError("prompts/negative_prompts/seeds must align")
        # latents must survive the UNet's downsample pyramid and re-align
        # with every skip connection on the way up
        levels = len(self.config.unet.block_channels)
        granule = self.VAE_FACTOR * (2 ** (levels - 1))
        if height % granule or width % granule:
            raise ValueError(f"height/width must be multiples of {granule}")
        g = list(guidance_scale) if isinstance(guidance_scale, (list, tuple)) \
            else [guidance_scale] * batch
        if len(g) != batch:
            raise ValueError("guidance_scale list must align with prompts")
        ids_c = self.tokenizer.encode_batch(prompts)
        ids_u = self.tokenizer.encode_batch(negative_prompts)
        vocab = self.config.text.vocab_size
        if int(ids_c.max()) >= vocab or int(ids_u.max()) >= vocab:
            raise ValueError(
                f"tokenizer produced id >= vocab_size ({vocab}); "
                "tokenizer and text-encoder config are mismatched")
        seeds_arr = np.asarray(seeds, dtype=np.uint64)
        args = self._place_batch(
            jnp.asarray(ids_c),
            jnp.asarray(ids_u),
            jnp.asarray(g, jnp.float32),
            jnp.asarray(seeds_arr & 0xFFFFFFFF, jnp.uint32),
            jnp.asarray(seeds_arr >> np.uint64(32), jnp.uint32),
        )
        # args are built BEFORE the bucket lookup so the AOT tier can
        # key (and compile) against the exact dispatch operands
        fn, warm, tag = self._get_bucket(
            batch, height, width, num_inference_steps, scheduler,
            aot_args=lambda: (params, *args))
        from arbius_tpu.obs import timed_dispatch

        with timed_dispatch(warm, tag):
            images = fn(params, *args)
        if self.mesh is not None:
            from arbius_tpu.parallel import meshsolve
            from arbius_tpu.quant import storage_dtype

            meshsolve.record_bucket_estimate(
                self._coll_est,
                (batch, height, width, num_inference_steps, scheduler),
                self.mesh, images, batch, params=params,
                wire_dtype=storage_dtype(self.precision)
                if self.precision != "bf16" else None, tag=tag)
        if as_device:
            return images
        return np.asarray(images)


# mesh layouts this family ships (docs/multichip.md): dp-only scales
# tasks bit-identically; dp×tp splits attention/MLP kernels via
# DEFAULT_TP_RULES and is its own determinism class. Each layout gets
# its own graphlint golden below — layout is data, like the rule table.
MESH_LAYOUTS: tuple[tuple[str, ...], ...] = (("dp",), ("dp", "tp"))


def trace_specs():
    """graphlint trace specs (models/trace_specs.py): the anythingv3
    bucket program at tiny topology, in both compute dtypes and under
    the two scheduler shapes (plain + ancestral-noise), all abstract —
    params via eval_shape, no weights, CPU-traceable in seconds. Each
    shipped mesh layout (MESH_LAYOUTS) traces over
    `parallel.abstract_mesh`, so the GSPMD sharding annotations land in
    the per-layout fingerprint with no physical devices involved."""
    import dataclasses

    from arbius_tpu.models.trace_specs import TraceSpec
    from arbius_tpu.parallel import meshsolve
    from arbius_tpu.schedulers import sampler_tag

    def build_bucket(dtype: str, steps: int, scheduler: str, axes=(),
                     precision: str = "bf16"):
        def build():
            from arbius_tpu.quant import abstract_quantized

            cfg = SD15Config.tiny()
            if dtype != "bfloat16":
                cfg = SD15Config(
                    unet=dataclasses.replace(cfg.unet, dtype=dtype),
                    vae=dataclasses.replace(cfg.vae, dtype=dtype),
                    text=dataclasses.replace(cfg.text, dtype=dtype))
            p = SD15Pipeline(cfg, mesh=meshsolve.golden_mesh(axes),
                             precision=precision)
            batch = 2 if axes else 1
            lh = 64 // p.VAE_FACTOR
            shapes = jax.eval_shape(p._init_fn(lh, lh),
                                    jax.random.PRNGKey(0))
            if precision != "bf16":
                # the quantized checkpoint tree: int8/fp8 kernels with
                # explicit f32 scales — what factory hands the runner
                shapes = abstract_quantized(shapes, precision)
            sds = jax.ShapeDtypeStruct
            length = cfg.text.max_length
            args = (shapes,
                    sds((batch, length), jnp.int32),
                    sds((batch, length), jnp.int32),
                    sds((batch,), jnp.float32),
                    sds((batch,), jnp.uint32), sds((batch,), jnp.uint32))
            return p.compiled_bucket(batch, 64, 64, steps, scheduler), args

        return build

    return [
        # quantized modes (docs/quantization.md): the anythingv3 bucket
        # with int8/fp8 checkpoint weights dequantized in-program — each
        # mode a pinned determinism class, keyed like a compute dtype
        TraceSpec(model="anythingv3", entry="txt2img",
                  bucket=f"b1.64x64.{sampler_tag('DDIM', 2)}",
                  mesh="single", dtype=mode,
                  build=build_bucket("bfloat16", 2, "DDIM",
                                     precision=mode))
        for mode in ("int8", "fp8")
    ] + [
        TraceSpec(model="anythingv3", entry="txt2img",
                  bucket=f"b1.64x64.{sampler_tag('DDIM', 2)}",
                  mesh="single", dtype=dtype,
                  build=build_bucket(dtype, 2, "DDIM"))
        for dtype in ("bfloat16", "float32")
    ] + [
        TraceSpec(model="anythingv3", entry="txt2img",
                  bucket=f"b1.64x64.{sampler_tag('K_EULER_ANCESTRAL', 2)}",
                  mesh="single", dtype="bfloat16",
                  build=build_bucket("bfloat16", 2, "K_EULER_ANCESTRAL")),
    ] + [
        TraceSpec(model="anythingv3", entry="txt2img",
                  bucket=f"b2.64x64.{sampler_tag('DDIM', 2)}",
                  mesh=meshsolve.golden_layout_tag(axes), dtype="bfloat16",
                  build=build_bucket("bfloat16", 2, "DDIM", axes))
        for axes in MESH_LAYOUTS
    ] + [
        # int8 × dp·tp: quantized kernels ride the tp rule table as
        # 1-byte shards — the layout (wire-byte win) the quantized
        # collective accounting meters (docs/quantization.md)
        TraceSpec(model="anythingv3", entry="txt2img",
                  bucket=f"b2.64x64.{sampler_tag('DDIM', 2)}",
                  mesh=meshsolve.golden_layout_tag(("dp", "tp")),
                  dtype="int8",
                  build=build_bucket("bfloat16", 2, "DDIM", ("dp", "tp"),
                                     "int8")),
    ]
