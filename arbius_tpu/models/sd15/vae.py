"""AutoencoderKL (the SD VAE) — latent <-> pixel codec.

txt2img only needs the decoder on the hot path; the encoder ships too for
img2img/file-input model classes (e.g. video matting preprocessing).
Latent scaling factor 0.18215 (SD-1.5 convention).
"""
from __future__ import annotations

from dataclasses import dataclass

import flax.linen as nn
import jax
import jax.numpy as jnp

from arbius_tpu.models.common import (
    Attention,
    Downsample,
    GroupNorm32,
    ResnetBlock,
    Upsample,
)

SD_LATENT_SCALE = 0.18215


@dataclass(frozen=True)
class VAEConfig:
    latent_channels: int = 4
    block_channels: tuple[int, ...] = (128, 256, 512, 512)
    layers_per_block: int = 2
    dtype: str = "bfloat16"

    @property
    def jdtype(self):
        return jnp.dtype(self.dtype)

    @classmethod
    def tiny(cls) -> "VAEConfig":
        return cls(block_channels=(8, 8, 8, 8), layers_per_block=1)


class _MidAttention(nn.Module):
    """Single-head full self-attention over the bottleneck spatial map."""
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x):
        b, h, w, c = x.shape
        residual = x
        x = GroupNorm32(epsilon=1e-6)(x)
        x = x.reshape(b, h * w, c)
        # qkv_bias=True: the published VAE checkpoints carry q/k/v biases
        x = Attention(num_heads=1, head_dim=c, dtype=self.dtype,
                      qkv_bias=True)(x)
        return residual + x.reshape(b, h, w, c)


class VAEDecoder(nn.Module):
    config: VAEConfig

    @nn.compact
    def __call__(self, z):
        cfg = self.config
        dt = cfg.jdtype
        z = z.astype(dt)
        z = nn.Conv(cfg.latent_channels, (1, 1), dtype=dt, name="post_quant")(z)
        h = nn.Conv(cfg.block_channels[-1], (3, 3), padding=1, dtype=dt,
                    name="conv_in")(z)
        h = ResnetBlock(cfg.block_channels[-1], dt, norm_eps=1e-6, name="mid_res_0")(h)
        h = _MidAttention(dt, name="mid_attn")(h)
        h = ResnetBlock(cfg.block_channels[-1], dt, norm_eps=1e-6, name="mid_res_1")(h)
        for level in reversed(range(len(cfg.block_channels))):
            ch = cfg.block_channels[level]
            for j in range(cfg.layers_per_block + 1):
                h = ResnetBlock(ch, dt, norm_eps=1e-6, name=f"up_{level}_res_{j}")(h)
            if level > 0:
                h = Upsample(ch, dt, name=f"up_{level}_us")(h)
        h = GroupNorm32(epsilon=1e-6, name="norm_out")(h)
        h = nn.silu(h)
        # final conv in fp32: pixel values feed the deterministic PNG path
        return nn.Conv(3, (3, 3), padding=1, dtype=jnp.float32,
                       name="conv_out")(h.astype(jnp.float32))


class VAEEncoder(nn.Module):
    config: VAEConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        dt = cfg.jdtype
        h = nn.Conv(cfg.block_channels[0], (3, 3), padding=1, dtype=dt,
                    name="conv_in")(x.astype(dt))
        for level, ch in enumerate(cfg.block_channels):
            for j in range(cfg.layers_per_block):
                h = ResnetBlock(ch, dt, norm_eps=1e-6, name=f"down_{level}_res_{j}")(h)
            if level < len(cfg.block_channels) - 1:
                h = Downsample(ch, dt, name=f"down_{level}_ds")(h)
        h = ResnetBlock(cfg.block_channels[-1], dt, norm_eps=1e-6, name="mid_res_0")(h)
        h = _MidAttention(dt, name="mid_attn")(h)
        h = ResnetBlock(cfg.block_channels[-1], dt, norm_eps=1e-6, name="mid_res_1")(h)
        h = GroupNorm32(epsilon=1e-6, name="norm_out")(h)
        h = nn.silu(h)
        # mean + logvar
        return nn.Conv(cfg.latent_channels * 2, (3, 3), padding=1,
                       dtype=jnp.float32, name="conv_out")(h.astype(jnp.float32))


def decode_to_images(pixels: jax.Array) -> jax.Array:
    """[-1,1] float decoder output -> uint8 RGB, deterministic rounding."""
    x = jnp.clip(pixels * 0.5 + 0.5, 0.0, 1.0)
    return jnp.round(x * 255.0).astype(jnp.uint8)
