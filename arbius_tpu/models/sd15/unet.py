"""Conditional UNet2D — the SD-1.5 denoiser (anythingv3's model class).

Reference capability target: the UNet the anythingv3 cog container runs
(templates/anythingv3.json declares SD-1.5 txt2img semantics). Architecture
follows the published SD-1.5 topology: 4-level encoder/decoder
(320/640/1280/1280 channels, 2 resnets per level), spatial transformers with
text cross-attention at the three highest resolutions, 1280-dim mid block.

Built TPU-first: NHWC, bf16 on the MXU, static shapes per (H, W) bucket —
the template's width/height enums form a small finite set, so every shape
bucket is a separate cached XLA executable.
"""
from __future__ import annotations

from dataclasses import dataclass

import flax.linen as nn
import jax.numpy as jnp

from arbius_tpu.models.common import (
    Downsample,
    GroupNorm32,
    ResnetBlock,
    SpatialTransformer,
    TimestepEmbedding,
    Upsample,
    sinusoidal_embedding,
)


@dataclass(frozen=True)
class UNetConfig:
    in_channels: int = 4
    out_channels: int = 4
    block_channels: tuple[int, ...] = (320, 640, 1280, 1280)
    layers_per_block: int = 2
    attention_levels: tuple[bool, ...] = (True, True, True, False)
    num_heads: int = 8
    head_dim: int | None = None   # set → heads vary per level (ch // head_dim)
    context_dim: int = 768
    transformer_depth: int = 1
    time_scale_shift: bool = False  # FiLM-style resnet conditioning
    dtype: str = "bfloat16"

    @property
    def jdtype(self):
        return jnp.dtype(self.dtype)

    def heads_for(self, ch: int) -> tuple[int, int]:
        """(num_heads, head_dim) at a channel width. SD-1.5 fixes the head
        COUNT; other published UNets (e.g. Kandinsky's decoder) fix the
        head DIM, so the count grows with width."""
        if self.head_dim is not None:
            return ch // self.head_dim, self.head_dim
        return self.num_heads, ch // self.num_heads

    @classmethod
    def tiny(cls) -> "UNetConfig":
        """Small config for tests: same topology, toy widths."""
        return cls(block_channels=(8, 8, 8, 8), layers_per_block=1,
                   num_heads=2, context_dim=16)


class UNet2DCondition(nn.Module):
    """epsilon-prediction UNet; __call__(latents[B,H,W,4], t[B], context[B,S,D])."""
    config: UNetConfig

    @nn.compact
    def __call__(self, x, t, context):
        cfg = self.config
        dt = cfg.jdtype
        x = x.astype(dt)
        context = context.astype(dt)

        temb = sinusoidal_embedding(t, cfg.block_channels[0])
        temb = TimestepEmbedding(cfg.block_channels[0] * 4, dt)(temb)

        h = nn.Conv(cfg.block_channels[0], (3, 3), padding=1, dtype=dt,
                    name="conv_in")(x)
        skips = [h]

        # encoder
        for level, ch in enumerate(cfg.block_channels):
            for j in range(cfg.layers_per_block):
                h = ResnetBlock(ch, dt, cfg.time_scale_shift,
                                name=f"down_{level}_res_{j}")(h, temb)
                if cfg.attention_levels[level]:
                    heads, hd = cfg.heads_for(ch)
                    h = SpatialTransformer(
                        heads, hd, cfg.transformer_depth,
                        dt, name=f"down_{level}_attn_{j}")(h, context)
                skips.append(h)
            if level < len(cfg.block_channels) - 1:
                h = Downsample(ch, dt, name=f"down_{level}_ds")(h)
                skips.append(h)

        # mid
        mid_ch = cfg.block_channels[-1]
        h = ResnetBlock(mid_ch, dt, cfg.time_scale_shift,
                        name="mid_res_0")(h, temb)
        mheads, mhd = cfg.heads_for(mid_ch)
        h = SpatialTransformer(mheads, mhd,
                               cfg.transformer_depth, dt, name="mid_attn")(h, context)
        h = ResnetBlock(mid_ch, dt, cfg.time_scale_shift,
                        name="mid_res_1")(h, temb)

        # decoder
        for level in reversed(range(len(cfg.block_channels))):
            ch = cfg.block_channels[level]
            for j in range(cfg.layers_per_block + 1):
                h = jnp.concatenate([h, skips.pop()], axis=-1)
                h = ResnetBlock(ch, dt, cfg.time_scale_shift,
                                name=f"up_{level}_res_{j}")(h, temb)
                if cfg.attention_levels[level]:
                    heads, hd = cfg.heads_for(ch)
                    h = SpatialTransformer(
                        heads, hd, cfg.transformer_depth,
                        dt, name=f"up_{level}_attn_{j}")(h, context)
            if level > 0:
                h = Upsample(ch, dt, name=f"up_{level}_us")(h)

        h = GroupNorm32(name="norm_out")(h)
        h = nn.silu(h)
        h = nn.Conv(self.config.out_channels, (3, 3), padding=1,
                    dtype=jnp.float32, name="conv_out")(h.astype(jnp.float32))
        return h
