"""CLIP-style causal text transformer (SD-1.5's conditioning encoder).

ViT-L/14 text tower topology: vocab 49408, 77 positions, width 768,
12 layers, 12 heads, quick-gelu MLP, causal mask, final LayerNorm.
"""
from __future__ import annotations

from dataclasses import dataclass

import flax.linen as nn
import jax.numpy as jnp
import numpy as np


@dataclass(frozen=True)
class TextEncoderConfig:
    vocab_size: int = 49408
    max_length: int = 77
    width: int = 768
    layers: int = 12
    heads: int = 12
    act: str = "quick_gelu"  # ViT-L towers; open_clip bigG towers use "gelu"
    dtype: str = "bfloat16"

    @property
    def jdtype(self):
        return jnp.dtype(self.dtype)

    @classmethod
    def tiny(cls) -> "TextEncoderConfig":
        return cls(vocab_size=512, max_length=16, width=16, layers=1, heads=2)


def quick_gelu(x):
    return x * nn.sigmoid(1.702 * x)


class _EncoderLayer(nn.Module):
    cfg: TextEncoderConfig

    @nn.compact
    def __call__(self, x, mask):
        dt = self.cfg.jdtype
        h = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32)(x).astype(dt)
        h = nn.SelfAttention(num_heads=self.cfg.heads, dtype=dt,
                             name="attn")(h, mask=mask)
        x = x + h
        h = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32)(x).astype(dt)
        h = nn.Dense(self.cfg.width * 4, dtype=dt)(h)
        # "gelu" towers (OpenCLIP ViT-H/bigG) use torch nn.GELU's EXACT
        # erf form; jax.nn.gelu defaults to the tanh approximation, which
        # would drift converted-weight activations across 24 layers
        h = (quick_gelu(h) if self.cfg.act == "quick_gelu"
             else nn.gelu(h, approximate=False))
        h = nn.Dense(self.cfg.width, dtype=dt)(h)
        return x + h


class TextEncoder(nn.Module):
    """__call__(token_ids[B, L]) -> last hidden state [B, L, width]."""
    config: TextEncoderConfig

    @nn.compact
    def __call__(self, ids):
        cfg = self.config
        dt = cfg.jdtype
        tok = nn.Embed(cfg.vocab_size, cfg.width, dtype=dt, name="token_embed")(ids)
        pos = self.param("pos_embed", nn.initializers.normal(0.01),
                         (cfg.max_length, cfg.width))
        x = tok + pos[None, : ids.shape[1]].astype(dt)
        causal = nn.make_causal_mask(ids)
        for i in range(cfg.layers):
            x = _EncoderLayer(cfg, name=f"layer_{i}")(x, causal)
        return nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32, name="final_norm")(x)
