"""Trace-spec registry — the model zoo's jittable entry points, enumerable.

graphlint (`arbius_tpu/analysis/graph`) audits COMPILED programs, not
Python source; for that it needs a durable answer to "what XLA programs
does this repo ship?". Each pipeline module answers with a
`trace_specs()` function returning `TraceSpec`s: a (model, entry,
shape-bucket, mesh layout, dtype) identity plus a `build()` thunk that
produces the jittable callable and abstract (ShapeDtypeStruct) example
arguments — everything `jax.make_jaxpr` needs, nothing concrete, so a
full-registry trace runs on a CPU-only host in seconds and never
allocates model weights (params come from `jax.eval_shape` over the
pipeline's own init).

Specs use the tiny test configs: the *topology* of the traced graph —
primitive mix, dtype discipline, reduction order, PRNG threading — is
what the GRAPH4xx rules and the golden fingerprints pin, and those
properties are identical between the tiny and full builds of the same
pipeline code. What tiny shapes cannot stand in for (weights, exact
bits) is covered by the recorded golden CIDs in `goldens/` instead.

The spec `key` doubles as the golden filename stem in `goldens/graph/`,
so it must stay filename-safe and stable across releases: renaming a
key IS a fingerprint-history reset for that program.
"""
from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Callable

_KEY_PART = re.compile(r"^[a-z0-9][a-z0-9_\-x.]*$")


@dataclass(frozen=True)
class TraceSpec:
    """One jittable entry point at one (bucket, mesh, dtype) identity.

    `build()` returns `(fn, args)` where `fn` is the jit-wrapped
    callable and `args` are abstract values (`jax.ShapeDtypeStruct`
    trees) — callers trace with `jax.make_jaxpr(fn)(*args)`.

    `allow` carries spec-level waivers with the same semantics as
    detlint's `# detlint: allow[RULE] reason` pragmas: each entry is
    `(rule_id, reason)`, the reason is mandatory, and waivers apply
    only to GRAPH4xx rule findings — fingerprint mismatches (GRAPH49x)
    can never be waived.
    """

    model: str   # template name, e.g. "anythingv3"
    entry: str   # entry point, e.g. "txt2img"
    bucket: str  # shape bucket tag, e.g. "b1.64x64.ddim.s2"
    mesh: str    # mesh layout tag: "single" or e.g. "dp2.sp2.tp2"
    dtype: str   # compute dtype of the spec, e.g. "bfloat16"
    build: Callable[[], tuple]
    allow: tuple = field(default=())

    @property
    def key(self) -> str:
        return f"{self.model}.{self.entry}.{self.bucket}.{self.mesh}.{self.dtype}"

    def waiver(self, rule_id: str) -> str | None:
        """Reason string if `rule_id` is waived for this spec, else None
        (a reasonless waiver waives nothing, like a reasonless pragma)."""
        for rid, reason in self.allow:
            if rid == rule_id and reason:
                return reason
        return None


def validate_specs(specs: list[TraceSpec]) -> list[TraceSpec]:
    """Shared registry hygiene: unique filename-safe keys, justified
    waivers. Returns the specs sorted by key (stable audit order)."""
    seen: dict[str, TraceSpec] = {}
    for s in specs:
        for part in (s.model, s.entry, s.bucket, s.mesh, s.dtype):
            if not _KEY_PART.match(part):
                raise ValueError(
                    f"trace spec {s.key!r}: part {part!r} is not "
                    "filename-safe ([a-z0-9_.x-])")
        if s.key in seen:
            raise ValueError(f"duplicate trace spec key {s.key!r}")
        for entry in s.allow:
            if len(entry) != 2 or not entry[1].strip():
                raise ValueError(
                    f"trace spec {s.key!r}: waiver {entry!r} needs "
                    "(rule_id, reason) with a non-empty reason")
        seen[s.key] = s
    return [seen[k] for k in sorted(seen)]


def all_trace_specs() -> list[TraceSpec]:
    """Every registered pipeline's trace specs, validated and sorted.

    Imports are deferred so that enumerating the registry is the only
    time the model zoo is pulled in — the analysis CLI stays importable
    without jax/flax side effects until it actually audits.
    """
    from arbius_tpu.models.kandinsky2 import pipeline as kandinsky2_pipeline
    from arbius_tpu.models.rvm import pipeline as rvm_pipeline
    from arbius_tpu.models.sd15 import pipeline as sd15_pipeline
    from arbius_tpu.models.textgen import pipeline as textgen_pipeline
    from arbius_tpu.models.video import pipeline as video_pipeline
    from arbius_tpu.parallel import meshsolve

    specs: list[TraceSpec] = []
    for mod in (sd15_pipeline, kandinsky2_pipeline, rvm_pipeline,
                video_pipeline, textgen_pipeline, meshsolve):
        specs.extend(mod.trace_specs())
    return validate_specs(specs)
