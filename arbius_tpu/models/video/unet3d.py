"""UNet3D — text-to-video denoiser (zeroscope / ModelScope model class).

Capability target: `templates/zeroscopev2xl.json` (≤96 frames, 1024×576)
and `templates/damo.json` (ModelScope 1.7B, 16 frames) — SURVEY.md §2.3.

Architecture: the standard factorized inflation of the 2D UNet — every
level interleaves (a) spatial resnet + spatial/text transformer applied
per-frame, with (b) temporal convolution and (c) temporal attention
applied per-pixel across frames. Temporal residual branches are
zero-initialized, so at init the model is exactly the 2D UNet replicated
over frames (the standard inflation trick, and a free correctness check).

Sequence parallelism is built in, not bolted on (SURVEY.md §2.6 plan):
with `sp_axis` set, the module runs under shard_map with the frame axis
sharded — temporal convs fetch a 1-frame halo from ring neighbours
(`halo_exchange`), temporal attention runs as ring attention
(`ops.ring_attention`), everything else is frame-local. Comms per step:
O(halo) + (sp-1) K/V hops, all ICI.

Shapes: __call__(x[B, T, H, W, C], t[B], context[B, L, D]) — T is the
per-shard frame count under shard_map, the full count otherwise.
"""
from __future__ import annotations

from dataclasses import dataclass

import flax.linen as nn
import jax.numpy as jnp

from arbius_tpu.models.common import (
    Downsample,
    GroupNorm32,
    ResnetBlock,
    SpatialTransformer,
    TimestepEmbedding,
    Upsample,
    sinusoidal_embedding,
)
from arbius_tpu.ops.ring import ring_attention, sp_attention_reference
from arbius_tpu.parallel import halo_exchange


@dataclass(frozen=True)
class UNet3DConfig:
    in_channels: int = 4
    out_channels: int = 4
    block_channels: tuple[int, ...] = (320, 640, 1280, 1280)
    layers_per_block: int = 2
    attention_levels: tuple[bool, ...] = (True, True, True, False)
    num_heads: int = 8
    context_dim: int = 1024
    transformer_depth: int = 1
    temporal_kernel: int = 3
    sp_axis: str | None = None    # mesh axis frames are sharded over
    dtype: str = "bfloat16"

    @property
    def jdtype(self):
        return jnp.dtype(self.dtype)

    @classmethod
    def tiny(cls, sp_axis: str | None = None) -> "UNet3DConfig":
        return cls(block_channels=(8, 8, 8, 8), layers_per_block=1,
                   num_heads=2, context_dim=16, sp_axis=sp_axis)


class TemporalConv(nn.Module):
    """Residual temporal conv; zero-init out ⇒ identity at init.

    Under sp, the kernel's (k-1)/2-frame halo comes from ring neighbours;
    edge shards see zeros — identical to the unsharded 'SAME' padding.
    """
    channels: int
    kernel: int = 3
    sp_axis: str | None = None
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):  # [B, T, H, W, C]
        h = GroupNorm32(name="norm")(x)
        h = nn.silu(h).astype(self.dtype)
        halo = (self.kernel - 1) // 2
        # operate with T adjacent to channels: [B, H, W, T, C]
        h = h.transpose(0, 2, 3, 1, 4)
        if self.sp_axis is not None:
            h = halo_exchange(h, self.sp_axis, axis=3, halo=halo)
            pad = "VALID"
        else:
            pad = [(halo, halo)]
        h = nn.Conv(self.channels, (self.kernel,), padding=pad,
                    dtype=self.dtype, name="conv")(h)
        h = nn.Conv(self.channels, (1,), dtype=self.dtype,
                    kernel_init=nn.initializers.zeros,
                    name="proj_out")(h)
        return x + h.transpose(0, 3, 1, 2, 4)


class TemporalAttention(nn.Module):
    """Per-pixel attention across frames; zero-init out ⇒ identity at init.

    With sp_axis: exact ring attention over the sharded frame axis.
    """
    channels: int
    num_heads: int
    sp_axis: str | None = None
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):  # [B, T, H, W, C]
        b, t, hh, ww, c = x.shape
        head_dim = c // self.num_heads
        h = GroupNorm32(name="norm")(x).astype(self.dtype)
        # tokens: frames; batch: every spatial site → [B*H*W, heads, T, D]
        h = h.transpose(0, 2, 3, 1, 4).reshape(b * hh * ww, t, c)
        qkv = nn.Dense(3 * c, use_bias=False, dtype=self.dtype,
                       name="to_qkv")(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(a):
            return a.reshape(a.shape[0], t, self.num_heads,
                             head_dim).transpose(0, 2, 1, 3)

        q, k, v = heads(q), heads(k), heads(v)
        if self.sp_axis is not None:
            out = ring_attention(q, k, v, axis_name=self.sp_axis)
        else:
            out = sp_attention_reference(q, k, v)
        out = out.transpose(0, 2, 1, 3).reshape(b * hh * ww, t, c)
        out = nn.Dense(c, dtype=self.dtype,
                       kernel_init=nn.initializers.zeros,
                       name="to_out")(out)
        out = out.reshape(b, hh, ww, t, c).transpose(0, 3, 1, 2, 4)
        return x + out


class UNet3DCondition(nn.Module):
    """eps-prediction video UNet; see module docstring for sharding."""
    config: UNet3DConfig

    def _spatial(self, fn, x):
        """Run a 2D module over [B*T, H, W, C]."""
        b, t = x.shape[0], x.shape[1]
        y = fn(x.reshape(b * t, *x.shape[2:]))
        return y.reshape(b, t, *y.shape[1:])

    @nn.compact
    def __call__(self, x, t_cond, context):
        cfg = self.config
        dt = cfg.jdtype
        x = x.astype(dt)
        b, nframes = x.shape[0], x.shape[1]
        context = context.astype(dt)
        # every frame of a sample shares its text context and timestep
        ctx_rep = jnp.repeat(context, nframes, axis=0)        # [B*T, L, D]
        temb = sinusoidal_embedding(t_cond, cfg.block_channels[0])
        temb = TimestepEmbedding(cfg.block_channels[0] * 4, dt)(temb)
        temb_rep = jnp.repeat(temb, nframes, axis=0)          # [B*T, E]

        def res(ch, name):
            return lambda h2d: ResnetBlock(ch, dt, name=name)(
                h2d, temb_rep[:h2d.shape[0]])

        def attn(ch, name):
            return lambda h2d: SpatialTransformer(
                cfg.num_heads, ch // cfg.num_heads, cfg.transformer_depth,
                dt, name=name)(h2d, ctx_rep[:h2d.shape[0]])

        h = self._spatial(
            lambda z: nn.Conv(cfg.block_channels[0], (3, 3), padding=1,
                              dtype=dt, name="conv_in")(z), x)
        skips = [h]
        for level, ch in enumerate(cfg.block_channels):
            for j in range(cfg.layers_per_block):
                h = self._spatial(res(ch, f"down_{level}_res_{j}"), h)
                h = TemporalConv(ch, cfg.temporal_kernel, cfg.sp_axis, dt,
                                 name=f"down_{level}_tconv_{j}")(h)
                if cfg.attention_levels[level]:
                    h = self._spatial(attn(ch, f"down_{level}_attn_{j}"), h)
                    h = TemporalAttention(ch, cfg.num_heads, cfg.sp_axis, dt,
                                          name=f"down_{level}_tattn_{j}")(h)
                skips.append(h)
            if level < len(cfg.block_channels) - 1:
                h = self._spatial(
                    lambda z, ch=ch, level=level: Downsample(
                        ch, dt, name=f"down_{level}_ds")(z), h)
                skips.append(h)

        mid_ch = cfg.block_channels[-1]
        h = self._spatial(res(mid_ch, "mid_res_0"), h)
        h = TemporalConv(mid_ch, cfg.temporal_kernel, cfg.sp_axis, dt,
                         name="mid_tconv")(h)
        h = self._spatial(attn(mid_ch, "mid_attn"), h)
        h = TemporalAttention(mid_ch, cfg.num_heads, cfg.sp_axis, dt,
                              name="mid_tattn")(h)
        h = self._spatial(res(mid_ch, "mid_res_1"), h)

        for level in reversed(range(len(cfg.block_channels))):
            ch = cfg.block_channels[level]
            for j in range(cfg.layers_per_block + 1):
                h = jnp.concatenate([h, skips.pop()], axis=-1)
                h = self._spatial(res(ch, f"up_{level}_res_{j}"), h)
                h = TemporalConv(ch, cfg.temporal_kernel, cfg.sp_axis, dt,
                                 name=f"up_{level}_tconv_{j}")(h)
                if cfg.attention_levels[level]:
                    h = self._spatial(attn(ch, f"up_{level}_attn_{j}"), h)
                    h = TemporalAttention(ch, cfg.num_heads, cfg.sp_axis, dt,
                                          name=f"up_{level}_tattn_{j}")(h)
            if level > 0:
                h = self._spatial(
                    lambda z, ch=ch, level=level: Upsample(
                        ch, dt, name=f"up_{level}_us")(z), h)

        h = self._spatial(lambda z: nn.Conv(
            cfg.out_channels, (3, 3), padding=1, dtype=jnp.float32,
            name="conv_out")(nn.silu(GroupNorm32(name="norm_out")(z))
                             .astype(jnp.float32)), h)
        return h
