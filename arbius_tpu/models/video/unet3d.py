"""UNet3D — text-to-video denoiser (zeroscope / ModelScope model class).

Capability target: `templates/zeroscopev2xl.json` (≤96 frames, 1024×576)
and `templates/damo.json` (ModelScope 1.7B, 16 frames) — SURVEY.md §2.3.
Both published checkpoints are the diffusers `UNet3DConditionModel`
layout (zeroscope v2 is a fine-tune of the ModelScope topology), and this
module implements that exact structure so the published weights convert
1:1 (`models/video/convert.py`):

  conv_in → transformer_in (temporal, 8 heads) → CrossAttnDownBlock3D ×3
  + DownBlock3D → UNetMidBlock3DCrossAttn → mirrored up blocks →
  conv_norm_out/conv_out. Every block layer runs resnet → TemporalConvLayer
  (4 GN+SiLU+frame-conv stages, last zero-init) → Transformer2DModel
  (spatial, per-frame) → TransformerTemporalModel (per-pixel over frames,
  double self-attention + GEGLU FF).

Sequence parallelism is built in, not bolted on (SURVEY.md §2.6 plan):
with `sp_axis` set, the module runs under shard_map with the frame axis
sharded — temporal convs fetch a 1-frame halo from ring neighbours
(`halo_exchange`) per conv stage, temporal attention runs as ring
attention (`ops.ring_attention`), everything else is frame-local. Comms
per step: O(halo) + (sp-1) K/V hops, all ICI.

At init the model is exactly the 2D UNet replicated over frames: the
published TemporalConvLayer zero-inits its last conv, and the temporal
transformers here zero-init proj_out (free correctness check; converted
checkpoints overwrite it either way).

Shapes: __call__(x[B, T, H, W, C], t[B], context[B, L, D]) — T is the
per-shard frame count under shard_map, the full count otherwise.
"""
from __future__ import annotations

from dataclasses import dataclass

import flax.linen as nn
import jax.numpy as jnp

from arbius_tpu.models.common import (
    GEGLU,
    Downsample,
    GroupNorm32,
    ResnetBlock,
    SpatialTransformer,
    TimestepEmbedding,
    Upsample,
    sinusoidal_embedding,
)
from arbius_tpu.ops.ring import ring_attention, sp_attention_reference
from arbius_tpu.ops.ulysses import ulysses_attention
from arbius_tpu.parallel import halo_exchange

SP_STRATEGIES = ("ring", "ulysses")


@dataclass(frozen=True)
class UNet3DConfig:
    in_channels: int = 4
    out_channels: int = 4
    block_channels: tuple[int, ...] = (320, 640, 1280, 1280)
    layers_per_block: int = 2
    attention_levels: tuple[bool, ...] = (True, True, True, False)
    head_dim: int = 64            # spatial+temporal heads = ch // head_dim
    tin_heads: int = 8            # transformer_in head count (published: 8)
    context_dim: int = 1024
    transformer_depth: int = 1
    sp_axis: str | None = None    # mesh axis frames are sharded over
    # how sharded temporal attention communicates (SURVEY §2.6 long-
    # context growth path): "ring" rotates K/V shards (never materializes
    # full-T K/V; bandwidth overlapped with compute), "ulysses" re-shards
    # frames→heads with two all_to_alls and attends over full T locally
    # (needs heads % sp == 0 at every level — head counts here are
    # ch // head_dim, so sp must divide min(block_channels)//head_dim and
    # tin_heads). Both are exact; see ops/ring.py vs ops/ulysses.py.
    sp_strategy: str = "ring"
    dtype: str = "bfloat16"

    def __post_init__(self):
        if self.sp_strategy not in SP_STRATEGIES:
            raise ValueError(
                f"sp_strategy {self.sp_strategy!r} not in {SP_STRATEGIES}")

    @property
    def jdtype(self):
        return jnp.dtype(self.dtype)

    @classmethod
    def tiny(cls, sp_axis: str | None = None,
             sp_strategy: str = "ring") -> "UNet3DConfig":
        return cls(block_channels=(8, 8, 8, 8), layers_per_block=1,
                   head_dim=4, tin_heads=2, context_dim=16, sp_axis=sp_axis,
                   sp_strategy=sp_strategy)


class TemporalConvLayer(nn.Module):
    """Published diffusers TemporalConvLayer: four GN+SiLU+(3,1,1)-conv
    stages with a zero-init final conv, residual. A (3,1,1) Conv3d is a
    1-frame-halo conv along the frame axis, so under sp each stage halo-
    exchanges one frame from its ring neighbours; edge shards see zeros —
    identical to the unsharded 'SAME' padding."""
    channels: int
    sp_axis: str | None = None
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):  # [B, T, H, W, C]
        h = x
        for name in ("conv1", "conv2", "conv3", "conv4"):
            h = GroupNorm32(name=f"{name}_norm")(h)
            h = nn.silu(h).astype(self.dtype)
            # frame-axis conv: operate with T adjacent to channels
            h = h.transpose(0, 2, 3, 1, 4)          # [B, H, W, T, C]
            if self.sp_axis is not None:
                h = halo_exchange(h, self.sp_axis, axis=3, halo=1)
                pad = "VALID"
            else:
                pad = [(1, 1)]
            h = nn.Conv(self.channels, (3,), padding=pad, dtype=self.dtype,
                        kernel_init=(nn.initializers.zeros
                                     if name == "conv4"
                                     else nn.initializers.lecun_normal()),
                        name=name)(h)
            h = h.transpose(0, 3, 1, 2, 4)
        return x + h


class TemporalSelfAttention(nn.Module):
    """Self-attention over the frame axis ([N, T, C] tokens = frames).

    With sp_axis: exact sharded attention over the frame axis, by the
    config's strategy — ring (online-softmax K/V passes, ops/ring.py) or
    ulysses (all_to_all frames→heads re-shard, ops/ulysses.py)."""
    num_heads: int
    head_dim: int
    sp_axis: str | None = None
    dtype: jnp.dtype = jnp.bfloat16
    sp_strategy: str = "ring"

    @nn.compact
    def __call__(self, x):
        n, t, c = x.shape
        inner = self.num_heads * self.head_dim
        q = nn.Dense(inner, use_bias=False, dtype=self.dtype, name="to_q")(x)
        k = nn.Dense(inner, use_bias=False, dtype=self.dtype, name="to_k")(x)
        v = nn.Dense(inner, use_bias=False, dtype=self.dtype, name="to_v")(x)

        def heads(a):
            return a.reshape(n, t, self.num_heads,
                             self.head_dim).transpose(0, 2, 1, 3)

        q, k, v = heads(q), heads(k), heads(v)
        if self.sp_axis is not None and self.sp_strategy == "ulysses":
            out = ulysses_attention(q, k, v, axis_name=self.sp_axis)
        elif self.sp_axis is not None:
            out = ring_attention(q, k, v, axis_name=self.sp_axis)
        else:
            out = sp_attention_reference(q, k, v)
        out = out.transpose(0, 2, 1, 3).reshape(n, t, inner)
        return nn.Dense(c, dtype=self.dtype, name="to_out")(out)


class TemporalTransformerBlock(nn.Module):
    """Published BasicTransformerBlock under double_self_attention=True
    (the TransformerTemporalModel configuration): LN→self-attn,
    LN→second self-attn, LN→GEGLU FF, all residual."""
    num_heads: int
    head_dim: int
    sp_axis: str | None = None
    dtype: jnp.dtype = jnp.bfloat16
    sp_strategy: str = "ring"

    @nn.compact
    def __call__(self, x):
        f32 = jnp.float32
        x = x + TemporalSelfAttention(
            self.num_heads, self.head_dim, self.sp_axis, self.dtype,
            sp_strategy=self.sp_strategy,
            name="attn1")(nn.LayerNorm(epsilon=1e-5, dtype=f32, name="norm1")(x)
                          .astype(self.dtype))
        x = x + TemporalSelfAttention(
            self.num_heads, self.head_dim, self.sp_axis, self.dtype,
            sp_strategy=self.sp_strategy,
            name="attn2")(nn.LayerNorm(epsilon=1e-5, dtype=f32, name="norm2")(x)
                          .astype(self.dtype))
        h = nn.LayerNorm(epsilon=1e-5, dtype=f32, name="norm3")(x).astype(self.dtype)
        h = GEGLU(x.shape[-1] * 4, self.dtype, name="ff")(h)
        h = nn.Dense(x.shape[-1], dtype=self.dtype, name="ff_out")(h)
        return x + h


class TemporalTransformer(nn.Module):
    """Published TransformerTemporalModel: GroupNorm, linear proj_in,
    transformer blocks over the frame axis per spatial site, linear
    proj_out, residual. `inner = heads·head_dim` may differ from the
    channel count (transformer_in: 8×64=512 over 320 channels)."""
    num_heads: int
    head_dim: int
    depth: int = 1
    sp_axis: str | None = None
    dtype: jnp.dtype = jnp.bfloat16
    sp_strategy: str = "ring"

    @nn.compact
    def __call__(self, x):  # [B, T, H, W, C]
        b, t, hh, ww, c = x.shape
        # TransformerTemporalModel pins this GroupNorm to eps=1e-6
        h = GroupNorm32(epsilon=1e-6, name="norm")(x).astype(self.dtype)
        # tokens: frames; batch: every spatial site → [B*H*W, T, C]
        h = h.transpose(0, 2, 3, 1, 4).reshape(b * hh * ww, t, c)
        h = nn.Dense(self.num_heads * self.head_dim, dtype=self.dtype,
                     name="proj_in")(h)
        for i in range(self.depth):
            h = TemporalTransformerBlock(
                self.num_heads, self.head_dim, self.sp_axis, self.dtype,
                sp_strategy=self.sp_strategy,
                name=f"block_{i}")(h)
        # zero-init: temporal branch is identity at init (inflation check)
        h = nn.Dense(c, dtype=self.dtype, kernel_init=nn.initializers.zeros,
                     name="proj_out")(h)
        h = h.reshape(b, hh, ww, t, c).transpose(0, 3, 1, 2, 4)
        return x + h


class UNet3DCondition(nn.Module):
    """eps-prediction video UNet; see module docstring for sharding."""
    config: UNet3DConfig

    def _spatial(self, fn, x):
        """Run a 2D module over [B*T, H, W, C]."""
        b, t = x.shape[0], x.shape[1]
        y = fn(x.reshape(b * t, *x.shape[2:]))
        return y.reshape(b, t, *y.shape[1:])

    @nn.compact
    def __call__(self, x, t_cond, context):
        cfg = self.config
        dt = cfg.jdtype
        x = x.astype(dt)
        b, nframes = x.shape[0], x.shape[1]
        context = context.astype(dt)
        # every frame of a sample shares its text context and timestep
        ctx_rep = jnp.repeat(context, nframes, axis=0)        # [B*T, L, D]
        temb = sinusoidal_embedding(t_cond, cfg.block_channels[0])
        temb = TimestepEmbedding(cfg.block_channels[0] * 4, dt)(temb)
        temb_rep = jnp.repeat(temb, nframes, axis=0)          # [B*T, E]

        def res(ch, name):
            return lambda h2d: ResnetBlock(ch, dt, name=name)(
                h2d, temb_rep[:h2d.shape[0]])

        def attn(ch, name):
            return lambda h2d: SpatialTransformer(
                ch // cfg.head_dim, cfg.head_dim, cfg.transformer_depth,
                dt, name=name)(h2d, ctx_rep[:h2d.shape[0]])

        def tconv(ch, name):
            return TemporalConvLayer(ch, cfg.sp_axis, dt, name=name)

        def tattn(ch, name):
            return TemporalTransformer(ch // cfg.head_dim, cfg.head_dim,
                                       cfg.transformer_depth, cfg.sp_axis,
                                       dt, sp_strategy=cfg.sp_strategy,
                                       name=name)

        h = self._spatial(
            lambda z: nn.Conv(cfg.block_channels[0], (3, 3), padding=1,
                              dtype=dt, name="conv_in")(z), x)
        # published: temporal transformer on the stem, fixed head count
        h = TemporalTransformer(cfg.tin_heads, cfg.head_dim,
                                cfg.transformer_depth, cfg.sp_axis, dt,
                                sp_strategy=cfg.sp_strategy,
                                name="transformer_in")(h)
        skips = [h]
        for level, ch in enumerate(cfg.block_channels):
            for j in range(cfg.layers_per_block):
                h = self._spatial(res(ch, f"down_{level}_res_{j}"), h)
                h = tconv(ch, f"down_{level}_tconv_{j}")(h)
                if cfg.attention_levels[level]:
                    h = self._spatial(attn(ch, f"down_{level}_attn_{j}"), h)
                    h = tattn(ch, f"down_{level}_tattn_{j}")(h)
                skips.append(h)
            if level < len(cfg.block_channels) - 1:
                h = self._spatial(
                    lambda z, ch=ch, level=level: Downsample(
                        ch, dt, name=f"down_{level}_ds")(z), h)
                skips.append(h)

        # published mid block: res0 → tconv0 → attn → tattn → res1 → tconv1
        mid_ch = cfg.block_channels[-1]
        h = self._spatial(res(mid_ch, "mid_res_0"), h)
        h = tconv(mid_ch, "mid_tconv_0")(h)
        h = self._spatial(attn(mid_ch, "mid_attn"), h)
        h = tattn(mid_ch, "mid_tattn")(h)
        h = self._spatial(res(mid_ch, "mid_res_1"), h)
        h = tconv(mid_ch, "mid_tconv_1")(h)

        for level in reversed(range(len(cfg.block_channels))):
            ch = cfg.block_channels[level]
            for j in range(cfg.layers_per_block + 1):
                h = jnp.concatenate([h, skips.pop()], axis=-1)
                h = self._spatial(res(ch, f"up_{level}_res_{j}"), h)
                h = tconv(ch, f"up_{level}_tconv_{j}")(h)
                if cfg.attention_levels[level]:
                    h = self._spatial(attn(ch, f"up_{level}_attn_{j}"), h)
                    h = tattn(ch, f"up_{level}_tattn_{j}")(h)
            if level > 0:
                h = self._spatial(
                    lambda z, ch=ch, level=level: Upsample(
                        ch, dt, name=f"up_{level}_us")(z), h)

        h = self._spatial(lambda z: nn.Conv(
            cfg.out_channels, (3, 3), padding=1, dtype=jnp.float32,
            name="conv_out")(nn.silu(GroupNorm32(name="norm_out")(z))
                             .astype(jnp.float32)), h)
        return h
