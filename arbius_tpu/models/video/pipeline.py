"""Text-to-video pipeline (zeroscope / damo template classes).

End-to-end jitted program per shape bucket: text encode → CFG UNet3D
denoise scan → per-frame VAE decode → uint8 frames. The node's video
runner encodes the frames to deterministic H.264 MP4 (codecs.encode_mp4_h264)
and CIDs the bytes — replacing the reference's cog container + ffmpeg
black box (`templates/zeroscopev2xl.json` out-1.mp4).

Parallel layout (mesh axes): dp shards samples, sp shards FRAMES — the
whole denoise scan runs under one shard_map, temporal ops communicating
via halo exchange + ring attention (see unet3d.py). Noise is derived per
(sample-key, step, GLOBAL frame index), so the sp layout does not change
which noise a frame sees — resharding changes only reduction order, not
the random stream.

Determinism contract: same as SD-1.5/Kandinsky — (model build, input,
seed, bucket, mesh layout) fixes output bytes; buckets are padded to a
canonical batch by the node.
"""
from __future__ import annotations

import dataclasses
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from arbius_tpu.models.sd15.text_encoder import TextEncoder, TextEncoderConfig
from arbius_tpu.models.sd15.tokenizer import ByteTokenizer
from arbius_tpu.models.sd15.vae import (
    SD_LATENT_SCALE,
    VAEConfig,
    VAEDecoder,
    decode_to_images,
)
from arbius_tpu.models.video.unet3d import UNet3DCondition, UNet3DConfig
from arbius_tpu.schedulers import get_sampler


@dataclass(frozen=True)
class Text2VideoConfig:
    unet: UNet3DConfig = UNet3DConfig()
    vae: VAEConfig = VAEConfig()
    # published ModelScope/zeroscope text tower: OpenCLIP ViT-H-class —
    # hidden 1024, 16 heads, 24 layers, plain gelu
    text: TextEncoderConfig = TextEncoderConfig(width=1024, heads=16,
                                                layers=24, act="gelu")

    @classmethod
    def tiny(cls, sp_axis: str | None = None,
             sp_strategy: str = "ring") -> "Text2VideoConfig":
        return cls(unet=UNet3DConfig.tiny(sp_axis=sp_axis,
                                          sp_strategy=sp_strategy),
                   vae=VAEConfig.tiny(),
                   text=TextEncoderConfig.tiny())


class Text2VideoPipeline:
    VAE_FACTOR = 8

    def __init__(self, config: Text2VideoConfig | None = None, tokenizer=None,
                 mesh=None, precision: str = "bf16"):
        from arbius_tpu.quant import validate_mode

        self.config = config or Text2VideoConfig()
        self.mesh = mesh
        # precision mode (docs/quantization.md): "bf16" is the historic
        # program byte-for-byte; int8/fp8 take the factory-quantized
        # UNet3D/temporal-conv weight tree (the ROADMAP's quantized
        # hot loop) and dequantize in-program — own golden per mode
        self.precision = validate_mode(precision)
        if self.config.text.width != self.config.unet.context_dim:
            raise ValueError(
                f"text width ({self.config.text.width}) must equal unet "
                f"context_dim ({self.config.unet.context_dim})")
        sp = mesh.shape.get("sp", 1) if mesh is not None else 1
        if sp > 1 and self.config.unet.sp_axis != "sp":
            raise ValueError(
                "mesh has sp>1 but unet.sp_axis is not 'sp' — the model "
                "must be built sharding-aware (UNet3DConfig(sp_axis='sp'))")
        if sp == 1 and self.config.unet.sp_axis is not None and mesh is None:
            raise ValueError("unet.sp_axis set but no mesh given")
        self.tokenizer = tokenizer or ByteTokenizer(
            max_length=self.config.text.max_length)
        self.text_encoder = TextEncoder(self.config.text)
        self.unet = UNet3DCondition(self.config.unet)
        self.vae = VAEDecoder(self.config.vae)
        self._buckets: dict[tuple, object] = {}
        self._coll_est: dict[tuple, dict] = {}  # per-bucket traffic estimate

    # -- params ----------------------------------------------------------
    def init_params(self, seed: int = 0, frames: int = 2, height: int = 64,
                    width: int = 64, dtype=None) -> dict:
        """Init with sp_axis disabled (collectives need a mesh); the param
        tree is identical either way, so these params drive both paths.

        One jitted program (eager flax init is a per-op round-trip over a
        remote-TPU tunnel); `dtype` folds the weights cast in so the f32
        tree is never fully resident (see SD15Pipeline.init_params)."""
        cfg = self.config
        lh, lw = height // self.VAE_FACTOR, width // self.VAE_FACTOR
        unet_local = UNet3DCondition(
            dataclasses.replace(cfg.unet, sp_axis=None))

        def _init(key):
            k1, k2, k3 = jax.random.split(key, 3)
            lat = jnp.zeros((1, frames, lh, lw, cfg.unet.in_channels))
            ids = jnp.zeros((1, cfg.text.max_length), jnp.int32)
            ctx = jnp.zeros((1, cfg.text.max_length, cfg.unet.context_dim))
            return {
                "unet": unet_local.init(k1, lat, jnp.zeros((1,)), ctx)["params"],
                "vae": self.vae.init(k2, lat[:, 0])["params"],
                "text": self.text_encoder.init(k3, ids)["params"],
            }

        from arbius_tpu.utils import with_cast

        return jax.jit(with_cast(_init, dtype))(jax.random.PRNGKey(seed))

    def place_params(self, params: dict, tp_rules=()) -> dict:
        """Video path shards dp×sp via shard_map with replicated params
        (in_spec P()); TP param sharding is not wired into this pipeline,
        so the default is full replication — pass rules only if you also
        change the shard_map in_specs."""
        if self.mesh is None:
            return params
        from arbius_tpu.parallel import shard_params

        return shard_params(params, self.mesh, list(tp_rules))

    # -- compiled bucket -------------------------------------------------
    def compiled_bucket(self, batch: int, frames: int, height: int,
                        width: int, steps: int, scheduler: str):
        return self._get_bucket(batch, frames, height, width, steps,
                                scheduler)[0]

    def bucket_tag(self, batch: int, frames: int, height: int, width: int,
                   steps: int, scheduler: str) -> str:
        """One definition of this family's executable-cache tag — the
        warm sets and the AOT disk-warm scan join on it
        (docs/compile-cache.md). Non-default precision modes suffix it
        (".int8"/".fp8") — a quantized bucket never shares a warm
        signal with its bf16 twin; bf16 tags stay byte-identical."""
        from arbius_tpu.quant import mode_tag

        return "video." + ".".join(
            str(k) for k in (batch, frames, height, width, steps,
                             scheduler)) + mode_tag(self.precision)

    def _get_bucket(self, batch: int, frames: int, height: int,
                    width: int, steps: int, scheduler: str,
                    aot_args=None):
        """(fn, warm, tag) — cache lookup reported through the
        jit-cache metrics (docs/observability.md); `aot_args` opts into
        the AOT disk tier (docs/compile-cache.md)."""
        from arbius_tpu.obs import jit_cache_get

        key = (batch, frames, height, width, steps, scheduler)
        return jit_cache_get(
            self._buckets, key,
            lambda: self._build_bucket(batch, frames, height, width,
                                       steps, scheduler),
            tag=self.bucket_tag(*key), aot_args=aot_args)

    def _build_bucket(self, batch: int, frames: int, height: int,
                      width: int, steps: int, scheduler: str):
        cfg = self.config
        sampler = get_sampler(scheduler, steps)
        lh, lw = height // self.VAE_FACTOR, width // self.VAE_FACTOR
        sp = self.mesh.shape.get("sp", 1) if self.mesh is not None else 1
        dp = self.mesh.shape.get("dp", 1) if self.mesh is not None else 1
        if frames % sp:
            raise ValueError(f"frames {frames} not divisible by sp={sp}")
        if batch % dp:
            raise ValueError(f"batch {batch} not divisible by dp={dp}")
        t_local = frames // sp
        precision = self.precision

        def run(params, ids_c, ids_u, guidance, seeds_lo, seeds_hi):
            if precision != "bf16":
                from arbius_tpu.quant import dequantize_tree

                # int8/fp8 kernels → f32 via their f32 scales (GRAPH407
                # contract); guarded so bf16 stays byte-identical
                params = dequantize_tree(params)
            b_local = ids_c.shape[0]
            if cfg.unet.sp_axis is not None:
                sp_rank = jax.lax.axis_index(cfg.unet.sp_axis)
            else:
                sp_rank = 0
            frame0 = sp_rank * t_local
            ctx_c = self.text_encoder.apply({"params": params["text"]}, ids_c)
            ctx_u = self.text_encoder.apply({"params": params["text"]}, ids_u)
            context = jnp.concatenate([ctx_u, ctx_c], axis=0)

            keys = jax.vmap(
                lambda lo, hi: jax.random.fold_in(jax.random.PRNGKey(lo), hi)
            )(seeds_lo, seeds_hi)

            def noise_for(step_tag):
                # noise keyed by (sample, step, GLOBAL frame): sp-invariant
                def per_sample(k):
                    kk = jax.random.fold_in(k, step_tag)
                    return jax.vmap(lambda f: jax.random.normal(
                        jax.random.fold_in(kk, f),
                        (lh, lw, cfg.unet.in_channels), jnp.float32))(
                        frame0 + jnp.arange(t_local))
                return jax.vmap(per_sample)(keys)

            # init-noise tag is outside the step range [0, num_model_calls)
            x = noise_for(jnp.int32(1 << 30)) * sampler.init_noise_sigma
            g = guidance.astype(jnp.float32)[:, None, None, None, None]

            def body(carry, i):
                x, state = carry
                xin = jnp.concatenate([x, x], axis=0) * sampler.input_scale[i]
                t = jnp.full((2 * b_local,), sampler.timesteps[i])
                eps = self.unet.apply({"params": params["unet"]}, xin, t,
                                      context)
                eps_u, eps_c = jnp.split(eps.astype(jnp.float32), 2, axis=0)
                eps = eps_u + g * (eps_c - eps_u)
                x, state = sampler.step(i, x, eps, state, noise_for(i))
                return (x, state), None

            (x, _), _ = jax.lax.scan(body, (x, sampler.init_carry(x)),
                                     jnp.arange(sampler.num_model_calls))
            flat = x.reshape(b_local * t_local, lh, lw,
                             cfg.unet.in_channels)
            pixels = self.vae.apply({"params": params["vae"]},
                                    flat / SD_LATENT_SCALE)
            images = decode_to_images(pixels)
            return images.reshape(b_local, t_local, height, width, 3)

        if self.mesh is not None:
            from jax.experimental.shard_map import shard_map
            from jax.sharding import PartitionSpec as P

            fn = jax.jit(shard_map(
                run, mesh=self.mesh,
                in_specs=(P(), P("dp"), P("dp"), P("dp"), P("dp"), P("dp")),
                out_specs=P("dp", "sp"),
                check_rep=False))
        else:
            fn = jax.jit(run)
        return fn

    # -- public API ------------------------------------------------------
    def generate(self, params: dict, prompts: list[str],
                 negative_prompts: list[str] | None, seeds: list[int], *,
                 num_frames: int = 16, width: int = 256, height: int = 256,
                 fps: int = 8, num_inference_steps: int = 20,
                 guidance_scale: float | list[float] = 9.0,
                 scheduler: str = "DDIM",
                 as_device: bool = False) -> np.ndarray:
        del fps  # container metadata, applied by the mp4 muxer
        batch = len(prompts)
        negs = negative_prompts or [""] * batch
        if len(negs) != batch or len(seeds) != batch:
            raise ValueError("prompts/negative_prompts/seeds must align")
        levels = len(self.config.unet.block_channels)
        granule = self.VAE_FACTOR * (2 ** (levels - 1))
        if height % granule or width % granule:
            raise ValueError(f"height/width must be multiples of {granule}")
        g = list(guidance_scale) if isinstance(guidance_scale, (list, tuple)) \
            else [guidance_scale] * batch
        ids_c = self.tokenizer.encode_batch(prompts)
        ids_u = self.tokenizer.encode_batch(negs)
        vocab = self.config.text.vocab_size
        if int(ids_c.max()) >= vocab or int(ids_u.max()) >= vocab:
            raise ValueError(
                f"tokenizer produced id >= vocab_size ({vocab}); "
                "tokenizer and text-encoder config are mismatched")
        seeds_arr = np.asarray(seeds, dtype=np.uint64)
        args = (jnp.asarray(ids_c), jnp.asarray(ids_u),
                jnp.asarray(g, jnp.float32),
                jnp.asarray(seeds_arr & 0xFFFFFFFF, jnp.uint32),
                jnp.asarray(seeds_arr >> np.uint64(32), jnp.uint32))
        # args before the lookup: the AOT tier keys against the exact
        # dispatch operands (docs/compile-cache.md)
        fn, warm, tag = self._get_bucket(
            batch, num_frames, height, width, num_inference_steps,
            scheduler, aot_args=lambda: (params, *args))
        from arbius_tpu.obs import timed_dispatch

        with timed_dispatch(warm, tag):
            out = fn(params, *args)
        if self.mesh is not None:
            from arbius_tpu.parallel import meshsolve

            # params ride the shard_map replicated (in_spec P()), so the
            # traffic model is the dp/sp output-gather + halo terms only
            # (out is uint8 already — no tp term exists for wire_dtype
            # to quantize; a future tp-sharded video path would thread
            # it like the image families do)
            meshsolve.record_bucket_estimate(
                self._coll_est,
                (batch, num_frames, height, width, num_inference_steps,
                 scheduler),
                self.mesh, out, batch, tag=tag)
        if as_device:
            # async-dispatch handle: the video runner's chunk pipeline
            # muxes the previous chunk while the chip crunches this one
            return out
        return np.asarray(out)


# mesh layouts this family ships (docs/multichip.md): the video path
# runs the whole denoise scan under shard_map — dp shards samples, sp
# shards frames (ring/ulysses temporal attention, ops/), tp rides the
# rule table. Unlike the image families there is no dp-only entry: the
# sp collectives are the reason this family meshes at all.
MESH_LAYOUTS: tuple[tuple[str, ...], ...] = (("dp", "sp", "tp"),)
# the shard_map hard-partitions the batch axis over dp — an indivisible
# canonical_batch is a boot error, not a replicate-degrade
# (meshsolve.check_mesh_contract reads this, like MESH_LAYOUTS, as data)
MESH_BATCH_HARD = True


def trace_specs():
    """graphlint trace specs (models/trace_specs.py): the UNet3D video
    bucket single-device AND under each shipped shard_map layout
    (MESH_LAYOUTS). The mesh variant traces over
    `parallel.abstract_mesh`, so the ring attention / halo exchange
    collectives land in the fingerprint with no physical devices (and
    no device ids) involved — mesh layout is part of the determinism
    class (docs/determinism.md) and therefore part of the golden key."""
    from arbius_tpu.models.trace_specs import TraceSpec
    from arbius_tpu.parallel import meshsolve
    from arbius_tpu.schedulers import sampler_tag

    def build_single(precision="bf16"):
        def build():
            p = Text2VideoPipeline(Text2VideoConfig.tiny(),
                                   precision=precision)
            return _bucket_args(p, batch=1, precision=precision)

        return build

    def build_sharded():
        p = Text2VideoPipeline(Text2VideoConfig.tiny(sp_axis="sp"),
                               mesh=meshsolve.golden_mesh(MESH_LAYOUTS[0]))
        return _bucket_args(p, batch=2)

    def _bucket_args(p, batch, precision="bf16"):
        shapes = jax.eval_shape(
            lambda: p.init_params(frames=2, height=64, width=64))
        if precision != "bf16":
            from arbius_tpu.quant import abstract_quantized

            shapes = abstract_quantized(shapes, precision)
        sds = jax.ShapeDtypeStruct
        length = p.config.text.max_length
        args = (shapes,
                sds((batch, length), jnp.int32),
                sds((batch, length), jnp.int32),
                sds((batch,), jnp.float32),
                sds((batch,), jnp.uint32), sds((batch,), jnp.uint32))
        return p.compiled_bucket(batch, 2, 64, 64, 2, "DDIM"), args

    bucket = f"f2.64x64.{sampler_tag('DDIM', 2)}"
    sharded_tag = meshsolve.golden_layout_tag(MESH_LAYOUTS[0])
    return [
        TraceSpec(model="zeroscopev2xl", entry="txt2vid",
                  bucket=f"b1.{bucket}", mesh="single", dtype="bfloat16",
                  build=build_single()),
        # quantized UNet3D/temporal-conv mode (docs/quantization.md)
        TraceSpec(model="zeroscopev2xl", entry="txt2vid",
                  bucket=f"b1.{bucket}", mesh="single", dtype="int8",
                  build=build_single("int8")),
        TraceSpec(model="zeroscopev2xl", entry="txt2vid",
                  bucket=f"b2.{bucket}", mesh=sharded_tag,
                  dtype="bfloat16", build=build_sharded),
    ]
