"""Text-to-video model family: UNet3D (zeroscope/damo template classes)
with built-in frame-axis sequence parallelism."""
from arbius_tpu.models.video.pipeline import Text2VideoConfig, Text2VideoPipeline
from arbius_tpu.models.video.unet3d import (
    TemporalAttention,
    TemporalConv,
    UNet3DCondition,
    UNet3DConfig,
)

__all__ = [
    "TemporalAttention", "TemporalConv", "Text2VideoConfig",
    "Text2VideoPipeline", "UNet3DCondition", "UNet3DConfig",
]
