"""Text-to-video model family: the published UNet3DConditionModel
topology (zeroscope/damo template classes) with built-in frame-axis
sequence parallelism."""
from arbius_tpu.models.video.convert import (
    convert_unet3d,
    unet3d_key_for,
)
from arbius_tpu.models.video.pipeline import Text2VideoConfig, Text2VideoPipeline
from arbius_tpu.models.video.unet3d import (
    TemporalConvLayer,
    TemporalTransformer,
    UNet3DCondition,
    UNet3DConfig,
)

__all__ = [
    "TemporalConvLayer", "TemporalTransformer", "Text2VideoConfig",
    "Text2VideoPipeline", "UNet3DCondition", "UNet3DConfig",
    "convert_unet3d", "unet3d_key_for",
]
