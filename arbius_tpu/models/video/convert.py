"""Checkpoint conversion: published text-to-video state dicts → param trees.

The reference mines zeroscopev2xl / damo through cog containers wrapping
the published weights (`templates/zeroscopev2xl.json`, `templates/
damo.json`). Both distributions are the diffusers layout — the ModelScope
`UNet3DConditionModel` (zeroscope v2 is a fine-tune of the same topology),
a standard `AutoencoderKL` VAE, and a CLIP text tower — so this module
maps that key space onto `models/video/unet3d.py`'s flax tree. The VAE
and text towers reuse sd15's converters verbatim: the published video
repos use the identical diffusers/CLIP naming, just other widths (1024-d
ViT-H-class text).

Same contract as sd15/convert.py (the family template): flat
`{key: numpy array}` in, completeness enforced, shape mismatches loud,
bijectivity tested in tests/test_video_convert.py. Numeric validation
against live published weights is a deployment-time step (zero egress) —
the boot self-test's golden CID is the final arbiter either way.
"""
from __future__ import annotations

import re

import numpy as np

from arbius_tpu.models.sd15.convert import (
    _GEGLU_LEAVES,
    ConversionError,
    _conv,
    _convert_tree,
    _geglu_gate,
    _geglu_gate_b,
    _geglu_val,
    _geglu_val_b,
    _ident,
    _linear,
    _unet_block_prefix,
    unet_key_for,
)
from arbius_tpu.models.sd15.convert import (
    convert_sd15_text as convert_video_text,
)
from arbius_tpu.models.sd15.convert import (
    convert_sd15_vae as convert_video_vae,
)

__all__ = ["convert_unet3d", "unet3d_key_for", "convert_video_vae",
           "convert_video_text", "export_tree"]


def _tconv3d(w):
    """torch Conv3d (3,1,1) kernel [O, I, 3, 1, 1] → flax frame-axis conv
    [3, I, O]."""
    w = np.asarray(w)[:, :, :, 0, 0]
    return np.ascontiguousarray(np.transpose(w, (2, 1, 0)))


def _proj_flex(w):
    """Spatial-transformer proj_in/out: published repos ship either a 1×1
    Conv2d [O, I, 1, 1] or (use_linear_projection) a Linear [O, I] —
    accept both into the flax 1×1-conv kernel [1, 1, I, O]."""
    w = np.asarray(w)
    if w.ndim == 2:
        w = w[:, :, None, None]
    return _conv(w)


# TemporalConvLayer: conv1 = Sequential(GN, SiLU, Conv3d) → .0/.2;
# conv2..4 = Sequential(GN, SiLU, Dropout, Conv3d) → .0/.3
def _tconv_leaf(rest: str):
    m = re.match(r"conv([1-4])_norm/GroupNorm_0/(scale|bias)$", rest)
    if m:
        leaf = "weight" if m.group(2) == "scale" else "bias"
        return f"conv{m.group(1)}.0.{leaf}", _ident
    m = re.match(r"conv([1-4])/(kernel|bias)$", rest)
    if m:
        conv_idx = 2 if m.group(1) == "1" else 3
        if m.group(2) == "kernel":
            return f"conv{m.group(1)}.{conv_idx}.weight", _tconv3d
        return f"conv{m.group(1)}.{conv_idx}.bias", _ident
    return None


# TemporalTransformerBlock (BasicTransformerBlock, double self-attention)
_TEMPORAL_BLOCK = {
    "norm1/scale": ("norm1.weight", _ident),
    "norm1/bias": ("norm1.bias", _ident),
    "norm2/scale": ("norm2.weight", _ident),
    "norm2/bias": ("norm2.bias", _ident),
    "norm3/scale": ("norm3.weight", _ident),
    "norm3/bias": ("norm3.bias", _ident),
    "attn1/to_q/kernel": ("attn1.to_q.weight", _linear),
    "attn1/to_k/kernel": ("attn1.to_k.weight", _linear),
    "attn1/to_v/kernel": ("attn1.to_v.weight", _linear),
    "attn1/to_out/kernel": ("attn1.to_out.0.weight", _linear),
    "attn1/to_out/bias": ("attn1.to_out.0.bias", _ident),
    "attn2/to_q/kernel": ("attn2.to_q.weight", _linear),
    "attn2/to_k/kernel": ("attn2.to_k.weight", _linear),
    "attn2/to_v/kernel": ("attn2.to_v.weight", _linear),
    "attn2/to_out/kernel": ("attn2.to_out.0.weight", _linear),
    "attn2/to_out/bias": ("attn2.to_out.0.bias", _ident),
    "ff_out/kernel": ("ff.net.2.weight", _linear),
    "ff_out/bias": ("ff.net.2.bias", _ident),
}


def _tattn_leaf(rest: str):
    """TransformerTemporalModel leaves under a temp_attentions prefix."""
    if rest == "norm/GroupNorm_0/scale":
        return "norm.weight", _ident
    if rest == "norm/GroupNorm_0/bias":
        return "norm.bias", _ident
    for proj in ("proj_in", "proj_out"):
        if rest == f"{proj}/kernel":
            return f"{proj}.weight", _linear
        if rest == f"{proj}/bias":
            return f"{proj}.bias", _ident
    m = re.match(r"block_(\d+)/(.+)$", rest)
    if m:
        tb = f"transformer_blocks.{m.group(1)}"
        leaf = _TEMPORAL_BLOCK.get(m.group(2)) or _GEGLU_LEAVES.get(
            m.group(2))
        if leaf:
            return f"{tb}.{leaf[0]}", leaf[1]
    return None


def _temporal_block_prefix(part: str, n_levels: int) -> str | None:
    """our 'down_2_tconv_1' style prefix -> diffusers temporal prefix."""
    m = re.match(r"down_(\d+)_tconv_(\d+)$", part)
    if m:
        return f"down_blocks.{m.group(1)}.temp_convs.{m.group(2)}"
    m = re.match(r"down_(\d+)_tattn_(\d+)$", part)
    if m:
        return f"down_blocks.{m.group(1)}.temp_attentions.{m.group(2)}"
    m = re.match(r"up_(\d+)_tconv_(\d+)$", part)
    if m:
        return (f"up_blocks.{n_levels - 1 - int(m.group(1))}"
                f".temp_convs.{m.group(2)}")
    m = re.match(r"up_(\d+)_tattn_(\d+)$", part)
    if m:
        return (f"up_blocks.{n_levels - 1 - int(m.group(1))}"
                f".temp_attentions.{m.group(2)}")
    m = re.match(r"mid_tconv_(\d+)$", part)
    if m:
        return f"mid_block.temp_convs.{m.group(1)}"
    if part == "mid_tattn":
        return "mid_block.temp_attentions.0"
    if part == "transformer_in":
        return "transformer_in"
    return None


def unet3d_key_for(path: str, n_levels: int = 4):
    """our flax path (joined with /) -> (diffusers key, transform).

    Temporal paths map here; everything else (conv_in/out, time embedding,
    resnets, spatial attentions, up/down samplers) is the 2D key space and
    delegates to sd15's unet_key_for."""
    part, _, rest = path.partition("/")
    prefix = _temporal_block_prefix(part, n_levels)
    if prefix is not None:
        if "tconv" in part:
            leaf = _tconv_leaf(rest)
        else:
            leaf = _tattn_leaf(rest)
        if leaf is None:
            raise ConversionError(f"unmapped temporal leaf {path!r}")
        return f"{prefix}.{leaf[0]}", leaf[1]
    key, tf = unet_key_for(path, n_levels)
    if tf is _conv and key.rsplit(".", 1)[0].endswith(("proj_in",
                                                       "proj_out")):
        return key, _proj_flex
    return key, tf


def convert_unet3d(state_dict: dict, template_params: dict,
                   n_levels: int = 4) -> dict:
    """Published UNet3DConditionModel state dict → UNet3DCondition tree."""
    return _convert_tree(template_params, state_dict,
                         lambda p: unet3d_key_for(p, n_levels))


def export_tree(params: dict, n_levels: int = 4) -> dict:
    """ours → published naming, inverting the leaf transforms (GEGLU
    halves re-fused; test round-trip + fixture fabrication)."""
    import jax

    out: dict[str, np.ndarray] = {}
    fuse: dict[str, dict[str, np.ndarray]] = {}

    def visit(path, leaf):
        p = "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                     for k in path)
        key, tf = unet3d_key_for(p, n_levels)
        w = np.asarray(leaf)
        if tf is _conv or tf is _proj_flex:
            out[key] = np.transpose(w, (3, 2, 0, 1))
        elif tf is _tconv3d:
            out[key] = np.transpose(w, (2, 1, 0))[:, :, :, None, None]
        elif tf is _linear:
            out[key] = np.transpose(w)
        elif tf in (_geglu_val, _geglu_gate):
            half = "val" if tf is _geglu_val else "gate"
            fuse.setdefault(key, {})[half] = np.transpose(w)
        elif tf in (_geglu_val_b, _geglu_gate_b):
            half = "val" if tf is _geglu_val_b else "gate"
            fuse.setdefault(key, {})[half] = w
        else:
            out[key] = w

    jax.tree_util.tree_map_with_path(visit, params)
    for key, halves in fuse.items():
        out[key] = np.concatenate([halves["val"], halves["gate"]], axis=0)
    return out
