"""textgen pipeline — deterministic LLM text serving, in-process.

The repo's first non-image family: a decoder-only LM whose WHOLE
generation — prefill, the autoregressive decode loop, and sampling —
is ONE jitted XLA program per shape bucket. The decode loop is a
`lax.scan` with the per-layer KV caches as explicit carry: no Python
step loop, no per-token dispatch, no retrace per length.

Shape buckets (docs/text-serving.md): a bucket is
(batch, prompt_bucket, decode_bucket, sampler). Prompts pad to the
prompt bucket edge with eos (ByteTokenizer discipline, NO attention
mask — padding is model input, exactly like image padding pixels), and
the loop always runs the full decode bucket; the solver truncates
host-side to each task's requested budget. Truncation is sound because
generation is causally prefix-stable: token i depends only on tokens
< i, so a longer decode bucket yields byte-identical prefixes. The
PROMPT bucket edge, by contrast, IS bytes-affecting (it changes the
positions everything sits at), which is why bucket edges are fleet-wide
determinism-class config (MiningConfig `textgen`), like canonical_batch.

Sampling: greedy is pure argmax over f32 logits. Seeded top-k restricts
to the `top_k` highest logits and draws categorically from a per-task
key chain — fold_in(PRNGKey(seed_lo), seed_hi) then fold_in(key, step)
per position, the same 53-bit taskid2seed threading the image families
use, so a task id always samples the same tokens on the same build.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from arbius_tpu.models.sd15.tokenizer import ByteTokenizer
from arbius_tpu.models.textgen.model import TextGenConfig, TextGenModel

# the deterministic byte tokenizer's control ids: raw UTF-8 bytes are
# ids 0..255, bos/eos sit above them (factory's tiny text tower uses
# the same pair)
BOS_ID = 257
EOS_ID = 258

SAMPLERS = ("greedy", "top_k")


def _fold_keys(seeds_lo, seeds_hi):
    """Per-task PRNG keys from the split 53-bit task seed: low word
    keys, high word folded in — identical derivation to the image
    pipelines, so seed handling stays one audited pattern."""
    return jax.vmap(
        lambda lo, hi: jax.random.fold_in(jax.random.PRNGKey(lo), hi)
    )(seeds_lo, seeds_hi)


def tokens_to_bytes(ids, limit: int, eos_id: int = EOS_ID) -> bytes:
    """Host-side detokenize: the first `limit` generated ids, stopped
    at the first eos, non-byte ids (bos, unused vocab tail) dropped —
    the mapping must be total over anything the model can emit."""
    out = bytearray()
    for tok in np.asarray(ids)[:limit]:
        tok = int(tok)
        if tok == eos_id:
            break
        if 0 <= tok < 256:
            out.append(tok)
    return bytes(out)


class TextGenPipeline:
    """Stateless module bundle + jitted per-bucket executables."""

    BOS_ID = BOS_ID
    EOS_ID = EOS_ID

    def __init__(self, config: TextGenConfig | None = None, mesh=None,
                 precision: str = "bf16",
                 prompt_buckets: tuple = (32, 64),
                 decode_buckets: tuple = (16, 32),
                 top_k: int = 8):
        from arbius_tpu.quant import validate_mode

        self.config = config or TextGenConfig()
        self.mesh = mesh  # jax.sharding.Mesh with a 'dp' axis, or None
        self.precision = validate_mode(precision)
        self.prompt_buckets = tuple(sorted(int(b) for b in prompt_buckets))
        self.decode_buckets = tuple(sorted(int(b) for b in decode_buckets))
        if not self.prompt_buckets or not self.decode_buckets:
            raise ValueError("prompt_buckets and decode_buckets must be "
                             "non-empty")
        if self.prompt_buckets[0] < 3:
            raise ValueError("prompt bucket edges must be >= 3 "
                             "(bos + at least one byte + eos)")
        if self.decode_buckets[0] < 1:
            raise ValueError("decode bucket edges must be >= 1")
        need = self.prompt_buckets[-1] + self.decode_buckets[-1]
        if need > self.config.max_positions:
            raise ValueError(
                f"bucket edges need {need} positions but the model tops "
                f"out at {self.config.max_positions}")
        self.top_k = int(top_k)
        if not 1 <= self.top_k <= self.config.vocab_size:
            raise ValueError(
                f"top_k ({self.top_k}) must be in [1, vocab_size]")
        self.model = TextGenModel(self.config)
        # per-instance executable cache (same rationale as sd15)
        self._buckets: dict[tuple, object] = {}
        self._coll_est: dict[tuple, dict] = {}
        self._tokenizers: dict[int, ByteTokenizer] = {}

    # -- bucket policy ---------------------------------------------------
    def prompt_bucket_for(self, prompt: str) -> int:
        """Smallest configured prompt edge that fits bos+bytes+eos;
        over-long prompts truncate into the top edge (the tokenizer's
        deterministic truncation, not an error — mirrors the reference
        miner accepting arbitrary prompt strings)."""
        need = len(str(prompt).encode("utf-8")) + 2
        for edge in self.prompt_buckets:
            if need <= edge:
                return edge
        return self.prompt_buckets[-1]

    def decode_bucket_for(self, max_new_tokens: int) -> int:
        """Smallest configured decode edge covering the requested
        budget; oversized budgets clamp to the top edge (the config
        cap keeps them unreachable through hydration)."""
        n = max(1, int(max_new_tokens))
        for edge in self.decode_buckets:
            if n <= edge:
                return edge
        return self.decode_buckets[-1]

    def _tokenizer(self, prompt_bucket: int) -> ByteTokenizer:
        tok = self._tokenizers.get(prompt_bucket)
        if tok is None:
            tok = ByteTokenizer(max_length=prompt_bucket,
                                bos_id=self.BOS_ID, eos_id=self.EOS_ID)
            self._tokenizers[prompt_bucket] = tok
        return tok

    # -- params ----------------------------------------------------------
    def _init_fn(self):
        p = self.prompt_buckets[0]

        def _init(key):
            ids = jnp.zeros((1, p), jnp.int32)
            # prefill touches every parameter decode reads (shared
            # setup-style submodules), so one init covers both methods
            return self.model.init(key, ids, p + 1,
                                   method=TextGenModel.prefill)["params"]

        return _init

    def init_params(self, seed: int = 0, dtype=None, **_unused) -> dict:
        """Deterministic parameter init as ONE jitted program (same
        remote-TPU dispatch rationale as SD15Pipeline.init_params)."""
        from arbius_tpu.utils import with_cast

        return jax.jit(with_cast(self._init_fn(), dtype))(
            jax.random.PRNGKey(seed))

    def init_params_placed(self, seed: int = 0, tp_rules=None,
                           **_unused) -> dict:
        """Fused init + mesh placement (one program, sharded outputs);
        on this family's dp-only layouts the rule table degrades to
        replication, which is exactly right."""
        if self.mesh is None:
            return self.init_params(seed=seed)
        from arbius_tpu.parallel import DEFAULT_TP_RULES, sharding_tree

        if tp_rules is None:
            tp_rules = DEFAULT_TP_RULES
        init = self._init_fn()
        key = jax.random.PRNGKey(seed)
        shapes = jax.eval_shape(init, key)
        out = sharding_tree(shapes, self.mesh, tp_rules)
        return jax.jit(init, out_shardings=out)(key)

    def place_params(self, params: dict, tp_rules=None) -> dict:
        if self.mesh is None:
            return params
        from arbius_tpu.parallel import DEFAULT_TP_RULES, shard_params

        if tp_rules is None:
            tp_rules = DEFAULT_TP_RULES
        return shard_params(params, self.mesh, tp_rules)

    def _place_batch(self, *arrays):
        if self.mesh is None:
            return arrays
        from arbius_tpu.parallel import meshsolve

        return meshsolve.shard_batch(self.mesh, *arrays)

    # -- compiled bucket -------------------------------------------------
    def bucket_tag(self, batch: int, prompt_bucket: int,
                   decode_bucket: int, sampler: str) -> str:
        """The ONE definition of this family's executable-cache tag
        (docs/compile-cache.md) — jit-cache warm set, AOT disk scan and
        scheduler warm boost all join on it. Sequence edges and the
        sampler are program shape, so they are in the tag; precision
        modes suffix it exactly like the image families."""
        from arbius_tpu.quant import mode_tag

        return "textgen." + ".".join(
            str(k) for k in (batch, prompt_bucket, decode_bucket,
                             sampler)) + mode_tag(self.precision)

    def _get_bucket(self, batch: int, prompt_bucket: int,
                    decode_bucket: int, sampler: str, aot_args=None):
        from arbius_tpu.obs import jit_cache_get

        key = (batch, prompt_bucket, decode_bucket, sampler)
        return jit_cache_get(
            self._buckets, key,
            lambda: self._build_bucket(batch, prompt_bucket,
                                       decode_bucket, sampler),
            tag=self.bucket_tag(*key), aot_args=aot_args)

    def _sampler_fn(self, sampler: str):
        """(logits[B, V] f32, keys[B], step) → int32 token ids [B].
        Greedy ignores the keys (argmax is seed-free); seeded top-k
        draws categorically over the k highest logits with the per-task
        key folded by step — PRNG threaded from inputs end to end
        (GRAPH406), never a literal key."""
        if sampler == "greedy":
            def sample(logits, keys, step):
                return jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return sample
        top_k = self.top_k

        def sample(logits, keys, step):
            def one(key, row):
                vals, idx = jax.lax.top_k(row, top_k)
                choice = jax.random.categorical(
                    jax.random.fold_in(key, step), vals)
                return idx[choice]
            return jax.vmap(one)(keys, logits).astype(jnp.int32)
        return sample

    def _decode_loop(self, prompt_bucket: int, decode_bucket: int,
                     sampler: str):
        """The decode-loop body shared by the composed bucket program
        and the separately-goldened decode trace: lax.scan over steps
        1..T-1 with (kv, last_token) as carry. Step i embeds t_{i-1}
        at position P+i-1 and samples t_i; t0 (sampled from prefill's
        logits) rides in as the carry seed."""
        p, t = prompt_bucket, decode_bucket
        sample = self._sampler_fn(sampler)

        def loop(params, kv, t0, keys):
            def body(carry, i):
                kv, tok = carry
                logits, kv = self.model.apply(
                    {"params": params}, tok, kv, p + i - 1,
                    method=TextGenModel.decode)
                nxt = sample(logits, keys, i)
                return (kv, nxt), nxt

            (_, _), rest = jax.lax.scan(body, (kv, t0),
                                        jnp.arange(1, t))
            return jnp.concatenate(
                [t0[:, None], jnp.moveaxis(rest, 0, 1)], axis=1)

        return loop

    def prefill_program(self, batch: int, prompt_bucket: int,
                        decode_bucket: int):
        """The prefill determinism class, jitted standalone for its
        graphlint golden: (params, ids[B, P]) → (last-position logits,
        per-layer KV caches at the bucket's full length)."""
        total = prompt_bucket + decode_bucket

        def pre(params, ids):
            return self.model.apply({"params": params}, ids, total,
                                    method=TextGenModel.prefill)

        return jax.jit(pre)

    def decode_program(self, batch: int, prompt_bucket: int,
                       decode_bucket: int, sampler: str):
        """The decode-loop determinism class, jitted standalone for its
        graphlint golden: (params, kv, t0, seeds_lo, seeds_hi) →
        int32 tokens [B, T]."""
        if sampler not in SAMPLERS:
            raise ValueError(f"sampler must be one of {SAMPLERS}")
        loop = self._decode_loop(prompt_bucket, decode_bucket, sampler)

        def dec(params, kv, t0, seeds_lo, seeds_hi):
            return loop(params, kv, t0, _fold_keys(seeds_lo, seeds_hi))

        return jax.jit(dec)

    def _build_bucket(self, batch: int, prompt_bucket: int,
                      decode_bucket: int, sampler: str):
        p, t = prompt_bucket, decode_bucket
        total = p + t
        precision = self.precision
        sample = self._sampler_fn(sampler)
        loop = self._decode_loop(p, t, sampler)

        def run(params, ids, seeds_lo, seeds_hi):
            if precision != "bf16":
                from arbius_tpu.quant import dequantize_tree

                # int8/fp8 checkpoint kernels → f32 via explicit f32
                # scales (GRAPH407); guarded so the bf16 program stays
                # byte-identical to a never-quantized build
                params = dequantize_tree(params)
            keys = _fold_keys(seeds_lo, seeds_hi)
            logits0, kv = self.model.apply(
                {"params": params}, ids, total,
                method=TextGenModel.prefill)
            t0 = sample(logits0, keys, 0)
            return loop(params, kv, t0, keys)

        if self.mesh is None:
            return jax.jit(run)
        # dp-only GSPMD: batch args dp-sharded, params replicated by
        # their boot placement, tokens gathered host-side in canonical
        # order (docs/multichip.md)
        from arbius_tpu.parallel import meshsolve

        spec, _ = meshsolve.batch_specs(self.mesh, batch)
        return jax.jit(run,
                       in_shardings=(None, spec(2), spec(1), spec(1)),
                       out_shardings=spec(2))

    # -- public API ------------------------------------------------------
    def compiled_bucket(self, batch: int, prompt_bucket: int,
                        decode_bucket: int, sampler: str):
        """Public handle on a bucket executable: (params, ids[B, P],
        seeds_lo, seeds_hi) → int32 tokens [B, T]. Contract for
        external drivers and the trace specs."""
        return self._get_bucket(batch, prompt_bucket, decode_bucket,
                                sampler)[0]

    def generate(
        self,
        params: dict,
        prompts: list[str],
        seeds: list[int],
        *,
        prompt_bucket: int,
        decode_bucket: int,
        sampler: str = "greedy",
        as_device: bool = False,
    ):
        """Run a sequence bucket; returns int32 token ids [B, T].

        `as_device=True` keeps the jax.Array un-transferred so the
        solver can overlap the next dispatch with detokenize/CID work,
        exactly like the image families. Same bits either way."""
        batch = len(prompts)
        if len(seeds) != batch:
            raise ValueError("prompts/seeds must align")
        if sampler not in SAMPLERS:
            raise ValueError(f"sampler must be one of {SAMPLERS}")
        p, t = int(prompt_bucket), int(decode_bucket)
        if p not in self.prompt_buckets:
            raise ValueError(
                f"prompt_bucket {p} is not a configured edge "
                f"{self.prompt_buckets}")
        if t not in self.decode_buckets:
            raise ValueError(
                f"decode_bucket {t} is not a configured edge "
                f"{self.decode_buckets}")
        ids = self._tokenizer(p).encode_batch([str(x) for x in prompts])
        seeds_arr = np.asarray(seeds, dtype=np.uint64)
        args = self._place_batch(
            jnp.asarray(ids),
            jnp.asarray(seeds_arr & 0xFFFFFFFF, jnp.uint32),
            jnp.asarray(seeds_arr >> np.uint64(32), jnp.uint32),
        )
        # args before lookup: the AOT tier keys on exact operands
        fn, warm, tag = self._get_bucket(
            batch, p, t, sampler, aot_args=lambda: (params, *args))
        from arbius_tpu.obs import timed_dispatch

        with timed_dispatch(warm, tag):
            tokens = fn(params, *args)
        if self.mesh is not None:
            from arbius_tpu.parallel import meshsolve
            from arbius_tpu.quant import storage_dtype

            meshsolve.record_bucket_estimate(
                self._coll_est, (batch, p, t, sampler), self.mesh,
                tokens, batch, params=params,
                wire_dtype=storage_dtype(self.precision)
                if self.precision != "bf16" else None, tag=tag)
        if as_device:
            return tokens
        return np.asarray(tokens)


# dp-only for now: tokens scale bit-identically over the batch axis;
# a tp split of the decode loop would be a new determinism class and
# ships only with its own golden (docs/multichip.md)
MESH_LAYOUTS: tuple[tuple[str, ...], ...] = (("dp",),)


def trace_specs():
    """graphlint trace specs: prefill and the decode loop goldened as
    SEPARATE determinism classes (docs/text-serving.md), plus the
    composed bucket program single/dp2 and int8 — all abstract (params
    via eval_shape, KV shapes via eval_shape over the prefill program),
    CPU-traceable in seconds."""
    from arbius_tpu.models.trace_specs import TraceSpec
    from arbius_tpu.parallel import meshsolve

    P, T = 8, 4  # tiny trace bucket: topology is what the golden pins

    def make_pipe(axes=(), precision="bf16"):
        return TextGenPipeline(TextGenConfig.tiny(),
                               mesh=meshsolve.golden_mesh(axes),
                               precision=precision,
                               prompt_buckets=(P,), decode_buckets=(T,),
                               top_k=4)

    def abstract(pipe, batch, precision="bf16"):
        shapes = jax.eval_shape(pipe._init_fn(), jax.random.PRNGKey(0))
        if precision != "bf16":
            from arbius_tpu.quant import abstract_quantized

            shapes = abstract_quantized(shapes, precision)
        sds = jax.ShapeDtypeStruct
        return (shapes, sds((batch, P), jnp.int32),
                sds((batch,), jnp.uint32), sds((batch,), jnp.uint32))

    def build_prefill():
        pipe = make_pipe()
        shapes, ids, _, _ = abstract(pipe, 1)
        return pipe.prefill_program(1, P, T), (shapes, ids)

    def build_decode(sampler):
        def build():
            pipe = make_pipe()
            shapes, ids, lo, hi = abstract(pipe, 1)
            _, kv = jax.eval_shape(pipe.prefill_program(1, P, T),
                                   shapes, ids)
            t0 = jax.ShapeDtypeStruct((1,), jnp.int32)
            return (pipe.decode_program(1, P, T, sampler),
                    (shapes, kv, t0, lo, hi))

        return build

    def build_generate(axes=(), precision="bf16", sampler="greedy"):
        def build():
            pipe = make_pipe(axes, precision)
            batch = 2 if axes else 1
            shapes, ids, lo, hi = abstract(pipe, batch, precision)
            return (pipe.compiled_bucket(batch, P, T, sampler),
                    (shapes, ids, lo, hi))

        return build

    bucket = f"b1.p{P}.t{T}"
    return [
        TraceSpec(model="textgen", entry="prefill", bucket=bucket,
                  mesh="single", dtype="bfloat16", build=build_prefill),
        TraceSpec(model="textgen", entry="decode",
                  bucket=f"{bucket}.greedy", mesh="single",
                  dtype="bfloat16", build=build_decode("greedy")),
        # seeded top-k: the golden proves the PRNG chain is threaded
        # from the seed inputs (GRAPH406), not baked in as a literal
        TraceSpec(model="textgen", entry="decode",
                  bucket=f"{bucket}.top_k", mesh="single",
                  dtype="bfloat16", build=build_decode("top_k")),
        TraceSpec(model="textgen", entry="generate",
                  bucket=f"{bucket}.greedy", mesh="single",
                  dtype="bfloat16", build=build_generate()),
        TraceSpec(model="textgen", entry="generate",
                  bucket=f"{bucket}.greedy", mesh="single", dtype="int8",
                  build=build_generate(precision="int8")),
    ] + [
        TraceSpec(model="textgen", entry="generate",
                  bucket=f"b2.p{P}.t{T}.greedy",
                  mesh=meshsolve.golden_layout_tag(axes),
                  dtype="bfloat16", build=build_generate(axes))
        for axes in MESH_LAYOUTS
    ]
