"""textgen — deterministic LLM text generation (docs/text-serving.md)."""
from arbius_tpu.models.textgen.model import TextGenConfig, TextGenModel
from arbius_tpu.models.textgen.pipeline import (
    BOS_ID,
    EOS_ID,
    MESH_LAYOUTS,
    SAMPLERS,
    TextGenPipeline,
    tokens_to_bytes,
)

__all__ = [
    "BOS_ID",
    "EOS_ID",
    "MESH_LAYOUTS",
    "SAMPLERS",
    "TextGenConfig",
    "TextGenModel",
    "TextGenPipeline",
    "tokens_to_bytes",
]
