"""Decoder-only text transformer with an explicit KV-cache API.

The textgen family's model is deliberately small and boring: token +
learned position embeddings, pre-LayerNorm attention/MLP blocks, a
final f32 LayerNorm and an f32 logits head. What makes it the repo's
LLM-serving shape is the SPLIT API the pipeline jits around:

  * `prefill(ids, total)`   — one dense causal pass over the padded
    prompt bucket; returns the last position's logits plus per-layer
    K/V caches already allocated at the bucket's full sequence length
    (`total` = prompt bucket + decode bucket), prompt rows filled.
  * `decode(tok, kv, pos)`  — one autoregressive step: embed a single
    token at `pos`, write its K/V into the carried caches, attend over
    positions <= pos, return next-token logits and the updated caches.

Both methods read the SAME parameters (setup-style submodules), so the
prefill and decode programs — two separately-goldened determinism
classes (docs/text-serving.md) — can never drift apart structurally.
Attention logits and softmax accumulate in f32 exactly like the image
towers (models/common.py discipline); K/V caches store the compute
dtype.
"""
from __future__ import annotations

from dataclasses import dataclass

import flax.linen as nn
import jax
import jax.numpy as jnp

# additive mask value: large-negative f32, the zoo's masked-softmax
# convention (finite so a fully-masked row still normalizes)
_NEG = -1e30


@dataclass(frozen=True)
class TextGenConfig:
    # 512 keeps the byte tokenizer's id space (0..255 bytes, bos 257,
    # eos 258) with headroom, and matches the tiny text-tower vocab
    vocab_size: int = 512
    # must cover max(prompt_buckets) + max(decode_buckets) of any
    # pipeline built on this topology (TextGenPipeline validates)
    max_positions: int = 128
    width: int = 64
    layers: int = 2
    heads: int = 2
    dtype: str = "bfloat16"

    @property
    def jdtype(self):
        return jnp.dtype(self.dtype)

    @property
    def head_dim(self) -> int:
        return self.width // self.heads

    def __post_init__(self):
        if self.width % self.heads:
            raise ValueError(
                f"width ({self.width}) must be divisible by heads "
                f"({self.heads})")

    @classmethod
    def tiny(cls) -> "TextGenConfig":
        return cls(vocab_size=512, max_positions=96, width=16,
                   layers=1, heads=2)


class _DecoderBlock(nn.Module):
    cfg: TextGenConfig

    def setup(self):
        cfg = self.cfg
        dt = cfg.jdtype
        self.ln1 = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32)
        self.wq = nn.Dense(cfg.width, dtype=dt)
        self.wk = nn.Dense(cfg.width, dtype=dt)
        self.wv = nn.Dense(cfg.width, dtype=dt)
        self.wo = nn.Dense(cfg.width, dtype=dt)
        self.ln2 = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32)
        self.mlp_up = nn.Dense(cfg.width * 4, dtype=dt)
        self.mlp_down = nn.Dense(cfg.width, dtype=dt)

    def _split(self, x):
        return x.reshape(*x.shape[:-1], self.cfg.heads, self.cfg.head_dim)

    def _mlp(self, x):
        h = self.ln2(x).astype(self.cfg.jdtype)
        h = self.mlp_down(nn.gelu(self.mlp_up(h), approximate=False))
        return x + h

    def prefill(self, x):
        """x[B, P, W] → (x'[B, P, W], k[B, P, H, D], v[B, P, H, D])."""
        cfg = self.cfg
        dt = cfg.jdtype
        h = self.ln1(x).astype(dt)
        q = self._split(self.wq(h))
        k = self._split(self.wk(h))
        v = self._split(self.wv(h))
        scale = cfg.head_dim ** -0.5
        logits = jnp.einsum("bphd,bmhd->bhpm", q.astype(jnp.float32),
                            k.astype(jnp.float32)) * scale
        p = x.shape[1]
        causal = jnp.tril(jnp.ones((p, p), bool))
        logits = jnp.where(causal[None, None], logits, _NEG)
        att = jax.nn.softmax(logits, axis=-1).astype(dt)
        o = jnp.einsum("bhpm,bmhd->bphd", att, v)
        x = x + self.wo(o.reshape(*o.shape[:2], cfg.width))
        return self._mlp(x), k, v

    def decode(self, x, k_cache, v_cache, pos):
        """One step: x[B, W] is the token at `pos`; caches [B, S, H, D]
        get this position's K/V written in place (dynamic_update_slice,
        so `pos` may be a traced scan index) and attention reads
        positions <= pos only."""
        cfg = self.cfg
        dt = cfg.jdtype
        h = self.ln1(x).astype(dt)
        q = self._split(self.wq(h))          # [B, H, D]
        k_new = self._split(self.wk(h))
        v_new = self._split(self.wv(h))
        k_cache = jax.lax.dynamic_update_slice(
            k_cache, k_new[:, None].astype(k_cache.dtype), (0, pos, 0, 0))
        v_cache = jax.lax.dynamic_update_slice(
            v_cache, v_new[:, None].astype(v_cache.dtype), (0, pos, 0, 0))
        scale = cfg.head_dim ** -0.5
        logits = jnp.einsum("bhd,bshd->bhs", q.astype(jnp.float32),
                            k_cache.astype(jnp.float32)) * scale
        valid = jnp.arange(k_cache.shape[1]) <= pos
        logits = jnp.where(valid[None, None], logits, _NEG)
        att = jax.nn.softmax(logits, axis=-1).astype(dt)
        o = jnp.einsum("bhs,bshd->bhd", att, v_cache.astype(dt))
        x = x + self.wo(o.reshape(o.shape[0], cfg.width))
        return self._mlp(x), k_cache, v_cache


class TextGenModel(nn.Module):
    """Decoder-only LM; `prefill` and `decode` share every parameter."""
    config: TextGenConfig

    def setup(self):
        cfg = self.config
        self.token_embed = nn.Embed(cfg.vocab_size, cfg.width,
                                    dtype=cfg.jdtype, name="token_embed")
        self.pos_embed = self.param("pos_embed",
                                    nn.initializers.normal(0.01),
                                    (cfg.max_positions, cfg.width))
        self.blocks = [_DecoderBlock(cfg, name=f"layer_{i}")
                       for i in range(cfg.layers)]
        self.final_norm = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32,
                                       name="final_norm")
        # f32 head: sampling (argmax / top-k) must compare logits at
        # full precision — a bf16 head could tie-break differently
        # across XLA versions
        self.lm_head = nn.Dense(cfg.vocab_size, dtype=jnp.float32,
                                name="lm_head")

    def prefill(self, ids, total: int):
        """ids[B, P] → (logits[B, V] f32 at the last prompt position,
        per-layer ((k, v), ...) caches of length `total` with rows
        0..P-1 filled). `total` is static (the bucket's P + T)."""
        cfg = self.config
        dt = cfg.jdtype
        p = ids.shape[1]
        x = self.token_embed(ids) + self.pos_embed[None, :p].astype(dt)
        kv = []
        for blk in self.blocks:
            x, k, v = blk.prefill(x)
            pad = ((0, 0), (0, total - p), (0, 0), (0, 0))
            kv.append((jnp.pad(k, pad), jnp.pad(v, pad)))
        x = self.final_norm(x[:, -1])
        return self.lm_head(x.astype(jnp.float32)), tuple(kv)

    def decode(self, tok, kv, pos):
        """tok[B] int32 at position `pos` → (logits[B, V] f32 for the
        NEXT position, updated caches)."""
        cfg = self.config
        x = self.token_embed(tok) \
            + jnp.take(self.pos_embed, pos, axis=0).astype(cfg.jdtype)
        new_kv = []
        for blk, (k, v) in zip(self.blocks, kv):
            x, k, v = blk.decode(x, k, v, pos)
            new_kv.append((k, v))
        x = self.final_norm(x)
        return self.lm_head(x.astype(jnp.float32)), tuple(new_kv)
