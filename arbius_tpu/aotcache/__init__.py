"""arbius_tpu.aotcache — fleet-wide AOT-serialized executable cache.

The compile storm is the cold-boot killer (docs/compile-cache.md): every
fleet worker used to re-trace AND re-compile every (family, bucket,
layout) executable at boot, and `arbius_compile_seconds` (PR 7) meters
exactly how much chip time that burns. This package persists compiled
executables across lives via JAX's AOT path — `jit(f).lower(*args)
.compile()` serialized with `jax.experimental.serialize_executable` —
into a content-addressed on-disk cache whose key is the **graphlint
canonical program fingerprint** plus the environment signature
(jaxlib/platform/device kind/count) and the argument/sharding/donation
signature (aotcache/store.py).

Because the key IS the program identity the goldens already pin,
invalidation is by construction: a drifted program (a changed sampler
table, a different accumulation dtype, a new mesh layout) hashes to a
different key and simply MISSES to a fresh trace+compile. There is no
version file to forget to bump and no way to load a stale executable.

The cache threads under the one existing executable-cache seam,
`obs.jit_cache_get` (the model pipelines' `_buckets`, the meshsolve
probes' `_fns`), as a second tier:

    memory (this life's dict)  →  disk (mmap + deserialize)  →
    trace + compile (and write back, atomic tmp+rename)

A corrupted, truncated, or wrong-environment entry falls back to
compile with a journaled `aot_cache_reject` event — never an error and
never a wrong answer. Determinism: a disk-hit dispatch is the SAME XLA
program the fresh compile would build (same fingerprint ⇒ same
canonical jaxpr ⇒ XLA's deterministic lowering), so CIDs are
byte-identical cache-on vs cache-off (tests/test_aotcache.py pins it
for image- and video-shaped probes and a real tiny SD-1.5, mesh-off
and dp2).

Metrics (docs/observability.md): `arbius_jit_cache_hits_total{tier}`
splits memory vs disk hits; `arbius_aot_cache_{loads,writes,rejects,
evictions}_total` and `arbius_aot_load_seconds` cover the disk tier.
All ambient-obs no-ops, like every obs helper.
"""
from __future__ import annotations

import pickle

from arbius_tpu.aotcache.store import (
    CacheReject,
    args_signature,
    derive_key,
    entry_path,
    env_signature,
    evict_lru,
    make_header,
    read_entry,
    read_header,
    scan,
    total_bytes,
    touch,
    write_entry,
)

_LOADS_HELP = ("AOT cache entries deserialized into live executables "
               "(disk-tier hits that skipped an XLA compile)")
_WRITES_HELP = ("Freshly compiled executables serialized into the AOT "
                "cache (atomic tmp+rename publishes)")
_REJECTS_HELP = ("AOT cache entries refused at load time (corrupt/"
                 "truncated/mismatched) — each also journals an "
                 "aot_cache_reject event; the dispatch falls back to a "
                 "fresh compile")
_EVICT_HELP = ("AOT cache entries deleted by LRU eviction under "
               "aot_cache.max_bytes")
_SKIPS_HELP = ("AOT cache interactions skipped without publishing — "
               "the journaled aot_cache_skip reason says which: the "
               "write-time load-back self-check failed (e.g. XLA-"
               "persistent-cache-served CPU executables re-serialize "
               "without their jitted symbols), the publish write "
               "failed (full/read-only shared dir), or key derivation "
               "failed (the lookup degraded to the lazy pre-AOT path) "
               "— never a failed solve (docs/compile-cache.md)")
_LOAD_SECONDS_HELP = ("Wall seconds to mmap + deserialize one AOT cache "
                      "entry into a live executable (tagged per "
                      "executable cache key) — the disk-tier cost that "
                      "replaces arbius_compile_seconds on a warm boot")


class AotCache:
    """One on-disk executable cache (usually one shared directory per
    fleet). Installed on a node's `Obs` (`obs.aot_cache`) so
    `jit_cache_get` finds it ambiently; safe to share across processes
    — every write is atomic and every read is digest-checked."""

    def __init__(self, cache_dir: str, *, max_bytes: int = 0,
                 layout: str = "single"):
        if not cache_dir:
            raise ValueError("AotCache needs a directory path")
        self.dir = cache_dir
        self.max_bytes = int(max_bytes)
        # the writer's mesh-layout tag (docs/multichip.md mesh_tag; the
        # node sets it at boot): stamped into every published header and
        # filtered on by tags(), so workers with DIFFERENT layouts can
        # share one directory without mis-counting each other's entries
        # as disk-warm — the cache KEY already separates their programs
        self.layout = layout
        self._env = None  # derived once, first use (jax must be up)

    # -- key -------------------------------------------------------------
    def env(self) -> dict:
        if self._env is None:
            self._env = env_signature()
        return self._env

    def _identity(self, jfn, args, donate_sig: str = ""
                  ) -> tuple[str, str, str]:
        """(key, program fingerprint, arg signature) from ONE trace.
        The fingerprint is graphlint's canonicalization over
        `jax.make_jaxpr`, which wraps the jitted callable in a pjit eqn
        — so jit-level in/out_shardings are part of the identity, the
        same way the per-layout goldens pin them."""
        import jax

        from arbius_tpu.analysis.graph.fingerprint import fingerprint

        fp = fingerprint(jax.make_jaxpr(jfn)(*args))
        arg_sig = args_signature(args)
        return (derive_key(fp, self.env(), arg_sig, donate_sig),
                fp, arg_sig)

    def key_for(self, jfn, args, *, donate_sig: str = "") -> str:
        """Trace `jfn` over `args` (no compile) and derive the content
        address."""
        return self._identity(jfn, args, donate_sig)[0]

    # -- tiers -----------------------------------------------------------
    def get_or_compile(self, build, args_thunk, *, tag: str | None = None,
                       donate_sig: str = ""):
        """The disk tier behind `obs.jit_cache_get`: build the jitted
        callable, trace it for its key, then load-or-compile. Returns
        `(fn, state)` with state ∈ {"disk", "compiled", "fallback"}:
        "disk"/"compiled" hand back an ALREADY-built executable (AOT —
        the first dispatch pays no build; the compile/load cost was
        timed into `arbius_compile_seconds` / `arbius_aot_load_seconds`
        here), "fallback" hands back the lazy jitted callable untouched
        because key derivation failed — the cache must never be the
        reason a solve fails, so a trace error degrades to the exact
        pre-AOT behavior (journaled `aot_cache_skip`). A compile error
        propagates: the lazy path would have raised it at dispatch too.
        A store failure (full/read-only shared dir, unserializable
        executable) is absorbed by `store` — the solve proceeds on the
        freshly compiled executable either way."""
        from arbius_tpu.obs import compile_timer, current_obs

        jfn = build()
        try:
            args = tuple(args_thunk())
            key, fp, arg_sig = self._identity(jfn, args, donate_sig)
        except Exception as e:  # noqa: BLE001 — degrade, never fail
            obs = current_obs()
            if obs is not None:
                obs.registry.counter("arbius_aot_cache_skips_total",
                                     _SKIPS_HELP).inc()
                obs.event("aot_cache_skip", key=None, tag=tag,
                          reason=f"key_derivation: {type(e).__name__}: "
                                 f"{str(e)[:120]}")
            return jfn, "fallback"
        fn = self.load(key, tag=tag)
        if fn is not None:
            return fn, "disk"
        import time

        # detlint: allow[DET101] obs compile timing; never reaches solve bytes
        t0 = time.perf_counter()
        with compile_timer(tag):
            compiled = jfn.lower(*args).compile()
        # detlint: allow[DET101] obs compile timing; never reaches solve bytes
        dt = time.perf_counter() - t0
        obs = current_obs()
        scope = getattr(obs, "perfscope", None) if obs is not None else None
        perf = None
        if scope is not None:
            # perfscope capture (docs/perfscope.md): the card reads
            # XLA's analyses off the fresh executable, and its compact
            # perf block rides the entry header so a future disk-hit
            # life amortizes the ORIGINAL compile cost
            perf = scope.record_executable(tag, compiled,
                                           compile_seconds=dt)
        self.store(key, compiled, program=fp, arg_sig=arg_sig, tag=tag,
                   donate_sig=donate_sig, perf=perf)
        return compiled, "compiled"

    def load(self, key: str, *, tag: str | None = None):
        """Deserialize one entry into a live executable, or None on a
        miss OR a reject (journaled — the caller compiles either way).
        The header's key and environment are re-checked against this
        process even though both are baked into the filename: a copied
        or renamed file must reject, not load."""
        import os

        from arbius_tpu.obs import current_obs

        path = entry_path(self.dir, key)
        if not os.path.exists(path):
            return None
        obs = current_obs()
        import time

        # detlint: allow[DET101] obs load timing; never reaches solve bytes
        t0 = time.perf_counter()
        try:
            header, payload, closer = read_entry(path)
            try:
                if header.get("key") != key:
                    raise CacheReject("key_mismatch", path)
                if header.get("env") != self.env():
                    raise CacheReject("env_mismatch", path)
                try:
                    serialized, in_tree, out_tree = pickle.loads(payload)
                    from jax.experimental.serialize_executable import (
                        deserialize_and_load,
                    )

                    fn = deserialize_and_load(serialized, in_tree,
                                              out_tree)
                except CacheReject:
                    raise
                except Exception as e:  # noqa: BLE001 — any deserializer
                    # failure is a reject, never a crash
                    raise CacheReject(
                        "deserialize_failed",
                        f"{path}: {type(e).__name__}: {e}") from None
            finally:
                closer()
        except CacheReject as e:
            if obs is not None:
                obs.registry.counter("arbius_aot_cache_rejects_total",
                                     _REJECTS_HELP).inc()
                obs.event("aot_cache_reject", key=key, tag=tag,
                          reason=e.reason)
            return None
        touch(path)
        if obs is not None:
            obs.registry.counter("arbius_aot_cache_loads_total",
                                 _LOADS_HELP).inc()
            obs.registry.histogram(
                "arbius_aot_load_seconds", _LOAD_SECONDS_HELP).observe(
                # detlint: allow[DET101] obs load timing; never reaches solve bytes
                time.perf_counter() - t0, tag=tag)
            scope = getattr(obs, "perfscope", None)
            if scope is not None:
                # a disk hit carries its card across lives: analyses
                # re-run on the deserialized executable, but the
                # ORIGINAL compile cost only survives in the header's
                # perf block (docs/perfscope.md amortization)
                scope.record_executable(tag, fn, source="disk",
                                        header_perf=header.get("perf"))
        return fn

    def store(self, key: str, compiled, *, program: str = "",
              arg_sig: str = "", tag: str | None = None,
              donate_sig: str = "", perf: dict | None = None) -> str | None:
        """Serialize + publish one compiled executable (atomic), then
        enforce the LRU budget. The header records the key's derivation
        components so `--verify` can re-derive it offline.

        Write-time self-check: the payload is loaded BACK through the
        exact read path before it may publish. Not paranoia — an
        executable that was itself served from XLA's persistent
        compilation cache re-serializes WITHOUT its jitted symbols on
        CPU (deserialize dies with "Symbols not found"), and a cache
        that publishes dead entries would reject-and-recompile on every
        future boot forever. A failed check counts
        `arbius_aot_cache_skips_total`, journals `aot_cache_skip`, and
        publishes nothing: the next life simply compiles again."""
        from jax.experimental.serialize_executable import (
            deserialize_and_load,
            serialize,
        )

        from arbius_tpu.obs import current_obs

        obs = current_obs()
        try:
            serialized, in_tree, out_tree = serialize(compiled)
            payload = pickle.dumps((serialized, in_tree, out_tree))
            s2, it2, ot2 = pickle.loads(payload)
            deserialize_and_load(s2, it2, ot2)
        except Exception as e:  # noqa: BLE001 — an unserializable
            # executable, or a load-back failure: the entry would be
            # dead on arrival (and the solve must proceed regardless)
            if obs is not None:
                obs.registry.counter("arbius_aot_cache_skips_total",
                                     _SKIPS_HELP).inc()
                obs.event("aot_cache_skip", key=key, tag=tag,
                          reason=f"{type(e).__name__}: "
                                 f"{str(e)[:120]}")
            return None
        header = make_header(key, program, self.env(), arg_sig, payload,
                             tag=tag, donate_sig=donate_sig,
                             layout=self.layout, perf=perf)
        try:
            path = write_entry(self.dir, key, header, payload)
        except OSError as e:
            # a full or read-only shared directory must not fail the
            # solve that just compiled successfully
            if obs is not None:
                obs.registry.counter("arbius_aot_cache_skips_total",
                                     _SKIPS_HELP).inc()
                obs.event("aot_cache_skip", key=key, tag=tag,
                          reason=f"write: {type(e).__name__}: "
                                 f"{str(e)[:120]}")
            return None
        if obs is not None:
            obs.registry.counter("arbius_aot_cache_writes_total",
                                 _WRITES_HELP).inc()
        evicted = evict_lru(self.dir, self.max_bytes, keep=key)
        if evicted and obs is not None:
            obs.registry.counter("arbius_aot_cache_evictions_total",
                                 _EVICT_HELP).inc(len(evicted))
            obs.event("aot_cache_evict", keys=evicted)
        return path

    # -- introspection (boot warm scan, CLI, /debug) ---------------------
    def tags(self) -> frozenset:
        """Every tag recorded in an entry whose environment AND mesh
        layout match THIS cache — the cross-life warm set costsched's
        `warm_boost` counts as warm at boot (docs/scheduler.md). The
        layout filter is what keeps differently-laid-out workers
        sharing one directory honest: a dp2 worker's entries are real
        executables a tp2 worker cannot load, so they must not read as
        warm to it. Header-only reads — no payload hashing at boot; an
        unreadable entry is simply absent (the load path journals the
        reject if a dispatch ever wants it)."""
        env = self.env()
        out = set()
        for _, path, _ in scan(self.dir):
            try:
                header = read_header(path)
            except CacheReject:
                continue
            if header.get("env") == env and header.get("tag") and \
                    header.get("layout", "single") == self.layout:
                out.add(header["tag"])
        return frozenset(out)

    def entries(self) -> list[dict]:
        """[{key, tag, program, payload_len, size}] sorted by key —
        the deterministic listing `tools/aotcache.py` renders."""
        out = []
        for key, path, size in scan(self.dir):
            try:
                header = read_header(path)
            except CacheReject as e:
                out.append({"key": key, "error": e.reason, "size": size})
                continue
            out.append({"key": key, "tag": header.get("tag"),
                        "program": header.get("program"),
                        "payload_len": header.get("payload_len"),
                        "size": size})
        return out

    def stats(self) -> dict:
        rows = scan(self.dir)
        return {"dir": self.dir, "entries": len(rows),
                "total_bytes": sum(s for _, _, s in rows),
                "max_bytes": self.max_bytes}


__all__ = [
    "AotCache", "CacheReject", "args_signature", "derive_key",
    "entry_path", "env_signature", "evict_lru", "make_header",
    "read_entry", "read_header", "scan", "total_bytes", "touch",
    "write_entry",
]
