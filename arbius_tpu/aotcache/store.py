"""On-disk entry format + key derivation for the AOT executable cache.

One cache entry is one file, `<key>.aotx`, fully self-describing:

    AOTC1\n                      magic + format version
    <8-digit header length>\n    decimal, zero-padded
    <header JSON>                sort_keys, utf-8
    <payload bytes>              pickle of (xla bytes, in_tree, out_tree)

The header carries every component the key was derived FROM (program
fingerprint, environment signature, argument/sharding signature,
donation signature) plus the payload's sha256 and length — so
`tools/aotcache.py --verify` can re-derive each entry's key offline and
a corrupted, truncated, or renamed file is detected BEFORE its pickle
is ever touched (`read_entry` hashes the payload against the header
first).

Key derivation (docs/compile-cache.md): the key is sha256 over

    aotc1 | <graphlint canonical program fingerprint>
          | <env: jax, jaxlib, platform, device kind, device count>
          | <args: per-leaf aval + sharding signature>
          | <donation signature>

The program fingerprint is `analysis.graph.fingerprint` over
`jax.make_jaxpr(jitted_fn)(*args)` — exactly the canonicalization the
graphlint goldens pin — so the golden that already defines program
identity IS the cache key: a drifted program hashes to a different key
and misses to a fresh compile; a stale executable is structurally
impossible to load. Everything the fingerprint cannot see (the XLA
build environment, the physical device layout, donation) rides in the
other components.

Concurrency: writes go to a per-process tmp file then `os.replace` —
atomic on POSIX, so fleet workers sharing one cache directory race as
last-writer-wins and a reader can never observe a torn entry (both
writers serialize the SAME program, so either winner is correct).
"""
from __future__ import annotations

import hashlib
import json
import os

MAGIC = b"AOTC1\n"
SUFFIX = ".aotx"
_LEN_DIGITS = 8
KEY_SCHEME = "aotc1"


class CacheReject(ValueError):
    """An entry that must not be loaded (corrupt/truncated/mismatched).

    Carries `reason` — the journaled `aot_cache_reject` event's label —
    so rejects are diagnosable from the flight recorder."""

    def __init__(self, reason: str, detail: str = ""):
        self.reason = reason
        super().__init__(f"{reason}: {detail}" if detail else reason)


# -- key derivation ----------------------------------------------------------

def env_signature() -> dict:
    """The execution environment a serialized executable is only valid
    for: jax/jaxlib versions (lowering + runtime ABI), backend platform
    and device kind (a cpu executable must never load on tpu, a v4
    executable never on v5p), and the visible device count (device
    assignment is baked into the compiled program)."""
    import jax
    import jaxlib

    dev = jax.devices()[0]
    return {
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "platform": dev.platform,
        "device_kind": getattr(dev, "device_kind", ""),
        "device_count": jax.device_count(),
    }


def args_signature(args) -> str:
    """Per-leaf aval + sharding digest of the dispatch arguments. The
    program fingerprint already captures jit-level in_shardings; this
    covers what it cannot — the COMMITTED placement of the concrete
    arguments (and their tree structure), so two call sites tracing the
    same program over differently-placed operands key separately."""
    import jax

    h = hashlib.sha256()
    leaves, treedef = jax.tree_util.tree_flatten(args)
    h.update(str(treedef).encode("utf-8"))
    for leaf in leaves:
        shape = getattr(leaf, "shape", ())
        dtype = getattr(leaf, "dtype", type(leaf).__name__)
        sharding = getattr(leaf, "sharding", None)
        h.update(f"{dtype}:{tuple(shape)}:{sharding}\n".encode("utf-8"))
    return h.hexdigest()


def derive_key(program_fingerprint: str, env: dict, arg_sig: str,
               donate_sig: str = "") -> str:
    """The content address: sha256 over the four identity components.
    Pure over its inputs — `tools/aotcache.py --verify` re-derives it
    from a stored header with no jax tracing involved."""
    material = "|".join([
        KEY_SCHEME, program_fingerprint,
        json.dumps(env, sort_keys=True), arg_sig, donate_sig])
    return hashlib.sha256(material.encode("utf-8")).hexdigest()


def make_header(key: str, program_fingerprint: str, env: dict,
                arg_sig: str, payload: bytes, *, tag: str | None = None,
                donate_sig: str = "", layout: str = "single",
                perf: dict | None = None) -> dict:
    # `layout` is advisory metadata for the warm SCAN only (the mesh
    # layout of the writer's solve programs — docs/multichip.md
    # mesh_tag): the cache KEY already separates layouts through the
    # fingerprint + arg shardings, but a scan cannot trace, so without
    # this field a tp2 worker would count a dp2 worker's entries as
    # disk-warm and boost exactly the buckets it cannot load.
    # `perf` (optional, docs/perfscope.md) is the writer's PerfCard
    # block — flops/bytes/HBM sizes and the ORIGINAL compile seconds —
    # so a disk-hit life amortizes the real compile cost instead of
    # pretending a deserialize was free. Advisory like `layout`: it is
    # NOT part of the key, and absent on pre-perfscope entries.
    header = {
        "format": 1,
        "key": key,
        "program": program_fingerprint,
        "env": dict(env),
        "arg_sig": arg_sig,
        "donate_sig": donate_sig,
        "tag": tag,
        "layout": layout,
        "payload_sha256": hashlib.sha256(payload).hexdigest(),
        "payload_len": len(payload),
    }
    if perf is not None:
        header["perf"] = dict(perf)
    return header


# -- file format -------------------------------------------------------------

def pack_entry(header: dict, payload: bytes) -> bytes:
    hdr = json.dumps(header, sort_keys=True).encode("utf-8")
    return b"".join([MAGIC, f"{len(hdr):0{_LEN_DIGITS}d}\n".encode(),
                     hdr, payload])


def entry_path(cache_dir: str, key: str) -> str:
    return os.path.join(cache_dir, key + SUFFIX)


def write_entry(cache_dir: str, key: str, header: dict,
                payload: bytes) -> str:
    """Atomic publish: write to a per-process tmp name, fsync, then
    `os.replace` onto the final name. Two fleet workers racing on one
    key are last-writer-wins and every reader sees a complete entry."""
    os.makedirs(cache_dir, exist_ok=True)
    path = entry_path(cache_dir, key)
    tmp = os.path.join(cache_dir, f".{key}.{os.getpid()}.tmp")
    blob = pack_entry(header, payload)
    with open(tmp, "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def _parse(buf, path: str) -> tuple[dict, object]:
    """(header, payload view) from a whole-entry buffer; raises
    CacheReject on any structural problem. The payload's sha256 is
    verified BEFORE the caller may unpickle it — garbage never reaches
    the deserializer. Every intermediate view is released on failure
    (a raised exception's traceback would otherwise pin an export into
    the caller's mmap and make its close() fail)."""
    view = memoryview(buf)
    payload = None
    ok = False
    try:
        hdr_start = len(MAGIC) + _LEN_DIGITS + 1
        if bytes(view[:len(MAGIC)]) != MAGIC:
            raise CacheReject("bad_magic", path)
        try:
            hdr_len = int(bytes(view[len(MAGIC):hdr_start - 1]))
        except ValueError:
            raise CacheReject("bad_header_length", path) from None
        body = hdr_start + hdr_len
        try:
            header = json.loads(
                bytes(view[hdr_start:body]).decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            raise CacheReject("bad_header", path) from None
        payload = view[body:]
        if len(payload) != header.get("payload_len"):
            raise CacheReject(
                "truncated", f"{path}: {len(payload)} bytes, header "
                f"says {header.get('payload_len')}")
        if hashlib.sha256(payload).hexdigest() != \
                header.get("payload_sha256"):
            raise CacheReject("payload_digest_mismatch", path)
        ok = True
        return header, payload
    finally:
        if not ok and payload is not None:
            payload.release()
        view.release()  # the payload slice references the base buffer,
        # not this view, so releasing it here is always safe


def read_entry(path: str) -> tuple[dict, object, object]:
    """(header, payload view, closer) — the payload is an mmap-backed
    memoryview (no copy of a multi-hundred-MB executable blob onto the
    heap just to hash it); call `closer()` once done with the view.
    Raises CacheReject on anything that must not be deserialized."""
    import mmap

    try:
        f = open(path, "rb")
    except OSError as e:
        raise CacheReject("unreadable", f"{path}: {e}") from None
    try:
        try:
            mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
        except (ValueError, OSError) as e:  # empty file / mmap failure
            raise CacheReject("unreadable", f"{path}: {e}") from None
        try:
            header, payload = _parse(mm, path)
        except CacheReject:
            mm.close()
            raise

        def closer(mm=mm, payload=payload):
            # the payload view exports a pointer into the mmap — it must
            # release first or mm.close() raises BufferError
            payload.release()
            mm.close()

        return header, payload, closer
    finally:
        f.close()  # the mmap keeps its own reference to the file


def read_header(path: str) -> dict:
    """Header only — the CHEAP read for warm-set scans and listings:
    parses magic + header JSON and stat-checks the payload LENGTH, but
    does NOT hash the payload (a boot scan over a shared cache of
    multi-hundred-MB executables must not re-digest gigabytes to
    collect tag strings). The load path (`read_entry`) still verifies
    the digest before anything is unpickled, and `--verify` audits it
    offline — a silently bit-flipped payload is caught exactly where
    it matters."""
    hdr_start = len(MAGIC) + _LEN_DIGITS + 1
    try:
        size = os.stat(path).st_size
        with open(path, "rb") as f:
            head = f.read(hdr_start)
            if len(head) < hdr_start or head[:len(MAGIC)] != MAGIC:
                raise CacheReject("bad_magic", path)
            try:
                hdr_len = int(head[len(MAGIC):hdr_start - 1])
            except ValueError:
                raise CacheReject("bad_header_length", path) from None
            raw = f.read(hdr_len)
    except OSError as e:
        raise CacheReject("unreadable", f"{path}: {e}") from None
    if len(raw) < hdr_len:
        raise CacheReject("bad_header", path)
    try:
        header = json.loads(raw.decode("utf-8"))
    except (ValueError, UnicodeDecodeError):
        raise CacheReject("bad_header", path) from None
    if size - hdr_start - hdr_len != header.get("payload_len"):
        raise CacheReject(
            "truncated", f"{path}: {size - hdr_start - hdr_len} payload "
            f"bytes, header says {header.get('payload_len')}")
    return header


# -- directory-level operations ---------------------------------------------

def scan(cache_dir: str) -> list[tuple[str, str, int]]:
    """[(key, path, size)] for every entry file, sorted by key —
    deterministic regardless of filesystem enumeration order."""
    try:
        names = sorted(os.listdir(cache_dir))
    except OSError:
        return []
    out = []
    for name in names:
        if not name.endswith(SUFFIX) or name.startswith("."):
            continue
        path = os.path.join(cache_dir, name)
        try:
            size = os.stat(path).st_size
        except OSError:
            continue  # evicted/replaced under our feet: not an error
        out.append((name[:-len(SUFFIX)], path, size))
    return out


def total_bytes(cache_dir: str) -> int:
    return sum(size for _, _, size in scan(cache_dir))


def evict_lru(cache_dir: str, max_bytes: int,
              keep: str | None = None) -> list[str]:
    """Delete least-recently-used entries (st_mtime order, name as the
    tiebreak) until the directory fits `max_bytes`. `keep` protects the
    just-written key — a cache whose budget is smaller than one entry
    degrades to holding that one entry rather than thrashing it.
    Returns the evicted keys, oldest first."""
    if max_bytes <= 0:
        return []
    entries = []
    for key, path, size in scan(cache_dir):
        try:
            mtime = os.stat(path).st_mtime
        except OSError:
            continue
        entries.append((mtime, key, path, size))
    total = sum(e[3] for e in entries)
    evicted: list[str] = []
    for mtime, key, path, size in sorted(entries):
        if total <= max_bytes:
            break
        if key == keep:
            continue
        try:
            os.remove(path)
        except OSError:
            continue  # another worker evicted it first
        total -= size
        evicted.append(key)
    return evicted


def touch(path: str) -> None:
    """Best-effort LRU bump on a load hit (mtime is the eviction
    clock; a read-only shared cache directory just stays untouched)."""
    try:
        os.utime(path, None)
    except OSError:
        pass
