// arbius native codec core — deterministic DEFLATE (fixed Huffman).
//
// Byte-identical by specification to arbius_tpu/codecs/deflate.py (see its
// module docstring for the spec). The Python module is the readable
// reference; this is the hot path the node uses to encode PNG/IDAT for
// every solved task. Cross-equivalence is asserted by
// tests/test_codecs.py::test_native_matches_python.
//
// Build: g++ -O2 -shared -fPIC -o build/libarbius_codecs.so codecs.cc
// (done automatically by arbius_tpu/codecs/_native.py on first import).

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

constexpr int kMinMatch = 3;
constexpr int kMaxMatch = 258;
constexpr int kWindow = 32768;
constexpr int kMaxChain = 32;
constexpr int kHashBits = 15;

struct LenEntry { uint16_t code; uint8_t extra; uint16_t base; };
constexpr LenEntry kLenBases[] = {
    {257,0,3},{258,0,4},{259,0,5},{260,0,6},{261,0,7},{262,0,8},{263,0,9},
    {264,0,10},{265,1,11},{266,1,13},{267,1,15},{268,1,17},{269,2,19},
    {270,2,23},{271,2,27},{272,2,31},{273,3,35},{274,3,43},{275,3,51},
    {276,3,59},{277,4,67},{278,4,83},{279,4,99},{280,4,115},{281,5,131},
    {282,5,163},{283,5,195},{284,5,227},{285,0,258},
};
struct DistEntry { uint16_t code; uint8_t extra; uint16_t base; };
constexpr DistEntry kDistBases[] = {
    {0,0,1},{1,0,2},{2,0,3},{3,0,4},{4,1,5},{5,1,7},{6,2,9},{7,2,13},
    {8,3,17},{9,3,25},{10,4,33},{11,4,49},{12,5,65},{13,5,97},{14,6,129},
    {15,6,193},{16,7,257},{17,7,385},{18,8,513},{19,8,769},{20,9,1025},
    {21,9,1537},{22,10,2049},{23,10,3073},{24,11,4097},{25,11,6145},
    {26,12,8193},{27,12,12289},{28,13,16385},{29,13,24577},
};

struct BitWriter {
  uint8_t* out;
  size_t cap;
  size_t pos = 0;
  uint32_t acc = 0;
  int nbits = 0;
  bool overflow = false;

  void bits(uint32_t value, int n) {
    acc |= value << nbits;
    nbits += n;
    while (nbits >= 8) {
      if (pos >= cap) { overflow = true; return; }
      out[pos++] = static_cast<uint8_t>(acc & 0xFF);
      acc >>= 8;
      nbits -= 8;
    }
  }
  void huff(uint32_t code, int n) {
    uint32_t rev = 0;
    for (int i = 0; i < n; i++) { rev = (rev << 1) | (code & 1); code >>= 1; }
    bits(rev, n);
  }
  size_t finish() {
    if (nbits) {
      if (pos >= cap) { overflow = true; return 0; }
      out[pos++] = static_cast<uint8_t>(acc & 0xFF);
      acc = 0; nbits = 0;
    }
    return pos;
  }
};

inline void fixed_litlen(int sym, uint32_t* code, int* n) {
  if (sym <= 143)      { *code = 0x30 + sym;          *n = 8; }
  else if (sym <= 255) { *code = 0x190 + (sym - 144); *n = 9; }
  else if (sym <= 279) { *code = sym - 256;           *n = 7; }
  else                 { *code = 0xC0 + (sym - 280);  *n = 8; }
}

inline uint32_t hash3(const uint8_t* d, size_t i) {
  uint32_t word = (uint32_t(d[i]) << 16) | (uint32_t(d[i + 1]) << 8) | d[i + 2];
  return (word * 2654435761u) >> (32 - kHashBits);
}

}  // namespace

extern "C" {

// Returns bytes written, or 0 if out_cap is too small.
size_t arbius_deflate_fixed(const uint8_t* data, size_t n,
                            uint8_t* out, size_t out_cap) {
  BitWriter w{out, out_cap};
  w.bits(1, 1);  // BFINAL
  w.bits(1, 2);  // BTYPE=01

  std::vector<int64_t> head(size_t(1) << kHashBits, -1);
  std::vector<int64_t> prev(kWindow, -1);

  // length -> (code, extra bits, extra value base) lookup
  static uint16_t len_code[kMaxMatch + 1];
  static uint8_t len_extra[kMaxMatch + 1];
  static uint16_t len_base[kMaxMatch + 1];
  static bool init = false;
  if (!init) {
    for (int length = kMinMatch; length <= kMaxMatch; length++) {
      for (int i = int(sizeof(kLenBases) / sizeof(LenEntry)) - 1; i >= 0; i--) {
        if (length >= kLenBases[i].base) {
          len_code[length] = kLenBases[i].code;
          len_extra[length] = kLenBases[i].extra;
          len_base[length] = kLenBases[i].base;
          break;
        }
      }
    }
    init = true;
  }

  size_t i = 0;
  while (i < n) {
    int match_len = 0;
    int64_t match_dist = 0;
    if (i + kMinMatch <= n) {
      int64_t cand = head[hash3(data, i)];
      int chain = 0;
      int limit = int(n - i < size_t(kMaxMatch) ? n - i : kMaxMatch);
      while (cand >= 0 && int64_t(i) - cand <= kWindow && chain < kMaxChain) {
        if (match_len == 0 ||
            (match_len < limit && data[cand + match_len] == data[i + match_len])) {
          int length = 0;
          while (length < limit && data[cand + length] == data[i + length])
            length++;
          if (length > match_len) {
            match_len = length;
            match_dist = int64_t(i) - cand;
            if (length == limit) break;
          }
        }
        cand = prev[cand % kWindow];
        chain++;
      }
    }
    if (match_len >= kMinMatch) {
      uint32_t code; int cn;
      fixed_litlen(len_code[match_len], &code, &cn);
      w.huff(code, cn);
      if (len_extra[match_len])
        w.bits(uint32_t(match_len - len_base[match_len]), len_extra[match_len]);
      int di = int(sizeof(kDistBases) / sizeof(DistEntry)) - 1;
      while (match_dist < kDistBases[di].base) di--;
      w.huff(kDistBases[di].code, 5);
      if (kDistBases[di].extra)
        w.bits(uint32_t(match_dist - kDistBases[di].base), kDistBases[di].extra);
      size_t end = i + match_len;
      while (i < end) {
        if (i + kMinMatch <= n) {
          uint32_t h = hash3(data, i);
          prev[i % kWindow] = head[h];
          head[h] = int64_t(i);
        }
        i++;
      }
    } else {
      uint32_t code; int cn;
      fixed_litlen(data[i], &code, &cn);
      w.huff(code, cn);
      if (i + kMinMatch <= n) {
        uint32_t h = hash3(data, i);
        prev[i % kWindow] = head[h];
        head[h] = int64_t(i);
      }
      i++;
    }
    if (w.overflow) return 0;
  }
  uint32_t code; int cn;
  fixed_litlen(256, &code, &cn);
  w.huff(code, cn);
  size_t written = w.finish();
  return w.overflow ? 0 : written;
}

}  // extern "C"
