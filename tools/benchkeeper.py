#!/usr/bin/env python
"""benchkeeper — merge the per-PR BENCH_r*.json files into one
canonical, stage-keyed BENCH_TRAJECTORY.json.

Every PR's bench run writes a BENCH_r<NN>.json at the repo root, in
one of three historical shapes (driver-era `{"n", "rc", "parsed"}`,
single-stage `{"ok", "stage", "result"}`, multi-stage
`{"round", "stages": {...}}`). Nothing aggregated them, so the bench
trajectory — the thing the per-PR files exist to build — stayed
empty. benchkeeper normalizes all three shapes into one schema and
emits a stage-keyed series:

    {
      "version": 1,
      "rounds": [4, 7, ...],          # rounds contributing any entry
      "skipped": [{"round": 1, "reason": "..."}],
      "stages": {
        "sched_ab": [{"round": 7, "metric": ..., "value": ...,
                      "unit": ..., "platform": ..., "vs_baseline": ...,
                      "elapsed_s": ...}, ...]   # sorted by round
      }
    }

    python tools/benchkeeper.py                   # write BENCH_TRAJECTORY.json
    python tools/benchkeeper.py --dir . --json    # print, write nothing
    python tools/benchkeeper.py --check           # drift audit (CI)

**Schema validation**: every headline entry must carry a string
`metric`, a numeric `value`, and a string `unit` — a malformed file
raises BENCH801 naming the file and field. **--check** re-derives the
trajectory and raises BENCH802 when the committed file differs —
"someone landed a BENCH round without regenerating the trajectory" is
a finding, not silence. Output is byte-deterministic for a fixed file
set (files sort by round, stages by name, keys sorted) — pinned
against the goldens in tests/fixtures/benchkeeper/.

Exit codes follow the shared lint contract (0 clean / 1 findings /
2 usage); `--json` prints the trajectory document itself (the
findings document still goes to stderr rendering in --check mode).
"""
from __future__ import annotations

import json
import os
import re
import sys

from _common import EXIT_CLEAN, EXIT_USAGE, lint_main

TRAJECTORY = "BENCH_TRAJECTORY.json"
_BENCH_RE = re.compile(r"BENCH_r(\d+)\.json\Z")


def _finding(path: str, rule: str, message: str, line: int = 0):
    from arbius_tpu.analysis.core import Finding

    return Finding(path=path, line=line, col=0, rule=rule,
                   severity="error", message=message,
                   snippet=os.path.basename(path))


def _entry(rnd: int, result: dict, platform, fname: str,
           findings: list) -> tuple[str, dict] | None:
    """(stage, schema-checked series entry) from one headline result
    dict, or None (with a BENCH801 finding) when the schema is off."""
    stage = result.get("stage")
    if not isinstance(stage, str) or not stage:
        findings.append(_finding(
            fname, "BENCH801",
            "headline result has no string `stage` — benchkeeper "
            "cannot key the series (docs/benchmarks.md)"))
        return None
    for field, types in (("metric", str), ("unit", str),
                         ("value", (int, float))):
        if not isinstance(result.get(field), types):
            findings.append(_finding(
                fname, "BENCH801",
                f"stage {stage!r}: headline `{field}` is "
                f"{type(result.get(field)).__name__}, expected "
                f"{types if isinstance(types, type) else 'number'}"))
            return None
    entry = {
        "round": rnd,
        "metric": result["metric"],
        "value": result["value"],
        "unit": result["unit"],
        "platform": platform,
        "vs_baseline": result.get("vs_baseline"),
        "elapsed_s": result.get("elapsed_s"),
    }
    return stage, entry


def merge_bench_files(dirpath: str) -> tuple[dict, list]:
    """(trajectory document, BENCH801 findings) from every
    BENCH_r*.json under `dirpath`. Deterministic: files sort by round
    number, never by filesystem order."""
    files = []
    for fname in os.listdir(dirpath):
        m = _BENCH_RE.match(fname)
        if m:
            files.append((int(m.group(1)), fname))
    files.sort()
    findings: list = []
    stages: dict[str, list] = {}
    skipped: list[dict] = []
    rounds: set[int] = set()
    for rnd, fname in files:
        path = os.path.join(dirpath, fname)
        try:
            with open(path, encoding="utf-8") as fh:
                doc = json.load(fh)
        except (OSError, ValueError) as e:
            findings.append(_finding(fname, "BENCH801",
                                     f"unreadable bench file: {e}"))
            continue
        if not isinstance(doc, dict):
            findings.append(_finding(fname, "BENCH801",
                                     "bench file is not a JSON object"))
            continue
        if "round" in doc and doc["round"] != rnd:
            findings.append(_finding(
                fname, "BENCH801",
                f"file says round {doc['round']} but the filename says "
                f"{rnd} — a misnamed (or miscopied) bench round"))
            continue
        pairs: list[tuple[str, dict]] = []
        if "stages" in doc:                      # multi-stage (r14+)
            for name in sorted(doc["stages"]):
                block = doc["stages"][name] or {}
                res = block.get("result") or {}   # tolerate null
                pair = _entry(rnd, dict(res, stage=res.get("stage",
                                                           name)),
                              block.get("platform"), fname, findings)
                if pair is not None:
                    pairs.append(pair)
        elif "result" in doc:                    # single-stage
            pair = _entry(rnd, doc.get("result") or {},
                          doc.get("platform"), fname, findings)
            if pair is not None:
                pairs.append(pair)
        elif "parsed" in doc:                    # driver-era
            if doc.get("parsed"):
                pair = _entry(rnd, doc["parsed"], None, fname,
                              findings)
                if pair is not None:
                    pairs.append(pair)
            else:
                skipped.append({
                    "round": rnd,
                    "reason": "no parsed result "
                              f"(driver rc={doc.get('rc')})"})
        else:
            findings.append(_finding(
                fname, "BENCH801",
                "unrecognized bench shape: none of stages/result/"
                "parsed present"))
        for stage, entry in pairs:
            stages.setdefault(stage, []).append(entry)
            rounds.add(rnd)
    for series in stages.values():
        series.sort(key=lambda e: e["round"])
    doc = {
        "version": 1,
        "rounds": sorted(rounds),
        "skipped": sorted(skipped, key=lambda s: s["round"]),
        "stages": {k: stages[k] for k in sorted(stages)},
    }
    return doc, findings


def render_trajectory(doc: dict) -> str:
    return json.dumps(doc, indent=1, sort_keys=True) + "\n"


def build_arg_parser(p):
    p.add_argument("--dir", default=".",
                   help="directory holding BENCH_r*.json (default: .)")
    p.add_argument("--out", default=None,
                   help=f"output path (default: <dir>/{TRAJECTORY})")
    p.add_argument("--check", action="store_true",
                   help="verify the committed trajectory matches a "
                        "regeneration (BENCH802 on drift); writes "
                        "nothing")
    p.add_argument("--json", action="store_true",
                   help="print the trajectory document to stdout "
                        "instead of writing it")
    return p


def collect(ns):
    if not os.path.isdir(ns.dir):
        print(f"benchkeeper: {ns.dir!r} is not a directory",
              file=sys.stderr)
        return EXIT_USAGE, []
    doc, findings = merge_bench_files(ns.dir)
    text = render_trajectory(doc)
    out_path = ns.out or os.path.join(ns.dir, TRAJECTORY)
    if ns.check:
        try:
            with open(out_path, encoding="utf-8") as fh:
                committed = fh.read()
        except OSError:
            committed = None
        if committed != text:
            findings.append(_finding(
                os.path.basename(out_path), "BENCH802",
                "committed trajectory does not match a regeneration "
                "from the BENCH_r*.json set — re-run "
                "`python tools/benchkeeper.py` and commit the result"))
        return None, findings
    # write/print modes: the trajectory document owns stdout, so
    # schema findings render to stderr and only set the exit code
    from _common import EXIT_FINDINGS

    for f in findings:
        print(f.text(), file=sys.stderr)
    if ns.json:
        sys.stdout.write(text)
        return (EXIT_FINDINGS if findings else EXIT_CLEAN), []
    with open(out_path, "w", encoding="utf-8", newline="\n") as fh:
        fh.write(text)
    n = sum(len(s) for s in doc["stages"].values())
    print(f"benchkeeper: wrote {out_path} ({n} entries across "
          f"{len(doc['stages'])} stage(s), {len(doc['skipped'])} "
          "round(s) skipped)", file=sys.stderr)
    return (EXIT_FINDINGS if findings else EXIT_CLEAN), []


def render(ns, findings, out):
    from arbius_tpu.analysis.cli import render_json

    if ns.json:
        render_json(findings, out)
        return
    for f in findings:
        out.write(f.text() + "\n")
    if findings:
        out.write(f"benchkeeper: {len(findings)} finding(s)\n")


def main(argv=None) -> int:
    return lint_main("benchkeeper", __doc__, build_arg_parser, collect,
                     render, argv)


if __name__ == "__main__":
    sys.exit(main())
