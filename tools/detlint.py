#!/usr/bin/env python
"""detlint — determinism & concurrency static analysis over the tree.

Pre-commit / CI front door for `arbius_tpu.analysis` (the rule catalog
lives in docs/static-analysis.md):

    python tools/detlint.py                      # lint arbius_tpu/
    python tools/detlint.py --json arbius_tpu    # stable JSON report
    python tools/detlint.py --baseline-update    # regenerate baseline
    python tools/detlint.py --select DET101 node # one rule, one dir

Exit codes: 0 clean / 1 findings / 2 usage error — safe to wire
directly into a pre-commit hook or CI step. A per-rule finding summary
is printed to stderr after the report (same aligned-table helper the
obs_dump metrics view uses). The whole main loop is tools/_common.py's
`lint_main` — graphlint.py is the same shell over the graph auditor.
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _common import lint_main

from arbius_tpu.analysis.cli import build_arg_parser, collect, render


def main(argv=None) -> int:
    return lint_main("detlint", __doc__, build_arg_parser, collect, render,
                     argv)


if __name__ == "__main__":
    sys.exit(main())
