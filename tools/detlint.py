#!/usr/bin/env python
"""detlint — determinism & concurrency static analysis over the tree.

Pre-commit / CI front door for `arbius_tpu.analysis` (the rule catalog
lives in docs/static-analysis.md):

    python tools/detlint.py                      # lint arbius_tpu/
    python tools/detlint.py --json arbius_tpu    # stable JSON report
    python tools/detlint.py --baseline-update    # regenerate baseline
    python tools/detlint.py --select DET101 node # one rule, one dir

Exit codes: 0 clean / 1 findings / 2 usage error — safe to wire
directly into a pre-commit hook or CI step. A per-rule finding summary
is printed to stderr after the report (same aligned-table helper the
obs_dump metrics view uses).
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _common import kv_table, make_parser

from arbius_tpu.analysis.cli import build_arg_parser, collect, render


def main(argv=None) -> int:
    parser = build_arg_parser(make_parser("detlint", __doc__))
    try:
        ns = parser.parse_args(argv)
    except SystemExit as e:
        return int(e.code or 0)
    rc, findings = collect(ns)
    if rc is not None:
        return rc
    render(ns, findings, sys.stdout)
    if findings and not ns.json:
        # quick triage view: which rules are firing, how often
        counts: dict[str, int] = {}
        for f in findings:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        print("\nfindings by rule:\n" + kv_table(counts), file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
