"""Shared arg/output plumbing for the operator tools in tools/.

Every tool renders terminal tables and builds its parser the same way,
so the formatting lives once here (obs_dump.py, detlint.py and
graphlint.py are the customers; new tools should start from these):

    make_parser(prog, doc)   argparse.ArgumentParser with the tool's
                             module docstring as raw description
    kv_table(mapping)        aligned `key  value` lines, keys sorted,
                             floats rendered %.6g — the obs metrics view
                             and the lint per-rule summaries
    lint_main(...)           the whole linter-tool main(): parse,
                             collect, render, per-rule stderr summary,
                             exit-code mapping

The lint exit-code contract (0 clean / 1 findings / 2 usage) and the
stable JSON report document are defined ONCE, in
`arbius_tpu.analysis.cli`, and re-exported here so the tools and the
`python -m` module entry points cannot drift apart — detlint.py and
graphlint.py are both ~10-line shells over `lint_main`.
"""
from __future__ import annotations

import argparse
import os
import sys

# `python tools/<tool>.py` puts tools/ (not the repo root) on sys.path;
# the shared contract below lives in the package, so resolve the root
# here once instead of in every tool
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from arbius_tpu.analysis.cli import (  # noqa: F401,E402 — re-exported contract
    EXIT_CLEAN,
    EXIT_FINDINGS,
    EXIT_USAGE,
    render_json as emit_json_report,
)


def make_parser(prog: str, doc: str | None) -> argparse.ArgumentParser:
    return argparse.ArgumentParser(
        prog=prog, description=doc,
        formatter_class=argparse.RawDescriptionHelpFormatter)


def kv_table(mapping: dict) -> str:
    """Aligned key/value table, keys sorted for stable output."""
    if not mapping:
        return ""
    width = max(len(str(k)) for k in mapping)
    lines = []
    for k in sorted(mapping):
        v = mapping[k]
        if isinstance(v, float):
            v = f"{v:.6g}"
        lines.append(f"{str(k).ljust(width)}  {v}")
    return "\n".join(lines)


def lint_main(prog: str, doc: str | None, build_arg_parser, collect,
              render, argv=None) -> int:
    """The one linter-tool main loop. `build_arg_parser`/`collect`/
    `render` are the module CLI's own functions (arbius_tpu.analysis.cli
    or .graph.cli), so tool and `python -m` module stay behavior-
    identical; this adds only the tool niceties (docstring help, the
    per-rule triage table on stderr) around the shared exit contract."""
    parser = build_arg_parser(make_parser(prog, doc))
    try:
        ns = parser.parse_args(argv)
    except SystemExit as e:
        # argparse exits 2 on usage error, 0 on --help — preserve both
        return int(e.code or 0)
    rc, findings = collect(ns)
    if rc is not None:
        return rc
    render(ns, findings, sys.stdout)
    if findings and not ns.json:
        # quick triage view: which rules are firing, how often
        counts: dict[str, int] = {}
        for f in findings:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        print("\nfindings by rule:\n" + kv_table(counts), file=sys.stderr)
    return EXIT_FINDINGS if findings else EXIT_CLEAN
