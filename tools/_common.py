"""Shared arg/output plumbing for the operator tools in tools/.

Every tool renders terminal tables and builds its parser the same way,
so the formatting lives once here (obs_dump.py and detlint.py are the
customers; new tools should start from these):

    make_parser(prog, doc)   argparse.ArgumentParser with the tool's
                             module docstring as raw description
    kv_table(mapping)        aligned `key  value` lines, keys sorted,
                             floats rendered %.6g — the obs metrics view
                             and the detlint per-rule summary
"""
from __future__ import annotations

import argparse


def make_parser(prog: str, doc: str | None) -> argparse.ArgumentParser:
    return argparse.ArgumentParser(
        prog=prog, description=doc,
        formatter_class=argparse.RawDescriptionHelpFormatter)


def kv_table(mapping: dict) -> str:
    """Aligned key/value table, keys sorted for stable output."""
    if not mapping:
        return ""
    width = max(len(str(k)) for k in mapping)
    lines = []
    for k in sorted(mapping):
        v = mapping[k]
        if isinstance(v, float):
            v = f"{v:.6g}"
        lines.append(f"{str(k).ljust(width)}  {v}")
    return "\n".join(lines)
