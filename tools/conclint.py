#!/usr/bin/env python
"""conclint — whole-node thread-topology + lockset race audit.

Pre-commit / CI front door for `arbius_tpu.analysis.conc` (the CONC4xx
rule catalog and the thread-topology model live in
docs/concurrency.md):

    python tools/conclint.py                      # audit arbius_tpu/
    python tools/conclint.py --json               # stable JSON report
    python tools/conclint.py --baseline-update    # regenerate baseline
    python tools/conclint.py --select CONC401     # one rule
    python tools/conclint.py --witness-report w.json   # fold in the
                                                  # simnet runtime witness

Exit codes: 0 clean / 1 findings / 2 usage error — the same lint
contract detlint.py and graphlint.py ship (tools/_common.py `lint_main`
is the whole main loop; this file is the same thin shell).
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _common import lint_main

from arbius_tpu.analysis.conc.cli import build_arg_parser, collect, render


def main(argv=None) -> int:
    return lint_main("conclint", __doc__, build_arg_parser, collect,
                     render, argv)


if __name__ == "__main__":
    sys.exit(main())
