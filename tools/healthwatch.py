#!/usr/bin/env python
"""healthwatch — alert catalog listing + offline fleet alert audit.

Two offline views over the live alert engine (docs/healthwatch.md):

    python tools/healthwatch.py --rules                  # the catalog
    python tools/healthwatch.py --eval <sidecar-dir>     # fleet audit
    python tools/healthwatch.py --eval <sidecar-dir> --json

**--rules** prints the shipped alert catalog (rule id, hysteresis,
signal, summary) at the default `alerts` configuration — the same
catalog OBS501's alert direction holds to docs/observability.md rows.

**--eval** reads a fleetscope sidecar directory (`fleet.sidecar_dir`,
docs/fleetscope.md): every member's persisted registry export carries
its healthwatch gauges (`arbius_alert_state{alert}` +
`arbius_alert_transitions_total{alert}`), so the fleet's alert posture
is auditable after the fact, per member, with no process to talk to.
A member whose snapshot shows a FIRING alert raises:

    HW701  alert firing on a fleet member at its last sidecar flush —
           the node ended (or last flushed) in a known-bad state

Pending/resolved states render in the table but do not fail the audit
(they are hysteresis in motion, not a standing condition). Members
without healthwatch gauges are listed as unwatched — a fleet that
*meant* to run the alert engine sees the gap instead of silence.

Exit codes follow the shared lint contract (0 clean / 1 findings /
2 usage); `--json` emits the same stable findings document every
linter tool does. Output is byte-deterministic for a fixed sidecar
set (members sort by name, alerts by rule id) — tier-1-pinned against
the goldens in tests/fixtures/healthwatch/.
"""
from __future__ import annotations

import sys

from _common import EXIT_CLEAN, EXIT_USAGE, lint_main

STATE_NAMES = {0: "ok", 1: "pending", 2: "firing", 3: "resolved"}


def catalog_lines() -> list[str]:
    """The shipped rule catalog at default config, one line per rule."""
    from arbius_tpu.node.config import AlertsConfig
    from arbius_tpu.obs.healthwatch import default_catalog

    lines = []
    for rule in default_catalog(AlertsConfig()):
        lines.append(f"{rule.name:22s} for_ticks={rule.for_ticks:<3d} "
                     f"signal={rule.signal:14s} {rule.summary}")
    return lines


def eval_sidecars(dirpath: str) -> tuple[list[dict], list]:
    """(per-member alert state rows, HW701 findings) from a fleetscope
    sidecar directory. Rows sort by (member, alert); a member without
    healthwatch gauges yields one `watched: False` row."""
    from arbius_tpu.analysis.core import Finding
    from arbius_tpu.obs.fleetscope import read_sidecars

    rows: list[dict] = []
    findings = []
    for member, export, _events in read_sidecars(dirpath,
                                                 with_events=False):
        metrics = export.get("metrics", {})
        states = metrics.get("arbius_alert_state")
        if states is None:
            rows.append({"member": member, "alert": None,
                         "state": None, "watched": False,
                         "transitions": 0})
            continue
        transitions = {
            key[0]: value for key, value in
            (metrics.get("arbius_alert_transitions_total") or {})
            .get("series", ())}
        for key, value in states.get("series", ()):
            alert = key[0]
            state = STATE_NAMES.get(int(value), f"state-{int(value)}")
            rows.append({"member": member, "alert": alert,
                         "state": state, "watched": True,
                         "transitions": int(transitions.get(alert, 0))})
            if state == "firing":
                findings.append(Finding(
                    path=member, line=0, col=0, rule="HW701",
                    severity="error",
                    message=(f"alert `{alert}` was FIRING at this "
                             "member's last sidecar flush — the node "
                             "ended (or last reported) in a known-bad "
                             "state (docs/healthwatch.md)"),
                    snippet=f"{member}:{alert}"))
    rows.sort(key=lambda r: (r["member"], r["alert"] or ""))
    findings.sort()
    return rows, findings


def build_arg_parser(p):
    p.add_argument("--rules", action="store_true",
                   help="print the shipped alert catalog and exit")
    p.add_argument("--eval", metavar="DIR", default=None,
                   help="audit every member sidecar under DIR "
                        "(fleet.sidecar_dir) — HW701 per firing alert")
    p.add_argument("--json", action="store_true",
                   help="machine-readable findings (eval mode)")
    return p


def collect(ns):
    ns._rows = []
    if ns.rules:
        for line in catalog_lines():
            print(line)
        return EXIT_CLEAN, []
    if not ns.eval:
        print("healthwatch: --rules or --eval <sidecar-dir> is required",
              file=sys.stderr)
        return EXIT_USAGE, []
    try:
        ns._rows, findings = eval_sidecars(ns.eval)
    except (OSError, ValueError) as e:
        print(f"healthwatch: {e}", file=sys.stderr)
        return EXIT_USAGE, []
    return None, findings


def render(ns, findings, out):
    from arbius_tpu.analysis.cli import render_json

    if ns.json:
        render_json(findings, out)
        return
    interesting = [r for r in ns._rows
                   if not r["watched"] or r["state"] != "ok"]
    for r in interesting:
        if not r["watched"]:
            out.write(f"{r['member']:16s} UNWATCHED (no healthwatch "
                      "gauges in this member's snapshot)\n")
        else:
            out.write(f"{r['member']:16s} {r['alert']:22s} "
                      f"{r['state']:9s} transitions="
                      f"{r['transitions']}\n")
    for f in findings:
        out.write(f.text() + "\n")
    watched = sum(1 for r in ns._rows if r["watched"])
    out.write(f"healthwatch: {len(findings)} firing alert(s) across "
              f"{watched} watched state row(s)\n")


def main(argv=None) -> int:
    return lint_main("healthwatch", __doc__, build_arg_parser, collect,
                     render, argv)


if __name__ == "__main__":
    sys.exit(main())
