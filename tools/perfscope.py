#!/usr/bin/env python
"""perfscope — audit perf cards for drift and export Chrome traces.

Two offline views over the perfscope subsystem (docs/perfscope.md):

    python tools/perfscope.py --db miner.db                 # PERF601 audit
    python tools/perfscope.py --db miner.db --json
    python tools/perfscope.py --db miner.db --drift-max 3.0
    python tools/perfscope.py --chrome-trace journal.json   # trace export
    python tools/perfscope.py --chrome-trace --fleet <sidecar-dir>

**Audit** reads the sqlite `perf_cards` table a node persists (joined
against its `cost_model` rows through the shared (model, bucket,
layout, mode) tag) and raises PERF601 when a bucket's drift leaves the
band — the fail-closed "your price model is lying" signal:

    PERF601  observed infer p50 ÷ static roofline outside
             [--drift-min, --drift-max] (default 0.5..2.0), for either
             the card's own observed window or the FITTED cost row
             re-checked against the card's roofline — a mispriced
             bucket fails the audit even when its live window looked
             consistent.

Exit codes follow the shared lint contract (0 clean / 1 findings /
2 usage), and `--json` emits the same stable findings document every
linter tool does.

**--chrome-trace** renders an obs journal (`GET /debug/journal`'s
`{"events": [...]}` JSON, or a bare event list) — or, with `--fleet`,
the federated fleet timeline including cross-process lease hops — as a
Chrome/Perfetto `trace.json`: one process per fleet member, one thread
per span tree (= one task lifecycle), lifecycle events as instants on
their task's track. Byte-deterministic for a fixed journal
(tier-1-pinned golden).
"""
from __future__ import annotations

import json
import sys

from _common import EXIT_CLEAN, EXIT_USAGE, lint_main

# PERF601 policy band (docs/perfscope.md): a healthy bucket's observed
# p50 sits within 2x of its roofline ON THE PEAKS THE CARD WAS BUILT
# WITH; outside it either the roofline peaks are wrong (re-tune the
# perfscope config) or the price model is lying (the finding)
DEFAULT_DRIFT_MIN = 0.5
DEFAULT_DRIFT_MAX = 2.0


def audit_cards(db_path: str, drift_min: float, drift_max: float) -> list:
    """PERF601 findings over a node db's persisted cards + cost rows.
    Deterministic: rows arrive in primary-key order and findings sort
    like every lint report."""
    from arbius_tpu.analysis.core import Finding
    from arbius_tpu.node.db import NodeDB

    db = NodeDB(db_path)
    try:
        cards = db.load_perf_cards()
        cost = {(m, b, l, md): cs
                for m, b, l, md, cs, _n, _u in db.load_cost_rows()}
    finally:
        db.close()
    findings = []

    def breach(key: tuple, ratio: float, what: str) -> None:
        findings.append(Finding(
            path=db_path, line=0, col=0, rule="PERF601",
            severity="error",
            message=(f"{what} drift {ratio:.3f} outside "
                     f"[{drift_min:g}, {drift_max:g}] for "
                     f"{key[0]}|{key[1]}|{key[2]}|{key[3]} — the price "
                     "model and the program's static roofline disagree "
                     "(docs/perfscope.md)"),
            snippet="|".join(key)))

    for model, bucket, layout, mode, card, _updated in cards:
        key = (model, bucket, layout, mode)
        roofline = float(card.get("roofline_seconds") or 0.0)
        drift = card.get("drift_ratio")
        if drift is not None and not (drift_min <= drift <= drift_max):
            breach(key, float(drift), "observed-window")
        chip_s = cost.get(key)
        if chip_s is not None and roofline > 0:
            # the FITTED row re-checked against the card: per-task
            # chip-seconds × the card's canonical batch is the bucket
            # wall the fit claims — a doctored/mispriced row fails
            # closed even when the live window looked fine
            batch = max(1, int(card.get("batch") or 1))
            ratio = (float(chip_s) * batch) / roofline
            if not (drift_min <= ratio <= drift_max):
                breach(key, ratio, "fitted-row")
    findings.sort()
    return findings


def load_journal(path: str) -> list[dict]:
    """`GET /debug/journal`-shaped `{"events": [...]}` or a bare event
    list — both are one journal."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict):
        doc = doc.get("events", [])
    if not isinstance(doc, list):
        raise ValueError(f"{path}: not a journal (expected a list or "
                         '{"events": [...]})')
    return doc


def build_arg_parser(p):
    p.add_argument("--db", help="node sqlite db holding perf_cards + "
                                "cost_model (audit mode)")
    p.add_argument("--drift-min", type=float, default=DEFAULT_DRIFT_MIN,
                   help=f"PERF601 band floor (default {DEFAULT_DRIFT_MIN})")
    p.add_argument("--drift-max", type=float, default=DEFAULT_DRIFT_MAX,
                   help=f"PERF601 band ceiling (default {DEFAULT_DRIFT_MAX})")
    p.add_argument("--chrome-trace", nargs="?", const=True, default=None,
                   metavar="JOURNAL",
                   help="render a journal JSON (or, with --fleet, the "
                        "federated timeline) as a Chrome/Perfetto "
                        "trace.json on stdout")
    p.add_argument("--fleet", metavar="DIR", default=None,
                   help="fleet.sidecar_dir to federate as the journal "
                        "source for --chrome-trace")
    p.add_argument("-o", "--out", default=None,
                   help="write the trace to a file instead of stdout")
    p.add_argument("--json", action="store_true",
                   help="machine-readable findings (audit mode)")
    return p


def collect(ns):
    if ns.chrome_trace is not None:
        from arbius_tpu.obs.perfscope import render_chrome_trace

        try:
            if ns.fleet:
                from arbius_tpu.obs.fleetscope import federate

                events = federate(ns.fleet)["events"]
            elif ns.chrome_trace is True:
                print("--chrome-trace needs a journal file or --fleet "
                      "<sidecar-dir>", file=sys.stderr)
                return EXIT_USAGE, []
            else:
                events = load_journal(ns.chrome_trace)
        except (OSError, ValueError) as e:
            print(f"perfscope: {e}", file=sys.stderr)
            return EXIT_USAGE, []
        out = render_chrome_trace(events)
        if ns.out:
            with open(ns.out, "w") as f:
                f.write(out)
            print(f"perfscope: wrote {ns.out} "
                  f"({len(events)} event(s))", file=sys.stderr)
        else:
            sys.stdout.write(out)
        return EXIT_CLEAN, []
    if not ns.db:
        print("perfscope: --db <node.sqlite> (audit) or --chrome-trace "
              "<journal.json> is required", file=sys.stderr)
        return EXIT_USAGE, []
    if ns.drift_min < 0 or ns.drift_max < ns.drift_min:
        print("perfscope: need 0 <= --drift-min <= --drift-max",
              file=sys.stderr)
        return EXIT_USAGE, []
    try:
        findings = audit_cards(ns.db, ns.drift_min, ns.drift_max)
    except OSError as e:
        print(f"perfscope: {e}", file=sys.stderr)
        return EXIT_USAGE, []
    return None, findings


def render(ns, findings, out):
    from arbius_tpu.analysis.cli import render_json

    if ns.json:
        render_json(findings, out)
        return
    for f in findings:
        out.write(f.text() + "\n")
    out.write(f"perfscope: {len(findings)} finding(s)\n" if findings
              else "perfscope: cards within the drift band\n")


def main(argv=None) -> int:
    return lint_main("perfscope", __doc__, build_arg_parser, collect,
                     render, argv)


if __name__ == "__main__":
    sys.exit(main())
