#!/usr/bin/env python
"""costmodel — inspect and re-fit the learned chip-seconds cost table.

The profit scheduler (docs/scheduler.md) prices every task from the
sqlite `cost_model` table NodeDB persists. This tool reads that table
and re-runs the deterministic seeded fit offline:

    python tools/costmodel.py --db miner.db --dump          # fitted rows
    python tools/costmodel.py --db miner.db --dump --json   # same, JSON
    python tools/costmodel.py --fit snapshot.json           # offline fit
    python tools/costmodel.py --fit snapshot.json --json

`--fit` consumes a histogram snapshot — the stage=infer recent window
as `{"samples": [["<cost tag>", seconds], ...]}` (the format
`GET /metrics`' histogram recent windows dump to, and what
tests/fixtures/costmodel/ pins) — and prints the rows the node would
fit from it. The fit is seeded and deterministic
(arbius_tpu/node/costmodel.py), so output is byte-identical for a
fixed snapshot; tier-1 pins it against a golden fixture.

Exit codes follow the shared tool contract: 0 on success, 2 on usage
errors (tools/_common.py).
"""
from __future__ import annotations

import json
import sys

from _common import EXIT_CLEAN, EXIT_USAGE, kv_table, make_parser

from arbius_tpu.node.costmodel import CostModel  # noqa: E402 (_common fixes path)

# render bound for unbounded bucket spaces (docs/text-serving.md): a
# sequence-bucketed family can accrue (prompt × decode × sampler) rows
# without limit, and an operator's terminal is not where to page them —
# the table caps here and says exactly how much it dropped
RENDER_CAP = 64


def render_rows(rows: list[dict]) -> str:
    """Fixed-format deterministic table, one line per fitted row, capped
    at RENDER_CAP rows (an explicit trailer counts the omitted ones —
    silent truncation would read as "that's everything"). Rows that
    joined a perf card (docs/perfscope.md) grow the static-fact
    columns; card-less snapshots under the cap render the historic
    table byte for byte (the tier-1 fixtures pin that)."""
    if not rows:
        return "(no fitted rows)"
    omitted = 0
    if len(rows) > RENDER_CAP:
        omitted = len(rows) - RENDER_CAP
        rows = rows[:RENDER_CAP]
    head = {"model": "model", "bucket": "bucket", "layout": "layout",
            "mode": "mode", "chip_seconds": "chip_seconds",
            "samples": "samples", "updated": "updated",
            "flops": "flops", "drift_ratio": "drift_ratio",
            "utilization": "utilization"}
    cols = ["model", "bucket", "layout", "mode", "chip_seconds",
            "samples", "updated"]
    if any("flops" in r for r in rows):
        cols += ["flops", "drift_ratio", "utilization"]
        for r in rows:
            for c in ("flops", "drift_ratio", "utilization"):
                r.setdefault(c, "-")
                if r[c] is None:
                    r[c] = "-"

    def cell(row, c):
        v = row[c]
        return f"{v:.6g}" if isinstance(v, float) else str(v)

    widths = {c: max(len(head[c]), *(len(cell(r, c)) for r in rows))
              for c in cols}
    lines = ["  ".join(head[c].ljust(widths[c]) for c in cols)]
    for r in rows:
        lines.append("  ".join(cell(r, c).ljust(widths[c]) for c in cols))
    if omitted:
        lines.append(f"({omitted} more buckets)")
    return "\n".join(ln.rstrip() for ln in lines)


def load_db_rows(db_path: str) -> list[dict]:
    """Fitted rows, each joined against its persisted perf card when
    the db has any (docs/perfscope.md) — flops and utilization next to
    the learned chip-seconds, through the shared (model, bucket,
    layout, mode) tag. A card-less db returns the historic row shape
    untouched."""
    from arbius_tpu.node.costmodel import CostRow
    from arbius_tpu.node.db import NodeDB

    db = NodeDB(db_path)
    try:
        rows = [CostRow(m, b, l, cs, n, up, mode=md).to_json()
                for m, b, l, md, cs, n, up in db.load_cost_rows()]
        cards = {(m, b, l, md): card
                 for m, b, l, md, card, _u in db.load_perf_cards()}
    finally:
        db.close()
    if cards:
        for r in rows:
            card = cards.get((r["model"], r["bucket"], r["layout"],
                              r["mode"]))
            if card is None:
                continue
            r["flops"] = card.get("flops")
            r["drift_ratio"] = card.get("drift_ratio")
            roofline = float(card.get("roofline_seconds") or 0.0)
            bucket_s = r["chip_seconds"] * max(1, int(card.get("batch")
                                                      or 1))
            r["utilization"] = round(roofline / bucket_s, 6) \
                if roofline > 0 and bucket_s > 0 else None
    return rows


def fit_snapshot(path: str, min_samples: int) -> dict:
    """Offline deterministic fit over a histogram snapshot file."""
    with open(path) as f:
        snap = json.load(f)
    model = CostModel(min_samples=min_samples)
    parsed = model.ingest_samples(
        [(tag, float(v)) for tag, v in snap.get("samples", [])])
    model.refit(now=int(snap.get("now", 0)))
    out = model.snapshot()
    out["ingested"] = parsed
    return out


def main(argv=None) -> int:
    p = make_parser("costmodel", __doc__)
    p.add_argument("--db", help="node sqlite db holding the cost_model "
                               "table (for --dump)")
    p.add_argument("--dump", action="store_true",
                   help="print the persisted fitted rows")
    p.add_argument("--fit", metavar="SNAPSHOT",
                   help="re-run the deterministic fit over a histogram "
                        "snapshot JSON file")
    p.add_argument("--min-samples", type=int, default=8,
                   help="min samples before a row predicts (--fit)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable output")
    ns = p.parse_args(argv)

    if bool(ns.dump) == bool(ns.fit):
        print("exactly one of --dump or --fit is required", file=sys.stderr)
        return EXIT_USAGE
    if ns.dump:
        if not ns.db:
            print("--dump needs --db <node.sqlite>", file=sys.stderr)
            return EXIT_USAGE
        rows = load_db_rows(ns.db)
        if ns.json:
            print(json.dumps({"rows": rows}, sort_keys=True, indent=1))
        else:
            print(render_rows(rows))
        return EXIT_CLEAN

    out = fit_snapshot(ns.fit, ns.min_samples)
    if ns.json:
        print(json.dumps(out, sort_keys=True, indent=1))
    else:
        print(render_rows(out["rows"]))
        print("\n" + kv_table({"ingested": out["ingested"],
                               "min_samples": out["min_samples"]}),
              file=sys.stderr)
    return EXIT_CLEAN


if __name__ == "__main__":
    sys.exit(main())
