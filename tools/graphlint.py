#!/usr/bin/env python
"""graphlint — jaxpr/XLA-program audit with golden fingerprints.

Pre-commit / CI front door for `arbius_tpu.analysis.graph` (rule
catalog and fingerprint model in docs/graph-audit.md): traces every
registered pipeline's jittable entry points to jaxprs (abstract shapes,
abstract meshes — CPU-only, seconds), runs the GRAPH4xx rules, and
checks canonical program fingerprints against goldens/graph/.

    python tools/graphlint.py                     # audit everything
    python tools/graphlint.py --json              # stable JSON report
    python tools/graphlint.py --list              # registered spec keys
    python tools/graphlint.py --spec anythingv3   # one model's specs
    python tools/graphlint.py --golden-update     # regenerate goldens

Exit codes: 0 clean / 1 findings (rule hit or fingerprint drift) /
2 usage error — identical contract to detlint.py; both are shells over
tools/_common.py's `lint_main`. Regenerating goldens is a reviewed
operation: goldens/graph/README.md says when it is legitimate.
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _common import lint_main

from arbius_tpu.analysis.graph.cli import build_arg_parser, collect, render


def main(argv=None) -> int:
    return lint_main("graphlint", __doc__, build_arg_parser, collect,
                     render, argv)


if __name__ == "__main__":
    sys.exit(main())
