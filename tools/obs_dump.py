#!/usr/bin/env python
"""obs_dump — pretty-print a miner node's obs state over ControlRPC.

Reads the observability surface a running node serves on its control
RPC port (docs/observability.md) and renders it for a terminal:

    python tools/obs_dump.py metrics                  # JSON metrics view
    python tools/obs_dump.py prom                     # raw Prometheus text
    python tools/obs_dump.py journal [--limit 50] [--kind retry]
    python tools/obs_dump.py journal --taskid 0x<taskid>
    python tools/obs_dump.py trace 0x<taskid>         # span tree

Target selection: --url http://127.0.0.1:<rpc_port> (default port 8080,
matching MiningConfig.example.json's rpc_port). The render functions
are pure (tests drive them against an in-process ControlRPC).

Fleet mode (docs/fleetscope.md): `--fleet <sidecar_dir>` reads the
fleet members' obs SIDECARS instead of a live node — `journal` and
`trace` merge every member's segments into one chain-time-ordered
timeline (each line prefixed with its member), `prom` renders the
federated exposition. Shares the merge code with tools/fleetscope.py.
"""
from __future__ import annotations

import json
import sys
import urllib.request

from _common import kv_table, make_parser


def fetch_json(url: str, timeout: float = 10.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.load(r)


def fetch_text(url: str, timeout: float = 10.0) -> str:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read().decode()


def render_metrics(m: dict) -> str:
    return kv_table(m)


def _event_line(e: dict) -> str:
    kind = e.get("kind", "?")
    core = {k: v for k, v in e.items()
            if k not in ("kind", "seq", "wall", "chain")}
    chain = f" chain={e['chain']}" if "chain" in e else ""
    return (f"#{e.get('seq', '?'):>6} {kind:<16}{chain} "
            + json.dumps(core, sort_keys=True, default=str))


def render_journal(events: list[dict]) -> str:
    return "\n".join(_event_line(e) for e in events)


def render_trace(roots: list[dict], indent: int = 0) -> str:
    """Indented span tree: name, wall duration, chain span, status."""
    out = []
    for sp in roots:
        dur_ms = sp.get("wall_s", 0.0) * 1000.0
        chain = ""
        if "chain_start" in sp and "chain_end" in sp:
            dc = sp["chain_end"] - sp["chain_start"]
            chain = f"  chain+{dc}s" if dc else ""
        status = "" if sp.get("status") == "ok" else \
            f"  !{sp.get('status')}: {sp.get('error', '')}"
        attrs = sp.get("attrs") or {}
        extra = ("  " + json.dumps(attrs, sort_keys=True, default=str)
                 ) if attrs else ""
        out.append(f"{'  ' * indent}{sp.get('name', '?'):<{max(1, 28 - 2 * indent)}}"
                   f" {dur_ms:9.2f} ms{chain}{status}{extra}")
        children = sp.get("children") or []
        if children:
            out.append(render_trace(children, indent + 1))
    return "\n".join(out)


def _fleet_main(ns) -> int:
    """--fleet: the same subcommands over merged sidecars (shared merge
    code: arbius_tpu.obs.fleetscope; docs/fleetscope.md)."""
    from fleetscope import render_timeline

    from arbius_tpu.obs.fleetscope import (
        federate,
        render_export,
        task_timeline,
    )

    try:
        view = federate(ns.fleet)
    except (OSError, ValueError) as e:
        print(f"obs_dump: {e}", file=sys.stderr)
        return 2
    if ns.cmd == "metrics":
        print("obs_dump: --fleet has no JSON metrics view — use "
              "`prom` (federated exposition) or tools/fleetscope.py",
              file=sys.stderr)
        return 2
    if ns.cmd == "prom":
        print(render_export(view["export"]), end="")
        return 0
    events = view["events"]
    if ns.cmd == "journal":
        if ns.kind:
            events = [e for e in events if e.get("kind") == ns.kind]
        if getattr(ns, "taskid", None):
            events = [e for e in events
                      if e.get("taskid") == ns.taskid
                      or ns.taskid in (e.get("taskids") or ())]
        # explicit: limit<=0 means "no events", not "all of them"
        # (events[-0:] would slice the whole list)
        print(render_timeline(events[-ns.limit:] if ns.limit > 0
                              else []))
        print(f"-- {len(events)} event(s) across "
              f"{len(view['members'])} member(s)", file=sys.stderr)
        return 0
    # trace: the cross-process timeline for one task (span ids are
    # per-process, so the fleet view is the ordered event chain, not
    # one tree)
    timeline = task_timeline(events, ns.taskid)
    if not timeline:
        print(f"no events recorded for {ns.taskid} across "
              f"{len(view['members'])} sidecar(s)", file=sys.stderr)
        return 1
    print(render_timeline(timeline))
    return 0


def main(argv=None) -> int:
    p = make_parser("obs_dump", __doc__)
    p.add_argument("--url", default="http://127.0.0.1:8080",
                   help="node control-RPC base URL")
    p.add_argument("--fleet", default=None, metavar="DIR",
                   help="read fleet obs sidecars under DIR instead of "
                        "a live node (docs/fleetscope.md)")
    sub = p.add_subparsers(dest="cmd", required=True)
    sub.add_parser("metrics", help="JSON metrics view (/api/metrics)")
    sub.add_parser("prom", help="Prometheus exposition (/metrics)")
    sp = sub.add_parser("journal", help="event journal (/debug/journal)")
    sp.add_argument("--limit", type=int, default=200)
    sp.add_argument("--kind", default=None,
                    help="filter by event kind (span, retry, job_failed, "
                         "alert_transition, …)")
    sp.add_argument("--taskid", default=None,
                    help="filter to one task's events (the /debug/trace "
                         "matching: taskid field or taskids membership)")
    sp = sub.add_parser("trace", help="span tree for a task (/debug/trace)")
    sp.add_argument("taskid")
    ns = p.parse_args(argv)
    if ns.fleet is not None:
        return _fleet_main(ns)
    base = ns.url.rstrip("/")

    if ns.cmd == "metrics":
        print(render_metrics(fetch_json(f"{base}/api/metrics")))
    elif ns.cmd == "prom":
        print(fetch_text(f"{base}/metrics"), end="")
    elif ns.cmd == "journal":
        q = f"?limit={ns.limit}" + (f"&kind={ns.kind}" if ns.kind else "") \
            + (f"&taskid={ns.taskid}" if ns.taskid else "")
        body = fetch_json(f"{base}/debug/journal{q}")
        print(render_journal(body["events"]))
        print(f"-- {len(body['events'])} event(s), capacity "
              f"{body['capacity']}, dropped {body['dropped']}",
              file=sys.stderr)
    elif ns.cmd == "trace":
        body = fetch_json(f"{base}/debug/trace?taskid={ns.taskid}")
        if not body["spans"]:
            print(f"no spans recorded for {ns.taskid} (journal may have "
                  "evicted them; see obs_journal_capacity)",
                  file=sys.stderr)
            return 1
        print(render_trace(body["spans"]))
    return 0


if __name__ == "__main__":
    sys.exit(main())
