"""TPU node admission smoke: boot self-test + live mining on the real chip.

The strongest end-to-end proof the framework can give on one chip: the
PRODUCTION node path — `build_registry` with the full 860M anythingv3
topology (bf16 weights), `ModelConfig.golden` set to the COMMITTED TPU
admission vector (`goldens/anythingv3.full.tpu.bfloat16.json`) — then

  1. `MinerNode.boot()`: re-executes the golden solve on-chip and
     refuses to mine on any CID mismatch (the reference's admission
     check, miner/src/index.ts:984-1001);
  2. a live task at the metric shape (512x512, 20 steps) through the
     full event -> solve -> commit -> reveal -> claim lifecycle against
     the in-process engine.

Claim discipline matches bench.py: SIGTERM converts to a clean exit so
the chip grant is released (a killed TPU-holding process wedges the
pool), heartbeats go to stderr, and the final summary is one JSON line
on stdout. Run from the repo root on the mining platform:

    python tools/tpu_node_smoke.py
"""
from __future__ import annotations

import json
import os
import signal
import sys
import time

_T0 = time.perf_counter()
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

BUDGET_S = int(os.environ.get("SMOKE_BUDGET_S", "2400"))


def _note(msg: str) -> None:
    print(f"[smoke +{time.perf_counter() - _T0:.0f}s] {msg}",
          file=sys.stderr, flush=True)


def main() -> int:
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(0))
    from arbius_tpu.utils.session import Heartbeat, arm_exit_watchdog

    hb = Heartbeat("smoke", _note)

    golden_path = os.path.join(
        _REPO, "goldens", "anythingv3.full.tpu.bfloat16.json")
    with open(golden_path) as f:
        vec = json.load(f)
    assert vec["platform"] == "tpu" and vec["weights_dtype"] == "bfloat16"

    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        # deliberate CPU run (dev host / CI): the axon plugin would dial
        # the remote-TPU tunnel regardless of the env var alone — force
        # the CPU backend so the platform gate below exits cleanly
        from arbius_tpu.utils import force_cpu_devices

        force_cpu_devices(1, strict=False)
    hb.set("claiming chip")
    import jax

    platform = jax.devices()[0].platform
    _note(f"platform={platform}")
    if platform != "tpu":
        _note("not on TPU — the admission vector is platform-specific; "
              "aborting (exit 4)")
        os._exit(4)

    # a BootError / solve failure after the claim must release it just as
    # promptly as success: stop the heartbeat and arm the teardown
    # watchdog on EVERY exit path (an exception propagating with the
    # heartbeat live can hang ~1500 s on a wedged tunnel, holding the
    # claim — the exact pool-wedging these helpers exist to prevent)
    try:
        return _post_claim(hb, vec, platform)
    finally:
        hb.set("releasing claim via clean exit")
        hb.stop()
        # on the exception path the watchdog must force a FAILURE code —
        # os._exit(0) after a BootError would report a failed admission
        # smoke as success to any exit-code-gating driver. SystemExit
        # with a 0/None code is NOT a failure: it's the SIGTERM handler's
        # designed clean claim release.
        exc = sys.exc_info()[1]
        failing = exc is not None and not (
            isinstance(exc, SystemExit) and not exc.code)
        arm_exit_watchdog(_note, 90.0, code=1 if failing else 0)


def run_live_burst(node, eng, user: str, mid_b: bytes, n_tasks: int,
                   deadline: float, note,
                   task_input: dict | None = None
                   ) -> tuple[dict, list[float]]:
    """Submit `n_tasks` at once and mine them through the full lifecycle,
    recording each task's submission→solution-on-chain wall time (queue
    wait + infer + CID + txs). Returns (summary, latencies). Extracted
    from the TPU smoke session so the burst/claim bookkeeping is
    CPU-testable (tests/test_smoke_burst.py) before it ever spends a
    chip claim."""
    base = task_input if task_input is not None else {
        "negative_prompt": "", "width": 512, "height": 512,
        "num_inference_steps": 20, "scheduler": "DPMSolverMultistep"}
    live = {"attempted": True, "solved": 0, "claimed": 0,
            "n_tasks": n_tasks, "solve_s": None}
    claimed_before = node.metrics.solutions_claimed
    latencies: list[float] = []
    t_submit: dict[bytes, float] = {}
    for i in range(n_tasks):
        tid = eng.submit_task(user, 0, user, mid_b, 0, json.dumps({
            "prompt": f"arbius smoke test {i}, a cat mining on a tpu",
            **base}).encode())
        t_submit[tid] = time.perf_counter()
    note(f"{n_tasks} tasks submitted")
    t0 = time.perf_counter()
    pending = set(t_submit)

    def drain_solved() -> None:
        for tid in [t for t in pending if t in eng.solutions]:
            # task-to-commitment wall time: burst submission →
            # solution on chain
            latencies.append(time.perf_counter() - t_submit[tid])
            pending.discard(tid)

    while node.tick() and time.perf_counter() < deadline:
        drain_solved()
    drain_solved()
    live["solve_s"] = round(time.perf_counter() - t0, 1)
    live["solved"] = n_tasks - len(pending)
    note(f"{live['solved']}/{n_tasks} solved in {live['solve_s']}s")
    if live["solved"]:
        eng.advance_time(2200)
        while node.tick() and time.perf_counter() < deadline + 120:
            pass
        # delta, not the node-lifetime counter: a reusable helper must
        # not attribute earlier claims to this burst
        live["claimed"] = node.metrics.solutions_claimed - claimed_before
    return live, latencies


def _post_claim(hb, vec, platform: str) -> int:
    from arbius_tpu.chain import WAD, Engine, TokenLedger
    from arbius_tpu.node import LocalChain, MinerNode
    from arbius_tpu.node.config import MiningConfig, ModelConfig
    from arbius_tpu.node.factory import build_registry

    miner, user = "0x" + "aa" * 20, "0x" + "01" * 20
    tok = TokenLedger()
    eng = Engine(tok, start_time=0)
    tok.mint(Engine.ADDRESS, 600_000 * WAD)
    for a in (miner, user):
        tok.mint(a, 1000 * WAD)
        tok.approve(a, Engine.ADDRESS, 10**30)
    with open(os.path.join(_REPO, "arbius_tpu", "templates", "data",
                           "anythingv3.json"), "rb") as f:
        mid_b = eng.register_model(user, user, 0, f.read())
    mid = "0x" + mid_b.hex()

    hb.set("build registry (full 860M topology, bf16)")
    # share bench.py's compile cache dir — node.boot() re-points the JAX
    # cache at MiningConfig.compile_cache_dir, so it must be set HERE
    # (an enable_compile_cache call before boot would be overridden)
    cfg = MiningConfig(
        compile_cache_dir=os.path.join(_REPO, ".jax_cache_bench"),
        models=(ModelConfig(
            id=mid, template="anythingv3", weights_dtype="bfloat16",
            golden=vec["golden"]),))
    registry = build_registry(cfg)

    chain = LocalChain(eng, miner)
    chain.validator_deposit(100 * WAD)
    node = MinerNode(chain, cfg, registry)

    hb.set("boot self-test: golden solve on-chip vs committed CID "
           "(includes jit compile)")
    t0 = time.perf_counter()
    node.boot()  # raises BootError on CID mismatch
    boot_s = time.perf_counter() - t0
    _note(f"boot self-test PASSED in {boot_s:.1f}s "
          f"(golden {vec['golden']['cid'][:18]}…)")

    # live mining burst: N tasks through the full event→solve→commit→
    # reveal→claim lifecycle, measured per task — BASELINE.md's p50/p95
    # task-to-commitment distribution (VERDICT r4 ask #6), not a single
    # sample. The boot self-test above already compiled the metric-shape
    # bucket, so the burst rides a warm executable.
    n_tasks = int(os.environ.get("SMOKE_TASKS", "20"))
    if time.perf_counter() - _T0 < BUDGET_S - 300:
        hb.set(f"live burst: {n_tasks} tasks at the metric shape")
        live, latencies = run_live_burst(
            node, eng, user, mid_b, n_tasks,
            deadline=_T0 + BUDGET_S - 240, note=_note)
    else:
        _note("skipping live burst (budget)")
        live = {"attempted": False, "solved": 0, "claimed": 0,
                "n_tasks": n_tasks, "solve_s": None}
        latencies = []

    def _pct(vals, q):
        if not vals:
            return None
        s = sorted(vals)
        return round(s[min(len(s) - 1, int(q * len(s)))], 2)

    # per-stage spans + the task-to-commitment distribution (the
    # counters a long-running miner exposes at /api/metrics)
    stages = {
        k: {"p50": _pct(list(v), 0.50), "p95": _pct(list(v), 0.95),
            "n": len(v)}
        for k, v in node.metrics.stage_seconds.items()}
    summary = {
        "smoke": "tpu_node_admission", "platform": platform,
        "boot_self_test": "passed", "boot_s": round(boot_s, 1),
        "golden_cid": vec["golden"]["cid"], **live,
        "task_to_commitment_p50_s": _pct(latencies, 0.50),
        "task_to_commitment_p95_s": _pct(latencies, 0.95),
        "task_to_commitment_s": [round(x, 2) for x in sorted(latencies)],
        "stage_seconds": stages,
        "elapsed_s": round(time.perf_counter() - _T0, 1),
    }
    print(json.dumps(summary), flush=True)
    # committed artifact (bench_runs/ is the provenance directory)
    out = os.path.join(_REPO, "bench_runs",
                       f"r05_smoke_{platform}_{n_tasks}tasks.json")
    with open(out, "w") as f:
        json.dump(summary, f)
    _note(f"summary written: {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
