"""TPU profiling session — attribute the anythingv3 solve's wall time.

VERDICT r4 weak #1: perf sits at ~2.0x the A100 anchor with an estimated
~8% MFU and no committed trace; round 5 must be profile-driven. This tool
is that profile: ONE chip claim (the bench.py session discipline —
heartbeat, SIGTERM-to-clean-exit, teardown watchdog, budget gates), and
against it:

  device     platform / device_kind / HBM — names the chip so MFU math
             uses the real peak, not a guess.
  matmul     big bf16 matmul microbench — the chip's ACHIEVABLE matmul
             rate through this tunnel/runtime (the MFU denominator that
             matters; paper peaks are not reachable by real programs).
  attn       flash-vs-einsum A/B at the exact SD-1.5 self-attention
             shapes (S=4096/d=40, S=1024/d=80) — answers the r4 verdict
             question "does flash even beat XLA einsum at SD shapes?"
             (ops/flash.py pads d to 128 lanes; einsum materializes S²).
  conv       the dominant 3x3 conv shape — reference MXU rate for the
             conv-heavy UNet trunk.
  segments   text / single CFG UNet step / VAE decode, each jitted and
             timed alone: 20*unet + vae + text vs the measured full
             generate attributes the gap (dispatch, transfer, sampler).
  trace      jax.profiler trace around warmed generate calls, written to
             bench_runs/traces/ — the committed artifact the verdict
             asked for.

Results stream as JSON lines into bench_runs/ (append-only file named by
date) the moment each exists, so a killed session keeps its evidence.
Run:  python tools/tpu_profile.py            (claims the real chip)
      JAX_PLATFORMS=cpu python tools/tpu_profile.py --cpu   (harness test)
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

_T0 = time.perf_counter()
BUDGET_S = int(os.environ.get("PROFILE_BUDGET_S", "3300"))
MARGIN_S = 150
BATCH = int(os.environ.get("PROFILE_BATCH", "4"))
WIDTH = HEIGHT = 512
STEPS = 20
SCHEDULER = "DPMSolverMultistep"


def _note(msg: str) -> None:
    print(f"[profile +{time.perf_counter() - _T0:.0f}s] {msg}",
          file=sys.stderr, flush=True)


def _left(deadline: float) -> float:
    return deadline - time.perf_counter()


def _timeit(fn, *args, warmup: int = 2, rounds: int = 5) -> float:
    """Median seconds per call, after warmup (compile + cache)."""
    import jax
    for _ in range(warmup):
        jax.block_until_ready(fn(*args))
    times = []
    for _ in range(rounds):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        times.append(time.perf_counter() - t0)
    times.sort()
    return times[len(times) // 2]


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true",
                    help="force CPU (harness self-test; tiny shapes)")
    ns = ap.parse_args()

    signal.signal(signal.SIGTERM, lambda *_: sys.exit(0))
    deadline = _T0 + BUDGET_S - MARGIN_S

    if ns.cpu:
        from arbius_tpu.utils import force_cpu_devices
        force_cpu_devices(1)

    from arbius_tpu.utils import enable_compile_cache
    from arbius_tpu.utils.session import Heartbeat, arm_exit_watchdog

    enable_compile_cache(os.path.join(_REPO, ".jax_cache_bench"))
    hb = Heartbeat("profile", _note)
    hb.set(f"claiming chip (budget {BUDGET_S}s)")

    import jax
    import jax.numpy as jnp
    import numpy as np

    devs = jax.devices()
    platform = devs[0].platform
    if not ns.cpu and platform != "tpu":
        # TPU-attempt mode but the backend silently fell back to CPU:
        # full-shape probes on host would take hours — abort like
        # bench.py's session child does
        _note("TPU attempt landed on a CPU backend — aborting (exit 4)")
        os._exit(4)
    out_path = os.path.join(
        _REPO, "bench_runs",
        f"r05_profile_{platform}_{BATCH}b.jsonl")

    def emit(line: dict) -> None:
        line["elapsed_s"] = round(time.perf_counter() - _T0, 1)
        with open(out_path, "a") as f:
            f.write(json.dumps(line) + "\n")
            f.flush()
            os.fsync(f.fileno())
        _note(f"result: {json.dumps(line)}")

    # -- device ----------------------------------------------------------
    d = devs[0]
    mem = {}
    try:
        stats = d.memory_stats() or {}
        mem = {k: stats[k] for k in ("bytes_limit", "bytes_in_use")
               if k in stats}
    except Exception:
        pass
    emit({"probe": "device", "platform": platform,
          "device_kind": getattr(d, "device_kind", "?"),
          "n_devices": len(devs), **mem})

    tiny = ns.cpu  # CPU harness test uses toy shapes throughout

    # -- matmul achievable peak ------------------------------------------
    hb.set("matmul microbench")
    try:
        n = 1024 if tiny else 8192
        key = jax.random.PRNGKey(0)
        a = jax.random.normal(key, (n, n), jnp.bfloat16)
        b = jax.random.normal(jax.random.fold_in(key, 1), (n, n), jnp.bfloat16)
        mm = jax.jit(lambda a, b: a @ b)
        sec = _timeit(mm, a, b)
        tflops = 2 * n ** 3 / sec / 1e12
        emit({"probe": "matmul_bf16", "n": n, "sec": round(sec, 5),
              "achieved_tflops": round(tflops, 1)})
    except Exception as e:
        emit({"probe": "matmul_bf16", "error": f"{type(e).__name__}: {e}"})

    # -- attention A/B at the real SD-1.5 self-attention shapes ----------
    # [B*CFG, H, S, D] with B=BATCH. FLOPs = 2 * 2 * BH * S^2 * D.
    from arbius_tpu.ops.flash import flash_attention
    from arbius_tpu.ops.ring import sp_attention_reference

    shapes = [(2 * BATCH, 8, 64, 16)] if tiny else [
        (2 * BATCH, 8, 4096, 40),   # level-0: 64x64 tokens, ch=320
        (2 * BATCH, 8, 1024, 80),   # level-1: 32x32 tokens, ch=640
        (2 * BATCH, 8, 256, 160),   # level-2: 16x16 tokens, ch=1280
    ]
    for bh, h, s, dd in shapes:
        if _left(deadline) < 300:
            _note("skipping remaining attention probes (budget)")
            break
        hb.set(f"attn A/B S={s} d={dd}")
        key = jax.random.PRNGKey(7)
        q = jax.random.normal(key, (bh, h, s, dd), jnp.bfloat16)
        k = jax.random.normal(jax.random.fold_in(key, 1), (bh, h, s, dd),
                              jnp.bfloat16)
        v = jax.random.normal(jax.random.fold_in(key, 2), (bh, h, s, dd),
                              jnp.bfloat16)
        flops = 2 * 2 * bh * h * s * s * dd
        import functools
        for name, fn in (
                ("flash", jax.jit(flash_attention)),
                ("flash_nopad", jax.jit(functools.partial(
                    flash_attention, pad_d=False))),
                ("einsum", jax.jit(sp_attention_reference))):
            try:
                sec = _timeit(fn, q, k, v)
                emit({"probe": "attention", "impl": name, "B": bh, "H": h,
                      "S": s, "D": dd, "sec": round(sec, 6),
                      "achieved_tflops": round(flops / sec / 1e12, 2)})
            except Exception as e:
                emit({"probe": "attention", "impl": name, "S": s, "D": dd,
                      "error": f"{type(e).__name__}: {e}"})

    # -- dominant conv shape ---------------------------------------------
    hb.set("conv microbench")
    try:
        cb, ch, hw = (2, 16, 16) if tiny else (2 * BATCH, 320, 64)
        x = jax.random.normal(jax.random.PRNGKey(9), (cb, hw, hw, ch),
                              jnp.bfloat16)
        w = jax.random.normal(jax.random.PRNGKey(10), (3, 3, ch, ch),
                              jnp.bfloat16)
        conv = jax.jit(lambda x, w: jax.lax.conv_general_dilated(
            x, w, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC")))
        sec = _timeit(conv, x, w)
        flops = 2 * cb * hw * hw * 9 * ch * ch
        emit({"probe": "conv3x3", "B": cb, "HW": hw, "C": ch,
              "sec": round(sec, 6),
              "achieved_tflops": round(flops / sec / 1e12, 2)})
    except Exception as e:
        emit({"probe": "conv3x3", "error": f"{type(e).__name__}: {e}"})

    # -- full pipeline: segment attribution ------------------------------
    from arbius_tpu.models.sd15 import ByteTokenizer, SD15Config, SD15Pipeline
    from arbius_tpu.node.factory import tiny_byte_tokenizer

    if tiny:
        cfg = SD15Config.tiny()
        pipe = SD15Pipeline(cfg, tokenizer=tiny_byte_tokenizer(cfg.text))
        w_, h_, steps_ = 128, 128, 4
    else:
        cfg = SD15Config()
        pipe = SD15Pipeline(cfg, tokenizer=ByteTokenizer())
        w_, h_, steps_ = WIDTH, HEIGHT, STEPS

    if _left(deadline) < 600:
        _note("not enough budget for pipeline segments; exiting early")
        hb.stop()
        arm_exit_watchdog(_note, 90.0)
        return

    hb.set("init_params (bf16, jitted on-device)")
    params = pipe.init_params(seed=0, height=h_, width=w_, dtype="bfloat16")
    jax.block_until_ready(params)
    lh, lw = h_ // 8, w_ // 8

    # text encoder alone
    hb.set("segment: text encoder")
    try:
        ids = jnp.zeros((BATCH, cfg.text.max_length), jnp.int32)
        te = jax.jit(lambda p, i: pipe.text_encoder.apply({"params": p}, i))
        sec = _timeit(te, params["text"], ids)
        emit({"probe": "segment", "name": "text_encoder", "batch": BATCH,
              "sec": round(sec, 5)})
    except Exception as e:
        emit({"probe": "segment", "name": "text_encoder",
              "error": f"{type(e).__name__}: {e}"})

    # one CFG UNet step alone (2B batch, the scan body's cost) — under
    # EACH attention impl: the program-level A/B that decides the
    # production dispatch (kernel microbenches above miss fusion effects)
    try:
        xin = jax.random.normal(jax.random.PRNGKey(3),
                                (2 * BATCH, lh, lw, cfg.unet.in_channels),
                                jnp.bfloat16)
        t = jnp.full((2 * BATCH,), 500.0)
        ctx = jax.random.normal(jax.random.PRNGKey(4),
                                (2 * BATCH, cfg.text.max_length,
                                 cfg.unet.context_dim), jnp.bfloat16)
        impls = ("auto",) if tiny else ("auto", "flash_nopad", "einsum")
    except Exception as e:  # input setup failure must not cost the
        # vae/full/trace probes (or the clean claim release)
        emit({"probe": "segment", "name": "unet_step_cfg",
              "error": f"setup: {type(e).__name__}: {e}"})
        impls = ()
    # restore the operator's pinned impl afterwards, not "auto" — the
    # remaining probes (vae/full_generate/trace) must run under the
    # dispatch the operator launched with. The impl is pinned at import
    # (ops/flash.py); the A/B threads each candidate through the explicit
    # setter and re-jits, the one legitimate way to flip it in-process.
    from arbius_tpu.ops.flash import set_attention_impl

    for impl in impls:
        if impl != "auto" and _left(deadline) < 240:
            _note(f"skipping unet A/B impl={impl} (budget)")
            continue
        hb.set(f"segment: unet step (CFG) attn={impl}")
        prior_impl = set_attention_impl(impl)
        try:
            un = jax.jit(lambda p, x, t, c: pipe.unet.apply(
                {"params": p}, x, t, c))
            sec = _timeit(un, params["unet"], xin, t, ctx)
            emit({"probe": "segment", "name": "unet_step_cfg",
                  "attn_impl": impl, "batch": BATCH, "sec": round(sec, 5),
                  "per_solve_x_steps": round(sec * steps_, 4)})
        except Exception as e:
            emit({"probe": "segment", "name": "unet_step_cfg",
                  "attn_impl": impl, "error": f"{type(e).__name__}: {e}"})
        finally:
            set_attention_impl(prior_impl)

    # VAE decode alone
    hb.set("segment: vae decode")
    try:
        from arbius_tpu.models.sd15.vae import decode_to_images
        lat = jax.random.normal(jax.random.PRNGKey(5),
                                (BATCH, lh, lw, cfg.unet.in_channels),
                                jnp.bfloat16)
        va = jax.jit(lambda p, z: decode_to_images(
            pipe.vae.apply({"params": p}, z)))
        sec = _timeit(va, params["vae"], lat)
        emit({"probe": "segment", "name": "vae_decode", "batch": BATCH,
              "sec": round(sec, 5)})
    except Exception as e:
        emit({"probe": "segment", "name": "vae_decode",
              "error": f"{type(e).__name__}: {e}"})

    # full generate (the metric path, host round-trip included)
    hb.set("segment: full generate")
    kw = dict(width=w_, height=h_, num_inference_steps=steps_,
              scheduler=SCHEDULER, guidance_scale=12.0)
    prompts = [f"arbius profile task {i}" for i in range(BATCH)]
    negs = [""] * BATCH
    out = pipe.generate(params, prompts, negs, list(range(BATCH)), **kw)
    assert out.dtype == np.uint8
    t0 = time.perf_counter()
    rounds = 3
    for r in range(rounds):
        pipe.generate(params, prompts, negs,
                      [(r + 1) * BATCH + i for i in range(BATCH)], **kw)
    sec_full = (time.perf_counter() - t0) / rounds
    emit({"probe": "segment", "name": "full_generate", "batch": BATCH,
          "steps": steps_, "sec": round(sec_full, 4),
          "sol_per_hour": round(3600.0 / (sec_full / BATCH), 1)})

    # -- profiler trace (the committed artifact) -------------------------
    if _left(deadline) > 120:
        hb.set("jax.profiler trace around 2 generates")
        trace_dir = os.path.join(
            _REPO, "bench_runs", "traces",
            f"r05_{platform}_prod_b{BATCH}" if not tiny
            else f"r05_{platform}_tiny_b{BATCH}")
        try:
            os.makedirs(trace_dir, exist_ok=True)
            with jax.profiler.trace(trace_dir):
                for r in (7, 8):
                    pipe.generate(params, prompts, negs,
                                  [r * BATCH + i for i in range(BATCH)], **kw)
            emit({"probe": "trace", "dir": os.path.relpath(trace_dir, _REPO),
                  "ok": True})
        except Exception as e:
            emit({"probe": "trace", "error": f"{type(e).__name__}: {e}"})

    hb.stop()
    _note("profile session complete; releasing claim via clean exit")
    arm_exit_watchdog(_note, 90.0)


if __name__ == "__main__":
    main()
