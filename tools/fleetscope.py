#!/usr/bin/env python
"""fleetscope — federate a fleet's obs sidecars from a terminal.

Every fleet member persists registry snapshots + journal segments to
its own `<member>.obs.sqlite` sidecar under `fleet.sidecar_dir`
(docs/fleetscope.md). This tool merges them offline — no fleet member
is contacted:

    python tools/fleetscope.py <dir> prom              # merged exposition
    python tools/fleetscope.py <dir> timeline          # fleet timeline
    python tools/fleetscope.py <dir> timeline --taskid 0x…   # one task
    python tools/fleetscope.py <dir> slo [--queue-wait-p95 S]
        [--time-to-commit-p99 S] [--steal-lag-p99 S]

`prom` renders the same byte format a node's GET /metrics uses; the
merge is deterministic (members sort by name — filesystem order never
reaches the output). `slo` estimates p50/p95/p99 from the federated
fixed-bucket histograms and exits 1 when a declared threshold is
breached (the same SLO layer `simsoak --flood` fails closed on).
"""
from __future__ import annotations

import json
import sys

from _common import EXIT_CLEAN, EXIT_FINDINGS, EXIT_USAGE, make_parser

# the federated histograms the SLO command reads (docs/fleetscope.md)
_SLO_METRICS = (
    ("queue_wait_seconds", "arbius_fleet_queue_wait_seconds"),
    ("time_to_commit_seconds", "arbius_fleet_time_to_commit_seconds"),
    ("steal_lag_seconds", "arbius_fleet_steal_lag_seconds"),
)


def _event_line(e: dict) -> str:
    core = {k: v for k, v in e.items()
            if k not in ("kind", "seq", "wall", "chain", "member")}
    chain = f" chain={e['chain']}" if "chain" in e else ""
    return (f"{e.get('member', '?'):<14} #{e.get('seq', '?'):>6} "
            f"{e.get('kind', '?'):<16}{chain} "
            + json.dumps(core, sort_keys=True, default=str))


def render_timeline(events: list[dict]) -> str:
    return "\n".join(_event_line(e) for e in events)


def slo_report(view: dict, slo) -> dict:
    """Percentile report from the federated export + the evaluation
    against `slo` (node.config.SLOConfig) — shared with render/tests."""
    from arbius_tpu.obs.fleetscope import (
        evaluate_slo,
        summarize_histogram_export,
    )

    metrics = view["export"].get("metrics", {})
    report = {}
    for block, metric in _SLO_METRICS:
        m = metrics.get(metric)
        report[block] = summarize_histogram_export(m) if m else \
            {"count": 0, "p50": None, "p95": None, "p99": None}
    report["breaches"] = evaluate_slo(slo, report)
    report["ok"] = not report["breaches"]
    return report


def main(argv=None) -> int:
    p = make_parser("fleetscope", __doc__)
    p.add_argument("dir", help="fleet.sidecar_dir to federate")
    sub = p.add_subparsers(dest="cmd", required=True)
    sub.add_parser("prom", help="merged Prometheus exposition")
    sp = sub.add_parser("timeline",
                        help="chain-time-ordered fleet journal")
    sp.add_argument("--taskid", default=None,
                    help="restrict to one task's cross-process lifecycle")
    sp.add_argument("--limit", type=int, default=500)
    sp = sub.add_parser("slo", help="federated SLO percentiles + verdict")
    sp.add_argument("--queue-wait-p95", type=float, default=None)
    sp.add_argument("--time-to-commit-p99", type=float, default=None)
    sp.add_argument("--steal-lag-p99", type=float, default=None)
    sp.add_argument("--json", action="store_true")
    ns = p.parse_args(argv)

    from arbius_tpu.obs.fleetscope import (
        federate,
        render_export,
        task_timeline,
    )

    try:
        view = federate(ns.dir)
    except (OSError, ValueError) as e:
        print(f"fleetscope: {e}", file=sys.stderr)
        return EXIT_USAGE

    if ns.cmd == "prom":
        print(render_export(view["export"]), end="")
        return EXIT_CLEAN
    if ns.cmd == "timeline":
        events = view["events"]
        if ns.taskid:
            events = task_timeline(events, ns.taskid)
        # explicit: limit<=0 means "no events", not "all of them"
        # (events[-0:] would slice the whole list)
        print(render_timeline(events[-ns.limit:] if ns.limit > 0
                              else []))
        print(f"-- {len(events)} event(s) across "
              f"{len(view['members'])} member(s): "
              f"{', '.join(view['members'])}", file=sys.stderr)
        return EXIT_CLEAN
    # slo
    from arbius_tpu.node.config import ConfigError, SLOConfig

    try:
        slo = SLOConfig(queue_wait_p95=ns.queue_wait_p95,
                        time_to_commit_p99=ns.time_to_commit_p99,
                        steal_lag_p99=ns.steal_lag_p99)
    except ConfigError as e:
        print(f"fleetscope: {e}", file=sys.stderr)
        return EXIT_USAGE
    report = slo_report(view, slo)
    if ns.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        for block, _ in _SLO_METRICS:
            b = report[block]
            print(f"{block:26s} count={b['count']:<8d} p50={b['p50']} "
                  f"p95={b['p95']} p99={b['p99']}")
        for breach in report["breaches"]:
            print(f"SLO101 {breach}")
        print("slo: " + ("ok" if report["ok"] else
                         f"{len(report['breaches'])} breach(es)"))
    return EXIT_CLEAN if report["ok"] else EXIT_FINDINGS


if __name__ == "__main__":
    sys.exit(main())
