#!/usr/bin/env python
"""aotcache — inspect, verify, and garbage-collect the AOT executable cache.

The fleet's AOT cache (docs/compile-cache.md) is a directory of
content-addressed `<key>.aotx` entries whose key derives from the
graphlint canonical program fingerprint + environment + argument
signatures. Every entry's header carries its own derivation components,
so this tool can audit a cache offline — no jax tracing, no devices:

    python tools/aotcache.py --dir aot-cache --list            # entries
    python tools/aotcache.py --dir aot-cache --stats           # totals
    python tools/aotcache.py --dir aot-cache --verify          # audit
    python tools/aotcache.py --dir aot-cache --gc --max-bytes N
    python tools/aotcache.py --dir aot-cache --list --json

`--verify` re-derives each entry's key from its stored header and
checks it against the filename (AOT501 on mismatch — a renamed or
doctored entry), after the payload digest check every read performs
(AOT502 on a corrupt/truncated entry). Output is byte-deterministic
for a fixed cache (entries sorted by key; no mtimes in reports) —
tier-1 pins it against a fixture cache. `--gc` applies the same LRU
eviction the node runs after each write, down to `--max-bytes`.

Exit codes follow the shared lint contract (tools/_common.py):
0 clean / 1 findings (--verify) / 2 usage error.
"""
from __future__ import annotations

import json
import sys

from _common import EXIT_CLEAN, EXIT_USAGE, kv_table, lint_main

from arbius_tpu.analysis.core import Finding  # noqa: E402 (_common fixes path)


def build_arg_parser(p):
    p.add_argument("--dir", default="aot-cache",
                   help="cache directory (default: aot-cache)")
    p.add_argument("--list", action="store_true",
                   help="list entries (key, tag, sizes), sorted by key")
    p.add_argument("--stats", action="store_true",
                   help="entry count + byte totals")
    p.add_argument("--verify", action="store_true",
                   help="re-derive every entry's key from its header; "
                        "exit 1 on any mismatch or corrupt entry")
    p.add_argument("--gc", action="store_true",
                   help="LRU-evict entries until the directory fits "
                        "--max-bytes")
    p.add_argument("--max-bytes", type=int, default=0,
                   help="size budget for --gc (required, > 0)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable output (stable: sorted keys)")
    return p


def verify_findings(cache_dir: str) -> list[Finding]:
    """AOT501 (key does not re-derive from the stored header) and
    AOT502 (entry unreadable/corrupt/truncated) findings, sorted by
    entry key. Pure over the directory contents. This is the FULL
    audit — unlike the boot warm scan's header-only reads, every
    payload is digest-verified here, so a silently bit-flipped blob
    surfaces offline instead of as a reject at some future boot."""
    from arbius_tpu.aotcache import CacheReject, derive_key, read_entry
    from arbius_tpu.aotcache.store import SUFFIX, scan

    def full_read(path):
        header, _, closer = read_entry(path)
        closer()
        return header

    findings = []
    for key, path, _size in scan(cache_dir):
        name = key + SUFFIX
        try:
            header = full_read(path)
        except CacheReject as e:
            findings.append(Finding(
                path=name, line=1, col=0, rule="AOT502", severity="error",
                message=f"unloadable cache entry: {e.reason}",
                snippet=key))
            continue
        derived = derive_key(header.get("program", ""),
                             header.get("env", {}),
                             header.get("arg_sig", ""),
                             header.get("donate_sig", ""))
        if derived != key or header.get("key") != key:
            findings.append(Finding(
                path=name, line=1, col=0, rule="AOT501", severity="error",
                message=("entry key does not re-derive from its header "
                         f"(derived {derived[:16]}…, header says "
                         f"{str(header.get('key'))[:16]}…) — renamed or "
                         "doctored entry"),
                snippet=key))
    return findings


def collect(ns):
    from arbius_tpu.aotcache import AotCache
    from arbius_tpu.aotcache.store import evict_lru, scan, total_bytes

    modes = [ns.list, ns.stats, ns.verify, ns.gc]
    if sum(bool(m) for m in modes) != 1:
        print("exactly one of --list/--stats/--verify/--gc is required",
              file=sys.stderr)
        return EXIT_USAGE, []
    if ns.verify:
        return None, verify_findings(ns.dir)
    if ns.gc:
        if ns.max_bytes <= 0:
            print("--gc needs --max-bytes > 0", file=sys.stderr)
            return EXIT_USAGE, []
        evicted = evict_lru(ns.dir, ns.max_bytes)
        out = {"evicted": evicted, "remaining_entries": len(scan(ns.dir)),
               "remaining_bytes": total_bytes(ns.dir)}
        if ns.json:
            print(json.dumps(out, sort_keys=True, indent=1))
        else:
            for key in evicted:
                print(f"evicted {key}")
            print(kv_table({"evicted": len(evicted),
                            "remaining_entries": out["remaining_entries"],
                            "remaining_bytes": out["remaining_bytes"]}))
        return EXIT_CLEAN, []
    cache = AotCache(ns.dir)
    if ns.stats:
        stats = cache.stats()
        del stats["max_bytes"]  # tool-side: no config context here
        if ns.json:
            print(json.dumps(stats, sort_keys=True, indent=1))
        else:
            print(kv_table(stats))
        return EXIT_CLEAN, []
    entries = cache.entries()
    if ns.json:
        print(json.dumps({"entries": entries}, sort_keys=True, indent=1))
        return EXIT_CLEAN, []
    if not entries:
        print("(empty cache)")
        return EXIT_CLEAN, []
    for e in entries:
        if "error" in e:
            print(f"{e['key'][:16]}…  UNREADABLE({e['error']})  "
                  f"{e['size']}B")
        else:
            print(f"{e['key'][:16]}…  {e['tag'] or '-'}  "
                  f"payload={e['payload_len']}B  file={e['size']}B")
    return EXIT_CLEAN, []


def render(ns, findings, out):
    """--verify report: the shared lint JSON document, or one text line
    per finding (both byte-deterministic for a fixed cache)."""
    from _common import emit_json_report

    if ns.json:
        emit_json_report(findings, out)
        return
    for f in findings:
        out.write(f.text() + "\n")
    if findings:
        out.write(f"aotcache: {len(findings)} finding(s)\n")
    else:
        out.write("aotcache: cache verified clean\n")


def main(argv=None) -> int:
    return lint_main("aotcache", __doc__, build_arg_parser, collect,
                     render, argv)


if __name__ == "__main__":
    sys.exit(main())
