#!/usr/bin/env python
"""simsoak — deterministic fault-injection soak of the miner lifecycle.

Pre-commit / CI front door for `arbius_tpu.sim` (scenario catalog,
fault plane, and SIM1xx invariant list in docs/fault-injection.md):
drives a real MinerNode over the signed-tx JSON-RPC stack against the
in-process devnet under seeded fault schedules, then audits the run
against the protocol invariants.

    python tools/simsoak.py                          # clean, seed 0
    python tools/simsoak.py --scenario tier1 --seeds 2   # the CI matrix
    python tools/simsoak.py --scenario chaos --seed 41 --json
    python tools/simsoak.py --scenario fleet-race    # 2-miner fleet
    python tools/simsoak.py --flood 10000            # 10k-task fleet soak
    python tools/simsoak.py --list                   # scenario catalog
    python tools/simsoak.py --inject-bug double-commit   # must exit 1

Exit codes: 0 clean / 1 invariant violations / 2 usage error —
identical contract to detlint.py / graphlint.py; all three are shells
over tools/_common.py's `lint_main`. Every failing run prints the
`--scenario`/`--seed` pair that reproduces it byte-identically.
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _common import lint_main

from arbius_tpu.sim.cli import build_arg_parser, collect, render


def main(argv=None) -> int:
    return lint_main("simsoak", __doc__, build_arg_parser, collect, render,
                     argv)


if __name__ == "__main__":
    sys.exit(main())
