"""simnet tier-1 suite: the scenario matrix + checker regressions.

The matrix (`TIER1_MATRIX` × seeds) is the standing gate: a real
MinerNode over signed txs into the in-process devnet, under seeded
fault schedules, must pass every SIM1xx invariant checker. The worlds
are expensive (every chain write is a signed EIP-1559 tx), so the
module-scoped `matrix` fixture runs each (scenario, seed) ONCE and
every test audits the cached run. The injected double-commit proves
the checkers can actually catch a violating node; the byte-identical-
report test proves a failing seed reproduces.
"""
from __future__ import annotations

import json

import pytest

from arbius_tpu.sim.bugs import DoubleCommitMinerNode
from arbius_tpu.sim.cli import main as sim_main
from arbius_tpu.sim.harness import SimHarness, run_scenario
from arbius_tpu.sim.invariants import check_all, classify_tasks, summarize
from arbius_tpu.sim.scenario import SCENARIOS, TIER1_MATRIX, get_scenario

SEEDS = (1, 2)


@pytest.fixture(scope="module")
def matrix(tmp_path_factory):
    """(scenario, seed) → (harness, result, findings) for the whole
    acceptance matrix — run once, audited by every test below. The
    seeded runs drive the staged solve pipeline (the harness default)
    WITH the conclint runtime witness instrumented (docs/concurrency.md:
    SIM110 audits the observed lock-order graph on every matrix run)
    AND the healthwatch alert engine enabled (docs/healthwatch.md:
    SIM113 audits the fault→alert coverage on every matrix run — each
    fault scenario must raise its mapped alert class, clean must raise
    none); one extra `(name, "sync")` run per scenario drives the
    SHIPPED default (pipeline.enabled=false, witness off, healthwatch
    off) through the same fault plane so the synchronous _solve_bucket
    path never rots uncovered — and doubles as the witness-off AND
    healthwatch-off CID baseline."""
    base = tmp_path_factory.mktemp("simnet")
    out = {}
    for name in TIER1_MATRIX:
        for seed in SEEDS:
            h = SimHarness(get_scenario(name), seed,
                           db_path=str(base / f"{name}-{seed}.sqlite"),
                           witness=True, healthwatch=True)
            result = h.run()
            out[(name, seed)] = (h, result, check_all(result))
        h = SimHarness(get_scenario(name), SEEDS[0],
                       db_path=str(base / f"{name}-sync.sqlite"),
                       pipeline=False)
        result = h.run()
        out[(name, "sync")] = (h, result, check_all(result))
    return out


# -- the acceptance matrix -------------------------------------------------

@pytest.mark.parametrize("seed", SEEDS)
@pytest.mark.parametrize("name", TIER1_MATRIX)
def test_scenario_matrix_holds_every_invariant(matrix, name, seed):
    _, result, findings = matrix[(name, seed)]
    assert not findings, (
        "invariant violations:\n  "
        + "\n  ".join(f.text() for f in findings)
        + f"\nreproduce byte-identically with: {result.repro()}")
    assert result.quiescent
    # every task accounted: exactly one terminal label each
    labels = classify_tasks(result)
    assert set(labels) == set(result.tasks)


@pytest.mark.parametrize("name", TIER1_MATRIX)
def test_sync_default_path_holds_every_invariant(matrix, name):
    """The shipped default (pipeline off, the synchronous solve path)
    passes the same scenario catalog; SIM109 self-disables because no
    staged executor ran."""
    _, result, findings = matrix[(name, "sync")]
    assert not result.pipeline_enabled
    assert not findings, (
        "invariant violations (pipeline OFF):\n  "
        + "\n  ".join(f.text() for f in findings))
    assert result.quiescent
    # the sync path journals no stage events — SIM109's degenerate
    # guard must not misfire on it
    assert not [e for e in result.journal_events
                if e.get("kind") == "pipeline_stage"]


def test_pipeline_and_sync_reach_identical_cids(matrix):
    """Same scenario, same seed, both schedules: every task's accepted
    solution CID is identical — the pipeline changed the schedule, not
    the bytes (the simnet version of the golden byte-equality gate).
    The piped run is witness-INSTRUMENTED and healthwatch-ENABLED while
    the sync run is neither, so this same assertion pins that BOTH are
    bookkeeping-only: witness-on/healthwatch-on CIDs are byte-identical
    to the off baseline."""
    _, piped, _ = matrix[("clean", SEEDS[0])]
    _, sync, _ = matrix[("clean", "sync")]
    assert piped.witness_report is not None
    assert sync.witness_report is None
    assert piped.healthwatch_enabled and not sync.healthwatch_enabled
    cids = lambda r: {"0x" + t.hex(): "0x" + s.cid.hex()
                      for t, s in r.engine.solutions.items()}
    assert cids(piped) == cids(sync) and cids(piped)


def test_witness_observes_the_matrix_without_findings(matrix):
    """Every instrumented matrix run produced a witness record (the
    wrapped locks actually saw traffic) and SIM110 stayed green — the
    checker ran, because witness_report is present, and `findings` above
    is already asserted empty per run. Here: pin that the record is
    non-degenerate and the observed order graph matches the documented
    state_lock → db-lock discipline."""
    from arbius_tpu.analysis.conc.witness import order_cycle

    for name in TIER1_MATRIX:
        _, result, _ = matrix[(name, SEEDS[0])]
        rep = result.witness_report
        assert rep is not None and rep["locks"], name
        locks = {l["lock"] for l in rep["locks"]}
        assert "NodeDB._lock" in locks, name
        assert order_cycle(rep) is None, (name, rep["order_edges"])
        # no watched attrs on a healthy node: nothing sampled
        assert rep["attr_writes"] == [], name


def test_clean_scenario_claims_everything(matrix):
    _, result, findings = matrix[("clean", 1)]
    assert not findings
    assert set(classify_tasks(result).values()) == {"claimed"}
    assert result.plane.fault_counts == {}


def test_faulty_scenarios_actually_inject(matrix):
    """A fault mix whose schedule degenerates to zero injections tests
    nothing — pin the matrix scenarios to nonzero injection counts."""
    for name in ("rpc-flap", "pin-fail", "reorg"):
        for seed in SEEDS:
            _, result, _ = matrix[(name, seed)]
            assert sum(result.plane.fault_counts.values()) > 0, (name, seed)


# -- crash-restart ---------------------------------------------------------

def test_crash_restart_recovers_from_checkpoint(matrix):
    _, result, findings = matrix[("crash-restart", 1)]
    assert not findings
    assert result.restarts == 1
    assert result.plane.crash_seqs, "the crash never fired"
    # the commitment that triggered the crash was revealed post-restart
    # with the SAME CID (SIM106 verified it; assert the pair exists)
    crash_seq = result.plane.crash_seqs[0]
    pre = [r for r in result.plane.audit[:crash_seq]
           if r.ok and r.method == "signalCommitment"
           and r.sender == result.miner_address]
    post_reveals = {("0x" + r.values[0].hex(), "0x" + r.values[1].hex())
                    for r in result.plane.audit[crash_seq:]
                    if r.ok and r.method == "submitSolution"
                    and r.sender == result.miner_address}
    crossed = [result.plane.commitments[r.values[0]] for r in pre
               if (result.plane.commitments[r.values[0]][1],
                   result.plane.commitments[r.values[0]][2])
               in post_reveals]
    assert crossed, "no pre-crash commitment was revealed after restart"
    # and the run still claims every task
    assert set(classify_tasks(result).values()) == {"claimed"}


# -- contestation ----------------------------------------------------------

def test_contested_scenario_slashes_the_adversary(matrix):
    from arbius_tpu.chain.fixedpoint import WAD
    from arbius_tpu.sim.harness import EVIL

    _, result, findings = matrix[("contested", 1)]
    assert not findings
    evil_tasks = [tid for tid, f in result.tasks.items() if f.evil]
    assert evil_tasks, "seed 1 produced no front-run tasks"
    labels = classify_tasks(result)
    for tid in evil_tasks:
        assert labels[tid] == "contested_resolved"
        con = result.engine.contestations[bytes.fromhex(tid[2:])]
        assert con.finish_start_index > 0
    # the adversary's escrow was slashed (yea side won 2-1), so its
    # stake ends strictly below its 200 wad deposit
    assert result.engine.validators[EVIL].staked < 200 * WAD


# -- checker regressions ---------------------------------------------------

def test_injected_double_commit_fails_closed(tmp_path):
    result = run_scenario(get_scenario("clean").with_tasks(4), 0,
                          db_path=str(tmp_path / "bug.sqlite"),
                          node_cls=DoubleCommitMinerNode)
    findings = check_all(result)
    sim103 = [f for f in findings if f.rule == "SIM103"]
    assert sim103, "the double-commit checker never fired"
    # the invariant diff is readable: both CIDs with their blocks
    msg = sim103[0].message
    assert "double-commit" in msg
    assert msg.count("0x1220") == 2
    assert msg.count("@ block") == 2
    assert sim103[0].taskid in result.tasks


def test_injected_race_is_witnessed_at_runtime(tmp_path):
    """The other half of the conclint injected-race regression (the
    static half lives in test_conclint.py): RacyCounterMinerNode bumps
    an unlocked counter from two roots; under the witness, SIM110 must
    fail the run — and the race never touches solve bytes, so every
    OTHER invariant stays green."""
    from arbius_tpu.sim.bugs import RacyCounterMinerNode

    result = run_scenario(get_scenario("clean").with_tasks(3), 0,
                          db_path=str(tmp_path / "racy.sqlite"),
                          node_cls=RacyCounterMinerNode, witness=True)
    findings = check_all(result)
    sim110 = [f for f in findings if f.rule == "SIM110"]
    assert sim110, "the witness never saw the injected race"
    assert "racy_counter" in sim110[0].message
    assert "NO witnessed lock" in sim110[0].message
    assert not [f for f in findings if f.rule != "SIM110"], \
        "the race bled into protocol invariants"
    # witness-on, buggy node: CIDs still deterministic (counter feeds
    # nothing) — every task claimed
    assert set(classify_tasks(result).values()) == {"claimed"}


def test_reports_are_byte_identical_per_seed(matrix, tmp_path):
    _, cached, _ = matrix[("rpc-flap", 1)]
    fresh = run_scenario(get_scenario("rpc-flap"), 1,
                         db_path=str(tmp_path / "fresh.sqlite"))
    a = json.dumps(summarize(cached), sort_keys=True)
    assert a == json.dumps(summarize(fresh), sort_keys=True)
    _, other_seed, _ = matrix[("rpc-flap", 2)]
    assert a != json.dumps(summarize(other_seed), sort_keys=True)


# -- healthwatch: fault→alert coverage (SIM113, docs/healthwatch.md) -------

def _raised(result):
    return sorted({e["alert"] for e in result.journal_events
                   if e.get("kind") == "alert_transition"})


def test_healthwatch_matrix_coverage_is_nondegenerate(matrix):
    """Every matrix run already asserts zero findings — SIM113
    included. Here: pin that the substrate is non-degenerate in BOTH
    directions: clean raises NO alert, and each fault scenario's
    journal shows its mapped alert class actually transitioning."""
    assert _raised(matrix[("clean", SEEDS[0])][1]) == []
    expect = {
        "rpc-flap": "rpc_degraded",
        "pin-fail": "pin_degraded",
        "reorg": "chain_replay",
        "crash-restart": "crash_recovered",
        "contested": "contention",
        "chaos": "job_quarantine",
    }
    for name, alert in expect.items():
        for seed in SEEDS:
            _, result, _ = matrix[(name, seed)]
            assert result.healthwatch_enabled
            assert alert in _raised(result), (name, seed,
                                              _raised(result))


def test_healthwatch_transitions_walk_the_state_machine(matrix):
    """The journaled record is a legal state-machine walk: per alert,
    consecutive transitions chain (prev == the last state), and each
    event records a genuine change (the once-per-state-change
    contract, generalized from perf_drift)."""
    _, result, _ = matrix[("pin-fail", SEEDS[0])]
    walks: dict[str, list] = {}
    for ev in result.journal_events:
        if ev.get("kind") != "alert_transition":
            continue
        walks.setdefault(ev["alert"], []).append(ev)
    assert walks, "pin-fail journaled no transitions"
    for alert, evs in walks.items():
        state = "ok"
        for ev in evs:
            assert ev["prev"] == state, (alert, evs)
            assert ev["state"] != ev["prev"], (alert, ev)
            state = ev["state"]


def test_injected_silent_fault_fails_sim113_only(tmp_path):
    """sim/bugs.py silent-fault: a node whose monitoring went dark
    (alert_transition events swallowed) under an actively faulting
    scenario MUST be caught by SIM113's coverage audit — and by
    nothing else (work still flows, retries still journal, CIDs still
    land)."""
    from arbius_tpu.sim.bugs import SilentFaultMinerNode

    result = run_scenario(get_scenario("rpc-flap"), 0,
                          db_path=str(tmp_path / "silent.sqlite"),
                          node_cls=SilentFaultMinerNode,
                          healthwatch=True)
    findings = check_all(result)
    sim113 = [f for f in findings if f.rule == "SIM113"]
    assert sim113, "the monitoring blackout went uncaught"
    assert "silent" in sim113[0].message
    assert not [f for f in findings if f.rule != "SIM113"], \
        "the injected blackout bled into other invariants"
    # monitoring-only: the run itself is healthy
    assert _raised(result) == []
    assert any(e.get("kind") == "retry"
               for e in result.journal_events), \
        "faults stopped journaling — the scenario degenerated"


def test_healthwatch_off_runs_skip_sim113(tmp_path):
    """The shipped default (alerts.enabled=false) is not audited —
    SIM113 gates on healthwatch_enabled exactly as SIM109/110 gate on
    their instrumentation."""
    from arbius_tpu.sim.bugs import SilentFaultMinerNode

    result = run_scenario(get_scenario("rpc-flap").with_tasks(3), 0,
                          db_path=str(tmp_path / "off.sqlite"),
                          node_cls=SilentFaultMinerNode)
    assert not result.healthwatch_enabled
    assert not [f for f in check_all(result) if f.rule == "SIM113"]


def test_cli_injected_silent_fault_exits_1(tmp_path, capsys):
    # silent-fault implies --healthwatch and forces a fault scenario
    rc = sim_main(["--inject-bug", "silent-fault", "--tasks", "4",
                   "--workdir", str(tmp_path)])
    captured = capsys.readouterr()
    assert rc == 1
    assert "SIM113" in captured.out


# -- obs integration -------------------------------------------------------

def test_fault_plane_counts_into_ambient_obs(matrix):
    h, result, _ = matrix[("pin-fail", 1)]
    counter = h.node.obs.registry.counter(
        "arbius_sim_faults_total", labelnames=("kind",))
    assert counter.value(kind="pin_fail") == \
        result.plane.fault_counts["pin_fail"] > 0


# -- CLI (shared lint exit contract) ---------------------------------------

def test_cli_exit_codes_and_json(tmp_path, capsys):
    assert sim_main(["--scenario", "clean", "--tasks", "3", "--json",
                     "--workdir", str(tmp_path)]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["findings"] == [] and doc["version"] == 1
    assert doc["runs"][0]["terminal"] == {"claimed": 3}
    assert sim_main(["--scenario", "does-not-exist"]) == 2
    capsys.readouterr()
    assert sim_main(["--seeds", "0"]) == 2
    capsys.readouterr()
    assert sim_main(["--inject-bug", "no-such-bug"]) == 2
    capsys.readouterr()
    assert sim_main(["--list"]) == 0
    out = capsys.readouterr().out
    for name in SCENARIOS:
        assert name in out


def test_cli_witness_out_writes_mergeable_report(tmp_path, capsys):
    wpath = tmp_path / "witness.json"
    rc = sim_main(["--scenario", "clean", "--tasks", "2",
                   "--workdir", str(tmp_path),
                   "--witness-out", str(wpath)])
    capsys.readouterr()
    assert rc == 0
    doc = json.loads(wpath.read_text())
    assert {l["lock"] for l in doc["locks"]} >= {"NodeDB._lock"}
    assert doc["attr_writes"] == []  # healthy node: nothing watched


def test_cli_injected_racy_counter_exits_1(tmp_path, capsys):
    # --inject-bug racy-counter implies the witness; SIM110 must fire
    rc = sim_main(["--inject-bug", "racy-counter", "--tasks", "2",
                   "--workdir", str(tmp_path)])
    captured = capsys.readouterr()
    assert rc == 1
    assert "SIM110" in captured.out


def test_cli_injected_bug_exits_1_with_repro_line(tmp_path, capsys):
    rc = sim_main(["--inject-bug", "double-commit", "--tasks", "3",
                   "--workdir", str(tmp_path)])
    captured = capsys.readouterr()
    assert rc == 1
    assert "SIM103" in captured.out
    # the failing run names its exact repro invocation
    assert "--scenario clean --seed 0" in captured.err


# -- fleet matrix (docs/fleet.md, SIM111) ----------------------------------

@pytest.fixture(scope="module")
def fleet_matrix(tmp_path_factory):
    """(scenario, seed) → result for the fleet half of the acceptance
    matrix: real multi-node fleets (coordinator + N signed-tx workers
    over the shared lease table) under the fleet failure schedules,
    every worker running its own healthwatch alert engine (SIM113
    audits per-member fault→alert coverage, docs/healthwatch.md)."""
    from arbius_tpu.sim.fleet import run_fleet_scenario
    from arbius_tpu.sim.scenario import FLEET_TIER1

    base = tmp_path_factory.mktemp("fleetnet")
    out = {}
    for name in FLEET_TIER1:
        for seed in SEEDS:
            workdir = base / f"{name}-{seed}"
            workdir.mkdir()
            result = run_fleet_scenario(get_scenario(name), seed,
                                        workdir=str(workdir),
                                        healthwatch=True)
            out[(name, seed)] = (result, check_all(result))
    return out


@pytest.mark.parametrize("seed", SEEDS)
@pytest.mark.parametrize("name", ("fleet-race", "fleet-partition",
                                  "fleet-coord-crash"))
def test_fleet_matrix_holds_every_invariant(fleet_matrix, name, seed):
    result, findings = fleet_matrix[(name, seed)]
    assert not findings, (
        "fleet invariant violations:\n  "
        + "\n  ".join(f.text() for f in findings)
        + f"\nreproduce byte-identically with: {result.repro()}")
    assert result.quiescent
    # every task claimed (strict scenarios) and every lease terminal
    assert set(classify_tasks(result).values()) == {"claimed"}
    assert set(result.lease_counts) == {"done"}


def test_fleet_race_spreads_work_across_workers(fleet_matrix):
    """Both miners actually mined — a fleet where one worker starves is
    a degenerate race that tests nothing."""
    result, _ = fleet_matrix[("fleet-race", SEEDS[0])]
    by_validator = {}
    for s in result.engine.solutions.values():
        by_validator[s.validator] = by_validator.get(s.validator, 0) + 1
    assert set(by_validator) == set(result.fleet_workers)
    assert all(n > 0 for n in by_validator.values())
    # and nobody ever double-committed or was deduped (clean race)
    assert not [h for h in result.lease_history
                if h[0] == "commit_dedup"]


def test_fleet_partition_steals_expired_leases(fleet_matrix):
    """The work-stealing claim: worker 1's leases expired during its
    partition and worker 0 stole them directly (no coordinator sweep
    available — it was partitioned too); no task was lost."""
    result, _ = fleet_matrix[("fleet-partition", SEEDS[0])]
    steals = [h for h in result.lease_history if h[0] == "steal"]
    assert steals, "the partition never forced a steal"
    ttl = result.scenario.fleet.lease_ttl
    assert all(h[4]["lag"] <= max(ttl, 2 * result.scenario.tick_seconds)
               for h in steals)
    # stolen tasks still ended claimed (counted in the matrix test);
    # the stealing worker's healthwatch raised steal_surge — the
    # fleet half of SIM113's coverage (docs/healthwatch.md)
    assert "steal_surge" in {
        e.get("alert") for e in result.journal_events
        if e.get("kind") == "alert_transition"}


def test_fleet_coordinator_crash_recovers_leases(fleet_matrix):
    result, _ = fleet_matrix[("fleet-coord-crash", SEEDS[0])]
    assert result.restarts == 1
    assert result.plane.fault_counts.get("coordinator_crash") == 1
    # recovery left nothing behind: pinned by the matrix test's
    # {"done"} lease assertion; here pin that work CONTINUED after the
    # crash (solutions landed in blocks after the crash round)
    assert sum(1 for s in result.engine.solutions.values()) == \
        len(result.tasks)


def test_fleet_of_one_matches_bare_node_byte_for_byte(tmp_path):
    """The determinism contract (docs/fleet.md): one worker behind the
    coordinator+lease plane produces the SAME solution set — same
    validator, byte-identical CIDs — as a bare synchronous MinerNode on
    the same scenario stream."""
    import dataclasses

    from arbius_tpu.sim.fleet import run_fleet_scenario
    from arbius_tpu.sim.scenario import FleetSpec

    clean = get_scenario("clean")
    fleet1 = dataclasses.replace(clean, name="clean-fleet1",
                                 fleet=FleetSpec(workers=1))
    (tmp_path / "fleet").mkdir()
    rf = run_fleet_scenario(fleet1, SEEDS[0],
                            workdir=str(tmp_path / "fleet"))
    rb = run_scenario(clean, SEEDS[0],
                      db_path=str(tmp_path / "bare.sqlite"),
                      pipeline=False)
    assert not check_all(rf)
    cids = lambda r: {"0x" + t.hex(): "0x" + s.cid.hex()
                     for t, s in r.engine.solutions.items()}
    assert cids(rf) == cids(rb) and cids(rf)
    assert {s.validator for s in rf.engine.solutions.values()} == \
        {s.validator for s in rb.engine.solutions.values()}


def test_fleet_matrix_passes_sim112_trace_chains(fleet_matrix):
    """Every fleet matrix run already asserts zero findings — SIM112
    included. Here: pin that the trace substrate is non-degenerate on
    a run with steals (fleet-partition): every lease carries a
    deal-rooted hop chain, and the sidecars federate into a timeline
    whose lease_hop adoptions cover every acquire/steal hop."""
    result, _ = fleet_matrix[("fleet-partition", SEEDS[0])]
    assert result.sidecar_dir
    hops_seen = 0
    for row in result.lease_rows:
        hops = json.loads(row["hops"])
        assert hops[0]["op"] == "deal"
        assert [h["hop"] for h in hops] == list(range(len(hops)))
        hops_seen += len(hops)
    assert hops_seen > len(result.lease_rows)  # acquires happened
    assert any(h["op"] == "steal"
               for row in result.lease_rows
               for h in json.loads(row["hops"]))
    from arbius_tpu.obs.fleetscope import federate, render_export

    view = federate(result.sidecar_dir)
    assert "coordinator" in view["members"] and \
        "worker-0" in view["members"]
    text = render_export(view["export"])
    assert "arbius_fleet_tasks_total" in text
    assert "arbius_fleet_queue_wait_seconds_count" in text
    adoptions = [e for e in view["events"]
                 if e.get("kind") == "lease_hop"]
    granted = sum(1 for row in result.lease_rows
                  for h in json.loads(row["hops"])
                  if h["op"] in ("acquire", "steal"))
    assert len(adoptions) == granted > 0


def test_injected_span_gap_fails_sim112_only(tmp_path):
    """sim/bugs.py span-gap: a worker whose obs drops the lease_hop
    adoption events MUST be caught by SIM112's trace-completeness
    audit — and by nothing else (work still flows, CIDs still land)."""
    from arbius_tpu.sim.bugs import SpanGapWorkerNode
    from arbius_tpu.sim.fleet import run_fleet_scenario

    result = run_fleet_scenario(get_scenario("fleet-race"), 0,
                                workdir=str(tmp_path),
                                node_cls=SpanGapWorkerNode)
    findings = check_all(result)
    sim112 = [f for f in findings if f.rule == "SIM112"]
    assert sim112, "the span gap went uncaught"
    assert "never adopted" in sim112[0].message
    assert not [f for f in findings if f.rule != "SIM112"], \
        "the injected trace gap bled into other invariants"
    # the gap is observability-only: every task still claimed
    assert all(s.claimed for s in result.engine.solutions.values())


def test_injected_double_lease_fails_closed(tmp_path):
    """sim/bugs.py double-lease: a worker that ignores the lease
    plane's commit exclusivity MUST be caught by SIM111's cross-worker
    dedupe audit — and by nothing else (the stray commitments never
    touch task outcomes)."""
    from arbius_tpu.sim.bugs import DoubleLeaseWorkerNode
    from arbius_tpu.sim.fleet import run_fleet_scenario

    result = run_fleet_scenario(get_scenario("fleet-race"), 0,
                                workdir=str(tmp_path),
                                node_cls=DoubleLeaseWorkerNode)
    findings = check_all(result)
    sim111 = [f for f in findings if f.rule == "SIM111"]
    assert sim111, "the double-lease went uncaught"
    assert "cross-process commit dedupe failed" in sim111[0].message
    assert not [f for f in findings if f.rule != "SIM111"], \
        "the injected bug bled into protocol invariants"


def test_cli_injected_double_lease_exits_1(tmp_path, capsys):
    # double-lease is fleet-only: the CLI swaps in fleet-race itself
    rc = sim_main(["--inject-bug", "double-lease",
                   "--workdir", str(tmp_path)])
    captured = capsys.readouterr()
    assert rc == 1
    assert "SIM111" in captured.out


# -- the 10k flood soak (docs/fleet.md) ------------------------------------

def test_flood_10k_bounded_queues_and_no_lost_tasks(tmp_path, capsys):
    """tools/simsoak.py --flood 10000: ten thousand task lifecycles
    through a 4-worker fleet on CPU inside the tier-1 budget. Proves at
    load: worker task/solve backlogs never exceed their bound (the
    lease table absorbs the flood — CONC302's story at fleet scale),
    every lease settles, no cross-worker double-commit, and the
    one-fsync-per-tick batching holds (sqlite commits ≪ tasks)."""
    rc = sim_main(["--flood", "10000", "--json",
                   "--workdir", str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 0
    doc = json.loads(out)
    assert doc["findings"] == []
    flood = doc["flood"]
    assert flood["claimed"] == flood["tasks"] == 10000
    assert flood["lease_counts"] == {"done": 10000}
    assert flood["commit_dedup"] == 0
    bound = flood["backlog_bound"]
    assert all(0 < d <= bound for d in flood["max_backlog"].values())
    # fsync batching at load: commits are per ROUND, not per job
    for commits in flood["db_commits"].values():
        assert commits < flood["tasks"] / 20
    # the flood actually queued deep in the lease plane (the durable
    # overflow buffer did its job)
    assert flood["max_pending_leases"] > bound
    # the SLO report (docs/fleetscope.md): fleet-wide p50/p95/p99 over
    # the full 10k corpus, chain time only, no objectives declared →
    # percentiles present, nothing breached
    slo = flood["slo"]
    assert slo["ok"] and slo["breaches"] == []
    for block in ("queue_wait_seconds", "time_to_commit_seconds"):
        b = slo[block]
        assert b["count"] == 10000
        assert 0 < b["p50"] <= b["p95"] <= b["p99"]


def test_flood_slo_breach_fails_closed(tmp_path, capsys):
    """An injected breach — a declared objective the measured corpus
    cannot meet — must fail the soak with SLO101 (exit 1)."""
    rc = sim_main(["--flood", "300", "--workers", "3",
                   "--slo", "time_to_commit_p99=0.5",
                   "--workdir", str(tmp_path)])
    captured = capsys.readouterr()
    assert rc == 1
    assert "SLO101" in captured.out
    assert "time_to_commit_seconds p99" in captured.out


def test_flood_slo_cli_usage_error():
    assert sim_main(["--flood", "5", "--slo", "bogus=1"]) == 2
    # a valid SLOConfig field the deterministic flood report cannot
    # evaluate (wall clock) is rejected, not silently never-checked
    assert sim_main(["--flood", "5",
                     "--slo", "chip_idle_fraction=0.2"]) == 2
    # --slo without --flood: a declared objective that would never be
    # evaluated must be a usage error, not a silent no-op
    assert sim_main(["--scenario", "clean",
                     "--slo", "time_to_commit_p99=1"]) == 2


def test_flood_report_deterministic(tmp_path):
    from arbius_tpu.sim.fleet import FleetFloodHarness

    reports = []
    for d in ("a", "b"):
        (tmp_path / d).mkdir()
        h = FleetFloodHarness(300, 3, str(tmp_path / d), seed=7)
        try:
            reports.append(h.run())
        finally:
            h.close()
    assert json.dumps(reports[0], sort_keys=True) == \
        json.dumps(reports[1], sort_keys=True)
    assert reports[0]["claimed"] == 300


def test_flood_cli_usage_errors():
    assert sim_main(["--flood", "0"]) == 2
    assert sim_main(["--flood", "5", "--workers", "0"]) == 2
