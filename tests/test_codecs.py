"""Codec layer tests: roundtrip correctness + cross-impl byte equality.

Determinism here is the whole game (SURVEY.md §7 hard parts #2): the PNG/MP4
bytes feed straight into the CID the miner commits on-chain. So every codec
is tested three ways: (1) structural validity via an independent decoder
(stdlib zlib inflate, PIL), (2) byte-stability across calls, and (3) the
native C++ deflate against the pure-Python spec implementation.
"""
from __future__ import annotations

import io
import zlib

import numpy as np
import pytest

from arbius_tpu.codecs import (
    deflate_compress,
    deflate_fixed,
    encode_jpeg,
    encode_mp4,
    encode_png,
    zlib_compress,
)


def _rng(seed=0):
    return np.random.default_rng(seed)


def _test_image(h=64, w=64, seed=0):
    """Natural-ish gradient + noise image, not pure noise."""
    yy, xx = np.mgrid[0:h, 0:w]
    base = np.stack([xx * 255 // max(w - 1, 1),
                     yy * 255 // max(h - 1, 1),
                     (xx + yy) * 255 // max(h + w - 2, 1)], axis=-1)
    noise = _rng(seed).integers(0, 32, (h, w, 3))
    return np.clip(base + noise, 0, 255).astype(np.uint8)


# -- deflate ---------------------------------------------------------------

DEFLATE_CASES = [
    b"",
    b"a",
    b"abc",
    b"aaaaaaaaaaaaaaaaaaaaaaaaaaaa",
    b"the quick brown fox jumps over the lazy dog" * 50,
    bytes(range(256)) * 10,
    _rng(1).integers(0, 256, 70000).astype(np.uint8).tobytes(),
    (b"\x00" * 300000),          # multi-window RLE
]


@pytest.mark.parametrize("data", DEFLATE_CASES, ids=range(len(DEFLATE_CASES)))
def test_deflate_roundtrip(data):
    comp = deflate_fixed(data)
    assert zlib.decompress(comp, wbits=-15) == data


@pytest.mark.parametrize("data", DEFLATE_CASES, ids=range(len(DEFLATE_CASES)))
def test_native_matches_python(data):
    from arbius_tpu.codecs import _native

    fn = _native.deflate_fixed()
    if fn is None:
        pytest.skip("native codec lib unavailable (no g++?)")
    assert fn(data) == deflate_fixed(data)


def test_zlib_container_valid():
    data = b"hello arbius" * 100
    assert zlib.decompress(zlib_compress(data)) == data


def test_deflate_compresses_repetitive_data():
    data = b"abcdef" * 10000
    assert len(deflate_compress(data)) < len(data) // 10


# -- png -------------------------------------------------------------------

def test_png_decodes_exactly():
    PIL = pytest.importorskip("PIL.Image")
    img = _test_image(48, 80)
    png = encode_png(img)
    decoded = np.asarray(PIL.open(io.BytesIO(png)).convert("RGB"))
    np.testing.assert_array_equal(decoded, img)


def test_png_deterministic():
    img = _test_image(32, 32, seed=7)
    assert encode_png(img) == encode_png(img.copy())


def test_png_rejects_bad_input():
    with pytest.raises(ValueError):
        encode_png(np.zeros((8, 8, 4), np.uint8))
    with pytest.raises(ValueError):
        encode_png(np.zeros((8, 8, 3), np.float32))


def test_png_golden_stability():
    """Pin the exact bytes of a small image: any change to the filter
    choice, deflate parameters, or chunk layout is a determinism-class
    break and must be a deliberate, versioned decision."""
    img = _test_image(16, 16, seed=3)
    import hashlib
    digest = hashlib.sha256(encode_png(img)).hexdigest()
    assert encode_png(img)[:8] == b"\x89PNG\r\n\x1a\n"
    assert digest == ("eef2e774ae4507ab3f55b1c4072453b5"
                      "05fd8b20cc74978a5ac2fbe81c9351f6"), digest


# -- jpeg ------------------------------------------------------------------

def test_jpeg_decodes_close():
    PIL = pytest.importorskip("PIL.Image")
    img = _test_image(64, 64, seed=5)
    jpg = encode_jpeg(img, quality=90)
    decoded = np.asarray(PIL.open(io.BytesIO(jpg)).convert("RGB"))
    assert decoded.shape == img.shape
    err = np.abs(decoded.astype(np.int32) - img.astype(np.int32))
    assert float(err.mean()) < 6.0, float(err.mean())


def test_jpeg_deterministic():
    img = _test_image(24, 40, seed=9)
    assert encode_jpeg(img) == encode_jpeg(img.copy())


def test_jpeg_quality_monotonic():
    img = _test_image(64, 64, seed=2)
    assert len(encode_jpeg(img, quality=95)) > len(encode_jpeg(img, quality=30))


def test_jpeg_flat_image_tiny():
    img = np.full((32, 32, 3), 128, np.uint8)
    assert len(encode_jpeg(img)) < 1200


# -- mp4 -------------------------------------------------------------------

def _parse_boxes(data: bytes):
    out = []
    off = 0
    while off < len(data):
        size = int.from_bytes(data[off:off + 4], "big")
        tag = data[off + 4:off + 8]
        out.append((tag, data[off + 8:off + size]))
        off += size
    return out


def test_mp4_structure():
    frames = np.stack([_test_image(32, 48, seed=i) for i in range(4)])
    mp4 = encode_mp4(frames, fps=8)
    boxes = _parse_boxes(mp4)
    assert [t for t, _ in boxes] == [b"ftyp", b"mdat", b"moov"]
    mdat = boxes[1][1]
    # each sample is a standalone JPEG inside mdat
    assert mdat[:2] == b"\xff\xd8"
    moov = dict(_parse_boxes(boxes[2][1]))
    assert b"mvhd" in moov and b"trak" in moov


def test_mp4_sample_offsets_point_at_jpegs():
    frames = np.stack([_test_image(16, 16, seed=i) for i in range(3)])
    mp4 = encode_mp4(frames, fps=4)
    # find stco inside the box tree and check each offset hits an SOI marker
    idx = mp4.find(b"stco")
    assert idx > 0
    n = int.from_bytes(mp4[idx + 8:idx + 12], "big")
    assert n == 3
    for i in range(n):
        off = int.from_bytes(mp4[idx + 12 + 4 * i:idx + 16 + 4 * i], "big")
        assert mp4[off:off + 2] == b"\xff\xd8"


def test_mp4_deterministic():
    frames = np.stack([_test_image(16, 24, seed=i) for i in range(2)])
    assert encode_mp4(frames) == encode_mp4(frames.copy())


def test_mp4_decodable_if_ffmpeg_present():
    import shutil
    import subprocess
    import tempfile

    if shutil.which("ffprobe") is None:
        pytest.skip("ffprobe not installed")
    frames = np.stack([_test_image(32, 32, seed=i) for i in range(4)])
    with tempfile.NamedTemporaryFile(suffix=".mp4") as f:
        f.write(encode_mp4(frames, fps=8))
        f.flush()
        out = subprocess.run(
            ["ffprobe", "-v", "error", "-show_entries",
             "stream=codec_name,nb_frames", "-of", "csv=p=0", f.name],
            capture_output=True, text=True)
        assert out.returncode == 0, out.stderr
        assert "mjpeg" in out.stdout
