"""obs subsystem tests — registry semantics, Prometheus exposition,
span nesting, journal ring-buffer eviction, expretry reporting, and the
end-to-end task lifecycle trace through `MinerNode.tick()` on the fake
chain (ISSUE 1 acceptance: /metrics parses, /debug/trace returns the
full span tree, obs overhead stays bounded)."""
from __future__ import annotations

import json
import re
import time
import urllib.error
import urllib.request

import pytest

from arbius_tpu.node import ConfigError, MiningConfig, load_config
from arbius_tpu.node.retry import RetriesExhausted, expretry
from arbius_tpu.obs import (
    EventJournal,
    MetricsRegistry,
    Obs,
    current_obs,
    span,
    task_trace,
    use_obs,
)

from test_node import build_world, drain, submit


# -- registry --------------------------------------------------------------

def test_counter_monotonic_and_get_or_create():
    reg = MetricsRegistry()
    c = reg.counter("t_total", "help")
    assert reg.counter("t_total") is c
    c.inc()
    c.inc(2.5)
    assert c.value() == 3.5
    with pytest.raises(ValueError):
        c.inc(-1)
    assert c.value() == 3.5


def test_labeled_counter_and_shape_mismatch():
    reg = MetricsRegistry()
    c = reg.counter("ops_total", labelnames=("op",))
    c.inc(op="a")
    c.inc(op="a")
    c.inc(op="b")
    assert c.value(op="a") == 2 and c.value(op="b") == 1
    with pytest.raises(ValueError):
        c.inc()  # missing declared label
    with pytest.raises(ValueError):
        reg.counter("ops_total", labelnames=())  # shape mismatch
    with pytest.raises(ValueError):
        reg.gauge("ops_total")  # kind mismatch
    h = reg.histogram("h_seconds", buckets=(1.0, 2.0))
    assert reg.histogram("h_seconds", buckets=(1.0, 2.0)) is h
    with pytest.raises(ValueError):
        reg.histogram("h_seconds", buckets=(5.0,))  # bucket mismatch


def test_gauge_set_and_callback():
    reg = MetricsRegistry()
    g = reg.gauge("depth")
    g.set(7)
    assert g.value() == 7
    box = [3]
    f = reg.gauge("live_depth", fn=lambda: box[0])
    assert f.value() == 3
    box[0] = 9
    assert "live_depth 9" in reg.render()


def test_dead_callback_gauge_does_not_kill_scrape():
    reg = MetricsRegistry()
    reg.counter("survivor_total").inc()
    reg.gauge("dead_depth", fn=lambda: 1 / 0)
    text = reg.render()  # must not raise
    assert "dead_depth NaN" in text
    assert "survivor_total 1" in text


def test_read_paths_do_not_materialize_series():
    reg = MetricsRegistry()
    h = reg.histogram("s_seconds", buckets=(1.0,), labelnames=("stage",))
    assert h.percentile(0.5, stage="infer") is None
    assert h.values(stage="infer") == []
    assert h.count(stage="infer") == 0
    c = reg.counter("r_total", labelnames=("op",))
    assert c.value(op="never") == 0
    text = reg.render()  # no empty series from the reads above
    assert "s_seconds_bucket" not in text
    assert "r_total{" not in text


def test_histogram_bucket_edges_and_percentile():
    reg = MetricsRegistry()
    h = reg.histogram("lat_seconds", buckets=(1.0, 2.0, 5.0))
    for v in (0.5, 1.0, 1.5, 2.0, 10.0):
        h.observe(v)
    text = reg.render()
    # le is inclusive: 1.0 lands in the le="1" bucket, 2.0 in le="2"
    assert 'lat_seconds_bucket{le="1"} 2' in text
    assert 'lat_seconds_bucket{le="2"} 4' in text
    assert 'lat_seconds_bucket{le="5"} 4' in text
    assert 'lat_seconds_bucket{le="+Inf"} 5' in text
    assert "lat_seconds_sum 15" in text
    assert "lat_seconds_count 5" in text
    # exact rolling percentiles (numpy 'linear' semantics)
    h2 = reg.histogram("p_seconds", buckets=(1.0,))
    for v in range(1, 11):
        h2.observe(float(v))
    assert h2.percentile(0.5) == pytest.approx(5.5)
    assert h2.percentile(0.95) == pytest.approx(9.55)
    assert reg.histogram("empty_seconds", buckets=(1.0,)).percentile(0.5) \
        is None


def test_histogram_recent_window_bounded_and_tagged():
    reg = MetricsRegistry()
    h = reg.histogram("w_seconds", buckets=(1.0,), recent_window=3)
    for i in range(5):
        h.observe(float(i), tag=f"t{i}")
    assert h.values() == [2.0, 3.0, 4.0]
    assert h.recent() == [("t2", 2.0), ("t3", 3.0), ("t4", 4.0)]
    assert h.count() == 5  # cumulative count unaffected by the window


def test_prometheus_golden_text():
    reg = MetricsRegistry()
    reg.counter("a_total", "things counted").inc(3)
    reg.gauge("b_depth", "queue depth").set(2)
    h = reg.histogram("c_seconds", "span time", buckets=(0.1, 1.0),
                      labelnames=("stage",))
    h.observe(0.05, stage="infer")
    h.observe(0.5, stage="infer")
    assert reg.render() == (
        "# HELP a_total things counted\n"
        "# TYPE a_total counter\n"
        "a_total 3\n"
        "# HELP b_depth queue depth\n"
        "# TYPE b_depth gauge\n"
        "b_depth 2\n"
        "# HELP c_seconds span time\n"
        "# TYPE c_seconds histogram\n"
        'c_seconds_bucket{stage="infer",le="0.1"} 1\n'
        'c_seconds_bucket{stage="infer",le="1"} 2\n'
        'c_seconds_bucket{stage="infer",le="+Inf"} 2\n'
        'c_seconds_sum{stage="infer"} 0.55\n'
        'c_seconds_count{stage="infer"} 2\n')


_PROM_LABEL = r'[a-zA-Z_][a-zA-Z0-9_]*="(\\.|[^"\\])*"'
_PROM_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
    rf"(\{{{_PROM_LABEL}(,{_PROM_LABEL})*\}})? "
    r"(NaN|[+-]?Inf|[+-]?[0-9.e+-]+)$")


def assert_valid_prometheus(text: str) -> dict:
    """Minimal exposition-format check: every line is a comment or a
    `name{labels} value` sample; histogram buckets are cumulative and
    agree with _count. Returns {sample_line_name: value}."""
    samples = {}
    assert text.endswith("\n")
    for line in text.splitlines():
        if not line or line.startswith("# "):
            continue
        assert _PROM_LINE.match(line), f"bad exposition line: {line!r}"
        name_part, value = line.rsplit(" ", 1)
        samples[name_part] = float(value)
    # bucket series must be cumulative, ending at the matching _count
    by_series: dict[str, list[float]] = {}
    for k, v in samples.items():
        if "_bucket{" in k:
            series = k.split("_bucket{")[0] + "{" + ",".join(
                p for p in k.split("{")[1].rstrip("}").split(",")
                if not p.startswith("le=")).rstrip(",")
            by_series.setdefault(series, []).append(v)
    for series, counts in by_series.items():
        assert counts == sorted(counts), f"non-cumulative {series}"
        base, labels = series.split("{", 1)
        labels = labels.rstrip("}").rstrip(",")
        count_key = f"{base}_count" + ("{" + labels + "}" if labels else "")
        assert samples[count_key] == counts[-1]
    return samples


def test_render_parses_as_prometheus():
    reg = MetricsRegistry()
    reg.counter("x_total", labelnames=("op",)).inc(op='we"ird\nname')
    reg.histogram("y_seconds", buckets=(0.5, 1.5)).observe(1.0)
    reg.gauge("z")
    assert_valid_prometheus(reg.render())


# -- journal ---------------------------------------------------------------

def test_journal_ring_buffer_eviction():
    j = EventJournal(capacity=4)
    for i in range(6):
        j.record("e", i=i)
    assert len(j) == 4
    assert j.dropped == 2
    evs = j.events()
    assert [e["i"] for e in evs] == [2, 3, 4, 5]
    assert [e["seq"] for e in evs] == [3, 4, 5, 6]  # seq keeps counting


def test_journal_filters():
    j = EventJournal(capacity=10)
    j.record("span", taskid="0xa")
    j.record("span", taskids=["0xa", "0xb"])
    j.record("retry", op="pin")
    assert len(j.events(kind="retry")) == 1
    assert len(j.events(taskid="0xa")) == 2
    assert len(j.events(taskid="0xb")) == 1
    assert len(j.events(limit=2)) == 2
    assert j.events(limit=0) == []  # not the evs[-0:] = everything trap
    assert j.events(limit=-5) == []


# -- spans -----------------------------------------------------------------

def test_span_nesting_attrs_and_chain_time():
    clock = [100]
    obs = Obs(journal_capacity=64, now_fn=lambda: clock[0])
    with obs.span("outer", taskid="0x1", model="m"):
        clock[0] = 105
        with obs.span("inner", taskid="0x1"):
            pass
    inner, outer = obs.journal.events(kind="span")
    assert inner["name"] == "inner" and outer["name"] == "outer"
    assert inner["parent_id"] == outer["span_id"]
    assert outer["parent_id"] is None
    assert outer["attrs"] == {"model": "m"}  # taskid hoisted out of attrs
    assert outer["taskid"] == "0x1"
    assert outer["chain_start"] == 100 and outer["chain_end"] == 105
    assert inner["chain_start"] == 105
    assert outer["wall_s"] >= inner["wall_s"] >= 0
    # span durations feed the registry histogram
    assert obs.registry.histogram(
        "arbius_span_seconds", labelnames=("name",)).count(name="outer") == 1


def test_span_error_status_propagates():
    obs = Obs(journal_capacity=8)
    with pytest.raises(RuntimeError):
        with obs.span("boom", taskid="0x2"):
            raise RuntimeError("kaput")
    (ev,) = obs.journal.events(kind="span")
    assert ev["status"] == "error" and "kaput" in ev["error"]
    assert obs.registry.counter(
        "arbius_span_errors_total", labelnames=("name",)).value(
        name="boom") == 1


def test_ambient_span_noop_without_active_obs():
    assert current_obs() is None
    with span("nobody.listening", taskid="0x3"):
        pass  # must not raise, must not record anywhere
    obs = Obs(journal_capacity=8)
    with use_obs(obs):
        assert current_obs() is obs
        with span("heard", taskid="0x3"):
            pass
    assert current_obs() is None
    assert [e["name"] for e in obs.journal.events(kind="span")] == ["heard"]


def test_disabled_obs_records_nothing_but_counts():
    obs = Obs(journal_capacity=8, enabled=False)
    with use_obs(obs):
        with span("quiet"):
            pass
        obs.event("retry", op="x")
    assert len(obs.journal) == 0
    obs.registry.counter("still_counts_total").inc()
    assert obs.registry.counter("still_counts_total").value() == 1


def test_task_trace_tree_assembly():
    obs = Obs(journal_capacity=64)
    with obs.span("job.solve", taskid="0xaa"):
        with obs.span("solve.batch", taskids=["0xaa", "0xbb"]):
            with obs.span("solve.infer"):  # no taskid: included as child
                pass
    with obs.span("job.other", taskid="0xcc"):
        pass
    roots = task_trace(obs.journal.events(), "0xaa")
    assert [r["name"] for r in roots] == ["job.solve"]
    batch = roots[0]["children"][0]
    assert batch["name"] == "solve.batch"
    assert [c["name"] for c in batch["children"]] == ["solve.infer"]
    # the unrelated task is excluded
    assert task_trace(obs.journal.events(), "0xcc")[0]["name"] == "job.other"
    assert len(task_trace(obs.journal.events(), "0xcc")) == 1


# -- expretry --------------------------------------------------------------

def _always_fail(calls):
    def fn():
        calls.append(1)
        raise ValueError("nope")
    return fn


def test_expretry_default_curve_unchanged():
    sleeps = []
    with pytest.raises(RetriesExhausted):
        expretry(_always_fail([]), tries=5, sleep=sleeps.append)
    assert sleeps == [1.5 ** a for a in range(4)]


def test_expretry_max_delay_caps_backoff():
    sleeps = []
    with pytest.raises(RetriesExhausted):
        expretry(_always_fail([]), tries=10, max_delay=2.0,
                 sleep=sleeps.append)
    assert sleeps[:2] == [1.0, 1.5]
    assert all(s <= 2.0 for s in sleeps)
    assert sleeps[-1] == 2.0  # the cap binds where 1.5**a exceeds it


def test_expretry_reports_into_obs():
    obs = Obs(journal_capacity=32)
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise ValueError("transient")
        return "ok"

    with use_obs(obs):
        assert expretry(flaky, sleep=lambda s: None, op="pin_files") == "ok"
        with pytest.raises(RetriesExhausted):
            expretry(_always_fail([]), tries=2, sleep=lambda s: None,
                     op="submit_solution")
    c = obs.registry.counter("arbius_retry_attempts_total",
                             labelnames=("op",))
    assert c.value(op="pin_files") == 2
    assert c.value(op="submit_solution") == 2
    assert obs.registry.counter("arbius_retry_exhausted_total",
                                labelnames=("op",)).value(
        op="submit_solution") == 1
    retries = obs.journal.events(kind="retry")
    assert {e["op"] for e in retries} == {"pin_files", "submit_solution"}
    assert retries[0]["attempt"] == 1 and "transient" in retries[0]["error"]
    (exhausted,) = obs.journal.events(kind="retry_exhausted")
    assert exhausted["op"] == "submit_solution"


def test_expretry_counters_survive_disabled_tracing():
    """obs_enabled=False stops span/journal recording only — the
    registry keeps counting (the /metrics contract)."""
    obs = Obs(journal_capacity=8, enabled=False)
    with use_obs(obs):
        with pytest.raises(RetriesExhausted):
            expretry(_always_fail([]), tries=3, sleep=lambda s: None,
                     op="pin_files")
    assert obs.registry.counter("arbius_retry_attempts_total",
                                labelnames=("op",)).value(
        op="pin_files") == 3
    assert len(obs.journal) == 0  # journal stays quiet when disabled


# -- config ----------------------------------------------------------------

def test_config_obs_knobs_validate():
    cfg = load_config(json.dumps({
        "obs_enabled": False, "obs_journal_capacity": 16,
        "retry_max_delay": None}))
    assert cfg.obs_enabled is False
    assert cfg.obs_journal_capacity == 16
    assert cfg.retry_max_delay is None
    assert MiningConfig().retry_max_delay == 30.0
    with pytest.raises(ConfigError):
        MiningConfig(obs_journal_capacity=0)
    with pytest.raises(ConfigError):
        MiningConfig(retry_max_delay=-1.0)
