"""costsched — profit-aware continuous batching (arbius_tpu/node/sched.py
+ costmodel.py, docs/scheduler.md).

The load-bearing property mirrors pipeline/mesh: the packer may only
change the ORDER buckets dispatch in, never the bytes — solution files
and CIDs must be identical costsched-on vs FIFO-off for image-shaped
and video-shaped fakes at canonical_batch 1 and 4. On top of that: the
learned fit is deterministic and golden-pinned, the gate degrades to
the exact static behavior on an empty cost_model table, fitted rows
persist across node lives, and the simnet mixed-family flood holds
every SIM1xx invariant with the scheduler reordering freely.
"""
from __future__ import annotations

import json

import pytest

from arbius_tpu.chain import WAD, Engine, TokenLedger
from arbius_tpu.l0.cid import cid_hex, cid_of_solution_files
from arbius_tpu.node import (
    LocalChain,
    MinerNode,
    MiningConfig,
    ModelConfig,
    ModelRegistry,
    RegisteredModel,
)
from arbius_tpu.node.config import ConfigError, SchedConfig, load_config
from arbius_tpu.node.costmodel import (
    CostModel,
    bucket_str,
    make_cost_tag,
    parse_cost_tag,
    seeded_fit,
)
from arbius_tpu.node.db import NodeDB
from arbius_tpu.node.solver import bucket_key, chunk_items
from arbius_tpu.templates.engine import load_template
from tests.test_node import MINER, MODEL_ADDR, USER, drain

SCHED_ON = SchedConfig(enabled=True)


class _RecordingPinner:
    def __init__(self):
        self.pinned: dict[str, dict] = {}

    def pin_files(self, files: dict, taskid: str = "") -> bytes:
        self.pinned[taskid] = dict(files)
        return cid_of_solution_files(files)

    def pin_blob(self, content: bytes, filename: str = "input") -> bytes:
        from arbius_tpu.l0.cid import dag_of_file

        return dag_of_file(content).cid


class _ImageFakeRunner:
    """SD15Runner-shaped (dispatch/finalize) deterministic PNG-ish
    bytes; logs dispatches so pack order is observable."""

    def __init__(self, log=None):
        self.log = log if log is not None else []

    def __call__(self, hydrated, seed):
        return self.finalize(self.dispatch([(hydrated, seed)]), 1)[0]

    def run_batch(self, items):
        return self.finalize(self.dispatch(items), len(items))

    def dispatch(self, items):
        self.log.append([h.get("prompt") for h, _ in items])
        return [self._bytes(h, s) for h, s in items]

    def finalize(self, dev, n_real):
        return [{"out-1.png": dev[i]} for i in range(n_real)]

    @staticmethod
    def _bytes(hydrated, seed):
        blob = json.dumps({k: v for k, v in sorted(hydrated.items())
                           if k != "seed"}).encode()
        return b"\x89PNG" + blob + seed.to_bytes(8, "big")


class _VideoFakeRunner(_ImageFakeRunner):
    """Text2VideoRunner-shaped: same surface, mp4-ish bytes, and the
    bucket key genuinely varies over num_frames (the video-family
    distinction the packer must respect)."""

    def finalize(self, dev, n_real):
        return [{"out-1.mp4": b"\x00\x00\x00 ftypisom" + dev[i]}
                for i in range(n_real)]


def _world(families, *, sched=None, canonical_batch=1, pipeline=None,
           min_fee_per_second=0, db_path=":memory:", registry=None,
           **cfg_overrides):
    """Engine + node over N model families. `families` is a list of
    (template_name, runner); returns (eng, node, [model_ids], pinner)."""
    from arbius_tpu.node.config import PipelineConfig

    tok = TokenLedger()
    eng = Engine(tok, start_time=10_000)
    tok.mint(Engine.ADDRESS, 600_000 * WAD)
    for a in (MINER, USER):
        tok.mint(a, 1_000 * WAD)
        tok.approve(a, Engine.ADDRESS, 10**30)
    mids = []
    reg = registry or ModelRegistry()
    model_cfgs = []
    for template, runner in families:
        mid = "0x" + eng.register_model(USER, MODEL_ADDR, 0, b"{}").hex()
        reg.register(RegisteredModel(
            id=mid, template=load_template(template), runner=runner))
        model_cfgs.append(ModelConfig(id=mid, template=template))
        mids.append(mid)
    chain = LocalChain(eng, MINER)
    chain.validator_deposit(100 * WAD)
    cfg = MiningConfig(
        db_path=db_path, models=tuple(model_cfgs),
        canonical_batch=canonical_batch,
        sched=sched or SchedConfig(),
        pipeline=pipeline or PipelineConfig(),
        min_fee_per_second=min_fee_per_second,
        **cfg_overrides)
    pinner = _RecordingPinner()
    node = MinerNode(chain, cfg, reg, pinner=pinner)
    node.boot()
    drain(node)
    return eng, node, mids, pinner


def _submit(eng, mid, raw, fee=0):
    return "0x" + eng.submit_task(
        USER, 0, USER, bytes.fromhex(mid[2:]), fee,
        json.dumps(raw, sort_keys=True).encode()).hex()


IMG_SHAPES = [{"width": 256, "height": 256}, {"width": 512, "height": 512}]
VID_SHAPES = [{"num_frames": 8}, {"num_frames": 16}]


def _mine_mixed(runner_cls, template, shapes, *, sched, canonical_batch,
                n_tasks=8):
    """Drive a mixed-shape queue through one world; returns
    {taskid: (cid, pinned files)}."""
    eng, node, (mid,), pinner = _world(
        [(template, runner_cls())], sched=sched,
        canonical_batch=canonical_batch)
    tids = []
    for i in range(n_tasks):
        raw = {"prompt": f"task {i}", "negative_prompt": "",
               **shapes[i % len(shapes)]}
        tids.append(_submit(eng, mid, raw, fee=(1 + i % 3) * WAD))
    drain(node)
    out = {}
    for tid in tids:
        sol = eng.solutions[bytes.fromhex(tid[2:])]
        out[tid] = ("0x" + sol.cid.hex(), pinner.pinned.get(tid))
    node.close()
    return out


# -- byte equality: the golden acceptance gate ------------------------------

@pytest.mark.parametrize("batch", [1, 4])
@pytest.mark.parametrize("template,runner_cls,shapes", [
    ("anythingv3", _ImageFakeRunner, IMG_SHAPES),
    ("zeroscopev2xl", _VideoFakeRunner, VID_SHAPES),
])
def test_cids_and_bytes_identical_costsched_on_vs_fifo(
        template, runner_cls, shapes, batch):
    fifo = _mine_mixed(runner_cls, template, shapes, sched=None,
                       canonical_batch=batch)
    cost = _mine_mixed(runner_cls, template, shapes, sched=SCHED_ON,
                       canonical_batch=batch)
    assert fifo.keys() == cost.keys()
    for tid in fifo:
        cid_f, files_f = fifo[tid]
        cid_c, files_c = cost[tid]
        assert cid_f == cid_c, f"CID drift for {tid}"
        assert files_f == files_c, f"byte drift for {tid}"
        assert cid_c == cid_hex(cid_of_solution_files(files_c))


def test_bytes_identical_with_pipeline_and_costsched():
    """Packer + staged executor together: pack order feeds the device
    stage, bytes still identical to the plain FIFO synchronous path."""
    from arbius_tpu.node.config import PipelineConfig

    pipe = PipelineConfig(enabled=True, depth=2, encode_workers=2,
                          max_inflight_pins=2)

    def run(sched, pipeline):
        eng, node, (mid,), pinner = _world(
            [("anythingv3", _ImageFakeRunner())], sched=sched,
            canonical_batch=4, pipeline=pipeline)
        tids = [_submit(eng, mid,
                        {"prompt": f"t{i}", "negative_prompt": "",
                         **IMG_SHAPES[i % 2]}, fee=(1 + i) * WAD)
                for i in range(6)]
        drain(node)
        out = {t: pinner.pinned.get(t) for t in tids}
        node.close()
        return out

    assert run(None, None) == run(SCHED_ON, pipe)


# -- packing order ----------------------------------------------------------

def _prime(node, mid, shape, per_task_seconds, n=None):
    """Hand the cost model enough samples that `predict` answers."""
    key = bucket_key(mid, shape)
    for _ in range(n or node.costmodel.min_samples):
        node.costmodel.observe(mid, bucket_str(key), node.solve_layout,
                               per_task_seconds)
    node.costmodel.refit(now=0)


def test_packer_orders_by_fee_per_chip_second():
    eng, node, (mid,), _ = _world([("anythingv3", _ImageFakeRunner())],
                                  sched=SCHED_ON)
    slow = {"width": 512, "height": 512}
    fast = {"width": 256, "height": 256}
    _prime(node, mid, slow, 10.0)
    _prime(node, mid, fast, 1.0)
    k_slow, k_fast = bucket_key(mid, slow), bucket_key(mid, fast)
    packed = node._sched.pack([(k_slow, [("j", slow)], 5 * WAD),
                               (k_fast, [("j", fast)], 5 * WAD)])
    # same fee, 10× cheaper chip seconds → fast bucket first
    assert [b.key for b in packed] == [k_fast, k_slow]
    assert packed[0].source == "cost_model"
    # warm preference: warm the slow bucket and give it a fee edge too
    node._sched.mark_warm(k_slow)
    packed = node._sched.pack([(k_slow, [("j", slow)], 50 * WAD),
                               (k_fast, [("j", fast)], 5 * WAD)])
    assert packed[0].key == k_fast or packed[0].warm  # scored, not FIFO
    # equal everything → warm wins via the boost
    node._sched.mark_warm(k_slow)
    a = node._sched.pack([(k_slow, [("j", slow)], 5 * WAD),
                          (k_fast, [("j", fast)], 50 * WAD)])
    assert a[0].key == k_fast
    node.close()


def test_packer_reorder_visible_in_dispatch_log_and_journal():
    log = []
    eng, node, (mid,), _ = _world([("anythingv3", _ImageFakeRunner(log))],
                                  sched=SCHED_ON)
    # priming keys must match what hydration produces: the template's
    # defaults fill steps/scheduler (anythingv3 → 20, DPMSolverMultistep)
    defaults = {"num_inference_steps": 20,
                "scheduler": "DPMSolverMultistep"}
    slow = {"width": 512, "height": 512, **defaults}
    fast = {"width": 256, "height": 256, **defaults}
    _prime(node, mid, slow, 10.0)
    _prime(node, mid, fast, 1.0)
    # arrival order: slow first — packer must flip it (equal fees)
    _submit(eng, mid, {"prompt": "slow", "negative_prompt": "", **slow},
            fee=WAD)
    _submit(eng, mid, {"prompt": "fast", "negative_prompt": "", **fast},
            fee=WAD)
    log.clear()
    drain(node)
    assert log[0] == ["fast"] and log[1] == ["slow"]
    packs = node.obs.journal.events(kind="sched_pack")
    assert packs and packs[-1]["order"][0]["bucket"].startswith("256x256")
    node.close()


def test_fifo_default_keeps_arrival_order():
    log = []
    eng, node, (mid,), _ = _world([("anythingv3", _ImageFakeRunner(log))])
    _prime(node, mid, {"width": 512, "height": 512}, 10.0)
    _submit(eng, mid, {"prompt": "a", "negative_prompt": "",
                       "width": 512, "height": 512}, fee=WAD)
    _submit(eng, mid, {"prompt": "b", "negative_prompt": "",
                       "width": 256, "height": 256}, fee=WAD)
    log.clear()
    drain(node)
    assert log == [["a"], ["b"]]
    node.close()


# -- the profitability gate -------------------------------------------------

def test_empty_cost_model_reproduces_static_gate_exactly():
    """Acceptance pin: with no cost_model rows the gate IS the static
    path — same decisions as a sched-disabled node for every fee, both
    before any samples (assumed_solve_seconds prior) and after (global
    infer p50)."""
    def build(sched):
        return _world([("anythingv3", _ImageFakeRunner())], sched=sched,
                      min_fee_per_second=WAD, assumed_solve_seconds=7.0)

    eng_a, node_a, (mid_a,), _ = build(None)
    eng_b, node_b, (mid_b,), _ = build(SCHED_ON)
    hyd = {"prompt": "x", "negative_prompt": "", "width": 512,
           "height": 512}
    fees = [0, 6 * WAD, 7 * WAD, 8 * WAD, 10**30]
    for fee in fees:
        assert node_a._fee_covers_cost(fee, model_id=mid_a, hydrated=hyd) \
            == node_b._fee_covers_cost(fee, model_id=mid_b, hydrated=hyd) \
            == (fee >= 7 * WAD)
    # feed both the same infer sample; the static p50 must take over
    for node in (node_a, node_b):
        node._h_stage.observe(3.0, stage="infer")
    for fee in fees:
        assert node_a._fee_covers_cost(fee, model_id=mid_a, hydrated=hyd) \
            == node_b._fee_covers_cost(fee, model_id=mid_b, hydrated=hyd) \
            == (fee >= 3 * WAD)
    ev = node_b.obs.journal.events(kind="gate_decision")
    assert ev and all(e["source"] in ("static",) for e in ev)
    node_a.close()
    node_b.close()


def test_learned_gate_prices_per_bucket_and_journals():
    eng, node, (mid,), _ = _world([("anythingv3", _ImageFakeRunner())],
                                  sched=SCHED_ON, min_fee_per_second=WAD,
                                  assumed_solve_seconds=2.0)
    slow = {"prompt": "s", "negative_prompt": "", "width": 512,
            "height": 512}
    _prime(node, mid, slow, 9.0)
    # static prior would accept 5 WAD (floor 2); the learned row knows
    # this bucket really costs 9 s/task and rejects it
    assert not node._fee_covers_cost(5 * WAD, model_id=mid, hydrated=slow,
                                     taskid="0xabc")
    assert node._fee_covers_cost(9 * WAD, model_id=mid, hydrated=slow)
    ev = node.obs.journal.events(kind="gate_decision")
    assert ev[-2]["source"] == "cost_model"
    assert ev[-2]["verdict"] == "reject"
    assert ev[-2]["taskid"] == "0xabc"
    assert ev[-1]["verdict"] == "accept"
    # an unknown bucket still prices statically
    cold = {"prompt": "c", "negative_prompt": "", "width": 128,
            "height": 128}
    assert node._fee_covers_cost(2 * WAD, model_id=mid, hydrated=cold)
    assert node.obs.journal.events(kind="gate_decision")[-1]["source"] \
        == "static"
    node.close()


def test_gate_ignores_learned_rows_when_sched_disabled():
    """`enabled: false` is the full pre-costsched path: even with
    predict-eligible rows accrued (the model keeps learning for
    /debug and a later enable), decisions stay static."""
    eng, node, (mid,), _ = _world([("anythingv3", _ImageFakeRunner())],
                                  min_fee_per_second=WAD,
                                  assumed_solve_seconds=2.0)
    slow = {"prompt": "s", "negative_prompt": "", "width": 512,
            "height": 512}
    _prime(node, mid, slow, 9.0)
    # the learned row (9 s) would reject 5 WAD; the static prior (2 s)
    # accepts it — and static must win with the scheduler disabled
    assert node._fee_covers_cost(5 * WAD, model_id=mid, hydrated=slow)
    ev = node.obs.journal.events(kind="gate_decision")
    assert ev[-1]["source"] == "static"
    assert ev[-1]["predicted_seconds"] == 2.0
    node.close()


def test_prefloor_rejects_spam_before_input_fetch():
    """An obviously underpriced task never costs an input fetch or a
    hydration (the gate's pre-costsched placement), and every task
    journals exactly ONE gate_decision."""
    eng, node, (mid,), _ = _world([("anythingv3", _ImageFakeRunner())],
                                  sched=SCHED_ON, min_fee_per_second=WAD,
                                  assumed_solve_seconds=10.0)
    fetched = []
    orig = node.chain.get_task_input_bytes
    node.chain.get_task_input_bytes = \
        lambda tid: (fetched.append(tid), orig(tid))[1]
    cheap = _submit(eng, mid, {"prompt": "spam", "negative_prompt": ""},
                    fee=0)
    drain(node)
    assert cheap not in fetched, "spam task's input was fetched"
    assert node.metrics.tasks_unprofitable == 1
    rich = _submit(eng, mid, {"prompt": "ok", "negative_prompt": ""},
                   fee=100 * WAD)
    drain(node)
    assert rich in fetched
    assert bytes.fromhex(rich[2:]) in eng.solutions
    ev = node.obs.journal.events(kind="gate_decision")
    per_task = [e["taskid"] for e in ev]
    assert per_task.count(cheap) == 1 and per_task.count(rich) == 1
    node.close()


def test_prefloor_is_conservative_under_learned_rows():
    """Under costsched the pre-floor uses the CHEAPEST predictable
    cost, so a task below its own bucket's learned cost but above the
    cheapest bucket's still reaches the precise per-bucket gate (and
    is rejected there, with the learned evidence)."""
    eng, node, (mid,), _ = _world([("anythingv3", _ImageFakeRunner())],
                                  sched=SCHED_ON, min_fee_per_second=WAD,
                                  assumed_solve_seconds=2.0)
    defaults = {"num_inference_steps": 20,
                "scheduler": "DPMSolverMultistep"}
    _prime(node, mid, {"width": 256, "height": 256, **defaults}, 1.0)
    _prime(node, mid, {"width": 512, "height": 512, **defaults}, 9.0)
    # 5 WAD: above the cheap bucket's 1 s floor (pre-floor passes),
    # below the 512² bucket's learned 9 s cost (precise gate rejects)
    tid = _submit(eng, mid, {"prompt": "mid", "negative_prompt": "",
                             "width": 512, "height": 512}, fee=5 * WAD)
    drain(node)
    assert bytes.fromhex(tid[2:]) not in eng.solutions
    ev = node.obs.journal.events(kind="gate_decision")
    assert ev[-1]["taskid"] == tid
    assert ev[-1]["verdict"] == "reject"
    assert ev[-1]["source"] == "cost_model"
    assert ev[-1]["predicted_seconds"] == 9.0
    node.close()


def test_unprofitable_counter_gains_model_label():
    eng, node, (mid,), _ = _world([("anythingv3", _ImageFakeRunner())],
                                  min_fee_per_second=WAD,
                                  assumed_solve_seconds=10.0)
    _submit(eng, mid, {"prompt": "cheap", "negative_prompt": ""}, fee=0)
    drain(node)
    c = node.obs.registry.counter("arbius_tasks_unprofitable_total",
                                  labelnames=("model",))
    assert c.value(model=mid) == 1
    # back-compat attribute sums the labeled children
    assert node.metrics.tasks_unprofitable == 1
    # and the rejection is journaled with the pricing evidence
    ev = node.obs.journal.events(kind="gate_decision")
    assert ev[-1]["verdict"] == "reject" and ev[-1]["model"] == mid
    assert ev[-1]["fee"] == "0"
    node.close()


# -- the learned fit --------------------------------------------------------

def test_seeded_fit_is_deterministic_and_robust():
    vals = [1.0, 1.1, 0.9, 1.05, 50.0]  # one straggler
    a = seeded_fit(vals, ("m", "b", "l"))
    assert a == seeded_fit(list(vals), ("m", "b", "l"))
    assert 0.9 <= a <= 1.1  # median, not mean
    big = [float(i % 17) for i in range(500)]
    assert seeded_fit(big, ("k",)) == seeded_fit(list(big), ("k",))
    # subsample keys matter (different seeds stream), values still sane
    assert 0.0 <= seeded_fit(big, ("other",)) <= 16.0


def test_cost_tag_roundtrip_and_ingest():
    key = ("0xmm", 512, 512, 20, "DDIM", None)
    tag = make_cost_tag(key[0], bucket_str(key), "single", 4)
    assert parse_cost_tag(tag) == ("0xmm", "512x512.s20.DDIM.f-",
                                   "single", "bf16", 4)
    # pre-quant 4-field tags (old snapshots, mixed-version fleets)
    # parse as bf16 — that is the program they metered
    assert parse_cost_tag("0xmm|512x512.s20.DDIM.f-|single|n4") == \
        ("0xmm", "512x512.s20.DDIM.f-", "single", "bf16", 4)
    assert parse_cost_tag(None) is None
    assert parse_cost_tag("0xtask") is None
    assert parse_cost_tag("a|b|c|nx") is None
    assert parse_cost_tag("a|b|c|bf16|nx") is None
    # a foreign 5-field tag must never mint an arbitrary mode key
    assert parse_cost_tag("a|b|c|junk|n2") is None
    m = CostModel(min_samples=2)
    assert m.ingest_samples([(tag, 8.0), (tag, 12.0), (None, 3.0),
                             ("garbage", 1.0)]) == 2
    m.refit(now=5)
    # 8s and 12s over 4 tasks each → 2.0 and 3.0 per task → median 2.5
    assert m.predict("0xmm", "512x512.s20.DDIM.f-", "single") == 2.5
    assert m.predict("0xmm", "512x512.s20.DDIM.f-", "dp2") is None
    # mode rides the tag: an int8 row never answers for bf16
    assert m.predict("0xmm", "512x512.s20.DDIM.f-", "single",
                     "int8") is None
    snap = m.snapshot()
    assert snap["rows"][0]["samples"] == 2
    assert snap["rows"][0]["updated"] == 5
    assert snap["rows"][0]["mode"] == "bf16"


def test_cost_rows_never_merge_across_precision_modes():
    """The quantserve pin (docs/quantization.md): the same (model,
    bucket, layout) at different precision modes fits SEPARATE rows —
    an int8 program's chip-seconds must never blend into (or answer
    for) its bf16 twin's price."""
    m = CostModel(min_samples=1)
    bf = make_cost_tag("0xmm", "512x512.s20.DDIM.f-", "single", 2)
    q8 = make_cost_tag("0xmm", "512x512.s20.DDIM.f-", "single", 2,
                       mode="int8")
    assert bf != q8
    m.ingest_samples([(bf, 8.0), (bf, 8.0), (q8, 4.0), (q8, 4.0)])
    m.refit(now=1)
    assert m.predict("0xmm", "512x512.s20.DDIM.f-", "single") == 4.0
    assert m.predict("0xmm", "512x512.s20.DDIM.f-", "single",
                     "int8") == 2.0
    rows = {(r.mode): r for r in m.sorted_rows()}
    assert set(rows) == {"bf16", "int8"}
    assert all(r.samples == 2 for r in rows.values())


def test_cost_model_persists_across_node_lives(tmp_path):
    db_path = str(tmp_path / "node.sqlite")
    eng, node, (mid,), _ = _world([("anythingv3", _ImageFakeRunner())],
                                  sched=SCHED_ON, db_path=db_path)
    for i in range(node.costmodel.min_samples):
        _submit(eng, mid, {"prompt": f"t{i}", "negative_prompt": ""},
                fee=WAD)
    drain(node)
    rows = node.db.load_cost_rows()
    assert rows, "mining must persist fitted cost rows"
    key = bucket_key(mid, {"width": 768, "height": 768,
                           "num_inference_steps": 20,
                           "scheduler": "DDIM"})
    node.close()

    # a fresh life on the same sqlite file prices immediately
    m2 = CostModel(min_samples=1)
    db2 = NodeDB(db_path)
    assert m2.load(db2) == len(rows)
    model, bucket, layout, mode = rows[0][:4]
    assert m2.predict(model, bucket, layout, mode) == \
        pytest.approx(rows[0][4])
    db2.close()


def test_pipeline_feeds_the_same_cost_signal(tmp_path):
    """Cost rows accrue under the staged executor too — the tag rides
    the per-bucket infer observation both schedules share."""
    from arbius_tpu.node.config import PipelineConfig

    eng, node, (mid,), _ = _world(
        [("anythingv3", _ImageFakeRunner())], sched=SCHED_ON,
        canonical_batch=2,
        pipeline=PipelineConfig(enabled=True, depth=2, encode_workers=2,
                                max_inflight_pins=2))
    for i in range(4):
        _submit(eng, mid, {"prompt": f"t{i}", "negative_prompt": ""},
                fee=WAD)
    drain(node)
    rows = node.costmodel.sorted_rows()
    assert rows and rows[0].model == mid
    assert rows[0].bucket == "768x768.s20.DPMSolverMultistep.f-"
    node.close()


# -- chunk_items edge cases (satellite) -------------------------------------

def test_chunk_items_empty_list():
    assert chunk_items([], 4) == []


def test_chunk_items_bucket_smaller_than_canonical_batch():
    items = [({"p": 1}, 11)]
    chunks = chunk_items(items, 4)
    assert len(chunks) == 1
    padded, real = chunks[0]
    assert real == 1 and len(padded) == 4
    assert padded == [({"p": 1}, 11)] * 4  # pad repeats the last real


def test_chunk_items_padding_repeat_correctness():
    items = [({"p": i}, i) for i in range(6)]
    chunks = chunk_items(items, 4)
    assert [real for _, real in chunks] == [4, 2]
    full, tail = chunks[0][0], chunks[1][0]
    assert full == items[:4]
    assert tail[:2] == items[4:6]
    assert tail[2:] == [items[5], items[5]]  # repeats the FINAL real item
    # exact multiple: no padding at all
    chunks = chunk_items(items[:4], 2)
    assert all(len(p) == 2 and r == 2 for p, r in chunks)


# -- jit-cache metrics (satellite) ------------------------------------------

def test_jit_cache_metrics_and_warm_set():
    import numpy as np

    from arbius_tpu.obs import Obs, use_obs
    from arbius_tpu.parallel.meshsolve import ShardedImageProbe

    obs = Obs(journal_capacity=64)
    probe = ShardedImageProbe()
    items = [({"prompt": "x"}, 7), ({"prompt": "y"}, 8)]
    with use_obs(obs):
        np.asarray(probe.dispatch(items))   # cold: miss + compile sample
        np.asarray(probe.dispatch(items))   # warm: hit
    reg = obs.registry
    assert reg.counter("arbius_jit_cache_misses_total").value() == 1
    assert reg.counter("arbius_jit_cache_hits_total",
                       labelnames=("tier",)).value(tier="memory") == 1
    h = reg.histogram("arbius_compile_seconds")
    assert h.count() == 1
    assert h.recent()[0][0] == "meshprobe.img.b2"
    assert "meshprobe.img.b2" in obs.jit_warm


# -- debug surface ----------------------------------------------------------

def test_debug_costmodel_endpoint():
    from arbius_tpu.node.rpc import ControlRPC

    eng, node, (mid,), _ = _world([("anythingv3", _ImageFakeRunner())],
                                  sched=SCHED_ON, rpc_port=0)
    _prime(node, mid, {"width": 512, "height": 512}, 4.0)
    rpc = ControlRPC(node, port=0)
    rpc.start()
    try:
        code, payload = rpc.debug_view("/debug/costmodel")
    finally:
        rpc.stop()
    assert code == 200
    assert payload["sched"]["policy"] == "costsched"
    assert payload["jit_warm"] == sorted(node.obs.jit_warm)
    assert payload["layout"] == "single"
    assert payload["cost_model"]["rows"][0]["chip_seconds"] == 4.0
    # the precision surface (docs/quantization.md): every row carries
    # its mode and the per-model mode table is served alongside
    assert payload["cost_model"]["rows"][0]["mode"] == "bf16"
    assert payload["modes"] == {mid.lower(): "bf16"}
    json.dumps(payload, sort_keys=True)  # JSON-able end to end
    node.close()


# -- config surface ---------------------------------------------------------

def test_sched_config_loads_and_validates():
    cfg = load_config({"sched": {"enabled": True, "min_samples": 4,
                                 "warm_boost": 2.0}})
    assert cfg.sched.enabled and cfg.sched.min_samples == 4
    assert not load_config({}).sched.enabled  # default: FIFO
    with pytest.raises(ConfigError, match="min_samples"):
        load_config({"sched": {"min_samples": 0}})
    with pytest.raises(ConfigError, match="warm_boost"):
        load_config({"sched": {"warm_boost": 0.5}})


# -- the costmodel CLI (golden-pinned) --------------------------------------

FIXTURES = "tests/fixtures/costmodel"


def _run_cli(argv):
    import io
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    try:
        import costmodel as cli
    finally:
        sys.path.pop(0)
    out = io.StringIO()
    old = sys.stdout
    sys.stdout = out
    try:
        rc = cli.main(argv)
    finally:
        sys.stdout = old
    return rc, out.getvalue()


def test_costmodel_cli_fit_matches_golden_byte_identical():
    rc, out = _run_cli(["--fit", f"{FIXTURES}/snapshot.json", "--json",
                        "--min-samples", "2"])
    assert rc == 0
    with open(f"{FIXTURES}/golden_fit.json") as f:
        assert out == f.read()
    # run twice: byte-identical (the seeded-fit determinism contract)
    rc2, out2 = _run_cli(["--fit", f"{FIXTURES}/snapshot.json", "--json",
                          "--min-samples", "2"])
    assert out2 == out


def test_costmodel_cli_dump_roundtrips_sqlite(tmp_path):
    db = NodeDB(str(tmp_path / "x.sqlite"))
    db.upsert_cost_rows([("0xaa", "512x512.s20.DDIM.f-", "single",
                          "bf16", 3.25, 12, 99)])
    db.close()
    rc, out = _run_cli(["--db", str(tmp_path / "x.sqlite"), "--dump",
                        "--json"])
    assert rc == 0
    rows = json.loads(out)["rows"]
    assert rows == [{"model": "0xaa", "bucket": "512x512.s20.DDIM.f-",
                     "layout": "single", "mode": "bf16",
                     "chip_seconds": 3.25, "samples": 12, "updated": 99}]
    rc, txt = _run_cli(["--db", str(tmp_path / "x.sqlite"), "--dump"])
    assert rc == 0 and "512x512.s20.DDIM.f-" in txt


def test_costmodel_cli_usage_errors():
    rc, _ = _run_cli(["--dump"])          # --dump without --db
    assert rc == 2
    rc, _ = _run_cli([])                  # neither mode
    assert rc == 2


# -- simnet: the scheduler under a mixed-family flood -----------------------

def test_simnet_sched_flood_holds_all_invariants():
    """Acceptance pin: the costsched packer reordering a burst-submitted
    two-family flood (varied shapes + fees, latency + slow-runner
    faults) keeps SIM101-109 green and every task claimed."""
    from arbius_tpu.sim.harness import run_scenario
    from arbius_tpu.sim.invariants import check_all, classify_tasks
    from arbius_tpu.sim.scenario import get_scenario

    result = run_scenario(get_scenario("sched-flood"), 1)
    findings = check_all(result)
    assert not findings, "\n".join(f.text() for f in findings)
    assert set(classify_tasks(result).values()) == {"claimed"}
    assert len(result.tasks) == 16
