"""Every example module must run clean — they are the Example/*.sol parity
surface and double as living documentation."""
from __future__ import annotations

import importlib

import pytest

EXAMPLES = [
    "register_model", "submit_task", "retract_task", "submit_solution",
    "claim_solution", "submit_contestation", "vote_on_contestation",
    "finish_contestation", "lookups", "validator_stake",
    "governance_proposal", "emission_curve",
    # full_mining_flow is the demo-mine CLI path — exercised in its own
    # (slow, jit-compiling) test below
]


@pytest.mark.parametrize("name", EXAMPLES)
def test_example_runs(name, capsys):
    mod = importlib.import_module(f"examples.{name}")
    mod.main()
    assert capsys.readouterr().out.strip()


def test_full_mining_flow_example(capsys):
    mod = importlib.import_module("examples.full_mining_flow")
    assert mod.main() == 0
    out = capsys.readouterr().out
    assert "claimed: True" in out
