"""Fleet unit suite: lease table semantics, coordinator intake, the
worker-mode seams, commit dedupe, wallet guard, config validation, and
the satellites that rode the fleet PR (NodeDB WAL/busy_timeout,
structured nonce-conflict classification, labeled callback gauges).

The end-to-end fleet scenarios (SIM111, partitions, coordinator crash,
the 10k flood) live in tests/test_sim.py with the rest of the simnet
matrix; this file covers the pieces in isolation.
"""
from __future__ import annotations

import json
import threading

import pytest

from arbius_tpu.fleet import (
    FleetCoordinator,
    LeaseFeed,
    LeaseTable,
    connect_fleet_db,
    make_worker_id,
)
from arbius_tpu.node.config import ConfigError, FleetConfig, load_config


@pytest.fixture
def table(tmp_path):
    t = LeaseTable(str(tmp_path / "leases.sqlite"))
    yield t
    t.close()


# -- lease table -----------------------------------------------------------

def test_add_acquire_in_insertion_order(table):
    for i in range(5):
        table.add_task(f"0x{i:02d}", "0xm", fee=i, blocktime=100 + i,
                       now=100 + i)
    grants = table.acquire("worker-0", now=110, ttl=30, limit=3)
    assert [g.taskid for g in grants] == ["0x00", "0x01", "0x02"]
    assert all(not g.stolen and g.attempts == 1 for g in grants)
    # the rest stays pending; re-acquire skips what is already leased
    more = table.acquire("worker-1", now=110, ttl=30, limit=10)
    assert [g.taskid for g in more] == ["0x03", "0x04"]
    assert table.counts() == {"leased": 5}


def test_add_task_is_replay_idempotent(table):
    assert table.add_task("0xaa", "0xm", 1, 100, 100)
    assert not table.add_task("0xaa", "0xm", 1, 100, 101)
    assert table.counts() == {"pending": 1}


def test_expired_lease_is_stolen_with_lag_recorded(table):
    table.add_task("0xaa", "0xm", 1, 100, 100)
    table.acquire("worker-0", now=100, ttl=30, limit=1)
    # not yet expired: nothing to steal
    assert table.acquire("worker-1", now=120, ttl=30, limit=1) == []
    grants = table.acquire("worker-1", now=140, ttl=30, limit=1)
    assert [g.taskid for g in grants] == ["0xaa"]
    assert grants[0].stolen and grants[0].attempts == 2
    steal = [h for h in table.history if h[0] == "steal"]
    assert steal and steal[0][4]["lag"] == 140 - 130


def test_heartbeat_keeps_a_lease_unstealable(table):
    table.add_task("0xaa", "0xm", 1, 100, 100)
    table.acquire("worker-0", now=100, ttl=30, limit=1)
    assert table.heartbeat("worker-0", now=125, ttl=30) == 1
    assert table.acquire("worker-1", now=140, ttl=30, limit=1) == []
    assert table.held("worker-0") == ["0xaa"]


def test_complete_is_holder_agnostic_and_terminal_once(table):
    table.add_task("0xaa", "0xm", 1, 100, 100)
    table.acquire("worker-0", now=100, ttl=30, limit=1)
    # another worker observed the solution on chain — it may settle
    assert table.complete("0xaa", "worker-1", now=110) == 10.0
    assert table.complete("0xaa", "worker-1", now=111) is None
    assert table.counts() == {"done": 1}


def test_release_returns_to_pending_then_fails_at_attempt_bound(table):
    table.add_task("0xaa", "0xm", 1, 100, 100)
    for attempt in range(1, 3):
        g = table.acquire(f"worker-{attempt}", now=100 + attempt,
                          ttl=30, limit=1)
        assert g[0].attempts == attempt
        state = table.release("0xaa", f"worker-{attempt}",
                              now=101 + attempt, max_attempts=2)
        assert state == ("pending" if attempt < 2 else "failed")
    assert table.counts() == {"failed": 1}


def test_reclaim_sweeps_expired_leases(table):
    for i in range(2):
        table.add_task(f"0x{i:02d}", "0xm", 1, 100, 100)
    table.acquire("worker-0", now=100, ttl=30, limit=2)
    assert table.reclaim(now=120, max_attempts=4) == []
    swept = table.reclaim(now=131, max_attempts=4)
    assert [(t, w) for t, w, _ in swept] == \
        [("0x00", "worker-0"), ("0x01", "worker-0")]
    assert swept[0][2] == 1  # lag past expiry
    assert table.counts() == {"pending": 2}


def test_claim_commit_grant_deny_and_takeover(table):
    table.add_task("0xaa", "0xm", 1, 100, 100)
    table.acquire("worker-0", now=100, ttl=30, limit=1)
    assert table.claim_commit("0xaa", "0xv0", "worker-0", "0xcid", 101)
    # idempotent resume for the holder
    assert table.claim_commit("0xaa", "0xv0", "worker-0", "0xcid", 102)
    # denied while the holder's lease is live
    assert not table.claim_commit("0xaa", "0xv1", "worker-1", "0xcid",
                                  110)
    # after the holder's lease expires and is stolen, rights transfer
    table.acquire("worker-1", now=140, ttl=30, limit=1)
    assert table.claim_commit("0xaa", "0xv1", "worker-1", "0xcid", 141)
    rows = table.commit_rows()
    assert [(r["taskid"], r["worker"]) for r in rows] == \
        [("0xaa", "worker-1")]


def test_two_handles_on_one_file_interoperate(tmp_path):
    """The cross-process analogue: two LeaseTable objects (separate
    sqlite connections) on one file see each other's transitions."""
    path = str(tmp_path / "shared.sqlite")
    a, b = LeaseTable(path), LeaseTable(path)
    try:
        a.add_task("0xaa", "0xm", 1, 100, 100)
        grants = b.acquire("worker-b", now=100, ttl=30, limit=1)
        assert [g.taskid for g in grants] == ["0xaa"]
        assert a.counts() == {"leased": 1}
        assert a.acquire("worker-a", now=101, ttl=30, limit=1) == []
    finally:
        a.close()
        b.close()


def test_connect_fleet_db_sets_the_discipline(tmp_path):
    conn = connect_fleet_db(str(tmp_path / "x.sqlite"),
                            busy_timeout_ms=1234)
    assert conn.execute("PRAGMA busy_timeout").fetchone()[0] == 1234
    assert conn.execute("PRAGMA journal_mode").fetchone()[0] == "wal"
    conn.close()


def test_wallet_guard_serializes_across_threads(table):
    """The shared-wallet mutex: a second enter blocks until the first
    exits (BEGIN IMMEDIATE on the shared file)."""
    order = []
    entered = threading.Event()
    release = threading.Event()

    def first():
        with table.wallet_guard("0xAB", "worker-0"):
            order.append("first-in")
            entered.set()
            release.wait(timeout=5)
        order.append("first-out")

    # second guard on its OWN handle (another "process")
    other = LeaseTable(table._path)
    t1 = threading.Thread(target=first)
    t1.start()
    assert entered.wait(timeout=5)

    def second():
        with other.wallet_guard("0xAB", "worker-1"):
            order.append("second-in")

    t2 = threading.Thread(target=second)
    t2.start()
    t2.join(timeout=0.3)
    assert "second-in" not in order  # still blocked behind first
    release.set()
    t1.join(timeout=5)
    t2.join(timeout=5)
    other.close()
    assert order == ["first-in", "first-out", "second-in"]


def test_tx_guard_reads_nonce_inside_the_guard():
    """EngineRpcClient.send_to must do nonce-read → sign → send inside
    the guard window, not sign first."""
    from contextlib import contextmanager

    from arbius_tpu.chain.rpc_client import EngineRpcClient
    from arbius_tpu.chain.wallet import Wallet

    events = []

    class Transport:
        def request(self, method, params):
            events.append(method)
            if method == "eth_getTransactionCount":
                return "0x7"
            if method == "eth_gasPrice":
                return "0x10"
            return "0x" + "00" * 32

    @contextmanager
    def guard():
        events.append("guard-enter")
        yield
        events.append("guard-exit")

    client = EngineRpcClient(Transport(), "0x" + "11" * 20,
                             Wallet.from_hex("0x" + "a1" * 32),
                             chain_id=31337, tx_guard=guard)
    client.send("signalCommitment", [b"\x00" * 32])
    assert events[0] == "guard-enter"
    assert events[-1] == "guard-exit"
    assert "eth_getTransactionCount" in events[1:-1]
    assert "eth_sendRawTransaction" in events[1:-1]


# -- coordinator + feed ----------------------------------------------------

def _world():
    from arbius_tpu.chain import Engine
    from arbius_tpu.chain.fixedpoint import WAD
    from arbius_tpu.chain.token import TokenLedger
    from arbius_tpu.node import LocalChain

    tok = TokenLedger()
    eng = Engine(tok, start_time=100_000)
    tok.mint(Engine.ADDRESS, 600_000 * WAD)
    user = "0x" + "b2" * 20
    miner = "0x" + "a1" * 20
    for a in (user, miner):
        tok.mint(a, 10_000 * WAD)
        tok.approve(a, Engine.ADDRESS, 10**30)
    tok.transfer(Engine.ADDRESS, "0x" + "99" * 20, 100_000 * WAD)
    eng.validator_deposit(miner, miner, 400 * WAD)
    mid = "0x" + eng.register_model(
        user, user, 0, b'{"meta":{"title":"t"}}').hex()
    return eng, LocalChain(eng, user), LocalChain(eng, miner), mid


def _submit(user_chain, mid, i=0):
    from arbius_tpu.chain.fixedpoint import WAD

    user_chain.submit_task(
        0, user_chain.address, mid, 1 * WAD,
        json.dumps({"prompt": f"t {i}", "negative_prompt": ""},
                   sort_keys=True).encode())


def test_coordinator_leases_only_registered_models(tmp_path):
    eng, user, miner, mid = _world()
    table = LeaseTable(str(tmp_path / "l.sqlite"))
    other = "0x" + eng.register_model(
        user.address, user.address, 0, b'{"meta":{"title":"o"}}').hex()
    FleetCoordinator(LocalChainView(eng), table, [mid],
                     FleetConfig(enabled=True))
    _submit(user, mid, 0)
    _submit(user, other, 1)
    counts = table.counts()
    assert counts == {"pending": 1}
    row = table.rows()[0]
    assert row["model"] == mid and row["state"] == "pending"
    table.close()


class LocalChainView:
    """Minimal coordinator chain facade over the in-process engine."""

    def __init__(self, engine):
        from arbius_tpu.node import LocalChain

        self._inner = LocalChain(engine, "0x" + "cc" * 20)

    def __getattr__(self, name):
        return getattr(self._inner, name)


def _worker_node(eng, miner, mid, table, tmp_path, index=0,
                 fleet_cfg=None):
    import hashlib

    from arbius_tpu.node import (
        MinerNode,
        MiningConfig,
        ModelConfig,
        ModelRegistry,
        NodeDB,
        RegisteredModel,
    )
    from arbius_tpu.templates.engine import load_template

    def runner(hydrated, seed):
        canon = json.dumps(
            {k: v for k, v in hydrated.items() if k != "seed"},
            sort_keys=True).encode()
        return {"out-1.png":
                hashlib.sha256(canon + seed.to_bytes(8, "big")).digest()}

    registry = ModelRegistry()
    registry.register(RegisteredModel(
        id=mid, template=load_template("anythingv3"), runner=runner))
    cfg = MiningConfig(models=(ModelConfig(id=mid,
                                           template="anythingv3"),))
    node = MinerNode(miner, cfg, registry, db=NodeDB(":memory:"),
                     store=None, pinner=None)
    fleet_cfg = fleet_cfg or FleetConfig(
        enabled=True, max_leases=2, backlog=3,
        lease_db=str(tmp_path / "unused.sqlite"))
    feed = LeaseFeed(table, make_worker_id(index), fleet_cfg)
    feed.attach(node)
    node.boot(skip_self_test=True)
    return node, feed


def test_worker_mode_ignores_task_events_and_pulls_leases(tmp_path):
    eng, user, miner, mid = _world()
    table = LeaseTable(str(tmp_path / "l.sqlite"))
    node, feed = _worker_node(eng, miner, mid, table, tmp_path)
    coord = FleetCoordinator(LocalChainView(eng), table, [mid],
                             FleetConfig(enabled=True))
    for i in range(5):
        _submit(user, mid, i)
    # the node saw the TaskSubmitted events but queued NOTHING itself
    assert not node.db.has_job("task", {"taskid": table.rows()[0]["taskid"]})
    node.tick()   # pump: pulls min(max_leases=2, backlog=3) = 2
    assert len(table.held("worker-0")) + \
        table.counts().get("done", 0) >= 2
    # backlog gate: with 3 task/solve jobs in flight no further pull
    depth = node.db.count_jobs(("task", "solve", "pinTaskInput"))
    assert depth <= 3
    table.close()


def test_fleet_lifecycle_settles_every_lease(tmp_path):
    eng, user, miner, mid = _world()
    table = LeaseTable(str(tmp_path / "l.sqlite"))
    node, feed = _worker_node(eng, miner, mid, table, tmp_path)
    FleetCoordinator(LocalChainView(eng), table, [mid],
                     FleetConfig(enabled=True))
    for i in range(4):
        _submit(user, mid, i)
    for _ in range(40):
        node.tick()
        counts = table.counts()
        if counts.get("done", 0) == 4:
            break
        jobs = [j for j in node.db.get_jobs(2**60, limit=100)
                if j.method not in ("automine", "validatorStake")]
        if jobs and all(j.waituntil > eng.now for j in jobs):
            eng.advance_time(max(j.waituntil for j in jobs) - eng.now,
                             blocks=1)
    assert table.counts() == {"done": 4}
    assert sum(1 for s in eng.solutions.values() if s.claimed) == 4
    table.close()


def test_commit_guard_skips_second_committer(tmp_path):
    """Unit version of the cross-process dedupe: rights already granted
    to a live other worker → the node journals commit_deduped and
    signals nothing."""
    eng, user, miner, mid = _world()
    table = LeaseTable(str(tmp_path / "l.sqlite"))
    node, feed = _worker_node(eng, miner, mid, table, tmp_path)
    table.add_task("0x" + "ab" * 32, mid, 1, 100, 100)
    # worker-9 holds the lease AND the rights, live
    table.acquire("worker-9", now=eng.now, ttl=10**6, limit=1)
    assert table.claim_commit("0x" + "ab" * 32, "0xother", "worker-9",
                              "0xcid", eng.now)
    before = len(eng.commitments)
    node._commit_reveal("0x" + "ab" * 32, "0x1220" + "00" * 32, eng.now)
    assert len(eng.commitments) == before
    deduped = [e for e in node.obs.journal.events()
               if e.get("kind") == "commit_deduped"]
    assert deduped and deduped[0]["taskid"] == "0x" + "ab" * 32
    assert node.obs.registry.counter(
        "arbius_fleet_commit_dedup_total").value() == 1
    table.close()


def test_invalid_task_settles_lease_invalid(tmp_path):
    eng, user, miner, mid = _world()
    table = LeaseTable(str(tmp_path / "l.sqlite"))
    node, feed = _worker_node(eng, miner, mid, table, tmp_path)
    FleetCoordinator(LocalChainView(eng), table, [mid],
                     FleetConfig(enabled=True))
    from arbius_tpu.chain.fixedpoint import WAD

    user.submit_task(0, user.address, mid, 1 * WAD, b'{"prompt": broken')
    node.tick()   # lease + task job (hydration fails -> invalid)
    node.tick()   # settle pass sees the invalid verdict
    assert table.counts() == {"invalid": 1}
    table.close()


# -- config ----------------------------------------------------------------

def test_fleet_config_validation():
    with pytest.raises(ConfigError, match="workers"):
        FleetConfig(workers=0)
    with pytest.raises(ConfigError, match="lease_ttl"):
        FleetConfig(lease_ttl=0)
    with pytest.raises(ConfigError, match="wallet_mode"):
        FleetConfig(wallet_mode="communal")
    with pytest.raises(ConfigError, match="lease_db"):
        FleetConfig(lease_db=":memory:")
    with pytest.raises(ConfigError, match="max_leases"):
        FleetConfig(max_leases=0)
    with pytest.raises(ConfigError, match="backlog"):
        FleetConfig(max_leases=4, backlog=2)
    with pytest.raises(ConfigError, match="max_attempts"):
        FleetConfig(max_attempts=0)
    with pytest.raises(ConfigError, match="busy_timeout"):
        FleetConfig(busy_timeout_ms=-1)


def test_fleet_block_loads_from_config_json():
    cfg = load_config(json.dumps({
        "fleet": {"enabled": True, "workers": 3, "lease_ttl": 45,
                  "wallet_mode": "shared"}}))
    assert cfg.fleet.enabled and cfg.fleet.workers == 3
    assert cfg.fleet.lease_ttl == 45
    assert cfg.fleet.wallet_mode == "shared"
    with pytest.raises(ConfigError, match="fleet"):
        load_config(json.dumps({"fleet": {"bogus_knob": 1}}))


def test_example_config_ships_a_fleet_block():
    import pathlib

    raw = (pathlib.Path(__file__).parent.parent /
           "MiningConfig.example.json").read_text()
    cfg = load_config(raw)
    assert not cfg.fleet.enabled   # out of the box: single node
    assert cfg.fleet.workers == 2 and cfg.fleet.lease_db


def test_db_busy_timeout_validated():
    with pytest.raises(ConfigError, match="db_busy_timeout_ms"):
        load_config(json.dumps({"db_busy_timeout_ms": -5}))


# -- NodeDB satellites -----------------------------------------------------

def test_nodedb_sets_wal_and_busy_timeout(tmp_path):
    from arbius_tpu.node import NodeDB

    db = NodeDB(str(tmp_path / "n.sqlite"), busy_timeout_ms=777)
    assert db._conn.execute("PRAGMA journal_mode").fetchone()[0] == "wal"
    assert db._conn.execute("PRAGMA busy_timeout").fetchone()[0] == 777
    db.close()


def test_nodedb_count_jobs(tmp_path):
    from arbius_tpu.node import NodeDB

    db = NodeDB(":memory:")
    db.queue_job("task", {"taskid": "0x1"}, concurrent=True)
    db.queue_job("solve", {"taskid": "0x1"})
    db.queue_job("claim", {"taskid": "0x1"}, waituntil=10**9)
    assert db.count_jobs(("task", "solve", "pinTaskInput")) == 2
    assert db.count_jobs(("claim",)) == 1
    db.close()


# -- obs satellite: labeled callback gauges --------------------------------

def test_labeled_callback_gauge():
    from arbius_tpu.obs.registry import MetricsRegistry

    reg = MetricsRegistry()
    source = {"pending": 3, "leased": 2}
    g = reg.gauge("arbius_fleet_leases", "leases by state",
                  labelnames=("state",), fn=lambda: source)
    assert g.value(state="pending") == 3.0
    assert g.value(state="nope") == 0.0
    rendered = reg.render()
    assert 'arbius_fleet_leases{state="leased"} 2' in rendered
    assert 'arbius_fleet_leases{state="pending"} 3' in rendered
    assert g.summary() == {"state=leased": 2.0, "state=pending": 3.0}
    source["done"] = 9   # collect-time: the NEXT scrape sees it
    assert g.value(state="done") == 9.0


def test_labeled_callback_gauge_survives_dead_source():
    from arbius_tpu.obs.registry import MetricsRegistry

    reg = MetricsRegistry()

    def dead():
        raise RuntimeError("closed handle")

    g = reg.gauge("arbius_dead", "x", labelnames=("state",), fn=dead)
    v = g.value(state="x")
    assert v != v   # NaN: a dead source must not look like "drained"
    assert "arbius_dead NaN" in reg.render()   # scrape does not explode


def test_release_by_stale_holder_is_rejected(table):
    """A worker whose expired lease was stolen must not flip the
    thief's LIVE lease: release is holder-checked (the fleet-partition
    race a non-atomic held()→release() pair can hit)."""
    table.add_task("0xaa", "0xm", 1, 100, 100)
    table.acquire("worker-0", now=100, ttl=30, limit=1)
    table.acquire("worker-1", now=140, ttl=30, limit=1)   # the steal
    assert table.release("0xaa", "worker-0", now=141,
                         max_attempts=1) == "stolen"
    # worker-1's lease untouched — still live, still theirs
    assert table.held("worker-1") == ["0xaa"]
    assert table.counts() == {"leased": 1}


def test_geth_shape_nonce_errors_classify_as_engine_errors():
    from arbius_tpu.chain import EngineError
    from arbius_tpu.chain.rpc_client import RpcError
    from arbius_tpu.node.rpc_chain import (
        ChainRpcError,
        _engine_error,
        is_nonce_error,
        nonce_conflict,
    )

    for msg in ("nonce too low: next nonce 3, tx nonce 5",
                "nonce too high", "replacement transaction underpriced",
                "already known"):
        e = RpcError("{...}", code=-32000, message=msg)
        assert is_nonce_error(e), msg
        assert isinstance(_engine_error(e), EngineError), msg
    # the phrases guard the MESSAGE field only — echoed calldata in
    # data (or an empty message falling back to the payload) never
    # classifies
    e = RpcError("{'data': 'nonce too low revert poem'}", code=-32000,
                 message="", data="nonce too low revert poem")
    assert not is_nonce_error(e) and nonce_conflict(e) is None
    assert isinstance(_engine_error(e), ChainRpcError)
