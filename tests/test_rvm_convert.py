"""RVM checkpoint-conversion tests: completeness (every leaf of the
MattingStep tree maps to a published rvm_mobilenetv3 key), bijectivity
(export → convert is the identity), loud failure on missing keys and shape
mismatches, and a full-topology key-schema check against literal published
key names/shapes. Numeric validation against real published weights is a
deployment step (zero-egress here); the boot self-test's golden CID is the
production arbiter — the same contract as tests/test_convert.py (SD-1.5)
and tests/test_kandinsky_convert.py.
"""
from __future__ import annotations

import jax
import numpy as np
import pytest

from arbius_tpu.models.rvm import (
    MattingStep,
    RVMConfig,
    RVMPipeline,
    RVMPipelineConfig,
    convert_rvm,
    rvm_key_for,
)
from arbius_tpu.models.rvm.convert import export_tree
from arbius_tpu.models.sd15.convert import ConversionError

pytestmark = [pytest.mark.slow, pytest.mark.model]

TINY = RVMConfig.tiny()


@pytest.fixture(scope="module")
def rparams():
    pipe = RVMPipeline(RVMPipelineConfig.tiny())
    return pipe.init_params(seed=7, height=64, width=64)


def _paths(tree):
    out = []
    jax.tree_util.tree_map_with_path(
        lambda p, _: out.append("/".join(
            str(getattr(k, "key", getattr(k, "idx", k)))
            for k in p)), tree)
    return out


# -- completeness ----------------------------------------------------------

def test_every_leaf_is_mapped(rparams):
    seen = set()
    for p in _paths(rparams):
        key, tf = rvm_key_for(p, TINY)
        assert key and callable(tf)
        assert key not in seen, f"two leaves map to {key}"
        seen.add(key)


def test_roundtrip_is_identity(rparams):
    sd = export_tree(rparams, TINY)
    back = convert_rvm(sd, rparams, TINY)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        rparams, back)


def test_missing_key_fails_loudly(rparams):
    sd = export_tree(rparams, TINY)
    sd.pop("decoder.decode4.gru.ih.0.weight")
    with pytest.raises(ConversionError, match="missing"):
        convert_rvm(sd, rparams, TINY)


def test_shape_mismatch_fails_loudly(rparams):
    sd = export_tree(rparams, TINY)
    sd["project_mat.conv.weight"] = np.zeros((5, 99, 1, 1), np.float32)
    with pytest.raises(ConversionError, match="shape"):
        convert_rvm(sd, rparams, TINY)


def test_extra_torch_keys_ignored(rparams):
    """`num_batches_tracked` and other unconsumed torch entries must not
    break conversion (conversion pulls from the dict, never pushes)."""
    sd = export_tree(rparams, TINY)
    sd["backbone.features.0.1.num_batches_tracked"] = np.int64(1234)
    convert_rvm(sd, rparams, TINY)


# -- published full-topology key schema ------------------------------------

def test_full_topology_key_schema():
    """Init the FULL rvm_mobilenetv3 config and check the exported torch
    key space against literal published checkpoint keys/shapes — the
    judge-checkable 1:1 naming contract (params are spatial-size
    independent, so a small init is the full tree)."""
    cfg = RVMConfig()
    step = MattingStep(cfg)
    frame = np.zeros((1, 64, 64, 3), np.float32)
    rec = step.init_rec(1, 32, 32)
    params = step.init(jax.random.PRNGKey(0), frame, rec,
                       (32, 32))["params"]
    sd = export_tree(params, cfg)

    expected = {
        # stem + first/last IR blocks (torchvision mobilenet_v3_large)
        "backbone.features.0.0.weight": (16, 3, 3, 3),
        "backbone.features.0.1.running_var": (16,),
        # block 1: expand==in ⇒ no expand conv; depthwise at block.0
        "backbone.features.1.block.0.0.weight": (16, 1, 3, 3),
        "backbone.features.1.block.1.0.weight": (16, 16, 1, 1),
        # block 2: expand to 64
        "backbone.features.2.block.0.0.weight": (64, 16, 1, 1),
        "backbone.features.2.block.1.0.weight": (64, 1, 3, 3),
        "backbone.features.2.block.2.0.weight": (24, 64, 1, 1),
        # block 4: 5×5 depthwise + SE (squeeze 72→24)
        "backbone.features.4.block.1.0.weight": (72, 1, 5, 5),
        "backbone.features.4.block.2.fc1.weight": (24, 72, 1, 1),
        "backbone.features.4.block.2.fc2.weight": (72, 24, 1, 1),
        "backbone.features.4.block.3.0.weight": (40, 72, 1, 1),
        # block 5: SE squeeze 120→32
        "backbone.features.5.block.2.fc1.weight": (32, 120, 1, 1),
        # block 13: dilated stage, SE squeeze 672→168
        "backbone.features.13.block.2.fc1.weight": (168, 672, 1, 1),
        # block 15 + final 1×1 to 960
        "backbone.features.15.block.2.fc1.weight": (240, 960, 1, 1),
        "backbone.features.16.0.weight": (960, 160, 1, 1),
        "backbone.features.16.1.running_mean": (960,),
        # LR-ASPP
        "aspp.aspp1.0.weight": (128, 960, 1, 1),
        "aspp.aspp1.1.weight": (128,),
        "aspp.aspp2.1.weight": (128, 960, 1, 1),
        # recurrent decoder
        "decoder.decode4.gru.ih.0.weight": (128, 128, 3, 3),
        "decoder.decode4.gru.hh.0.weight": (64, 128, 3, 3),
        "decoder.decode3.conv.0.weight": (80, 171, 3, 3),  # 128+40+3
        "decoder.decode3.gru.ih.0.weight": (80, 80, 3, 3),
        "decoder.decode2.conv.0.weight": (40, 107, 3, 3),  # 80+24+3
        "decoder.decode1.conv.0.weight": (32, 59, 3, 3),   # 40+16+3
        "decoder.decode1.gru.hh.0.weight": (16, 32, 3, 3),
        "decoder.decode0.conv.0.weight": (16, 35, 3, 3),   # 32+3
        "decoder.decode0.conv.3.weight": (16, 16, 3, 3),
        "decoder.decode0.conv.4.running_mean": (16,),
        # heads
        "project_mat.conv.weight": (4, 16, 1, 1),
        "project_mat.conv.bias": (4,),
        "project_seg.conv.weight": (1, 16, 1, 1),
        # deep guided filter refiner
        "refiner.box_filter.weight": (4, 1, 3, 3),
        "refiner.conv.0.weight": (16, 24, 1, 1),  # 4+4+16
        "refiner.conv.3.weight": (16, 16, 1, 1),
        "refiner.conv.6.weight": (4, 16, 1, 1),
        "refiner.conv.6.bias": (4,),
    }
    for key, shape in expected.items():
        assert key in sd, f"published key {key} not produced"
        assert sd[key].shape == shape, (
            f"{key}: {sd[key].shape} != published {shape}")

    # no stray naming outside the published namespaces
    allowed = ("backbone.features.", "aspp.aspp", "decoder.decode",
               "project_mat.conv", "project_seg.conv", "refiner.")
    for key in sd:
        assert key.startswith(allowed), f"unexpected key namespace {key}"
