"""meshsolve — pod-scale sharded inference on the live solve path.

The determinism contract under test (docs/multichip.md): dp shards
SAMPLES, so a dp-only layout must be BIT-identical to mesh-off; tp/sp
layouts are their own determinism classes, pinned by per-layout
graphlint goldens rather than byte equality — except for the probe
programs, whose math is layout-invariant BY CONSTRUCTION and therefore
pins the machinery (bucketing, chunking, placement, canonical gather)
at every layout. All of this runs on the forced 8-way CPU device
harness (tests/conftest.py), no accelerator involved.
"""
import logging
import pathlib

import numpy as np
import pytest

from arbius_tpu.node.config import ConfigError, MiningConfig, ModelConfig
from arbius_tpu.node.solver import RegisteredModel, solve_cid_batch
from arbius_tpu.obs import Obs, use_obs
from arbius_tpu.parallel import MeshSpec, abstract_mesh, meshsolve, validate_axes
from arbius_tpu.templates.engine import hydrate_input, load_template

GOLDENS_DIR = pathlib.Path(__file__).resolve().parent.parent / "goldens" / "graph"


# -- boot-time validation ---------------------------------------------------

def test_validate_axes_unknown_axis_names_the_registry():
    with pytest.raises(ValueError) as e:
        validate_axes({"dp": 2, "zz": 2})
    msg = str(e.value)
    assert "zz" in msg and "dp" in msg and "tp" in msg


@pytest.mark.parametrize("bad", [0, -1, "2", 2.0, True])
def test_validate_axes_rejects_non_positive_int(bad):
    with pytest.raises(ValueError) as e:
        validate_axes({"dp": bad})
    assert "positive integer" in str(e.value)


def test_validate_axes_device_count_is_one_clear_sentence():
    """The whole point of the satellite: a shape that does not fit the
    visible devices must die with a sentence naming the shape, the
    counts, and the CPU-testing escape hatch — not a deep XLA reshape
    failure."""
    with pytest.raises(ValueError) as e:
        validate_axes({"dp": 4, "tp": 4}, 8)
    msg = str(e.value)
    assert "needs 16 devices" in msg and "jax sees 8" in msg
    assert "--xla_force_host_platform_device_count=16" in msg


def test_boot_mesh_rejects_oversized_shape():
    with pytest.raises(ValueError, match="needs 16 devices"):
        meshsolve.boot_mesh({"dp": 16})


@pytest.mark.parametrize("bad", [{}, {"dp": 0}, {"xx": 2}, "dp2", 2])
def test_mining_config_validates_mesh_at_load(bad):
    with pytest.raises(ConfigError):
        MiningConfig(mesh=bad)


def test_mining_config_accepts_mesh_layouts():
    for mesh in (None, {"dp": 4, "tp": 2}, {"dp": 2, "sp": 2, "tp": 2}):
        assert MiningConfig(mesh=mesh).mesh == mesh


def test_boot_mesh_publishes_device_gauge():
    obs = Obs()
    assert meshsolve.boot_mesh(None, registry=obs.registry) is None
    assert obs.registry.gauge("arbius_mesh_devices").value() == 0.0
    mesh = meshsolve.boot_mesh({"dp": 2, "tp": 2}, registry=obs.registry)
    assert mesh is not None and mesh.shape["dp"] == 2
    assert obs.registry.gauge("arbius_mesh_devices").value() == 4.0


def test_check_mesh_contract_batch_video_fails_image_warns(caplog):
    from arbius_tpu.models.sd15 import pipeline as sd15
    from arbius_tpu.models.video import pipeline as video

    mesh = meshsolve.boot_mesh({"dp": 2})
    # image-only fleet: degrade path, warn but run
    with caplog.at_level(logging.WARNING, logger="arbius.meshsolve"):
        meshsolve.check_mesh_contract(mesh, {"anythingv3": sd15}, 3)
    assert any("not divisible" in r.message for r in caplog.records)
    # video hard-partitions the batch axis (MESH_BATCH_HARD): boot
    # failure, not first-task — at its one shipped dp·sp·tp layout
    mesh3 = meshsolve.boot_mesh({"dp": 2, "sp": 2, "tp": 2})
    with pytest.raises(ValueError, match="zeroscopev2xl"):
        meshsolve.check_mesh_contract(mesh3, {"zeroscopev2xl": video}, 3)
    meshsolve.check_mesh_contract(mesh3, {"zeroscopev2xl": video}, 4)
    meshsolve.check_mesh_contract(None, {"zeroscopev2xl": video}, 3)


def test_check_mesh_contract_rejects_unshipped_layout():
    """An enabled family must not boot in a determinism class that no
    graphlint golden pins: sd15 ships dp and dp·tp, so a dp·sp mesh —
    valid axes, fits the devices — is a boot error naming the family,
    its shipped layouts, and the missing golden."""
    from arbius_tpu.models.sd15 import pipeline as sd15

    mesh = meshsolve.boot_mesh({"dp": 2, "sp": 2})
    with pytest.raises(ValueError) as e:
        meshsolve.check_mesh_contract(mesh, {"anythingv3": sd15}, 2)
    msg = str(e.value)
    assert "anythingv3" in msg and "dp·tp" in msg and "golden" in msg


def test_check_mesh_contract_rejects_ungoldened_axis_size():
    """tp=4 at a shipped LAYOUT is still an unshipped determinism
    class: the goldens pin tp=2, and a 4-way kernel partition is a
    different psum order. dp stays size-free (bytes are dp-invariant
    by the layout argument, so dp4 needs no golden of its own)."""
    from arbius_tpu.models.sd15 import pipeline as sd15

    mesh = meshsolve.boot_mesh({"dp": 2, "tp": 4})
    with pytest.raises(ValueError, match="tp=4"):
        meshsolve.check_mesh_contract(mesh, {"anythingv3": sd15}, 2)
    mesh = meshsolve.boot_mesh({"dp": 4, "tp": 2})
    meshsolve.check_mesh_contract(mesh, {"anythingv3": sd15}, 4)


def test_build_registry_rejects_unshipped_layout():
    """The gate wired end-to-end: config → build_registry dies at boot
    for a (family, layout) pair with no golden, before any runner or
    params exist."""
    from arbius_tpu.node.factory import build_registry

    cfg = MiningConfig(
        models=(ModelConfig(id="0x" + "11" * 32, template="anythingv3",
                            tiny=True),),
        mesh={"dp": 2, "sp": 2})
    with pytest.raises(ValueError, match="anythingv3"):
        build_registry(cfg)


def test_factory_mesh_contracts_cover_every_mesh_family():
    """The contract table rides the builder table: every mesh-capable
    template resolves to a pipeline module that publishes MESH_LAYOUTS
    (robust_video_matting stays single-device on purpose)."""
    from arbius_tpu.node import factory

    cfg = MiningConfig(models=tuple(
        ModelConfig(id="0x" + f"{i:02x}" * 32, template=t, tiny=True)
        for i, t in enumerate(factory._BUILDERS)))
    contracts = factory.mesh_contracts(cfg)
    assert set(contracts) == set(factory._BUILDERS)
    assert all(getattr(mod, "MESH_LAYOUTS") for mod in contracts.values())


# -- dispatch-time placement ------------------------------------------------

def test_batch_specs_shard_when_divisible_else_replicate():
    mesh = meshsolve.boot_mesh({"dp": 2})
    spec, sharded = meshsolve.batch_specs(mesh, 4)
    assert sharded and spec(2).spec[0] == "dp"
    spec, sharded = meshsolve.batch_specs(mesh, 3)
    assert not sharded and spec(2).spec == ()


def test_estimate_and_record_collective_bytes():
    assert meshsolve.estimate_collective_bytes(None, (2, 8, 8), "f4") == {}
    mesh = meshsolve.boot_mesh({"dp": 2})
    est = meshsolve.estimate_collective_bytes(mesh, (2, 8, 8), np.float32)
    # each chip holds half the 512-byte output and receives the rest
    assert est == {"dp": 256}
    obs = Obs()
    with use_obs(obs):
        meshsolve.record_collective_bytes(est)
        meshsolve.record_collective_bytes(est)
    c = obs.registry.counter("arbius_collective_bytes_total",
                             labelnames=("axis",))
    assert c.value(axis="dp") == 512.0
    # no ambient obs: a no-op, never a crash (library code is node-free)
    meshsolve.record_collective_bytes(est)


def test_record_bucket_estimate_caches_and_skips_degraded_batch():
    """The hot-loop contract: the estimate is computed once per bucket
    (later dispatches reuse the cached dict), and a bucket that degraded
    to a replicated batch is not charged dp gathers that never cross
    chips."""
    mesh = meshsolve.boot_mesh({"dp": 2})
    cache: dict = {}
    obs = Obs()
    with use_obs(obs):
        # batch 3 does not divide dp=2: replicated batch, no dp traffic
        meshsolve.record_bucket_estimate(
            cache, 3, mesh, np.zeros((3, 8, 8), np.float32), 3)
        assert cache[3] == {}
        # batch 4 shards: half the 1024-byte output crosses chips
        out4 = np.zeros((4, 8, 8), np.float32)
        meshsolve.record_bucket_estimate(cache, 4, mesh, out4, 4)
        assert cache[4] == {"dp": 512}
        # second dispatch reuses the cache (poison it to prove reuse)
        cache[4] = {"dp": 7}
        meshsolve.record_bucket_estimate(cache, 4, mesh, out4, 4)
    c = obs.registry.counter("arbius_collective_bytes_total",
                             labelnames=("axis",))
    assert c.value(axis="dp") == 519.0  # 512 + the poisoned 7
    # mesh=None: no-op, caches nothing
    meshsolve.record_bucket_estimate(cache, 1, None,
                                     np.zeros((1,), np.float32), 1)
    assert 1 not in cache


def test_tp_estimate_counts_rule_sharded_params():
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = meshsolve.boot_mesh({"dp": 2, "tp": 2})
    params = {
        "qkv": jax.device_put(np.zeros((8, 8), np.float32),
                              NamedSharding(mesh, P(None, "tp"))),
        "norm": jax.device_put(np.zeros((8,), np.float32),
                               NamedSharding(mesh, P())),
    }
    est = meshsolve.estimate_collective_bytes(mesh, (2, 8, 8), np.float32,
                                              params=params)
    # ring allreduce term: 2·(tp-1)/tp of the 256-byte sharded slab;
    # the replicated norm leaf contributes nothing
    assert est["tp"] == 256


# -- byte equality across layouts (the acceptance gate) ---------------------

_TMPL = load_template("anythingv3")


def _items(n):
    return [(hydrate_input({"prompt": f"mesh task {i}",
                            "negative_prompt": ""}, _TMPL), 1000 + i)
            for i in range(n)]


def _cids(runner, canonical_batch):
    model = RegisteredModel(id="0x" + "11" * 32, template=_TMPL,
                            runner=runner)
    return [c for c, _ in solve_cid_batch(model, _items(5),
                                          canonical_batch=canonical_batch)]


@pytest.mark.parametrize("canonical_batch", [1, 4])
@pytest.mark.parametrize("probe_cls,layouts", [
    (meshsolve.ShardedImageProbe, ({"dp": 2}, {"dp": 2, "tp": 2})),
    (meshsolve.ShardedSeqProbe, ({"dp": 2}, {"dp": 2, "sp": 2})),
], ids=["image", "seq"])
def test_probe_cids_identical_at_every_layout(probe_cls, layouts,
                                              canonical_batch):
    """Same bucket at mesh-off, dp-only, and dp·tp (image) / dp·sp
    (video-shaped): byte-identical files ⇒ identical CIDs, through the
    REAL node solve path (bucketing, canonical-batch padding, chunk
    prefetch, gather). 5 items over canonical_batch 4 also exercises
    the padded under-filled final chunk."""
    base = _cids(probe_cls(mesh=None), canonical_batch)
    assert len(set(base)) == 5  # distinct inputs ⇒ distinct bytes
    for layout in layouts:
        mesh = meshsolve.boot_mesh(layout)
        assert _cids(probe_cls(mesh=mesh), canonical_batch) == base, layout


def test_seq_probe_underfilled_bucket_degrades_bitwise():
    """batch % dp != 0 cannot ride the shard_map (it hard-partitions
    the batch axis); the probe degrades that bucket to the single-device
    program whose bytes match by construction."""
    mesh = meshsolve.boot_mesh({"dp": 2, "sp": 2})
    base = _cids(meshsolve.ShardedSeqProbe(mesh=None), 3)
    assert _cids(meshsolve.ShardedSeqProbe(mesh=mesh), 3) == base


@pytest.mark.slow
@pytest.mark.model
def test_sd15_real_pipeline_dp2_bitwise_equal_to_mesh_off():
    """The real (tiny) SD-1.5 bucket program: dp-only sharding is a pure
    layout change — same XLA math per sample — so the generated images
    are BIT-identical to mesh-off. tp layouts are deliberately NOT
    asserted equal: reduction order moves, which is why each tp layout
    is its own golden-pinned determinism class."""
    from arbius_tpu.models.sd15 import SD15Config, SD15Pipeline
    from arbius_tpu.node.factory import tiny_byte_tokenizer

    cfg = SD15Config.tiny()
    kw = dict(width=64, height=64, num_inference_steps=2,
              scheduler="DDIM")
    out = {}
    for name, mesh in (("off", None),
                       ("dp2", meshsolve.boot_mesh({"dp": 2}))):
        p = SD15Pipeline(cfg, tokenizer=tiny_byte_tokenizer(cfg.text),
                        mesh=mesh)
        params = p.place_params(p.init_params(seed=0))
        out[name] = p.generate(params, ["a cat", "a dog"], ["", ""],
                               [11, 12], **kw)
    np.testing.assert_array_equal(out["off"], out["dp2"])


# -- per-layout goldens (the graphlint gate) --------------------------------

def test_every_shipped_family_layout_pair_has_a_golden():
    """Each family publishes its shipped layouts as data (MESH_LAYOUTS);
    every (family, layout) pair must have a golden fingerprint under
    goldens/graph/ — the per-layout determinism classes are pinned, not
    implied."""
    from arbius_tpu.models import all_trace_specs

    by_model: dict[str, set[str]] = {}
    for s in all_trace_specs():
        by_model.setdefault(s.model, set()).add(s.mesh)
        assert (GOLDENS_DIR / f"{s.key}.json").exists(), s.key

    def tag(axes):
        return ".".join(f"{a}2" for a in axes)

    from arbius_tpu.models.kandinsky2 import pipeline as k2
    from arbius_tpu.models.sd15 import pipeline as sd15
    from arbius_tpu.models.video import pipeline as video

    for model, mod in (("anythingv3", sd15), ("kandinsky2", k2),
                       ("zeroscopev2xl", video)):
        for axes in mod.MESH_LAYOUTS:
            assert tag(axes) in by_model[model], (model, axes)
    assert {"dp2.tp2", "single"} <= by_model["meshprobe"]
    assert "dp2.sp2" in by_model["meshprobe"]


def test_seq_probe_noncanonical_psum_fires_graph403():
    """The GRAPH403 gate, pinned through a REAL meshsolve-shaped psum:
    the shipped seq probe reduces over the canonical single axis and
    audits clean; the same program built with a deliberately
    non-canonical multi-axis reduction order is a finding."""
    import jax
    import jax.numpy as jnp

    from arbius_tpu.analysis.graph import run_rules, trace_spec
    from arbius_tpu.models import TraceSpec

    mesh = abstract_mesh(MeshSpec(dp=2, sp=2))
    sds = jax.ShapeDtypeStruct
    args = (sds((8, 8), jnp.float32), sds((2,), jnp.uint32))

    def spec_for(fn, tag):
        return TraceSpec(model="synthetic", entry=f"seqprobe-{tag}",
                         bucket="b2.f4", mesh="dp2.sp2", dtype="float32",
                         build=lambda: (fn, args))

    good = meshsolve.build_seq_probe_fn(mesh, 4)
    assert not run_rules(trace_spec(spec_for(good, "canonical")))

    bad = meshsolve.build_seq_probe_fn(mesh, 4, psum_axes=("sp", "dp"))
    hits = run_rules(trace_spec(spec_for(bad, "reversed")))
    assert [f.rule for f in hits] == ["GRAPH403"]
    assert "canonical" in hits[0].message


# -- simnet under a mesh ----------------------------------------------------

def test_simnet_clean_and_crash_restart_hold_on_dp2_mesh(tmp_path):
    """The satellite's end-to-end gate: the full signed-tx miner
    lifecycle with REAL sharded XLA solves on the virtual dp2 mesh —
    SIM101-109 hold for `clean` and `crash-restart`, and every accepted
    CID matches the mesh-off run of the same probe (same seed, same
    fault schedule)."""
    from arbius_tpu.sim.harness import run_scenario
    from arbius_tpu.sim.invariants import check_all
    from arbius_tpu.sim.scenario import get_scenario

    def cids(r):
        return {"0x" + t.hex(): "0x" + s.cid.hex()
                for t, s in r.engine.solutions.items()}

    for name in ("clean", "crash-restart"):
        base = run_scenario(get_scenario(name), 1, mesh={},
                            db_path=str(tmp_path / f"{name}-off.sqlite"))
        meshed = run_scenario(get_scenario(name), 1, mesh={"dp": 2},
                              db_path=str(tmp_path / f"{name}-dp2.sqlite"))
        for r in (base, meshed):
            findings = check_all(r)
            assert not findings, (name, [f.text() for f in findings])
            assert r.quiescent
        assert cids(base) == cids(meshed) and cids(base), name
    assert meshed.restarts == 1  # the crash actually happened
