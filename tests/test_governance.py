"""Governance tests — mirror of the reference's governance.test.ts flow:
delegate → propose → vote → queue (timelock) → execute, quorum math, and
the setSolutionMineableRate-via-governance scenario (SURVEY.md §4).
"""
from __future__ import annotations

import pytest

from arbius_tpu.chain import Engine, TokenLedger, WAD
from arbius_tpu.chain.governance import (
    Governor,
    GovernanceError,
    ProposalState,
    TIMELOCK_MIN_DELAY,
    VOTING_DELAY,
    VOTING_PERIOD,
)

ALICE = "0x" + "a1" * 20
BOB = "0x" + "b2" * 20
CAROL = "0x" + "c3" * 20


def world():
    tok = TokenLedger()
    eng = Engine(tok, start_time=1000)
    gov = Governor(eng)
    tok.mint(ALICE, 100_000 * WAD)
    tok.mint(BOB, 50_000 * WAD)
    tok.mint(CAROL, 10_000 * WAD)
    for a in (ALICE, BOB, CAROL):
        tok.delegate(a, a)      # self-delegate, as governance.test.ts does
    eng.advance_time(10, 1)     # checkpoints land before any snapshot
    return eng, tok, gov


def pass_proposal(eng, gov, pid, voters=(ALICE, BOB)):
    eng.advance_time(0, VOTING_DELAY + 1)
    for v in voters:
        gov.cast_vote(v, pid, 1)
    eng.advance_time(0, VOTING_PERIOD)
    gov.queue(pid)
    eng.advance_time(TIMELOCK_MIN_DELAY + 1)
    gov.execute(pid)


def test_delegation_checkpoints():
    eng, tok, gov = world()
    assert tok.get_votes(ALICE) == 100_000 * WAD
    block = eng.block_number
    eng.advance_time(0, 5)
    tok.transfer(ALICE, BOB, 40_000 * WAD)
    assert tok.get_votes(ALICE) == 60_000 * WAD
    assert tok.get_votes(BOB) == 90_000 * WAD
    # history preserved at the earlier block
    assert tok.get_past_votes(ALICE, block) == 100_000 * WAD


def test_full_lifecycle_executes_action():
    eng, tok, gov = world()
    fired = []
    pid = gov.propose(ALICE, [lambda: fired.append("treasury-move")],
                      "move treasury funds")
    assert gov.state(pid) == ProposalState.PENDING
    eng.advance_time(0, VOTING_DELAY + 1)
    assert gov.state(pid) == ProposalState.ACTIVE
    gov.cast_vote(ALICE, pid, 1)
    gov.cast_vote(BOB, pid, 1)
    eng.advance_time(0, VOTING_PERIOD)
    assert gov.state(pid) == ProposalState.SUCCEEDED
    gov.queue(pid)
    assert gov.state(pid) == ProposalState.QUEUED
    with pytest.raises(GovernanceError, match="timelock"):
        gov.execute(pid)
    eng.advance_time(TIMELOCK_MIN_DELAY + 1)
    gov.execute(pid)
    assert fired == ["treasury-move"]
    assert gov.state(pid) == ProposalState.EXECUTED


def test_quorum_4_percent():
    """Carol alone (10k of 160k = 6.25%) meets quorum; a tiny voter does
    not (OZ GovernorVotesQuorumFraction(4))."""
    eng, tok, gov = world()
    pid = gov.propose(ALICE, [lambda: None], "carol only")
    eng.advance_time(0, VOTING_DELAY + 1)
    gov.cast_vote(CAROL, pid, 1)
    eng.advance_time(0, VOTING_PERIOD)
    assert gov.state(pid) == ProposalState.SUCCEEDED

    tiny = "0x" + "d4" * 20
    tok.mint(tiny, 100 * WAD)
    tok.delegate(tiny, tiny)
    eng.advance_time(0, 1)
    pid2 = gov.propose(ALICE, [lambda: None], "tiny only")
    eng.advance_time(0, VOTING_DELAY + 1)
    gov.cast_vote(tiny, pid2, 1)
    eng.advance_time(0, VOTING_PERIOD)
    assert gov.state(pid2) == ProposalState.DEFEATED


def test_against_votes_defeat():
    eng, tok, gov = world()
    pid = gov.propose(ALICE, [lambda: None], "contested")
    eng.advance_time(0, VOTING_DELAY + 1)
    gov.cast_vote(BOB, pid, 1)       # 50k for
    gov.cast_vote(ALICE, pid, 0)     # 100k against
    eng.advance_time(0, VOTING_PERIOD)
    assert gov.state(pid) == ProposalState.DEFEATED
    with pytest.raises(GovernanceError, match="not successful"):
        gov.queue(pid)


def test_proposal_threshold():
    eng, tok, gov = world()
    pauper = "0x" + "e5" * 20
    with pytest.raises(GovernanceError, match="threshold"):
        gov.propose(pauper, [lambda: None], "no stake no say")


def test_no_double_vote_and_snapshot_weights():
    """Votes use the SNAPSHOT block weight: tokens acquired after the
    snapshot don't count (vote-buying defense, same spirit as the
    engine's stake-age gate)."""
    eng, tok, gov = world()
    pid = gov.propose(ALICE, [lambda: None], "snapshot rules")
    eng.advance_time(0, VOTING_DELAY + 1)
    gov.cast_vote(CAROL, pid, 1)
    with pytest.raises(GovernanceError, match="already voted"):
        gov.cast_vote(CAROL, pid, 1)
    # BOB ships tokens to CAROL after the snapshot; CAROL already voted
    # with 10k and BOB's vote still carries his snapshot weight
    tok.transfer(BOB, CAROL, 50_000 * WAD)
    w = gov.cast_vote(BOB, pid, 1)
    assert w == 50_000 * WAD


def test_mineable_rate_via_governance():
    """governance.test.ts:128-444 headline: setSolutionMineableRate goes
    through propose → vote → queue → execute, then affects claims."""
    eng, tok, gov = world()
    mid = eng.register_model(ALICE, BOB, 0, b'{"meta":{"title":"gov"}}')
    assert eng.models[mid].rate == 0
    pid = gov.propose(
        ALICE, [lambda: eng.set_solution_mineable_rate(mid, WAD // 10)],
        "set kandinsky2 mineable rate to 0.1")
    pass_proposal(eng, gov, pid)
    assert eng.models[mid].rate == WAD // 10


def test_description_cid_stored():
    eng, tok, gov = world()
    pid = gov.propose(ALICE, [lambda: None], "ipfs me")
    p = gov.proposals[pid]
    from arbius_tpu.l0.cid import cid_onchain
    assert p.description_cid == cid_onchain(b"ipfs me")
    assert gov.proposals_created == [pid]


def _count_world():
    """Minimal world for multi-action execute-retry semantics."""
    from arbius_tpu.chain import Engine, TokenLedger, WAD
    from arbius_tpu.chain.governance import (
        Governor,
        TIMELOCK_MIN_DELAY,
        VOTING_DELAY,
        VOTING_PERIOD,
    )

    tok = TokenLedger()
    eng = Engine(tok, start_time=1000)
    voter = "0x" + "aa" * 20
    tok.mint(voter, 600_000 * WAD)
    tok.delegate(voter, voter)
    eng.mine_block()
    gov = Governor(eng)
    return eng, gov, voter, (VOTING_DELAY, VOTING_PERIOD, TIMELOCK_MIN_DELAY)


def test_failed_action_retry_does_not_double_apply():
    """A multi-action proposal whose second action reverts must stay
    QUEUED, and a retry must resume AFTER the action that already ran
    (no double-apply of action 1)."""
    import pytest as _pytest

    from arbius_tpu.chain.governance import GovernanceError, ProposalState

    eng, gov, voter, (delay, period, tl) = _count_world()
    ran = []
    fail = [True]

    def a1():
        ran.append("a1")

    def a2():
        if fail[0]:
            raise GovernanceError("boom")
        ran.append("a2")

    pid = gov.propose(voter, [a1, a2], "two actions")
    eng.advance_time(1, blocks=delay + 1)
    gov.cast_vote(voter, pid, 1)
    eng.advance_time(1, blocks=period + 1)
    gov.queue(pid)
    eng.advance_time(tl + 1, blocks=1)
    with _pytest.raises(GovernanceError, match="boom"):
        gov.execute(pid)
    assert gov.state(pid) == ProposalState.QUEUED   # re-executable
    assert ran == ["a1"]
    fail[0] = False
    gov.execute(pid)
    assert ran == ["a1", "a2"]                      # a1 NOT re-applied
    assert gov.state(pid) == ProposalState.EXECUTED
