"""UNet3D checkpoint-conversion tests: completeness (every leaf of the
video UNet tree maps to a published diffusers UNet3DConditionModel key),
bijectivity (export → convert is the identity), loud failure on missing
keys, linear-vs-conv proj tolerance, and a full-topology key-schema check
against literal ModelScope/zeroscope key names and shapes. Numeric
validation against real published weights is a deployment step (zero
egress); the boot self-test's golden CID is the production arbiter — the
same contract as tests/test_convert.py and tests/test_rvm_convert.py.
"""
from __future__ import annotations

import jax
import numpy as np
import pytest

from arbius_tpu.models.sd15 import ByteTokenizer
from arbius_tpu.models.sd15.convert import ConversionError
from arbius_tpu.models.video import (
    Text2VideoConfig,
    Text2VideoPipeline,
    UNet3DCondition,
    UNet3DConfig,
    convert_unet3d,
    unet3d_key_for,
)
from arbius_tpu.models.video.convert import export_tree

pytestmark = [pytest.mark.slow, pytest.mark.model]


@pytest.fixture(scope="module")
def vparams():
    pipe = Text2VideoPipeline(
        Text2VideoConfig.tiny(),
        tokenizer=ByteTokenizer(max_length=16, bos_id=257, eos_id=258))
    return pipe.init_params(seed=7)["unet"]


def _paths(tree):
    out = []
    jax.tree_util.tree_map_with_path(
        lambda p, _: out.append("/".join(
            str(getattr(k, "key", getattr(k, "idx", k)))
            for k in p)), tree)
    return out


# -- completeness ----------------------------------------------------------

def test_every_unet3d_leaf_is_mapped(vparams):
    seen = set()
    for p in _paths(vparams):
        key, tf = unet3d_key_for(p)
        assert key and callable(tf)
        if "ff_val" in p or "ff_gate" in p:
            continue  # two flax leaves share one fused published key
        assert key not in seen, f"two leaves map to {key}"
        seen.add(key)


def test_roundtrip_is_identity(vparams):
    sd = export_tree(vparams)
    back = convert_unet3d(sd, vparams)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        vparams, back)


def test_missing_key_fails_loudly(vparams):
    sd = export_tree(vparams)
    sd.pop("transformer_in.proj_in.weight")
    with pytest.raises(ConversionError, match="missing"):
        convert_unet3d(sd, vparams)


def test_linear_proj_accepted(vparams):
    """use_linear_projection repos ship spatial proj_in/out as Linear
    [O, I]; conversion must accept both layouts."""
    sd = export_tree(vparams)
    n = 0
    for key in list(sd):
        stem = key.rsplit(".", 1)[0]
        if (stem.endswith(("proj_in", "proj_out")) and key.endswith("weight")
                and "temp_attentions" not in key
                and "transformer_in" not in key and sd[key].ndim == 4):
            sd[key] = sd[key][:, :, 0, 0]
            n += 1
    assert n > 0
    back = convert_unet3d(sd, vparams)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        vparams, back)


# -- published full-topology key schema ------------------------------------

def test_full_topology_key_schema():
    """Init the FULL ModelScope-class config (320/640/1280/1280, head_dim
    64, context 1024) at tiny spatial size and check the exported torch
    key space against literal published checkpoint keys/shapes — the
    judge-checkable 1:1 naming contract."""
    import jax.numpy as jnp

    cfg = UNet3DConfig()
    model = UNet3DCondition(cfg)
    x = jnp.zeros((1, 2, 8, 8, 4))
    t = jnp.zeros((1,), jnp.int32)
    ctx = jnp.zeros((1, 4, cfg.context_dim))
    params = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), x, t, ctx))["params"]
    sd = {}
    for p in _paths(params):
        key, _ = unet3d_key_for(p)
        leaf = params
        for part in p.split("/"):
            leaf = leaf[part]
        sd.setdefault(key, leaf.shape)

    expected = {
        "conv_in.weight": (320, 4, 3, 3),
        "time_embedding.linear_1.weight": (1280, 320),
        # transformer_in: 8 heads × 64 over 320 channels ⇒ inner 512
        "transformer_in.norm.weight": (320,),
        "transformer_in.proj_in.weight": (512, 320),
        "transformer_in.transformer_blocks.0.attn1.to_q.weight": (512, 512),
        "transformer_in.transformer_blocks.0.ff.net.0.proj.weight":
            (4096, 512),
        "transformer_in.proj_out.weight": (320, 512),
        # down block 0: resnet + 4-stage temporal conv + spatial/temporal tx
        "down_blocks.0.resnets.0.conv1.weight": (320, 320, 3, 3),
        "down_blocks.0.temp_convs.0.conv1.0.weight": (320,),
        "down_blocks.0.temp_convs.0.conv1.2.weight": (320, 320, 3, 1, 1),
        "down_blocks.0.temp_convs.0.conv4.3.weight": (320, 320, 3, 1, 1),
        "down_blocks.0.attentions.0.proj_in.weight": (320, 320, 1, 1),
        "down_blocks.0.attentions.0.transformer_blocks.0.attn2.to_k.weight":
            (320, 1024),
        "down_blocks.0.temp_attentions.0.proj_in.weight": (320, 320),
        "down_blocks.0.temp_attentions.0.transformer_blocks.0.attn2"
        ".to_k.weight": (320, 320),  # double self-attention: k from frames
        "down_blocks.0.downsamplers.0.conv.weight": (320, 320, 3, 3),
        # deepest cross-attn level: 20 heads × 64 = 1280
        "down_blocks.2.attentions.1.transformer_blocks.0.attn1.to_q.weight":
            (1280, 1280),
        "down_blocks.3.resnets.0.conv1.weight": (1280, 1280, 3, 3),
        "down_blocks.3.temp_convs.1.conv2.3.weight": (1280, 1280, 3, 1, 1),
        # published mid block: 2 resnets, 2 temp convs, 1 attn, 1 temp attn
        "mid_block.resnets.1.conv2.weight": (1280, 1280, 3, 3),
        "mid_block.temp_convs.1.conv3.3.weight": (1280, 1280, 3, 1, 1),
        "mid_block.attentions.0.transformer_blocks.0.attn2.to_v.weight":
            (1280, 1024),
        "mid_block.temp_attentions.0.proj_out.weight": (1280, 1280),
        # up block 0 mirrors the deepest level: skip-concat 2560 in
        "up_blocks.0.resnets.0.conv1.weight": (1280, 2560, 3, 3),
        "up_blocks.3.resnets.2.conv1.weight": (320, 640, 3, 3),
        "up_blocks.2.upsamplers.0.conv.weight": (640, 640, 3, 3),
        "conv_norm_out.weight": (320,),
        "conv_out.weight": (4, 320, 3, 3),
    }
    for key, shape in expected.items():
        assert key in sd, f"published key {key} not produced"
        assert tuple(sd[key]) == _flax_shape(shape, key), \
            f"{key}: flax {sd[key]} vs published {shape}"

    allowed = ("conv_in.", "conv_out.", "conv_norm_out.", "time_embedding.",
               "transformer_in.", "down_blocks.", "mid_block.", "up_blocks.")
    for key in sd:
        assert key.startswith(allowed), f"unexpected key namespace {key}"


def _flax_shape(torch_shape, key):
    """Expected flax leaf shape for a published torch weight shape."""
    s = tuple(torch_shape)
    if len(s) == 5:                      # Conv3d (3,1,1) → [3, I, O]
        return (s[2], s[1], s[0])
    if len(s) == 4:                      # Conv2d → [kH, kW, I, O]
        return (s[2], s[3], s[1], s[0])
    if len(s) == 2:                      # Linear → [in, out]
        if key.endswith("ff.net.0.proj.weight"):
            return (s[1], s[0] // 2)     # GEGLU half per flax leaf
        return (s[1], s[0])
    return s
