"""Sampler correctness tests.

The strongest check is analytic: for a data distribution that is a delta at
x0*, the exact noise prediction is eps(x, t) = (x - sqrt(acp_t) * x0*) /
sqrt(1 - acp_t) (in timestep space) or (x - x0*) / sigma (in sigma space).
Driving any correct sampler with this oracle must converge to x0*.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from arbius_tpu.schedulers import SAMPLER_NAMES, alphas_cumprod, get_sampler


X0 = 3.0  # the delta-distribution target
SHAPE = (4,)


def run_sampler(name: str, num_steps: int, seed: int = 0):
    """Scan the sampler against the exact-oracle model."""
    s = get_sampler(name, num_steps)
    acp = jnp.asarray(alphas_cumprod(), dtype=jnp.float32)
    x0 = jnp.full(SHAPE, X0, dtype=jnp.float32)

    def model(x_scaled, t):
        # oracle eps in timestep space; works for both families because
        # sigma-space samplers feed x_scaled = x/sqrt(sig^2+1) which equals
        # the timestep-space sample sqrt(acp)*x0 + sqrt(1-acp)*eps.
        a = jnp.interp(t, jnp.arange(acp.shape[0], dtype=jnp.float32), acp)
        return (x_scaled - jnp.sqrt(a) * x0) / jnp.sqrt(1.0 - a)

    key = jax.random.PRNGKey(seed)
    x = jax.random.normal(key, SHAPE, dtype=jnp.float32) * s.init_noise_sigma

    def body(carry, i):
        x, state = carry
        eps = model(x * s.input_scale[i], s.timesteps[i])
        noise = jax.random.normal(jax.random.fold_in(key, i), SHAPE, dtype=jnp.float32)
        x, state = s.step(i, x, eps, state, noise)
        return (x, state), None

    (x, _), _ = jax.lax.scan(body, (x, s.init_carry(x)), jnp.arange(s.num_model_calls))
    return np.asarray(x)


@pytest.mark.parametrize("name", SAMPLER_NAMES)
def test_converges_to_delta_target(name):
    steps = 30
    out = run_sampler(name, steps)
    # the oracle's x0 prediction is exact, so all samplers should land close.
    # Timestep-space samplers terminate at alphas_cumprod[0] (not 1.0), so
    # sqrt(1-acp[0]) ~ 0.03 of terminal noise legitimately remains.
    tol = 0.25 if name == "K_EULER_ANCESTRAL" else 0.11
    assert np.allclose(out, X0, atol=tol), f"{name}: {out}"


@pytest.mark.parametrize("name", SAMPLER_NAMES)
def test_more_steps_not_worse(name):
    if name == "K_EULER_ANCESTRAL":
        pytest.skip("stochastic path; covered by delta test")
    e20 = np.abs(run_sampler(name, 20) - X0).max()
    e80 = np.abs(run_sampler(name, 80) - X0).max()
    assert e80 <= e20 + 1e-3


@pytest.mark.parametrize("name", SAMPLER_NAMES)
def test_bit_determinism(name):
    a = run_sampler(name, 25, seed=7)
    b = run_sampler(name, 25, seed=7)
    assert (a == b).all()


def test_ancestral_noise_matters():
    a = run_sampler("K_EULER_ANCESTRAL", 25, seed=1)
    b = run_sampler("K_EULER_ANCESTRAL", 25, seed=2)
    assert not (a == b).all()


@pytest.mark.parametrize("name", SAMPLER_NAMES)
def test_jit_and_table_shapes(name):
    s = get_sampler(name, 10)
    expected_calls = 11 if name == "PNDM" else 10
    assert s.num_model_calls == expected_calls
    assert s.timesteps.shape == (expected_calls,)
    assert s.input_scale.shape == (expected_calls,)
    # descending conditioning timesteps (PNDM repeats one)
    ts = np.asarray(s.timesteps)
    assert (np.diff(ts) <= 0).all()

    # step must be jittable with traced index
    x = jnp.ones((2, 2))
    carry = s.init_carry(x)
    stepped = jax.jit(lambda i, x, c: s.step(i, x, x * 0.1, c, x * 0.0))(
        jnp.asarray(0), x, carry)
    assert stepped[0].shape == x.shape


def test_ddim_few_steps_close_for_delta():
    # with an exact x0 prediction DDIM converges almost immediately.
    # (NOT at 1 step: leading spacing makes the single timestep t=1, so the
    # init noise is fed in at the wrong noise level — faithful semantics.)
    out = run_sampler("DDIM", 2)
    assert np.allclose(out, X0, atol=0.11)


def test_sampler_cache_and_validation():
    assert get_sampler("DDIM", 20) is get_sampler("DDIM", 20)
    with pytest.raises(ValueError, match="unknown scheduler"):
        get_sampler("UniPC", 20)
    with pytest.raises(ValueError, match="num_steps"):
        get_sampler("DDIM", 0)
