"""Live-chain mining path: RpcChain over a devnet speaking real signed txs.

The reference only exercises its signing stack against live Nova
(`miner/test/utils.test.ts:60-69`); here the loop closes hermetically:
wallet signs EIP-1559 → RLP bytes → DevnetNode RLP-decodes, recovers the
sender from the secp256k1 signature, ABI-decodes calldata, applies it to
the in-process EngineV1 — then the node reads it all back through
eth_call/eth_getLogs. End-to-end: MinerNode mines a task through the
full JSON-RPC surface with zero LocalChain shortcuts.
"""
import json
import threading

import pytest

from arbius_tpu.chain import Engine, EngineError, TokenLedger, WAD
from arbius_tpu.chain.devnet import DevnetNode, DevnetError
from arbius_tpu.chain.rlp import Eip1559Tx, decode_signed_eip1559, rlp_decode, rlp_encode
from arbius_tpu.chain.rpc_client import EngineRpcClient, JsonRpcTransport
from arbius_tpu.chain.wallet import Wallet
from arbius_tpu.l0.abi import abi_decode, abi_encode
from arbius_tpu.node.rpc_chain import RpcChain

CHAIN_ID = 31337
KEY_MINER = "0x" + "11" * 32
KEY_USER = "0x" + "22" * 32


class DevnetTransport:
    """Transport-shim: JsonRpcTransport semantics without HTTP."""

    def __init__(self, node: DevnetNode):
        self.node = node

    def request(self, method, params):
        from arbius_tpu.chain.rpc_client import RpcError

        try:
            return self.node.request(method, params)
        except DevnetError as e:
            raise RpcError(str(e)) from None


def make_world():
    tok = TokenLedger()
    eng = Engine(tok, start_time=1000)
    tok.mint(Engine.ADDRESS, 600_000 * WAD)
    dev = DevnetNode(eng, chain_id=CHAIN_ID)
    miner, user = Wallet.from_hex(KEY_MINER), Wallet.from_hex(KEY_USER)
    tok.mint(miner.address, 1000 * WAD)
    tok.mint(user.address, 1000 * WAD)
    mid = eng.register_model(user.address, user.address, 0,
                             b'{"meta":{"title":"t"}}')
    return eng, dev, miner, user, "0x" + mid.hex()


def make_chain(dev, wallet):
    client = EngineRpcClient(DevnetTransport(dev), dev.engine_address,
                             wallet, chain_id=CHAIN_ID)
    return RpcChain(client, dev.token_address)


# -- primitives ----------------------------------------------------------

def test_rlp_decode_roundtrip():
    cases = [b"", b"\x01", b"dog", b"a" * 60, [b"cat", [b"", b"\x7f"]],
             [], [b"x" * 300, [b"y"] * 20]]
    for item in cases:
        assert rlp_decode(rlp_encode(item)) == item
    with pytest.raises(ValueError):
        rlp_decode(rlp_encode(b"dog") + b"\x00")
    with pytest.raises(ValueError):
        rlp_decode(b"\x85abc")  # declares 5 bytes, provides 3
    with pytest.raises(ValueError):
        rlp_decode(b"\xc5\x83do")  # list payload truncated


def test_signed_tx_decode_recovers_sender():
    w = Wallet.from_hex(KEY_MINER)
    tx = Eip1559Tx(chain_id=CHAIN_ID, nonce=7, max_priority_fee_per_gas=1,
                   max_fee_per_gas=100, gas_limit=21000,
                   to="0x" + "e1" * 20, value=5, data=b"\xde\xad")
    dec = decode_signed_eip1559(tx.sign(w))
    assert dec.sender == w.address
    assert dec.tx == tx
    assert dec.tx_hash == tx.tx_hash(w)


def test_abi_decode_roundtrip():
    types = ["address", "bytes32", "uint256", "bool", "bytes", "string",
             "uint64", "uint8"]
    values = ["0x" + "ab" * 20, b"\x01" * 32, 2**200, True, b"xyz" * 30,
              "hello", 2**40, 7]
    assert abi_decode(types, abi_encode(types, values)) == values
    with pytest.raises(ValueError):
        abi_decode(["uint256"], b"\x00" * 16)


# -- devnet JSON-RPC surface ----------------------------------------------

def test_devnet_signed_task_submission_updates_engine():
    eng, dev, miner, user, mid = make_world()
    client = EngineRpcClient(DevnetTransport(dev), dev.engine_address,
                             user, chain_id=CHAIN_ID)
    input_bytes = json.dumps({"prompt": "hi"}).encode()
    client.send("submitTask", [0, user.address, mid, 0, input_bytes])
    assert len(eng.tasks) == 1
    tid = next(iter(eng.tasks))
    # view read-back through eth_call
    raw = client.eth_call("tasks(bytes32)", ["bytes32"], ["0x" + tid.hex()])
    model, fee, owner, blocktime, version, cid = abi_decode(
        ["bytes32", "uint256", "address", "uint64", "uint8", "bytes"], raw)
    assert model == bytes.fromhex(mid[2:]) and owner == user.address.lower()
    # the input rides the calldata, recoverable via the logged tx
    logs = client.get_logs("TaskSubmitted", 0, dev.engine.block_number)
    assert len(logs) == 1
    tx = client.get_transaction(logs[0]["transactionHash"])
    assert bytes.fromhex(tx["input"][2:]).endswith(b"\x00" * 0 + input_bytes
                                                   .ljust((len(input_bytes) + 31) // 32 * 32, b"\x00"))


def test_devnet_rejects_wrong_nonce_and_bad_chain_id():
    eng, dev, miner, user, mid = make_world()
    tx = Eip1559Tx(chain_id=CHAIN_ID, nonce=5, max_priority_fee_per_gas=1,
                   max_fee_per_gas=2, gas_limit=100000,
                   to=dev.engine_address, value=0,
                   data=bytes.fromhex("00000000"))
    with pytest.raises(DevnetError, match="nonce"):
        dev.request("eth_sendRawTransaction",
                    ["0x" + tx.sign(miner).hex()])
    tx2 = Eip1559Tx(chain_id=999, nonce=0, max_priority_fee_per_gas=1,
                    max_fee_per_gas=2, gas_limit=100000,
                    to=dev.engine_address, value=0, data=b"\x00" * 4)
    with pytest.raises(DevnetError, match="chain id"):
        dev.request("eth_sendRawTransaction",
                    ["0x" + tx2.sign(miner).hex()])


def test_devnet_http_transport():
    eng, dev, miner, user, mid = make_world()
    server = dev.serve("127.0.0.1", 0)
    port = server.server_address[1]
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        tr = JsonRpcTransport(f"http://127.0.0.1:{port}")
        assert int(tr.request("eth_blockNumber", []), 16) >= 1
        client = EngineRpcClient(tr, dev.engine_address, user,
                                 chain_id=CHAIN_ID)
        client.send("submitTask", [0, user.address, mid, 0, b"{}"])
        assert len(eng.tasks) == 1
        from arbius_tpu.chain.rpc_client import RpcError

        with pytest.raises(RpcError, match="revert"):
            client.send("claimSolution", ["0x" + "77" * 32])
    finally:
        server.shutdown()


# -- RpcChain facade ------------------------------------------------------

def test_rpc_chain_reads_and_none_mapping():
    eng, dev, miner, user, mid = make_world()
    chain = make_chain(dev, miner)
    assert chain.get_task("0x" + "00" * 32) is None
    assert chain.get_solution("0x" + "00" * 32) is None
    assert chain.get_contestation("0x" + "00" * 32) is None
    assert chain.version() == 0
    assert chain.token_balance() == 1000 * WAD
    assert chain.validator_staked() == 0
    assert chain.min_claim_solution_time() == eng.min_claim_solution_time
    assert chain.now == eng.now


def test_rpc_chain_validator_deposit_self_heals_allowance():
    eng, dev, miner, user, mid = make_world()
    chain = make_chain(dev, miner)
    assert chain.token_allowance(dev.engine_address) == 0
    chain.validator_deposit(10 * WAD)
    assert chain.validator_staked() == 10 * WAD
    assert chain.token_allowance(dev.engine_address) > 0


def test_rpc_chain_revert_maps_to_engine_error():
    eng, dev, miner, user, mid = make_world()
    chain = make_chain(dev, miner)
    with pytest.raises(EngineError):
        chain.claim_solution("0x" + "42" * 32)


def test_rpc_chain_event_polling_decodes_args():
    eng, dev, miner, user, mid = make_world()
    chain = make_chain(dev, miner)
    seen = []
    chain.subscribe(lambda ev: seen.append(ev))
    user_chain = make_chain(dev, user)
    user_chain.submit_task(0, user.address, mid, 0,
                           json.dumps({"prompt": "x"}).encode())
    n = chain.poll_events()
    assert n == 1 and seen[0].name == "TaskSubmitted"
    args = seen[0].args
    tid = "0x" + args["id"].hex()
    assert args["sender"] == user.address.lower()
    assert args["fee"] == 0
    assert isinstance(args["model"], bytes)
    # input bytes recovered from the submitting tx's calldata
    assert chain.get_task_input_bytes(tid) == \
        json.dumps({"prompt": "x"}).encode()
    # replays are not re-delivered
    assert chain.poll_events() == 0


def test_miner_node_mines_end_to_end_over_rpc():
    """The VERDICT's done-criterion: the node mines through a fake JSON-RPC
    chain — poll logs → hydrate → solve (tiny SD-1.5) → signed commit →
    signed reveal → time travel → signed claim."""
    from arbius_tpu.node import MinerNode, MiningConfig, ModelConfig, build_registry

    eng, dev, miner, user, mid = make_world()
    chain = make_chain(dev, miner)
    cfg = MiningConfig(
        models=(ModelConfig(id=mid, template="anythingv3", tiny=True),),
        compile_cache_dir=None)
    node = MinerNode(chain, cfg, build_registry(cfg))
    node.boot(skip_self_test=True)

    user_chain = make_chain(dev, user)
    user_chain.submit_task(0, user.address, mid, 0, json.dumps({
        "prompt": "arbius test cat", "negative_prompt": "",
        "width": 128, "height": 128, "num_inference_steps": 2,
        "scheduler": "DDIM"}).encode())

    for _ in range(6):
        node.tick()
    tid_b = next(iter(eng.tasks))
    sol = eng.solutions.get(tid_b)
    assert sol is not None, "node did not submit a solution over RPC"
    assert sol.validator == miner.address.lower()
    assert sol.cid.startswith(b"\x12\x20")
    # the stake job must have topped us up through the signed-tx path
    assert chain.validator_staked() >= eng.get_validator_minimum()

    dev.request("evm_increaseTime", [eng.min_claim_solution_time + 200])
    dev.request("evm_mine", [])
    for _ in range(4):
        node.tick()
    assert eng.solutions[tid_b].claimed
    assert node.metrics.solutions_claimed == 1


def test_rpc_chain_full_commit_reveal_claim():
    eng, dev, miner, user, mid = make_world()
    chain = make_chain(dev, miner)
    chain.validator_deposit(100 * WAD)
    user_chain = make_chain(dev, user)
    user_chain.submit_task(0, user.address, mid, 0, b"{}")
    chain.poll_events()
    tid = "0x" + next(iter(eng.tasks)).hex()
    cid = "0x1220" + "ab" * 32
    commitment = chain.generate_commitment(tid, cid)
    chain.signal_commitment(commitment)
    chain.submit_solution(tid, cid)
    sol = chain.get_solution(tid)
    assert sol is not None and sol.validator == miner.address.lower()
    dev.request("evm_increaseTime", [eng.min_claim_solution_time + 100])
    dev.request("evm_mine", [])
    before = chain.token_balance()
    chain.claim_solution(tid)
    assert eng.solutions[next(iter(eng.tasks))].claimed
    assert chain.token_balance() >= before


def test_nonce_conflict_parsed_structurally():
    """The satellite fix: classification reads the error MESSAGE field
    (devnet shape `nonce N != expected M`), never a substring scan of
    the stringified payload — calldata echoed in `data` that happens to
    contain the word "nonce" must classify as a transport fault."""
    from arbius_tpu.chain import EngineError
    from arbius_tpu.chain.rpc_client import RpcError
    from arbius_tpu.node.rpc_chain import (
        ChainRpcError,
        _engine_error,
        nonce_conflict,
    )

    # the devnet's exact rejection (FaultTransport re-wraps it raw)
    e = RpcError("nonce 5 != expected 3")
    assert nonce_conflict(e) == (5, 3)
    assert isinstance(_engine_error(e), EngineError)

    # structured JSON-RPC error object: message carries the sentence
    e = RpcError("{'code': -32000, ...}", code=-32000,
                 message="err: nonce 12 != expected 11")
    assert nonce_conflict(e) == (12, 11)
    assert isinstance(_engine_error(e), EngineError)

    # a task payload echoing "nonce" in the DATA is NOT a conflict
    e = RpcError("server error", code=-32000,
                 message="internal failure",
                 data='{"input": "write a poem about a nonce"}')
    assert nonce_conflict(e) is None
    assert isinstance(_engine_error(e), ChainRpcError)

    # nor is a malformed almost-match in the message itself
    assert nonce_conflict(RpcError("nonce mismatch somewhere")) is None
    # reverts still classify as engine errors
    assert isinstance(_engine_error(RpcError("execution revert: no")),
                      EngineError)


def test_devnet_nonce_rejection_classifies_via_transport():
    """End to end through the live transport wrapper: a wrong-nonce tx
    into the devnet surfaces as EngineError (state-dependent retry),
    not as a retryable transport fault."""
    from arbius_tpu.chain import EngineError
    from arbius_tpu.chain.rlp import Eip1559Tx
    from arbius_tpu.node.rpc_chain import _engine_error, nonce_conflict
    from arbius_tpu.chain.rpc_client import RpcError

    eng, dev, miner, user, mid = make_world()
    tx = Eip1559Tx(chain_id=CHAIN_ID, nonce=9, max_priority_fee_per_gas=1,
                   max_fee_per_gas=10, gas_limit=100000,
                   to=dev.engine_address, value=0, data=b"")
    raw = tx.sign(miner)
    try:
        DevnetTransport(dev).request("eth_sendRawTransaction",
                                     ["0x" + raw.hex()])
    except RpcError as e:
        assert nonce_conflict(e) == (9, 0)
        assert isinstance(_engine_error(e), EngineError)
    else:
        raise AssertionError("wrong nonce was accepted")
