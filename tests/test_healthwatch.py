"""healthwatch tier-1 suite (docs/healthwatch.md): the alert state
machine's hysteresis edges, the rule catalog's config plumbing, the
engine over a fake node, the /debug/alerts + /debug/journal surfaces,
and the offline tools (tools/healthwatch.py, tools/benchkeeper.py)
against their fixture goldens. The simnet coverage invariant (SIM113)
and the CID on-vs-off pins live in tests/test_sim.py."""
from __future__ import annotations

import json
import os
import sys
import urllib.request

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

from arbius_tpu.node.config import AlertsConfig, ConfigError
from arbius_tpu.obs import Obs
from arbius_tpu.obs.healthwatch import (
    RULE_NAMES,
    AlertRule,
    AlertStateMachine,
    HealthWatch,
    default_catalog,
)

FIXDIR = os.path.join(os.path.dirname(__file__), "fixtures")


def _machine(for_ticks: int, resolve_ticks: int = 1) -> AlertStateMachine:
    return AlertStateMachine(
        AlertRule(name="t", summary="t", signal="t",
                  for_ticks=for_ticks),
        resolve_ticks=resolve_ticks)


def _walk(m: AlertStateMachine, actives) -> list:
    out = []
    for i, active in enumerate(actives):
        change = m.step(bool(active), now=i)
        if change is not None:
            out.append(change)
    return out


# -- the state machine's hysteresis edges (the satellite contract) ----------

def test_breach_resolving_at_for_ticks_minus_one_never_fires():
    """A condition active for exactly for_ticks-1 evaluations then
    clear goes ok → pending → ok and NEVER fires."""
    m = _machine(for_ticks=3)
    changes = _walk(m, [1, 1, 0, 0])
    assert changes == [("ok", "pending"), ("pending", "ok")]
    assert all("firing" not in c for c in changes)
    assert m.state == "ok"


def test_sustained_breach_fires_exactly_once():
    m = _machine(for_ticks=3)
    changes = _walk(m, [1, 1, 1, 1, 1, 1])
    # one pending entry, one firing entry — NOT one event per active
    # evaluation (the perf_drift once-per-crossing contract)
    assert changes == [("ok", "pending"), ("pending", "firing")]
    assert m.state == "firing"


def test_firing_resolves_then_returns_to_ok():
    m = _machine(for_ticks=1, resolve_ticks=2)
    changes = _walk(m, [1, 0, 0, 0])
    assert changes == [("ok", "firing"), ("firing", "resolved"),
                       ("resolved", "ok")]
    # resolve_ticks=2: the resolved → ok edge waited 2 quiet evals
    assert m.state == "ok"


def test_flapping_series_journals_one_transition_per_state_change():
    """Alternating condition: every recorded change is a genuine state
    change (no duplicates), and the walk is a legal chain."""
    m = _machine(for_ticks=1, resolve_ticks=1)
    changes = _walk(m, [1, 0, 1, 0, 1])
    assert changes == [("ok", "firing"), ("firing", "resolved"),
                       ("resolved", "firing"), ("firing", "resolved"),
                       ("resolved", "firing")]
    state = "ok"
    for old, new in changes:
        assert old == state and new != old
        state = new


def test_reactivation_from_resolved_respects_hysteresis():
    """With for_ticks > 1 a resolved alert re-arms through pending —
    one blip after resolution does not re-fire."""
    m = _machine(for_ticks=2)
    changes = _walk(m, [1, 1, 0, 1, 0, 0])
    assert changes == [("ok", "pending"), ("pending", "firing"),
                       ("firing", "resolved"), ("resolved", "pending"),
                       ("pending", "ok")]
    assert "firing" not in {new for _, new in changes[3:]}, \
        "one blip after resolution must not re-fire"


# -- catalog / config plumbing ----------------------------------------------

def test_rule_names_match_default_catalog():
    names = tuple(r.name for r in default_catalog(AlertsConfig()))
    assert names == RULE_NAMES
    assert len(set(names)) == len(names)


def test_per_rule_override_reaches_the_machine():
    cfg = AlertsConfig(per_rule={"rpc_degraded": 7})
    by_name = {r.name: r for r in default_catalog(cfg)}
    assert by_name["rpc_degraded"].for_ticks == 7
    assert by_name["pin_degraded"].for_ticks == cfg.for_ticks


def test_alerts_config_validation_one_sentence_errors():
    with pytest.raises(ConfigError, match="unknown rule"):
        AlertsConfig(per_rule={"not_a_rule": 2})
    with pytest.raises(ConfigError, match="for_ticks"):
        AlertsConfig(for_ticks=0)
    with pytest.raises(ConfigError, match="per_rule"):
        AlertsConfig(per_rule={"rpc_degraded": 0})
    with pytest.raises(ConfigError, match="stall_burst"):
        AlertsConfig(stall_burst=0)
    from arbius_tpu.node.config import load_config

    with pytest.raises(ConfigError, match="alerts"):
        load_config('{"alerts": {"bogus_knob": 1}}')
    cfg = load_config('{"alerts": {"enabled": true, '
                      '"per_rule": {"stuck_tick": 2}}}')
    assert cfg.alerts.enabled


def test_example_config_ships_a_validated_alerts_block():
    from arbius_tpu.node.config import load_config

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cfg = load_config(open(os.path.join(
        repo, "MiningConfig.example.json")).read())
    assert cfg.alerts.enabled is False
    assert cfg.alerts.for_ticks == 3 and cfg.alerts.per_rule == {}


# -- the engine over a fake node --------------------------------------------

class _FakeChain:
    def __init__(self):
        self.now = 0


class _FakeDB:
    def __init__(self):
        self.due = []

    def get_jobs(self, now, limit=None):
        return self.due[:limit]


class _FakeNode:
    def __init__(self, obs):
        self.obs = obs
        self.chain = _FakeChain()
        self.db = _FakeDB()
        self.task_feed = None


def _watch(**cfg):
    obs = Obs()
    hw = HealthWatch(obs, AlertsConfig(enabled=True, **cfg))
    return obs, hw, _FakeNode(obs)


def test_quarantine_rule_fires_on_counter_delta():
    obs, hw, node = _watch()
    c = obs.registry.counter("arbius_jobs_failed_total",
                             labelnames=("method",))
    hw.evaluate(node)
    assert hw.states()["job_quarantine"] == "ok"
    c.inc(method="solve")
    node.chain.now = 5
    hw.evaluate(node)
    assert hw.states()["job_quarantine"] == "firing"   # for_ticks=1
    node.chain.now = 10
    hw.evaluate(node)                                  # no new failures
    assert hw.states()["job_quarantine"] == "resolved"
    trans = obs.journal.events(kind="alert_transition")
    assert [(e["prev"], e["state"]) for e in trans] == \
        [("ok", "firing"), ("firing", "resolved")]
    assert obs.registry.counter(
        "arbius_alert_transitions_total",
        labelnames=("alert",)).value(alert="job_quarantine") == 2


def test_stuck_tick_watchdog_uses_chain_time_only():
    obs, hw, node = _watch(stuck_after_seconds=10)
    node.db.due = [object()]
    hw.evaluate(node, 0)                 # t=0: anchors progress
    node.chain.now = 8
    hw.evaluate(node, 0)
    assert hw.states()["stuck_tick"] == "ok"
    node.chain.now = 20                  # 20s with due jobs, no work
    hw.evaluate(node, 0)
    assert hw.states()["stuck_tick"] == "firing"
    node.chain.now = 25
    hw.evaluate(node, 3)                 # progress: jobs processed
    assert hw.states()["stuck_tick"] == "resolved"


def test_unprofitable_streak_needs_consecutive_ticks():
    obs, hw, node = _watch(unprofitable_streak=3)
    c = obs.registry.counter("arbius_tasks_unprofitable_total",
                             labelnames=("model",))
    for now in (1, 2):
        c.inc(model="0xm")
        node.chain.now = now
        hw.evaluate(node)
    assert hw.states()["unprofitable_streak"] == "pending"
    node.chain.now = 3
    hw.evaluate(node)                    # a tick with NO rejects
    assert hw.states()["unprofitable_streak"] == "ok", \
        "the streak must reset — that is the hysteresis edge"
    for now in (4, 5, 6):
        c.inc(model="0xm")
        node.chain.now = now
        hw.evaluate(node)
    assert hw.states()["unprofitable_streak"] == "firing"


def test_pipeline_stall_is_a_storm_threshold_not_backpressure():
    obs, hw, node = _watch(stall_burst=4, for_ticks=1)
    c = obs.registry.counter("arbius_pipeline_stalls_total",
                             labelnames=("stage",))
    c.inc(stage="encode")                # routine backpressure
    hw.evaluate(node)
    assert hw.states()["pipeline_stall"] == "ok"
    c.inc(4, stage="network")            # a storm in one tick
    node.chain.now = 5
    hw.evaluate(node)
    assert hw.states()["pipeline_stall"] == "firing"


def test_crash_recovered_holds_then_resolves():
    obs = Obs()
    hw = HealthWatch(obs, AlertsConfig(enabled=True, crash_hold_ticks=2),
                     recovered=True)
    node = _FakeNode(obs)
    hw.evaluate(node)
    assert hw.states()["crash_recovered"] == "firing"
    node.chain.now = 5
    hw.evaluate(node)
    assert hw.states()["crash_recovered"] == "firing"
    node.chain.now = 10
    hw.evaluate(node)                    # hold expired
    assert hw.states()["crash_recovered"] == "resolved"


def test_slo_rules_use_bucket_estimates():
    from arbius_tpu.node.config import SLOConfig
    from arbius_tpu.obs.registry import CHAIN_SECONDS_BUCKETS

    obs = Obs()
    hw = HealthWatch(obs, AlertsConfig(enabled=True, for_ticks=1),
                     slo=SLOConfig(queue_wait_p95=10.0))
    node = _FakeNode(obs)
    h = obs.registry.histogram("arbius_fleet_queue_wait_seconds",
                               buckets=CHAIN_SECONDS_BUCKETS)
    for _ in range(20):
        h.observe(2.0)
    hw.evaluate(node)
    assert hw.states()["slo_queue_wait"] == "ok"
    for _ in range(80):
        h.observe(500.0)                 # p95 now far above 10s
    node.chain.now = 5
    hw.evaluate(node)
    assert hw.states()["slo_queue_wait"] == "firing"
    # an undeclared objective never evaluates
    assert hw.states()["slo_time_to_commit"] == "ok"


def test_evaluate_never_raises(monkeypatch):
    obs, hw, node = _watch()
    monkeypatch.setattr(hw, "_signals",
                        lambda *a: (_ for _ in ()).throw(RuntimeError()))
    hw.evaluate(node)                    # must not propagate
    assert [e["kind"] for e in obs.journal.events(
        kind="healthwatch_skip")] == ["healthwatch_skip"]


def test_alert_gauges_render_states_and_prometheus_alerts_block():
    obs, hw, node = _watch()
    obs.registry.counter("arbius_jobs_failed_total",
                         labelnames=("method",)).inc(method="solve")
    hw.evaluate(node)
    text = obs.registry.render()
    assert 'arbius_alert_state{alert="job_quarantine"} 2' in text
    assert 'arbius_alert_state{alert="stuck_tick"} 0' in text
    assert ('ALERTS{alertname="job_quarantine",alertstate="firing"} 1'
            in text)
    # every catalog rule is enumerable from the one scrape
    for name in RULE_NAMES:
        assert f'arbius_alert_state{{alert="{name}"}}' in text


# -- node + RPC surfaces ----------------------------------------------------

def _get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=5) as r:
        return json.loads(r.read().decode())


@pytest.fixture()
def alert_world():
    from arbius_tpu.node.rpc import ControlRPC

    from test_node import build_world

    eng, tok, chain, node, mid = build_world(
        alerts=AlertsConfig(enabled=True))
    rpc = ControlRPC(node)
    rpc.start()
    yield eng, node, rpc
    rpc.stop()
    node.close()


def test_debug_alerts_endpoint_and_journal_filters(alert_world):
    eng, node, rpc = alert_world
    doc = _get(rpc.port, "/debug/alerts")
    assert doc["enabled"] is True
    assert [a["alert"] for a in doc["alerts"]] == sorted(RULE_NAMES)
    assert all(a["state"] == "ok" for a in doc["alerts"])

    # force a flap: job_quarantine fires, resolves, returns to ok
    c = node.obs.registry.counter("arbius_jobs_failed_total",
                                  labelnames=("method",))
    c.inc(method="x")
    node.tick()
    doc = _get(rpc.port, "/debug/alerts")
    by_name = {a["alert"]: a for a in doc["alerts"]}
    assert by_name["job_quarantine"]["state"] == "firing"
    assert by_name["job_quarantine"]["transitions"] == 1
    eng.advance_time(5)
    node.tick()
    eng.advance_time(5)
    node.tick()

    # /debug/journal?kind=alert_transition: exactly the transition
    # record, in seq (journal) order — test-pinned ordering
    doc = _get(rpc.port, "/debug/journal?kind=alert_transition")
    events = doc["events"]
    assert [e["kind"] for e in events] == ["alert_transition"] * 3
    assert [(e["prev"], e["state"]) for e in events] == \
        [("ok", "firing"), ("firing", "resolved"), ("resolved", "ok")]
    assert [e["seq"] for e in events] == sorted(e["seq"] for e in events)
    # kind + limit compose: limit keeps the NEWEST events post-filter
    doc = _get(rpc.port, "/debug/journal?kind=alert_transition&limit=1")
    assert [(e["prev"], e["state"]) for e in doc["events"]] == \
        [("resolved", "ok")]


def test_debug_journal_taskid_filter_mirrors_trace_semantics(alert_world):
    from arbius_tpu.chain import WAD

    from test_node import drain, submit

    eng, node, rpc = alert_world
    mid = node.registry.ids()[0]
    tid = submit(eng, mid, fee=10 * WAD)
    drain(node)
    doc = _get(rpc.port, f"/debug/journal?taskid={tid}")
    events = doc["events"]
    assert events, "the task's lifecycle journaled nothing"
    assert all(e.get("taskid") == tid or tid in (e.get("taskids") or ())
               for e in events)
    assert [e["seq"] for e in events] == sorted(e["seq"] for e in events)
    # identical to the journal API the /debug/trace view uses
    assert events == node.obs.journal.events(taskid=tid, limit=200)
    # an unknown task filters to nothing (not an error)
    doc = _get(rpc.port, "/debug/journal?taskid=0x" + "ab" * 32)
    assert doc["events"] == []


# -- tools/healthwatch.py (fixture-goldened) --------------------------------

def make_eval_sidecars(dirpath: str) -> None:
    """A deterministic 3-member sidecar set: worker-0 ends with
    rpc_degraded FIRING and pin_degraded pending, worker-1 is healthy,
    and the coordinator never ran healthwatch (unwatched). Shared by
    the golden test and the golden regeneration snippet in
    tests/fixtures/healthwatch/README.md."""
    from arbius_tpu.obs.fleetscope import ObsSidecar, sidecar_path

    def member(name, build):
        obs = Obs()
        build(obs)
        side = ObsSidecar(sidecar_path(dirpath, name), name, obs)
        side.flush(now=123)
        side.close()

    def worker0(obs):
        hw = HealthWatch(obs, AlertsConfig(enabled=True))
        for now in (100, 105, 110):
            hw._machines["rpc_degraded"].step(True, now)
        hw._machines["pin_degraded"].step(True, 110)
        hw._c_transitions.inc(2, alert="rpc_degraded")
        hw._c_transitions.inc(alert="pin_degraded")

    member("worker-0", worker0)
    member("worker-1",
           lambda obs: HealthWatch(obs, AlertsConfig(enabled=True)))
    member("coordinator", lambda obs: None)


def test_healthwatch_tool_eval_matches_goldens(tmp_path, capsys):
    import healthwatch as hw_tool

    make_eval_sidecars(str(tmp_path))
    rc = hw_tool.main(["--eval", str(tmp_path)])
    out = capsys.readouterr().out
    want = open(os.path.join(FIXDIR, "healthwatch",
                             "eval.golden.txt")).read()
    assert out == want
    assert rc == 1                      # a firing alert fails the audit

    rc = hw_tool.main(["--eval", str(tmp_path), "--json"])
    out = capsys.readouterr().out
    want = open(os.path.join(FIXDIR, "healthwatch",
                             "eval.golden.json")).read()
    assert out == want
    doc = json.loads(out)
    assert [f["rule"] for f in doc["findings"]] == ["HW701"]
    assert doc["findings"][0]["path"] == "worker-0"


def test_healthwatch_tool_eval_is_byte_deterministic(tmp_path, capsys):
    import healthwatch as hw_tool

    outs = []
    for d in ("a", "b"):
        (tmp_path / d).mkdir()
        make_eval_sidecars(str(tmp_path / d))
        hw_tool.main(["--eval", str(tmp_path / d), "--json"])
        outs.append(capsys.readouterr().out)
    assert outs[0] == outs[1]


def test_healthwatch_tool_rules_and_usage(tmp_path, capsys):
    import healthwatch as hw_tool

    assert hw_tool.main(["--rules"]) == 0
    out = capsys.readouterr().out
    for name in RULE_NAMES:
        assert name in out
    assert hw_tool.main([]) == 2
    capsys.readouterr()
    assert hw_tool.main(["--eval", str(tmp_path / "nope")]) == 2


def test_healthwatch_tool_clean_fleet_exits_0(tmp_path, capsys):
    import healthwatch as hw_tool

    from arbius_tpu.obs.fleetscope import ObsSidecar, sidecar_path

    obs = Obs()
    HealthWatch(obs, AlertsConfig(enabled=True))
    side = ObsSidecar(sidecar_path(str(tmp_path), "worker-0"),
                      "worker-0", obs)
    side.flush(now=1)
    side.close()
    assert hw_tool.main(["--eval", str(tmp_path)]) == 0
    assert "0 firing alert(s)" in capsys.readouterr().out


# -- tools/benchkeeper.py (fixture-goldened) --------------------------------

BENCHDIR = os.path.join(FIXDIR, "benchkeeper")


def test_benchkeeper_merges_every_shape_to_the_golden(capsys):
    import benchkeeper

    rc = benchkeeper.main(["--dir", BENCHDIR, "--json"])
    out = capsys.readouterr().out
    want = open(os.path.join(BENCHDIR, "trajectory.golden.json")).read()
    assert out == want
    assert rc == 0
    doc = json.loads(out)
    # all three historical shapes landed: driver-era parsed (r02),
    # single-stage (r03), multi-stage (r04); the rc=124 round skipped
    assert doc["rounds"] == [2, 3, 4]
    assert [s["round"] for s in doc["skipped"]] == [1]
    assert sorted(doc["stages"]) == ["coldboot", "sched_ab",
                                     "sustained"]
    assert [e["round"] for e in doc["stages"]["sched_ab"]] == [3, 4]


def test_benchkeeper_write_and_check_roundtrip(tmp_path, capsys):
    import shutil

    import benchkeeper

    for f in os.listdir(BENCHDIR):
        if f.startswith("BENCH_r"):
            shutil.copy(os.path.join(BENCHDIR, f), tmp_path / f)
    assert benchkeeper.main(["--dir", str(tmp_path)]) == 0
    capsys.readouterr()
    assert (tmp_path / "BENCH_TRAJECTORY.json").exists()
    assert benchkeeper.main(["--dir", str(tmp_path), "--check"]) == 0
    capsys.readouterr()
    # drift (a landed bench round without regeneration) fails closed
    (tmp_path / "BENCH_r09.json").write_text(json.dumps({
        "ok": True, "stage": "flood",
        "result": {"metric": "m", "value": 1.0, "unit": "u",
                   "stage": "flood"}}))
    assert benchkeeper.main(["--dir", str(tmp_path), "--check"]) == 1
    assert "BENCH802" in capsys.readouterr().out


def test_benchkeeper_schema_violations_are_findings(tmp_path, capsys):
    import benchkeeper

    (tmp_path / "BENCH_r05.json").write_text(json.dumps({
        "ok": True, "stage": "x",
        "result": {"metric": "m", "value": "NOT A NUMBER",
                   "unit": "u", "stage": "x"}}))
    (tmp_path / "BENCH_r06.json").write_text("{not json")
    (tmp_path / "BENCH_r08.json").write_text(json.dumps({
        "ok": True, "round": 4, "stages": {}}))   # misnamed round
    rc = benchkeeper.main(["--dir", str(tmp_path), "--json"])
    err = capsys.readouterr().err
    assert rc == 1
    assert err.count("BENCH801") == 3
    assert "BENCH_r08.json" in err and "misnamed" in err


def test_repo_trajectory_covers_the_committed_bench_rounds():
    """The committed BENCH_TRAJECTORY.json agrees with a regeneration
    from the repo's BENCH_r*.json set for every round it covers — the
    trajectory can no longer silently drift from the files it
    aggregates. Deliberately TOLERANT of bench rounds newer than the
    committed trajectory (the bench driver lands BENCH files between
    sessions; `tools/benchkeeper.py --check` is the strict CI gate):
    coverage of new rounds is the next regeneration's job, agreement
    on covered rounds is this pin's."""
    import benchkeeper

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    regen, _findings = benchkeeper.merge_bench_files(repo)
    committed = json.load(open(os.path.join(repo,
                                            "BENCH_TRAJECTORY.json")))
    covered = set(committed["rounds"])
    assert covered, "the committed trajectory is empty"
    assert covered <= set(regen["rounds"])
    for stage, series in committed["stages"].items():
        regen_series = [e for e in regen["stages"].get(stage, ())
                        if e["round"] in covered]
        assert series == regen_series, stage
