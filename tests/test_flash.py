"""Flash-attention kernel tests (interpret mode on CPU; the same kernel
compiles for TPU). Oracle: the einsum reference with f32 softmax."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from arbius_tpu.ops.flash import flash_attention
from arbius_tpu.ops.ring import sp_attention_reference

pytestmark = [pytest.mark.slow, pytest.mark.model]


def rand(shape, key, dtype=jnp.float32):
    return jax.random.normal(jax.random.PRNGKey(key), shape, dtype)


@pytest.mark.parametrize("b,h,s,d", [
    (1, 1, 128, 128),     # exactly one tile
    (2, 3, 256, 64),      # padded head_dim
    (1, 2, 200, 40),      # ragged seq + ragged dim (SD-1.5 head shape)
    (1, 1, 384, 128),     # multi K-block loop
])
def test_flash_matches_reference(b, h, s, d):
    q, k, v = (rand((b, h, s, d), i) for i in range(3))
    got = np.asarray(flash_attention(q, k, v, interpret=True))
    want = np.asarray(sp_attention_reference(q, k, v))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("b,h,s,d", [
    (2, 3, 256, 40),      # SD-1.5 level-0 head shape, no explicit d pad
    (1, 2, 200, 33),      # ragged everything
])
def test_flash_nopad_matches_reference_and_padded(b, h, s, d):
    """pad_d=False hands the native head dim to the kernel (Mosaic lane-
    pads internally); same math as the padded variant to reduction-order
    ULPs (a K=40 vs K=128 contraction associates differently, so bits
    may drift — switching the production default therefore re-records
    the platform goldens, the round-4 discipline)."""
    q, k, v = (rand((b, h, s, d), i) for i in range(3))
    got = np.asarray(flash_attention(q, k, v, interpret=True, pad_d=False))
    want = np.asarray(sp_attention_reference(q, k, v))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)
    padded = np.asarray(flash_attention(q, k, v, interpret=True))
    np.testing.assert_allclose(got, padded, rtol=1e-5, atol=1e-6)


def test_flash_cross_attention_shape():
    """kv_len ≠ q_len (text cross-attention: 77 context tokens)."""
    q = rand((1, 2, 256, 64), 0)
    k = rand((1, 2, 77, 64), 1)
    v = rand((1, 2, 77, 64), 2)
    got = np.asarray(flash_attention(q, k, v, interpret=True))
    want = np.asarray(sp_attention_reference(q, k, v))
    assert got.shape == (1, 2, 256, 64)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_flash_bf16_inputs():
    q, k, v = (rand((1, 2, 128, 64), i, jnp.bfloat16) for i in range(3))
    got = np.asarray(flash_attention(q, k, v, interpret=True),
                     dtype=np.float32)
    want = np.asarray(sp_attention_reference(q, k, v), dtype=np.float32)
    np.testing.assert_allclose(got, want, rtol=2e-2, atol=2e-2)


def test_flash_extreme_logits():
    q = jnp.full((1, 1, 128, 64), 20.0)
    k = jnp.full((1, 1, 128, 64), 20.0)
    v = rand((1, 1, 128, 64), 3)
    out = np.asarray(flash_attention(q, k, v, interpret=True))
    assert np.isfinite(out).all()
    np.testing.assert_allclose(
        out, np.asarray(sp_attention_reference(q, k, v)), rtol=1e-5,
        atol=1e-5)


def test_attention_impl_pinned_at_import_and_explicitly_settable():
    """ISSUE satellite: the dispatch is pinned ONCE (env read at import);
    in-process flips go through set_attention_impl, which validates and
    returns the prior value for restore."""
    import pytest

    from arbius_tpu.ops import flash

    assert flash.attention_impl() in flash.VALID_ATTN_IMPLS
    prior = flash.set_attention_impl("einsum")
    try:
        assert flash.attention_impl() == "einsum"
        with pytest.raises(ValueError, match="bogus"):
            flash.set_attention_impl("bogus")
        assert flash.attention_impl() == "einsum"  # rejected = unchanged
    finally:
        flash.set_attention_impl(prior)
    assert flash.attention_impl() == prior
    # None restores the env-pinned import-time value
    flash.set_attention_impl("flash")
    flash.set_attention_impl(None)
    assert flash.attention_impl() == flash._read_attn_impl()
