"""SD-1.5 pipeline on the virtual dp mesh: shards run, bits reproduce."""
import numpy as np

from arbius_tpu.models.sd15 import ByteTokenizer, SD15Config, SD15Pipeline
from arbius_tpu.parallel import MeshSpec, build_mesh
import pytest

pytestmark = [pytest.mark.slow, pytest.mark.model]


def test_sd15_dp_mesh_reproducible():
    mesh = build_mesh(MeshSpec(dp=8))
    pipe = SD15Pipeline(SD15Config.tiny(), mesh=mesh,
                        tokenizer=ByteTokenizer(max_length=16,
                                                bos_id=257, eos_id=258))
    params = pipe.place_params(pipe.init_params(seed=7))
    kw = dict(width=64, height=64, num_inference_steps=2, scheduler="DDIM")
    prompts = [f"task {i}" for i in range(8)]
    negs = [""] * 8
    seeds = list(range(100, 108))
    a = pipe.generate(params, prompts, negs, seeds, **kw)
    b = pipe.generate(params, prompts, negs, seeds, **kw)
    assert a.shape == (8, 64, 64, 3) and a.dtype == np.uint8
    np.testing.assert_array_equal(a, b)
    # different seeds -> different images (sanity that dp lanes are live)
    assert not np.array_equal(a[0], a[1])


def test_sd15_dp_mesh_batch_divisibility():
    mesh = build_mesh(MeshSpec(dp=8))
    pipe = SD15Pipeline(SD15Config.tiny(), mesh=mesh,
                        tokenizer=ByteTokenizer(max_length=16,
                                                bos_id=257, eos_id=258))
    params = pipe.place_params(pipe.init_params(seed=7))
    try:
        pipe.generate(params, ["x"] * 3, [""] * 3, [1, 2, 3],
                      width=64, height=64, num_inference_steps=1)
    except ValueError as e:
        assert "divisible" in str(e)
    else:
        raise AssertionError("expected divisibility error")


def test_pp_over_sd15_text_encoder_layers():
    """Pipeline parallelism on a production SD-1.5 module: the text
    encoder's identical-layer stack split over pp=2 (its 12-layer ViT-L
    stack is the flagship's natural layer-stack pipeline; the UNet's
    levels change activation shape and belong to tp/dp). Composes pp×dp:
    microbatch batch dim sharded over dp. Must equal the plain forward
    bitwise-tolerably."""
    import jax
    import jax.numpy as jnp
    import flax.linen as nn

    from arbius_tpu.models.sd15.text_encoder import (
        TextEncoder,
        TextEncoderConfig,
        _EncoderLayer,
    )
    from arbius_tpu.parallel import pipeline_apply, stack_stage_params

    cfg = TextEncoderConfig(vocab_size=64, max_length=8, width=16,
                            layers=4, heads=2, dtype="float32")
    enc = TextEncoder(cfg)
    ids = jax.random.randint(jax.random.PRNGKey(1), (4, 8), 0, 64)
    params = enc.init(jax.random.PRNGKey(0), ids)["params"]
    ref = np.asarray(enc.apply({"params": params}, ids))

    mesh = build_mesh(MeshSpec(pp=2, dp=2), devices=jax.devices()[:4])
    S = mesh.shape["pp"]
    k = cfg.layers // S

    class Stage(nn.Module):
        """k consecutive encoder layers — every stage same signature."""
        @nn.compact
        def __call__(self, x):
            mask = nn.make_causal_mask(jnp.zeros(x.shape[:2], jnp.int32))
            for i in range(k):
                x = _EncoderLayer(cfg, name=f"layer_{i}")(x, mask)
            return x

    stage = Stage()
    stacked = stack_stage_params([
        {f"layer_{j}": params[f"layer_{s * k + j}"] for j in range(k)}
        for s in range(S)])

    # embeddings / final norm sit outside the pipelined stack, exactly as
    # TextEncoder computes them
    x = (params["token_embed"]["embedding"][ids]
         + params["pos_embed"][None, : ids.shape[1]])
    mid = pipeline_apply(
        lambda p, h: stage.apply({"params": p}, h),
        stacked, x.astype(jnp.float32), mesh, axis="pp",
        microbatches=2, batch_axis="dp")
    fin = params["final_norm"]
    out = nn.LayerNorm(epsilon=1e-5).apply(
        {"params": {"scale": fin["scale"], "bias": fin["bias"]}},
        jnp.asarray(mid))
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5, rtol=1e-5)
