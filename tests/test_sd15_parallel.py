"""SD-1.5 pipeline on the virtual dp mesh: shards run, bits reproduce."""
import numpy as np

from arbius_tpu.models.sd15 import ByteTokenizer, SD15Config, SD15Pipeline
from arbius_tpu.parallel import MeshSpec, build_mesh
import pytest

pytestmark = [pytest.mark.slow, pytest.mark.model]


def test_sd15_dp_mesh_reproducible():
    mesh = build_mesh(MeshSpec(dp=8))
    pipe = SD15Pipeline(SD15Config.tiny(), mesh=mesh,
                        tokenizer=ByteTokenizer(max_length=16,
                                                bos_id=257, eos_id=258))
    params = pipe.place_params(pipe.init_params(seed=7))
    kw = dict(width=64, height=64, num_inference_steps=2, scheduler="DDIM")
    prompts = [f"task {i}" for i in range(8)]
    negs = [""] * 8
    seeds = list(range(100, 108))
    a = pipe.generate(params, prompts, negs, seeds, **kw)
    b = pipe.generate(params, prompts, negs, seeds, **kw)
    assert a.shape == (8, 64, 64, 3) and a.dtype == np.uint8
    np.testing.assert_array_equal(a, b)
    # different seeds -> different images (sanity that dp lanes are live)
    assert not np.array_equal(a[0], a[1])


def test_sd15_dp_mesh_batch_divisibility():
    mesh = build_mesh(MeshSpec(dp=8))
    pipe = SD15Pipeline(SD15Config.tiny(), mesh=mesh,
                        tokenizer=ByteTokenizer(max_length=16,
                                                bos_id=257, eos_id=258))
    params = pipe.place_params(pipe.init_params(seed=7))
    try:
        pipe.generate(params, ["x"] * 3, [""] * 3, [1, 2, 3],
                      width=64, height=64, num_inference_steps=1)
    except ValueError as e:
        assert "divisible" in str(e)
    else:
        raise AssertionError("expected divisibility error")
